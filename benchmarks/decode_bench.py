"""Single-chip autoregressive decode benchmark (KV-cache path).

Measures ``llama_generate`` (models/generate.py: one compiled
prefill+decode program, per-layer KV caches updated in-place via
dynamic_update_slice) on the real chip. Decode is HBM-bandwidth-bound —
every step streams the full parameter set plus the KV cache — so
alongside tokens/s this reports **MBU** (memory-bandwidth utilization:
bytes-that-must-move per step / step time / peak HBM bandwidth), the
decode analog of training MFU.

Per-step time is isolated by differencing two generation lengths
(256 vs 32 new tokens): each timed call re-runs the prefill too, and
at large batch the prefill is a material fraction of the wall time —
dividing a whole call by its decode steps would overstate ms/step.

Run on a real TPU chip::

    python benchmarks/decode_bench.py [--out results.json]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

# Peak HBM GB/s by device generation (v5e: 819 GB/s per chip).
_HBM_PEAK = {"v4": 1228e9, "v5e": 819e9, "v5 lite": 819e9,
             "v5p": 2765e9, "v6e": 1640e9, "cpu": 100e9}

# (batch, prompt_len): bs1 is the latency point, bs16/bs64 throughput.
CONFIGS = [(1, 128), (16, 128), (64, 128)]
NEW_LONG, NEW_SHORT = 256, 32


def _paged_row(params, cfg, batch=16, t0_len=128, new_tokens=64):
    """Paged-cache decode throughput on the same chip: the serving
    engine's continuous-batching step (host-gathered paged KV,
    models/generate.llama_decode_step) at a fixed batch, all requests
    arriving at t=0. Reports the paged lane's tok/s next to the fused
    contiguous kernel's headline so the host-gather tax — the gap a
    device-resident paged-attention kernel would close (docs/
    serving.md) — is a number, not a guess."""
    import time as _time

    import numpy as np

    from horovod_tpu.serving.engine import DecodeEngine
    from horovod_tpu.serving.scheduler import Request

    eng = DecodeEngine(params, cfg, block_size=32,
                       n_blocks=batch * ((t0_len + new_tokens) // 32 + 2),
                       max_batch=batch, max_context=t0_len + new_tokens)
    rng = np.random.default_rng(1)
    for rid in range(batch):
        eng.submit(Request(
            rid=rid,
            prompt=rng.integers(0, cfg.vocab_size,
                                size=t0_len).astype(np.int32),
            max_new_tokens=new_tokens))
    eng.step()  # admit + compile prefill/decode off the clock
    t0 = _time.time()
    steps0, toks0 = eng.steps, eng.tokens_out
    eng.run_until_idle()
    dt = _time.time() - t0
    steps = eng.steps - steps0
    tok_s = (eng.tokens_out - toks0) / dt
    return {
        "metric": f"decode_paged_tok_s_b{batch}",
        "value": round(tok_s, 1),
        "unit": f"tok/s continuous-batching paged KV (batch {batch}, "
                f"prompt {t0_len}, {new_tokens} new, "
                f"{dt / max(steps, 1) * 1e3:.2f} ms/step incl host "
                "gather)",
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--paged", action="store_true",
                    help="also run the paged-KV serving-engine lane")
    args = ap.parse_args()

    import numpy as np

    import bench
    from horovod_tpu.models import llama_init
    from horovod_tpu.models.generate import llama_generate

    if jax.devices()[0].platform == "cpu":
        print("decode_bench needs an accelerator; skipping",
              file=sys.stderr)
        return

    cfg = bench._flagship_cfg()
    params = llama_init(cfg, jax.random.PRNGKey(0))
    n_params = sum(x.size for x in jax.tree.leaves(params))
    param_bytes = sum(x.size * x.dtype.itemsize
                      for x in jax.tree.leaves(params))
    hbm_peak = bench.match_device_table(jax.devices()[0], _HBM_PEAK)

    def timed(gen, prompt, reps=3):
        # Materialize to HOST, not block_until_ready: on some PJRT
        # transports block_until_ready returns before the program
        # finishes, which once inflated this row 1000x. The [B, T+new]
        # int32 copy itself is microseconds.
        t0 = time.time()
        np.asarray(gen(params, prompt))
        first_s = time.time() - t0
        t0 = time.time()
        for _ in range(reps):
            np.asarray(gen(params, prompt))
        return first_s, (time.time() - t0) / reps

    rows = []
    for batch, t0_len in CONFIGS:
        prompt = jax.random.randint(jax.random.PRNGKey(1),
                                    (batch, t0_len), 0, cfg.vocab_size)
        gen_long = jax.jit(
            lambda p, t: llama_generate(p, t, cfg, NEW_LONG))
        gen_short = jax.jit(
            lambda p, t: llama_generate(p, t, cfg, NEW_SHORT))
        first_s, dt_long = timed(gen_long, prompt)
        _, dt_short = timed(gen_short, prompt)
        # Decode-only per-step time: the prefill and fixed dispatch
        # costs cancel in the difference.
        step_s = (dt_long - dt_short) / (NEW_LONG - NEW_SHORT)
        tok_s = batch / step_s
        # Bytes per decode step: all params + the KV cache traffic.
        # _decode_attention reads the FULL padded cache
        # [B, t0+new, Hkv, D] every step (dense einsum, masked by
        # index), so a run of n steps streams n*(t0+n) positions; the
        # differenced window's effective length per step is
        # (L*(t0+L) - S*(t0+S)) / (L-S) = t0 + L + S.
        kv_mean = (cfg.n_layers * batch
                   * (t0_len + NEW_LONG + NEW_SHORT)
                   * cfg.n_kv_heads * cfg.head_dim * 2 * 2)
        mbu = (param_bytes + kv_mean) / step_s / hbm_peak
        row = {
            "metric": f"decode_tok_s_b{batch}",
            "value": round(tok_s, 1),
            "unit": f"tok/s decode-only ({n_params / 1e6:.0f}M params "
                    f"bf16, batch {batch}, prompt {t0_len}, "
                    f"{step_s * 1e3:.2f} ms/step, MBU {mbu:.2f}, "
                    f"first call incl compile {first_s:.0f}s, "
                    f"{jax.devices()[0].device_kind})",
            "vs_baseline": round(mbu, 3),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)
    if args.paged:
        row = _paged_row(params, cfg)
        rows.append(row)
        print(json.dumps(row), flush=True)
    if args.out:
        payload = {
            "note": "Decode (KV cache) on one real chip; per-step time "
                    "isolated by differencing 256- vs 32-token "
                    "generations (prefill cancels). vs_baseline "
                    "carries MBU (step bytes / step time / peak HBM "
                    "bw) - the bandwidth-roofline utilization, "
                    "decode's analog of MFU.",
            "rows": rows,
        }
        with open(args.out, "w") as f:
            json.dump(payload, f, indent=1)


if __name__ == "__main__":
    main()
