"""On-chip proof of the autotuner (HOROVOD_AUTOTUNE=1).

Reference analog: ``horovod/common/parameter_manager.cc`` + autotuning
docs — the reference tunes fusion-buffer size and cycle time online by
scoring realized bytes/sec; ours does the same with a Bayesian
optimizer over the (fusion_threshold, cycle_time) grid
(``csrc/parameter_manager.cc`` + ``csrc/bayes_opt.cc``).

This benchmark runs an EAGER training loop twice in one process:

1. autotune OFF, default knobs — baseline ms/step;
2. shutdown, re-init with ``HOROVOD_AUTOTUNE=1`` +
   ``HOROVOD_AUTOTUNE_LOG`` — run until the optimizer converges (the
   log stops changing knobs), then time steps at the converged
   operating point.

Two lanes:

- default: the GROUPED flagship row (one pre-grouped allreduce/step —
  bench.make_eager_step). r5 proved this a null result: with one
  fused tensor per step the fusion threshold has nothing to fuse.
- ``--ungrouped``: the per-parameter row (bench.
  make_eager_ungrouped_step — 183 small allreduces/step at the 809M
  20-layer geometry), where the fusion buffer and cycle time genuinely
  bind and the tuner has a number to move (VERDICT r5 #4). Unlike the
  grouped lane this one also runs on a CPU-only box: the knobs govern
  the CONTROL plane (enqueue batching, negotiation cadence), which the
  native core runs identically there — the row is labeled with its
  substrate either way.

Emits JSON rows and writes ``--out`` (e.g.
``benchmarks/results_r06_autotune.json``) with the warmup->converged
knob trajectory parsed from the autotune log::

    python benchmarks/autotune_bench.py --ungrouped [--out results.json]
"""

import argparse
import csv
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def _eager_loop(cfg, batch, seq, steps, warmup, make_step=None):
    """One eager-Horovod training run (bench.make_eager_step — the
    SAME step the eager bench row times — or any other builder, e.g.
    the ungrouped per-grad one); returns mean ms/step over the last
    ``steps`` steps (after ``warmup``)."""
    import numpy as np

    import bench
    import horovod_tpu.jax as hvd
    from horovod_tpu.jax import xla_ici

    hvd.init()
    if not xla_ici.active() and jax.devices()[0].platform != "cpu":
        xla_ici.enable()

    data = bench._data(cfg, batch, seq)
    try:
        step, carry, _ = (make_step or bench.make_eager_step)(cfg)
        loss, carry = step(carry, data)
        np.asarray(loss)
        for i in range(warmup):
            loss, carry = step(carry, data)
            if i % 16 == 15:   # bound async run-ahead (HBM)
                np.asarray(loss)
        np.asarray(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, carry = step(carry, data)
        np.asarray(loss)
        dt = (time.perf_counter() - t0) / steps
    finally:
        hvd.shutdown()
    return dt


def _parse_log(path):
    """(trajectory rows, converged knob dict). The tuner logs one CSV
    row per scored window, and on convergence appends a FINAL row at
    the chosen operating point (csrc/parameter_manager.cc), so
    rows[-1] is the knobs the post-convergence steps ran with. Missing
    or empty log -> empty trajectory (the measurements still count)."""
    rows = []
    try:
        with open(path) as f:
            for row in csv.DictReader(f):
                rows.append({
                    "fusion_threshold_bytes":
                        int(row["fusion_threshold_bytes"]),
                    "cycle_time_ms": float(row["cycle_time_ms"]),
                    "score_bytes_per_sec":
                        float(row["score_bytes_per_sec"]),
                })
    except OSError as e:
        print(f"autotune log unreadable ({e}); reporting empty "
              f"trajectory", file=sys.stderr)
    conv = ({"fusion_threshold_bytes":
             rows[-1]["fusion_threshold_bytes"],
             "cycle_time_ms": rows[-1]["cycle_time_ms"]}
            if rows else {})
    return rows, conv


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--steps", type=int, default=15)
    # The tuner scores one window per <=5 s of wall time and converges
    # after 20 samples (HOROVOD_AUTOTUNE_STEPS), so the tuning phase
    # needs ~20 x 5 s / step-time steps before the timed window.
    ap.add_argument("--tune-steps", type=int, default=200)
    ap.add_argument("--ungrouped", action="store_true",
                    help="per-parameter allreduces (183 small tensors/"
                         "step) instead of one grouped tree — the "
                         "workload where fusion/cycle knobs bind")
    # Scored windows before the tuner fixes its knobs
    # (HOROVOD_AUTOTUNE_STEPS; core default 20). The ungrouped lane's
    # windows span ~one step each (kMinWindowBytes closes fast on many
    # small tensors), so per-window scores are noisy and the Bayesian
    # optimizer wants more samples than the grouped lane needed.
    ap.add_argument("--autotune-steps", type=int, default=None)
    args = ap.parse_args()

    import bench
    from horovod_tpu.models import LlamaConfig

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu and not args.ungrouped:
        print("the grouped autotune lane needs an accelerator "
              "(use --ungrouped for the control-plane lane); skipping",
              file=sys.stderr)
        return

    if args.ungrouped:
        make_step = bench.make_eager_ungrouped_step
        if on_cpu:
            # Same 183-allreduce CONTROL-plane shape (9 stacked leaves
            # x 20 layers + 3), toy payloads: the fusion/cycle knobs
            # act on enqueue batching and negotiation cadence, which
            # the core runs identically on the CPU substrate.
            cfg = LlamaConfig.tiny(n_layers=20, dtype="float32")
            batch, seq = 2, 64
            lane = "ungrouped-per-grad (tiny model, cpu control-plane)"
        else:
            cfg = bench._same_size_cfg("bfloat16")   # 809M, 20 layers
            batch, seq = 4, 2048
            lane = "ungrouped-per-grad 809M"
        # Bursty per-grad traffic needs score windows spanning SEVERAL
        # steps (one gradient tree of bytes per step), or per-window
        # bytes/sec is dominated by where the window boundary lands in
        # the compute/allreduce burst cycle — set the floor to ~6 steps
        # of gradient bytes.
        import jax as _jax

        from horovod_tpu.models import llama_init
        shapes = _jax.eval_shape(
            lambda k: llama_init(cfg, k), _jax.random.PRNGKey(0))
        step_bytes = sum(x.size * x.dtype.itemsize
                         for x in _jax.tree.leaves(shapes))
        os.environ["HOROVOD_AUTOTUNE_WINDOW_BYTES"] = str(6 * step_bytes)
        os.environ["HOROVOD_AUTOTUNE_WINDOW_CYCLES"] = "40"
    else:
        make_step = None
        cfg = bench._flagship_cfg()
        batch, seq = 4, 2048
        lane = "grouped flagship"

    log_path = "/tmp/hvdtpu_autotune.csv"

    for k in ("HOROVOD_AUTOTUNE", "HOROVOD_AUTOTUNE_LOG"):
        os.environ.pop(k, None)
    dt_off = _eager_loop(cfg, batch, seq, args.steps, warmup=3,
                         make_step=make_step)

    os.environ["HOROVOD_AUTOTUNE"] = "1"
    os.environ["HOROVOD_AUTOTUNE_LOG"] = log_path
    if args.autotune_steps:
        os.environ["HOROVOD_AUTOTUNE_STEPS"] = str(args.autotune_steps)
    try:
        dt_on = _eager_loop(cfg, batch, seq, args.steps,
                            warmup=args.tune_steps, make_step=make_step)
    finally:
        for k in ("HOROVOD_AUTOTUNE", "HOROVOD_AUTOTUNE_LOG",
                  "HOROVOD_AUTOTUNE_STEPS",
                  "HOROVOD_AUTOTUNE_WINDOW_BYTES",
                  "HOROVOD_AUTOTUNE_WINDOW_CYCLES"):
            os.environ.pop(k, None)

    trajectory, converged = _parse_log(log_path)
    row = {
        "metric": "autotune_eager_step_ms",
        "value": round(dt_on * 1e3, 2),
        "unit": (f"ms/step eager {lane} at converged knobs "
                 f"(default knobs: {dt_off * 1e3:.2f} ms/step; "
                 f"converged: {converged}; "
                 f"{len(trajectory)} scored windows, "
                 f"{jax.devices()[0].device_kind})"),
        "vs_baseline": round(dt_off / dt_on, 4),
    }
    print(json.dumps(row), flush=True)
    if args.out:
        payload = {
            "note": f"HOROVOD_AUTOTUNE=1 over the eager {lane} "
                    "training loop (size-1 data plane: the knobs "
                    "govern the core's enqueue->negotiate->fuse "
                    "control path). vs_baseline = default-knob step "
                    "time / converged-knob step time (>1 means the "
                    "tuner helped). Trajectory = every scored "
                    "(fusion, cycle, bytes/sec) window from "
                    "HOROVOD_AUTOTUNE_LOG, in order.",
            "lane": lane,
            "substrate": str(jax.devices()[0].device_kind),
            "default_step_ms": round(dt_off * 1e3, 2),
            "converged_step_ms": round(dt_on * 1e3, 2),
            "converged_knobs": converged,
            "trajectory": trajectory,
            "rows": [row],
        }
        with open(args.out, "w") as f:
            json.dump(payload, f, indent=1)


if __name__ == "__main__":
    main()
