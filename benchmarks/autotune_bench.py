"""On-chip proof of the autotuner (HOROVOD_AUTOTUNE=1).

Reference analog: ``horovod/common/parameter_manager.cc`` + autotuning
docs — the reference tunes fusion-buffer size and cycle time online by
scoring realized bytes/sec; ours does the same with a Bayesian
optimizer over the (fusion_threshold, cycle_time) grid
(``csrc/parameter_manager.cc`` + ``csrc/bayes_opt.cc``).

This benchmark runs the EAGER flagship training loop (the same
grad -> hvd.grouped_allreduce -> adam shape as bench.py's eager row)
twice in one process on the real chip:

1. autotune OFF, default knobs — baseline ms/step;
2. shutdown, re-init with ``HOROVOD_AUTOTUNE=1`` +
   ``HOROVOD_AUTOTUNE_LOG`` — run until the optimizer converges (the
   log stops changing knobs), then time steps at the converged
   operating point.

Emits JSON rows and writes ``results_r05_autotune.json`` with the
warmup->converged knob trajectory parsed from the autotune log.

Run on a real TPU chip::

    python benchmarks/autotune_bench.py [--out results.json]
"""

import argparse
import csv
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def _eager_loop(cfg, batch, seq, steps, warmup):
    """One eager-Horovod training run (bench.make_eager_step — the
    SAME step the eager bench row times); returns mean ms/step over
    the last ``steps`` steps (after ``warmup``)."""
    import numpy as np

    import bench
    import horovod_tpu.jax as hvd
    from horovod_tpu.jax import xla_ici

    hvd.init()
    if not xla_ici.active() and jax.devices()[0].platform != "cpu":
        xla_ici.enable()

    data = bench._data(cfg, batch, seq)
    try:
        step, carry, _ = bench.make_eager_step(cfg)
        loss, carry = step(carry, data)
        np.asarray(loss)
        for i in range(warmup):
            loss, carry = step(carry, data)
            if i % 16 == 15:   # bound async run-ahead (HBM)
                np.asarray(loss)
        np.asarray(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, carry = step(carry, data)
        np.asarray(loss)
        dt = (time.perf_counter() - t0) / steps
    finally:
        hvd.shutdown()
    return dt


def _parse_log(path):
    """(trajectory rows, converged knob dict). The tuner logs one CSV
    row per scored window, and on convergence appends a FINAL row at
    the chosen operating point (csrc/parameter_manager.cc), so
    rows[-1] is the knobs the post-convergence steps ran with. Missing
    or empty log -> empty trajectory (the measurements still count)."""
    rows = []
    try:
        with open(path) as f:
            for row in csv.DictReader(f):
                rows.append({
                    "fusion_threshold_bytes":
                        int(row["fusion_threshold_bytes"]),
                    "cycle_time_ms": float(row["cycle_time_ms"]),
                    "score_bytes_per_sec":
                        float(row["score_bytes_per_sec"]),
                })
    except OSError as e:
        print(f"autotune log unreadable ({e}); reporting empty "
              f"trajectory", file=sys.stderr)
    conv = ({"fusion_threshold_bytes":
             rows[-1]["fusion_threshold_bytes"],
             "cycle_time_ms": rows[-1]["cycle_time_ms"]}
            if rows else {})
    return rows, conv


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--steps", type=int, default=15)
    # The tuner scores one window per <=5 s of wall time and converges
    # after 20 samples (HOROVOD_AUTOTUNE_STEPS), so the tuning phase
    # needs ~20 x 5 s / step-time steps before the timed window.
    ap.add_argument("--tune-steps", type=int, default=200)
    args = ap.parse_args()

    import bench

    if jax.devices()[0].platform == "cpu":
        print("autotune_bench needs an accelerator; skipping",
              file=sys.stderr)
        return

    cfg = bench._flagship_cfg()
    batch, seq = 4, 2048
    log_path = "/tmp/hvdtpu_autotune.csv"

    for k in ("HOROVOD_AUTOTUNE", "HOROVOD_AUTOTUNE_LOG"):
        os.environ.pop(k, None)
    dt_off = _eager_loop(cfg, batch, seq, args.steps, warmup=3)

    os.environ["HOROVOD_AUTOTUNE"] = "1"
    os.environ["HOROVOD_AUTOTUNE_LOG"] = log_path
    try:
        dt_on = _eager_loop(cfg, batch, seq, args.steps,
                            warmup=args.tune_steps)
    finally:
        for k in ("HOROVOD_AUTOTUNE", "HOROVOD_AUTOTUNE_LOG"):
            os.environ.pop(k, None)

    trajectory, converged = _parse_log(log_path)
    row = {
        "metric": "autotune_eager_step_ms",
        "value": round(dt_on * 1e3, 2),
        "unit": (f"ms/step eager flagship at converged knobs "
                 f"(default knobs: {dt_off * 1e3:.2f} ms/step; "
                 f"converged: {converged}; "
                 f"{len(trajectory)} scored windows, "
                 f"{jax.devices()[0].device_kind})"),
        "vs_baseline": round(dt_off / dt_on, 4),
    }
    print(json.dumps(row), flush=True)
    if args.out:
        payload = {
            "note": "HOROVOD_AUTOTUNE=1 over the eager flagship "
                    "training loop on one real chip (size-1 device "
                    "plane). vs_baseline = default-knob step time / "
                    "converged-knob step time (>1 means the tuner "
                    "helped). Trajectory = every scored "
                    "(fusion, cycle, bytes/sec) window from "
                    "HOROVOD_AUTOTUNE_LOG, in order.",
            "default_step_ms": round(dt_off * 1e3, 2),
            "converged_step_ms": round(dt_on * 1e3, 2),
            "converged_knobs": converged,
            "trajectory": trajectory,
            "rows": [row],
        }
        with open(args.out, "w") as f:
            json.dump(payload, f, indent=1)


if __name__ == "__main__":
    main()
