"""Single-chip long-context training benchmark (flash-kernel path).

Proves the net-new long-context stack's single-chip leg (SURVEY.md
§5.7): the streamed pallas flash kernels (VMEM O(block), independent of
sequence length — see ops/flash_attention.py) train the 1.39B flagship
at sequence lengths the round-3 kernels could not compile (scoped-VMEM
OOM in the backward at T=8192). The multi-chip leg (ring / Ulysses
sequence parallelism) reuses the same kernels via
``flash_attention_chunk``; this benchmark is the in-chip baseline those
paths are compared against.

Run on a real TPU chip::

    python benchmarks/long_context_bench.py [--out results.json]

Writes one row per (batch, seq) config: MFU, tokens/s, ms/step.
"""

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

# (batch, seq, remat): 8192+ tokens of context on ONE chip; t16384 at
# b1 is the largest activation footprint that fits beside the 1.39B
# model. The remat tradeoff flips with T: the flagship's "attn+gate"
# (save FFN gate residuals, skip their recompute) wins at t2048 but
# its per-layer [B,T,d_ff] saves grow linearly in T and OOM HBM at
# t8192 (19.4G needed) — the long rows drop back to "attn".
CONFIGS = [(4, 2048, None), (2, 8192, "attn"), (1, 16384, "attn")]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None, help="write JSON rows here")
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()

    import bench  # repo-root bench machinery (MFU accounting)

    if jax.devices()[0].platform == "cpu":
        print("long_context_bench needs an accelerator; skipping",
              file=sys.stderr)
        return

    rows = []
    for batch, seq, remat in CONFIGS:
        cfg = bench._flagship_cfg()
        if remat is not None:
            cfg = dataclasses.replace(cfg, remat=remat)
        t0 = time.time()
        row = bench.run_spmd(cfg, batch, seq, args.steps,
                             f"long_context_mfu_t{seq}",
                             f"pure-bf16 seq {seq}")
        row["wall_s"] = round(time.time() - t0, 1)
        rows.append(row)
        print(json.dumps(row), flush=True)
    if args.out:
        payload = {
            "note": "1.39B flagship, streamed flash kernels, one real "
                    "chip. t8192/t16384 rows were scoped-VMEM compile "
                    "errors before the r4 kernel streaming "
                    "(docs/benchmarks.md).",
            "rows": rows,
        }
        with open(args.out, "w") as f:
            json.dump(payload, f, indent=1)


if __name__ == "__main__":
    main()
