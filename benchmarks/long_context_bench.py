"""Single-chip long-context training benchmark (flash-kernel path).

Proves the net-new long-context stack's single-chip leg (SURVEY.md
§5.7): the streamed pallas flash kernels (VMEM O(block), independent of
sequence length — see ops/flash_attention.py) train the 1.39B flagship
at sequence lengths the round-3 kernels could not compile (scoped-VMEM
OOM in the backward at T=8192). The multi-chip leg (ring / Ulysses
sequence parallelism) reuses the same kernels via
``flash_attention_chunk``; this benchmark is the in-chip baseline those
paths are compared against.

Run on a real TPU chip::

    python benchmarks/long_context_bench.py [--out results.json]

Writes one row per (batch, seq) config: MFU, tokens/s, ms/step.
"""

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

# (batch, seq, remat): 8192+ tokens of context on ONE chip; t16384 at
# b1 is the largest activation footprint that fits beside the 1.4B
# model. The remat tradeoff flips with T: the flagship's "attn+gate"
# (save FFN gate residuals, skip their recompute) wins at t2048 but
# its per-layer [B,T,d_ff] saves grow linearly in T and OOM HBM at
# t8192 — the t8192 row drops to "attn", and at t16384 the r5 flagship
# geometry (d_ff 13312) needs full remat even for the flash residuals'
# neighbors to fit.
# Largest activation footprint FIRST: the t16384 row only fits on a
# virgin heap (the axon allocator fragments across configs — same
# behavior bench.py works around for its flagship row).
CONFIGS = [(1, 16384, True), (2, 8192, "attn"), (4, 2048, None)]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None, help="write JSON rows here")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--one", type=int, default=0,
                    help="child mode: run ONLY config #N (1-based)")
    args = ap.parse_args()

    import bench  # repo-root bench machinery (MFU accounting)

    if jax.devices()[0].platform == "cpu":
        print("long_context_bench needs an accelerator; skipping",
              file=sys.stderr)
        return

    if args.one:
        # Child mode: ONE config on a virgin heap. The fused step (not
        # the split grad/apply) — at these activation footprints the
        # split layout's non-donatable gradient copy is what OOMs.
        batch, seq, remat = CONFIGS[args.one - 1]
        cfg = bench._flagship_cfg()
        if remat is not None:
            cfg = dataclasses.replace(cfg, remat=remat)
        row = bench.run_spmd_fused(cfg, batch, seq, args.steps,
                                   f"long_context_mfu_t{seq}",
                                   f"pure-bf16 seq {seq}")
        print(json.dumps(row), flush=True)
        return

    # Orchestrator: one subprocess per config — every row gets a virgin
    # heap (the axon allocator fragments across configs; the t16384 row
    # does not survive any same-process predecessor) and a failing row
    # cannot take the others down.
    import subprocess

    rows = []
    for i in range(1, len(CONFIGS) + 1):
        batch, seq, remat = CONFIGS[i - 1]
        t0 = time.time()
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--one",
                 str(i), "--steps", str(args.steps)],
                capture_output=True, text=True, timeout=540, check=True)
            row = None
            for line in reversed(out.stdout.strip().splitlines()):
                try:
                    row = json.loads(line)
                    break
                except json.JSONDecodeError:
                    continue
            if row is None:
                raise RuntimeError(
                    f"no row in child output: {out.stdout[-200:]!r}")
        except Exception as e:  # noqa: BLE001 — keep the other rows
            row = {"metric": f"long_context_mfu_t{seq}",
                   "error": f"{type(e).__name__}: {str(e)[:200]}"}
        row["wall_s"] = round(time.time() - t0, 1)
        rows.append(row)
        print(json.dumps(row), flush=True)
    if args.out:
        payload = {
            "note": "1.4B flagship, streamed flash kernels, one real "
                    "chip; one subprocess per row (virgin heap). "
                    "t8192/t16384 rows were scoped-VMEM compile errors "
                    "before the r4 kernel streaming "
                    "(docs/benchmarks.md).",
            "rows": rows,
        }
        with open(args.out, "w") as f:
            json.dump(payload, f, indent=1)


if __name__ == "__main__":
    main()
