"""Ring-allreduce bus-bandwidth micro-benchmark (BASELINE.json's
north-star transport metric).

Run under the launcher, one process per rank:

    horovodrun -np 4 python benchmarks/allreduce_bench.py \
        --size-mb 64 --iters 10

Every rank allreduces a float32 buffer; rank 0 prints one JSON line with
the achieved algorithm bandwidth (payload/time) and bus bandwidth
(the ring moves 2(N-1)/N x payload per rank, the standard NCCL-tests
convention), for both the first (cold negotiation) and steady-state
(response-cache bitvector) iterations.

On a TPU pod with the xla_ici device plane enabled the same script
measures HBM-to-HBM collectives over ICI; on CPU hosts it measures the
native host TCP ring.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size-mb", type=float, default=64.0)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--grouped", type=int, default=0,
                    help="split the payload into N tensors fused by the "
                         "runtime (exercises the fusion buffer)")
    ap.add_argument("--op", default="allreduce",
                    choices=("allreduce", "adasum", "allgather",
                             "reducescatter"),
                    help="collective to time; adasum = allreduce with "
                         "op=Adasum (device-plane recursive doubling); "
                         "allgather/reducescatter require --grouped")
    args = ap.parse_args()
    if args.op in ("allgather", "reducescatter") and not args.grouped:
        ap.error(f"--op {args.op} requires --grouped (the grouped "
                 f"variants are the benched surface)")

    # Honor JAX_PLATFORMS at the config level: some images register an
    # accelerator plugin in sitecustomize that overrides the env var, and
    # a host-ring benchmark must not bounce its outputs through an
    # accelerator transfer per iteration.
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import horovod_tpu.jax as hvd
    from horovod_tpu.jax import xla_ici

    hvd.init()
    n = hvd.size()
    elems = int(args.size_mb * (1 << 20) / 4)
    payload_bytes = elems * 4

    # Allocate ONCE, outside the timed region (NCCL-tests convention).
    # The xla_ici device plane only engages for jax.Array inputs, so on
    # TPU the payload must be a device array (HBM-to-HBM over ICI);
    # numpy would silently fall back to the host ring. On the host ring
    # numpy is the honest choice — jax arrays would just add two copies
    # per iteration.
    device_plane = xla_ici.active()

    def make(arr):
        if device_plane:
            import jax.numpy as jnp

            return jnp.asarray(arr)
        return arr

    base = np.full(elems, float(hvd.rank() + 1), np.float32)
    if args.grouped:
        parts = [make(p) for p in np.array_split(base, args.grouped)]
    else:
        payload = make(base)

    def materialize(out):
        # Completion probe must match the plane: on the device plane the
        # result lives in HBM and np.asarray would time a full
        # device→host transfer (over a tunnel, dwarfing the collective);
        # block_until_ready is the honest fence there. The host ring's
        # result is already host memory.
        if device_plane:
            import jax

            jax.block_until_ready(out)
        else:
            np.asarray(out)

    names = [f"bench.g{j}" for j in range(args.grouped or 0)]

    def one_iter(i):
        t0 = time.perf_counter()
        if args.op == "allgather":
            outs = hvd.grouped_allgather(parts, names=names)
            materialize(outs[0])
        elif args.op == "reducescatter":
            outs = hvd.grouped_reducescatter(parts, names=names,
                                             op=hvd.Sum)
            materialize(outs[0])
        elif args.grouped:
            op = hvd.Adasum if args.op == "adasum" else hvd.Sum
            outs = hvd.grouped_allreduce(parts, names=names, op=op)
            materialize(outs[0])
        else:
            op = hvd.Adasum if args.op == "adasum" else hvd.Sum
            out = hvd.allreduce(payload, name="bench.allreduce", op=op)
            materialize(out)
        return time.perf_counter() - t0

    cold = one_iter(0)
    times = [one_iter(i + 1) for i in range(args.iters)]
    steady = float(np.median(times))

    if hvd.rank() == 0:
        # NCCL-tests bus-bandwidth conventions per collective: the ring
        # moves 2(N-1)/N x payload per rank for allreduce-likes and
        # (N-1)/N for allgather/reducescatter.
        if args.op in ("allgather", "reducescatter"):
            bus_factor = (n - 1) / n
        else:
            bus_factor = 2.0 * (n - 1) / n
        print(json.dumps({
            "metric": f"ring_{args.op}_bandwidth",
            "op": args.op,
            "plane": "xla_ici" if device_plane else "host_ring",
            "ranks": n,
            "payload_mb": round(payload_bytes / (1 << 20), 2),
            "grouped": args.grouped,
            "cold_s": round(cold, 4),
            "steady_s": round(steady, 4),
            "algo_gbps": round(payload_bytes / steady / 1e9, 3),
            "bus_gbps": round(payload_bytes * bus_factor / steady / 1e9, 3),
        }), flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    sys.exit(main())
