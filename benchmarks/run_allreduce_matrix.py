"""Drive benchmarks/allreduce_bench.py over a plane × ranks × payload ×
grouping matrix and assemble benchmarks/results_r{N}.json.

Reference analog: the reference's perf story benches NCCL at up to 128
GPUs (``ops/nccl_operations.cc`` scaling claims, docs/benchmarks.rst);
this matrix is its single-box analog: the xla_ici device plane at 1-4
ranks (forced-CPU jax devices when no multi-chip hardware — the same
substrate tests/parallel/test_xla_ici.py uses) plus the host TCP ring,
cold (first negotiation + compile) vs steady state (response-cache
bitvector + executable replay).

Usage: python benchmarks/run_allreduce_matrix.py [--out results.json]
       [--skip-tpu]

Absolute GB/s on a one-core box is scheduler-limited noise for ranks>1
(every rank shares the core); ratios (cold/steady, grouped/flat) and
bus_gbps>0 are the meaningful signals there. The single-rank TPU row
measures real replay latency on the chip.
"""

import argparse
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_case(plane, ranks, size_mb, grouped, op="allreduce", iters=10,
             timeout=600):
    """One launcher run; returns the parsed JSON row or an error row."""
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    if plane == "xla_ici_cpu":
        env["JAX_PLATFORMS"] = "cpu"
        env["HOROVOD_XLA_DATA_PLANE"] = "1"
    elif plane == "host_ring":
        env["JAX_PLATFORMS"] = "cpu"
        env["HOROVOD_XLA_DATA_PLANE"] = "0"
    elif plane == "xla_ici_tpu":
        env.pop("JAX_PLATFORMS", None)
        env["HOROVOD_XLA_DATA_PLANE"] = "1"
        # The axon sitecustomize lives on PYTHONPATH; keep it reachable
        # alongside the repo (clobbering it kills the TPU plugin).
        axon = "/root/.axon_site"
        if os.path.isdir(axon):
            env["PYTHONPATH"] += os.pathsep + axon
    cmd = [sys.executable, "-m", "horovod_tpu.runner.launch", "-np",
           str(ranks), sys.executable,
           os.path.join(ROOT, "benchmarks", "allreduce_bench.py"),
           "--size-mb", str(size_mb), "--iters", str(iters),
           "--op", op]
    if grouped:
        cmd += ["--grouped", str(grouped)]
    t0 = time.time()
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout, cwd=ROOT)
    row = None
    for line in proc.stdout.splitlines():
        line = line.strip()
        # launcher prefixes rank output; the JSON row is rank 0's line
        idx = line.find('{"metric"')
        if idx >= 0:
            try:
                row = json.loads(line[idx:])
            except json.JSONDecodeError:
                pass
    if row is None:
        return {"metric": f"ring_{op}_bandwidth", "op": op,
                "plane": plane,
                "ranks": ranks, "payload_mb": size_mb, "grouped": grouped,
                "error": (proc.stderr or proc.stdout)[-400:],
                "rc": proc.returncode}
    row["plane_config"] = plane
    row["wall_s"] = round(time.time() - t0, 1)
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        ROOT, "benchmarks", "results_r05.json"))
    ap.add_argument("--skip-tpu", action="store_true")
    args = ap.parse_args()

    cases = [
        # The headline: device plane at N>1 — fused-program scaling.
        ("xla_ici_cpu", 2, 8, 0, "allreduce"),
        ("xla_ici_cpu", 2, 64, 0, "allreduce"),
        ("xla_ici_cpu", 4, 8, 0, "allreduce"),
        ("xla_ici_cpu", 4, 64, 0, "allreduce"),
        # r5: the full 8-rank timing matrix (r4 proved 8-rank
        # CORRECTNESS only — tests/parallel/test_xla_ici.py).
        ("xla_ici_cpu", 8, 8, 0, "allreduce"),
        ("xla_ici_cpu", 8, 64, 0, "allreduce"),
        ("xla_ici_cpu", 8, 8, 64, "allreduce"),
        # Device-plane Adasum (recursive doubling) + the grouped
        # allgather/reducescatter surfaces, previously unbenched.
        ("xla_ici_cpu", 4, 8, 0, "adasum"),
        ("xla_ici_cpu", 8, 8, 0, "adasum"),
        ("xla_ici_cpu", 8, 8, 16, "allgather"),
        ("xla_ici_cpu", 8, 8, 16, "reducescatter"),
        # 64-tensor fused group through ONE compiled program.
        ("xla_ici_cpu", 2, 8, 64, "allreduce"),
        ("xla_ici_cpu", 4, 8, 64, "allreduce"),
        # Host TCP ring for continuity with r02.
        ("host_ring", 2, 8, 0, "allreduce"),
        ("host_ring", 4, 8, 0, "allreduce"),
    ]
    if not args.skip_tpu:
        # Real-chip single-rank replay latency (r02 continuity).
        cases += [("xla_ici_tpu", 1, 8, 0, "allreduce"),
                  ("xla_ici_tpu", 1, 64, 0, "allreduce"),
                  ("xla_ici_tpu", 1, 8, 64, "allreduce")]

    rows = []
    for plane, ranks, mb, grouped, op in cases:
        print(f"== {plane} ranks={ranks} {mb}MB grouped={grouped} "
              f"op={op}", file=sys.stderr)
        row = run_case(plane, ranks, mb, grouped, op)
        if "error" in row:
            # One retry: rendezvous port binds occasionally race on a
            # busy box (observed rate ~1/15 launches).
            print("retrying after error", file=sys.stderr)
            row = run_case(plane, ranks, mb, grouped, op)
        print(json.dumps(row), file=sys.stderr)
        rows.append(row)

    out = {
        "note": ("xla_ici_cpu rows run the REAL device data plane "
                 "(negotiation + cached fused XLA programs) on forced-CPU "
                 "jax devices — the no-hardware substrate; on one core, "
                 "absolute GB/s at ranks>1 is scheduler-bound, so read "
                 "cold/steady and grouped ratios, not GB/s. xla_ici_tpu "
                 "rows are the real chip (single rank: replay latency). "
                 "host_ring rows are the native TCP ring."),
        "rows": rows,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
