"""Single-chip sparse-MoE training benchmark (dropless grouped-GEMM).

The MoE stack is net-new vs the reference (Horovod has no model layer
at all). The single-chip training path dispatches via the dropless
sorted grouped-GEMM (``ops/grouped_moe.py``: argsort by expert +
megablox ragged matmuls — no capacity factor, no one-hot dispatch
einsums, no dropped tokens); expert-parallel meshes use the GShard
einsum path instead. This benchmark trains a 1.49B-total /
889M-active MoE decoder on the real chip and reports MFU against
ACTIVE parameters — the standard sparse accounting (a routed token
runs K of E experts, so its model FLOPs are 6·N_active, not
6·N_total).

Run on a real TPU chip::

    python benchmarks/moe_bench.py [--out results.json]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax


def _moe_cfg():
    from horovod_tpu.models import LlamaConfig

    # Sized for one 16G chip in pure bf16 (params+grads+2 adam moments
    # = 8 bytes/param): 4 experts top-2 halves the FFN FLOPs per token
    # while the parameter count stays flagship-class. The default
    # moe_impl="auto" resolves to the dropless grouped-GEMM dispatch
    # (ops/grouped_moe.py) on the single-chip program — no capacity
    # padding, no one-hot dispatch einsums. remat="attn+moe"
    # additionally saves the per-layer y_slots residual ([S*K, D] bf16)
    # so backward skips the down-projection GEMM re-run, and
    # scan_unroll turns the stacked expert-weight dynamic slices
    # static (r5 sweep: 563 -> 495 ms/step all-in vs the r4 GShard
    # path).
    return LlamaConfig(vocab_size=32768, d_model=2048, n_layers=12,
                       n_heads=16, n_kv_heads=8, d_ff=4096,
                       n_experts=4, n_experts_per_token=2,
                       dtype="bfloat16", remat="attn+moe",
                       param_dtype="bfloat16", scan_unroll=12)


def _active_params(params, cfg):
    """Total minus the (E-K)/E share of expert weights a token never
    touches."""
    total = sum(x.size for x in jax.tree.leaves(params))
    expert = sum(
        x.size for name, x in params["layers"].items()
        if name.startswith("moe_"))
    inactive = expert * (cfg.n_experts - cfg.n_experts_per_token) \
        // cfg.n_experts
    return total, total - inactive


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()

    import functools

    import jax.numpy as jnp
    import optax

    import bench
    from horovod_tpu.models import llama_init, llama_loss

    if jax.devices()[0].platform == "cpu":
        print("moe_bench needs an accelerator; skipping", file=sys.stderr)
        return

    cfg = _moe_cfg()
    batch, seq = 4, 2048
    params = llama_init(cfg, jax.random.PRNGKey(0))
    total, active = _active_params(params, cfg)
    tx = optax.adam(3e-4)
    carry = (params, tx.init(params))
    del params

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(carry, data):
        params, opt = carry
        loss, grads = jax.value_and_grad(llama_loss)(params, data, cfg)
        updates, opt = tx.update(grads, opt, params)
        return loss, (optax.apply_updates(params, updates), opt)

    t0 = time.time()
    dt = bench._timed(step, carry, bench._data(cfg, batch, seq),
                      args.steps, "moe_train_step_mfu")
    row = bench._mfu_row(
        "moe_train_step_mfu",
        f"sparse MoE E{cfg.n_experts} top-{cfg.n_experts_per_token}, "
        f"{total / 1e6:.0f}M total / {active / 1e6:.0f}M active",
        active, cfg, batch, seq,
        dt)
    row["wall_s"] = round(time.time() - t0, 1)
    print(json.dumps(row), flush=True)
    if args.out:
        payload = {
            "note": "MoE decoder on one real chip; MFU counts ACTIVE "
                    "params (6*N_active + attention) per the standard "
                    "sparse accounting. Dropless sorted grouped-GEMM "
                    "dispatch (megablox), remat=attn+moe, unrolled "
                    "layer scan; every routed token-slot is computed "
                    "(no capacity factor, no drops).",
            "rows": [row],
        }
        with open(args.out, "w") as f:
            json.dump(payload, f, indent=1)


if __name__ == "__main__":
    main()
