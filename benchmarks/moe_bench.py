"""Single-chip sparse-MoE training benchmark (dropless grouped-GEMM).

The MoE stack is net-new vs the reference (Horovod has no model layer
at all). The single-chip training path dispatches via the dropless
sorted grouped-GEMM (``ops/grouped_moe.py``: argsort by expert +
megablox ragged matmuls — no capacity factor, no one-hot dispatch
einsums, no dropped tokens); expert-parallel meshes use the GShard
einsum path instead. This benchmark trains a 1.49B-total /
889M-active MoE decoder on the real chip and reports MFU against
ACTIVE parameters — the standard sparse accounting (a routed token
runs K of E experts, so its model FLOPs are 6·N_active, not
6·N_total).

Round-6 attack on the 0.55 wall: the default configuration is now the
SPLIT-PROGRAM step (``parallel.make_split_train_step``) with
``remat="moe"`` (backward re-runs NO grouped matmul) and 2-way
microbatch gradient accumulation — the formulation r5 identified but
could not run, because the same math as ONE monolithic jit crashes
this environment's AOT compile helper (HTTP 500; see
``benchmarks/aot_crash_repro.py``). The split step compiles the
per-microbatch grad program and the single-pass fused-adam apply
program separately and never hands the helper the
full-save+microbatch monolith. If the attack config still fails here,
this bench FAILS LOUDLY (nonzero rc) instead of silently skipping —
the r5 silent-skip is what hid the blocker for a round. The r5
configuration is reachable as
``--remat attn+moe --microbatches 1 --update split``.

Run on a real TPU chip::

    python benchmarks/moe_bench.py [--out results.json]
"""

import argparse
import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax


def _moe_cfg(remat="moe"):
    from horovod_tpu.models import LlamaConfig

    # Sized for one 16G chip in pure bf16 (params+grads+2 adam moments
    # = 8 bytes/param): 4 experts top-2 halves the FFN FLOPs per token
    # while the parameter count stays flagship-class. The default
    # moe_impl="auto" resolves to the dropless grouped-GEMM dispatch
    # (ops/grouped_moe.py) on the single-chip program — no capacity
    # padding, no one-hot dispatch einsums. remat="moe" saves the whole
    # expert chain (x_sorted, pre-silu gate, up, y_slots) so backward
    # re-runs NO grouped matmul; its HBM price is what the microbatch
    # accumulation pays for. scan_unroll turns the stacked
    # expert-weight dynamic slices static (r5 sweep: -24 ms/step).
    return LlamaConfig(vocab_size=32768, d_model=2048, n_layers=12,
                       n_heads=16, n_kv_heads=8, d_ff=4096,
                       n_experts=4, n_experts_per_token=2,
                       dtype="bfloat16", remat=remat,
                       param_dtype="bfloat16", scan_unroll=12)


def _active_params(params, cfg):
    """Total minus the (E-K)/E share of expert weights a token never
    touches."""
    total = sum(x.size for x in jax.tree.leaves(params))
    expert = sum(
        x.size for name, x in params["layers"].items()
        if name.startswith("moe_"))
    inactive = expert * (cfg.n_experts - cfg.n_experts_per_token) \
        // cfg.n_experts
    return total, total - inactive


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--microbatches", type=int, default=2,
                    help="grad-accumulation microbatches (2 = the r6 "
                         "attack config; 1 = monolithic-batch grad "
                         "program)")
    ap.add_argument("--remat", default="moe",
                    help="remat save-set (moe = r6 attack; attn+moe = "
                         "the r5 configuration)")
    ap.add_argument("--update", default="fused",
                    choices=("fused", "split"),
                    help="optimizer apply: single-pass fused adam vs "
                         "optax split apply")
    ap.add_argument("--batch", type=int, default=4)
    args = ap.parse_args()

    import optax

    import bench
    from horovod_tpu.models import llama_init, llama_loss
    from horovod_tpu.parallel import fused_adam, make_split_train_step

    if jax.devices()[0].platform == "cpu":
        print("moe_bench needs an accelerator; skipping", file=sys.stderr)
        return

    cfg = _moe_cfg(args.remat)
    batch, seq = args.batch, 2048
    # Param counts from shapes only — no device allocation yet.
    shapes = jax.eval_shape(lambda k: llama_init(cfg, k),
                            jax.random.PRNGKey(0))
    total, active = _active_params(shapes, cfg)
    tx = (fused_adam(3e-4) if args.update == "fused"
          else optax.adam(3e-4))
    ts = make_split_train_step(
        lambda p, d: llama_loss(p, d, cfg), tx,
        microbatches=args.microbatches)

    t0 = time.time()
    try:
        # Initial carry passed as a TEMPORARY (no caller-held reference
        # to the donated buffers — the axon ghost-copy rule, see
        # bench.run_spmd).
        dt = bench._timed(ts.step,
                          ts.init(llama_init(cfg, jax.random.PRNGKey(0))),
                          bench._data(cfg, batch, seq),
                          args.steps, "moe_train_step_mfu")
    except Exception:
        # LOUD failure (nonzero rc): r5's silent skip is what hid the
        # AOT-helper blocker for a whole round. The traceback is the
        # artifact; aot_crash_repro.py minimizes it.
        traceback.print_exc()
        print(
            f"MOE BENCH FAILED: the attack config (split-program step, "
            f"remat={args.remat!r}, {args.microbatches}-way microbatch "
            f"accumulation, update={args.update!r}) did not complete. "
            f"If this is the AOT compile helper crash (HTTP 500), "
            f"reproduce/minimize with benchmarks/aot_crash_repro.py; "
            f"the r5 configuration is `--remat attn+moe "
            f"--microbatches 1 --update split` (0.494 active-MFU).",
            file=sys.stderr)
        sys.exit(2)
    row = bench._mfu_row(
        "moe_train_step_mfu",
        f"sparse MoE E{cfg.n_experts} top-{cfg.n_experts_per_token}, "
        f"{total / 1e6:.0f}M total / {active / 1e6:.0f}M active, "
        f"remat={args.remat}, accum{args.microbatches}, "
        f"update-{args.update}",
        active, cfg, batch, seq,
        dt)
    row["wall_s"] = round(time.time() - t0, 1)
    print(json.dumps(row), flush=True)
    if args.out:
        payload = {
            "note": "MoE decoder on one real chip; MFU counts ACTIVE "
                    "params (6*N_active + attention) per the standard "
                    "sparse accounting. Dropless sorted grouped-GEMM "
                    "dispatch (megablox), split-program train step "
                    f"(remat={args.remat}, {args.microbatches}-way "
                    "microbatch grad accumulation, "
                    f"{args.update} adam apply); every routed "
                    "token-slot is computed (no capacity factor, no "
                    "drops).",
            "rows": [row],
        }
        with open(args.out, "w") as f:
            json.dump(payload, f, indent=1)


if __name__ == "__main__":
    main()
