"""Single-chip ResNet-50 training throughput (images/sec).

The reference's headline benchmark family is tf_cnn_benchmarks
ResNet/Inception images-per-second at scale (BASELINE.md: ~90% of
linear at 128 GPUs; BASELINE.json target: ResNet-50 images/sec/chip
with >=90% scaling efficiency). Multi-chip scaling needs a pod; this
bench records the per-chip leg on real hardware — synthetic ImageNet
(224x224), bf16 compute, SGD+momentum, one fused jit train step, the
same shape the reference benches.

Run on a real TPU chip::

    python benchmarks/resnet_bench.py [--out results.json]
"""

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()

    import jax.numpy as jnp
    import numpy as np
    import optax

    from horovod_tpu.models import (
        ResNetConfig,
        resnet_init,
        resnet_loss,
    )

    if jax.devices()[0].platform == "cpu":
        print("resnet_bench needs an accelerator; skipping",
              file=sys.stderr)
        return

    cfg = ResNetConfig(depth=50)
    params, state = resnet_init(cfg, jax.random.PRNGKey(0))
    n_params = sum(x.size for x in jax.tree.leaves(params))
    tx = optax.sgd(0.1, momentum=0.9)
    carry = (params, state, tx.init(params))
    del params, state

    images = jax.random.normal(jax.random.PRNGKey(1),
                               (args.batch, 224, 224, 3), jnp.bfloat16)
    labels = jax.random.randint(jax.random.PRNGKey(2), (args.batch,),
                                0, cfg.num_classes)
    batch = {"images": images, "labels": labels}

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(carry, batch):
        params, state, opt = carry
        (loss, state), grads = jax.value_and_grad(
            resnet_loss, has_aux=True)(params, state, batch, cfg)
        updates, opt = tx.update(grads, opt, params)
        return loss, (optax.apply_updates(params, updates), state, opt)

    t0 = time.time()
    loss, carry = step(carry, batch)
    # Materialize to host: block_until_ready returns early on some
    # PJRT transports (see decode_bench).
    np.asarray(loss)
    first_s = time.time() - t0
    t0 = time.time()
    for _ in range(args.steps):
        loss, carry = step(carry, batch)
    np.asarray(loss)
    dt = (time.time() - t0) / args.steps
    img_s = args.batch / dt
    # The reference's public per-GPU figure for context: ~195 img/s on
    # a Pascal P100 (tf_cnn_benchmarks era); modern accelerators are
    # far past it — vs_baseline normalizes against 1000 img/s/chip as
    # a round contemporary bar.
    row = {
        "metric": "resnet50_img_s",
        "value": round(img_s, 1),
        "unit": f"images/s ({n_params / 1e6:.0f}M params, ResNet-50 "
                f"bf16 train, batch {args.batch}, 224x224 synthetic, "
                f"{dt * 1e3:.0f} ms/step, first call incl compile "
                f"{first_s:.0f}s, {jax.devices()[0].device_kind})",
        "vs_baseline": round(img_s / 1000.0, 3),
    }
    print(json.dumps(row), flush=True)
    if args.out:
        payload = {
            "note": "ResNet-50 bf16 training on one real chip, "
                    "synthetic 224x224 ImageNet (the reference's "
                    "tf_cnn_benchmarks shape). vs_baseline normalizes "
                    "by a 1000 img/s/chip contemporary bar.",
            "rows": [row],
        }
        with open(args.out, "w") as f:
            json.dump(payload, f, indent=1)


if __name__ == "__main__":
    main()
