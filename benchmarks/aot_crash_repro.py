"""Minimal repro for the AOT-compile-helper crash (HTTP 500, exit 1).

Context (rounds 5-6, one TPU v5e 16G chip on the axon transport): the
MoE configuration that should clear the 0.55 active-MFU bar —
``remat="moe"`` (save the full expert chain; backward re-runs no
grouped matmul) combined with microbatch gradient accumulation —
CRASHES this environment's out-of-process AOT TPU compile helper when
expressed as one monolithic jit. The helper dies with an HTTP 500
rather than reporting a clean OOM or a compile diagnostic, so the
failure class is indistinguishable from infrastructure flake without a
minimal repro. This script is that repro: each documented formulation
is compiled (never executed) in its own subprocess via
``jit(...).lower().compile()``, and the script reports which
formulations crash the helper.

Documented crashing formulations (reproduced r5, on-chip):

1. ``scan``      — remat="moe" fwd+bwd+adam, 2-way microbatch
                   accumulation as a ``lax.scan`` over the microbatch
                   axis, one jit.
2. ``unrolled``  — the same with the two microbatch grad computations
                   unrolled as straight-line Python inside one jit
                   (rules out scan-specific compiler paths).
3. ``bigtile``   — single-batch remat="moe" monolith with grouped-GEMM
                   tilings above 1024 in the contraction/output
                   directions ((512, 2048, 1024)); crashes even
                   WITHOUT microbatching — evidence the helper limit
                   is program/working-set size, not the accumulation
                   loop.

The control (``split``) lowers the SAME math as the r6 split-program
step — a per-microbatch grad program plus a fused-adam apply program,
compiled separately — and is expected to compile everywhere; it is how
``benchmarks/moe_bench.py`` now runs the attack config.

Exit code: 0 when every monolithic formulation compiles (the
environment is fixed — retire this script and re-run the monolith
sweep); 1 when any formulation crashes (the blocker reproduces).
On CPU hosts: prints a note and exits 0 (the helper is TPU-side).

Usage::

    python benchmarks/aot_crash_repro.py            # run all cases
    python benchmarks/aot_crash_repro.py --case scan  # one, in-process
"""

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

CASES = ("scan", "unrolled", "bigtile", "split")


def _cfg(remat="moe"):
    # The exact bench MoE geometry, imported (not copied) from
    # benchmarks/moe_bench.py: the crash is shape-dependent — tiny
    # shapes compile fine — so the repro must pin whatever config the
    # bench actually runs, including future geometry changes.
    from benchmarks.moe_bench import _moe_cfg

    return _moe_cfg(remat)


def _compile_case(case):
    """Lower + AOT-compile one formulation in THIS process. Raises (or
    the helper kills the process) on the crash."""
    import jax
    import jax.numpy as jnp

    from horovod_tpu.models import llama_init, llama_loss
    from horovod_tpu.parallel import fused_adam, make_split_train_step

    cfg = _cfg()
    B, T, M = 4, 2048, 2
    key = jax.random.PRNGKey(0)
    shapes = jax.eval_shape(lambda k: llama_init(cfg, k), key)
    data = jax.eval_shape(
        lambda: {"tokens": jnp.zeros((B, T), jnp.int32),
                 "targets": jnp.zeros((B, T), jnp.int32)})
    tx = fused_adam(3e-4)
    opt_shapes = jax.eval_shape(tx.init, shapes)

    def loss_fn(p, d):
        return llama_loss(p, d, cfg)

    if case == "split":
        # Control: the r6 split-program formulation — grad program and
        # apply program lowered/compiled SEPARATELY. Expected to
        # compile everywhere.
        mb = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct((s.shape[0] // M,)
                                           + s.shape[1:], s.dtype),
            data)
        grad = jax.jit(lambda p, d: jax.value_and_grad(
            lambda pp, dd: loss_fn(pp, dd) / M)(p, d))
        grad.lower(shapes, mb).compile()
        apply = jax.jit(tx.apply, donate_argnums=(0, 2))
        apply.lower(shapes, shapes, opt_shapes).compile()
        return

    if case == "bigtile":
        # Monolith WITHOUT microbatching, but with grouped-GEMM tile
        # sizes above 1024 — crashes the helper on its own.
        from horovod_tpu.ops import grouped_moe

        grouped_moe._TILING = (512, 2048, 1024)
        grouped_moe._TILING_DLHS = (512, 2048, 1024)
        grouped_moe._TILING_TGMM = (512, 2048, 1024)

        def step(carry, d):
            params, opt = carry
            loss, g = jax.value_and_grad(loss_fn)(params, d)
            params, opt = tx.apply(params, g, opt)
            return loss, (params, opt)

        jax.jit(step, donate_argnums=(0,)).lower(
            (shapes, opt_shapes), data).compile()
        return

    # The two microbatch-accumulation monoliths: ONE jit containing
    # fwd+bwd per microbatch (remat="moe") + the adam apply.
    def mono(carry, d):
        params, opt = carry
        mbs = jax.tree.map(
            lambda x: x.reshape((M, B // M) + x.shape[1:]), d)
        if case == "scan":
            def body(acc, mb):
                loss, g = jax.value_and_grad(
                    lambda p, dd: loss_fn(p, dd) / M)(params, mb)
                return (acc[0] + loss,
                        jax.tree.map(jnp.add, acc[1], g)), None
            zero = (jnp.zeros((), jnp.float32),
                    jax.tree.map(jnp.zeros_like, params))
            (loss, grads), _ = jax.lax.scan(body, zero, mbs)
        elif case == "unrolled":
            loss, grads = None, None
            for i in range(M):
                mb = jax.tree.map(lambda x: x[i], mbs)
                li, gi = jax.value_and_grad(
                    lambda p, dd: loss_fn(p, dd) / M)(params, mb)
                loss = li if loss is None else loss + li
                grads = gi if grads is None else jax.tree.map(
                    jnp.add, grads, gi)
        else:
            raise ValueError(f"unknown case {case!r}")
        params, opt = tx.apply(params, grads, opt)
        return loss, (params, opt)

    jax.jit(mono, donate_argnums=(0,)).lower(
        (shapes, opt_shapes), data).compile()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--case", choices=CASES, default=None,
                    help="compile ONE formulation in-process (used by "
                         "the per-case subprocesses)")
    ap.add_argument("--timeout", type=int, default=1200)
    args = ap.parse_args()

    import jax

    if jax.devices()[0].platform == "cpu":
        print("aot_crash_repro targets the TPU AOT compile helper; "
              "nothing to reproduce on CPU", file=sys.stderr)
        return

    if args.case:
        _compile_case(args.case)
        print(f"case {args.case}: compiled OK", flush=True)
        return

    results = {}
    for case in CASES:
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--case", case],
                capture_output=True, text=True, timeout=args.timeout)
        except subprocess.TimeoutExpired:
            # A hung compile is a distinct observation from the HTTP
            # 500 crash — record it and keep sweeping the other cases.
            results[case] = f"HUNG after {args.timeout} s"
            print(f"[{case}] {results[case]}", flush=True)
            continue
        ok = proc.returncode == 0
        results[case] = "compiled" if ok else (
            f"CRASHED rc={proc.returncode}: "
            + proc.stderr.strip().splitlines()[-1][:200]
            if proc.stderr.strip() else f"CRASHED rc={proc.returncode}")
        print(f"[{case}] {results[case]}", flush=True)
    print(json.dumps(results), flush=True)
    if results.get("split") != "compiled":
        # The control failing is WORSE than the blocker reproducing:
        # the split formulation is the path moe_bench ships on.
        print("NOTE: the split-program CONTROL failed — the failure is "
              "not monolith-specific; investigate the environment "
              "before trusting any monolith result above.",
              file=sys.stderr)
        sys.exit(1)
    if not all(v == "compiled" for k, v in results.items()
               if k != "split"):
        sys.exit(1)
    print("every monolithic formulation compiled — the AOT helper "
          "blocker is gone; re-run the remat='moe' monolith sweep and "
          "retire this repro.", flush=True)


if __name__ == "__main__":
    main()
