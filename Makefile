# Build the native core runtime (csrc/ -> horovod_tpu/lib/libhvdtpu_core.so).
# Reference analog: horovod's CMake-driven per-framework extensions
# (setup.py + CMakeLists.txt). Ours is a single framework-agnostic .so
# loaded via ctypes (horovod_tpu/common/basics.py), plus an optional
# TensorFlow op library (csrc/tf_ops.cc -> libhvdtpu_tf.so) built against
# the installed TF's headers — the analog of horovod/tensorflow/mpi_ops.cc
# + xla_mpi_ops.cc.

CXX      ?= g++
CXXFLAGS ?= -O2 -g -std=c++17 -fPIC -Wall -Wextra -Wno-unused-parameter -pthread
LDFLAGS  ?= -shared -pthread

SRC := $(filter-out csrc/tf_ops.cc,$(wildcard csrc/*.cc))
HDR := $(wildcard csrc/*.h)
OUT := horovod_tpu/lib/libhvdtpu_core.so
TF_OUT := horovod_tpu/lib/libhvdtpu_tf.so

# TF build flags come from the installed wheel; empty when TF is absent.
PYTHON ?= python3

TSAN_OUT := horovod_tpu/lib/libhvdtpu_core_tsan.so
ASAN_OUT := horovod_tpu/lib/libhvdtpu_core_asan.so

.PHONY: core tf clean test test-quick test-flaky lint lint-csrc \
  model-check \
  core-tsan core-asan metrics-smoke zero-smoke elastic-smoke \
  reshard-smoke chaos-smoke obs-smoke scale-smoke perf-smoke \
  serve-smoke wire-smoke fusion-smoke fleet-obs-smoke

core: $(OUT)

$(OUT): $(SRC) $(HDR)
	@mkdir -p horovod_tpu/lib
	$(CXX) $(CXXFLAGS) $(SRC) $(LDFLAGS) -o $(OUT)

# Sanitizer builds of the core runtime (load via HVDTPU_CORE_LIB=...,
# LD_PRELOAD the matching runtime — tests/single/test_sanitizer_smoke.py
# drives a multi-threaded allreduce through the TSan build).
core-tsan: $(TSAN_OUT)
core-asan: $(ASAN_OUT)

$(TSAN_OUT): $(SRC) $(HDR)
	@mkdir -p horovod_tpu/lib
	$(CXX) -O1 -g -std=c++17 -fPIC -fsanitize=thread -pthread \
	  $(SRC) $(LDFLAGS) -fsanitize=thread -o $(TSAN_OUT)

$(ASAN_OUT): $(SRC) $(HDR)
	@mkdir -p horovod_tpu/lib
	$(CXX) -O1 -g -std=c++17 -fPIC -fsanitize=address -pthread \
	  $(SRC) $(LDFLAGS) -fsanitize=address -o $(ASAN_OUT)

# Strict-warning build of the native core: full -Wextra, warnings as
# errors, a REAL -O2 compile+link (not -fsyntax-only — optimization-
# dependent warnings like -Wmaybe-uninitialized need the middle-end).
# tf_ops.cc is excluded exactly as in the core build: it requires the
# installed TF's headers, which the lint box may not have.
lint-csrc:
	$(CXX) -O2 -std=c++17 -fPIC -Werror -Wall -Wextra -pthread \
	  $(SRC) $(LDFLAGS) -o /dev/null
	@echo "lint-csrc: clean ($(words $(SRC)) files, -Werror -Wall -Wextra)"

# hvdcheck: exhaustive protocol model checking (elastic / wire /
# serving control planes) + the seeded-mutant suite + the csrc<->Python
# ABI drift guards. Pure Python, no jax, sub-second — see
# docs/analysis.md ("hvdcheck").
model-check:
	$(PYTHON) -m horovod_tpu.analysis.model --all

# Python lint: ruff (when installed — the driver container does not
# ship it; config lives in pyproject.toml) + an hvdlint static-analysis
# pass over every shipped program + the hvdcheck protocol/ABI gate
# (see docs/analysis.md).
lint: model-check
	@if command -v ruff >/dev/null 2>&1; then \
	  ruff check horovod_tpu bench.py; \
	else \
	  echo "lint: ruff not installed; skipping style pass"; \
	fi
	JAX_PLATFORMS=cpu $(PYTHON) -m horovod_tpu.analysis.lint --all

tf: $(TF_OUT)

# The TF build flags come from ONE python probe at rule-execution time
# (tensorflow imports are multi-second; `make core` must not pay them).
$(TF_OUT): csrc/tf_ops.cc $(OUT)
	@set -e; \
	probe=$$($(PYTHON) -c "import tensorflow as tf, os; print(' '.join(tf.sysconfig.get_compile_flags())); print(' '.join(tf.sysconfig.get_link_flags())); print(os.path.join(os.path.dirname(tf.__file__), 'include'))" 2>/dev/null); \
	test -n "$$probe" || { echo "tensorflow not importable; skipping"; exit 1; }; \
	cflags=$$(printf '%s\n' "$$probe" | sed -n 1p); \
	lflags=$$(printf '%s\n' "$$probe" | sed -n 2p); \
	inc=$$(printf '%s\n' "$$probe" | sed -n 3p); \
	$(CXX) -O2 -g -std=c++17 -fPIC -Wno-deprecated-declarations \
	  csrc/tf_ops.cc $$cflags -Icsrc -I$$inc/external/highwayhash \
	  -I$$inc/external/farmhash_archive/src \
	  -shared -pthread $$lflags \
	  -Lhorovod_tpu/lib -l:libhvdtpu_core.so '-Wl,-rpath,$$ORIGIN' \
	  -o $(TF_OUT)

clean:
	rm -rf horovod_tpu/lib build

test: core
	python -m pytest tests/ -x -q

# Sub-5-minute lane: core runtime units, the multi-rank eager-ops file,
# and the elastic driver path (the full suite is ~25 min).
test-quick: core
	python -m pytest tests/ -m "quick and not slow" -x -q

# Rerun the load-flaky tests STANDALONE (serial, nothing else competing
# for the box) and in CI ORDER: the exact plugin-disable set of the
# tier-1 command (no xdist, no randomization, no cache) so collection
# order matches what CI ran — a flake that depends on which test warmed
# the core before it reproduces here or not at all. The loadflaky
# discipline: run THIS lane before blaming a diff for a shard failure —
# if it is green, the failure was load, not a regression (never
# hand-type the pytest invocation again).
test-flaky: core
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -m loadflaky -q \
	  -p no:cacheprovider -p no:xdist -p no:randomly

# Striped-wire smoke: selftest bit-identity at K in {1,4} (+ CRC +
# SIMD), exact per-channel byte reconciliation on a real 2-rank K=4
# job, and K=4 transport goodput beating the K=1 baseline at 16 MiB
# (docs/wire.md; horovod_tpu/common/wire_smoke.py; ~60 s).
wire-smoke: core
	$(PYTHON) -m horovod_tpu.common.wire_smoke

# Telemetry smoke: 2 real eager ranks, exact byte accounting in the
# metrics snapshot, cache steady state, per-rank timelines merged with
# straggler attribution (horovod_tpu/telemetry/smoke.py; ~10 s).
metrics-smoke: core
	JAX_PLATFORMS=cpu $(PYTHON) -m horovod_tpu.telemetry.smoke

# ZeRO-1 smoke: 2 real eager ranks drive the sharded-optimizer lane
# end to end — sharded-vs-replicated parity, 1/N per-rank optimizer
# bytes, reduce-scatter/allgather byte reconciliation (docs/zero.md;
# horovod_tpu/jax/zero_smoke.py; ~30 s).
zero-smoke: core
	JAX_PLATFORMS=cpu $(PYTHON) -m horovod_tpu.jax.zero_smoke

# Elastic smoke: 2 real ranks; rank 1 is killed by deterministic fault
# injection mid-step, rank 0 gets the typed recoverable error, re-forms
# a 1-rank ring in place and resumes from the last commit, with the
# fault lifecycle booked in the metrics snapshot (docs/elastic.md;
# horovod_tpu/jax/elastic_smoke.py; ~30 s).
elastic-smoke: core
	JAX_PLATFORMS=cpu $(PYTHON) -m horovod_tpu.jax.elastic_smoke

# Chaos-matrix smoke: the three self-healing acceptance behaviors under
# the HOROVOD_FAULT_INJECT grammar (kill|stop|reset|flip|delay) — a
# SIGSTOP stall healed in place on the retry ladder (same epoch, zero
# faults), a wire bit-flip caught by per-chunk CRC32C and NAK-resent,
# and SIGKILL + blacklist-parole rejoin regrowing N-1 -> N with the
# training trajectory pinned against an uninterrupted N-rank run
# (docs/elastic.md; tests/parallel/test_chaos_matrix.py; ~2 min).
chaos-smoke: core
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/parallel/test_chaos_matrix.py \
	  -q -p no:cacheprovider \
	  -k "heals_in_place or bitflip_detected or parole_rejoin"

# Observability smoke: 2 real ranks with the debug endpoint up; an
# injected stop:<ms> stall escalates to a typed fault — /healthz must
# answer on both ranks mid-run, every rank leaves a black-box event-
# ring dump, and the merged post-mortem names the stalled rank without
# declaring anyone dead (docs/metrics.md;
# horovod_tpu/telemetry/obs_smoke.py; ~20 s).
obs-smoke: core
	JAX_PLATFORMS=cpu $(PYTHON) -m horovod_tpu.telemetry.obs_smoke

# Fleet-observatory smoke: 2 real ranks run step-marked train loops;
# an injected stop:<ms> stall on rank 1 heals in place through the
# retry ladder while the driver polls the live /fleet aggregation on
# rank 0 — every rank's rank-seconds buckets must sum to its window to
# the microsecond (unattributed < 1%), rank 1's SLO check must breach
# stall_ms naming phase "stall" and record the typed slo_breach event,
# and report.py --fleet over the black-box dumps must surface the same
# verdict post-mortem (docs/fleet.md;
# horovod_tpu/telemetry/fleet_smoke.py; ~25 s).
fleet-obs-smoke: core
	JAX_PLATFORMS=cpu $(PYTHON) -m horovod_tpu.telemetry.fleet_smoke

# Step-anatomy smoke: 2 real ranks run an eager loop under a StepTimer
# (step windows + overlap ledger) with a chaos delay:<ms> straggler
# injection on rank 1 — asserts exposed + hidden == total wire time
# reconciles within 1% of the wire_us histogram, and that the
# cross-rank critical-path merge (report.py --critical-path) names the
# delayed rank with phase "stall" on exactly the injected step
# (docs/metrics.md; horovod_tpu/telemetry/perf_smoke.py; ~20 s).
perf-smoke: core
	JAX_PLATFORMS=cpu $(PYTHON) -m horovod_tpu.telemetry.perf_smoke

# Jit-lane fusion smoke: hvdlint C7 gate (interleaving statically
# verified on the fused step, fires on a bunched fixture), then 2 real
# ranks run hvd.make_fused_train_step under a StepTimer — asserts the
# overlap-ledger invariant (exposed + hidden == total per plane, with
# hidden > 0: wire drained while segments dispatched) and that
# HOROVOD_JIT_FUSION flips the schedule with BIT-identical loss/params
# (docs/fusion.md; horovod_tpu/jax/fusion_smoke.py; ~40 s).
fusion-smoke: core
	JAX_PLATFORMS=cpu $(PYTHON) -m horovod_tpu.jax.fusion_smoke

# Large-world smoke: one 64-rank simulated world (thread-per-rank over
# socketpairs, csrc/simworld.cc) runs a negotiation + allreduce round
# in BOTH gather modes (flat star vs HOROVOD_CONTROL_TREE) with the
# per-phase control-plane latency rows emitted, then an injected kill
# surfaces typed attribution on all 63 survivors and the streaming
# post-mortem merge over their dumps names the dead rank as root cause
# (docs/scale.md; horovod_tpu/simworld/scale_smoke.py; ~15 s).
scale-smoke: core
	JAX_PLATFORMS=cpu $(PYTHON) -m horovod_tpu.simworld.scale_smoke

# Serving chaos smoke: a 2-rank prefill/decode world serves a Poisson
# request trace with int8 paged KV shipped over the CRC-framed host
# ring; the decode rank is SIGKILLed mid-trace and every admitted
# request must complete on the survivor with greedy output
# token-identical to llama_generate — AND the latency cliff must be
# EXPLAINED: every completed rid stitches into a gap-free request span
# chain (per-phase sums == wall time exactly), the chaos victim's
# orphans carry fault_requeue spans and only they do, and
# report.py --requests renders the tail attribution over the dumps
# (docs/serving.md; horovod_tpu/serving/serve_smoke.py; ~60 s).
serve-smoke: core
	JAX_PLATFORMS=cpu $(PYTHON) -m horovod_tpu.serving.serve_smoke

# Cross-plane + redistribute smoke: 4 real ranks emulate 2 slices x 2
# chips under HOROVOD_CROSS_PLANE=hier — hierarchical train-step parity
# with exact per-plane wire books, a checkpoint reshard round-trip via
# hvd.redistribute plans with <1% measured-vs-predicted reconciliation,
# and the 1/local_size cross-plane byte bound (docs/redistribute.md;
# horovod_tpu/jax/reshard_smoke.py; ~20 s).
reshard-smoke: core
	JAX_PLATFORMS=cpu $(PYTHON) -m horovod_tpu.jax.reshard_smoke
