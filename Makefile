# Build the native core runtime (csrc/ -> horovod_tpu/lib/libhvdtpu_core.so).
# Reference analog: horovod's CMake-driven per-framework extensions
# (setup.py + CMakeLists.txt). Ours is a single framework-agnostic .so
# loaded via ctypes (horovod_tpu/common/basics.py).

CXX      ?= g++
CXXFLAGS ?= -O2 -g -std=c++17 -fPIC -Wall -Wextra -Wno-unused-parameter -pthread
LDFLAGS  ?= -shared -pthread

SRC := $(wildcard csrc/*.cc)
HDR := $(wildcard csrc/*.h)
OUT := horovod_tpu/lib/libhvdtpu_core.so

.PHONY: core clean test

core: $(OUT)

$(OUT): $(SRC) $(HDR)
	@mkdir -p horovod_tpu/lib
	$(CXX) $(CXXFLAGS) $(SRC) $(LDFLAGS) -o $(OUT)

clean:
	rm -rf horovod_tpu/lib build

test: core
	python -m pytest tests/ -x -q
