"""End-to-end elastic recovery for the torch frontend: kill a worker
mid-step, survivors roll back to the last TorchState commit, the driver
respawns the slot, training finishes at the full step count.

Reference analog: test/integration/test_elastic_torch.py (SURVEY.md §4).
"""

import json
import os
import sys

from horovod_tpu.runner.elastic.discovery import FixedHosts
from horovod_tpu.runner.elastic.driver import ElasticDriver

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

WORKER_SRC = """
import json, os, sys
sys.path.insert(0, {repo!r})
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import torch
import horovod_tpu.torch as hvd

tmp = {tmp!r}
hvd.init()
torch.manual_seed(7)

model = torch.nn.Linear(4, 1)
base_opt = torch.optim.SGD(model.parameters(), lr=0.05)
opt = hvd.DistributedOptimizer(base_opt,
                               named_parameters=model.named_parameters())
state = hvd.elastic.TorchState(model=model, optimizer=base_opt, step=0)

rng = np.random.RandomState(3)
x = torch.from_numpy(rng.rand(64, 4).astype("float32"))
y = torch.from_numpy(rng.rand(64, 1).astype("float32"))


@hvd.elastic.run
def train(state):
    while state.step < 12:
        if state.step == 6:
            try:
                fd = os.open(os.path.join(tmp, "suicide.lock"),
                             os.O_CREAT | os.O_EXCL)
                os.close(fd)
                os._exit(17)
            except FileExistsError:
                pass
        i = (state.step * 8) % 64
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(x[i:i + 8]), y[i:i + 8])
        loss.backward()
        opt.step()
        state.step += 1
        state.commit()

train(state)
digest = float(sum(p.detach().sum() for p in model.parameters()))
peers = hvd.allgather_object(digest)
wid = os.environ["HOROVOD_WORKER_ID"].replace(":", "_")
with open(os.path.join(tmp, "done." + wid), "w") as f:
    json.dump({{"step": int(state.step), "size": hvd.size(),
               "peers": peers}}, f)
hvd.shutdown()
"""


def test_torch_elastic_kill_and_recover(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER_SRC.format(repo=REPO, tmp=str(tmp_path)))

    env = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
    driver = ElasticDriver(FixedHosts({"localhost": 2}),
                           [sys.executable, str(worker.resolve())],
                           min_np=2, max_np=2, poll_interval=0.5,
                           start_timeout=120, env=env)
    driver.start()
    try:
        rc = driver.wait_for_completion()
    finally:
        driver.stop()
    assert rc == 0

    done = sorted(tmp_path.glob("done.*"))
    assert len(done) == 2, [p.name for p in done]
    for p in done:
        r = json.loads(p.read_text())
        assert r["step"] == 12
        assert r["size"] == 2
        assert all(abs(d - r["peers"][0]) < 1e-5 for d in r["peers"]), r
    assert (tmp_path / "suicide.lock").exists()
