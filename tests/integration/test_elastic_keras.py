"""End-to-end elastic recovery for the Keras frontend: kill a worker
mid-fit, the survivors roll back to the last KerasState commit, the
driver respawns the slot, and training finishes at the full epoch count.

Reference analog: test/integration/test_elastic_tensorflow_keras.py
(SURVEY.md §4).
"""

import json
import os
import sys

from horovod_tpu.runner.elastic.discovery import FixedHosts
from horovod_tpu.runner.elastic.driver import ElasticDriver

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

WORKER_SRC = """
import json, os, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import tensorflow as tf
import horovod_tpu.tensorflow.keras as hvd

tmp = {tmp!r}
hvd.init()
tf.keras.utils.set_random_seed(1234)

model = tf.keras.Sequential([
    tf.keras.layers.Dense(8, input_shape=(4,)),
    tf.keras.layers.Dense(1),
])
model.compile(optimizer=hvd.DistributedOptimizer(
    tf.keras.optimizers.SGD(0.01)), loss="mse")
state = hvd.elastic.KerasState(model, batch=0, epoch=0)

rng = np.random.RandomState(0)
x = rng.rand(64, 4).astype("float32")
y = rng.rand(64, 1).astype("float32")


class Suicide(tf.keras.callbacks.Callback):
    def on_epoch_begin(self, epoch, logs=None):
        if epoch == 2:
            try:
                fd = os.open(os.path.join(tmp, "suicide.lock"),
                             os.O_CREAT | os.O_EXCL)
                os.close(fd)
                os._exit(17)
            except FileExistsError:
                pass


@hvd.elastic.run
def train(state):
    # Audit trail for the harness: every (re)entry records the epoch it
    # resumes from; post-crash entries must NOT restart at 0.
    after_kill = os.path.exists(os.path.join(tmp, "suicide.lock"))
    with open(os.path.join(tmp, "entries.log"), "a") as f:
        f.write(json.dumps({{"epoch": int(state.epoch),
                            "after_kill": after_kill}}) + "\\n")
    cbs = [Suicide(),
           hvd.elastic.UpdateBatchStateCallback(state),
           hvd.elastic.UpdateEpochStateCallback(state),
           hvd.elastic.CommitStateCallback(state, batches_per_commit=4)]
    model.fit(x[hvd.rank()::hvd.size()], y[hvd.rank()::hvd.size()],
              batch_size=8, epochs=4, initial_epoch=state.epoch,
              callbacks=cbs, verbose=0)

train(state)
digest = float(sum(np.sum(w) for w in model.get_weights()))
peers = hvd.allgather_object(digest)
wid = os.environ["HOROVOD_WORKER_ID"].replace(":", "_")
with open(os.path.join(tmp, "done." + wid), "w") as f:
    json.dump({{"epoch": int(state.epoch), "size": hvd.size(),
               "digest": digest, "peers": peers}}, f)
hvd.shutdown()
"""


MIDEPOCH_WORKER_SRC = """
import json, os, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import tensorflow as tf
import horovod_tpu.tensorflow.keras as hvd

tmp = {tmp!r}
hvd.init()
tf.keras.utils.set_random_seed(1234)

model = tf.keras.Sequential([
    tf.keras.layers.Dense(8, input_shape=(4,)),
    tf.keras.layers.Dense(1),
])
model.compile(optimizer=hvd.DistributedOptimizer(
    tf.keras.optimizers.SGD(0.01)), loss="mse")
state = hvd.elastic.KerasState(model, batch=0, epoch=0)

rng = np.random.RandomState(0)
x = rng.rand(64, 4).astype("float32")
y = rng.rand(64, 1).astype("float32")
# 32 samples/rank at batch_size 8 -> 4 steps per epoch.


class SuicideMidEpoch(tf.keras.callbacks.Callback):
    def on_train_batch_begin(self, batch, logs=None):
        # Die in epoch 1 entering batch 2 (state.batch == 2 committed).
        if getattr(state, "epoch", 0) == 1 and state.batch == 2:
            try:
                fd = os.open(os.path.join(tmp, "suicide.lock"),
                             os.O_CREAT | os.O_EXCL)
                os.close(fd)
                os._exit(17)
            except FileExistsError:
                pass


class BatchCounter(tf.keras.callbacks.Callback):
    def on_epoch_begin(self, epoch, logs=None):
        self._n = 0

    def on_train_batch_end(self, batch, logs=None):
        self._n += 1

    def on_epoch_end(self, epoch, logs=None):
        with open(os.path.join(tmp, "epochs.log"), "a") as f:
            f.write(json.dumps(
                {{"rank": hvd.rank(), "epoch": int(epoch),
                  "batches": self._n,
                  "after_kill": os.path.exists(
                      os.path.join(tmp, "suicide.lock"))}}) + "\\n")


@hvd.elastic.run
def train(state):
    cbs = [SuicideMidEpoch(),
           hvd.elastic.UpdateBatchStateCallback(state),
           hvd.elastic.UpdateEpochStateCallback(state),
           BatchCounter(),
           hvd.elastic.CommitStateCallback(state, batches_per_commit=1)]
    model.fit(x[hvd.rank()::hvd.size()], y[hvd.rank()::hvd.size()],
              batch_size=8, epochs=3, initial_epoch=state.epoch,
              callbacks=cbs, verbose=0)

train(state)
wid = os.environ["HOROVOD_WORKER_ID"].replace(":", "_")
with open(os.path.join(tmp, "done." + wid), "w") as f:
    json.dump({{"epoch": int(state.epoch), "size": hvd.size()}}, f)
hvd.shutdown()
"""


def test_keras_elastic_midepoch_resume_runs_remaining_steps(tmp_path):
    """A worker dies two batches into epoch 1; recovery must finish that
    epoch with the REMAINING two steps, not re-run all four (the keras 3
    params['steps'] workaround — UpdateBatchStateCallback's early epoch
    stop)."""
    worker = tmp_path / "worker.py"
    worker.write_text(MIDEPOCH_WORKER_SRC.format(repo=REPO,
                                                 tmp=str(tmp_path)))
    env = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
           "TF_CPP_MIN_LOG_LEVEL": "3"}
    driver = ElasticDriver(FixedHosts({"localhost": 2}),
                           [sys.executable, str(worker.resolve())],
                           min_np=2, max_np=2, poll_interval=0.5,
                           start_timeout=120, env=env)
    driver.start()
    try:
        rc = driver.wait_for_completion()
    finally:
        driver.stop()
    assert rc == 0
    assert (tmp_path / "suicide.lock").exists()

    done = sorted(tmp_path.glob("done.*"))
    assert len(done) == 2, [p.name for p in done]
    for p in done:
        r = json.loads(p.read_text())
        assert r["epoch"] == 3 and r["size"] == 2

    entries = [json.loads(ln) for ln in
               (tmp_path / "epochs.log").read_text().splitlines()]
    # The resumed epoch 1 must have run exactly the 2 remaining steps on
    # every rank that completed it after the kill; full epochs run 4.
    resumed = [e for e in entries if e["epoch"] == 1 and e["after_kill"]]
    assert resumed, entries
    assert all(e["batches"] == 2 for e in resumed), resumed
    for later in (2,):
        full = [e for e in entries if e["epoch"] == later]
        assert full and all(e["batches"] == 4 for e in full), entries


def test_keras_elastic_kill_and_recover(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER_SRC.format(repo=REPO, tmp=str(tmp_path)))

    env = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
           "TF_CPP_MIN_LOG_LEVEL": "3"}
    # min_np == world size: after the kill the survivor must wait for the
    # respawned slot and re-rendezvous at size 2 (exercises recovery
    # rather than letting the survivor finish alone).
    driver = ElasticDriver(FixedHosts({"localhost": 2}),
                           [sys.executable, str(worker.resolve())],
                           min_np=2, max_np=2, poll_interval=0.5,
                           start_timeout=120, env=env)
    driver.start()
    try:
        rc = driver.wait_for_completion()
    finally:
        driver.stop()
    assert rc == 0

    done = sorted(tmp_path.glob("done.*"))
    assert len(done) == 2, [p.name for p in done]
    results = [json.loads(p.read_text()) for p in done]
    for r in results:
        assert r["epoch"] == 4          # reached the full epoch count
        assert r["size"] == 2           # the killed slot was respawned
        # all ranks converged to identical weights after recovery
        assert all(abs(p - r["peers"][0]) < 1e-5 for p in r["peers"]), r
    assert (tmp_path / "suicide.lock").exists()
    # Recovery must RESUME, not retrain: after the kill, every train()
    # (re)entry syncs committed progress (epoch 2) from a survivor; an
    # entry at epoch 0 would mean a fresh respawn won rank 0 and wiped
    # the committed state with untrained weights.
    entries = [json.loads(ln) for ln in
               (tmp_path / "entries.log").read_text().splitlines()]
    post_kill = [e for e in entries if e["after_kill"]]
    assert post_kill, entries
    assert all(e["epoch"] >= 2 for e in post_kill), entries
