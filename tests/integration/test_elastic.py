"""End-to-end elastic recovery: kill a worker mid-training, driver
respawns it, survivors roll back to the last commit and finish.

Reference analog: test/integration/test_elastic_torch.py (drives a real
elastic run and kills workers; SURVEY.md §4).
"""

import json
import os
import sys

from horovod_tpu.runner.elastic.discovery import FixedHosts
from horovod_tpu.runner.elastic.driver import ElasticDriver

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

WORKER_SRC = """
import json, os, sys
sys.path.insert(0, {repo!r})
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import horovod_tpu.jax as hvd
from horovod_tpu.jax import elastic

tmp = {tmp!r}
hvd.init()
state = elastic.JaxState(step=0, value=np.zeros(4, np.float32))

@elastic.run
def train(state):
    while state.step < 10:
        if state.step == 5:
            # Exactly one process across the whole job dies, once.
            try:
                fd = os.open(os.path.join(tmp, "suicide.lock"),
                             os.O_CREAT | os.O_EXCL)
                os.close(fd)
                os._exit(17)
            except FileExistsError:
                pass
        out = hvd.allreduce(np.ones(4, np.float32),
                            name=f"step{{state.step}}", op=hvd.Sum)
        state.value = np.asarray(state.value) + np.asarray(out)
        state.step += 1
        state.commit()
    return state

train(state)
wid = os.environ["HOROVOD_WORKER_ID"].replace(":", "_")
with open(os.path.join(tmp, f"done.{{wid}}"), "w") as f:
    json.dump({{"step": int(state.step),
               "value": np.asarray(state.value).tolist(),
               "size": hvd.size()}}, f)
hvd.shutdown()
"""


def test_elastic_kill_and_recover(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER_SRC.format(repo=REPO, tmp=str(tmp_path)))

    env = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
    driver = ElasticDriver(FixedHosts({"localhost": 3}),
                           [sys.executable, str(worker.resolve())],
                           min_np=2, max_np=3, poll_interval=0.5,
                           start_timeout=90, env=env)
    driver.start()
    try:
        rc = driver.wait_for_completion()
    finally:
        driver.stop()
    assert rc == 0

    done = sorted(tmp_path.glob("done.*"))
    assert len(done) == 3, [p.name for p in done]
    results = [json.loads(p.read_text()) for p in done]
    for r in results:
        assert r["step"] == 10
        assert r["size"] == 3
        # Every completed step contributed an allreduce of ones*size; the
        # killed step rolled back, so the total is exactly 10 * 3.
        assert r["value"] == [30.0] * 4, r
    # The kill actually happened (the recovery path was exercised).
    assert (tmp_path / "suicide.lock").exists()
