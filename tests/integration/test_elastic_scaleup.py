"""End-to-end elastic scale-UP: discovery adds slots mid-training, the
driver notifies workers, they take HostsUpdatedInterrupt (no rollback)
and resume at the larger world size.

Reference analog: test/integration/test_elastic_torch.py's
host-addition cases (SURVEY.md §3.4: HostsUpdatedInterrupt path).
"""

import json
import os
import sys
import time

from horovod_tpu.runner.elastic.discovery import HostDiscoveryScript
from horovod_tpu.runner.elastic.driver import ElasticDriver

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

WORKER_SRC = """
import json, os, sys, time
sys.path.insert(0, {repo!r})
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import horovod_tpu.jax as hvd
from horovod_tpu.jax import elastic

tmp = {tmp!r}
hvd.init()
state = elastic.JaxState(step=0, sizes=[])

@elastic.run
def train(state):
    while state.step < 24:
        out = hvd.allreduce(np.ones(2, np.float32),
                            name=f"s{{state.step}}", op=hvd.Sum)
        state.sizes = list(state.sizes) + [int(np.asarray(out)[0])]
        state.step += 1
        state.commit()
        time.sleep(0.4)  # slow enough for discovery to change mid-run

train(state)
wid = os.environ["HOROVOD_WORKER_ID"].replace(":", "_")
with open(os.path.join(tmp, "done." + wid), "w") as f:
    json.dump({{"sizes": [int(s) for s in state.sizes],
               "final": hvd.size()}}, f)
hvd.shutdown()
"""


def test_elastic_scale_up(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER_SRC.format(repo=REPO, tmp=str(tmp_path)))
    hosts_file = tmp_path / "hosts.txt"
    hosts_file.write_text("localhost:2\n")
    script = tmp_path / "discover.sh"
    script.write_text(f"#!/bin/sh\ncat {hosts_file}\n")
    script.chmod(0o755)

    env = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
    driver = ElasticDriver(HostDiscoveryScript(str(script)),
                           [sys.executable, str(worker.resolve())],
                           min_np=2, max_np=3, poll_interval=0.5,
                           start_timeout=60, env=env)
    driver.start()
    try:
        # Let the 2-rank world make progress, then add a slot.
        time.sleep(3)
        hosts_file.write_text("localhost:3\n")
        rc = driver.wait_for_completion()
    finally:
        driver.stop()
    assert rc == 0

    done = sorted(tmp_path.glob("done.*"))
    assert len(done) == 3, [p.name for p in done]
    finals = [json.loads(p.read_text()) for p in done]
    assert all(r["final"] == 3 for r in finals), finals
    # The longest-lived workers saw both world sizes: allreduce of ones
    # sums to the size, so their history goes 2,...,2,3,...,3.
    grew = [r for r in finals if 2 in r["sizes"] and 3 in r["sizes"]]
    assert grew, finals
    for r in finals:
        assert sorted(r["sizes"]) == r["sizes"], r  # never shrank


def test_elastic_scale_down(tmp_path):
    """Slot shrink: the driver kills the excess worker (not booked as a
    host failure), survivors recover and finish at the smaller size."""
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER_SRC.format(repo=REPO, tmp=str(tmp_path)))
    hosts_file = tmp_path / "hosts.txt"
    hosts_file.write_text("localhost:3\n")
    script = tmp_path / "discover.sh"
    script.write_text(f"#!/bin/sh\ncat {hosts_file}\n")
    script.chmod(0o755)

    env = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
    driver = ElasticDriver(HostDiscoveryScript(str(script)),
                           [sys.executable, str(worker.resolve())],
                           min_np=2, max_np=3, poll_interval=0.5,
                           start_timeout=60, env=env)
    driver.start()
    try:
        time.sleep(3)
        hosts_file.write_text("localhost:2\n")
        rc = driver.wait_for_completion()
    finally:
        driver.stop()
    assert rc == 0  # the deliberate kill must not fail the job

    done = sorted(tmp_path.glob("done.*"))
    assert len(done) == 2, [p.name for p in done]  # no respawn of slot 2
    finals = [json.loads(p.read_text()) for p in done]
    assert all(r["final"] == 2 for r in finals), finals
    shrank = [r for r in finals if 3 in r["sizes"] and 2 in r["sizes"]]
    assert shrank, finals
    # the deliberate kill must not be booked as a host failure at all
    # (three bookings would blacklist the host)
    assert driver._host_failures.get("localhost", 0) == 0
