"""The stall inspector names the missing ranks of a stuck collective.

Reference analog: test/single/test_stall.py (SURVEY.md §4) — one rank
delays its submission past HOROVOD_STALL_CHECK_TIME; the coordinator
must log a warning naming the tensor and the absent rank, and the run
must still complete once the straggler arrives (stall is a diagnostic,
not an abort).
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

WORKER_SRC = """
import os, sys, time
sys.path.insert(0, {repo!r})
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import horovod_tpu.jax as hvd

hvd.init()
if hvd.rank() == 1:
    # Past the 2s warning threshold but well under 10s: only fires if
    # the inspector honors sub-10s check times (interval = warn/2, not
    # the old hardcoded 10s sweep).
    time.sleep(5)
out = hvd.allreduce(np.ones(4, np.float32), name="late.tensor",
                    op=hvd.Sum)
assert float(np.asarray(out)[0]) == 2.0
print("RANK" + str(hvd.rank()) + " DONE", flush=True)
hvd.shutdown()
"""


def test_stall_warning_names_missing_rank(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER_SRC.format(repo=REPO))
    env = dict(os.environ,
               PYTHONPATH=REPO,
               JAX_PLATFORMS="cpu",
               HOROVOD_STALL_CHECK_TIME="2")
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         sys.executable, str(worker)],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    log = out.stdout + out.stderr
    assert out.returncode == 0, log[-3000:]
    assert log.count("DONE") == 2, log[-3000:]
    assert "Stall detected" in log, log[-3000:]
    assert "late.tensor" in log, log[-3000:]
    # the delayed rank (1) is the one named missing
    stall_line = next(ln for ln in log.splitlines()
                      if "Stall detected" in ln)
    assert "missing ranks: 1" in stall_line, stall_line
