"""File-mailbox fake of the mpi4py surface the MPI control plane uses.

Injected as ``sys.modules["mpi4py"]`` by tests: point-to-point
``isend``/``recv`` become atomic file renames in a shared directory
(FAKE_MPI_DIR), so a multi-process HOROVOD_CONTROLLER=mpi run needs NO
sockets of any kind — which is exactly what the zero-TCP test asserts.
Message ordering per (src, dst, tag) stream is by sequence number;
``os.replace`` makes publication atomic. Mirrors the reference's
elastic-test pattern of faking infrastructure at the API seam.
"""

import os
import pickle
import time


class _Req:
    def test(self):
        return (True, None)


class _SubComm:
    def __init__(self, rank, size):
        self._rank, self._size = rank, size

    def Get_rank(self):
        return self._rank

    def Get_size(self):
        return self._size


class _Comm:
    def __init__(self):
        self.dir = os.environ["FAKE_MPI_DIR"]
        self.rank = int(os.environ["FAKE_MPI_RANK"])
        self.size = int(os.environ["FAKE_MPI_SIZE"])
        self._send_seq = {}
        self._recv_seq = {}

    def Get_rank(self):
        return self.rank

    def Get_size(self):
        return self.size

    def Split_type(self, kind, key=0):
        # Single-host fake: every rank shares the "node".
        return _SubComm(self.rank, self.size)

    def Split(self, color=0, key=0):
        # Distinct colors per rank in the bootstrap's usage.
        return _SubComm(0, 1)

    def _path(self, src, dst, tag, seq):
        return os.path.join(self.dir, f"m_{src}_{dst}_{tag}_{seq:08d}")

    def isend(self, data, dest, tag=0):
        seq = self._send_seq.get((dest, tag), 0)
        self._send_seq[(dest, tag)] = seq + 1
        final = self._path(self.rank, dest, tag, seq)
        tmp = final + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(data, f)
        os.replace(tmp, final)
        return _Req()

    def recv(self, source, tag=0):
        seq = self._recv_seq.get((source, tag), 0)
        self._recv_seq[(source, tag)] = seq + 1
        path = self._path(source, self.rank, tag, seq)
        deadline = time.time() + 60
        while not os.path.exists(path):
            if time.time() > deadline:
                raise TimeoutError(f"fake MPI recv timed out: {path}")
            time.sleep(0.002)
        with open(path, "rb") as f:
            data = pickle.load(f)
        os.remove(path)
        return data


class _MPIModule:
    COMM_TYPE_SHARED = 1

    def __init__(self):
        self.COMM_WORLD = _Comm()

    def Is_initialized(self):
        return True

    def Is_finalized(self):
        return False


MPI = _MPIModule()


class rc:  # mpi4py.rc lookalike (mpi_bootstrap sets rc.initialize)
    initialize = False
