"""Multi-process test harness: run a worker fn on N local ranks.

Reference analog: the reference runs test/parallel/* under
``horovodrun -np 2 pytest ...``; we instead spawn ranks in-test so plain
``pytest tests/`` covers distributed behavior (same spirit as the reference's
elastic unit tests that fake workers as threads — SURVEY.md §4).
"""

import multiprocessing as mp
import os
import socket
import sys
import traceback

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _entry(fn, rank, size, port, q, env):
    os.environ.update({
        "HOROVOD_RANK": str(rank),
        "HOROVOD_SIZE": str(size),
        "HOROVOD_LOCAL_RANK": str(rank),
        "HOROVOD_LOCAL_SIZE": str(size),
        "HOROVOD_CONTROLLER_ADDR": "127.0.0.1",
        "HOROVOD_CONTROLLER_PORT": str(port),
        # keep jax off any accelerator inside workers
        "JAX_PLATFORMS": "cpu",
    })
    os.environ.update(env or {})
    sys.path.insert(0, REPO_ROOT)
    # The driver image's sitecustomize registers the axon TPU plugin in
    # every interpreter; force workers onto CPU at the config level too
    # (env alone is not enough — see tests/conftest.py).
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except ImportError:
        pass
    try:
        result = fn(rank, size)
        q.put((rank, None, result))
    except Exception as e:  # noqa: BLE001
        traceback.print_exc()
        q.put((rank, f"{type(e).__name__}: {e}", None))


def run_ranks(fn, size, timeout=90, env=None):
    """Run fn(rank, size) on `size` spawned processes; return results by rank.

    Raises AssertionError if any rank fails.
    """
    ctx = mp.get_context("spawn")
    port = free_port()
    q = ctx.Queue()
    procs = [
        ctx.Process(target=_entry, args=(fn, r, size, port, q, env))
        for r in range(size)
    ]
    for p in procs:
        p.start()
    results = {}
    errors = {}
    try:
        for _ in range(size):
            rank, err, res = q.get(timeout=timeout)
            if err is not None:
                errors[rank] = err
            results[rank] = res
    finally:
        for p in procs:
            p.join(timeout=15)
            if p.is_alive():
                p.terminate()
    assert not errors, f"rank failures: {errors}"
    return [results[r] for r in range(size)]
