"""Response cache: steady-state bitvector negotiation stays correct.

Reference analog: the reference exercises the cache implicitly by looping
ops under HOROVOD_CACHE_CAPACITY (test/parallel/test_torch.py) — every
repeat of a named tensor after the first rides the cache-hit path. We assert
correctness over many cycles plus the eviction path (metadata change) and
that the hit counters actually engage (the bits, not full requests, carried
the steady state).
"""

import numpy as np
import pytest

from tests.utils_mp import run_ranks


def _init():
    from horovod_tpu.common import basics
    b = basics.HorovodBasics()
    b.init()
    return b


def _ops():
    from horovod_tpu.common import eager_ops
    return eager_ops


def _worker_steady_state(rank, size):
    b = _init()
    ops = _ops()
    try:
        # Same tensor names over many steps: first step negotiates fully,
        # later steps must be pure cache hits.
        steps, ngrads = 12, 6
        for step in range(steps):
            hs = [
                ops.allreduce_async(
                    np.full(8, float(rank + step + i), np.float32),
                    f"grad.{i}")
                for i in range(ngrads)
            ]
            for i, h in enumerate(hs):
                np.testing.assert_allclose(
                    h.synchronize(),
                    sum(rk + step + i for rk in range(size)))
            # Broadcast and reducescatter are cacheable too.
            h = ops.broadcast_async(np.full(4, float(rank), np.float64), 0,
                                    "bcast.w")
            np.testing.assert_allclose(h.synchronize(), 0.0)
            h = ops.reducescatter_async(
                np.full((size * 2, 3), float(rank), np.float32), "rs.w")
            np.testing.assert_allclose(h.synchronize(),
                                       sum(range(size)))
        hits, misses, entries = b.response_cache_stats()
        assert entries == ngrads + 2, (hits, misses, entries)
        # Every post-first-step op must be a hit.
        assert hits >= (steps - 1) * (ngrads + 2), (hits, misses, entries)
        return hits
    finally:
        b.shutdown()


def _worker_eviction(rank, size):
    b = _init()
    ops = _ops()
    try:
        # Warm the cache, then change the shape under the same name: the
        # coordinator must evict everywhere and renegotiate, and results must
        # stay correct (reference analog: cache invalidation on metadata
        # change in response_cache.cc).
        for shape in ((4,), (4,), (6,), (6,), (2, 3), (4,)):
            h = ops.allreduce_async(np.full(shape, float(rank), np.float32),
                                    "mutating")
            np.testing.assert_allclose(h.synchronize(), sum(range(size)))
        # Dtype change under the same name.
        for dt in (np.float32, np.float64, np.float32):
            h = ops.allreduce_async(np.full(3, rank, dt), "mutdtype")
            np.testing.assert_allclose(h.synchronize(), sum(range(size)))
        # Reduce-op change under the same name.
        h = ops.allreduce_async(np.full(3, float(rank + 1), np.float64),
                                "mutop", op=ops.ReduceOp.SUM)
        np.testing.assert_allclose(h.synchronize(),
                                   sum(range(1, size + 1)))
        h = ops.allreduce_async(np.full(3, float(rank + 1), np.float64),
                                "mutop", op=ops.ReduceOp.MAX)
        np.testing.assert_allclose(h.synchronize(), float(size))
        return True
    finally:
        b.shutdown()


def _worker_disabled(rank, size):
    b = _init()
    ops = _ops()
    try:
        for step in range(4):
            h = ops.allreduce_async(np.full(5, float(rank), np.float32),
                                    "nocache")
            np.testing.assert_allclose(h.synchronize(), sum(range(size)))
        hits, _, entries = b.response_cache_stats()
        assert hits == 0 and entries == 0, (hits, entries)
        return True
    finally:
        b.shutdown()


def _worker_skewed_arrival(rank, size):
    b = _init()
    ops = _ops()
    try:
        import time
        # Ranks reach the cached collective at very different times: bits
        # must wait in the coordinator's pending table until all ranks set
        # them (completion spans cycles).
        for step in range(5):
            time.sleep(0.02 * rank)
            h = ops.allreduce_async(np.full(4, float(rank * step),
                                            np.float32), "skew")
            np.testing.assert_allclose(h.synchronize(),
                                       sum(rk * step for rk in range(size)))
        return True
    finally:
        b.shutdown()


def _worker_join_covers_pending_bits(rank, size):
    b = _init()
    ops = _ops()
    try:
        # Warm the cache on all ranks.
        for step in range(2):
            h = ops.allreduce_async(np.full(4, float(rank + 1), np.float32),
                                    "g")
            np.testing.assert_allclose(h.synchronize(),
                                       sum(range(1, size + 1)))
        # Rank != 0 joins immediately; rank 0 rides the cache-hit path once
        # more. The pending bit must be completed by join coverage (the
        # joined ranks contribute zeros), exactly like the full-request path.
        if rank == 0:
            h = ops.allreduce_async(np.full(4, 7.0, np.float32), "g")
            np.testing.assert_allclose(h.synchronize(), 7.0)
        ops.join()  # blocks until every rank has joined
        return True
    finally:
        b.shutdown()


# The steady-state runs are load-flaky under the full tier-1 suite: 12
# steps x 8 synchronized collectives per rank leave the 90 s harness
# deadline with no headroom once leftover workers from earlier parallel
# tests (or a busy CI box) steal the cores. The assertions are pure
# correctness — only the SLACK widens, and the loadflaky marker lets a
# saturated shard deselect them explicitly instead of failing spuriously.
@pytest.mark.loadflaky
def test_cache_steady_state_2ranks():
    hits = run_ranks(_worker_steady_state, 2, timeout=300)
    assert all(h > 0 for h in hits)


@pytest.mark.loadflaky
def test_cache_steady_state_4ranks():
    run_ranks(_worker_steady_state, 4, timeout=300)


def test_cache_eviction_on_metadata_change():
    run_ranks(_worker_eviction, 2)


def test_cache_disabled_by_env():
    run_ranks(_worker_disabled, 2, env={"HOROVOD_CACHE_CAPACITY": "0"})


def test_cache_skewed_arrival():
    run_ranks(_worker_skewed_arrival, 3)


def test_cache_join_covers_pending_bits():
    run_ranks(_worker_join_covers_pending_bits, 2)
