"""Chaos tests for the preemption-native elastic core (docs/elastic.md).

A rank SIGKILLed mid-step must never hang survivors: every survivor
raises a typed ``HorovodPeerFailureError`` within the wire deadline,
attributing the dead rank; ``hvdtpu_reinit`` then re-forms an N-1 ring
over the survivors WITHOUT process restart, and:

- uncompressed allreduce on the re-formed ring is BIT-identical to a
  numpy ring-order replay of a fresh N-1 world (the ring_ops.h rotation
  helpers are reused, so the rotation math cannot drift);
- a silent stall (SIGSTOP, no socket EOF) still surfaces within
  ``HOROVOD_WIRE_TIMEOUT_MS``, with the stalled peer + elapsed ms in
  the message;
- the full recovery glue (``hvd.elastic.run`` + commit/restore/sync
  over the in-process reinit path) resumes training from the last
  commit and lands on the same trajectory as an uninterrupted N-1 run.

Workers live in this importable module (never ``python -c`` strings —
spawn must re-import them; the r11 gotcha).
"""

import multiprocessing as mp
import os
import signal
import sys
import time

import numpy as np
import pytest

from tests.utils_mp import REPO_ROOT, free_port

pytestmark = pytest.mark.quick

_COUNT = 4096 + 37  # ragged on purpose
_TIMEOUT_MS = 2000  # small wire deadline so chaos tests stay fast


def _rank_input(rank, count):
    e = np.arange(count, dtype=np.float64)
    v = (((rank + 1) * 1315423911 + (e + 1) * 2654435761) % 2001) / 500 - 2
    return v.astype(np.float32)


def _ring_reference(inputs):
    """Bit-exact ring-order allreduce(SUM) replay (tests/parallel/
    test_ring_wire.py): segment j's partial starts at rank j, each later
    owner adds its own values in ring order."""
    n = len(inputs)
    count = inputs[0].size
    q, r = divmod(count, n)
    seg = [q + (1 if i < r else 0) for i in range(n)]
    out = np.empty_like(inputs[0])
    off = 0
    for j in range(n):
        sl = slice(off, off + seg[j])
        acc = inputs[j][sl].copy()
        for t in range(1, n):
            acc = inputs[(j + t) % n][sl] + acc
        out[sl] = acc
        off += seg[j]
    return out


def _entry(fn, rank, size, port, q, env):
    os.environ.update({
        "HOROVOD_RANK": str(rank),
        "HOROVOD_SIZE": str(size),
        "HOROVOD_LOCAL_RANK": str(rank),
        "HOROVOD_LOCAL_SIZE": str(size),
        "HOROVOD_CONTROLLER_ADDR": "127.0.0.1",
        "HOROVOD_CONTROLLER_PORT": str(port),
        "JAX_PLATFORMS": "cpu",
    })
    os.environ.update(env or {})
    sys.path.insert(0, REPO_ROOT)
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except ImportError:
        pass
    try:
        q.put((rank, None, fn(rank, size)))
    except Exception as e:  # noqa: BLE001
        import traceback

        traceback.print_exc()
        q.put((rank, f"{type(e).__name__}: {e}", None))


def run_chaos(fn, size, victims, timeout=120, env=None,
              expect_sigkill=True):
    """run_ranks that tolerates `victims` dying: collects results from
    the survivors only, then reaps the victims (SIGCONT+SIGKILL covers
    SIGSTOPped ones). Returns {rank: result} for survivors."""
    ctx = mp.get_context("spawn")
    port = free_port()
    q = ctx.Queue()
    procs = {
        r: ctx.Process(target=_entry, args=(fn, r, size, port, q, env))
        for r in range(size)
    }
    for p in procs.values():
        p.start()
    results, errors = {}, {}
    want = size - len(victims)
    deadline = time.monotonic() + timeout
    try:
        while len(results) + len(errors) < want:
            remaining = deadline - time.monotonic()
            assert remaining > 0, (
                f"survivors hung: got {sorted(results)} of {want}")
            try:
                rank, err, res = q.get(timeout=min(remaining, 5.0))
            except Exception:  # noqa: BLE001 — queue.Empty
                continue
            if err is not None:
                errors[rank] = err
            else:
                results[rank] = res
    finally:
        for r, p in procs.items():
            if r in victims and p.is_alive():
                # Reap a victim that stopped (SIGSTOP) instead of dying.
                os.kill(p.pid, signal.SIGCONT)
                p.kill()
            p.join(timeout=15)
            if p.is_alive():
                p.terminate()
                p.join(timeout=5)
    assert not errors, f"survivor failures: {errors}"
    if expect_sigkill:
        for v in victims:
            assert procs[v].exitcode == -signal.SIGKILL, (
                v, procs[v].exitcode)
    return results


# ---- SIGKILL mid-step: typed error, attribution, bit-exact reform ----

_KILL_VICTIM = 2
# 3 warmup allreduces execute as ops 0..2 (one response each; sequential
# synchronize, so nothing fuses); the injected death lands at the top of
# op 3 — the "boom" collective — before the victim joins the ring.
_KILL_AT_OP = 3


def _kill_reform_worker(rank, size):
    from horovod_tpu.common import basics, eager_ops as ops
    from horovod_tpu.common.exceptions import (
        HorovodInternalError,
        HorovodPeerFailureError,
    )

    b = basics.HorovodBasics()
    b.init()
    victim = _KILL_VICTIM
    inputs = [_rank_input(r, _COUNT) for r in range(size)]
    for i in range(_KILL_AT_OP):
        out = ops.allreduce_async(inputs[rank], f"warm.{i}").synchronize()
        ref = _ring_reference(inputs)
        assert np.array_equal(out.view(np.uint32), ref.view(np.uint32))

    t0 = time.monotonic()
    try:
        ops.allreduce_async(inputs[rank], "boom").synchronize()
        return "boom-did-not-fail"  # victim dies inside; survivors raise
    except HorovodPeerFailureError as e:
        elapsed = time.monotonic() - t0
        # Typed, attributed, within deadline + slack (EOF detection is
        # near-instant; the non-neighbor worst case pays one deadline).
        assert victim in e.fault_ranks, (e.fault_ranks, str(e))
        assert e.epoch == 0
        assert elapsed < _TIMEOUT_MS / 1000.0 + 8.0, elapsed
    assert b.lib.hvdtpu_loop_failed() == 1
    fault = b.last_fault()
    assert fault is not None and victim in fault["ranks"], fault
    assert not fault["recovered"]

    # Survivors converge on the same dead set -> same reinit arguments.
    survivors = [r for r in range(size) if r != victim]
    b.reinit(survivors, 1)
    assert b.epoch() == 1
    assert b.rank() == survivors.index(rank)
    assert b.size() == len(survivors)
    assert b.last_fault()["recovered"] is True

    # Re-formed N-1 ring: bit-identical to a fresh N-1 numpy replay
    # (same rotation helpers => same association order).
    sub_inputs = [inputs[r] for r in survivors]
    out = ops.allreduce_async(inputs[rank], "reformed").synchronize()
    ref = _ring_reference(sub_inputs)
    assert np.array_equal(out.view(np.uint32), ref.view(np.uint32))

    # Telemetry booked the fault lifecycle.
    snap = b.metrics_snapshot()
    el = snap["elastic"]
    assert el["epoch"] == 1
    assert el["faults_detected"] >= 1
    assert el["faults_recovered"] == 1
    assert el["ranks_blacklisted"] == 1
    assert el["detect_us"]["count"] >= 1
    b.shutdown()
    return "ok"


def test_sigkilled_rank_typed_error_and_bitexact_reform():
    results = run_chaos(
        _kill_reform_worker, 3, victims={_KILL_VICTIM},
        env={"HOROVOD_WIRE_TIMEOUT_MS": str(_TIMEOUT_MS),
             "HOROVOD_FAULT_INJECT": f"{_KILL_VICTIM}:{_KILL_AT_OP}"})
    assert results == {0: "ok", 1: "ok"}


def _kill_reform_striped_worker(rank, size):
    from horovod_tpu.common import basics, eager_ops as ops
    from horovod_tpu.common.exceptions import HorovodInternalError

    b = basics.HorovodBasics()
    b.init()
    assert b.wire_channels_established() == 4
    victim = _KILL_VICTIM
    inputs = [_rank_input(r, _COUNT) for r in range(size)]
    for i in range(_KILL_AT_OP):
        ops.allreduce_async(inputs[rank], f"warm.{i}").synchronize()
    try:
        ops.allreduce_async(inputs[rank], "boom").synchronize()
        return "boom-did-not-fail"
    except HorovodInternalError:
        pass
    survivors = [r for r in range(size) if r != victim]
    b.reinit(survivors, 1)
    # The re-formed ring rebuilt ALL K sockets per survivor pair: the
    # established count survives the epoch bump, and a striped
    # allreduce over the new mesh is bit-identical to the fresh-(N-1)
    # numpy replay (striping never changes the reduce order).
    assert b.wire_channels_established() == 4
    assert b.wire_channels() == 4
    sub_inputs = [inputs[r] for r in survivors]
    out = ops.allreduce_async(inputs[rank], "reformed").synchronize()
    ref = _ring_reference(sub_inputs)
    assert np.array_equal(out.view(np.uint32), ref.view(np.uint32))
    # Striped traffic flowed on the regrown mesh: more than one channel
    # bucket moved bytes (the N-1=2 world is pairwise, so the paired
    # plan spreads directions over the stripe set).
    chans = b.metrics_snapshot()["wire"]["channels"]
    assert len(chans) > 1, chans
    assert sum(c["tx_bytes"] + c["rx_bytes"] for c in chans[1:]) > 0, chans
    b.shutdown()
    return "ok"


def test_reinit_rebuilds_all_stripe_channels():
    """Elastic re-formation under HOROVOD_WIRE_CHANNELS=4: reinit must
    rebuild all K sockets per survivor pair (the channel id rides the
    re-rendezvous hello at the bumped epoch) and the striped ring on
    the regrown mesh stays bit-exact."""
    results = run_chaos(
        _kill_reform_striped_worker, 3, victims={_KILL_VICTIM},
        env={"HOROVOD_WIRE_TIMEOUT_MS": str(_TIMEOUT_MS),
             "HOROVOD_WIRE_CHANNELS": "4",
             "HOROVOD_RING_CHUNK_BYTES": "1024",
             "HOROVOD_FAULT_INJECT": f"{_KILL_VICTIM}:{_KILL_AT_OP}"})
    assert results == {0: "ok", 1: "ok"}


# ---- silent stall (SIGSTOP): deadline attribution, no EOF to lean on --


def _stall_worker(rank, size):
    from horovod_tpu.common import basics, eager_ops as ops
    from horovod_tpu.common.exceptions import HorovodPeerFailureError

    b = basics.HorovodBasics()
    b.init()
    assert b.wire_timeout_ms() == _TIMEOUT_MS
    x = np.ones(64, np.float32)
    ops.allreduce_async(x, "w0").synchronize()
    if rank == 1:
        os.kill(os.getpid(), signal.SIGSTOP)  # freeze, do not die
        return "stopped"  # unreachable until SIGCONT; parent reaps us
    t0 = time.monotonic()
    try:
        ops.allreduce_async(x, "stall").synchronize()
        return "stall-did-not-fail"
    except HorovodPeerFailureError as e:
        elapsed = time.monotonic() - t0
        msg = str(e)
        # The stalled peer + stalled milliseconds ride the message.
        assert 1 in e.fault_ranks, (e.fault_ranks, msg)
        assert "rank 1" in msg and "ms" in msg, msg
        assert 0.5 < elapsed < _TIMEOUT_MS / 1000.0 + 10.0, elapsed
    b.shutdown()
    return "ok"


def test_sigstopped_peer_times_out_with_attribution():
    results = run_chaos(
        _stall_worker, 2, victims={1},
        env={"HOROVOD_WIRE_TIMEOUT_MS": str(_TIMEOUT_MS)},
        expect_sigkill=False)  # the victim is reaped by the harness
    assert results == {0: "ok"}


# ---- full recovery glue: commit/restore/sync over in-process reinit --

_TRAIN_STEPS = 8
_TRAIN_FAIL_STEP = 5
_TRAIN_DIM = 257
_TRAIN_LR = 0.1
# state.sync() costs 2 broadcasts (ops 0-1); step s's allreduce is op
# 2 + s, so the victim dies at the top of step _TRAIN_FAIL_STEP.
_TRAIN_KILL_OP = 2 + _TRAIN_FAIL_STEP


def _grad(step, rank):
    return np.full(_TRAIN_DIM, 0.01 * (step + 1) * (rank + 1), np.float32)


def _train_reference():
    """The expected trajectory: 3-rank mean grads through the last
    commit (end of step _TRAIN_FAIL_STEP - 1), then 2-rank mean grads —
    exactly an uninterrupted N-1 run resumed from the commit."""
    p = np.zeros(_TRAIN_DIM, np.float64)
    for s in range(_TRAIN_STEPS):
        world = (1, 2, 3) if s < _TRAIN_FAIL_STEP else (1, 2)
        mean = 0.01 * (s + 1) * sum(world) / len(world)
        p = p - _TRAIN_LR * mean
    return p


def _train_worker(rank, size):
    from horovod_tpu.common import basics, eager_ops as ops
    from horovod_tpu.common import elastic as hvd_elastic
    from horovod_tpu.common.elastic import ObjectState

    b = basics.HorovodBasics()
    hvd_elastic.init()

    state = ObjectState(step=0, params=np.zeros(_TRAIN_DIM, np.float32))
    epochs_seen = []

    @hvd_elastic.run_fn
    def train(state):
        epochs_seen.append(b.epoch())
        while state.step < _TRAIN_STEPS:
            g = _grad(state.step, b.rank())
            mean = ops.allreduce_async(
                g, f"grad.{state.step}.{b.epoch()}",
                op=ops.ReduceOp.AVERAGE).synchronize()
            state.params = state.params - _TRAIN_LR * mean
            state.step += 1
            state.commit()
        return state.params

    params = train(state)
    # The victim (rank 2) never gets here; survivors recovered in place.
    assert epochs_seen == [0, 1], epochs_seen
    assert (b.epoch(), b.size()) == (1, 2), (b.epoch(), b.size())
    assert state.step == _TRAIN_STEPS, state.step
    np.testing.assert_allclose(params, _train_reference(), rtol=1e-5,
                               atol=1e-7)
    b.shutdown()
    return "ok"


def test_elastic_run_recovers_training_from_last_commit():
    results = run_chaos(
        _train_worker, 3, victims={2},
        env={"HOROVOD_WIRE_TIMEOUT_MS": str(_TIMEOUT_MS),
             "HOROVOD_FAULT_INJECT": f"2:{_TRAIN_KILL_OP}"})
    assert results == {0: "ok", 1: "ok"}


# ---- elastic x hierarchical: reinit re-derives the slice layout ------

_HIER_SIZE = 6          # 3 emulated hosts x 2 ranks, host-major
_HIER_LOCAL = 2
_HIER_WARMUPS = 2       # ops 0..1; both victims die at op 2


def _hier_reform_worker(rank, size):
    import os

    os.environ.update({
        "HOROVOD_LOCAL_RANK": str(rank % _HIER_LOCAL),
        "HOROVOD_LOCAL_SIZE": str(_HIER_LOCAL),
        "HOROVOD_CROSS_RANK": str(rank // _HIER_LOCAL),
        "HOROVOD_CROSS_SIZE": str(size // _HIER_LOCAL),
    })
    from horovod_tpu.common import basics, eager_ops as ops
    from horovod_tpu.common.exceptions import HorovodPeerFailureError

    b = basics.HorovodBasics()
    b.init()
    assert b.hier_split() == _HIER_LOCAL  # hier active pre-fault
    vals = (np.arange(512, dtype=np.float32) % 5) - 2  # exact ints
    for i in range(_HIER_WARMUPS):
        out = ops.allreduce_async(vals * (rank + 1),
                                  f"warm.{i}").synchronize()
        np.testing.assert_array_equal(out, vals * sum(range(1, size + 1)))
    # BOTH ranks of host 2 die at the same collective — the whole slice
    # vanishes, which is exactly the preemption shape (a spot slice is
    # reclaimed wholesale).
    if rank >= 4:
        b.set_fault_inject(rank, _HIER_WARMUPS)
    try:
        ops.allreduce_async(vals, "boom").synchronize()
        return "boom-did-not-fail"
    except HorovodPeerFailureError as e:
        assert set(e.fault_ranks) & {4, 5}, e.fault_ranks

    # Survivors = hosts 0 and 1 intact: the re-derived layout must tile
    # 2 hosts x 2 ranks and KEEP the hierarchical decomposition (the
    # pre-fix core force-flattened here).
    b.reinit([0, 1, 2, 3], 1)
    assert b.size() == 4
    assert b.local_size() == _HIER_LOCAL, b.local_size()
    assert b.cross_size() == 2, b.cross_size()
    assert b.local_rank() == b.rank() % _HIER_LOCAL
    assert b.hier_split() == _HIER_LOCAL, b.hier_split()

    snap0 = b.metrics_snapshot()["wire"]["cross_tx_bytes"]
    out = ops.allreduce_async(vals * (rank + 1), "reformed").synchronize()
    np.testing.assert_array_equal(out, vals * 10)  # exact: sum 1..4
    assert b.metrics_snapshot()["wire"]["cross_tx_bytes"] > snap0
    b.shutdown()
    return "ok"


def test_reinit_rederives_hier_layout_when_slice_dies_whole():
    results = run_chaos(
        _hier_reform_worker, _HIER_SIZE, victims={4, 5},
        env={"HOROVOD_WIRE_TIMEOUT_MS": str(_TIMEOUT_MS),
             "HOROVOD_CROSS_PLANE": "hier"})
    assert results == {r: "ok" for r in range(4)}


def _hier_uneven_worker(rank, size):
    import os

    os.environ.update({
        "HOROVOD_LOCAL_RANK": str(rank % 2),
        "HOROVOD_LOCAL_SIZE": "2",
        "HOROVOD_CROSS_RANK": str(rank // 2),
        "HOROVOD_CROSS_SIZE": str(size // 2),
    })
    from horovod_tpu.common import basics, eager_ops as ops
    from horovod_tpu.common.exceptions import HorovodPeerFailureError

    b = basics.HorovodBasics()
    b.init()
    assert b.hier_split() == 2
    x = np.ones(64, np.float32)
    ops.allreduce_async(x, "w0").synchronize()
    try:
        ops.allreduce_async(x, "boom").synchronize()
        return "boom-did-not-fail"
    except HorovodPeerFailureError:
        pass
    # One rank of host 1 died: 3 survivors cannot tile 2-per-host, so
    # the reform falls back to the flat ring (correctness over plane
    # optimality) — and still computes exact results.
    b.reinit([0, 1, 2], 1)
    assert b.size() == 3
    assert b.hier_split() == 0, b.hier_split()
    assert b.local_size() == 3  # flat layout
    out = ops.allreduce_async(np.full(7, float(b.rank() + 1), np.float32),
                              "flat").synchronize()
    np.testing.assert_array_equal(out, np.full(7, 6.0))
    b.shutdown()
    return "ok"


def test_reinit_falls_back_flat_on_uneven_survivor_tiling():
    results = run_chaos(
        _hier_uneven_worker, 4, victims={3},
        env={"HOROVOD_WIRE_TIMEOUT_MS": str(_TIMEOUT_MS),
             "HOROVOD_CROSS_PLANE": "hier",
             "HOROVOD_FAULT_INJECT": "3:1"})
    assert results == {0: "ok", 1: "ok", 2: "ok"}


# ---- reinit must FAIL (not hang) when a listed survivor never shows --


def _reinit_timeout_worker(rank, size):
    from horovod_tpu.common import basics, eager_ops as ops
    from horovod_tpu.common.exceptions import HorovodPeerFailureError

    b = basics.HorovodBasics()
    b.init()
    x = np.ones(64, np.float32)
    ops.allreduce_async(x, "w0").synchronize()  # op 0; rank 1 dies at op 1
    try:
        ops.allreduce_async(x, "boom").synchronize()
        return "boom-did-not-fail"
    except HorovodPeerFailureError:
        pass
    # Wrongly list the dead rank as a survivor: the re-formation
    # rendezvous must time out with -4 within HOROVOD_START_TIMEOUT,
    # never hang in accept (the pre-fix behavior). Set the tight
    # timeout only NOW — reinit re-reads env — so the initial
    # rendezvous keeps its startup-skew patience.
    os.environ["HOROVOD_START_TIMEOUT"] = "3"
    t0 = time.monotonic()
    try:
        b.reinit([0, 1], 1)
        return "bad-reinit-did-not-fail"
    except RuntimeError as e:
        assert "rendezvous failed" in str(e), str(e)
        assert time.monotonic() - t0 < 20, time.monotonic() - t0
    # The failed attempt restored the old (dead) world; a correct
    # survivor list still recovers.
    b.reinit([0], 2)
    out = ops.allreduce_async(x, "solo").synchronize()
    assert np.array_equal(out, x)
    assert b.epoch() == 2 and b.size() == 1
    b.shutdown()
    return "ok"


def test_reinit_times_out_on_missing_survivor():
    results = run_chaos(
        _reinit_timeout_worker, 2, victims={1},
        env={"HOROVOD_WIRE_TIMEOUT_MS": str(_TIMEOUT_MS),
             "HOROVOD_FAULT_INJECT": "1:1"})
    assert results == {0: "ok"}


# ---- knob plumbing (no ring needed) ----------------------------------


def test_wire_timeout_knob_roundtrip():
    from horovod_tpu.common import basics

    b = basics.HorovodBasics()
    saved = b.wire_timeout_ms()
    try:
        b.set_wire_timeout_ms(12345)
        assert b.wire_timeout_ms() == 12345
        b.set_wire_timeout_ms(0)  # 0 = deadline disabled
        assert b.wire_timeout_ms() == 0
    finally:
        b.set_wire_timeout_ms(saved)


def test_last_fault_none_without_fault():
    from horovod_tpu.common import basics

    assert basics.HorovodBasics().last_fault() is None
