"""xla_ici device data plane: eager collectives as cached XLA programs.

Reference analog: test/parallel/test_torch.py's op×dtype sweeps — but for
the device path, where the payload stays a jax array end-to-end and the
fused group executes as one compiled program over a gloo (test) / ICI
(TPU) mesh. Expected values are analytic, as in the reference.
"""

import numpy as np
import pytest

from tests.utils_mp import run_ranks

_ENV = {"HOROVOD_XLA_DATA_PLANE": "1"}


def _worker_basic_ops(rank, size):
    import jax
    import jax.numpy as jnp

    import horovod_tpu.jax as hvd
    from horovod_tpu.jax import xla_ici

    hvd.init()
    try:
        assert xla_ici.active()
        # sum
        out = hvd.allreduce(jnp.full((4,), float(rank)), op=hvd.Sum)
        assert isinstance(out, jax.Array)
        np.testing.assert_allclose(np.asarray(out), sum(range(size)))
        # average
        out = hvd.allreduce(jnp.full((3, 2), float(rank + 1)),
                            op=hvd.Average)
        np.testing.assert_allclose(np.asarray(out), (size + 1) / 2)
        # min / max / product over rank-distinct values
        vals = jnp.array([float(rank + 1), float(-rank)])
        np.testing.assert_allclose(
            np.asarray(hvd.allreduce(vals, op=hvd.Min)),
            [1.0, -(size - 1)])
        np.testing.assert_allclose(
            np.asarray(hvd.allreduce(vals, op=hvd.Max)),
            [float(size), 0.0])
        np.testing.assert_allclose(
            np.asarray(hvd.allreduce(jnp.full((2,), float(rank + 2)),
                                     op=hvd.Product)),
            float(np.prod([i + 2 for i in range(size)])))
        # scalar round-trip keeps its shape
        out = hvd.allreduce(jnp.asarray(float(rank)), op=hvd.Sum)
        assert out.shape == ()
        np.testing.assert_allclose(float(out), sum(range(size)))
        # int dtype
        out = hvd.allreduce(jnp.full((4,), rank, jnp.int32), op=hvd.Sum)
        assert out.dtype == jnp.int32
        np.testing.assert_array_equal(np.asarray(out), sum(range(size)))
        # prescale/postscale fold into the program
        out = hvd.allreduce(jnp.full((2,), float(rank + 1)), op=hvd.Sum,
                            prescale_factor=0.5, postscale_factor=4.0)
        np.testing.assert_allclose(
            np.asarray(out), 0.5 * sum(i + 1 for i in range(size)) * 4.0)
        return "ok"
    finally:
        hvd.shutdown()


def test_device_allreduce_ops():
    assert run_ranks(_worker_basic_ops, 2, env=_ENV,
                     timeout=240) == ["ok"] * 2


def _worker_bcast_gather_scatter(rank, size):
    import jax.numpy as jnp

    import horovod_tpu.jax as hvd

    hvd.init()
    try:
        # broadcast
        out = hvd.broadcast(jnp.full((2, 3), float(rank + 5)), root_rank=1)
        np.testing.assert_allclose(np.asarray(out), 6.0)
        # ragged allgather: rank r contributes r+1 rows
        out = hvd.allgather(jnp.full((rank + 1, 2), float(rank)))
        exp = np.concatenate(
            [np.full((i + 1, 2), float(i)) for i in range(size)])
        np.testing.assert_allclose(np.asarray(out), exp)
        # reducescatter with an uneven first-dim split (5 rows over 2)
        big = jnp.arange(10, dtype=jnp.float32).reshape(5, 2) * (rank + 1)
        out = hvd.reducescatter(big, op=hvd.Sum)
        full = (np.arange(10, dtype=np.float32).reshape(5, 2)
                * sum(i + 1 for i in range(size)))
        rows = [5 // size + (1 if i < 5 % size else 0) for i in range(size)]
        off = sum(rows[:rank])
        np.testing.assert_allclose(np.asarray(out),
                                   full[off:off + rows[rank]])
        return "ok"
    finally:
        hvd.shutdown()


def test_device_bcast_gather_scatter():
    assert run_ranks(_worker_bcast_gather_scatter, 2, env=_ENV,
                     timeout=240) == ["ok"] * 2


def _worker_fusion_and_cache(rank, size):
    import jax.numpy as jnp

    import horovod_tpu.jax as hvd
    from horovod_tpu.jax import xla_ici

    hvd.init()
    try:
        # Async burst: same dtype/op tensors may fuse into one program.
        # Values must be exact either way; steady-state repeats must reuse
        # the executable cache instead of growing it.
        for step in range(4):
            hs = [hvd.allreduce_async(
                      jnp.full((8 + i,), float(rank + step)),
                      name=f"grad.{i}", op=hvd.Sum)
                  for i in range(3)]
            for i, h in enumerate(hs):
                out = h.synchronize()
                assert out.shape == (8 + i,)
                np.testing.assert_allclose(
                    np.asarray(out), sum(range(size)) + size * step)
            if step == 2:
                # Steps 0-1 may group differently (first negotiation vs
                # response-cache replay); by step 2 the cached grouping is
                # the steady state and must stop compiling.
                steady = len(xla_ici.data_plane()._exec_cache)
        assert len(xla_ici.data_plane()._exec_cache) == steady, \
            "executable cache grew on steady-state replay"
        # Device responses must HIT the response cache in steady state
        # (regression: the cached slot once dropped the device flag, which
        # forced eviction + full renegotiation every cycle).
        from horovod_tpu.common.basics import HorovodBasics
        hits = HorovodBasics().lib.hvdtpu_response_cache_hits()
        assert hits > 0, "device tensors never hit the response cache"
        return "ok"
    finally:
        hvd.shutdown()


def test_device_fusion_and_executable_cache():
    # A long cycle makes the async burst land in ONE negotiation cycle
    # every step, so the fused grouping — and thus the executable-cache
    # signature — is deterministic on a loaded one-core box.
    env = dict(_ENV, HOROVOD_CYCLE_TIME="50")
    assert run_ranks(_worker_fusion_and_cache, 2, env=env,
                     timeout=240) == ["ok"] * 2


def _worker_alltoall(rank, size):
    import jax
    import jax.numpy as jnp

    import horovod_tpu.jax as hvd

    hvd.init()
    try:
        # Equal splits: rank r sends block j (rows of value r*10+j) to
        # rank j; rank r ends with [0*10+r, 1*10+r, ...].
        rows = 2
        x = jnp.concatenate([jnp.full((rows, 3), float(rank * 10 + j))
                             for j in range(size)])
        out = hvd.alltoall(x)
        assert isinstance(out, jax.Array)
        exp = np.concatenate([np.full((rows, 3), float(j * 10 + rank))
                              for j in range(size)])
        np.testing.assert_allclose(np.asarray(out), exp)
        # Steady state: repeated device alltoall hits the response cache
        # (static shapes make it cacheable, unlike the host path).
        from horovod_tpu.common.basics import HorovodBasics
        for _ in range(3):
            out = hvd.alltoall(x, name="a2a.steady")
        hits, _, _ = HorovodBasics().response_cache_stats()
        assert hits > 0, "device alltoall never hit the response cache"
        # Ragged splits fall back to the host ring transparently.
        splits = [rank + 1] + [1] * (size - 1)
        total = sum(splits)
        xr = jnp.arange(total, dtype=jnp.float32)
        out = hvd.alltoall(xr, splits=splits)
        assert out.ndim == 1
        return "ok"
    finally:
        hvd.shutdown()


def test_device_alltoall():
    # Equal-split device alltoall is opt-in (a rank can't see its peers'
    # shapes, so ragged splits=None must default to the host ring).
    env = dict(_ENV, HOROVOD_XLA_ALLTOALL="1")
    assert run_ranks(_worker_alltoall, 2, env=env,
                     timeout=240) == ["ok"] * 2


def _worker_grouped_atomic(rank, size):
    import jax.numpy as jnp

    import horovod_tpu.jax as hvd
    from horovod_tpu.jax import xla_ici

    hvd.init()
    try:
        # HOROVOD_FUSION_THRESHOLD=16 bytes: ordinary fusion can't merge
        # these tensors, so ONE executable whose signature carries all
        # three shapes proves the group negotiated atomically.
        hs = hvd.grouped_allreduce_async(
            [jnp.full((8 + i,), float(rank)) for i in range(3)],
            names=[f"g.{i}" for i in range(3)], op=hvd.Sum)
        for i, h in enumerate(hs):
            out = h.synchronize()
            assert out.shape == (8 + i,)
            np.testing.assert_allclose(np.asarray(out), sum(range(size)))
        sigs = list(xla_ici.data_plane()._exec_cache)
        assert any(len(sig[3]) == 3 for sig in sigs), \
            f"group did not fuse into one program: {sigs}"
        return "ok"
    finally:
        hvd.shutdown()


def test_device_grouped_allreduce_atomic():
    env = dict(_ENV, HOROVOD_FUSION_THRESHOLD="16")
    assert run_ranks(_worker_grouped_atomic, 2, env=env,
                     timeout=240) == ["ok"] * 2


def _worker_grouped_gather_scatter(rank, size):
    import jax.numpy as jnp

    import horovod_tpu.jax as hvd

    hvd.init()
    try:
        # Grouped allgather (ragged first dims per member) through the
        # device plane: atomic negotiation, per-tensor responses.
        outs = hvd.grouped_allgather(
            [jnp.full((rank + 1, 2), float(rank + i)) for i in range(3)],
            names=[f"gag.{i}" for i in range(3)])
        for i, o in enumerate(outs):
            exp = np.concatenate(
                [np.full((r + 1, 2), float(r + i)) for r in range(size)])
            np.testing.assert_allclose(np.asarray(o), exp)
        # Grouped reducescatter: 4 rows split over the member ranks.
        outs = hvd.grouped_reducescatter(
            [jnp.arange(8, dtype=jnp.float32).reshape(4, 2) * (rank + 1 + i)
             for i in range(2)],
            names=[f"grs.{i}" for i in range(2)], op=hvd.Sum)
        rows = 4 // size
        for i, o in enumerate(outs):
            full = (np.arange(8, dtype=np.float32).reshape(4, 2)
                    * sum(r + 1 + i for r in range(size)))
            np.testing.assert_allclose(
                np.asarray(o), full[rank * rows:(rank + 1) * rows])
        return "ok"
    finally:
        hvd.shutdown()


def test_device_grouped_allgather_reducescatter():
    assert run_ranks(_worker_grouped_gather_scatter, 2, env=_ENV,
                     timeout=240) == ["ok"] * 2


def test_host_grouped_allgather_reducescatter():
    # Same worker with the device plane OFF exercises the host-path
    # grouped enqueues (eager_ops.grouped_*_async).
    assert run_ranks(_worker_grouped_gather_scatter, 2,
                     env={"HOROVOD_XLA_DATA_PLANE": "0"},
                     timeout=240) == ["ok"] * 2


def _worker_grouped_gather_process_set(rank, size):
    import jax.numpy as jnp

    import horovod_tpu.jax as hvd

    hvd.init()
    try:
        # Grouped allgather over a process-set SUBSET (ranks 0,2 of 3):
        # the non-member runs an unrelated collective concurrently — the
        # atomic group must complete among members only. Process sets
        # register collectively (same order on every rank).
        ps = hvd.add_process_set([0, 2])
        ps_solo = hvd.add_process_set([1])
        if rank in (0, 2):
            pos = (0, None, 1)[rank]
            outs = hvd.grouped_allgather(
                [jnp.full((pos + 1, 2), float(rank + i))
                 for i in range(2)],
                names=[f"psg.{i}" for i in range(2)], process_set_id=ps)
            for i, o in enumerate(outs):
                exp = np.concatenate(
                    [np.full((p + 1, 2), float(r + i))
                     for r, p in ((0, 0), (2, 1))])
                np.testing.assert_allclose(np.asarray(o), exp)
        else:
            out = hvd.allreduce(jnp.full((4,), 7.0), op=hvd.Sum,
                                process_set_id=ps_solo)
            np.testing.assert_allclose(np.asarray(out), 7.0)
        hvd.barrier()
        return "ok"
    finally:
        hvd.shutdown()


def test_device_grouped_allgather_process_set():
    assert run_ranks(_worker_grouped_gather_process_set, 3, env=_ENV,
                     timeout=300) == ["ok"] * 3


def _worker_elastic_fast_reinit(rank, size):
    import jax.numpy as jnp

    import horovod_tpu.jax as hvd
    from horovod_tpu.common.basics import HorovodBasics
    from horovod_tpu.jax import xla_ici

    hvd.init()
    try:
        out = hvd.allreduce(jnp.full((8,), float(rank)), op=hvd.Sum)
        np.testing.assert_allclose(np.asarray(out), sum(range(size)))
        dp = xla_ici.data_plane()
        n0 = dp.executable_cache_size()
        assert n0 > 0
        # The same-size epoch transition every SURVIVING rank runs in
        # elastic reset(): core down+up, device plane disable+enable.
        # Topology unchanged -> the compiled executables must be reused,
        # not recompiled (SURVEY §7 "cached-topology fast path").
        HorovodBasics().shutdown()
        xla_ici.disable()
        HorovodBasics().init()
        xla_ici.enable()
        assert dp.cache_reuses == 1 and dp.cache_invalidations == 0
        assert dp.executable_cache_size() == n0
        out = hvd.allreduce(jnp.full((8,), float(rank + 1)), op=hvd.Sum)
        np.testing.assert_allclose(np.asarray(out),
                                   sum(range(1, size + 1)))
        assert dp.executable_cache_size() == n0, \
            "same-signature collective recompiled after fast re-init"
        # Topology drift invalidates the lot.
        dp._retained_topology = ("another", "world")
        xla_ici.disable()
        xla_ici.enable()
        assert dp.cache_invalidations == 1
        assert dp.executable_cache_size() == 0
        out = hvd.allreduce(jnp.full((8,), 1.0), op=hvd.Sum)
        np.testing.assert_allclose(np.asarray(out), float(size))
        return "ok"
    finally:
        hvd.shutdown()


def test_elastic_fast_reinit_reuses_executables():
    assert run_ranks(_worker_elastic_fast_reinit, 2, env=_ENV,
                     timeout=240) == ["ok"] * 2


def _worker_donated_allreduce(rank, size):
    import jax.numpy as jnp

    import horovod_tpu.jax as hvd
    from horovod_tpu.jax.optimizer import allreduce_gradients

    hvd.init()
    try:
        # Grouped donated allreduce: results exact over repeated steps
        # (cached donated program replays) and the donated signature is
        # distinct from the non-donated one.
        for step in range(3):
            xs = [jnp.full((16,), float(rank + i + step)) for i in range(3)]
            hs = hvd.grouped_allreduce_async(
                xs, [f"don.{i}" for i in range(3)], op=hvd.Sum, donate=True)
            del xs  # donation contract: no live refs past the collective
            outs = [h.synchronize() for h in hs]
            for i, o in enumerate(outs):
                np.testing.assert_allclose(
                    np.asarray(o), sum(r + i + step for r in range(size)))
        # The gradient-tree helper with donation (the bench/optimizer
        # fast path) — tree in, averaged tree out.
        grads = {"w": jnp.full((4, 2), float(rank + 1)),
                 "b": jnp.full((4,), float(rank))}
        reduced = allreduce_gradients(grads, op=hvd.Average, donate=True)
        np.testing.assert_allclose(np.asarray(reduced["w"]),
                                   (size + 1) / 2)
        np.testing.assert_allclose(np.asarray(reduced["b"]),
                                   sum(range(size)) / size)
        return "ok"
    finally:
        hvd.shutdown()


def test_device_donated_allreduce():
    assert run_ranks(_worker_donated_allreduce, 2, env=_ENV,
                     timeout=240) == ["ok"] * 2


def _worker_process_set(rank, size):
    import jax.numpy as jnp

    import horovod_tpu.jax as hvd

    hvd.init()
    try:
        ps = hvd.add_process_set([0, 1])
        out = hvd.allreduce(jnp.full((4,), float(rank + 1)), op=hvd.Sum,
                            process_set_id=ps)
        np.testing.assert_allclose(np.asarray(out), 3.0)  # 1 + 2
        return "ok"
    finally:
        hvd.shutdown()


def test_device_process_set():
    assert run_ranks(_worker_process_set, 2, env=_ENV,
                     timeout=240) == ["ok"] * 2


def _worker_join(rank, size):
    import jax.numpy as jnp

    import horovod_tpu.jax as hvd

    hvd.init()
    try:
        if rank == 0:
            # Joined peers contribute zeros on-device.
            out = hvd.allreduce(jnp.full((4,), 3.0), op=hvd.Sum,
                                name="grad.j")
            np.testing.assert_allclose(np.asarray(out), 3.0)
            last = hvd.join()
        else:
            last = hvd.join()
        assert last >= 0
        return "ok"
    finally:
        hvd.shutdown()


def test_device_join_synthesizes_zeros():
    assert run_ranks(_worker_join, 2, env=_ENV, timeout=240) == ["ok"] * 2


def _worker_failed_collective_no_leak(rank, size):
    import jax.numpy as jnp

    import horovod_tpu.jax as hvd
    from horovod_tpu.common.exceptions import HorovodInternalError
    from horovod_tpu.jax import xla_ici

    hvd.init()
    try:
        # Mismatched dtypes across ranks -> ERROR response; the input
        # pinned in the data plane registry must be released.
        dt = jnp.float32 if rank == 0 else jnp.int32
        try:
            hvd.allreduce(jnp.zeros((4,), dt), name="bad.dtype", op=hvd.Sum)
            raise AssertionError("mismatched dtypes should fail")
        except HorovodInternalError:
            pass
        assert not xla_ici.data_plane()._inputs, "leaked device input"
        # The core must still be usable afterwards.
        out = hvd.allreduce(jnp.full((2,), float(rank)), op=hvd.Sum)
        np.testing.assert_allclose(np.asarray(out), sum(range(size)))
        return "ok"
    finally:
        hvd.shutdown()


def test_failed_device_collective_releases_input():
    assert run_ranks(_worker_failed_collective_no_leak, 2, env=_ENV,
                     timeout=240) == ["ok"] * 2


def _worker_adasum_host_fallback(rank, size):
    import jax.numpy as jnp

    import horovod_tpu.jax as hvd
    from horovod_tpu.jax import mpi_ops, xla_ici

    hvd.init()
    try:
        # Non-power-of-two group: Adasum stays on the host ring; the
        # result is still a jax array.
        x = jnp.full((4,), float(rank + 1))
        assert not mpi_ops._device_path(x, hvd.Adasum)
        assert not xla_ici.adasum_device_supported(0, x.dtype)
        out = hvd.allreduce(x, op=hvd.Adasum)
        assert out.shape == (4,)
        assert np.isfinite(np.asarray(out)).all()
        return "ok"
    finally:
        hvd.shutdown()


def test_adasum_falls_back_to_host_path():
    # 3 ranks: not a power of two -> host path serves Adasum.
    assert run_ranks(_worker_adasum_host_fallback, 3, env=_ENV,
                     timeout=240) == ["ok"] * 3


def _adasum_ref(vectors):
    """Recursive-doubling Adasum in numpy (same pairing as the device
    program and csrc/adasum.cc's closed form)."""
    vs = [np.asarray(v, np.float64) for v in vectors]
    n, d = len(vs), 1
    while d < n:
        nxt = list(vs)
        for i in range(n):
            a, b = vs[i], vs[i ^ d]
            dot, na, nb = (a * b).sum(), (a * a).sum(), (b * b).sum()
            ca = 1.0 - dot / (2.0 * na) if na > 0 else 1.0
            cb = 1.0 - dot / (2.0 * nb) if nb > 0 else 1.0
            nxt[i] = ca * a + cb * b
        vs, d = nxt, d * 2
    return vs[0]


def _worker_adasum_device(rank, size):
    import jax.numpy as jnp

    import horovod_tpu.jax as hvd
    from horovod_tpu.jax import mpi_ops

    hvd.init()
    try:
        x = jnp.arange(1.0, 7.0) * (rank + 1) - rank  # rank-distinct
        assert mpi_ops._device_path(x, hvd.Adasum)  # pow2 float group
        out = hvd.allreduce(x, op=hvd.Adasum, name="adasum.dev")
        ref = _adasum_ref([np.arange(1.0, 7.0) * (r + 1) - r
                           for r in range(size)])
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5)
        # orthogonal gradients behave like sum; identical ones like mean
        e = jnp.zeros((4,)).at[rank % 4].set(1.0)
        if size <= 4:
            out = hvd.allreduce(e, op=hvd.Adasum, name="adasum.orth")
            np.testing.assert_allclose(
                np.asarray(out),
                np.sum([np.eye(4)[r % 4] for r in range(size)], axis=0),
                rtol=1e-5)
        same = jnp.full((3,), 2.0)
        out = hvd.allreduce(same, op=hvd.Adasum, name="adasum.same")
        np.testing.assert_allclose(np.asarray(out), 2.0, rtol=1e-5)
        return "ok"
    finally:
        hvd.shutdown()


def test_adasum_device_plane():
    for size in (2, 4):
        assert run_ranks(_worker_adasum_device, size, env=_ENV,
                         timeout=240) == ["ok"] * size


def _worker_timeline_xprof(rank, size):
    import glob
    import json
    import os
    import tempfile

    import jax.numpy as jnp

    import horovod_tpu.jax as hvd

    hvd.init()
    try:
        d = tempfile.mkdtemp()
        tl = os.path.join(d, "t.json")
        hvd.start_timeline(tl, xprof_dir=d)
        out = hvd.allreduce(jnp.ones((4,)), op=hvd.Sum, name="tl.ar")
        assert float(out[0]) == size
        gathered = hvd.allgather(jnp.ones((2,)), name="tl.ag")
        assert gathered.shape == (2 * size,)
        hvd.stop_timeline()
        trace = json.load(open(tl))  # valid chrome trace
        # The device plane's execution phase must be visible, not just
        # negotiation: ExecuteDeviceResponse wraps the XLA replay in
        # XLA_<OP> activity spans (VERDICT r3 missing #4).
        names = {e.get("name") for e in trace if isinstance(e, dict)}
        assert "NEGOTIATE" in names, sorted(names)
        assert "XLA_ALLREDUCE" in names, sorted(names)
        assert "XLA_ALLGATHER" in names, sorted(names)
        spans = [e for e in trace if isinstance(e, dict)
                 and e.get("name") == "XLA_ALLREDUCE"]
        assert any(e.get("ph") == "B" and
                   e.get("args", {}).get("tensor") == "tl.ar"
                   for e in spans), spans
        assert glob.glob(d + "/**/*.xplane.pb", recursive=True), \
            "no xprof trace written"
        return "ok"
    finally:
        hvd.shutdown()


def _worker_dtype_matrix(rank, size):
    import jax.numpy as jnp

    import horovod_tpu.jax as hvd

    hvd.init()
    try:
        # bf16 is what real TPU gradients are; int32 rounds the matrix
        # out (64-bit dtypes need jax x64 mode — the host-path tests
        # cover those; bool rides broadcast's uint8 path).
        for dt, tol in ((jnp.bfloat16, 1e-2), (jnp.float16, 1e-2),
                        (jnp.float32, 1e-6), (jnp.int32, 0)):
            out = hvd.allreduce(jnp.full((8,), rank + 1, dt), op=hvd.Sum,
                                name=f"dt.{jnp.dtype(dt).name}")
            assert out.dtype == dt, (dt, out.dtype)
            np.testing.assert_allclose(
                np.asarray(out.astype(jnp.float64)),
                sum(i + 1 for i in range(size)), atol=float(tol))
        out = hvd.broadcast(jnp.array([True, False, rank == 0]),
                            root_rank=1)
        assert out.dtype == jnp.bool_
        np.testing.assert_array_equal(np.asarray(out),
                                      [True, False, size == 1])
        return "ok"
    finally:
        hvd.shutdown()


def test_device_dtype_matrix():
    assert run_ranks(_worker_dtype_matrix, 2, env=_ENV,
                     timeout=240) == ["ok"] * 2


def test_timeline_with_xprof_bridge():
    assert run_ranks(_worker_timeline_xprof, 2, env=_ENV,
                     timeout=240) == ["ok"] * 2


@pytest.mark.parametrize("np_ranks", [3])
def test_device_three_ranks(np_ranks):
    assert run_ranks(_worker_basic_ops, np_ranks, env=_ENV,
                     timeout=300) == ["ok"] * np_ranks


def _worker_eight_ranks(rank, size):
    import jax.numpy as jnp

    import horovod_tpu.jax as hvd
    from horovod_tpu.common.basics import HorovodBasics
    from horovod_tpu.jax import xla_ici

    hvd.init()
    try:
        assert hvd.size() == 8
        # 1. allreduce at pod-like width.
        out = hvd.allreduce(jnp.full((16,), float(rank)), op=hvd.Sum,
                            name="w8.ar")
        np.testing.assert_allclose(np.asarray(out), sum(range(size)))
        # 2. grouped allgather (ragged) + grouped reducescatter, one
        # atomic group each across all 8 ranks.
        outs = hvd.grouped_allgather(
            [jnp.full((rank + 1, 2), float(rank + i)) for i in range(2)],
            names=[f"w8.gag.{i}" for i in range(2)])
        for i, o in enumerate(outs):
            exp = np.concatenate(
                [np.full((r + 1, 2), float(r + i)) for r in range(size)])
            np.testing.assert_allclose(np.asarray(o), exp)
        outs = hvd.grouped_reducescatter(
            [jnp.arange(16, dtype=jnp.float32).reshape(8, 2) * (rank + 1)],
            names=["w8.grs"], op=hvd.Sum)
        full = (np.arange(16, dtype=np.float32).reshape(8, 2)
                * sum(r + 1 for r in range(size)))
        np.testing.assert_allclose(np.asarray(outs[0]),
                                   full[rank:rank + 1])
        # 3. process-set subset: the evens gather among themselves while
        # the odds run an unrelated allreduce concurrently.
        evens = hvd.add_process_set([0, 2, 4, 6])
        odds = hvd.add_process_set([1, 3, 5, 7])
        if rank % 2 == 0:
            out = hvd.allgather(jnp.full((1, 2), float(rank)),
                                name="w8.ps", process_set_id=evens)
            exp = np.concatenate(
                [np.full((1, 2), float(r)) for r in (0, 2, 4, 6)])
            np.testing.assert_allclose(np.asarray(out), exp)
        else:
            out = hvd.allreduce(jnp.full((4,), 1.0), op=hvd.Sum,
                                name="w8.ps", process_set_id=odds)
            np.testing.assert_allclose(np.asarray(out), 4.0)
        hvd.barrier()
        # 4. elastic same-topology re-init at width 8: the executable
        # cache must survive (reuse, not recompile).
        dp = xla_ici.data_plane()
        n0 = dp.executable_cache_size()
        assert n0 > 0
        HorovodBasics().shutdown()
        xla_ici.disable()
        HorovodBasics().init()
        xla_ici.enable()
        assert dp.cache_reuses == 1 and dp.cache_invalidations == 0
        assert dp.executable_cache_size() == n0
        out = hvd.allreduce(jnp.full((16,), float(rank)), op=hvd.Sum,
                            name="w8.ar")  # same signature -> cache hit
        np.testing.assert_allclose(np.asarray(out), sum(range(size)))
        assert dp.executable_cache_size() == n0
        return "ok"
    finally:
        hvd.shutdown()


def test_device_eight_ranks():
    # The dryrun proves 8-device SPMD; this proves the EAGER plane —
    # negotiation, fused programs, process sets, elastic fast re-init —
    # at the same width (VERDICT r3 weak #1). 8 procs share one core:
    # generous timeout, absolute values still analytic.
    assert run_ranks(_worker_eight_ranks, 8, env=_ENV,
                     timeout=600) == ["ok"] * 8
