"""Autoscaler chaos (docs/scale.md): offered load ramps and the
telemetry-driven policy GROWS the world through the blacklist-parole
door at a healthy commit point, then SHRINKS it back when the load
drops — elastic as capacity management, not just fault response.

The policy is the pure AutoscalePolicy over a deterministic offered-
load trace (so every rank computes the identical decision at the same
commit — the SPMD agreement rule), and the actions it drives are the
REAL machinery: scale-up absorbs a live parolee knocking at the door
via an epoch transition (the r14 rejoin path, trajectory pinned the
same way test_chaos_matrix pins it), scale-down re-forms the ring
without the evicted rank through the negotiated-shutdown drain
(``hvd.elastic.shrink``), no fault anywhere.

Workers live in this importable module (spawn re-imports them).
"""

import numpy as np
import pytest

from tests.utils_mp import free_port
from tests.parallel.test_chaos_matrix import run_chaos

pytestmark = pytest.mark.quick

_AS_STEPS = 8
_AS_DIM = 97
_AS_LR = 0.1
_AS_BASE = 2          # world before the ramp
_AS_MAX = 3           # world at peak load


def _as_offered_load(step):
    """Queue-depth trace: overloaded while the ramp lasts, idle after."""
    return 100 if step <= 1 else 0


def _as_worlds_by_step(step):
    """Expected world (1-based rank multipliers) per step, given the
    policy knobs below: up streak completes at step 1's commit (grow),
    idle streak completes at step 5's commit (shrink)."""
    if step <= 1:
        return (1, 2)
    if step <= 5:
        return (1, 2, 3)
    return (1, 2)


def _as_reference(through_step=_AS_STEPS):
    p = np.zeros(_AS_DIM, np.float64)
    for s in range(through_step):
        world = _as_worlds_by_step(s)
        mean = 0.01 * (s + 1) * sum(world) / len(world)
        p = p - _AS_LR * mean
    return p


def _as_policy():
    from horovod_tpu.telemetry.autoscale import AutoscalePolicy

    # t is the step index; cooldown_s=0.5 expires by the next commit.
    return AutoscalePolicy(min_size=_AS_BASE, max_size=_AS_MAX, step=1,
                           up_queue_depth=8, up_consecutive=2,
                           down_consecutive=4, down_skew_ms=50.0,
                           cooldown_s=0.5)


class _Evicted(Exception):
    pass


def _as_train(state, b, ops, epochs_seen, sizes_seen):
    from horovod_tpu.common import elastic as hvd_elastic
    from horovod_tpu.common.exceptions import HostsUpdatedInterrupt
    from horovod_tpu.telemetry.autoscale import Signals

    policy = _as_policy()

    @hvd_elastic.run_fn
    def train(state):
        epochs_seen.append(b.epoch())
        while state.step < _AS_STEPS:
            g = np.full(_AS_DIM, 0.01 * (state.step + 1) * (b.rank() + 1),
                        np.float32)
            mean = ops.allreduce_async(
                g, f"as.{state.step}.{b.epoch()}",
                op=ops.ReduceOp.AVERAGE).synchronize()
            state.params = state.params - _AS_LR * mean
            sizes_seen.append((state.step, b.size()))
            state.step += 1
            state.commit()
            # One observation per commit: the offered-load trace plus
            # the LIVE signals (world size; rank 0 sees the pending
            # parolee). Every rank decides identically.
            decision = policy.decide(Signals(
                t=float(state.step - 1), world_size=b.size(),
                queue_depth=_as_offered_load(state.step - 1),
                straggler_skew_ms=0.0,
                pending_rejoiners=(
                    hvd_elastic._door.pending_count()
                    if hvd_elastic._door is not None else 0)))
            if decision.action == "up":
                # Healthy-commit scale-up: the epoch transition freezes
                # and absorbs the parolee at the door (r14 machinery).
                raise HostsUpdatedInterrupt(False)
            if decision.action == "down":
                victims = set(range(decision.target_size, b.size()))
                if not hvd_elastic.shrink(victims):
                    raise _Evicted()  # this rank left the world
        return state.params

    return train(state)


def _as_run_worker(rank, size, expect_epochs):
    import os
    import time

    from horovod_tpu.common import basics, eager_ops as ops
    from horovod_tpu.common import elastic as hvd_elastic
    from horovod_tpu.common.elastic import ObjectState

    b = basics.HorovodBasics()
    hvd_elastic.init()
    if rank == 0 and int(os.environ.get("HOROVOD_RANK", rank)) == 0:
        # Gate training on the parolee knocking, so the up decision
        # deterministically has a joiner to absorb.
        deadline = time.monotonic() + 60
        door = hvd_elastic._ensure_door()
        while door.pending_count() == 0:
            assert time.monotonic() < deadline, "joiner never knocked"
            time.sleep(0.05)
    state = ObjectState(step=0, params=np.zeros(_AS_DIM, np.float32))
    epochs_seen, sizes_seen = [], []
    try:
        params = _as_train(state, b, ops, epochs_seen, sizes_seen)
    except _Evicted:
        # The scale-down victim: its trajectory is pinned through the
        # shrink step, then it leaves the world cleanly (no fault) —
        # free to re-enter through the door at the next ramp.
        np.testing.assert_allclose(
            state.params, _as_reference(max(s for s, _ in sizes_seen) + 1),
            rtol=1e-5, atol=1e-7)
        assert not b.is_initialized()
        return "evicted"
    np.testing.assert_allclose(params, _as_reference(), rtol=1e-5,
                               atol=1e-7)
    assert epochs_seen == expect_epochs, epochs_seen
    # Grown to 3 for the loaded steps, back to 2 after the drain.
    worlds = sorted(set(sizes_seen))
    assert (0, _AS_BASE) in worlds and (7, _AS_BASE) in worlds, worlds
    assert (2, _AS_MAX) in worlds and (5, _AS_MAX) in worlds, worlds
    assert (b.size(), b.epoch()) == (_AS_BASE, 2), (b.size(), b.epoch())
    el = b.metrics_snapshot()["elastic"]
    # Capacity management, not fault response: zero faults end to end.
    assert el["faults_detected"] == 0, el
    assert el["ranks_rejoined"] == 1, el
    b.shutdown()
    return "ok"


def _as_survivor_worker(rank, size):
    return _as_run_worker(rank, size, expect_epochs=[0, 1])


def _as_joiner_worker(rank, size):
    import time

    from horovod_tpu.common import basics
    from horovod_tpu.common import elastic as hvd_elastic

    b = basics.HorovodBasics()
    deadline = time.monotonic() + 60
    while True:
        try:
            asg = hvd_elastic.rejoin(timeout=120)
            break
        except (OSError, ConnectionError):
            assert time.monotonic() < deadline, "door never opened"
            time.sleep(0.2)
    assert asg["rank"] == _AS_MAX - 1 and asg["size"] == _AS_MAX, asg
    # The joiner is rank 2 — the shrink victim once the load drops.
    return _as_run_worker(asg["rank"], asg["size"], expect_epochs=[1])


def test_autoscaler_grows_through_parole_door_then_shrinks_back():
    rejoin_port = free_port()
    results = run_chaos(
        _as_survivor_worker, _AS_BASE, victims=set(),
        expect_sigkill=False, timeout=180,
        env={"HOROVOD_WIRE_TIMEOUT_MS": "5000",
             "HOROVOD_REJOIN_PORT": str(rejoin_port),
             # Growth is the AUTOSCALER's call, not the commit poll's.
             "HOROVOD_REJOIN_POLL": "0"},
        extra=[(_as_joiner_worker,
                {"HOROVOD_WORKER_ID": "as-parolee:1"})])
    assert results == {0: "ok", 1: "ok", _AS_BASE: "evicted"}, results
