"""Observability round (docs/metrics.md): the structured event ring on
real multi-rank wire traffic, black-box post-mortems merged into one
causal timeline, and the live debug endpoint answering while a peer is
SIGSTOPped — the exact situation introspection exists for.

Workers live in this importable module (never ``python -c`` strings —
spawn must re-import them; the r11 gotcha).
"""

import json
import os
import signal
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from tests.utils_mp import REPO_ROOT, free_port

pytestmark = pytest.mark.quick


def _entry(fn, rank, size, port, q, env):
    os.environ.update({
        "HOROVOD_RANK": str(rank),
        "HOROVOD_SIZE": str(size),
        "HOROVOD_LOCAL_RANK": str(rank),
        "HOROVOD_LOCAL_SIZE": str(size),
        "HOROVOD_CONTROLLER_ADDR": "127.0.0.1",
        "HOROVOD_CONTROLLER_PORT": str(port),
        "JAX_PLATFORMS": "cpu",
    })
    os.environ.update(env or {})
    sys.path.insert(0, REPO_ROOT)
    try:
        q.put((rank, None, fn(rank, size)))
    except Exception as e:  # noqa: BLE001
        import traceback

        traceback.print_exc()
        q.put((rank, f"{type(e).__name__}: {e}", None))


def run_ranks(fn, size, victims=(), timeout=120, env=None):
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    port = free_port()
    q = ctx.Queue()
    victims = set(victims)
    procs = {
        r: ctx.Process(target=_entry, args=(fn, r, size, port, q, env))
        for r in range(size)
    }
    for p in procs.values():
        p.start()
    results, errors = {}, {}
    want = size - len(victims)
    deadline = time.monotonic() + timeout
    try:
        while len(results) + len(errors) < want:
            remaining = deadline - time.monotonic()
            assert remaining > 0, (
                f"workers hung: got {sorted(results)} of {want}")
            try:
                rank, err, res = q.get(timeout=min(remaining, 5.0))
            except Exception:  # noqa: BLE001 — queue.Empty
                continue
            if err is not None:
                errors[rank] = err
            else:
                results[rank] = res
    finally:
        for r, p in procs.items():
            if p.is_alive():
                os.kill(p.pid, signal.SIGCONT)
                if r in victims:
                    p.kill()
            p.join(timeout=15)
            if p.is_alive():
                p.terminate()
                p.join(timeout=5)
    assert not errors, f"worker failures: {errors}"
    return results


# ---- the ring records real wire traffic, typed and plane-tagged ------


def _wire_events_worker(rank, size):
    from horovod_tpu.common import basics, eager_ops as ops

    b = basics.HorovodBasics()
    b.init()
    x = np.full(65536, float(rank + 1), np.float32)
    for i in range(3):
        ops.allreduce_async(x.copy(), f"ev.{i}").synchronize()
    evs = b.events()
    by_type = {}
    for e in evs:
        by_type.setdefault(e["type"], []).append(e)
    # Negotiation rounds, per-op-class launches, and per-transfer wire
    # spans all landed in the ring, in seq order.
    assert "negotiate_begin" in by_type and "negotiate_end" in by_type
    launches = by_type["response_launch"]
    assert len(launches) >= 3
    assert all(e["op_class"] == 0 for e in launches), launches
    assert all(e["bytes"] == 65536 * 4 for e in launches), launches
    spans = by_type.get("wire_span", [])
    assert spans, sorted(by_type)
    assert all(s["plane"] == 0 for s in spans), spans
    assert all(s["tx_bytes"] > 0 and s["rx_bytes"] > 0 for s in spans)
    chunks = by_type.get("wire_chunk", [])
    assert chunks and all(c["len"] > 0 for c in chunks), len(chunks)
    seqs = [e["seq"] for e in evs]
    assert seqs == sorted(seqs)
    # Drain consumes: the first call takes everything recorded so far;
    # an immediate second call may race a straggling background cycle
    # (negotiation bookkeeping under load) but never re-delivers or
    # newly produces traffic events — all wire activity was recorded
    # before the last synchronize() returned.
    assert len(b.events_drain()) >= len(seqs)
    residue = b.events_drain()
    assert all(e["type"] not in ("response_launch", "wire_span",
                                 "wire_chunk") for e in residue), residue
    b.shutdown()
    return "ok"


def test_event_ring_records_wire_traffic():
    results = run_ranks(_wire_events_worker, 2,
                        env={"HOROVOD_RING_CHUNK_BYTES": "32768"})
    assert results == {0: "ok", 1: "ok"}


# ---- stall post-mortem: first-stalled attribution, no false death ----

_STALL_MS = 1600


def _stall_postmortem_worker(rank, size):
    from horovod_tpu.common import basics, eager_ops as ops
    from horovod_tpu.common.exceptions import HorovodInternalError

    b = basics.HorovodBasics()
    b.init()
    if rank == 1:
        b.set_fault_inject_spec(f"1:2:stop:{_STALL_MS}")
    x = np.ones(4096, np.float32)
    try:
        for i in range(4):
            ops.allreduce_async(x, f"pm.{i}").synchronize()
        return "did-not-fail"
    except HorovodInternalError:
        pass
    # Keep sockets open until every survivor has classified its fault
    # (the r12 ordering rule), then report.
    time.sleep(1.0)
    b.shutdown()
    return "ok"


def test_stall_postmortem_names_first_stalled_rank(tmp_path, capsys):
    bb_dir = str(tmp_path / "bb")
    results = run_ranks(
        _stall_postmortem_worker, 2, timeout=120,
        env={"HOROVOD_WIRE_TIMEOUT_MS": "500",
             "HOROVOD_WIRE_RETRY_ATTEMPTS": "0",
             "HOROVOD_BLACKBOX_DIR": bb_dir})
    assert set(results.values()) == {"ok"}, results
    from horovod_tpu.telemetry import postmortem

    files = sorted(os.listdir(bb_dir))
    assert files == ["blackbox-rank0.jsonl", "blackbox-rank1.jsonl"], files
    analysis = postmortem.merge_post_mortem(bb_dir)
    # Both processes survived the stall: a timeout is SUSPICION, and a
    # rank that wrote its own dump is demonstrably alive — no false
    # root-cause death, both named ranks are secondary timeouts...
    assert analysis["root_cause_ranks"] == [], analysis
    assert analysis["secondary_suspects"], analysis
    # ...while the first-stalled analysis names the SIGSTOPped rank:
    # its last forward-progress event before the stall surfaced is the
    # earliest on the merged wall axis.
    assert analysis["first_stalled_rank"] == 1, {
        k: analysis[k] for k in ("first_stalled_rank", "per_rank")}
    # The CLI renders the same verdict (report.py --post-mortem).
    from horovod_tpu.telemetry import report

    rc = report.main(["--post-mortem",
                      os.path.join(bb_dir, "blackbox-rank0.jsonl"),
                      os.path.join(bb_dir, "blackbox-rank1.jsonl")])
    assert rc == 0
    out = capsys.readouterr().out
    assert "first stalled: rank 1" in out, out


# ---- /healthz and /stacks answer while the peer is SIGSTOPped --------

_DBG_STALL_MS = 3000


def _debug_while_stalled_worker(rank, size):
    from horovod_tpu.common import basics, eager_ops as ops

    b = basics.HorovodBasics()
    b.init()
    if rank == 1:
        b.set_fault_inject_spec(f"1:2:stop:{_DBG_STALL_MS}")
    x = np.ones(1024, np.float32)
    for i in range(2):
        ops.allreduce_async(x, f"dbg.{i}").synchronize()
    if rank == 0:
        # Signal the driver: the NEXT collective stalls on the stopped
        # peer — poll my debug port now.
        with open(os.environ["OBS_READY_FILE"], "w") as f:
            f.write("ready")
    out = ops.allreduce_async(x, "dbg.stall").synchronize()
    assert np.allclose(out, 2.0), out[:4]
    el = b.metrics_snapshot()["elastic"]
    assert el["faults_detected"] == 0, el
    b.shutdown()
    return {"heals": el["heals"]}


def test_healthz_and_stacks_respond_while_peer_sigstopped(tmp_path):
    ready = str(tmp_path / "ready")
    dbg_port = free_port()
    polled = {}

    def poll():
        deadline = time.monotonic() + 60
        while not os.path.exists(ready):
            if time.monotonic() > deadline:
                return
            time.sleep(0.02)
        # Rank 0 is (or is about to be) blocked inside a collective on
        # a SIGSTOPped peer; its daemon debug thread must still answer.
        time.sleep(0.3)
        base = f"http://127.0.0.1:{dbg_port}"
        for path, key in (("/healthz", "healthz"), ("/stacks", "stacks"),
                          ("/events?n=64", "events"),
                          ("/requests?n=8", "requests")):
            try:
                body = urllib.request.urlopen(base + path,
                                              timeout=10).read()
                polled[key] = body
            except Exception as e:  # noqa: BLE001
                polled[key] = e

    poller = threading.Thread(target=poll)
    poller.start()
    results = run_ranks(
        _debug_while_stalled_worker, 2, timeout=180,
        env={"HOROVOD_WIRE_TIMEOUT_MS": "600",
             "HOROVOD_WIRE_RETRY_ATTEMPTS": "6",
             "HOROVOD_WIRE_RETRY_BACKOFF_MS": "400",
             "HOROVOD_DEBUG_PORT": str(dbg_port),
             "OBS_READY_FILE": ready})
    poller.join(timeout=30)
    # The stall healed in place on the retry ladder...
    assert results[0]["heals"] >= 1, results
    # ...and mid-stall the wedged rank answered every endpoint.
    assert isinstance(polled.get("healthz"), bytes), polled
    health = json.loads(polled["healthz"])
    assert health["rank"] == 0 and health["initialized"], health
    # The autoscaler's signal set rides /healthz (docs/scale.md): one
    # endpoint serves everything the scaling policy consumes — field
    # set PINNED here (r17 adds the overlap-ledger pair, r18 the
    # serving quartet, r19 the rolling-latency trio + eviction
    # amplification, r23 the fleet/SLO trio; autoscale Signals
    # defaults keep older payloads constructing).
    for key in ("queue_depth", "straggler_skew_ms", "step_time_ewma_ms",
                "pending_rejoiners", "debug_port", "overlap_efficiency",
                "exposed_wire_ms", "serving_queue_depth",
                "inflight_sequences", "kv_blocks_free",
                "kv_blocks_total", "serving_p50_ms", "serving_p99_ms",
                "requests_served", "recomputed_prefill_tokens",
                "useful_tokens", "eviction_amplification",
                "slo_breaches", "fleet_utilization",
                "rank_seconds_unattributed_share"):
        assert key in health, (key, sorted(health))
    # No serving loop in this process: the sentinel defaults, not a
    # phantom empty pool.
    assert health["serving_queue_depth"] == 0, health
    assert health["kv_blocks_total"] == -1, health
    assert health["requests_served"] == 0, health
    assert health["eviction_amplification"] == 0.0, health
    # No observatory live in this process either: the fleet zeros.
    assert health["slo_breaches"] == 0, health
    assert health["fleet_utilization"] == 0.0, health
    # /requests answers on a non-serving rank too: an empty in-flight
    # table, not an error (docs/serving.md).
    assert isinstance(polled.get("requests"), bytes), polled
    assert json.loads(polled["requests"]) == [], polled["requests"]
    assert health["debug_port"] == dbg_port, health
    assert isinstance(health["queue_depth"], int), health
    assert isinstance(health["pending_rejoiners"], int), health
    assert isinstance(health["overlap_efficiency"], float), health
    assert isinstance(health["exposed_wire_ms"], float), health
    assert 0.0 <= health["overlap_efficiency"] <= 1.0, health
    assert isinstance(polled.get("stacks"), bytes), polled
    assert b"File" in polled["stacks"] or b"Thread" in polled["stacks"]
    assert isinstance(polled.get("events"), bytes), polled
    assert json.loads(polled["events"]), "empty events tail"


# ---- HOROVOD_DEBUG_PORT=0: ephemeral bind for co-located ranks -------


def test_debug_port_zero_binds_ephemeral_and_advertises_port():
    """`HOROVOD_DEBUG_PORT=base` collides across co-located simulated
    ranks (every process computes base+rank from the same env); `=0`
    binds an ephemeral port per process, discoverable via
    hvd.debug_port(), /healthz, and the X-Hvdtpu-Debug-Port header."""
    from horovod_tpu.common.basics import HorovodBasics
    from horovod_tpu.telemetry import debug_server

    b = HorovodBasics()
    old = os.environ.get("HOROVOD_DEBUG_PORT")
    os.environ["HOROVOD_DEBUG_PORT"] = "0"
    try:
        port = debug_server.maybe_start(b)
        assert port and port > 0, port
        assert debug_server.debug_port() == port
        import horovod_tpu.jax as hvd

        assert hvd.debug_port() == port
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            assert r.headers.get("X-Hvdtpu-Debug-Port") == str(port)
            health = json.loads(r.read())
        assert health["debug_port"] == port, health
        # Idempotent: a second start keeps the same server.
        assert debug_server.maybe_start(b) == port
    finally:
        debug_server.stop()
        if old is None:
            os.environ.pop("HOROVOD_DEBUG_PORT", None)
        else:
            os.environ["HOROVOD_DEBUG_PORT"] = old
    assert debug_server.debug_port() is None
