"""End-to-end chunked/compressed ring over 4 real ranks (TCP loopback).

The native-level matrix (tests/single/test_ring_engine.py) pins the
engine against its ring-order reference; this file pins the FULL stack
— enqueue, negotiation, fusion-buffer path, knob env plumbing — at 4
OS ranks, plus the wire-vs-logical metrics counters the telemetry
layer reads:

- uncompressed results are BIT-identical to a numpy ring-order
  reference for ragged counts, at both tiny and default chunk sizes
  (i.e. chunking/overlap moved no bits);
- the compressed path stays inside the documented bf16 bound AND the
  new ``wire`` counters show ~2x fewer transport bytes than logical
  for fp32 payloads while the per-op logical bytes stay full-width;
- broadcast/allgather/reducescatter ride the same unified
  ``HOROVOD_RING_CHUNK_BYTES`` knob (tiny chunks, correct results).

Quick lane alongside tests/parallel/test_mpi_control.py.
"""

import os

import numpy as np
import pytest

from tests.utils_mp import run_ranks

pytestmark = pytest.mark.quick

# Ragged on purpose: not divisible by 4 ranks, not chunk-aligned.
_BIG = (1 << 18) + 531


def _ring_reference(inputs):
    """Bit-exact ring-order allreduce(SUM) reference.

    Segment j's partial starts as rank j's values; each later owner on
    the ring computes own + partial (f32 adds in ring order — the same
    association sequence csrc/ring_ops.cc executes, chunked or not).
    """
    n = len(inputs)
    count = inputs[0].size
    q, r = divmod(count, n)
    seg = [q + (1 if i < r else 0) for i in range(n)]
    out = np.empty_like(inputs[0])
    off = 0
    for j in range(n):
        sl = slice(off, off + seg[j])
        acc = inputs[j][sl].copy()
        for t in range(1, n):
            acc = inputs[(j + t) % n][sl] + acc
        out[sl] = acc
        off += seg[j]
    return out


def _rank_input(rank, count):
    # Deterministic, sign-varying, non-dyadic values.
    e = np.arange(count, dtype=np.float64)
    v = (((rank + 1) * 1315423911 + (e + 1) * 2654435761) % 2001) / 500 - 2
    return v.astype(np.float32)


def _init(rank):
    from horovod_tpu.common import basics

    b = basics.HorovodBasics()
    b.init()
    return b


def _worker_exact(rank, size):
    b = _init(rank)
    from horovod_tpu.common import eager_ops as ops

    try:
        assert b.ring_chunk_bytes() == int(
            os.environ["HOROVOD_RING_CHUNK_BYTES"])
        assert b.wire_compression() is False
        inputs = [_rank_input(r, _BIG) for r in range(size)]
        ref = _ring_reference(inputs)
        out = ops.allreduce_async(inputs[rank], "rw.sum").synchronize()
        # Bitwise, not allclose: chunking/overlap must move NO bits.
        assert np.array_equal(out.view(np.uint32), ref.view(np.uint32))

        # AVERAGE: the folded postscale must match ScaleBuffer's f32
        # semantics (double multiply, one f32 rounding) exactly.
        out = ops.allreduce_async(inputs[rank], "rw.avg",
                                  op=ops.ReduceOp.AVERAGE).synchronize()
        exp = (ref.astype(np.float64) * (1.0 / size)).astype(np.float32)
        assert np.array_equal(out.view(np.uint32), exp.view(np.uint32))

        # Ragged small counts: zero-length segments included.
        for count in (1, size - 1, size + 3, 1025):
            small = [_rank_input(r, count) for r in range(size)]
            out = ops.allreduce_async(small[rank],
                                      f"rw.small.{count}").synchronize()
            sref = _ring_reference(small)
            assert np.array_equal(out.view(np.uint32), sref.view(np.uint32))

        # Unified chunk knob: broadcast/allgather/reducescatter run at
        # this test's (tiny) granularity and must still be correct.
        bc = ops.broadcast_async(
            inputs[2] if rank == 2 else np.zeros(_BIG, np.float32), 2,
            "rw.bc").synchronize()
        assert np.array_equal(bc.view(np.uint32), inputs[2].view(np.uint32))
        ag = ops.allgather_async(np.full((3, 5), rank, np.int32),
                                 "rw.ag").synchronize()
        assert ag.shape == (3 * size, 5)
        np.testing.assert_array_equal(ag[::3, 0], np.arange(size))
        # ReduceScatterv's -1 segment rotation starts segment j's
        # partial at rank j+1 (vs j for allreduce) — replay that order.
        rs = ops.reducescatter_async(inputs[rank][: size * 7],
                                     "rw.rs").synchronize()
        sl = slice(rank * 7, (rank + 1) * 7)
        acc = inputs[(rank + 1) % size][sl].copy()
        for t in range(2, size + 1):
            acc = inputs[(rank + t) % size][sl] + acc
        assert np.array_equal(rs.view(np.uint32), acc.view(np.uint32))
        return "ok"
    finally:
        b.shutdown()


@pytest.mark.parametrize("chunk", ["4096", str(256 * 1024)])
def test_chunked_uncompressed_bit_identity(chunk):
    assert run_ranks(_worker_exact, 4, timeout=180,
                     env={"HOROVOD_RING_CHUNK_BYTES": chunk,
                          "HOROVOD_WIRE_COMPRESSION": "0"}) == ["ok"] * 4


def _worker_compressed(rank, size):
    b = _init(rank)
    from horovod_tpu.common import eager_ops as ops

    try:
        assert b.wire_compression() is True
        inputs = [_rank_input(r, _BIG) for r in range(size)]
        ref = _ring_reference(inputs)

        snap0 = b.metrics_snapshot()
        out = ops.allreduce_async(inputs[rank], "rwc.sum").synchronize()
        snap1 = b.metrics_snapshot()

        # docs/wire.md bound: N+1 bf16 roundings of partials <= 2N.
        np.testing.assert_allclose(out, ref, atol=size * size * 2 ** -7)

        # ~2x wire-byte reduction: transport bytes vs full-width bytes
        # for the same traffic. The tiny negotiation-cycle barrier/
        # bookkeeping traffic is noise against a ~1 MB payload.
        tx = snap1["wire"]["tx_bytes"] - snap0["wire"]["tx_bytes"]
        txl = (snap1["wire"]["tx_logical_bytes"]
               - snap0["wire"]["tx_logical_bytes"])
        assert txl > 0
        assert 0.45 < tx / txl < 0.55, (tx, txl)
        # The ring moves 2(N-1)/N x payload per rank at full width.
        expect_logical = 2 * (size - 1) / size * inputs[rank].nbytes
        assert abs(txl - expect_logical) / expect_logical < 0.05
        # Logical per-op accounting stays full-width (the op moved the
        # same PAYLOAD; only the wire narrowed).
        ar = snap1["ops"]["allreduce"]["bytes"] - \
            snap0["ops"].get("allreduce", {}).get("bytes", 0)
        assert ar == inputs[rank].nbytes
        # Compression is rank-consistent: everyone must hold identical
        # bits, pinned here by identical means/extrema per rank.
        return (float(out.sum()), float(out.min()), float(out.max()))
    finally:
        b.shutdown()


def test_compressed_wire_halves_bytes():
    results = run_ranks(_worker_compressed, 4, timeout=180,
                        env={"HOROVOD_RING_CHUNK_BYTES": "16384",
                             "HOROVOD_WIRE_COMPRESSION": "1"})
    assert all(r == results[0] for r in results)


def _worker_compressed_reducescatter(rank, size):
    b = _init(rank)
    from horovod_tpu.common import eager_ops as ops

    try:
        assert b.wire_compression() is True
        count = size * 5000 + size  # shard-even on purpose
        inputs = [_rank_input(r, count) for r in range(size)]
        shard = count // size
        sl = slice(rank * shard, (rank + 1) * shard)

        snap0 = b.metrics_snapshot()
        out = ops.reducescatter_async(inputs[rank], "zrs.sum").synchronize()
        snap1 = b.metrics_snapshot()
        assert out.shape == (shard,)

        # EXACT bf16-hop replay of the compressed engine, in ring order:
        # seg j's chain starts at rank j+1 (the rot=-1 rotation —
        # ring_owned_segment(r, N, -1) == r), each hop ships the current
        # f32 partial as bf16 and the receiver accumulates in f32.
        import ml_dtypes

        bf16 = lambda x: x.astype(ml_dtypes.bfloat16).astype(  # noqa: E731
            np.float32)
        acc = inputs[(rank + 1) % size][sl].copy()
        for t in range(2, size + 1):
            acc = inputs[(rank + t) % size][sl] + bf16(acc)
        assert np.array_equal(out.view(np.uint32), acc.view(np.uint32))

        # Wire ratio ~0.5: the reduce phase ships bf16; logical volume
        # is the (N-1)/N ring factor at full f32 width.
        tx = snap1["wire"]["tx_bytes"] - snap0["wire"]["tx_bytes"]
        txl = (snap1["wire"]["tx_logical_bytes"]
               - snap0["wire"]["tx_logical_bytes"])
        assert txl > 0
        assert 0.45 < tx / txl < 0.55, (tx, txl)
        expect_logical = (size - 1) / size * inputs[rank].nbytes
        assert abs(txl - expect_logical) / expect_logical < 0.05
        # Per-op logical accounting stays full-width.
        rs = snap1["ops"]["reducescatter"]["bytes"] - \
            snap0["ops"].get("reducescatter", {}).get("bytes", 0)
        assert rs == inputs[rank].nbytes

        # AVERAGE folds exactly like the uncompressed path: postscale
        # applied once, ScaleBuffer's f32 semantics.
        avg = ops.reducescatter_async(inputs[rank], "zrs.avg",
                                      op=ops.ReduceOp.AVERAGE).synchronize()
        exp = (acc.astype(np.float64) * (1.0 / size)).astype(np.float32)
        assert np.array_equal(avg.view(np.uint32), exp.view(np.uint32))

        # Bit-consistency mirror of the allreduce case: a repeat run
        # must reproduce the identical bits (the compressed engine is
        # deterministic, chunked or not).
        rep = ops.reducescatter_async(inputs[rank], "zrs.rep").synchronize()
        assert np.array_equal(rep.view(np.uint32), out.view(np.uint32))
        return "ok"
    finally:
        b.shutdown()


# loadflaky: this case (and its uncompressed-ratio sibling below) has
# failed ONLY under full-suite load — r17 during a concurrent .so
# relink, r18 with no rebuild in flight; 3x standalone + the
# `make test-flaky` lane were green both times. Four spawned ranks
# racing the wire-timeout budget on a busy box is load sensitivity,
# not a wire regression — rerun `make test-flaky` standalone before
# blaming a diff (the r13 de-flake discipline; busy CI shards may
# deselect with -m 'not loadflaky').
@pytest.mark.loadflaky
def test_compressed_reducescatter_wire_and_bits():
    assert run_ranks(_worker_compressed_reducescatter, 4, timeout=180,
                     env={"HOROVOD_RING_CHUNK_BYTES": "8192",
                          "HOROVOD_WIRE_COMPRESSION": "1"}) == ["ok"] * 4


def _worker_uncompressed_ratio(rank, size):
    b = _init(rank)
    from horovod_tpu.common import eager_ops as ops

    try:
        snap0 = b.metrics_snapshot()
        ops.allreduce_async(_rank_input(rank, _BIG),
                            "rwu.sum").synchronize()
        snap1 = b.metrics_snapshot()
        tx = snap1["wire"]["tx_bytes"] - snap0["wire"]["tx_bytes"]
        txl = (snap1["wire"]["tx_logical_bytes"]
               - snap0["wire"]["tx_logical_bytes"])
        assert tx == txl  # no compression -> wire == logical, exactly
        return "ok"
    finally:
        b.shutdown()


@pytest.mark.loadflaky  # see the note on the reducescatter case above
def test_uncompressed_wire_equals_logical():
    assert run_ranks(_worker_uncompressed_ratio, 2, timeout=120,
                     env={"HOROVOD_WIRE_COMPRESSION": "0"}) == ["ok"] * 2
