"""The expanded chaos matrix (docs/elastic.md): self-healing under the
HOROVOD_FAULT_INJECT grammar — kill | stop:<ms> | reset | flip:<bit> |
delay:<ms>.

Pins the three acceptance behaviors of the self-healing elastic round:

1. A transient stall (SIGSTOP < retry budget, then SIGCONT) heals IN
   PLACE: the collective completes at the same epoch, ``faults_detected``
   stays 0, and the ``heals`` counter moves.
2. SIGKILL followed by a host rejoin regrows the world N-1 -> N at a
   bumped epoch through the blacklist-parole door, and the training
   trajectory matches an uninterrupted N-rank run from the last commit.
3. An injected bit-flip on a CRC-framed chunk (including the bf16
   cross-plane hop) is detected, NAK-healed by resend, and NEVER
   silently reduced into the result; a persistently corrupting link
   escalates to a typed ``HorovodWireCorruptionError`` naming
   rank + chunk.

Plus the satellite lanes: a kill mid-``redistribute`` (alltoallv plan
step) surfaces typed errors on every survivor within the wire deadline,
and ``hvd.elastic.survivors()`` is rank-consistent.

Workers live in this importable module (never ``python -c`` strings —
spawn must re-import them; the r11 gotcha).
"""

import multiprocessing as mp
import os
import signal
import sys
import time

import numpy as np
import pytest

from tests.utils_mp import REPO_ROOT, free_port

pytestmark = pytest.mark.quick

_COUNT = 2048 + 19  # ragged on purpose
_TIMEOUT_MS = 600   # tight wire deadline so chaos tests stay fast


def _rank_input(rank, count):
    e = np.arange(count, dtype=np.float64)
    v = (((rank + 1) * 1315423911 + (e + 1) * 2654435761) % 2001) / 500 - 2
    return v.astype(np.float32)


def _ring_reference(inputs):
    """Bit-exact ring-order allreduce(SUM) replay (see
    tests/parallel/test_ring_wire.py)."""
    n = len(inputs)
    count = inputs[0].size
    q, r = divmod(count, n)
    seg = [q + (1 if i < r else 0) for i in range(n)]
    out = np.empty_like(inputs[0])
    off = 0
    for j in range(n):
        sl = slice(off, off + seg[j])
        acc = inputs[j][sl].copy()
        for t in range(1, n):
            acc = inputs[(j + t) % n][sl] + acc
        out[sl] = acc
        off += seg[j]
    return out


def _entry(fn, rank, size, port, q, env):
    os.environ.update({
        "HOROVOD_RANK": str(rank),
        "HOROVOD_SIZE": str(size),
        "HOROVOD_LOCAL_RANK": str(rank),
        "HOROVOD_LOCAL_SIZE": str(size),
        "HOROVOD_CONTROLLER_ADDR": "127.0.0.1",
        "HOROVOD_CONTROLLER_PORT": str(port),
        "JAX_PLATFORMS": "cpu",
    })
    os.environ.update(env or {})
    sys.path.insert(0, REPO_ROOT)
    try:
        q.put((rank, None, fn(rank, size)))
    except Exception as e:  # noqa: BLE001
        import traceback

        traceback.print_exc()
        q.put((rank, f"{type(e).__name__}: {e}", None))


def run_chaos(fn, size, victims=(), timeout=120, env=None,
              expect_sigkill=True, extra=()):
    """Spawn `size` ring workers plus optional `extra` (fn, env) side
    processes (e.g. a parole joiner, reported as rank size+i), collect
    results from everyone not in `victims`, then reap victims
    (SIGCONT+SIGKILL covers SIGSTOPped ones)."""
    ctx = mp.get_context("spawn")
    port = free_port()
    q = ctx.Queue()
    victims = set(victims)
    procs = {
        r: ctx.Process(target=_entry, args=(fn, r, size, port, q, env))
        for r in range(size)
    }
    for i, (xfn, xenv) in enumerate(extra):
        merged = dict(env or {})
        merged.update(xenv or {})
        procs[size + i] = ctx.Process(
            target=_entry, args=(xfn, size + i, size, port, q, merged))
    for p in procs.values():
        p.start()
    results, errors = {}, {}
    want = len(procs) - len(victims)
    deadline = time.monotonic() + timeout
    try:
        while len(results) + len(errors) < want:
            remaining = deadline - time.monotonic()
            assert remaining > 0, (
                f"workers hung: got {sorted(results)} of {want}")
            try:
                rank, err, res = q.get(timeout=min(remaining, 5.0))
            except Exception:  # noqa: BLE001 — queue.Empty
                continue
            if err is not None:
                errors[rank] = err
            else:
                results[rank] = res
    finally:
        for r, p in procs.items():
            if r in victims and p.is_alive():
                os.kill(p.pid, signal.SIGCONT)
                p.kill()
            p.join(timeout=15)
            if p.is_alive():
                p.terminate()
                p.join(timeout=5)
    assert not errors, f"worker failures: {errors}"
    if expect_sigkill:
        for v in victims:
            assert procs[v].exitcode == -signal.SIGKILL, (
                v, procs[v].exitcode)
    return results


# ---- (1) transient stall heals in place: same epoch, zero faults -----

_STOP_MS = 1800
_STOP_AT_OP = 2
_HEAL_OPS = 4


def _stall_heal_worker(rank, size):
    from horovod_tpu.common import basics, eager_ops as ops

    b = basics.HorovodBasics()
    b.init()
    assert b.wire_retry_attempts() == 6
    if rank == 1:
        # SIGSTOP mid-collective, SIGCONT by the forked waker: the GC-
        # pause / spot-throttle shape. Shorter than the healing budget.
        b.set_fault_inject_spec(f"1:{_STOP_AT_OP}:stop:{_STOP_MS}")
    inputs = [_rank_input(r, _COUNT) for r in range(size)]
    ref = _ring_reference(inputs)
    for i in range(_HEAL_OPS):
        out = ops.allreduce_async(inputs[rank], f"op.{i}").synchronize()
        assert np.array_equal(out.view(np.uint32), ref.view(np.uint32)), i
    el = b.metrics_snapshot()["elastic"]
    # Healed in place: no fault, no epoch bump, no shrink.
    assert b.epoch() == 0
    assert el["faults_detected"] == 0, el
    assert b.lib.hvdtpu_loop_failed() == 0
    b.shutdown()
    return {"heals": el["heals"], "retries": el["retries"]}


def test_sigstop_within_retry_budget_heals_in_place():
    results = run_chaos(
        _stall_heal_worker, 2, victims=set(), expect_sigkill=False,
        env={"HOROVOD_WIRE_TIMEOUT_MS": str(_TIMEOUT_MS),
             "HOROVOD_WIRE_RETRY_ATTEMPTS": "6",
             "HOROVOD_WIRE_RETRY_BACKOFF_MS": "300"})
    assert set(results) == {0, 1}
    # The non-stopped rank rode out the stall on the healing ladder.
    assert results[0]["heals"] >= 1, results
    assert results[0]["retries"] >= 1, results


# ---- (1b) the same stall WITHOUT the ladder still faults (r12) -------


def _stall_fault_worker(rank, size):
    from horovod_tpu.common import basics, eager_ops as ops
    from horovod_tpu.common.exceptions import HorovodPeerFailureError

    b = basics.HorovodBasics()
    b.init()
    assert b.wire_retry_attempts() == 0
    if rank == 1:
        b.set_fault_inject_spec(f"1:1:stop:{_STOP_MS}")
    x = np.ones(256, np.float32)
    ops.allreduce_async(x, "w0").synchronize()
    try:
        ops.allreduce_async(x, "boom").synchronize()
        return "did-not-fail"
    except HorovodPeerFailureError as e:
        assert 1 in e.fault_ranks, e.fault_ranks
    b.shutdown()
    return "ok"


def test_sigstop_without_retry_budget_still_faults():
    results = run_chaos(
        _stall_fault_worker, 2, victims={1}, expect_sigkill=False,
        env={"HOROVOD_WIRE_TIMEOUT_MS": str(_TIMEOUT_MS),
             "HOROVOD_WIRE_RETRY_ATTEMPTS": "0"})
    assert results == {0: "ok"}


# ---- (3) bit-flip: CRC detects, NAK-resend heals, result exact -------

_FLIP_AT_OP = 1


def _flip_heal_worker(rank, size):
    from horovod_tpu.common import basics, eager_ops as ops

    b = basics.HorovodBasics()
    b.init()
    assert b.wire_crc()
    if rank == 1:
        b.set_fault_inject_spec(f"1:{_FLIP_AT_OP}:flip:77")
    inputs = [_rank_input(r, _COUNT) for r in range(size)]
    ref = _ring_reference(inputs)
    for i in range(3):
        out = ops.allreduce_async(inputs[rank], f"op.{i}").synchronize()
        # The flipped chunk was caught and resent: NEVER silently
        # reduced into the result (bit-exact against the ring replay).
        assert np.array_equal(out.view(np.uint32), ref.view(np.uint32)), i
    el = b.metrics_snapshot()["elastic"]
    assert b.epoch() == 0
    assert el["faults_detected"] == 0, el
    b.shutdown()
    return {"crc_errors": el["crc_errors"], "heals": el["heals"]}


def test_bitflip_detected_and_healed_by_resend():
    results = run_chaos(
        _flip_heal_worker, 2, victims=set(), expect_sigkill=False,
        env={"HOROVOD_WIRE_TIMEOUT_MS": "5000",
             "HOROVOD_WIRE_CRC": "1",
             "HOROVOD_WIRE_RETRY_ATTEMPTS": "2"})
    total_errors = sum(r["crc_errors"] for r in results.values())
    total_heals = sum(r["heals"] for r in results.values())
    assert total_errors >= 1, results
    assert total_heals >= 1, results


_HIER_SIZE = 4
_HIER_LOCAL = 2


def _flip_hier_worker(rank, size):
    os.environ.update({
        "HOROVOD_LOCAL_RANK": str(rank % _HIER_LOCAL),
        "HOROVOD_LOCAL_SIZE": str(_HIER_LOCAL),
        "HOROVOD_CROSS_RANK": str(rank // _HIER_LOCAL),
        "HOROVOD_CROSS_SIZE": str(size // _HIER_LOCAL),
    })
    from horovod_tpu.common import basics, eager_ops as ops

    b = basics.HorovodBasics()
    b.init()
    assert b.hier_split() == _HIER_LOCAL and b.cross_compression()
    if rank == 1:
        # flip:<bit>:<skip>: let the intra-slice reduce-scatter frame
        # pass, corrupt the NEXT data frame rank 1 sends — the
        # bf16-compressed INTER-SLICE chunk of the hierarchical
        # decomposition (the acceptance target: CRC covers the
        # cross-plane bf16 hop like any other).
        b.set_fault_inject_spec("1:1:flip:5:1")
    vals = (np.arange(_COUNT, dtype=np.float32) % 7) - 3  # exact ints
    ops.allreduce_async(vals * (rank + 1), "warm").synchronize()
    out = ops.allreduce_async(vals * (rank + 1), "boom").synchronize()
    np.testing.assert_array_equal(out, vals * sum(range(1, size + 1)))
    el = b.metrics_snapshot()["elastic"]
    assert el["faults_detected"] == 0, el
    b.shutdown()
    return {"crc_errors": el["crc_errors"], "heals": el["heals"]}


def test_bitflip_on_bf16_cross_plane_chunk_healed():
    results = run_chaos(
        _flip_hier_worker, _HIER_SIZE, victims=set(), expect_sigkill=False,
        env={"HOROVOD_WIRE_TIMEOUT_MS": "5000",
             "HOROVOD_WIRE_CRC": "1",
             "HOROVOD_WIRE_RETRY_ATTEMPTS": "2",
             "HOROVOD_CROSS_PLANE": "hier",
             "HOROVOD_CROSS_PLANE_COMPRESSION": "1"})
    assert sum(r["crc_errors"] for r in results.values()) >= 1, results
    assert sum(r["heals"] for r in results.values()) >= 1, results


def _flip_escalation_worker(rank, size):
    from horovod_tpu.common import basics, eager_ops as ops
    from horovod_tpu.common import elastic as hvd_elastic
    from horovod_tpu.common.exceptions import (
        HorovodInternalError,
        HorovodWireCorruptionError,
    )

    b = basics.HorovodBasics()
    b.init()
    if rank == 1:
        # Persistent flip: every resend is corrupted too, so the
        # receiver must exhaust the NAK budget and escalate.
        b.set_fault_inject_spec("1:1:flip:-9")
    x = _rank_input(rank, _COUNT)
    ops.allreduce_async(x, "warm").synchronize()
    try:
        ops.allreduce_async(x, "boom").synchronize()
        return "did-not-fail"
    except HorovodWireCorruptionError as e:
        # Typed, naming rank + chunk; only reachable on the receiver.
        assert rank == 0, "only the downstream neighbor verifies"
        assert 1 in e.fault_ranks, e.fault_ranks
        assert e.chunk is not None and e.chunk >= 0, e.chunk
        assert "CRC32C" in str(e), str(e)
        fault = b.last_fault()
        assert fault["kind"] == "corruption", fault
        assert fault["certain"] is False, fault
        # A corrupting link names a LIVE peer: driver-less shrink must
        # refuse to evict it.
        assert hvd_elastic.survivors() is None
    except HorovodInternalError:
        # The corrupting sender's own transfer dies on the receiver's
        # abort (timeout or EOF) — typed, but not as corruption.
        assert rank == 1
    el = b.metrics_snapshot()["elastic"]
    assert el["crc_errors"] >= 1 or rank == 1, el
    b.shutdown()
    return "ok"


def test_persistent_corruption_escalates_typed_wire_corruption():
    results = run_chaos(
        _flip_escalation_worker, 2, victims=set(), expect_sigkill=False,
        env={"HOROVOD_WIRE_TIMEOUT_MS": str(_TIMEOUT_MS),
             "HOROVOD_WIRE_CRC": "1",
             "HOROVOD_WIRE_RETRY_ATTEMPTS": "1"})
    assert results == {0: "ok", 1: "ok"}


# ---- striped transport chaos: fault ONE channel, the rest stay up ----

_STRIPE_K = 4
_STRIPE_LANE = 1  # the targeted stripe lane (chunk idx % width == 1)


def _flip_one_channel_worker(rank, size):
    from horovod_tpu.common import basics, eager_ops as ops

    b = basics.HorovodBasics()
    b.init()
    assert b.wire_crc()
    assert b.wire_channels_established() == _STRIPE_K
    if rank == 1:
        # flip:<bit>:<skip>:<chan> — corrupt the FIRST data frame rank
        # 1 sends on stripe lane 1 only. The channel filter is what
        # makes the skip count deterministic under striping (lanes
        # stream concurrently; a lane-blind counter would race).
        b.set_fault_inject_spec(f"1:{_FLIP_AT_OP}:flip:77:0:{_STRIPE_LANE}")
    inputs = [_rank_input(r, _COUNT) for r in range(size)]
    ref = _ring_reference(inputs)
    for i in range(3):
        out = ops.allreduce_async(inputs[rank], f"op.{i}").synchronize()
        # The corrupted lane healed via NAK/resend while the other
        # lanes streamed on: result still bit-exact, nothing wedged.
        assert np.array_equal(out.view(np.uint32), ref.view(np.uint32)), i
    el = b.metrics_snapshot()["elastic"]
    assert el["faults_detected"] == 0, el
    assert b.epoch() == 0
    bad_chunks = [e["chunk"] for e in b.events(512)
                  if e["type"] == "crc_error"]
    b.shutdown()
    return {"crc_errors": el["crc_errors"], "heals": el["heals"],
            "bad_chunks": bad_chunks}


def test_flip_on_one_stripe_channel_heals_without_wedging_others():
    """A mid-transfer CRC fault on ONE stripe channel NAK-heals while
    the other K-1 channels keep streaming — the striped satellite of
    the r14 acceptance. The corrupt chunk's index must map to the
    targeted lane (chunk idx % width == lane), pinning that the chaos
    grammar's channel selector actually lands where it says."""
    results = run_chaos(
        _flip_one_channel_worker, 2, victims=set(), expect_sigkill=False,
        env={"HOROVOD_WIRE_TIMEOUT_MS": "5000",
             "HOROVOD_WIRE_CRC": "1",
             "HOROVOD_WIRE_RETRY_ATTEMPTS": "2",
             "HOROVOD_WIRE_CHANNELS": str(_STRIPE_K),
             "HOROVOD_RING_CHUNK_BYTES": "1024"})
    assert sum(r["crc_errors"] for r in results.values()) >= 1, results
    assert sum(r["heals"] for r in results.values()) >= 1, results
    # At size 2 with K=4 the paired plan runs width K/2 = 2: the
    # receiver (rank 0) verified the corrupt chunk on the targeted
    # lane — its GLOBAL chunk index is congruent to the lane mod width.
    bad = [c for r in results.values() for c in r["bad_chunks"]]
    assert bad, results
    assert all(c % (_STRIPE_K // 2) == _STRIPE_LANE for c in bad), bad


def _reset_one_channel_worker(rank, size):
    from horovod_tpu.common import basics, eager_ops as ops
    from horovod_tpu.common.exceptions import HorovodInternalError

    b = basics.HorovodBasics()
    b.init()
    assert b.wire_channels_established() == _STRIPE_K
    x = _rank_input(rank, _COUNT)
    ops.allreduce_async(x, "warm").synchronize()
    if rank == 1:
        # reset:<chan>: abort only stripe channel 1's sockets — the
        # dead-NIC-queue shape. The peer sees EOF on that channel's fd
        # mid-transfer and must surface the typed r12 fault promptly
        # (certain attribution), not hang on the surviving channels.
        b.set_fault_inject_spec("1:2:reset:1")
    try:
        for i in range(3):
            ops.allreduce_async(x, f"op.{i}").synchronize()
        status = "no-error"
    except HorovodInternalError as e:
        status = "typed"
        if rank == 0:
            fault = b.last_fault()
            assert fault is not None and 1 in fault["ranks"], fault
    b.shutdown()
    return status


def test_reset_of_one_stripe_channel_escalates_typed_fault():
    """Killing ONE stripe channel's sockets mid-run escalates through
    the typed r12 fault path within the wire deadline — the other K-1
    live channels must not mask a dead stripe into a silent hang."""
    results = run_chaos(
        _reset_one_channel_worker, 2, victims=set(), expect_sigkill=False,
        env={"HOROVOD_WIRE_TIMEOUT_MS": str(_TIMEOUT_MS),
             "HOROVOD_WIRE_CHANNELS": str(_STRIPE_K),
             "HOROVOD_RING_CHUNK_BYTES": "1024"},
        timeout=60)
    # The EOF lands on whoever is mid-transfer against the reset
    # channel; at minimum ONE rank must have surfaced the typed error.
    assert "typed" in results.values(), results


# ---- (2) SIGKILL + parole rejoin: N-1 -> N regrow, pinned trajectory -

_TRAIN_STEPS = 8
_TRAIN_FAIL_STEP = 5
_TRAIN_DIM = 193
_TRAIN_LR = 0.1
# state.sync() costs 2 broadcasts (ops 0-1); step s's allreduce is op
# 2 + s, so the victim dies at the top of step _TRAIN_FAIL_STEP.
_TRAIN_KILL_OP = 2 + _TRAIN_FAIL_STEP
_REJOIN_SIZE = 3
_REJOIN_VICTIM = 2


def _grad(step, rank):
    return np.full(_TRAIN_DIM, 0.01 * (step + 1) * (rank + 1), np.float32)


def _train_reference(worlds_by_step):
    """Expected trajectory given the (1-based rank multipliers of the)
    world each step ran in."""
    p = np.zeros(_TRAIN_DIM, np.float64)
    for s in range(_TRAIN_STEPS):
        world = worlds_by_step(s)
        mean = 0.01 * (s + 1) * sum(world) / len(world)
        p = p - _TRAIN_LR * mean
    return p


def _train_reference_uninterrupted(size):
    """An uninterrupted `size`-rank run: the acceptance pin — the healed
    world (kill -> shrink+regrow through the parole door in ONE epoch
    transition) must land on exactly this trajectory."""
    return _train_reference(lambda s: tuple(range(1, size + 1)))


def _rejoin_train(state, b, ops, epochs_seen):
    from horovod_tpu.common import elastic as hvd_elastic

    @hvd_elastic.run_fn
    def train(state):
        epochs_seen.append(b.epoch())
        while state.step < _TRAIN_STEPS:
            g = _grad(state.step, b.rank())
            mean = ops.allreduce_async(
                g, f"grad.{state.step}.{b.epoch()}",
                op=ops.ReduceOp.AVERAGE).synchronize()
            state.params = state.params - _TRAIN_LR * mean
            state.step += 1
            state.commit()
        return state.params

    return train(state)


def _rejoin_survivor_worker(rank, size):
    from horovod_tpu.common import basics, eager_ops as ops
    from horovod_tpu.common import elastic as hvd_elastic
    from horovod_tpu.common.elastic import ObjectState

    b = basics.HorovodBasics()
    hvd_elastic.init()
    if rank == 0:
        # Gate training on the joiner being paroled at the door, so the
        # kill's epoch transition deterministically absorbs it (rank 0
        # gates everyone: collectives can't proceed without it).
        deadline = time.monotonic() + 60
        door = hvd_elastic._ensure_door()
        while door.pending_count() == 0:
            assert time.monotonic() < deadline, "joiner never knocked"
            time.sleep(0.05)
    state = ObjectState(step=0,
                        params=np.zeros(_TRAIN_DIM, np.float32))
    epochs_seen = []
    params = _rejoin_train(state, b, ops, epochs_seen)
    # One transition: epoch 0 (3 ranks) -> epoch 1 (2 survivors + 1
    # paroled joiner = 3 ranks again).
    assert epochs_seen == [0, 1], epochs_seen
    assert (b.epoch(), b.size()) == (1, _REJOIN_SIZE)
    np.testing.assert_allclose(
        params, _train_reference_uninterrupted(_REJOIN_SIZE),
        rtol=1e-5, atol=1e-7)
    el = b.metrics_snapshot()["elastic"]
    assert el["ranks_blacklisted"] == 1, el
    assert el["ranks_rejoined"] == 1, el
    assert el["faults_recovered"] == 1, el
    b.shutdown()
    return "ok"


def _join_and_train(expected_size, reference):
    from horovod_tpu.common import basics, eager_ops as ops
    from horovod_tpu.common import elastic as hvd_elastic
    from horovod_tpu.common.elastic import ObjectState

    # A FRESH process: no old rank, no state. Knock on the parole door
    # (retrying while the survivors' rank 0 finishes its own init) and
    # block until an epoch transition absorbs us.
    b = basics.HorovodBasics()
    deadline = time.monotonic() + 60
    while True:
        try:
            asg = hvd_elastic.rejoin(timeout=120)
            break
        except (OSError, ConnectionError):
            assert time.monotonic() < deadline, "door never opened"
            time.sleep(0.2)
    assert asg["rank"] == expected_size - 1 and asg["size"] == expected_size
    assert b.epoch() == asg["epoch"] == 1
    state = ObjectState(step=0,
                        params=np.zeros(_TRAIN_DIM, np.float32))
    epochs_seen = []
    params = _rejoin_train(state, b, ops, epochs_seen)
    # First sync() pulled the survivors' last commit; the joiner's own
    # trajectory from there matches the same pin as theirs.
    assert epochs_seen == [1], epochs_seen
    np.testing.assert_allclose(params, reference, rtol=1e-5, atol=1e-7)
    b.shutdown()
    return "ok"


def _rejoin_joiner_worker(rank, size):
    return _join_and_train(
        _REJOIN_SIZE, _train_reference_uninterrupted(_REJOIN_SIZE))


def test_sigkill_then_parole_rejoin_regrows_and_pins_trajectory():
    rejoin_port = free_port()
    results = run_chaos(
        _rejoin_survivor_worker, _REJOIN_SIZE, victims={_REJOIN_VICTIM},
        timeout=180,
        env={"HOROVOD_WIRE_TIMEOUT_MS": "2000",
             "HOROVOD_REJOIN_PORT": str(rejoin_port),
             # Joiners are absorbed at the FAULT transition only, so the
             # kill's op index (and the trajectory) stay deterministic.
             "HOROVOD_REJOIN_POLL": "0",
             "HOROVOD_FAULT_INJECT":
                 f"{_REJOIN_VICTIM}:{_TRAIN_KILL_OP}:kill"},
        extra=[(_rejoin_joiner_worker,
                {"HOROVOD_FAULT_INJECT": "",
                 "HOROVOD_WORKER_ID": "parolee:1"})])
    assert results == {0: "ok", 1: "ok", _REJOIN_SIZE: "ok"}


# ---- (2b) healthy scale-up: a commit absorbs the joiner, no fault ----

_GROW_SIZE = 2  # before the joiner; grows to 3


def _grow_reference():
    # Step 0 runs at 2 ranks; the first commit absorbs the joiner and
    # every later step runs at 3.
    return _train_reference(
        lambda s: (1, 2) if s == 0 else (1, 2, 3))


def _grow_survivor_worker(rank, size):
    from horovod_tpu.common import basics, eager_ops as ops
    from horovod_tpu.common import elastic as hvd_elastic
    from horovod_tpu.common.elastic import ObjectState

    b = basics.HorovodBasics()
    hvd_elastic.init()
    if rank == 0:
        deadline = time.monotonic() + 60
        door = hvd_elastic._ensure_door()
        while door.pending_count() == 0:
            assert time.monotonic() < deadline, "joiner never knocked"
            time.sleep(0.05)
    state = ObjectState(step=0,
                        params=np.zeros(_TRAIN_DIM, np.float32))
    epochs_seen = []
    params = _rejoin_train(state, b, ops, epochs_seen)
    assert epochs_seen == [0, 1], epochs_seen
    assert (b.epoch(), b.size()) == (1, _GROW_SIZE + 1)
    np.testing.assert_allclose(params, _grow_reference(), rtol=1e-5,
                               atol=1e-7)
    el = b.metrics_snapshot()["elastic"]
    # Pure parole: grown, nothing blacklisted, zero faults.
    assert el["ranks_rejoined"] == 1, el
    assert el["ranks_blacklisted"] == 0, el
    assert el["faults_detected"] == 0, el
    b.shutdown()
    return "ok"


def _grow_joiner_worker(rank, size):
    return _join_and_train(_GROW_SIZE + 1, _grow_reference())


def test_healthy_commit_absorbs_parole_joiner_scale_up():
    rejoin_port = free_port()
    results = run_chaos(
        _grow_survivor_worker, _GROW_SIZE, victims=set(),
        expect_sigkill=False, timeout=180,
        env={"HOROVOD_WIRE_TIMEOUT_MS": "5000",
             "HOROVOD_REJOIN_PORT": str(rejoin_port)},
        extra=[(_grow_joiner_worker,
                {"HOROVOD_WORKER_ID": "parolee:2"})])
    assert results == {0: "ok", 1: "ok", _GROW_SIZE: "ok"}


# ---- satellite: kill mid-redistribute (alltoallv plan step) ----------

_RESHARD_SIZE = 4
_RESHARD_VICTIM = 3
_RESHARD_ROWS = 64


def _reshard_kill_worker(rank, size):
    from horovod_tpu.common import basics, eager_ops as ops
    from horovod_tpu.common.exceptions import HorovodPeerFailureError
    from horovod_tpu.parallel import reshard

    b = basics.HorovodBasics()
    b.init()
    ops.allreduce_async(np.ones(8, np.float32), "warm").synchronize()
    # Sharded -> sharded with shifted boundaries: a pure alltoallv plan.
    src = reshard.Layout.from_rows(
        [(0, 10), (10, 30), (40, 20), (60, 4)])
    dst = reshard.Layout.sharded(_RESHARD_ROWS, size)
    plan = reshard.plan_redistribute((_RESHARD_ROWS, 5), np.float32,
                                     src, dst)
    assert [s.op for s in plan.steps] == ["alltoallv"], plan.steps
    s0, n0 = src.range_of(rank)
    full = np.arange(_RESHARD_ROWS * 5, dtype=np.float32).reshape(-1, 5)
    local = full[s0:s0 + n0]
    if rank == _RESHARD_VICTIM:
        b.set_fault_inject(rank, 1)  # die at the alltoallv itself
    t0 = time.monotonic()
    try:
        out = reshard.execute_plan(plan, local, name="chaos.reshard")
        return "reshard-did-not-fail"
    except HorovodPeerFailureError as e:
        # Every survivor: typed, within the deadline + slack, never a
        # hang (the planner's multi-step sequences ride the same
        # recoverable wire as any collective).
        elapsed = time.monotonic() - t0
        assert _RESHARD_VICTIM in e.fault_ranks, (e.fault_ranks, str(e))
        assert elapsed < 2.0 + 8.0, elapsed
    b.shutdown()
    return "ok"


def test_kill_mid_redistribute_raises_typed_on_every_survivor(tmp_path):
    bb_dir = str(tmp_path / "blackbox")
    results = run_chaos(
        _reshard_kill_worker, _RESHARD_SIZE, victims={_RESHARD_VICTIM},
        env={"HOROVOD_WIRE_TIMEOUT_MS": "2000",
             "HOROVOD_BLACKBOX_DIR": bb_dir})
    assert results == {r: "ok" for r in range(_RESHARD_SIZE - 1)}
    # Black-box post-mortem (docs/metrics.md): every survivor dumped
    # its event-ring tail the moment it recorded the fault, and the
    # merged causal timeline names the injected-fault rank as root
    # cause — proven death, not one of the secondary timeouts the
    # stall propagated to.
    from horovod_tpu.telemetry import postmortem

    for r in range(_RESHARD_SIZE - 1):
        path = os.path.join(bb_dir, f"blackbox-rank{r}.jsonl")
        assert os.path.exists(path), f"no black-box dump for rank {r}"
        dumps = postmortem.load_blackbox(path)
        assert dumps and dumps[-1]["events"], path
    analysis = postmortem.merge_post_mortem(bb_dir)
    assert analysis["root_cause_ranks"] == [_RESHARD_VICTIM], analysis[
        "root_cause_ranks"]
    assert _RESHARD_VICTIM not in analysis["ranks"]
    # The injected collective shows up in the merged causal window.
    types = {e["type"] for e in analysis["timeline"]}
    assert "fault" in types and "response_launch" in types, types


# ---- satellite: reshard_rows rebalances after a world change ---------


def _reshard_rows_worker(rank, size):
    from horovod_tpu.common import basics
    from horovod_tpu.parallel import reshard

    b = basics.HorovodBasics()
    b.init()
    # Simulated post-regrow state: ranks 0..size-2 hold the old even
    # shards, the "joiner" (last rank) holds nothing.
    n_rows = 31
    old = reshard.Layout.sharded(n_rows, size - 1)
    rows_held = [old.range_of(r)[1] for r in range(size - 1)] + [0]
    full = np.arange(n_rows * 3, dtype=np.float32).reshape(-1, 3)
    if rank < size - 1:
        s0, n0 = old.range_of(rank)
        local = full[s0:s0 + n0]
    else:
        local = np.zeros((0, 3), np.float32)
    out = reshard.reshard_rows(local, rows_held)
    s1, n1 = reshard.Layout.sharded(n_rows, size).range_of(rank)
    np.testing.assert_array_equal(out, full[s1:s1 + n1])
    b.shutdown()
    return "ok"


def test_reshard_rows_flows_state_onto_regrown_world():
    results = run_chaos(_reshard_rows_worker, 3, victims=set(),
                        expect_sigkill=False,
                        env={"HOROVOD_WIRE_TIMEOUT_MS": "5000"})
    assert results == {0: "ok", 1: "ok", 2: "ok"}


# ---- satellite: survivors() is rank-consistent -----------------------


def _survivors_worker(rank, size):
    from horovod_tpu.common import basics, eager_ops as ops
    from horovod_tpu.common import elastic as hvd_elastic
    from horovod_tpu.common.exceptions import HorovodInternalError

    b = basics.HorovodBasics()
    b.init()
    assert hvd_elastic.survivors() is None  # no fault yet
    x = np.ones(64, np.float32)
    ops.allreduce_async(x, "w0").synchronize()
    try:
        ops.allreduce_async(x, "boom").synchronize()
        return "did-not-fail"
    except HorovodInternalError:
        pass
    alive = hvd_elastic.survivors()
    # Keep our sockets OPEN until every survivor has recorded its own
    # fault (the r12 ordering rule reinit itself follows): shutting
    # down now would feed late-detecting survivors an EOF from a live
    # rank and skew THEIR dead set. Non-neighbors pay one wire
    # deadline, so one deadline + slack covers the slowest detector.
    time.sleep(_TIMEOUT_MS / 1000.0 + 3.0)
    b.shutdown()
    return alive


def test_survivors_identical_on_every_rank():
    results = run_chaos(
        _survivors_worker, 4, victims={1},
        env={"HOROVOD_WIRE_TIMEOUT_MS": str(_TIMEOUT_MS),
             "HOROVOD_FAULT_INJECT": "1:1:kill"})
    assert set(results) == {0, 2, 3}
    lists = {tuple(v) for v in results.values()}
    assert lists == {(0, 2, 3)}, results


# ---- grammar + knob plumbing (no ring needed) ------------------------


def test_fault_grammar_rejects_malformed_specs():
    from horovod_tpu.common import basics

    b = basics.HorovodBasics()
    for bad in ("nonsense", "1", "1:2:explode", "1:2:stop",
                "1:2:stop:-5", "1:2:kill:7", "1:2:flip",
                "x:2:kill", "1:y", "1:2:delay:0", "1:2:stop:3:4",
                "1:2:flip:5:x", "1:2:flip:-5:1", "1:2:flip:5:-1",
                # bit must fit the packed low field even without skip
                "1:2:flip:2000000"):
        rc = b.lib.hvdtpu_set_fault_inject_spec(bad.encode())
        assert rc == -2, (bad, rc)
    # Well-formed specs parse (arming needs init; -1 = parsed but no
    # state, never -2).
    for good in ("0:3", "2:5:kill", "1:2:stop:250", "0:1:reset",
                 "1:4:flip:17", "1:4:flip:-17", "1:4:flip:17:2",
                 "3:9:delay:100"):
        rc = b.lib.hvdtpu_set_fault_inject_spec(good.encode())
        assert rc in (0, -1), (good, rc)


def test_wire_heal_and_crc_knob_roundtrips():
    from horovod_tpu.common import basics

    b = basics.HorovodBasics()
    saved = (b.wire_retry_attempts(), b.wire_retry_backoff_ms(),
             b.wire_crc())
    try:
        b.set_wire_retry_attempts(7)
        assert b.wire_retry_attempts() == 7
        b.set_wire_retry_backoff_ms(123)
        assert b.wire_retry_backoff_ms() == 123
        b.set_wire_crc(True)
        assert b.wire_crc() is True
        b.set_wire_crc(False)
        assert b.wire_crc() is False
    finally:
        b.set_wire_retry_attempts(saved[0])
        b.set_wire_retry_backoff_ms(saved[1])
        b.set_wire_crc(saved[2])
