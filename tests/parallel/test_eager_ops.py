"""Distributed correctness of the core's eager collectives, 2 and 4 ranks.

Reference analog: test/parallel/test_torch.py's op tests — expected values
are analytic closed forms (allreduce of rank-valued tensors = sum(range(size))
etc.), asserted across dtypes and shapes (SURVEY.md §4).
"""

import numpy as np
import pytest

from tests.utils_mp import run_ranks


def _init(rank):
    from horovod_tpu.common import basics
    b = basics.HorovodBasics()
    b.init()
    return b


def _ops():
    from horovod_tpu.common import eager_ops
    return eager_ops


def _worker_all_collectives(rank, size):
    b = _init(rank)
    ops = _ops()
    try:
        # --- allreduce: sum, average across dtypes ---
        for dt in (np.float32, np.float64, np.int32, np.int64, np.float16):
            h = ops.allreduce_async(np.full(5, rank, dt), f"ar.{np.dtype(dt)}")
            r = h.synchronize()
            assert r.dtype == np.dtype(dt)
            np.testing.assert_allclose(r.astype(np.float64),
                                       sum(range(size)), rtol=1e-3)
        h = ops.allreduce_async(np.full(5, float(rank), np.float32), "avg",
                                op=ops.ReduceOp.AVERAGE)
        np.testing.assert_allclose(h.synchronize(), sum(range(size)) / size)

        # --- min / max / product ---
        h = ops.allreduce_async(np.full(3, float(rank + 1), np.float64),
                                "min", op=ops.ReduceOp.MIN)
        np.testing.assert_allclose(h.synchronize(), 1.0)
        h = ops.allreduce_async(np.full(3, float(rank + 1), np.float64),
                                "max", op=ops.ReduceOp.MAX)
        np.testing.assert_allclose(h.synchronize(), float(size))
        h = ops.allreduce_async(np.full(3, float(rank + 1), np.float64),
                                "prod", op=ops.ReduceOp.PRODUCT)
        np.testing.assert_allclose(h.synchronize(),
                                   float(np.prod(range(1, size + 1))))

        # --- prescale / postscale ---
        h = ops.allreduce_async(np.full(4, float(rank), np.float32), "scale",
                                prescale_factor=2.0, postscale_factor=0.5)
        np.testing.assert_allclose(h.synchronize(), sum(range(size)))

        # --- fusion: many small tensors in flight at once ---
        hs = [ops.allreduce_async(np.full(3, float(rank + i), np.float32),
                                  f"fuse.{i}") for i in range(8)]
        for i, h in enumerate(hs):
            np.testing.assert_allclose(
                h.synchronize(), sum(rk + i for rk in range(size)))

        # --- allgather with unequal first dims ---
        h = ops.allgather_async(np.full((rank + 1, 2), float(rank),
                                        np.float32), "ag")
        r = h.synchronize()
        exp = np.concatenate(
            [np.full((rk + 1, 2), float(rk), np.float32)
             for rk in range(size)])
        np.testing.assert_allclose(r, exp)

        # --- broadcast from non-zero root ---
        root = size - 1
        h = ops.broadcast_async(np.full(4, float(rank), np.float64), root,
                                "bc")
        np.testing.assert_allclose(h.synchronize(), float(root))

        # --- alltoall with explicit splits ---
        data = np.arange(size * 2, dtype=np.float32) + 100 * rank
        h = ops.alltoall_async(data, [2] * size, "a2a")
        r = h.synchronize()
        exp = np.concatenate(
            [np.arange(rank * 2, rank * 2 + 2, dtype=np.float32) + 100 * rk
             for rk in range(size)])
        np.testing.assert_allclose(r, exp)

        # --- reducescatter ---
        h = ops.reducescatter_async(
            np.full((size * 3, 2), float(rank + 1), np.float32), "rs")
        r = h.synchronize()
        assert r.shape == (3, 2)
        np.testing.assert_allclose(r, sum(range(1, size + 1)))

        # --- bfloat16 ---
        import ml_dtypes
        h = ops.allreduce_async(np.full(8, float(rank), ml_dtypes.bfloat16),
                                "bf16")
        np.testing.assert_allclose(h.synchronize().astype(np.float32),
                                   sum(range(size)))

        ops.barrier()
        return "ok"
    finally:
        b.shutdown()


@pytest.mark.parametrize("size", [2, 4])
def test_all_collectives(size):
    assert run_ranks(_worker_all_collectives, size) == ["ok"] * size


def _worker_shape_mismatch(rank, size):
    b = _init(rank)
    ops = _ops()
    try:
        # Ranks submit different shapes -> coordinator must reject with a
        # HorovodInternalError on every rank, not hang.
        h = ops.allreduce_async(np.zeros(3 + rank, np.float32), "bad")
        try:
            h.synchronize()
            return "no-error"
        except ops.HorovodInternalError as e:
            assert "mismatched" in str(e)
            return "ok"
    finally:
        b.shutdown()


def test_shape_mismatch_errors():
    assert run_ranks(_worker_shape_mismatch, 2) == ["ok"] * 2


def _worker_large_fused(rank, size):
    b = _init(rank)
    ops = _ops()
    try:
        # 32 MB tensor: per-rank ring segments far exceed kernel socket
        # buffers, exercising the non-blocking duplex path (a blocking send
        # here would deadlock the ring).
        n = 1 << 23
        h = ops.allreduce_async(
            np.arange(n, dtype=np.float32) % 97 * (rank + 1), "big")
        r = h.synchronize()
        np.testing.assert_allclose(
            r, np.arange(n, dtype=np.float32) % 97 * sum(range(1, size + 1)))
        return "ok"
    finally:
        b.shutdown()


def test_large_tensor():
    assert run_ranks(_worker_large_fused, 2) == ["ok"] * 2


def _worker_join(rank, size):
    b = _init(rank)
    ops = _ops()
    try:
        # Uneven workloads: rank r performs (r + 1) * 2 allreduces, then
        # joins. Joined ranks must contribute zeros, so step i's expected sum
        # covers only ranks still active at step i.
        steps = (rank + 1) * 2
        results = []
        for i in range(steps):
            h = ops.allreduce_async(np.full(4, float(rank + 1), np.float32),
                                    f"join.ar.{i}")
            results.append(h.synchronize())
        last = ops.join()
        for i, r in enumerate(results):
            active = [rk for rk in range(size) if (rk + 1) * 2 > i]
            np.testing.assert_allclose(r, sum(rk + 1 for rk in active))
        # Every rank joined; the last to join did the most steps.
        assert last == size - 1, f"last_joined_rank={last}"

        # allgather with a joined rank: joined ranks contribute zero rows.
        if rank > 0:
            h = ops.allgather_async(
                np.full((2, 3), float(rank), np.float32), "join.ag")
            r = h.synchronize()
            exp = np.concatenate([np.full((2, 3), float(rk), np.float32)
                                  for rk in range(1, size)])
            np.testing.assert_allclose(r, exp)
            ops.join()
        else:
            ops.join()
        return "ok"
    finally:
        b.shutdown()


@pytest.mark.parametrize("size", [2, 3])
def test_join(size):
    assert run_ranks(_worker_join, size) == ["ok"] * size


def _worker_join_broadcast_barrier(rank, size):
    b = _init(rank)
    ops = _ops()
    try:
        # Rank 0 joins first; the others broadcast from the LAST rank and run
        # a barrier. Rank 0's synthesized participation must honor the real
        # root (regression: default root 0 corrupted the ring) and keep its
        # local barrier counter aligned for the post-join barrier.
        if rank == 0:
            ops.join()
        else:
            h = ops.broadcast_async(np.full(4, float(rank), np.float64),
                                    size - 1, "jb.bc")
            np.testing.assert_allclose(h.synchronize(), float(size - 1))
            ops.barrier()
            ops.join()
        # Everybody active again: this barrier hangs if counters diverged.
        ops.barrier()
        h = ops.allreduce_async(np.full(2, 1.0, np.float32), "jb.final")
        np.testing.assert_allclose(h.synchronize(), float(size))
        return "ok"
    finally:
        b.shutdown()


def test_join_broadcast_and_barrier():
    assert run_ranks(_worker_join_broadcast_barrier, 3) == ["ok"] * 3
