"""Distributed correctness of the core's eager collectives, 2 and 4 ranks.

Reference analog: test/parallel/test_torch.py's op tests — expected values
are analytic closed forms (allreduce of rank-valued tensors = sum(range(size))
etc.), asserted across dtypes and shapes (SURVEY.md §4).
"""

import numpy as np
import pytest

from tests.utils_mp import run_ranks

# Part of the sub-5-minute CI lane (make test-quick).
pytestmark = pytest.mark.quick


def _init(rank):
    from horovod_tpu.common import basics
    b = basics.HorovodBasics()
    b.init()
    return b


def _ops():
    from horovod_tpu.common import eager_ops
    return eager_ops


def _worker_all_collectives(rank, size):
    b = _init(rank)
    ops = _ops()
    try:
        # --- allreduce: sum, average across dtypes ---
        for dt in (np.float32, np.float64, np.int32, np.int64, np.float16):
            h = ops.allreduce_async(np.full(5, rank, dt), f"ar.{np.dtype(dt)}")
            r = h.synchronize()
            assert r.dtype == np.dtype(dt)
            np.testing.assert_allclose(r.astype(np.float64),
                                       sum(range(size)), rtol=1e-3)
        h = ops.allreduce_async(np.full(5, float(rank), np.float32), "avg",
                                op=ops.ReduceOp.AVERAGE)
        np.testing.assert_allclose(h.synchronize(), sum(range(size)) / size)

        # --- min / max / product ---
        h = ops.allreduce_async(np.full(3, float(rank + 1), np.float64),
                                "min", op=ops.ReduceOp.MIN)
        np.testing.assert_allclose(h.synchronize(), 1.0)
        h = ops.allreduce_async(np.full(3, float(rank + 1), np.float64),
                                "max", op=ops.ReduceOp.MAX)
        np.testing.assert_allclose(h.synchronize(), float(size))
        h = ops.allreduce_async(np.full(3, float(rank + 1), np.float64),
                                "prod", op=ops.ReduceOp.PRODUCT)
        np.testing.assert_allclose(h.synchronize(),
                                   float(np.prod(range(1, size + 1))))

        # --- prescale / postscale ---
        h = ops.allreduce_async(np.full(4, float(rank), np.float32), "scale",
                                prescale_factor=2.0, postscale_factor=0.5)
        np.testing.assert_allclose(h.synchronize(), sum(range(size)))

        # --- fusion: many small tensors in flight at once ---
        hs = [ops.allreduce_async(np.full(3, float(rank + i), np.float32),
                                  f"fuse.{i}") for i in range(8)]
        for i, h in enumerate(hs):
            np.testing.assert_allclose(
                h.synchronize(), sum(rk + i for rk in range(size)))

        # --- allgather with unequal first dims ---
        h = ops.allgather_async(np.full((rank + 1, 2), float(rank),
                                        np.float32), "ag")
        r = h.synchronize()
        exp = np.concatenate(
            [np.full((rk + 1, 2), float(rk), np.float32)
             for rk in range(size)])
        np.testing.assert_allclose(r, exp)

        # --- broadcast from non-zero root ---
        root = size - 1
        h = ops.broadcast_async(np.full(4, float(rank), np.float64), root,
                                "bc")
        np.testing.assert_allclose(h.synchronize(), float(root))

        # --- alltoall with explicit splits ---
        data = np.arange(size * 2, dtype=np.float32) + 100 * rank
        h = ops.alltoall_async(data, [2] * size, "a2a")
        r = h.synchronize()
        exp = np.concatenate(
            [np.arange(rank * 2, rank * 2 + 2, dtype=np.float32) + 100 * rk
             for rk in range(size)])
        np.testing.assert_allclose(r, exp)

        # --- reducescatter ---
        h = ops.reducescatter_async(
            np.full((size * 3, 2), float(rank + 1), np.float32), "rs")
        r = h.synchronize()
        assert r.shape == (3, 2)
        np.testing.assert_allclose(r, sum(range(1, size + 1)))

        # --- bfloat16 ---
        import ml_dtypes
        h = ops.allreduce_async(np.full(8, float(rank), ml_dtypes.bfloat16),
                                "bf16")
        np.testing.assert_allclose(h.synchronize().astype(np.float32),
                                   sum(range(size)))

        ops.barrier()
        return "ok"
    finally:
        b.shutdown()


@pytest.mark.parametrize("size", [2, 4])
def test_all_collectives(size):
    assert run_ranks(_worker_all_collectives, size) == ["ok"] * size


def _worker_shape_mismatch(rank, size):
    b = _init(rank)
    ops = _ops()
    try:
        # Ranks submit different shapes -> coordinator must reject with a
        # HorovodInternalError on every rank, not hang.
        h = ops.allreduce_async(np.zeros(3 + rank, np.float32), "bad")
        try:
            h.synchronize()
            return "no-error"
        except ops.HorovodInternalError as e:
            assert "mismatched" in str(e)
            return "ok"
    finally:
        b.shutdown()


def test_shape_mismatch_errors():
    assert run_ranks(_worker_shape_mismatch, 2) == ["ok"] * 2


def _worker_large_fused(rank, size):
    b = _init(rank)
    ops = _ops()
    try:
        # 32 MB tensor: per-rank ring segments far exceed kernel socket
        # buffers, exercising the non-blocking duplex path (a blocking send
        # here would deadlock the ring).
        n = 1 << 23
        h = ops.allreduce_async(
            np.arange(n, dtype=np.float32) % 97 * (rank + 1), "big")
        r = h.synchronize()
        np.testing.assert_allclose(
            r, np.arange(n, dtype=np.float32) % 97 * sum(range(1, size + 1)))
        return "ok"
    finally:
        b.shutdown()


def test_large_tensor():
    assert run_ranks(_worker_large_fused, 2) == ["ok"] * 2


def _worker_join(rank, size):
    b = _init(rank)
    ops = _ops()
    try:
        # Uneven workloads: rank r performs (r + 1) * 2 allreduces, then
        # joins. Joined ranks must contribute zeros, so step i's expected sum
        # covers only ranks still active at step i.
        steps = (rank + 1) * 2
        results = []
        for i in range(steps):
            h = ops.allreduce_async(np.full(4, float(rank + 1), np.float32),
                                    f"join.ar.{i}")
            results.append(h.synchronize())
        last = ops.join()
        for i, r in enumerate(results):
            active = [rk for rk in range(size) if (rk + 1) * 2 > i]
            np.testing.assert_allclose(r, sum(rk + 1 for rk in active))
        # Every rank joined; the last to join did the most steps.
        assert last == size - 1, f"last_joined_rank={last}"

        # allgather with a joined rank: joined ranks contribute zero rows.
        if rank > 0:
            h = ops.allgather_async(
                np.full((2, 3), float(rank), np.float32), "join.ag")
            r = h.synchronize()
            exp = np.concatenate([np.full((2, 3), float(rk), np.float32)
                                  for rk in range(1, size)])
            np.testing.assert_allclose(r, exp)
            ops.join()
        else:
            ops.join()
        return "ok"
    finally:
        b.shutdown()


@pytest.mark.parametrize("size", [2, 3])
def test_join(size):
    assert run_ranks(_worker_join, size) == ["ok"] * size


def _worker_join_broadcast_barrier(rank, size):
    b = _init(rank)
    ops = _ops()
    try:
        # Rank 0 joins first; the others broadcast from the LAST rank and run
        # a barrier. Rank 0's synthesized participation must honor the real
        # root (regression: default root 0 corrupted the ring) and keep its
        # local barrier counter aligned for the post-join barrier.
        if rank == 0:
            ops.join()
        else:
            h = ops.broadcast_async(np.full(4, float(rank), np.float64),
                                    size - 1, "jb.bc")
            np.testing.assert_allclose(h.synchronize(), float(size - 1))
            ops.barrier()
            ops.join()
        # Everybody active again: this barrier hangs if counters diverged.
        ops.barrier()
        h = ops.allreduce_async(np.full(2, 1.0, np.float32), "jb.final")
        np.testing.assert_allclose(h.synchronize(), float(size))
        return "ok"
    finally:
        b.shutdown()


def test_join_broadcast_and_barrier():
    assert run_ranks(_worker_join_broadcast_barrier, 3) == ["ok"] * 3


def _worker_process_sets(rank, size):
    b = _init(rank)
    ops = _ops()
    from horovod_tpu.common import process_sets as psets
    try:
        evens = psets.add_process_set([r for r in range(size) if r % 2 == 0])
        odds = psets.add_process_set([r for r in range(size) if r % 2 == 1])
        mine = evens if rank % 2 == 0 else odds
        other = odds if rank % 2 == 0 else evens
        group = [r for r in range(size) if r % 2 == rank % 2]
        assert mine.included() and not other.included()
        assert mine.size() == len(group)
        assert mine.rank() == group.index(rank)

        # allreduce over the subgroup only.
        h = ops.allreduce_async(np.full(4, float(rank + 1), np.float32),
                                "ps.ar", process_set_id=mine)
        np.testing.assert_allclose(h.synchronize(),
                                   sum(r + 1 for r in group))
        # average divides by the SET size.
        h = ops.allreduce_async(np.full(4, float(rank + 1), np.float32),
                                "ps.avg", op=ops.ReduceOp.AVERAGE,
                                process_set_id=mine)
        np.testing.assert_allclose(
            h.synchronize(), sum(r + 1 for r in group) / len(group))

        # allgather over the subgroup, unequal first dims.
        h = ops.allgather_async(np.full((mine.rank() + 1, 2), float(rank),
                                        np.float32), "ps.ag",
                                process_set_id=mine)
        exp = np.concatenate([np.full((i + 1, 2), float(r), np.float32)
                              for i, r in enumerate(group)])
        np.testing.assert_allclose(h.synchronize(), exp)

        # broadcast from the set's last member.
        h = ops.broadcast_async(np.full(3, float(rank), np.float64),
                                group[-1], "ps.bc", process_set_id=mine)
        np.testing.assert_allclose(h.synchronize(), float(group[-1]))

        # global collectives still work alongside.
        h = ops.allreduce_async(np.full(2, 1.0, np.float32), "ps.global")
        np.testing.assert_allclose(h.synchronize(), float(size))

        psets.remove_process_set(evens)
        psets.remove_process_set(odds)
        return "ok"
    finally:
        b.shutdown()


@pytest.mark.parametrize("size", [3, 4])
def test_process_sets(size):
    assert run_ranks(_worker_process_sets, size) == ["ok"] * size


def _adasum_expected(vecs):
    """Replicates csrc/adasum.cc's reduction tree in numpy (float64)."""
    def combine(a, b):
        dot, na, nb = float(a @ b), float(a @ a), float(b @ b)
        ca = 1.0 if na == 0 else 1.0 - dot / (2 * na)
        cb = 1.0 if nb == 0 else 1.0 - dot / (2 * nb)
        return ca * a + cb * b

    vecs = [v.astype(np.float64) for v in vecs]
    n, p = len(vecs), 1
    while p * 2 <= n:
        p *= 2
    for r in range(n - p):
        vecs[r] = combine(vecs[r], vecs[r + p])
    dist = 1
    while dist < p:
        stage = list(vecs)
        for r in range(p):
            vecs[r] = combine(stage[r], stage[r ^ dist])
        dist *= 2
    return vecs[0]


def _worker_adasum(rank, size):
    b = _init(rank)
    ops = _ops()
    try:
        rng = np.random.RandomState(17 + rank)
        v = rng.randn(64)
        h = ops.allreduce_async(v.copy(), "adasum", op=ops.ReduceOp.ADASUM)
        r = h.synchronize()
        exp = _adasum_expected([np.random.RandomState(17 + rk).randn(64)
                                for rk in range(size)])
        np.testing.assert_allclose(r, exp, rtol=1e-12)

        # Scale invariance: identical gradients average back to themselves.
        w = np.arange(8, dtype=np.float64) + 1
        h = ops.allreduce_async(w.copy(), "adasum.same",
                                op=ops.ReduceOp.ADASUM)
        np.testing.assert_allclose(h.synchronize(), w, rtol=1e-12)

        # Integer dtype is rejected cleanly, not a hang.
        h = ops.allreduce_async(np.ones(4, np.int32), "adasum.int",
                                op=ops.ReduceOp.ADASUM)
        try:
            h.synchronize()
            return "no-error"
        except ops.HorovodInternalError as e:
            assert "floating-point" in str(e)
        return "ok"
    finally:
        b.shutdown()


@pytest.mark.parametrize("size", [2, 3, 4])
def test_adasum(size):
    assert run_ranks(_worker_adasum, size) == ["ok"] * size


def _worker_autotune(rank, size):
    b = _init(rank)
    ops = _ops()
    try:
        # Correctness must hold while the autotuner walks the knob grid and
        # broadcasts new values mid-training.
        for i in range(30):
            h = ops.allreduce_async(
                np.full(1024, float(rank + i), np.float32), f"at.{i}")
            np.testing.assert_allclose(
                h.synchronize(), sum(rk + i for rk in range(size)))
        if rank == 0:
            import os
            log = os.environ["HOROVOD_AUTOTUNE_LOG"]
            assert open(log).readline().startswith(
                "fusion_threshold_bytes,cycle_time_ms")
        return "ok"
    finally:
        b.shutdown()


def test_autotune(tmp_path):
    log = str(tmp_path / "autotune.csv")
    assert run_ranks(_worker_autotune, 2,
                     env={"HOROVOD_AUTOTUNE": "1",
                          "HOROVOD_AUTOTUNE_LOG": log}) == ["ok"] * 2


def _worker_runtime_timeline(rank, size):
    import json
    import os

    b = _init(rank)
    ops = _ops()
    try:
        path = os.path.join(os.environ["HVDTPU_TEST_TMP"], f"tl.{rank}.json")
        b.start_timeline(path)
        h = ops.allreduce_async(np.full(8, float(rank), np.float32), "tl.ar")
        h.synchronize()
        ops.barrier()
        b.stop_timeline()
        events = json.load(open(path))
        names = {e.get("name") for e in events if e}
        assert "RING_ALLREDUCE" in names, names
        return "ok"
    finally:
        b.shutdown()


def test_runtime_timeline(tmp_path):
    assert run_ranks(_worker_runtime_timeline, 2,
                     env={"HVDTPU_TEST_TMP": str(tmp_path)}) == ["ok"] * 2


def _worker_ps_barrier_and_errors(rank, size):
    b = _init(rank)
    ops = _ops()
    from horovod_tpu.common import process_sets as psets
    try:
        sub = psets.add_process_set([0, 1])
        # Set-scoped barrier on a subset, then a global barrier: per-set
        # sequence numbers must keep the global barrier aligned
        # (regression: a single global counter desynced and hung here).
        if rank in (0, 1):
            ops.barrier(process_set_id=sub)
        ops.barrier()

        # Unknown process set -> error, not a silent hang.
        h = ops.allreduce_async(np.ones(3, np.float32), "ps.unknown",
                                process_set_id=999)
        try:
            h.synchronize()
            return "no-error"
        except ops.HorovodInternalError as e:
            assert "process set" in str(e)

        # Non-member submitting on a set -> error surfaced to that rank.
        if rank == size - 1 and rank not in (0, 1):
            h = ops.allreduce_async(np.ones(3, np.float32), "ps.foreign",
                                    process_set_id=sub)
            try:
                h.synchronize()
                return "no-error"
            except ops.HorovodInternalError as e:
                assert "not a member" in str(e)
        ops.barrier()
        return "ok"
    finally:
        b.shutdown()


def test_process_set_barrier_and_errors():
    assert run_ranks(_worker_ps_barrier_and_errors, 3) == ["ok"] * 3


def _worker_grouped_atomic_host(rank, size):
    b = _init(rank)
    ops = _ops()
    try:
        # Threshold is 16 bytes (env): only atomic group negotiation can
        # fuse these. Values must be exact and all handles complete.
        for step in range(3):
            handles = ops.grouped_allreduce_async(
                [np.full(6 + i, float(rank + step), np.float32)
                 for i in range(3)],
                [f"g.{i}" for i in range(3)])
            for i, h in enumerate(handles):
                out = h.synchronize()
                assert out.shape == (6 + i,)
                np.testing.assert_allclose(out,
                                           sum(range(size)) + size * step)
        # Grouped tensors bypass the response cache entirely.
        hits, misses, entries = b.response_cache_stats()
        assert entries == 0, f"grouped tensors were cached: {entries}"
        return "ok"
    finally:
        b.shutdown()


def test_grouped_allreduce_atomic_negotiation():
    env = {"HOROVOD_FUSION_THRESHOLD": "16"}
    assert run_ranks(_worker_grouped_atomic_host, 2, env=env,
                     timeout=120) == ["ok"] * 2


def _worker_grouped_mismatched_order(rank, size):
    b = _init(rank)
    ops = _ops()
    try:
        # Ranks disagree on grouping (rank 0 groups, rank 1 enqueues the
        # same names individually): the coordinator must surface an error
        # rather than hang.
        if rank == 0:
            handles = ops.grouped_allreduce_async(
                [np.zeros(4, np.float32), np.zeros(5, np.float32)],
                ["mm.0", "mm.1"])
        else:
            handles = [ops.allreduce_async(np.zeros(4, np.float32), "mm.0"),
                       ops.allreduce_async(np.zeros(5, np.float32), "mm.1")]
        saw_error = False
        for h in handles:
            try:
                h.synchronize()
            except ops.HorovodInternalError:
                saw_error = True
        assert saw_error, "mismatched grouping should error"
        return "ok"
    finally:
        b.shutdown()


def test_grouped_mismatched_order_errors():
    assert run_ranks(_worker_grouped_mismatched_order, 2,
                     timeout=120) == ["ok"] * 2


def _worker_hierarchical(rank, size):
    import os

    # Fake a 2-node x 2-rank layout on localhost (host-major ranks).
    local_size = 2
    os.environ.update({
        "HOROVOD_LOCAL_RANK": str(rank % local_size),
        "HOROVOD_LOCAL_SIZE": str(local_size),
        "HOROVOD_CROSS_RANK": str(rank // local_size),
        "HOROVOD_CROSS_SIZE": str(size // local_size),
    })
    b = _init(rank)
    ops = _ops()
    try:
        # Values must match the flat ring exactly, across ops and sizes
        # (including counts not divisible by local_size).
        for n in (1, 7, 64):
            h = ops.allreduce_async(
                np.arange(n, dtype=np.float64) * (rank + 1), f"h.sum.{n}")
            np.testing.assert_allclose(
                h.synchronize(),
                np.arange(n) * sum(i + 1 for i in range(size)))
        h = ops.allreduce_async(np.full(5, float(rank), np.float32), "h.avg",
                                op=ops.ReduceOp.AVERAGE)
        np.testing.assert_allclose(h.synchronize(),
                                   sum(range(size)) / size)
        h = ops.allreduce_async(np.array([float(rank)]), "h.max",
                                op=ops.ReduceOp.MAX)
        np.testing.assert_allclose(h.synchronize(), size - 1)
        # Fused path (several tensors in one cycle) through hierarchical.
        hs = [ops.allreduce_async(np.full(6, float(rank + i), np.float32),
                                  f"h.f.{i}") for i in range(3)]
        for i, h in enumerate(hs):
            np.testing.assert_allclose(h.synchronize(),
                                       sum(range(size)) + size * i)
        return "ok"
    finally:
        b.shutdown()


def test_hierarchical_allreduce():
    env = {"HOROVOD_HIERARCHICAL_ALLREDUCE": "1"}
    assert run_ranks(_worker_hierarchical, 4, env=env,
                     timeout=180) == ["ok"] * 4


def _worker_hierarchical_heterogeneous(rank, size):
    import os

    # Ranks disagree on local_size (2 vs 3): the collective eligibility
    # check must disable hierarchical mode everywhere — results still
    # exact via the flat ring, no deadlock.
    local_size = 2 if rank < 2 else 3
    os.environ.update({
        "HOROVOD_LOCAL_RANK": str(rank % local_size),
        "HOROVOD_LOCAL_SIZE": str(local_size),
        "HOROVOD_CROSS_RANK": str(rank // local_size),
        "HOROVOD_CROSS_SIZE": "2",
    })
    b = _init(rank)
    ops = _ops()
    try:
        h = ops.allreduce_async(np.full(9, float(rank), np.float64), "het")
        np.testing.assert_allclose(h.synchronize(), sum(range(size)))
        return "ok"
    finally:
        b.shutdown()


def test_hierarchical_disabled_on_heterogeneous_layout():
    env = {"HOROVOD_HIERARCHICAL_ALLREDUCE": "1"}
    assert run_ranks(_worker_hierarchical_heterogeneous, 4, env=env,
                     timeout=180) == ["ok"] * 4
