"""Distributed correctness of the horovod_tpu.torch frontend.

Reference analog: test/parallel/test_torch.py — ops, in-place semantics,
DistributedOptimizer end-to-end training equivalence, SyncBatchNorm vs
single-process big-batch closed form (SURVEY.md §4).
"""

import numpy as np
import pytest

from tests.utils_mp import run_ranks


def _worker_ops(rank, size):
    import torch
    import horovod_tpu.torch as hvd

    hvd.init()
    try:
        assert hvd.rank() == rank and hvd.size() == size

        # in-place allreduce_ writes into the same storage
        t = torch.full((4, 3), float(rank))
        out = hvd.allreduce_(t, op=hvd.Sum)
        assert out.data_ptr() == t.data_ptr()
        assert torch.allclose(t, torch.full((4, 3),
                                            float(sum(range(size)))))

        # out-of-place leaves input untouched
        t2 = torch.full((5,), float(rank))
        r = hvd.allreduce(t2)  # Average
        assert torch.allclose(t2, torch.full((5,), float(rank)))
        assert torch.allclose(r, torch.full((5,),
                                            sum(range(size)) / size))

        # bfloat16
        bf = hvd.allreduce(torch.full((8,), float(rank),
                                      dtype=torch.bfloat16), op=hvd.Sum)
        assert bf.dtype == torch.bfloat16
        assert torch.allclose(bf.float(),
                              torch.full((8,), float(sum(range(size)))))

        # allgather unequal first dim
        g = hvd.allgather(torch.full((rank + 1, 2), float(rank)))
        assert g.shape == (sum(range(1, size + 1)), 2)

        # broadcast_ in place from root
        b = torch.full((3,), float(rank))
        hvd.broadcast_(b, root_rank=size - 1)
        assert torch.allclose(b, torch.full((3,), float(size - 1)))

        # alltoall / reducescatter
        a2a = hvd.alltoall(torch.arange(size * 2, dtype=torch.float32)
                           + 100.0 * rank, splits=[2] * size)
        exp = np.concatenate(
            [np.arange(rk_ * 0 + rank * 2, rank * 2 + 2,
                       dtype=np.float32) + 100 * rk_
             for rk_ in range(size)])
        np.testing.assert_allclose(a2a.numpy(), exp)

        rs = hvd.reducescatter(torch.full((size * 2, 3), float(rank + 1)),
                               op=hvd.Sum)
        assert torch.allclose(rs, torch.full((2, 3),
                                             float(sum(range(1, size + 1)))))

        # grouped allgather / reducescatter (atomic negotiation)
        gouts = hvd.grouped_allgather(
            [torch.full((rank + 1, 2), float(rank + i)) for i in range(3)])
        for i, g in enumerate(gouts):
            exp = np.concatenate(
                [np.full((rk + 1, 2), float(rk + i)) for rk in range(size)])
            np.testing.assert_allclose(g.numpy(), exp)
        routs = hvd.grouped_reducescatter(
            [torch.full((size * 2, 3), float(rank + 1 + i))
             for i in range(2)], op=hvd.Sum)
        for i, r_ in enumerate(routs):
            assert torch.allclose(
                r_, torch.full((2, 3),
                               float(sum(rk + 1 + i for rk in range(size)))))

        # broadcast_object / allgather_object
        obj = hvd.broadcast_object({"x": rank}, root_rank=0)
        assert obj == {"x": 0}
        objs = hvd.allgather_object(rank * 10)
        assert objs == [rk * 10 for rk in range(size)]

        hvd.barrier()
        return "ok"
    finally:
        hvd.shutdown()


@pytest.mark.parametrize("size", [2, 4])
def test_torch_ops(size):
    assert run_ranks(_worker_ops, size) == ["ok"] * size


def _worker_device_bridge(rank, size):
    """Device-tensor path (ref adapter_v2.cc/ready_event.cc): tensors
    bridge via dlpack into the jax frontend's data plane instead of the
    CPU numpy view. HOROVOD_TORCH_DEVICE_OPS=1 forces the bridge so the
    path is exercised with jax CPU arrays (identical code path to TPU)."""
    import torch
    import horovod_tpu.torch as hvd
    from horovod_tpu.torch import mpi_ops

    hvd.init()
    try:
        t = torch.full((4, 3), float(rank))
        assert mpi_ops._use_device_bridge(t)  # env forces it

        # in-place: result lands in the original tensor object
        out = hvd.allreduce_(t, op=hvd.Sum)
        assert out is t
        assert torch.allclose(t, torch.full((4, 3),
                                            float(sum(range(size)))))

        # out-of-place average
        r = hvd.allreduce(torch.full((5,), float(rank)))
        assert torch.allclose(r, torch.full((5,),
                                            sum(range(size)) / size))

        # bfloat16 survives the dlpack round trip
        bf = hvd.allreduce(torch.full((8,), float(rank),
                                      dtype=torch.bfloat16), op=hvd.Sum)
        assert bf.dtype == torch.bfloat16
        assert torch.allclose(bf.float(),
                              torch.full((8,), float(sum(range(size)))))

        # broadcast_ in-place from a non-zero root
        b = torch.full((3,), float(rank))
        hvd.broadcast_(b, root_rank=size - 1)
        assert torch.allclose(b, torch.full((3,), float(size - 1)))

        # allgather with unequal first dims
        g = hvd.allgather(torch.full((rank + 1, 2), float(rank)))
        assert g.shape == (sum(range(1, size + 1)), 2)

        # reducescatter
        rs = hvd.reducescatter(torch.full((size * 2, 3), float(rank + 1)),
                               op=hvd.Sum)
        assert torch.allclose(rs, torch.full((2, 3),
                                             float(sum(range(1, size + 1)))))

        # grouped: one atomic negotiation through the bridge, results
        # land in-place in the original tensors
        ts = [torch.full((3,), float(rank + i)) for i in range(3)]
        outs = hvd.grouped_allreduce_(ts, op=hvd.Sum,
                                      names=[f"bg.{i}" for i in range(3)])
        for i, (t, o) in enumerate(zip(ts, outs)):
            assert o is t
            assert torch.allclose(t, torch.full(
                (3,), float(sum(rk + i for rk in range(size)))))
        return "ok"
    finally:
        hvd.shutdown()


def test_torch_device_bridge():
    assert run_ranks(_worker_device_bridge, 2,
                     env={"HOROVOD_TORCH_DEVICE_OPS": "1"},
                     timeout=180) == ["ok"] * 2


def _make_model(seed):
    import torch

    torch.manual_seed(seed)
    return torch.nn.Sequential(
        torch.nn.Linear(10, 16), torch.nn.ReLU(), torch.nn.Linear(16, 4))


def _worker_optimizer(rank, size):
    import torch
    import horovod_tpu.torch as hvd

    hvd.init()
    try:
        # Distributed run: each rank sees a different shard of the batch.
        torch.manual_seed(1234)
        full_x = torch.randn(8 * size, 10)
        full_y = torch.randn(8 * size, 4)
        x = full_x[rank * 8:(rank + 1) * 8]
        y = full_y[rank * 8:(rank + 1) * 8]

        model = _make_model(seed=7 + rank)  # deliberately diverged init
        hvd.broadcast_parameters(model.state_dict(), root_rank=0)

        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        opt = hvd.DistributedOptimizer(
            opt, named_parameters=model.named_parameters())
        hvd.broadcast_optimizer_state(opt, root_rank=0)

        for _ in range(3):
            opt.zero_grad()
            loss = torch.nn.functional.mse_loss(model(x), y)
            loss.backward()
            opt.step()

        # Single-process reference on the full batch (grad averaging over
        # ranks == full-batch gradient since shards are equal-sized).
        ref = _make_model(seed=7)
        ref_opt = torch.optim.SGD(ref.parameters(), lr=0.1)
        for _ in range(3):
            ref_opt.zero_grad()
            torch.nn.functional.mse_loss(ref(full_x), full_y).backward()
            ref_opt.step()

        for (n, p), (_, rp) in zip(model.named_parameters(),
                                   ref.named_parameters()):
            np.testing.assert_allclose(p.detach().numpy(),
                                       rp.detach().numpy(), rtol=1e-4,
                                       atol=1e-5), n
        return "ok"
    finally:
        hvd.shutdown()


def test_distributed_optimizer_matches_full_batch():
    assert run_ranks(_worker_optimizer, 2) == ["ok"] * 2


def _worker_optimizer_fp16(rank, size):
    import torch
    import horovod_tpu.torch as hvd

    hvd.init()
    try:
        model = _make_model(seed=3 + rank)
        hvd.broadcast_parameters(model.state_dict(), root_rank=0)
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.05),
            named_parameters=model.named_parameters(),
            compression=hvd.Compression.fp16)
        torch.manual_seed(99)
        x, y = torch.randn(4, 10), torch.randn(4, 4)
        opt.zero_grad()
        torch.nn.functional.mse_loss(model(x), y).backward()
        opt.step()
        # all ranks identical after step (same data, averaged grads)
        blob = hvd.allgather_object(
            [p.detach().numpy() for p in model.parameters()])
        for other in blob[1:]:
            for a, b in zip(blob[0], other):
                np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4)
        return "ok"
    finally:
        hvd.shutdown()


def test_distributed_optimizer_fp16():
    assert run_ranks(_worker_optimizer_fp16, 2) == ["ok"] * 2


def _worker_backward_passes(rank, size):
    import torch
    import horovod_tpu.torch as hvd

    hvd.init()
    try:
        model = torch.nn.Linear(4, 1, bias=False)
        with torch.no_grad():
            model.weight.fill_(0.0)
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=1.0),
            named_parameters=model.named_parameters(),
            backward_passes_per_step=2)
        # two backward passes, one step
        for i in range(2):
            out = model(torch.full((1, 4), float(rank + 1 + i))).sum()
            out.backward()
        opt.step()
        # grad wrt w = x; accumulated over 2 passes, averaged by /2 then
        # across ranks: mean over ranks of mean(x_i)
        exp = -np.mean([np.mean([rk + 1, rk + 2]) for rk in range(size)])
        np.testing.assert_allclose(
            model.weight.detach().numpy(), np.full((1, 4), exp), rtol=1e-5)
        return "ok"
    finally:
        hvd.shutdown()


def test_backward_passes_per_step():
    assert run_ranks(_worker_backward_passes, 2) == ["ok"] * 2


def _worker_sync_bn(rank, size):
    import torch
    import horovod_tpu.torch as hvd

    hvd.init()
    try:
        torch.manual_seed(0)
        full = torch.randn(4 * size, 3, 5, 5)
        x = full[rank * 4:(rank + 1) * 4].clone().requires_grad_(True)

        bn = hvd.SyncBatchNorm(3)
        out = bn(x)
        loss = (out * out).mean()
        loss.backward()

        # reference: plain BatchNorm over the concatenated global batch
        xr = full.clone().requires_grad_(True)
        bn_ref = torch.nn.BatchNorm2d(3)
        out_ref = bn_ref(xr)
        ((out_ref * out_ref).mean() / size * size).backward()

        np.testing.assert_allclose(
            out.detach().numpy(),
            out_ref.detach().numpy()[rank * 4:(rank + 1) * 4],
            rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(bn.running_mean.numpy(),
                                   bn_ref.running_mean.numpy(), rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_allclose(bn.running_var.numpy(),
                                   bn_ref.running_var.numpy(), rtol=1e-3,
                                   atol=1e-4)
        # grad wrt local shard matches the global-batch gradient. The ref
        # loss averages over the full batch (N*size elements) while ours
        # averages over N, so scale ref grads by size.
        np.testing.assert_allclose(
            x.grad.numpy(),
            xr.grad.numpy()[rank * 4:(rank + 1) * 4] * size,
            rtol=1e-3, atol=1e-5)
        return "ok"
    finally:
        hvd.shutdown()


def test_sync_batch_norm():
    assert run_ranks(_worker_sync_bn, 2) == ["ok"] * 2


def _worker_lightning_protocol(rank, size):
    import numpy as np
    import torch

    import horovod_tpu.torch as hvd
    from horovod_tpu.spark.lightning import train_protocol_model

    hvd.init()
    try:
        torch.manual_seed(1234 + rank)  # diverge per rank pre-broadcast

        class Lit(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.net = torch.nn.Linear(3, 1)

            def forward(self, x):
                return self.net(x)

            def training_step(self, batch, batch_idx):
                x, y = batch
                return torch.nn.functional.mse_loss(self(x), y)

            def configure_optimizers(self):
                return torch.optim.SGD(self.parameters(), lr=0.05)

        model = Lit()
        rng = np.random.RandomState(rank)  # rank-local data shard
        x = torch.from_numpy(rng.randn(16, 3).astype("float32"))
        y = x @ torch.tensor([[1.0], [-1.0], [2.0]])
        train_protocol_model(model, x, y, batch_size=8, epochs=2,
                             distributed=True)
        # broadcast + averaged grads => identical params on all ranks
        digest = float(sum(p.detach().sum() for p in model.parameters()))
        digests = hvd.allgather_object(digest)
        assert all(abs(d - digests[0]) < 1e-6 for d in digests), digests
        return "ok"
    finally:
        hvd.shutdown()


@pytest.mark.parametrize("size", [2])
def test_lightning_protocol_distributed(size):
    assert run_ranks(_worker_lightning_protocol, size, timeout=180) \
        == ["ok"] * size
