"""Distributed correctness of the TF/Keras frontends.

Reference analog: test/parallel/test_tensorflow.py +
test_tensorflow2_keras.py (SURVEY.md §4).
"""

import numpy as np
import pytest

from tests.utils_mp import run_ranks

_TF_ENV = {"TF_CPP_MIN_LOG_LEVEL": "3", "CUDA_VISIBLE_DEVICES": ""}

def _assert_ok_or_loud_skip(results, n):
    """The native-op tests must never pass vacuously: when the op
    library is unavailable (no tf2xla headers) the suite shows an
    explicit SKIP, not a green pass (VERDICT r2 'weak' #1)."""
    if results == ["skip"] * n:
        pytest.skip("native TF op library unavailable in this image "
                    "(tf2xla headers missing) — in-jit collectives NOT "
                    "exercised")
    assert results == ["ok"] * n



def test_async_build_never_blocks_init(tmp_path, monkeypatch):
    """A cold `make tf` takes minutes; hvd.init() must NOT block on it
    (VERDICT r2 #5): default async mode kicks off a detached build and
    returns immediately with the numpy fallback."""
    import time

    from horovod_tpu.tensorflow import mpi_ops

    root = tmp_path
    (root / "Makefile").write_text("tf:\n\tsleep 2\n\ttouch done\n")
    lib = root / "lib" / "libhvdtpu_tf.so"
    monkeypatch.delenv("HOROVOD_TF_NATIVE_BUILD", raising=False)
    t0 = time.monotonic()
    with pytest.raises(mpi_ops._NativeBuildPending):
        mpi_ops._ensure_built(str(lib), str(root))
    assert time.monotonic() - t0 < 1.5, "init path blocked on the build"
    # A second caller while the build lock is held also returns at once.
    t0 = time.monotonic()
    with pytest.raises(mpi_ops._NativeBuildPending):
        mpi_ops._ensure_built(str(lib), str(root))
    assert time.monotonic() - t0 < 1.5
    # The detached build itself runs to completion for the NEXT process.
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and not (root / "done").exists():
        time.sleep(0.2)
    assert (root / "done").exists(), "background build never ran"
    # sync mode blocks and builds inline (CI pre-warm path).
    monkeypatch.setenv("HOROVOD_TF_NATIVE_BUILD", "sync")
    (root / "Makefile").write_text(f"tf:\n\ttouch {lib}\n")
    mpi_ops._ensure_built(str(lib), str(root))
    assert lib.exists()
    # off: no build attempt, immediate fallback signal.
    lib.unlink()
    monkeypatch.setenv("HOROVOD_TF_NATIVE_BUILD", "off")
    with pytest.raises(FileNotFoundError):
        mpi_ops._ensure_built(str(lib), str(root))
    # A failing background build leaves a marker; later processes stop
    # relaunching the doomed build and fall back at once.
    monkeypatch.delenv("HOROVOD_TF_NATIVE_BUILD", raising=False)
    (root / "Makefile").write_text("tf:\n\texit 1\n")
    with pytest.raises(mpi_ops._NativeBuildPending):
        mpi_ops._ensure_built(str(lib), str(root))
    deadline = time.monotonic() + 15
    marker = root / "lib" / ".tf_build_failed"
    while time.monotonic() < deadline and not marker.exists():
        time.sleep(0.2)
    assert marker.exists(), "failed build left no marker"
    with pytest.raises(FileNotFoundError, match="FAILED"):
        mpi_ops._ensure_built(str(lib), str(root))


def _worker_tf_ops(rank, size):
    import tensorflow as tf
    import horovod_tpu.tensorflow as hvd

    hvd.init()
    try:
        assert hvd.rank() == rank and hvd.size() == size

        r = hvd.allreduce(tf.fill([4, 3], float(rank)), op=hvd.Sum)
        np.testing.assert_allclose(r.numpy(), sum(range(size)))

        avg = hvd.allreduce(tf.fill([5], float(rank)))
        np.testing.assert_allclose(avg.numpy(), sum(range(size)) / size)

        g = hvd.allgather(tf.fill([rank + 1, 2], float(rank)))
        assert g.shape == (sum(range(1, size + 1)), 2)

        b = hvd.broadcast(tf.fill([3], float(rank)), root_rank=size - 1)
        np.testing.assert_allclose(b.numpy(), float(size - 1))

        outs = hvd.grouped_allreduce(
            [tf.fill([2], float(rank + i)) for i in range(3)], op=hvd.Sum)
        for i, o in enumerate(outs):
            np.testing.assert_allclose(o.numpy(),
                                       sum(rk + i for rk in range(size)))

        # broadcast_variables
        v = tf.Variable(tf.fill([4], float(rank)))
        hvd.broadcast_variables([v], root_rank=0)
        np.testing.assert_allclose(v.numpy(), 0.0)
        return "ok"
    finally:
        hvd.shutdown()


@pytest.mark.parametrize("size", [2])
def test_tf_ops(size):
    assert run_ranks(_worker_tf_ops, size, env=_TF_ENV, timeout=180) \
        == ["ok"] * size


def _worker_gradient_tape(rank, size):
    import tensorflow as tf
    import horovod_tpu.tensorflow as hvd

    hvd.init()
    try:
        w = tf.Variable([[1.0], [2.0]])
        x = tf.constant([[float(rank + 1), 0.0]])
        with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
            y = tf.reduce_sum(tf.matmul(x, w))
        (gw,) = tape.gradient(y, [w])
        # dy/dw = x^T; averaged across ranks
        exp = np.array([[np.mean([rk + 1 for rk in range(size)])], [0.0]])
        np.testing.assert_allclose(gw.numpy(), exp)

        # fp16 compression path
        with hvd.DistributedGradientTape(tf.GradientTape(),
                                         compression=hvd.Compression.fp16) \
                as tape2:
            y2 = tf.reduce_sum(tf.matmul(x, w))
        (gw2,) = tape2.gradient(y2, [w])
        assert gw2.dtype == tf.float32
        np.testing.assert_allclose(gw2.numpy(), exp, rtol=1e-3)
        return "ok"
    finally:
        hvd.shutdown()


def test_distributed_gradient_tape():
    assert run_ranks(_worker_gradient_tape, 2, env=_TF_ENV, timeout=180) \
        == ["ok"] * 2


def _worker_jit_compiled_train_step(rank, size):
    """A FULL train step (forward, DistributedGradientTape.gradient,
    optimizer apply) under tf.function(jit_compile=True): the native
    tf2xla kernels lower the collectives to XLA custom-calls into the
    core (reference analog: xla_mpi_ops.cc / HOROVOD_ENABLE_XLA_OPS)."""
    import tensorflow as tf
    import horovod_tpu.tensorflow as hvd
    from horovod_tpu.tensorflow import mpi_ops

    hvd.init()
    try:
        if mpi_ops._load_native() is None:
            return "skip"  # no TF headers in this env: fallback only

        w = tf.Variable([[1.0], [2.0]])
        opt = tf.keras.optimizers.SGD(0.5)

        @tf.function(jit_compile=True)
        def train_step(x):
            with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
                y = tf.reduce_sum(tf.matmul(x, w))
            grads = tape.gradient(y, [w])
            opt.apply_gradients(zip(grads, [w]))
            return grads[0]

        x = tf.constant([[float(rank + 1), 0.0]])
        gw = train_step(x)
        exp = np.array([[np.mean([rk + 1 for rk in range(size)])], [0.0]])
        np.testing.assert_allclose(gw.numpy(), exp)
        # the update actually applied the AVERAGED gradient, identically
        # on every rank
        np.testing.assert_allclose(w.numpy(), [[1.0 - 0.5 * exp[0, 0]],
                                               [2.0]])
        # replay: the compiled program re-negotiates the same tensor
        # names each step (response-cache steady state)
        gw2 = train_step(x)
        np.testing.assert_allclose(gw2.numpy(), exp)

        # in-jit broadcast, from a non-zero root
        @tf.function(jit_compile=True)
        def bstep(t):
            return hvd.broadcast(t, root_rank=size - 1, name="jit.b") * 2.0

        out = bstep(tf.fill([3], float(rank)))
        np.testing.assert_allclose(out.numpy(), 2.0 * (size - 1))
        return "ok"
    finally:
        hvd.shutdown()


def test_jit_compiled_train_step():
    results = run_ranks(_worker_jit_compiled_train_step, 2, env=_TF_ENV,
                        timeout=300)
    _assert_ok_or_loud_skip(results, 2)


def _worker_jit_managed_ops(rank, size):
    """allgather / reducescatter / alltoall inside jit_compile=True
    (equal shapes across ranks — the static-shape contract of the
    compiled path; ragged stays on the eager/graph CPU kernels)."""
    import tensorflow as tf
    import horovod_tpu.tensorflow as hvd
    from horovod_tpu.tensorflow import mpi_ops

    hvd.init()
    try:
        if mpi_ops._load_native() is None:
            return "skip"

        @tf.function(jit_compile=True)
        def step(t):
            g = hvd.allgather(t, name="jm.ag")              # [2s, 3]
            rs = hvd.reducescatter(g, op=hvd.Sum, name="jm.rs")  # [2, 3]
            a = hvd.alltoall(t, name="jm.a2a")              # [2, 3]
            return g, rs, a

        t = tf.fill([2, 3], float(rank + 1))
        g, rs, a = step(t)
        exp_g = np.repeat(np.arange(1, size + 1, dtype=np.float32), 2)
        np.testing.assert_allclose(g.numpy(), exp_g[:, None] * np.ones(3))
        # summed-then-scattered: this rank holds its own 2 rows x size
        np.testing.assert_allclose(rs.numpy(), size * (rank + 1))
        # equal-split alltoall: one row from every rank
        exp_a = np.repeat(np.arange(1, size + 1, dtype=np.float32),
                          2 // size if size <= 2 else 1)[:2]
        np.testing.assert_allclose(np.sort(a.numpy()[:, 0]),
                                   np.sort(exp_a))
        return "ok"
    finally:
        hvd.shutdown()


def test_jit_managed_collectives():
    results = run_ranks(_worker_jit_managed_ops, 2, env=_TF_ENV,
                        timeout=300)
    _assert_ok_or_loud_skip(results, 2)


def _worker_native_process_sets(rank, size):
    """process_set_id flows through the native TF ops (eager + jit):
    evens/odds each allreduce only within their set."""
    import tensorflow as tf
    import horovod_tpu.tensorflow as hvd
    from horovod_tpu.tensorflow import mpi_ops

    hvd.init()
    try:
        if mpi_ops._load_native() is None:
            return "skip"
        evens = hvd.add_process_set([r for r in range(size) if r % 2 == 0])
        odds = hvd.add_process_set([r for r in range(size) if r % 2 == 1])
        hvd.barrier()
        mine = evens if rank % 2 == 0 else odds
        peers = [r for r in range(size) if r % 2 == rank % 2]

        out = hvd.allreduce(tf.fill([3], float(rank + 1)), op=hvd.Sum,
                            name="nps.ar", process_set_id=mine)
        np.testing.assert_allclose(out.numpy(),
                                   sum(r + 1 for r in peers))

        @tf.function(jit_compile=True)
        def j(t):
            return hvd.allreduce(t, op=hvd.Sum, name="nps.jar",
                                 process_set_id=mine) * 2.0

        out = j(tf.fill([2], float(rank + 1)))
        np.testing.assert_allclose(out.numpy(),
                                   2.0 * sum(r + 1 for r in peers))
        return "ok"
    finally:
        hvd.shutdown()


def test_native_ops_process_sets():
    results = run_ranks(_worker_native_process_sets, 4, env=_TF_ENV,
                        timeout=300)
    _assert_ok_or_loud_skip(results, 4)


def _worker_keras_jit_compile_fit(rank, size):
    """model.compile(jit_compile=True): keras 3's own XLA train function
    contains the DistributedOptimizer's grouped allreduce — it must
    compile via the native tf2xla kernels and keep replicas in sync."""
    import tensorflow as tf
    import horovod_tpu.keras as hvd
    from horovod_tpu.tensorflow import mpi_ops

    hvd.init()
    try:
        if mpi_ops._load_native() is None:
            return "skip"
        tf.keras.utils.set_random_seed(42 + rank)
        model = tf.keras.Sequential([
            tf.keras.layers.Dense(4, input_shape=(8,)),
            tf.keras.layers.Dense(1),
        ])
        opt = hvd.DistributedOptimizer(tf.keras.optimizers.SGD(0.1))
        model.compile(optimizer=opt, loss="mse", jit_compile=True)
        hvd.broadcast_variables(model.variables, root_rank=0, prefix="m")
        x = tf.random.stateless_uniform([16, 8], seed=[rank, 1])
        y = tf.random.stateless_uniform([16, 1], seed=[rank, 2])
        model.fit(x, y, batch_size=8, epochs=2, verbose=0)

        import horovod_tpu.tensorflow as hvdtf

        for i, v in enumerate(model.trainable_variables):
            g = hvdtf.allgather(tf.reshape(v, [1, -1]),
                                name=f"kjc.{i}").numpy()
            for row in g[1:]:
                np.testing.assert_allclose(row, g[0], rtol=1e-5,
                                           atol=1e-6)
        return "ok"
    finally:
        hvd.shutdown()


def test_keras_jit_compile_fit():
    results = run_ranks(_worker_keras_jit_compile_fit, 2, env=_TF_ENV,
                        timeout=300)
    _assert_ok_or_loud_skip(results, 2)


def _worker_keras(rank, size):
    import tensorflow as tf
    import horovod_tpu.keras as hvd

    hvd.init()
    try:
        tf.keras.utils.set_random_seed(42 + rank)
        model = tf.keras.Sequential(
            [tf.keras.layers.Dense(4, input_shape=(8,)),
             tf.keras.layers.Dense(1)])
        opt = hvd.DistributedOptimizer(tf.keras.optimizers.SGD(0.1))

        # broadcast weights from rank 0 (diverged seeds above)
        hvd.broadcast_variables(model.variables, root_rank=0,
                                prefix="model")

        x = tf.random.stateless_uniform([4, 8], seed=[rank, 1])
        y = tf.random.stateless_uniform([4, 1], seed=[rank, 2])
        with tf.GradientTape() as tape:
            loss = tf.reduce_mean((model(x) - y) ** 2)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))

        # all ranks converge to identical weights
        import horovod_tpu.tensorflow as hvdtf

        for i, v in enumerate(model.trainable_variables):
            gathered = hvdtf.allgather(
                tf.reshape(v, [1, -1]), name=f"check.{i}")
            arr = gathered.numpy()
            for row in arr[1:]:
                np.testing.assert_allclose(row, arr[0], rtol=1e-5,
                                           atol=1e-6)
        return "ok"
    finally:
        hvd.shutdown()


def test_keras_optimizer():
    assert run_ranks(_worker_keras, 2, env=_TF_ENV, timeout=240) == ["ok"] * 2


def _worker_keras_fit(rank, size):
    """model.fit drives the optimizer INSIDE tf.function (symbolic grads)
    — the graph-mode grouped-allreduce path, plus compile() accepting the
    dynamic-subclass DistributedOptimizer."""
    import numpy as np
    import tensorflow as tf

    import horovod_tpu.keras as hvd

    hvd.init()
    try:
        tf.keras.utils.set_random_seed(42 + rank)
        model = tf.keras.Sequential(
            [tf.keras.layers.Dense(4, input_shape=(8,)),
             tf.keras.layers.Dense(1)])
        opt = hvd.DistributedOptimizer(tf.keras.optimizers.SGD(0.05))
        assert isinstance(opt, tf.keras.optimizers.Optimizer)
        model.compile(optimizer=opt, loss="mse")

        rng = np.random.RandomState(7 + rank)  # different data per rank
        x = rng.rand(32, 8).astype(np.float32)
        y = rng.rand(32, 1).astype(np.float32)
        model.fit(
            x, y, batch_size=8, epochs=1, verbose=0,
            callbacks=[hvd.callbacks.BroadcastGlobalVariablesCallback(0)])

        # Averaged grads + identical starting weights => identical weights.
        import horovod_tpu.tensorflow as hvdtf

        for i, v in enumerate(model.trainable_variables):
            gathered = hvdtf.allgather(
                tf.reshape(v, [1, -1]), name=f"fitcheck.{i}")
            arr = gathered.numpy()
            for row in arr[1:]:
                np.testing.assert_allclose(row, arr[0], atol=1e-5)
        return "ok"
    finally:
        hvd.shutdown()


def test_keras_model_fit():
    assert run_ranks(_worker_keras_fit, 2, env=_TF_ENV,
                     timeout=300) == ["ok"] * 2


def _worker_keras_sum_once(rank, size):
    """Regression: keras 3's apply_gradients delegates to apply(); the
    wrapper must allreduce exactly once (op=Sum would show a factor of
    `size` error if both were overridden)."""
    import numpy as np
    import tensorflow as tf

    import horovod_tpu.keras as hvd

    hvd.init()
    try:
        v = tf.Variable([1.0, 2.0])
        opt = hvd.DistributedOptimizer(tf.keras.optimizers.SGD(1.0),
                                       op=hvd.Sum)
        grad = tf.constant([float(rank + 1), 0.0])
        opt.apply_gradients([(grad, v)])
        # sum of (rank+1) over 2 ranks = 3; v[0] = 1 - 1.0*3 = -2
        expected = 1.0 - sum(r + 1 for r in range(size))
        np.testing.assert_allclose(v.numpy()[0], expected, atol=1e-6)
        return "ok"
    finally:
        hvd.shutdown()


def test_keras_allreduce_applied_once():
    assert run_ranks(_worker_keras_sum_once, 2, env=_TF_ENV,
                     timeout=240) == ["ok"] * 2


def _worker_sync_bn(rank, size):
    """SyncBatchNormalization: training moments span ranks — each rank
    feeds a different constant, normalized output must use the GLOBAL
    mean, and moving stats must match the global batch."""
    import numpy as np
    import tensorflow as tf

    import horovod_tpu.tensorflow as hvd

    hvd.init()
    try:
        bn = hvd.SyncBatchNormalization(momentum=0.0, epsilon=0.0)
        # rank 0 feeds zeros, rank 1 feeds twos -> global mean 1, var 1
        x = tf.fill([4, 3], float(rank * 2))
        y = bn(x, training=True)
        np.testing.assert_allclose(bn.moving_mean.numpy(), 1.0, atol=1e-5)
        np.testing.assert_allclose(bn.moving_variance.numpy(), 1.0,
                                   atol=1e-5)
        expected = (rank * 2 - 1.0) / 1.0  # (x - mean)/sqrt(var)
        np.testing.assert_allclose(y.numpy(), expected, atol=1e-4)
        # eval path uses moving stats, no collective
        y_eval = bn(tf.fill([2, 3], 1.0), training=False)
        np.testing.assert_allclose(y_eval.numpy(), 0.0, atol=1e-4)
        return "ok"
    finally:
        hvd.shutdown()


def test_sync_batch_norm():
    assert run_ranks(_worker_sync_bn, 2, env=_TF_ENV,
                     timeout=240) == ["ok"] * 2


def _worker_sync_bn_graph_mode(rank, size):
    """training passed as a symbolic tensor inside tf.function must
    branch via smart_cond, not Python truthiness (regression test)."""
    import numpy as np
    import tensorflow as tf

    import horovod_tpu.tensorflow as hvd

    hvd.init()
    try:
        bn = hvd.SyncBatchNormalization(momentum=0.0, epsilon=0.0)

        @tf.function
        def run(x, training):
            return bn(x, training=training)

        x = tf.fill([4, 3], float(rank * 2))
        y = run(x, tf.constant(True))
        np.testing.assert_allclose(bn.moving_mean.numpy(), 1.0, atol=1e-5)
        np.testing.assert_allclose(y.numpy(), rank * 2 - 1.0, atol=1e-4)
        y_eval = run(tf.fill([2, 3], 1.0), tf.constant(False))
        np.testing.assert_allclose(y_eval.numpy(), 0.0, atol=1e-4)
        # config round-trips through JSON (no live objects inside)
        import json
        json.dumps(bn.get_config())
        return "ok"
    finally:
        hvd.shutdown()


def test_sync_batch_norm_graph_mode():
    assert run_ranks(_worker_sync_bn_graph_mode, 2, env=_TF_ENV,
                     timeout=240) == ["ok"] * 2


def _worker_keras_grad_aggregation(rank, size):
    """backward_passes_per_step=3: the variable must move only every 3rd
    apply, by the cross-rank average of the accumulated-average grads."""
    import numpy as np
    import tensorflow as tf

    import horovod_tpu.keras as hvd

    hvd.init()
    try:
        opt = hvd.DistributedOptimizer(
            tf.keras.optimizers.SGD(learning_rate=1.0),
            backward_passes_per_step=3)
        v = tf.Variable([10.0, 10.0])
        # rank r applies grads (r+1)*[1,1] three times; the boundary
        # update is avg over passes (= (r+1)) then avg over ranks
        # (= 1.5 for 2 ranks), lr 1.0.
        for step in range(3):
            opt.apply([tf.constant([float(rank + 1)] * 2)], [v])
            if step < 2:
                np.testing.assert_allclose(v.numpy(), 10.0, atol=1e-6,
                                           err_msg=f"moved at step {step}")
        delta = sum(i + 1 for i in range(size)) / size
        np.testing.assert_allclose(v.numpy(), 10.0 - delta, atol=1e-5)
        # iterations counts EVERY backward pass (LR schedules keyed on it
        # must not run N times slow), and a second cycle works
        # (accumulators reset).
        assert int(opt.iterations.numpy()) == 3
        for _ in range(3):
            opt.apply([tf.constant([float(rank + 1)] * 2)], [v])
        np.testing.assert_allclose(v.numpy(), 10.0 - 2 * delta, atol=1e-5)
        assert int(opt.iterations.numpy()) == 6

        # Same behavior under tf.function (slot/accumulator creation must
        # happen outside the traced cond).
        opt2 = hvd.DistributedOptimizer(
            tf.keras.optimizers.SGD(learning_rate=1.0),
            backward_passes_per_step=2)
        v2 = tf.Variable([4.0])

        @tf.function
        def train_step(g):
            opt2.apply([g], [v2])

        train_step(tf.constant([float(rank + 1)]))
        np.testing.assert_allclose(v2.numpy(), 4.0, atol=1e-6)
        train_step(tf.constant([float(rank + 1)]))
        np.testing.assert_allclose(v2.numpy(), 4.0 - delta, atol=1e-5)
        return "ok"
    finally:
        hvd.shutdown()


def test_keras_gradient_aggregation():
    assert run_ranks(_worker_keras_grad_aggregation, 2, env=_TF_ENV,
                     timeout=240) == ["ok"] * 2
