"""HOROVOD_CONTROLLER=mpi: the zero-TCP control + data planes.

Reference analog: horovod/common/mpi_controller.cc — on firewalled
MPI-only fabrics the reference never opens ad-hoc sockets. Ours routes
the controller's frames and the host ring's chunks through mpi4py
callbacks (csrc/wire.h external transport); these tests run 3 real OS
ranks over the file-mailbox fake (tests/fake_mpi.py) and assert both
collective correctness AND that the process opened ZERO new sockets of
any family — the property the mode exists for.
"""

import os
import tempfile

import numpy as np
import pytest

from tests.utils_mp import run_ranks

pytestmark = pytest.mark.quick


def _socket_fds():
    fds = []
    d = "/proc/self/fd"
    for f in os.listdir(d):
        try:
            target = os.readlink(os.path.join(d, f))
        except OSError:
            continue
        if target.startswith("socket:"):
            fds.append(target)
    return sorted(fds)


def _worker(rank, size):
    import sys

    os.environ["FAKE_MPI_RANK"] = str(rank)
    os.environ["FAKE_MPI_SIZE"] = str(size)
    os.environ["HOROVOD_CONTROLLER"] = "mpi"
    # Prove the TCP rendezvous is unused: poison the endpoint.
    os.environ["HOROVOD_CONTROLLER_ADDR"] = "203.0.113.1"  # TEST-NET
    os.environ["HOROVOD_CONTROLLER_PORT"] = "1"
    # The file mailbox costs ~ms per message; a relaxed cycle keeps the
    # background loop from hammering it.
    os.environ.setdefault("HOROVOD_CYCLE_TIME", "20")

    import tests.fake_mpi as fake_mpi

    sys.modules["mpi4py"] = fake_mpi

    baseline = _socket_fds()

    from horovod_tpu.common import basics, eager_ops, elastic

    elastic.init()
    b = basics.HorovodBasics()
    assert b.rank() == rank and b.size() == size

    # Host-ring collectives over the external transport (tag-1 chunks).
    out = eager_ops.allreduce_async(
        np.full(8, float(rank + 1), np.float32), "mpi.ar").synchronize()
    np.testing.assert_allclose(out, sum(range(1, size + 1)))

    gathered = eager_ops.allgather_async(
        np.full((2, 3), rank, np.int32), "mpi.ag").synchronize()
    assert gathered.shape == (2 * size, 3)
    np.testing.assert_array_equal(gathered[::2, 0], np.arange(size))

    bc = eager_ops.broadcast_async(
        np.full(4, float(rank), np.float64), 1, "mpi.bc").synchronize()
    np.testing.assert_allclose(bc, 1.0)

    # >1 MB payloads drive the CHUNKED ring paths, where every send
    # must pair with an equal-length recv on the message transport
    # (regression: the broadcast root used to send one whole-buffer
    # message against the forwarders' 1 MB chunked receives).
    big = 3 * (1 << 20) // 4 + 531  # ~3 MB of f32, not chunk-aligned
    out = eager_ops.allreduce_async(
        np.full(big, float(rank + 1), np.float32),
        "mpi.ar.big").synchronize()
    np.testing.assert_allclose(out[:4], sum(range(1, size + 1)))
    bc = eager_ops.broadcast_async(
        np.arange(big, dtype=np.float32) if rank == 0
        else np.zeros(big, np.float32), 0, "mpi.bc.big").synchronize()
    np.testing.assert_allclose(bc[-3:], np.arange(big - 3, big))

    after = _socket_fds()
    assert after == baseline, (
        f"HOROVOD_CONTROLLER=mpi opened sockets: baseline={baseline} "
        f"after={after}")

    b.shutdown()
    return True


def test_mpi_control_plane_zero_tcp_three_ranks(tmp_path):
    with tempfile.TemporaryDirectory() as mailbox:
        results = run_ranks(_worker, 3, timeout=180,
                            env={"FAKE_MPI_DIR": mailbox})
    assert all(results)


def _worker_no_transport(rank, size):
    os.environ["HOROVOD_CONTROLLER"] = "mpi"
    from horovod_tpu.common import basics

    try:
        basics.HorovodBasics().init()
    except RuntimeError:
        return True
    return False


def test_mpi_controller_requires_transport():
    """HOROVOD_CONTROLLER=mpi without a registered transport must fail
    loudly at init, not silently fall back to TCP."""
    results = run_ranks(_worker_no_transport, 2, timeout=60)
    assert all(results)
