"""Eager ZeRO-1 lane (``hvd.DistributedFusedAdam(zero=True)``) over
real OS ranks on the host ring.

Pins the acceptance math of the zero round (docs/zero.md):

- sharded-vs-replicated parity: the pipelined reduce-scatter ->
  shard-adam -> allgather step equals the replicated fused adam fed the
  rank-mean gradients, at 2 and 4 ranks;
- per-rank optimizer state measured at 1/N of the replicated state;
- the metrics snapshot books the new collective mix — reducescatter
  down + allgather up, ZERO allreduces — and the logical bytes
  reconcile with ``telemetry.predict.zero_layout_bytes`` within 1%;
- ``overlap=False`` (phase-separated) computes bit-identical params to
  the pipelined default — overlap is a SCHEDULE change only.

Quick lane alongside tests/parallel/test_ring_wire.py.
"""

import numpy as np
import pytest

from tests.utils_mp import run_ranks

pytestmark = pytest.mark.quick

_SHAPES = [(48, 16), (33,), (16, 8), (65,)]


def _worker_parity(rank, size):
    import jax
    import jax.numpy as jnp

    import horovod_tpu.jax as hvd
    from horovod_tpu import telemetry
    from horovod_tpu.parallel.precision import fused_adam
    from horovod_tpu.parallel.zero import (
        optimizer_state_bytes,
        zero_bucket_layout,
    )
    from horovod_tpu.telemetry.predict import zero_layout_bytes

    hvd.init()
    try:
        params = {f"p{i}": jnp.full(s, 0.05 * (i + 1), jnp.float32)
                  for i, s in enumerate(_SHAPES)}
        grads = {f"p{i}": jnp.full(s, 0.1 * (rank + 1) * (i - 1.5),
                                   jnp.float32)
                 for i, s in enumerate(_SHAPES)}
        gmean = {f"p{i}": jnp.full(s, 0.1 * (i - 1.5) * (size + 1) / 2,
                                   jnp.float32)
                 for i, s in enumerate(_SHAPES)}
        bucket = 2048
        copy = lambda t: jax.tree.map(jnp.array, t)  # noqa: E731

        steps = 3
        zopt = hvd.DistributedFusedAdam(1e-2, zero=True,
                                        bucket_bytes=bucket)
        sep = hvd.DistributedFusedAdam(1e-2, zero=True,
                                       bucket_bytes=bucket,
                                       overlap=False)
        ref = fused_adam(1e-2)
        zs, ss, rs = zopt.init(params), sep.init(params), ref.init(params)
        zp, sp, rp = copy(params), copy(params), copy(params)

        telemetry.metrics_reset()
        for _ in range(steps):
            zp, zs = zopt.apply(zp, grads, zs)
        snap = telemetry.snapshot()
        for _ in range(steps):
            sp, ss = sep.apply(sp, grads, ss)
            rp, rs = ref.apply(rp, gmean, rs)

        # Parity with the replicated update on the mean gradients.
        for k in params:
            np.testing.assert_allclose(np.asarray(zp[k]),
                                       np.asarray(rp[k]),
                                       rtol=1e-5, atol=1e-7, err_msg=k)
            # Overlap is a schedule, not a numerics, knob: bit-equal.
            assert np.array_equal(
                np.asarray(zp[k]).view(np.uint32),
                np.asarray(sp[k]).view(np.uint32)), k

        # 1/N optimizer state per rank (padding + counter = slack).
        zbytes = optimizer_state_bytes(zs)
        rbytes = optimizer_state_bytes(rs)
        assert zbytes < rbytes / size * 1.15, (zbytes, rbytes)

        # Collective mix + byte reconciliation (<1%).
        layout = zero_bucket_layout(list(params.values()), size, bucket)
        predicted = zero_layout_bytes(layout) * steps
        moved = (snap["ops"].get("reducescatter", {}).get("bytes", 0)
                 + snap["ops"].get("allgather", {}).get("bytes", 0))
        assert snap["ops"].get("allreduce", {}).get("tensors", 0) == 0
        assert abs(moved / predicted - 1.0) < 0.01, (moved, predicted)
        return (zbytes, rbytes)
    finally:
        hvd.shutdown()


@pytest.mark.parametrize("size", [2, 4])
def test_eager_zero_parity_and_state_cut(size):
    results = run_ranks(_worker_parity, size, timeout=240)
    assert all(r == results[0] for r in results)


def _worker_compressed(rank, size):
    import jax
    import jax.numpy as jnp

    import horovod_tpu.jax as hvd
    from horovod_tpu.common import basics
    from horovod_tpu.jax.compression import Compression

    hvd.init()
    try:
        b = basics.HorovodBasics()
        assert b.wire_compression() is True
        params = {f"p{i}": jnp.full(s, 0.05 * (i + 1), jnp.float32)
                  for i, s in enumerate(_SHAPES)}
        grads = {f"p{i}": jnp.full(s, 0.1 * (rank + 1) * (i - 1.5),
                                   jnp.float32)
                 for i, s in enumerate(_SHAPES)}
        zopt = hvd.DistributedFusedAdam(1e-2, zero=True,
                                        bucket_bytes=2048,
                                        compression=Compression.bf16)
        state = zopt.init(params)
        zp = jax.tree.map(jnp.array, params)

        snap0 = b.metrics_snapshot()
        for _ in range(2):
            zp, state = zopt.apply(zp, grads, state)
        snap1 = b.metrics_snapshot()

        # bf16 everywhere on the wire. Against the LOGICAL bytes the
        # ratio is 2/3 — the compressed reduce-scatter halves its
        # (f32-logical) phase while the bf16 allgather payload is
        # natively narrow (tx == logical there, both already half of
        # f32). The acceptance-shaped number is transport vs the
        # FULL-WIDTH f32 volume the uncompressed lane would move
        # (2 x (N-1)/N x padded x 4 per step): ~0.5.
        from horovod_tpu.parallel.zero import zero_bucket_layout

        layout = zero_bucket_layout(list(params.values()), size, 2048)
        padded = sum(b.padded for b in layout.buckets)
        full_f32 = 2 * 2 * (size - 1) / size * padded * 4  # 2 steps
        tx = snap1["wire"]["tx_bytes"] - snap0["wire"]["tx_bytes"]
        txl = (snap1["wire"]["tx_logical_bytes"]
               - snap0["wire"]["tx_logical_bytes"])
        assert 0.60 < tx / txl < 0.72, (tx, txl)
        assert 0.45 < tx / full_f32 < 0.60, (tx, full_f32)
        # Rank consistency: the decompressed params are the SAME bits
        # on every rank (owners consume the decoded image too).
        return [float(np.asarray(v).sum()) for v in zp.values()]
    finally:
        hvd.shutdown()


def test_eager_zero_compressed_wire_halves_and_stays_consistent():
    results = run_ranks(_worker_compressed, 4, timeout=240,
                        env={"HOROVOD_WIRE_COMPRESSION": "1",
                             "HOROVOD_RING_CHUNK_BYTES": "4096"})
    assert all(r == results[0] for r in results)
