"""Distributed correctness of the horovod_tpu.jax frontend.

Reference analog: test/parallel/test_torch.py — the frontend-level op,
optimizer-wrap, and broadcast_parameters tests; expected values are
analytic closed forms (SURVEY.md §4).
"""

import numpy as np
import pytest

from tests.utils_mp import run_ranks


def _worker_ops(rank, size):
    import jax.numpy as jnp
    import horovod_tpu.jax as hvd

    hvd.init()
    try:
        assert hvd.rank() == rank and hvd.size() == size
        assert hvd.is_initialized()

        # allreduce average (the default op, like the reference)
        r = hvd.allreduce(jnp.full((4, 3), float(rank)), name="ar")
        np.testing.assert_allclose(np.asarray(r), sum(range(size)) / size)

        # sum + async/poll/synchronize
        h = hvd.allreduce_async(jnp.full(5, float(rank)), name="ar2",
                                op=hvd.Sum)
        while not hvd.poll(h):
            pass
        np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                                   sum(range(size)))

        # grouped allreduce (atomic negotiation)
        outs = hvd.grouped_allreduce(
            [jnp.full(3, float(rank + i)) for i in range(4)], op=hvd.Sum)
        for i, o in enumerate(outs):
            np.testing.assert_allclose(np.asarray(o),
                                       sum(rk + i for rk in range(size)))

        # allgather / broadcast / alltoall / reducescatter
        g = hvd.allgather(jnp.full((rank + 1, 2), float(rank)), name="ag")
        assert np.asarray(g).shape == (sum(range(1, size + 1)), 2)

        b = hvd.broadcast(jnp.full(4, float(rank)), root_rank=size - 1)
        np.testing.assert_allclose(np.asarray(b), float(size - 1))

        a2a = hvd.alltoall(jnp.arange(size * 2, dtype=jnp.float32)
                           + 100.0 * rank, splits=[2] * size)
        exp = np.concatenate(
            [np.arange(rank * 2, rank * 2 + 2, dtype=np.float32) + 100 * rk
             for rk in range(size)])
        np.testing.assert_allclose(np.asarray(a2a), exp)

        rs = hvd.reducescatter(jnp.full((size * 2, 2), float(rank + 1)),
                               op=hvd.Sum)
        np.testing.assert_allclose(np.asarray(rs), sum(range(1, size + 1)))

        # bfloat16 path (TPU's native dtype)
        bf = hvd.allreduce(jnp.full(8, float(rank), jnp.bfloat16), op=hvd.Sum)
        np.testing.assert_allclose(np.asarray(bf.astype(jnp.float32)),
                                   sum(range(size)))

        hvd.barrier()
        return "ok"
    finally:
        hvd.shutdown()


@pytest.mark.parametrize("size", [2, 4])
def test_jax_ops(size):
    assert run_ranks(_worker_ops, size) == ["ok"] * size


def _worker_broadcast_helpers(rank, size):
    import jax.numpy as jnp
    import horovod_tpu.jax as hvd

    hvd.init()
    try:
        # broadcast_parameters on a nested pytree
        params = {"dense": {"w": jnp.full((3, 3), float(rank)),
                            "b": jnp.full(3, float(rank))},
                  "scale": jnp.asarray(float(rank))}
        params = hvd.broadcast_parameters(params, root_rank=0)
        for leaf in (params["dense"]["w"], params["dense"]["b"],
                     params["scale"]):
            np.testing.assert_allclose(np.asarray(leaf), 0.0)

        # broadcast_object / allgather_object
        obj = hvd.broadcast_object({"lr": 0.1 * (rank + 1), "tag": rank},
                                   root_rank=1)
        assert obj == {"lr": 0.2, "tag": 1}

        objs = hvd.allgather_object(("rank", rank))
        assert objs == [("rank", rk) for rk in range(size)]
        return "ok"
    finally:
        hvd.shutdown()


def test_broadcast_helpers():
    assert run_ranks(_worker_broadcast_helpers, 2) == ["ok"] * 2


def _worker_distributed_optimizer(rank, size):
    import jax
    import jax.numpy as jnp
    import optax
    import horovod_tpu.jax as hvd

    hvd.init()
    try:
        # Each rank computes a different local grad; after the distributed
        # update every rank must hold identical params equal to the
        # all-rank-averaged-gradient update.
        params = {"w": jnp.ones(4), "b": jnp.zeros(2)}
        tx = hvd.DistributedOptimizer(optax.sgd(0.5), op=hvd.Average)
        state = tx.init(params)

        grads = {"w": jnp.full(4, float(rank + 1)),
                 "b": jnp.full(2, float(rank))}
        updates, state = tx.update(grads, state, params)
        params = optax.apply_updates(params, updates)

        gw = np.mean([rk + 1 for rk in range(size)])
        gb = np.mean([float(rk) for rk in range(size)])
        np.testing.assert_allclose(np.asarray(params["w"]), 1 - 0.5 * gw)
        np.testing.assert_allclose(np.asarray(params["b"]), -0.5 * gb,
                                   rtol=1e-6)

        # fp16 compression path
        tx2 = hvd.DistributedOptimizer(optax.sgd(1.0),
                                       compression=hvd.Compression.fp16)
        s2 = tx2.init(params)
        up2, s2 = tx2.update({"w": jnp.full(4, float(rank)),
                              "b": jnp.zeros(2)}, s2, params)
        assert jax.tree.leaves(up2)[1].dtype == jnp.float32
        return "ok"
    finally:
        hvd.shutdown()


def test_distributed_optimizer():
    assert run_ranks(_worker_distributed_optimizer, 2) == ["ok"] * 2


def _worker_backward_passes(rank, size):
    import jax.numpy as jnp
    import optax
    import horovod_tpu.jax as hvd

    hvd.init()
    try:
        params = {"w": jnp.zeros(3)}
        tx = hvd.DistributedOptimizer(optax.sgd(1.0),
                                      backward_passes_per_step=2)
        state = tx.init(params)
        # First pass: accumulate only, params unchanged.
        up, state = tx.update({"w": jnp.full(3, 2.0 * (rank + 1))}, state,
                              params)
        params = optax.apply_updates(params, up)
        np.testing.assert_allclose(np.asarray(params["w"]), 0.0)
        # Second pass: allreduce of the local mean, then apply.
        up, state = tx.update({"w": jnp.full(3, 4.0 * (rank + 1))}, state,
                              params)
        params = optax.apply_updates(params, up)
        local_means = [(2.0 * (rk + 1) + 4.0 * (rk + 1)) / 2
                       for rk in range(size)]
        np.testing.assert_allclose(np.asarray(params["w"]),
                                   -np.mean(local_means))
        return "ok"
    finally:
        hvd.shutdown()


def test_backward_passes_per_step():
    assert run_ranks(_worker_backward_passes, 2) == ["ok"] * 2
