"""The HOROVOD_JIT_FUSION knob is a SCHEDULE knob, never a numerics
knob (docs/fusion.md): fused and unfused lanes must produce
bit-identical loss trajectories and parameters.

Two lanes, both pinned:

- the jit lane — ``make_split_train_step(zero=...)`` one-program fused
  step (``parallel.fusion.make_fused_zero_programs``, reordered jaxpr)
  vs the unfused split step, under the vmap(axis_name) emulation;
- the host lane — ``hvd.make_fused_train_step`` over real OS ranks on
  the loopback ring: segmented backward + interleaved eager
  reduce-scatters + next-step-deferred allgathers vs the
  bulk-synchronous schedule.
"""

import numpy as np
import pytest

from tests.utils_mp import run_ranks

pytestmark = pytest.mark.quick

_SHAPES = {"w1": (16, 32), "w2": (32, 16), "b2": (16,), "w3": (16, 4)}


def _bits(a):
    return np.asarray(a).view(np.uint32)


def _mlp_setup():
    import jax
    import jax.numpy as jnp

    def loss_fn(params, batch):
        h = jnp.tanh(batch["x"] @ params["w1"])
        h = jnp.tanh(h @ params["w2"] + params["b2"])
        logits = h @ params["w3"]
        return jnp.mean((logits - batch["y"]) ** 2)

    keys = jax.random.split(jax.random.PRNGKey(0), len(_SHAPES))
    params = {name: (jnp.zeros(shape) if len(shape) == 1 else
                     jax.random.normal(k, shape) * 0.1)
              for k, (name, shape) in zip(keys, _SHAPES.items())}
    batch = {"x": jax.random.normal(jax.random.PRNGKey(7), (8, 16)),
             "y": jax.random.normal(jax.random.PRNGKey(8), (8, 4))}
    return loss_fn, params, batch


def _worker_host_lane(rank, size):
    import jax
    import jax.numpy as jnp

    import horovod_tpu.jax as hvd
    from horovod_tpu.parallel import fusion

    hvd.init()
    try:
        loss_fn, params, batch = _mlp_setup()
        copy = lambda t: jax.tree.map(jnp.array, t)  # noqa: E731
        steps = 4
        init, step, finish = hvd.make_fused_train_step(
            loss_fn, 1e-2, bucket_bytes=2048)

        def run(fused):
            fusion.set_jit_fusion(fused)
            carry = init(copy(params))
            losses = []
            for i in range(steps):
                loss, carry = step(carry, batch)
                losses.append(np.asarray(loss))
                # Fused: params lag one step (allgathers in flight);
                # unfused: materialized before step returns.
                assert (carry[2] is not None) == fused
            p, carry = finish(carry)
            assert carry[2] is None
            return losses, p

        losses_f, params_f = run(True)
        losses_u, params_u = run(False)
        for lf, lu in zip(losses_f, losses_u):
            assert np.array_equal(_bits(lf), _bits(lu)), (lf, lu)
        for k in params:
            assert np.array_equal(_bits(params_f[k]),
                                  _bits(params_u[k])), k
        return [float(x) for x in losses_f]
    finally:
        from horovod_tpu.parallel.fusion import set_jit_fusion

        set_jit_fusion(None)
        hvd.shutdown()


def test_host_lane_fused_matches_unfused_bitwise():
    results = run_ranks(_worker_host_lane, 2, timeout=240)
    # Replicated params + identical batches: every rank must see the
    # identical trajectory.
    assert all(r == results[0] for r in results)


@pytest.mark.parametrize("microbatches", [1, 2])
def test_jit_lane_fused_matches_unfused_bitwise(microbatches):
    import jax
    import jax.numpy as jnp

    from horovod_tpu.parallel import fusion
    from horovod_tpu.parallel.precision import fused_adam
    from horovod_tpu.parallel.train_step import make_split_train_step
    from horovod_tpu.parallel.zero import ZeroConfig

    loss_fn, params, batch = _mlp_setup()
    copy = lambda t: jax.tree.map(jnp.array, t)  # noqa: E731
    zero = ZeroConfig(size=4, bucket_bytes=1024)

    def run(fused):
        fusion.set_jit_fusion(fused)
        try:
            ts = make_split_train_step(loss_fn, fused_adam(1e-2),
                                       zero=zero,
                                       microbatches=microbatches)
            carry = ts.init(copy(params))
            losses = []
            for _ in range(4):
                loss, carry = ts.step(carry, batch)
                losses.append(np.asarray(loss))
            return losses, carry[0]
        finally:
            fusion.set_jit_fusion(None)

    losses_f, params_f = run(True)
    losses_u, params_u = run(False)
    for lf, lu in zip(losses_f, losses_u):
        assert np.array_equal(_bits(lf), _bits(lu)), (lf, lu)
    for leaf_f, leaf_u in zip(jax.tree.leaves(params_f),
                              jax.tree.leaves(params_u)):
        assert np.array_equal(_bits(leaf_f), _bits(leaf_u))
    # The knob actually changed the traced schedule: the fused lane is
    # ONE program whose jaxpr carries the reduce-scatters interleaved.
    assert losses_f[0] == losses_u[0]
