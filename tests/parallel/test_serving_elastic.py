"""Elastic serving: prefill/decode disaggregation over the host ring,
and the chaos acceptance — SIGKILL a decode rank mid-trace, every
admitted request completes on the survivors with token-identical
greedy output (docs/serving.md "Elastic behavior").

Two-rank worlds: rank 0 frontend+prefill, rank 1 decode; int8 paged KV
blocks ship over the CRC-framed chunked host ring (one alltoall per
assignment round). The kill test's recovery path is the full r12/r14
machinery: typed ``HorovodPeerFailureError`` at the round boundary ->
in-place 1-rank re-formation -> orphaned requests re-queued and decoded
by the survivor — whose replay must be indistinguishable from a world
where the victim never existed.

Workers live in this importable module (spawn must re-import them —
the r11 gotcha).
"""

import os
import signal

import numpy as np
import pytest

from tests.parallel.test_chaos_matrix import run_chaos

pytestmark = pytest.mark.quick

_N_REQUESTS = 8
_RPS = 120.0
_TRACE_SEED = 9
_KILL_ROUND = 5


def _setup(quantized):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from horovod_tpu.models import LlamaConfig, llama_init
    from horovod_tpu.serving.scheduler import poisson_trace

    cfg = LlamaConfig.tiny(dtype="float32", n_layers=2)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    trace = poisson_trace(_N_REQUESTS, _RPS, seed=_TRACE_SEED,
                          prompt_len=(4, 10), max_new=(3, 7),
                          vocab_size=cfg.vocab_size)
    return cfg, params, trace


def _make_loop(cfg, params, trace, hook=None, quantized=True):
    from horovod_tpu.serving.service import ServingLoop

    return ServingLoop(params, cfg, trace, block_size=8, n_blocks=64,
                       max_batch=4, max_context=32,
                       quantized=quantized, steps_per_round=2,
                       prefill_per_round=2, round_hook=hook)


def _verify_all(report, cfg, params, trace):
    import jax

    from horovod_tpu.models import llama_generate

    assert report["served"] == len(trace), (
        report["served"], len(trace))
    for req in trace:
        ref = np.asarray(llama_generate(
            params, jax.numpy.asarray(req.prompt[None, :]), cfg,
            req.max_new_tokens))[0]
        got = report["completed"][req.rid]
        np.testing.assert_array_equal(got, ref, err_msg=f"rid {req.rid}")


def _disagg_worker(rank, size):
    """No-fault 2-rank disaggregation: every request decodes REMOTELY
    (rank 1) off int8 blocks shipped from rank 0's prefill, and the
    output is still llama_generate's exact tokens (f32 reference —
    quantization must not leak into the greedy path's determinism, see
    test_serving.py's quantized-parity note; this seed decodes
    identically, pinning the shipped-vs-local path equivalence)."""
    from horovod_tpu.common import elastic as hvd_elastic
    from horovod_tpu.common.basics import HorovodBasics

    b = HorovodBasics()
    hvd_elastic.init()
    cfg, params, trace = _setup(quantized=False)
    loop = _make_loop(cfg, params, trace, quantized=False)
    report = loop.run()
    if b.rank() == 0:
        assert report["faults_survived"] == 0, report
        _verify_all(report, cfg, params, trace)
        # Disaggregation really happened: the frontend never decoded.
        assert loop.engine.steps == 0, loop.engine.steps
        # r19 rolling-latency signals live on the frontend.
        sig = loop.signals()
        assert sig["requests_served"] == len(trace), sig
        assert sig["serving_p99_ms"] >= sig["serving_p50_ms"] > 0, sig
    else:
        assert report["served"] > 0, "decode rank served nothing"
    # Request-tracing dump for the cross-rank stitch assertion in the
    # test driver (every rank contributes its view of each rid).
    dump_dir = os.environ.get("REQTRACE_DUMPS")
    if dump_dir:
        from horovod_tpu.telemetry import critpath

        critpath.write_event_dump(
            os.path.join(dump_dir, f"blackbox-rank{b.rank()}.jsonl"),
            b.rank(), b.size(), b.events_drain())
    b.shutdown()
    return "ok"


def test_two_rank_disaggregated_poisson_serves_all(tmp_path):
    dump_dir = str(tmp_path / "reqtrace")
    os.makedirs(dump_dir)
    results = run_chaos(_disagg_worker, 2, victims=(), timeout=240,
                        env={"HOROVOD_WIRE_TIMEOUT_MS": "4000",
                             "HOROVOD_EVENTS": "1",
                             "REQTRACE_DUMPS": dump_dir},
                        expect_sigkill=False)
    assert results == {0: "ok", 1: "ok"}
    # Cross-rank trace stitching on a REAL disaggregated run: every
    # rid's chain reassembles from BOTH ranks' dumps on the anchor-pair
    # wall axis — the frontend contributes queued/prefill/kv_ship, the
    # decode rank contributes decode_wait/decode_active, the chain is
    # gap-free with per-phase sums reconciling exactly, and no request
    # carries a fault_requeue span (nothing faulted).
    from horovod_tpu.telemetry import reqtrace

    chains = reqtrace.stitch(dump_dir)
    assert len(chains) == _N_REQUESTS, sorted(chains)
    for rid, c in sorted(chains.items()):
        assert c["complete"], rid
        assert c["ranks"] == [0, 1], (rid, c["ranks"])
        assert reqtrace.chain_gaps(c) == [], rid
        assert sum(c["phase_us"].values()) == c["wall_us"], rid
        assert "fault_requeue" not in c["phase_us"], (rid, c["phase_us"])
        span_ranks = {s["phase"]: s["rank"] for s in c["spans"]}
        assert span_ranks.get("kv_ship") == 0, (rid, span_ranks)
        assert any(s["phase"] == "decode_active" and s["rank"] == 1
                   for s in c["spans"]), (rid, c["spans"])


def _kill_worker(rank, size):
    from horovod_tpu.common import elastic as hvd_elastic
    from horovod_tpu.common.basics import HorovodBasics

    b = HorovodBasics()
    hvd_elastic.init()
    cfg, params, trace = _setup(quantized=True)

    def hook(loop, round_idx):
        if rank == 1 and round_idx == _KILL_ROUND:
            os.kill(os.getpid(), signal.SIGKILL)

    loop = _make_loop(cfg, params, trace, hook=hook, quantized=True)
    report = loop.run()
    assert b.rank() == 0  # the only survivor reports
    assert report["faults_survived"] >= 1, report
    assert b.size() == 1, b.size()
    _verify_all(report, cfg, params, trace)
    # The survivor genuinely took over decoding.
    assert loop.engine.steps > 0
    el = b.metrics_snapshot()["elastic"]
    assert el["faults_detected"] >= 1, el
    b.shutdown()
    return "ok"


def test_kill_decode_rank_midtrace_completes_on_survivor():
    """The ISSUE acceptance chaos case: SIGKILL the decode rank with
    admitted sequences in flight; the surviving frontend re-forms a
    1-rank world, re-queues the orphans, and serves the WHOLE trace
    token-identically to llama_generate."""
    results = run_chaos(_kill_worker, 2, victims={1}, timeout=240,
                        env={"HOROVOD_WIRE_TIMEOUT_MS": "2000"})
    assert results == {0: "ok"}
