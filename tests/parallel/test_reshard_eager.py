"""Real-wire redistribute + cross-plane hierarchical eager lane
(docs/redistribute.md), over 4 spawned ranks on TCP loopback.

- ``execute_plan`` moves checkpoint-style row shards through the eager
  host collectives; every rank's content must match the numpy
  simulator, and the MEASURED wire bytes must equal the plan's
  prediction exactly (uncompressed — the <1% smoke criterion is the
  compressed/fused superset, here it is byte-exact).
- ``HOROVOD_CROSS_PLANE=hier`` on an emulated 2-slice x 2-rank layout:
  eager allreduce stays exact (integer-valued fills — association-free)
  while only the predicted 1/local_size share of bytes crosses the
  cross plane; ``auto`` picks the same decomposition by itself on an
  eligible layout; ``ring`` pins it off.

Workers live in this importable module (spawn re-imports them — the
r11 gotcha).
"""

import numpy as np
import pytest

from tests.utils_mp import run_ranks

pytestmark = pytest.mark.quick

_ROWS = 29
_COLS = 3


def _init():
    from horovod_tpu.common import basics

    b = basics.HorovodBasics()
    b.init()
    return b


def _full():
    return np.arange(_ROWS * _COLS, dtype=np.float32).reshape(
        _ROWS, _COLS)


def _wire_tx(b):
    return b.metrics_snapshot()["wire"]["tx_bytes"]


def _worker_reshard_chain(rank, size):
    from horovod_tpu.parallel.reshard import (
        Layout,
        execute_plan,
        plan_redistribute,
        simulate_plan,
    )

    b = _init()
    try:
        full = _full()
        src = Layout.sharded(_ROWS, size)
        uneven = Layout.from_rows([(0, 2), (2, 11), (13, 7), (20, 9)])
        rep = Layout.replicated(size)
        local = full[src.rows[rank][0]:src.rows[rank][0] +
                     src.rows[rank][1]]
        sim_locals = [full[s:s + c] for s, c in src.rows]

        chain = [(src, uneven, "a"), (uneven, rep, "b"), (rep, src, "c")]
        for src_l, dst_l, tag in chain:
            plan = plan_redistribute(full.shape, np.float32, src_l, dst_l)
            before = _wire_tx(b)
            out = execute_plan(plan, local, name=f"rs.{tag}")
            moved = _wire_tx(b) - before
            assert moved == plan.wire_tx_bytes(rank), \
                (tag, moved, plan.wire_tx_bytes(rank))
            sim_locals = simulate_plan(plan, sim_locals)
            np.testing.assert_array_equal(out, sim_locals[rank])
            local = out
        # Round-tripped back to the original shard.
        s, c = src.rows[rank]
        np.testing.assert_array_equal(local, full[s:s + c])

        # partial -> sharded: the gradient-shard path.
        addend = np.full((_ROWS, _COLS), float(rank + 1), np.float32)
        plan = plan_redistribute(full.shape, np.float32,
                                 Layout.partial(size), src)
        out = execute_plan(plan, addend, name="rs.part")
        np.testing.assert_array_equal(
            out, np.full((c, _COLS), float(sum(range(1, size + 1)))))
        return "ok"
    finally:
        b.shutdown()


def test_reshard_chain_bytes_reconcile_exactly():
    assert run_ranks(_worker_reshard_chain, 4,
                     timeout=180) == ["ok"] * 4


def _slice_env(rank, local_size):
    return {
        "HOROVOD_LOCAL_RANK": str(rank % local_size),
        "HOROVOD_LOCAL_SIZE": str(local_size),
        "HOROVOD_CROSS_RANK": str(rank // local_size),
        "HOROVOD_CROSS_SIZE": str(4 // local_size),
    }


def _worker_hier_cross_plane(rank, size):
    import os

    os.environ.update(_slice_env(rank, 2))
    b = _init()
    try:
        from horovod_tpu.common import eager_ops as ops
        from horovod_tpu.parallel.reshard import hier_wire_bytes

        assert b.cross_plane() == "hier"
        assert b.hier_split() == 2
        count = 4096 + 37
        vals = np.arange(count, dtype=np.float32) % 7 - 3  # exact ints
        warm = ops.allreduce_async(vals * (rank + 1), "warm").synchronize()
        np.testing.assert_array_equal(warm, vals * 10)

        snap0 = b.metrics_snapshot()["wire"]
        out = ops.allreduce_async(vals * (rank + 1), "h").synchronize()
        snap1 = b.metrics_snapshot()["wire"]
        np.testing.assert_array_equal(out, vals * 10)  # exact: sum 1..4
        pred = hier_wire_bytes(count, 4, size, 2, rank)
        assert snap1["cross_tx_bytes"] - snap0["cross_tx_bytes"] == \
            pred["cross"]
        assert snap1["tx_bytes"] - snap0["tx_bytes"] == \
            pred["cross"] + pred["intra"]
        return "ok"
    finally:
        b.shutdown()


def test_hier_mode_exact_with_predicted_cross_bytes():
    env = {"HOROVOD_CROSS_PLANE": "hier"}
    assert run_ranks(_worker_hier_cross_plane, 4, env=env,
                     timeout=180) == ["ok"] * 4


def _worker_auto_picks_hier(rank, size):
    import os

    os.environ.update(_slice_env(rank, 2))
    b = _init()
    try:
        from horovod_tpu.common import eager_ops as ops

        # auto on an eligible 2-slice layout = hierarchical, by itself.
        assert b.cross_plane() == "auto"
        assert b.hier_split() == 2
        x = np.full(9, float(rank), np.float64)
        out = ops.allreduce_async(x, "a").synchronize()
        np.testing.assert_array_equal(out, np.full(9, 6.0))
        snap = b.metrics_snapshot()["wire"]
        assert snap["cross_tx_bytes"] > 0
        return "ok"
    finally:
        b.shutdown()


def test_auto_mode_picks_hier_on_eligible_layout():
    assert run_ranks(_worker_auto_picks_hier, 4,
                     timeout=180) == ["ok"] * 4


def _worker_ring_mode_stays_flat(rank, size):
    import os

    os.environ.update(_slice_env(rank, 2))
    b = _init()
    try:
        from horovod_tpu.common import eager_ops as ops

        assert b.cross_plane() == "ring"
        assert b.hier_split() == 0
        x = np.full(9, float(rank), np.float64)
        out = ops.allreduce_async(x, "r").synchronize()
        np.testing.assert_array_equal(out, np.full(9, 6.0))
        assert b.metrics_snapshot()["wire"]["cross_tx_bytes"] == 0
        return "ok"
    finally:
        b.shutdown()


def test_ring_mode_pins_cross_plane_off():
    env = {"HOROVOD_CROSS_PLANE": "ring"}
    assert run_ranks(_worker_ring_mode_stays_flat, 4, env=env,
                     timeout=180) == ["ok"] * 4
