"""TF/Keras elastic state, object collectives, and optimizer dispatch.

Reference analog: test/parallel/test_tensorflow.py (broadcast_object,
allgather_object) and the elastic state unit tests (SURVEY.md §4) —
distributed correctness via analytic closed forms on 2 local ranks.
"""

import numpy as np
import pytest

from tests.utils_mp import run_ranks

_TF_ENV = {"TF_CPP_MIN_LOG_LEVEL": "3", "CUDA_VISIBLE_DEVICES": ""}


def _worker_objects(rank, size):
    import horovod_tpu.tensorflow as hvd

    hvd.init()
    try:
        obj = hvd.broadcast_object({"lr": 0.1 * (rank + 1), "rank": rank},
                                   root_rank=1)
        assert obj == {"lr": 0.2, "rank": 1}

        fn = hvd.broadcast_object_fn(root_rank=0)
        assert fn(["a", rank]) == ["a", 0]

        gathered = hvd.allgather_object({"rank": rank, "pad": "x" * rank})
        assert [g["rank"] for g in gathered] == list(range(size))
        return "ok"
    finally:
        hvd.shutdown()


@pytest.mark.parametrize("size", [2])
def test_tf_object_collectives(size):
    assert run_ranks(_worker_objects, size, env=_TF_ENV, timeout=180) \
        == ["ok"] * size


def _worker_tf_state(rank, size):
    import tensorflow as tf
    import horovod_tpu.tensorflow as hvd

    hvd.init()
    try:
        v = tf.Variable(tf.fill([3], float(rank)))
        state = hvd.elastic.TensorFlowState(variables=[v], step=rank)

        # sync(): every rank adopts rank 0's snapshot.
        state.sync()
        np.testing.assert_allclose(v.numpy(), 0.0)
        assert state.step == 0

        # commit/restore round-trip.
        v.assign(tf.fill([3], 7.0))
        state.step = 11
        state.commit()
        v.assign(tf.fill([3], -1.0))
        state.step = 99
        state.restore()
        np.testing.assert_allclose(v.numpy(), 7.0)
        assert state.step == 11
        return "ok"
    finally:
        hvd.shutdown()


@pytest.mark.parametrize("size", [2])
def test_tf_elastic_state(size):
    assert run_ranks(_worker_tf_state, size, env=_TF_ENV, timeout=180) \
        == ["ok"] * size


def _worker_keras_state(rank, size):
    import tensorflow as tf
    import horovod_tpu.tensorflow.keras as hvd

    hvd.init()
    try:
        tf.keras.utils.set_random_seed(1000 + rank)  # diverge per rank
        model = tf.keras.Sequential(
            [tf.keras.layers.Dense(4, input_shape=(3,)),
             tf.keras.layers.Dense(1)])
        opt = hvd.DistributedOptimizer(tf.keras.optimizers.SGD(0.1))
        model.compile(optimizer=opt, loss="mse")

        state = hvd.elastic.KerasState(model, batch=0, epoch=0)
        state.sync()
        # After sync all ranks hold identical (rank 0's) weights.
        digest = float(sum(np.sum(w) for w in model.get_weights()))
        all_digests = hvd.allgather_object(digest)
        assert all(abs(d - all_digests[0]) < 1e-6 for d in all_digests)

        x = np.random.RandomState(0).randn(8, 3).astype("float32")
        y = np.random.RandomState(1).randn(8, 1).astype("float32")
        cbs = [hvd.elastic.CommitStateCallback(state, batches_per_commit=2),
               hvd.elastic.UpdateBatchStateCallback(state),
               hvd.elastic.UpdateEpochStateCallback(state)]
        model.fit(x, y, batch_size=4, epochs=2, verbose=0, callbacks=cbs,
                  initial_epoch=state.epoch)
        assert state.epoch == 2
        assert state.batch == 0  # reset at epoch end
        return "ok"
    finally:
        hvd.shutdown()


@pytest.mark.parametrize("size", [2])
def test_keras_elastic_state_and_callbacks(size):
    assert run_ranks(_worker_keras_state, size, env=_TF_ENV, timeout=240) \
        == ["ok"] * size


def _worker_tf_distopt_dispatch(rank, size):
    import tensorflow as tf
    import horovod_tpu.tensorflow as hvd

    hvd.init()
    try:
        # keras optimizer path: returns a genuine keras optimizer subclass.
        opt = hvd.DistributedOptimizer(tf.keras.optimizers.Adam(1e-3))
        assert isinstance(opt, tf.keras.optimizers.Adam)

        # Apply rank-dependent grads; vars must end identical (averaged).
        v = tf.Variable(tf.zeros([4]))
        opt.apply_gradients([(tf.fill([4], float(rank + 1)), v)])
        gathered = hvd.allgather_object(v.numpy().tolist())
        assert gathered[0] == gathered[-1]
        return "ok"
    finally:
        hvd.shutdown()


@pytest.mark.parametrize("size", [2])
def test_tf_distributed_optimizer_dispatch(size):
    assert run_ranks(_worker_tf_distopt_dispatch, size, env=_TF_ENV,
                     timeout=180) == ["ok"] * size


def _worker_v1_optimizer(rank, size):
    import tensorflow as tf
    import horovod_tpu.tensorflow as hvd

    hvd.init()
    try:
        opt = hvd.DistributedOptimizer(
            tf.compat.v1.train.GradientDescentOptimizer(0.5))
        assert isinstance(opt, tf.compat.v1.train.Optimizer)

        # loss grad = rank+1 on each rank → averaged grad is identical,
        # so after one minimize() the variable matches on every rank.
        v = tf.Variable([2.0])
        opt.minimize(lambda: v * float(rank + 1), var_list=[v])
        expected = 2.0 - 0.5 * (sum(range(1, size + 1)) / size)
        np.testing.assert_allclose(v.numpy(), [expected], rtol=1e-6)

        try:
            hvd.DistributedOptimizer(
                tf.compat.v1.train.GradientDescentOptimizer(0.5),
                backward_passes_per_step=4)
            raise AssertionError("expected ValueError")
        except ValueError:
            pass
        return "ok"
    finally:
        hvd.shutdown()


@pytest.mark.parametrize("size", [2])
def test_tf_v1_distributed_optimizer(size):
    assert run_ranks(_worker_v1_optimizer, size, env=_TF_ENV,
                     timeout=180) == ["ok"] * size
