"""Shared pytest config.

Mirrors the reference's test substrate choice (SURVEY.md §4): everything is
testable with a handful of local CPU processes / virtual devices. We force
JAX onto the CPU platform with 8 virtual devices so mesh/sharding tests
(`jax.sharding.Mesh` over dp/tp/sp axes) run without TPU hardware — the same
code path the driver's `dryrun_multichip` validates.
"""

import os
import subprocess
import sys

# Must be set before jax initializes a backend. Forced (not setdefault):
# the driver environment exports JAX_PLATFORMS=axon (one real TPU chip)
# and a sitecustomize re-registers the axon plugin at interpreter start,
# so we must also override at the jax.config level below — tests want 8
# virtual CPU devices.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (after the env setup above, before any backend use)

jax.config.update("jax_platforms", "cpu")

# hvdlint fixtures (hvdlint / hvdlint_shipped) for every test file —
# see horovod_tpu/analysis/pytest_plugin.py.
pytest_plugins = ("horovod_tpu.analysis.pytest_plugin",)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ensure_core_built():
    """Build the native core (csrc/ -> horovod_tpu/lib/) if missing/stale."""
    subprocess.run(
        ["make", "-s", "core"], cwd=REPO_ROOT, check=True,
        stdout=subprocess.DEVNULL,
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "quick: sub-5-minute CI lane — core runtime, one multi-rank "
        "file, one elastic path (make test-quick)")
    config.addinivalue_line(
        "markers",
        "loadflaky: timing-sensitive under a loaded box (multi-process "
        "steady-state assertions); runs with widened slack, and a busy "
        "CI shard may deselect with -m 'not loadflaky'")
    config.addinivalue_line(
        "markers",
        "slow: heavyweight lanes (e.g. the 256-rank simulated world) "
        "excluded from the tier-1 budget via -m 'not slow'; covered by "
        "the full suite and bench.py --scale")
    _ensure_core_built()


def pytest_collection_modifyitems(config, items):
    # Keep deterministic ordering: single-process unit tests first.
    items.sort(key=lambda it: ("parallel" in str(it.fspath), str(it.fspath)))


sys.path.insert(0, REPO_ROOT)
