"""Simulated large-world harness (docs/scale.md): thread-per-rank
controllers over socketpairs run the REAL negotiation protocol — flat
star and HOROVOD_CONTROL_TREE tree gather — plus the real ring
allreduce, in one process. Pins:

- negotiation + allreduce completes and verifies at both small and
  large worlds, both gather modes (256 ranks = the north-star size);
- the per-phase control-plane profile (gather/broadcast/rendezvous
  histograms) comes out of every run — the scaling-curve plumbing;
- the tree gather beats the flat star's GROWTH: sub-linear vs the
  sequential baseline between 32 and 128 ranks (ratioed, so a loaded
  CI box shifts both sides together);
- an injected kill surfaces typed PeerFailure attribution naming the
  dead rank on the survivors, flat and tree.
"""

import pytest

from horovod_tpu.common.basics import HorovodBasics

pytestmark = pytest.mark.quick

_b = HorovodBasics()


def _run(ranks, **kw):
    return _b.simworld_run(ranks, **kw)


def test_small_world_flat_and_tree_complete_and_verify():
    for fanout in (0, 2):
        rep = _run(8, tree_fanout=fanout, elems=512, rounds=3)
        assert rep["rc"] == 0 and rep["allreduce_ok"], rep
        assert rep["round_us"]["count"] == 3, rep
        for phase in ("rendezvous", "gather", "broadcast"):
            assert rep["phases"][phase]["count"] > 0, (fanout, phase)
        # Steady state: rounds 2+ ride the response-cache bit path —
        # the gather still records once per cycle.
        assert rep["phases"]["gather"]["count"] == 3, rep


@pytest.mark.slow
def test_256_rank_world_completes_negotiation_and_allreduce():
    # The acceptance world size (ISSUE r16 / ROADMAP item 5). ~10 s.
    for fanout in (0, 8):
        rep = _run(256, tree_fanout=fanout, elems=64, rounds=2)
        assert rep["rc"] == 0 and rep["allreduce_ok"], (fanout, rep)
        assert rep["data_mesh"] == "ring", rep  # fd-budget topology


def test_tree_gather_grows_sublinearly_vs_flat():
    """The tentpole claim, pinned at CI-safe sizes: growing the world
    32 -> 128 (4x) must grow the tree gather's mean latency by LESS
    than it grows the flat star's. Ratio-of-ratios, so machine speed
    and load cancel; 1.35x headroom on top keeps a noisy box green
    while still failing if the tree gather ever degenerates to
    sequential behavior."""

    def gather_mean(ranks, fanout):
        rep = _run(ranks, tree_fanout=fanout, elems=64, rounds=6)
        assert rep["rc"] == 0, rep
        h = rep["phases"]["gather"]
        return h["sum_us"] / h["count"]

    flat_growth = gather_mean(128, 0) / max(gather_mean(32, 0), 1.0)
    tree_growth = gather_mean(128, 8) / max(gather_mean(32, 8), 1.0)
    assert tree_growth < flat_growth * 1.35, (
        f"tree gather grew {tree_growth:.2f}x from 32->128 ranks vs "
        f"flat {flat_growth:.2f}x — not sub-linear vs the baseline")


def test_injected_kill_names_dead_rank_flat_and_tree():
    for fanout in (0, 8):
        rep = _run(64, tree_fanout=fanout, elems=64, rounds=3,
                   kill_rank=37, kill_round=1)
        assert rep["rc"] == 0, rep
        fault = rep["fault"]
        assert fault["typed_faults"] == 63, (fanout, fault)
        assert fault["named_rank"] == 37, (fanout, fault)


def test_refuses_to_run_next_to_live_core_and_bad_args():
    # Bad arguments are rejected outright (rc -1 -> RuntimeError).
    with pytest.raises(RuntimeError, match="bad arguments"):
        _run(1)
    with pytest.raises(RuntimeError, match="bad arguments"):
        _run(8, kill_rank=3)  # kill without a kill_round
