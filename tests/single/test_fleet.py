"""Fleet observatory (docs/fleet.md): rank-seconds ledgers that
reconcile to the microsecond on hand-built and simworld-synthesized
dumps, the SLO grammar/drift/recording contract, breach folding, the
256-rank aggregation latency bar, the live observatory's endpoint
derivation and sick-rank tolerance, and the report.py --fleet CLI. No
core, no processes: everything here is pure interval math plus the
simworld dump synthesizer (r16 gotcha 1)."""

import json
import os
import time

import pytest

import bench
from horovod_tpu.simworld import harness
from horovod_tpu.telemetry import (
    critpath,
    fleet,
    perfwatch,
    postmortem,
    report,
    slo,
)

pytestmark = pytest.mark.quick

_UNIX0 = 1_700_000_000_000_000


def _write_dump(path, rank, events, steady0=0, unix0=_UNIX0, size=2):
    header = {"kind": "blackbox_header", "rank": rank, "size": size,
              "epoch": 0, "unix_us": unix0, "steady_us": steady0,
              "fault": {}}
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        f.write(json.dumps(header) + "\n")
        for seq, ev in enumerate(events):
            f.write(json.dumps({"seq": seq, **ev}) + "\n")
    return path


def _at(wall, steady0=0, unix0=_UNIX0):
    return wall - unix0 + steady0


def _known_events():
    """One rank, two steps, every evidence class at KNOWN offsets:
    step 1 wall 0..100k carries a request (queued 10k..20k, prefill
    20k..30k), a wire span 40k..60k whose wait block covers only
    50k..60k (exposed = 10k), and a 20k retry window 70k..90k; steps
    are separated by a 10k idle gap, step 2 wall 110k..150k is pure
    compute."""
    return [
        {"ts_us": _at(0), "type": "step_begin", "step": 1},
        {"ts_us": _at(10_000), "type": "request", "phase": 0, "rid": 7,
         "aux": 0, "phase_name": "queued"},
        {"ts_us": _at(20_000), "type": "request", "phase": 1, "rid": 7,
         "aux": 0, "phase_name": "prefill"},
        {"ts_us": _at(30_000), "type": "request", "phase": 7, "rid": 7,
         "aux": 0, "phase_name": "done"},
        {"ts_us": _at(60_000), "type": "wire_span", "plane": 0,
         "dur_us": 20_000, "tx_bytes": 1, "rx_bytes": 1},
        {"ts_us": _at(60_000), "type": "wait", "dur_us": 10_000},
        {"ts_us": _at(90_000), "type": "retry_window", "attempt": 1,
         "window_ms": 20},
        {"ts_us": _at(100_000), "type": "step_end", "step": 1,
         "dur_us": 100_000},
        {"ts_us": _at(110_000), "type": "step_begin", "step": 2},
        {"ts_us": _at(150_000), "type": "step_end", "step": 2,
         "dur_us": 40_000},
    ]


# ---- ledger reconciliation --------------------------------------------


def test_ledger_reconciles_known_dump_to_the_microsecond(tmp_path):
    path = _write_dump(str(tmp_path / "blackbox-rank0.jsonl"), 0,
                       _known_events())
    dump = postmortem.load_blackbox(path)[-1]
    l = fleet.ledger_from_dump(dump)
    b = l["buckets"]
    assert l["window_us"] == 150_000
    # The r17 standard: exact integer reconciliation, zero remainder.
    assert sum(b.values()) == l["window_us"]
    assert b == {
        "compute": 90_000,        # step windows minus claimed evidence
        "exposed_wire": 10_000,   # span ∩ wait, NOT the 20k raw span
        "negotiation": 0,
        "serving_prefill": 10_000,
        "serving_decode": 0,
        "serving_queued": 10_000,
        "stall": 20_000,
        "idle": 10_000,           # the inter-step gap 100k..110k
        "unattributed": 0,
    }, b
    # useful = compute + exposed + prefill = 110k of 150k.
    assert l["utilization"] == round(110_000 / 150_000, 6)


def test_explicit_window_books_unseen_time_as_unattributed(tmp_path):
    path = _write_dump(str(tmp_path / "blackbox-rank0.jsonl"), 0,
                       _known_events())
    dump = postmortem.load_blackbox(path)[-1]
    l = fleet.ledger_from_dump(dump, window=(_at(0) + _UNIX0,
                                             _at(200_000) + _UNIX0))
    assert l["window_us"] == 200_000
    assert sum(l["buckets"].values()) == 200_000
    # The 50k past the last event carries no evidence: it must stay
    # visible as a remainder, never be absorbed into compute/idle.
    assert l["buckets"]["unattributed"] == 50_000


def test_default_window_opens_at_first_step_mark(tmp_path):
    """Startup before the first marked step (imports, rendezvous,
    debug-server binds) is not schedulable rank-time: a step-marked
    rank's default window must open at the first step mark, not at the
    earliest recorded event — else every ledger starts with a bogus
    unattributed lead-in."""
    events = [{"ts_us": _at(-30_000), "type": "epoch", "epoch": 1},
              *_known_events()]
    path = _write_dump(str(tmp_path / "blackbox-rank0.jsonl"), 0, events)
    dump = postmortem.load_blackbox(path)[-1]
    l = fleet.ledger_from_dump(dump)
    assert l["window_us"] == 150_000, l
    assert l["buckets"]["unattributed"] == 0
    # An UNMARKED rank (pure serving lane) keeps the first-event open.
    bare = [{"ts_us": _at(5_000), "type": "request", "phase": 0,
             "rid": 1, "aux": 0, "phase_name": "queued"},
            {"ts_us": _at(25_000), "type": "request", "phase": 7,
             "rid": 1, "aux": 0, "phase_name": "done"}]
    path2 = _write_dump(str(tmp_path / "b" / "blackbox-rank0.jsonl"),
                        0, bare)
    l2 = fleet.ledger_from_dump(postmortem.load_blackbox(path2)[-1])
    assert l2["window_us"] == 20_000
    assert l2["buckets"]["serving_queued"] == 20_000


def test_overlapping_evidence_claims_by_priority_without_double_count(
        tmp_path):
    """A retry window overlapping a wire span: stall claims first,
    exposed wire gets only the uncovered remainder — the union claim
    keeps the sum exact no matter how evidence overlaps."""
    path = _write_dump(str(tmp_path / "blackbox-rank0.jsonl"), 0, [
        {"ts_us": _at(0), "type": "step_begin", "step": 1},
        # stall 40k..80k, raw span 50k..90k -> exposed only 80k..90k
        {"ts_us": _at(80_000), "type": "retry_window", "attempt": 1,
         "window_ms": 40},
        {"ts_us": _at(90_000), "type": "wire_span", "plane": 0,
         "dur_us": 40_000, "tx_bytes": 1, "rx_bytes": 1},
        {"ts_us": _at(100_000), "type": "step_end", "step": 1,
         "dur_us": 100_000},
    ])
    l = fleet.ledger_from_dump(postmortem.load_blackbox(path)[-1])
    b = l["buckets"]
    assert sum(b.values()) == l["window_us"] == 100_000
    assert b["stall"] == 40_000
    assert b["exposed_wire"] == 10_000, b
    assert b["compute"] == 50_000


def test_ledger_from_events_is_the_live_twin():
    """Ring-event dicts straight from hvd.events(): ts_us IS the axis
    (zero clock anchors), same reconciliation contract."""
    events = [
        {"seq": 0, "ts_us": 1_000, "type": "step_begin", "step": 1},
        {"seq": 1, "ts_us": 5_000, "type": "wire_span", "plane": 0,
         "dur_us": 2_000, "tx_bytes": 1, "rx_bytes": 1},
        {"seq": 2, "ts_us": 9_000, "type": "step_end", "step": 1,
         "dur_us": 8_000},
    ]
    l = fleet.ledger_from_events(events, rank=3)
    assert l["rank"] == 3
    assert l["window_us"] == 8_000
    assert l["buckets"]["exposed_wire"] == 2_000
    assert l["buckets"]["compute"] == 6_000
    assert sum(l["buckets"].values()) == 8_000


def test_dominant_phase_and_ledger_signals():
    l = {"window_us": 100_000,
         "buckets": {name: 0 for name in fleet.BUCKETS}}
    l["buckets"].update(stall=30_000, compute=20_000, idle=50_000)
    # idle is an absence of evidence, not a phase — stall dominates.
    assert fleet.dominant_phase(l) == "stall"
    sig = fleet.ledger_signals(l)
    assert sig["stall_ms"] == 30.0
    assert sig["queued_idle_share"] == 0.0
    empty = {"window_us": 0,
             "buckets": {name: 0 for name in fleet.BUCKETS}}
    assert fleet.dominant_phase(empty) == ""
    assert fleet.ledger_signals(empty)["queued_idle_share"] == 0.0


# ---- simworld fleet lane ----------------------------------------------


def test_simworld_fleet_analysis_64_ranks(tmp_path):
    """The synthesized fleet with the full r23 evidence surface: every
    rank reconciles exactly, fused-lane waits halve the exposed wire,
    critpath names the straggler, and the recorded breach folds out of
    rank 0's dump once."""
    ranks, steps, slow = 64, 4, 21
    harness.write_sim_step_dumps(
        str(tmp_path), ranks=ranks, steps=steps, slow_rank=slow,
        waits=True, serving=True,
        breach={"objective": 4, "rank": slow, "value": 750, "phase": 6,
                "objective_name": "stall_ms", "phase_name": "stall"})
    a = fleet.analyze(str(tmp_path))
    assert a["ranks"] == list(range(ranks))
    for rank, l in a["per_rank"].items():
        assert sum(l["buckets"].values()) == l["window_us"], rank
        assert l["buckets"]["unattributed"] == 0, rank
        # waits=True: the wait block is half of each span, so exposed
        # wire must be exactly half the raw span measure per step.
        span = 15_000 if rank == slow else 180_000 - 15_000 - 2_000
        assert l["buckets"]["exposed_wire"] == steps * (span // 2), rank
    assert a["fleet"]["worst_rank"] == slow
    assert a["fleet"]["worst_via"] == "critpath"
    assert a["critpath"]["blocking_counts"] == {slow: steps}
    (breach,) = a["slo"]["breach_events"]
    assert breach["source_rank"] == 0
    assert breach["objective"] == "stall_ms"
    assert breach["breach_rank"] == slow
    assert breach["phase"] == "stall"
    # Rendering names the worst rank and the breach.
    text = fleet.format_fleet(a, max_ranks=8)
    assert f"worst rank: {slow} (via critpath)" in text, text
    assert f"breach [stall_ms] rank {slow}" in text, text
    assert "... 56 more ranks" in text, text


def test_simworld_256_rank_aggregation_stays_interactive(tmp_path):
    """The acceptance bar: the 256-rank fleet fold must stay an
    interactive operation (< 2 s; bench.py --fleet-util watches the
    same number as `analyze_s`)."""
    harness.write_sim_step_dumps(str(tmp_path), ranks=256, steps=4,
                                 slow_rank=85, waits=True, serving=True)
    t0 = time.perf_counter()
    a = fleet.analyze(str(tmp_path))
    dt = time.perf_counter() - t0
    assert dt < 2.0, dt
    assert len(a["ranks"]) == 256
    assert a["fleet"]["worst_rank"] == 85


def test_fused_lane_wait_intersection_in_critpath(tmp_path):
    """The offline/live equivalence satellite: with wait events in the
    dump, critpath's `wire` phase is spans ∩ waits (the ledger's
    exposed measure); without them the raw span union stands."""
    harness.write_sim_step_dumps(str(tmp_path), ranks=2, steps=1,
                                 slow_rank=0, waits=True)
    dump = postmortem.load_blackbox(
        str(tmp_path / "blackbox-rank1.jsonl"))[-1]
    phases = critpath.phase_intervals(dump)
    span = 180_000 - 15_000 - 2_000
    assert critpath.union_measure(phases["wire"]) == span // 2
    assert critpath.union_measure(phases["wait"]) == span // 2
    bare = str(tmp_path / "nowaits")
    harness.write_sim_step_dumps(bare, ranks=2, steps=1, slow_rank=0)
    dump2 = postmortem.load_blackbox(
        os.path.join(bare, "blackbox-rank1.jsonl"))[-1]
    phases2 = critpath.phase_intervals(dump2)
    assert not phases2["wait"]
    assert critpath.union_measure(phases2["wire"]) == span


# ---- SLO grammar / drift / recording ----------------------------------


def test_slo_grammar_rejects_typos_loudly():
    with pytest.raises(ValueError, match="unknown signal"):
        slo.parse("serving_p99 < 250")
    with pytest.raises(ValueError, match="unknown operator"):
        slo.parse("stall_ms <= 500")
    with pytest.raises(ValueError, match="expected"):
        slo.parse("stall_ms<500")
    obj = slo.parse("overlap_efficiency > 0.4")
    assert obj == slo.Objective("overlap_efficiency", ">", 0.4)
    # One ';'-separated string (the --slo / HOROVOD_SLO form).
    objs = slo.parse_all("stall_ms < 500; serving_p99_ms < 2000")
    assert [o.name for o in objs] == ["stall_ms", "serving_p99_ms"]


def test_slo_threshold_operators_per_rank():
    engine = slo.SloEngine(("stall_ms < 500",
                            "overlap_efficiency > 0.4"))
    out = engine.evaluate(
        {0: {"stall_ms": 100.0, "overlap_efficiency": 0.8},
         1: {"stall_ms": 900.0, "overlap_efficiency": 0.2}},
        phases={1: "stall"})
    # Attribution is exact by construction: only rank 1's own signals
    # breached, and each breach names rank 1.
    assert [(b.objective, b.rank, b.phase) for b in out] == [
        ("stall_ms", 1, "stall"), ("overlap_efficiency", 1, "stall")]
    # Missing signals are not judged (train-only rank, no serving p99).
    assert engine.evaluate({2: {}}) == []
    assert engine.breaches == out


def test_slo_drift_warmup_and_frozen_baseline():
    engine = slo.SloEngine(("step_time_ewma_ms drift> 2.0",))
    # Warmup: the first _DRIFT_WARMUP observations are never judged
    # against an empty baseline.
    for _ in range(3):
        assert engine.evaluate({0: {"step_time_ewma_ms": 100.0}}) == []
    # 2.5x the learned baseline breaches...
    (b,) = engine.evaluate({0: {"step_time_ewma_ms": 250.0}})
    assert b.objective == "step_time_ewma_ms" and b.rank == 0
    # ...and the baseline stays frozen during the regression (the
    # perfwatch rule: slow must not become the new normal), so the
    # sustained regression keeps breaching.
    for _ in range(5):
        assert len(engine.evaluate({0: {"step_time_ewma_ms": 250.0}})
                   ) == 1
    # A healthy rank alongside keeps its own independent baseline.
    assert engine.evaluate({1: {"step_time_ewma_ms": 250.0}}) == []


def test_slo_record_encodes_ms_and_permille():
    """record() crosses into the C ring by id: ms objectives record
    rounded ms, ratio objectives permille, phases by BUCKETS index."""
    calls = []

    class _Basics:
        def record_slo(self, objective, rank, value, bucket):
            calls.append((objective, rank, value, bucket))

    engine = slo.SloEngine()
    engine.record(_Basics(), [
        slo.Breach("stall_ms", 3, 1234.4, "stall"),
        slo.Breach("overlap_efficiency", 1, 0.25, "exposed_wire"),
        slo.Breach("serving_p99_ms", 2, 9.0, ""),
    ])
    assert calls == [
        (slo.OBJECTIVES.index("stall_ms"), 3, 1234,
         fleet.BUCKETS.index("stall")),
        (slo.OBJECTIVES.index("overlap_efficiency"), 1, 250,
         fleet.BUCKETS.index("exposed_wire")),
        (slo.OBJECTIVES.index("serving_p99_ms"), 2, 9, -1),
    ]


def test_postmortem_folds_redumped_breach_once():
    """Satellite 4: a process re-dumps its ring tail on every fault, so
    the same (rank, seq) breach reaches the merge repeatedly — the
    post-mortem verdict list must not multiply with the fault count."""
    ev = {"type": "slo_breach", "rank": 0, "seq": 41, "t_ms": 12.5,
          "objective_name": "stall_ms", "breach_rank": 1, "value": 900,
          "phase_name": "stall"}
    other = dict(ev, seq=42, breach_rank=2)
    folded = postmortem._fold_slo_breaches([ev, dict(ev), other])
    assert len(folded) == 2, folded
    assert folded[0] == {"source_rank": 0, "objective": "stall_ms",
                         "breach_rank": 1, "value": 900,
                         "phase": "stall", "t_ms": 12.5}


# ---- live observatory -------------------------------------------------


def test_observatory_endpoint_derivation(monkeypatch):
    monkeypatch.setenv("HOROVOD_DEBUG_PORT", "9400")
    monkeypatch.setenv("HOROVOD_SIZE", "3")
    monkeypatch.setenv("HOROVOD_DEBUG_HOST", "0.0.0.0")
    obs = fleet.FleetObservatory()
    # bind-all is not dialable: derivation substitutes loopback.
    assert obs.resolve_endpoints() == {0: "127.0.0.1:9400",
                                      1: "127.0.0.1:9401",
                                      2: "127.0.0.1:9402"}
    # Ephemeral-port worlds have nothing to derive.
    monkeypatch.setenv("HOROVOD_DEBUG_PORT", "0")
    assert fleet.FleetObservatory().resolve_endpoints() == {}
    explicit = fleet.FleetObservatory(endpoints={5: "10.0.0.1:7000"})
    assert explicit.resolve_endpoints() == {5: "10.0.0.1:7000"}


def test_observatory_tolerates_unreachable_ranks():
    """A fleet view that dies with its sickest rank is useless: dead
    endpoints become error rows, the view still answers."""
    obs = fleet.FleetObservatory(endpoints={0: "127.0.0.1:9",
                                            1: "127.0.0.1:9"},
                                 timeout=0.2)
    view = obs.fleet_json()
    assert view["size"] == 2 and view["reachable"] == 0
    assert all("error" in e for e in view["ranks"].values())
    assert view["fleet"]["utilization"] == 0.0
    assert view["fleet"]["worst_rank"] is None
    # read_fleet_signals consumes the stashed view, never re-polls.
    assert obs.last_view is view
    assert len(obs.history) == 1


def test_maybe_observatory_is_a_process_singleton():
    fleet.reset_observatory()
    try:
        a = fleet.maybe_observatory(None)
        assert fleet.maybe_observatory(None) is a
    finally:
        fleet.reset_observatory()


def test_hvd_slo_env_overrides_default_objectives(monkeypatch):
    monkeypatch.setenv("HOROVOD_SLO", "stall_ms < 100")
    obs = fleet.FleetObservatory()
    assert [f"{o.name} {o.op} {o.threshold:g}"
            for o in obs.engine.objectives] == ["stall_ms < 100"]


# ---- report CLI -------------------------------------------------------


def test_report_cli_fleet(tmp_path, capsys):
    harness.write_sim_step_dumps(str(tmp_path / "dumps"), ranks=4,
                                 steps=2, slow_rank=2, waits=True)
    out_json = str(tmp_path / "fleet.json")
    rc = report.main(["--fleet", "--slo", "stall_ms < 500",
                      str(tmp_path / "dumps"), "-o", out_json])
    assert rc == 0
    out = capsys.readouterr().out
    assert "fleet: 4 ranks" in out, out
    assert "worst rank: 2 (via critpath)" in out, out
    with open(out_json) as f:
        saved = json.load(f)
    assert saved["slo"]["objectives"] == ["stall_ms < 500"]
    assert saved["fleet"]["worst_rank"] == 2


# ---- perfwatch / bench --diff over fleet_utilization rows -------------


def _fleet_row(util, ranks=64, breaches=0, analyze_s=0.1):
    return {"metric": "fleet_utilization", "config": "simworld",
            "ranks": ranks, "steps": 8, "schema": 1,
            "utilization": util, "unattributed_share": 0.0,
            "breaches": breaches, "worst_rank": ranks // 3,
            "analyze_s": analyze_s}


def test_perfwatch_flags_utilization_collapse_at_index(tmp_path):
    rows = [_fleet_row(0.8) for _ in range(10)] \
        + [_fleet_row(0.3) for _ in range(4)]
    series = perfwatch.bench_series(rows)
    key = ("fleet_utilization/simworld/64", "utilization")
    assert series[key] == [0.8] * 10 + [0.3] * 4, sorted(series)
    verdicts = {(v["metric"], v["field"]): v
                for v in perfwatch.watch(series)}
    v = verdicts[key]
    assert v["regressed"] and v["index"] == 10, v
    # breaches growing is watched too (direction up).
    assert perfwatch.field_direction("fleet_utilization",
                                     "breaches") == "up"
    assert perfwatch.field_direction("fleet_utilization",
                                     "analyze_s") == "up"


def test_perfwatch_never_cross_joins_world_sizes():
    """`ranks` is identity: a 64-rank and a 256-rank row interleaved
    must form two series, not one EWMA baseline flagging every
    world-size transition."""
    rows = []
    for _ in range(8):
        rows.append(_fleet_row(0.8, ranks=64))
        rows.append(_fleet_row(0.5, ranks=256))
    series = perfwatch.bench_series(rows)
    assert series[("fleet_utilization/simworld/64", "utilization")] \
        == [0.8] * 8
    assert series[("fleet_utilization/simworld/256", "utilization")] \
        == [0.5] * 8
    assert all(not v["regressed"] for v in perfwatch.watch(series))


def test_bench_diff_over_fleet_rows(tmp_path):
    old = str(tmp_path / "old.json")
    new = str(tmp_path / "new.json")
    with open(old, "w") as f:
        f.write(json.dumps(_fleet_row(0.8, breaches=1)) + "\n")
    with open(new, "w") as f:
        f.write(json.dumps(_fleet_row(0.4, breaches=3)) + "\n")
    lines, worst = bench._diff_rows(old, new)
    text = "\n".join(lines)
    assert "utilization" in text and "-50.0%" in text, text
    assert "breaches" in text, text
    assert worst >= 2.0, worst  # breaches 1 -> 3 is the worst delta
