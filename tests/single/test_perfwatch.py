"""Perf-regression sentinel (docs/benchmarks.md "perfwatch"): the EWMA
baseline flags an injected 2x step-time regression at the right row, a
±5% noise trace stays quiet, the changepoint localizes the regime
shift, the schema guard refuses mixed row formats, and the --budget CLI
gate exits nonzero exactly when a watched series regressed."""

import json
import random

import pytest

from horovod_tpu.telemetry import perfwatch

pytestmark = pytest.mark.quick


def _noisy(base, n, jitter, seed):
    rng = random.Random(seed)
    return [base * (1 + rng.uniform(-jitter, jitter)) for _ in range(n)]


def test_injected_2x_regression_flagged_at_index():
    series = _noisy(0.100, 12, 0.03, seed=3) + _noisy(0.200, 8, 0.03,
                                                      seed=4)
    d = perfwatch.detect(series, direction="up")
    assert d["regressed"], d
    assert d["index"] == 12, d
    assert d["ratio"] > 1.8, d
    # Baseline stays frozen at the pre-regression level: the slow
    # regime must not teach it that slow is normal.
    assert d["baseline"] < 0.12, d


def test_noise_trace_stays_quiet():
    series = _noisy(0.100, 40, 0.05, seed=11)
    d = perfwatch.detect(series, direction="up")
    assert not d["regressed"], d
    # Same for the down direction (busbw/efficiency series).
    assert not perfwatch.detect(series, direction="down")["regressed"]


def test_single_outlier_not_flagged():
    """One GC pause must not gate CI: flagging needs `consecutive`
    breaches in a row."""
    series = _noisy(0.100, 10, 0.02, seed=5) + [0.300] \
        + _noisy(0.100, 10, 0.02, seed=6)
    assert not perfwatch.detect(series, direction="up")["regressed"]


def test_flagged_ratio_not_polluted_by_earlier_outlier():
    """A transient unflagged outlier must not leave its magnitude in
    the verdict: `ratio` describes the FLAGGED regression."""
    series = ([1.0] * 6 + [3.0]            # lone 3x outlier, no flag
              + [1.0] * 6 + [1.4, 1.4, 1.4])  # the real 1.4x regression
    d = perfwatch.detect(series, direction="up")
    assert d["regressed"] and d["index"] == 13, d
    assert d["ratio"] < 2.0, d  # 1.4x-ish, not the outlier's 3x


def test_down_direction_for_efficiency_series():
    series = [0.8] * 10 + [0.3] * 5
    d = perfwatch.detect(series, direction="down")
    assert d["regressed"] and d["index"] == 10, d


def test_changepoint_localizes_shift():
    series = [1.0] * 9 + [2.0] * 7
    index, shift = perfwatch.changepoint(series)
    assert index == 9, index
    assert shift == 2.0, shift
    assert perfwatch.changepoint([1.0, 2.0]) == (None, 1.0)


def test_schema_guard_refuses_mixed_rows():
    rows = [{"metric": "a", "schema": 1}, {"metric": "b", "schema": 2}]
    with pytest.raises(SystemExit, match="MIXED schema"):
        perfwatch.check_schema(rows)
    # Uniform (or absent = legacy 0) stamps pass.
    assert perfwatch.check_schema([{"metric": "a", "schema": 1}]) == 1
    assert perfwatch.check_schema([{"metric": "a"}]) == 0


def test_scraper_series_derivation():
    """Interval series from cumulative scraper snapshots: busbw from
    wire tx deltas, overlap efficiency from ledger deltas, step time
    from ledger step-count deltas."""
    rows = []
    for i in range(4):
        rows.append({
            "ts": 10.0 * i,
            "wire": {
                "tx_bytes": int(5e9) * i,
                "overlap": {
                    "steps": 100 * i,
                    "intra": {"hidden_us": 600_000 * i,
                              "total_us": 1_000_000 * i},
                    "cross": {"hidden_us": 0, "total_us": 0},
                },
            },
        })
    s = perfwatch.scraper_series(rows)
    assert s[("scrape", "busbw_gbps")] == [0.5, 0.5, 0.5]
    assert s[("scrape", "overlap_efficiency")] == [0.6, 0.6, 0.6]
    assert s[("scrape", "step_time_ms")] == [100.0, 100.0, 100.0]


def test_real_bench_row_shapes_are_watchable():
    """The gate must bite on the rows bench.py ACTUALLY emits: per-size
    busbw lives in a nested `points` list, step time is `step_s`, and
    the MFU headline is the generic `value` (down = regression only
    because the metric name says mfu)."""
    rows = []
    for r in range(6):
        rows.append({
            "metric": "ring_busbw", "config": "overlap", "ranks": 2,
            "schema": 1,
            "points": [
                {"payload_bytes": 1 << 24,
                 "busbw_gbps": 0.66 if r < 4 else 0.22,
                 "step_s": 0.05},
                {"payload_bytes": 1 << 20, "busbw_gbps": 0.30,
                 "step_s": 0.007},
            ]})
        rows.append({"metric": "llama_train_step_mfu", "schema": 1,
                     "value": 0.69, "vs_baseline": 1.7})
    s = perfwatch.bench_series(rows)
    # Per-size points become their own series (no 16MiB/1MiB regime
    # interleaving), keyed by the full row identity.
    k16 = ("ring_busbw/overlap/2/16777216", "busbw_gbps")
    assert s[k16] == [0.66] * 4 + [0.22] * 2, sorted(s)
    assert len(s[("ring_busbw/overlap/2/1048576", "busbw_gbps")]) == 6
    # The 16 MiB collapse is flagged; the MFU headline is watched via
    # `value` and stays quiet.
    verdicts = perfwatch.watch(s)
    flagged = {(v["metric"], v["field"]): v["regressed"]
               for v in verdicts}
    assert flagged[k16] is True, verdicts
    assert flagged[("llama_train_step_mfu", "value")] is False
    # `value` on a metric whose name says nothing is NOT watchable
    # (direction unknown — flagging it would alarm on unit changes).
    assert perfwatch.field_direction("llama_update_sweep",
                                     "value") is None
    assert perfwatch.field_direction("llama_train_step_mfu",
                                     "value") == "down"


def _write_rows(path, values, field="mean_step_s", metric="eager"):
    with open(path, "w") as f:
        for v in values:
            f.write(json.dumps(
                {"metric": metric, field: v, "schema": 1}) + "\n")
    return str(path)


def test_budget_cli_gates_on_regression(tmp_path, capsys):
    reg = _write_rows(tmp_path / "reg.jsonl",
                      [0.1] * 10 + [0.2] * 5)
    assert perfwatch.main(["--bench", reg, "--budget"]) == 1
    out = capsys.readouterr().out
    assert "REGRESSED" in out and "at row 10" in out, out
    quiet = _write_rows(tmp_path / "quiet.jsonl",
                        _noisy(0.1, 20, 0.05, seed=9))
    assert perfwatch.main(["--bench", quiet, "--budget"]) == 0
    out = capsys.readouterr().out
    assert "ok" in out and "REGRESSED" not in out, out


def test_budget_gate_fails_on_zero_watchable_series(tmp_path, capsys):
    """A gate with nothing to gate on fails distinctly (exit 2): a
    renamed field or a wrong path must not ship a regression under a
    green check — same fail-loud rule as the schema guard."""
    p = tmp_path / "renamed.jsonl"
    p.write_text(json.dumps(
        {"metric": "eager", "renamed_step_field": 0.1, "schema": 1})
        + "\n")
    assert perfwatch.main(["--bench", str(p), "--budget"]) == 2
    # Report mode (no gate) still exits 0 on the same input.
    assert perfwatch.main(["--bench", str(p)]) == 0


def test_budget_cli_json_rows(tmp_path, capsys):
    reg = _write_rows(tmp_path / "reg.jsonl", [1.0] * 8 + [2.5] * 4)
    assert perfwatch.main(["--bench", reg, "--json"]) == 0  # report mode
    rows = [json.loads(line)
            for line in capsys.readouterr().out.splitlines()]
    assert rows and rows[0]["regressed"], rows
    assert rows[0]["changepoint_index"] == 8, rows
