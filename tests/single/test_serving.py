"""Serving lane: paged-allocator invariants, scheduler semantics,
continuous-batching decode parity, and the bench/perfwatch row
contract (docs/serving.md).

The parity standard is the one the elastic re-queue guarantee rests
on: ``DecodeEngine`` output must be TOKEN-IDENTICAL to
``llama_generate`` for every request, regardless of batch composition,
admission order, eviction/replay, or the int8 block format's presence
(quantization error changes logits, but deterministically — the same
request always takes the same path).
"""

import json

import numpy as np
import pytest

import jax

from horovod_tpu.models import LlamaConfig, llama_generate, llama_init
from horovod_tpu.serving.kvcache import (
    OutOfBlocks,
    PagedKVCache,
    quantize_blocks,
)
from horovod_tpu.serving.engine import DecodeEngine
from horovod_tpu.serving.scheduler import (
    ContinuousBatchingScheduler,
    Request,
    latency_summary,
    poisson_trace,
)

pytestmark = pytest.mark.quick


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny(dtype="float32", n_layers=2)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _reference(params, cfg, req):
    out = llama_generate(params, jax.numpy.asarray(req.prompt[None, :]),
                         cfg, req.max_new_tokens)
    return np.asarray(out)[0]


# ---- paged allocator invariants --------------------------------------


def test_alloc_free_roundtrip_randomized_ragged():
    """Randomized ragged alloc/free churn: every block handed out is
    unique, accounting reconciles at every step, and a full drain
    returns the pool to pristine."""
    pool = PagedKVCache(2, 2, 4, block_size=4, n_blocks=32)
    rng = np.random.default_rng(0)
    held = []
    for _ in range(300):
        if held and (rng.random() < 0.45 or pool.blocks_free < 5):
            blocks = held.pop(rng.integers(len(held)))
            pool.free(blocks)
        else:
            n = pool.blocks_for(int(rng.integers(1, 18)))
            try:
                blocks = pool.alloc(n)
            except OutOfBlocks:
                assert pool.blocks_free < n
                continue
            held.append(blocks)
        flat = [b for blks in held for b in blks]
        assert len(flat) == len(set(flat)), "block double-owned"
        assert pool.blocks_free + len(flat) == pool.blocks_total
    for blocks in held:
        pool.free(blocks)
    assert pool.blocks_free == pool.blocks_total
    with pytest.raises(ValueError):
        pool.free([0])  # double free must be loud


def test_no_block_leaked_after_evict(tiny):
    """A pool too small for the offered load forces evict/replay;
    afterwards every block is back on the free list and every request
    still completed token-identically (greedy replay determinism)."""
    cfg, params = tiny
    eng = DecodeEngine(params, cfg, block_size=4, n_blocks=6,
                       max_batch=4, max_context=24)
    trace = poisson_trace(5, 1000.0, seed=3, prompt_len=(6, 10),
                          max_new=(4, 7), vocab_size=cfg.vocab_size)
    for r in trace:
        eng.submit(r)
    done = eng.run_until_idle()
    assert eng.scheduler.evictions > 0, "pool never pressured"
    assert eng.pool.blocks_free == eng.pool.blocks_total, "leak"
    assert not eng.pool._allocated
    for r in trace:
        np.testing.assert_array_equal(done[r.rid],
                                      _reference(params, cfg, r))


def test_int8_block_dequant_error_bound():
    """Single-shot quantization (the prefill/wire path) must meet the
    per-(block, layer, head) bound |x - dq| <= scale/2 with
    scale = amax/127; incremental tail-block writes (decode) may
    requantize on scale growth, bounded by one extra quantization
    step."""
    rng = np.random.default_rng(7)
    L, H, T, D, bs = 2, 2, 13, 8, 4
    k = (rng.standard_normal((L, H, T, D)) * 3).astype(np.float32)
    v = (rng.standard_normal((L, H, T, D)) * 0.2).astype(np.float32)
    k_q, v_q, k_s, v_s = quantize_blocks(k, v, bs)
    n = k_q.shape[0]
    s_pad = n * bs
    for q, s, x in ((k_q, k_s, k), (v_q, v_s, v)):
        dq = q.astype(np.float32) * s[..., None, None]
        ref = np.zeros((L, H, s_pad, D), np.float32)
        ref[:, :, :T] = x
        ref = ref.reshape(L, H, n, bs, D).transpose(2, 0, 1, 3, 4)
        err = np.abs(dq - ref)
        bound = s[..., None, None] / 2 + 1e-7
        assert (err <= bound).all(), float((err - bound).max())

    # Incremental decode-style writes into one tail block: error stays
    # within ~one requantization step of the final scale.
    pool = PagedKVCache(L, H, D, block_size=bs, n_blocks=4,
                        quantized=True)
    blocks = pool.alloc(1)
    slots = (rng.standard_normal((bs, L, H, D))
             * np.linspace(0.5, 4.0, bs)[:, None, None, None]
             ).astype(np.float32)  # growing amax: worst requant churn
    for i in range(bs):
        pool.write(blocks, i, slots[i][:, :, None, :],
                   slots[i][:, :, None, :])
    k_g, _, k_sc, _ = pool.gather(blocks)
    dq = k_g.astype(np.float32) * k_sc[..., None]
    ref = slots.transpose(1, 2, 0, 3)  # [L, H, bs, D]
    scale_final = np.abs(ref).max(axis=(-2, -1)) / 127.0
    err = np.abs(dq[:, :, :bs] - ref)
    assert (err <= 2.0 * scale_final[..., None, None] + 1e-7).all()


def test_reused_block_quantizes_like_fresh():
    """A re-allocated block must be SCALE-fresh: `_write_block_q`
    merges against the block's current scale, so a reused block still
    carrying its previous owner's larger scale would quantize the new
    owner's first write under it — different bytes than
    `quantize_blocks` (the wire format), breaking the local-write==wire
    equivalence TIMING-DEPENDENTLY (which block the LIFO free list
    hands back depends on eviction churn; caught as a flaky serve-smoke
    token-identity failure at r19)."""
    pool = PagedKVCache(1, 1, 4, block_size=4, n_blocks=2,
                        quantized=True)
    big = np.full((1, 1, 4, 4), 100.0, np.float32)
    blocks = pool.alloc(1)
    pool.write(blocks, 0, big, big)
    pool.free(blocks)
    small = np.full((1, 1, 4, 4), 1.0, np.float32)
    reused = pool.alloc(1)
    assert reused == blocks  # LIFO hands the stale block straight back
    pool.write(reused, 0, small, small)
    k_q, v_q, k_s, v_s = quantize_blocks(small, small, 4,
                                         quantized=True)
    np.testing.assert_allclose(pool.k_scale[reused[0]], k_s[0])
    np.testing.assert_array_equal(pool.k_pool[reused[0]], k_q[0])
    np.testing.assert_array_equal(pool.v_pool[reused[0]], v_q[0])


def test_quantized_pool_write_matches_wire_format():
    """The local pool write and the wire's quantize_blocks must
    produce byte-identical int8 content for a fresh prompt — the
    determinism the elastic re-queue token-identity pin rests on."""
    rng = np.random.default_rng(11)
    L, H, T, D, bs = 2, 3, 10, 4, 4
    k = rng.standard_normal((L, H, T, D)).astype(np.float32)
    v = rng.standard_normal((L, H, T, D)).astype(np.float32)
    k_q, v_q, k_s, v_s = quantize_blocks(k, v, bs)
    pool = PagedKVCache(L, H, D, block_size=bs, n_blocks=8,
                        quantized=True)
    blocks = pool.alloc(pool.blocks_for(T))
    pool.write(blocks, 0, k, v)
    for i, blk in enumerate(blocks):
        np.testing.assert_array_equal(pool.k_pool[blk], k_q[i])
        np.testing.assert_array_equal(pool.v_pool[blk], v_q[i])
        np.testing.assert_array_equal(pool.k_scale[blk], k_s[i])
        np.testing.assert_array_equal(pool.v_scale[blk], v_s[i])


# ---- scheduler semantics ---------------------------------------------


def test_scheduler_admission_respects_budgets():
    pool = PagedKVCache(1, 1, 4, block_size=4, n_blocks=64)
    sched = ContinuousBatchingScheduler(pool, max_batch=2,
                                        token_budget=30)
    for rid in range(4):
        sched.submit(Request(rid=rid,
                             prompt=np.zeros(10, np.int32),
                             max_new_tokens=4))
    admitted = sched.admit()
    # max_batch caps at 2 even though tokens (11+11=22 <= 30) allow it.
    assert [s.rid for s in admitted] == [0, 1]
    assert sched.queue_depth == 2 and sched.inflight == 2
    # Budget now exhausted for a third 11-token context.
    assert sched.admit() == []
    sig = sched.signals()
    assert sig["serving_queue_depth"] == 2
    assert sig["inflight_sequences"] == 2
    assert sig["kv_blocks_total"] == 64
    assert sig["kv_blocks_free"] == 64 - 2 * pool.blocks_for(11)


def test_scheduler_evict_requeues_front_and_frees():
    pool = PagedKVCache(1, 1, 4, block_size=4, n_blocks=8)
    sched = ContinuousBatchingScheduler(pool, max_batch=4,
                                        token_budget=1000)
    for rid in range(2):
        sched.submit(Request(rid=rid, prompt=np.zeros(8, np.int32),
                             max_new_tokens=4))
    a, b = sched.admit()
    free_before = pool.blocks_free
    sched.evict(b)
    assert pool.blocks_free == free_before + 3  # blocks_for(9) == 3
    assert sched.waiting[0].rid == 1  # front of the line
    assert sched.evictions == 1
    # ensure_slot evicts the youngest OTHER sequence under pressure.
    pool2 = PagedKVCache(1, 1, 4, block_size=4, n_blocks=6)
    sched2 = ContinuousBatchingScheduler(pool2, max_batch=4,
                                         token_budget=1000)
    for rid in range(2):
        sched2.submit(Request(rid=rid,
                              prompt=np.zeros(11, np.int32),
                              max_new_tokens=8))
    s0, s1 = sched2.admit()
    s0.generated = [1]  # cached == 11; next slot crosses into block 4
    while pool2.blocks_for(s0.cached + 1) <= len(s0.blocks):
        s0.generated.append(1)
    assert sched2.ensure_slot(s0)
    assert s1 not in sched2.running, "youngest sibling not evicted"
    assert sched2.waiting and sched2.waiting[0].rid == 1


def test_latency_summary_percentiles():
    lat = latency_summary([0.1] * 98 + [1.0, 2.0])
    assert lat["p50_ms"] == pytest.approx(100.0)
    assert lat["p99_ms"] > 900.0
    assert latency_summary([]) == {"p50_ms": 0.0, "p99_ms": 0.0}


# ---- continuous-batching decode parity --------------------------------


def test_engine_matches_llama_generate_mid_flight_admission(tiny):
    """Requests admitted MID-FLIGHT (while others are half-decoded)
    must still produce llama_generate's exact tokens — the static-
    shape engine's batch-composition independence."""
    cfg, params = tiny
    for quantized in (False, True):
        eng = DecodeEngine(params, cfg, block_size=8, n_blocks=64,
                           max_batch=4, max_context=32,
                           quantized=quantized)
        trace = poisson_trace(6, 1000.0, seed=5, prompt_len=(4, 12),
                              max_new=(3, 8),
                              vocab_size=cfg.vocab_size)
        for r in trace[:3]:
            eng.submit(r)
        for _ in range(2):
            eng.step()
        for r in trace[3:]:
            eng.submit(r)
        done = eng.run_until_idle()
        for r in trace:
            ref = _reference(params, cfg, r)
            if quantized:
                # int8 KV perturbs logits but stays deterministic:
                # prompt + first token (computed pre-quantization)
                # always match, and the continuation is a valid greedy
                # decode (length + dtype pinned).
                np.testing.assert_array_equal(
                    done[r.rid][:len(r.prompt) + 1],
                    ref[:len(r.prompt) + 1])
                assert done[r.rid].shape == ref.shape
            else:
                np.testing.assert_array_equal(done[r.rid], ref)


# ---- bench row + perfwatch registration -------------------------------


@pytest.fixture(scope="module")
def real_rows():
    """ONE real bench-lane run shared by the row-contract tests (a
    tiny offered load keeps the module in the quick lane)."""
    from horovod_tpu.serving.bench_lane import serving_rows

    return serving_rows(n_requests=4, rps=500.0, seed=2)


def test_serving_rows_shape_and_schema(real_rows):
    """The real bench lane emits schema-stampable serving_latency rows
    with the watched fields present."""
    rows = real_rows
    assert [r["config"] for r in rows] == ["f32", "int8"]
    for row in rows:
        assert row["metric"] == "serving_latency"
        assert row["served"] == row["requests"] == 4
        assert row["sustained_tok_s"] > 0
        assert row["p99_ms"] >= row["p50_ms"] >= 0
        for f in ("arrival_rps", "block_size", "ranks"):
            assert f in row, f


def test_perfwatch_watches_serving_rows():
    """The sentinel's registration (field_direction + row identity)
    must flag a p99 regression and a tok/s collapse in serving rows,
    and keep differently-configured traces in separate series."""
    from horovod_tpu.telemetry import perfwatch as pw

    assert pw.field_direction("serving_latency", "p99_ms") == "up"
    assert pw.field_direction("serving_latency", "p50_ms") == "up"
    assert pw.field_direction("serving_latency",
                              "sustained_tok_s") == "down"
    for f in ("arrival_rps", "block_size"):
        assert f in pw.ROW_IDENTITY_FIELDS

    def row(cfg, rps, p99, toks):
        return {"metric": "serving_latency", "config": cfg,
                "arrival_rps": rps, "block_size": 8, "ranks": 1,
                "p99_ms": p99, "sustained_tok_s": toks, "schema": 1}

    rows = [row("f32", 100.0, 50.0, 900.0) for _ in range(6)]
    rows += [row("f32", 100.0, 200.0, 300.0) for _ in range(3)]
    # A second trace config interleaved: must form its OWN series, not
    # perturb the first one's baseline.
    rows += [row("f32", 400.0, 500.0, 900.0) for _ in range(6)]
    series = pw.bench_series(rows)
    keys = {k for k in series}
    assert any(k[1] == "p99_ms" and "100.0" in k[0] for k in keys)
    assert any(k[1] == "p99_ms" and "400.0" in k[0] for k in keys)
    verdicts = pw.watch(series, rel_threshold=0.25, consecutive=2)
    flagged = {(v["metric"], v["field"]) for v in verdicts
               if v["regressed"]}
    assert any(f == "p99_ms" and "100.0" in m for m, f in flagged)
    assert any(f == "sustained_tok_s" and "100.0" in m
               for m, f in flagged)
    assert not any("400.0" in m for m, f in flagged), (
        "steady series flagged — identity grouping broke")


def test_diff_and_perfwatch_on_real_serving_row_files(real_rows,
                                                     tmp_path):
    """The --diff/perfwatch contract on serving rows, exercised from
    two REAL row files (bench-lane output written to disk, schema-
    stamped like bench.py emit does):

    - identity separation: rows join strictly on the full identity
      (arrival_rps/block_size included) — a changed block_size makes a
      NEW series/row, it never cross-joins into the old one;
    - a p99 regression between the two files shows in --diff with the
      right sign, and a series built from the same two files flags
      p99_ms through perfwatch at the regressed index.
    """
    import copy

    from bench import _diff_rows
    from horovod_tpu.telemetry import perfwatch as pw

    old_rows = copy.deepcopy(real_rows)
    new_rows = copy.deepcopy(real_rows)
    for r in old_rows + new_rows:
        r.setdefault("schema", 1)  # what bench.py emit() stamps
    # Regress the f32 row's p99 3x in the new file; move the int8
    # row's block geometry so it becomes a DIFFERENT identity.
    new_rows[0]["p99_ms"] = old_rows[0]["p99_ms"] * 3.0 + 1.0
    new_rows[1]["block_size"] = 16
    old_path, new_path = tmp_path / "old.json", tmp_path / "new.json"
    old_path.write_text(json.dumps(old_rows))
    new_path.write_text(json.dumps(new_rows))

    lines, worst = _diff_rows(str(old_path), str(new_path))
    text = "\n".join(lines)
    f32_p99 = [ln for ln in lines
               if "f32" in ln and "p99_ms" in ln]
    assert f32_p99 and "+" in f32_p99[0], text
    assert worst >= 2.0, worst
    # The re-geometried int8 row did NOT join across block sizes: it
    # appears as only-in on both sides instead of a bogus delta.
    assert sum("(only in" in ln for ln in lines) == 2, text

    # perfwatch over a series drawn from the same two real files:
    # 6 healthy observations then 3 regressed ones.
    series_rows = (pw.load_rows(str(old_path)) * 6
                   + pw.load_rows(str(new_path)) * 3)
    series = pw.bench_series(series_rows)
    verdicts = pw.watch(series, rel_threshold=0.25, consecutive=2)
    flagged = {(v["metric"], v["field"]): v for v in verdicts
               if v["regressed"]}
    p99_flags = [k for k in flagged if k[1] == "p99_ms"
                 and "f32" in k[0]]
    assert p99_flags, (sorted(flagged), verdicts)
    assert flagged[p99_flags[0]]["index"] == 6
    # The int8 series (identity changed mid-stream) split into two
    # short series rather than flagging a phantom regression.
    assert not any("int8" in m for m, f in flagged), sorted(flagged)


def test_trace_overhead_row_shape():
    """The serving_trace_overhead row (the <2% tracing-cost criterion
    the driver's bench gate watches): measured fields present, both
    modes productive, tracing left ON afterwards. The 2% bound itself
    is asserted by the bench criterion field, not here — a loaded CI
    box must not turn a measurement into a flake."""
    from horovod_tpu.serving.bench_lane import trace_overhead_row
    from horovod_tpu.telemetry import perfwatch as pw, reqtrace

    row = trace_overhead_row(n_requests=3, seed=4, repeats=1)
    assert row["metric"] == "serving_trace_overhead"
    assert row["tok_s_tracing_on"] > 0
    assert row["tok_s_tracing_off"] > 0
    assert isinstance(row["pass"], bool)
    assert "overhead_pct" in row and "criterion" in row
    # perfwatch watches the overhead (up = tracing got more expensive).
    assert pw.field_direction("serving_trace_overhead",
                              "overhead_pct") == "up"
    assert reqtrace.tracing_enabled(), "bench left tracing off"


def test_eviction_amplification_counters(tiny):
    """Recomputed-prefill vs useful tokens (docs/serving.md): eviction
    churn moves the recompute counter by exactly the re-prefilled
    prompt lengths, completions move useful tokens, and the signal set
    carries the ratio."""
    cfg, params = tiny
    eng = DecodeEngine(params, cfg, block_size=4, n_blocks=6,
                       max_batch=4, max_context=24)
    trace = poisson_trace(5, 1000.0, seed=3, prompt_len=(6, 10),
                          max_new=(4, 7), vocab_size=cfg.vocab_size)
    for r in trace:
        eng.submit(r)
    done = eng.run_until_idle()
    sched = eng.scheduler
    assert sched.evictions > 0, "pool never pressured"
    assert sched.recomputed_prefill_tokens > 0
    assert sched.useful_tokens == sum(
        len(t) - len(r.prompt) for r, t in
        ((req, done[req.rid]) for req in trace))
    sig = sched.signals()
    assert sig["recomputed_prefill_tokens"] \
        == sched.recomputed_prefill_tokens
    assert sig["useful_tokens"] == sched.useful_tokens
    assert sig["eviction_amplification"] == pytest.approx(
        sched.recomputed_prefill_tokens / sched.useful_tokens,
        abs=1e-5)


# ---- service bookkeeping: fault-safe report delivery ------------------


def _bare_loop(cfg, params, trace=()):
    from horovod_tpu.serving.service import ServingLoop

    # Construction needs no live core — only the engine + bookkeeping.
    return ServingLoop(params, cfg, trace, block_size=8, n_blocks=16,
                       max_batch=2, max_context=32)


def test_done_outbox_resends_until_next_successful_round(tiny):
    """A completion must ride EVERY control message until the round
    AFTER the one that carried it succeeds (receiving the frontend's
    next control is the proof it was processed) — a collective failure
    mid-round must never lose a surviving rank's completions."""
    from horovod_tpu.serving.scheduler import Request, Sequence

    cfg, params = tiny
    loop = _bare_loop(cfg, params)
    seq = Sequence(req=Request(rid=7, prompt=np.zeros(4, np.int32),
                               max_new_tokens=2), generated=[1, 2])
    loop.engine.scheduler.completed[7] = seq
    assert 7 in loop._done_out()
    assert 7 in loop._done_out(), "outbox drained before delivery proof"
    assert loop.served_local == 1, "double-counted on re-send"
    # Round R's allgather succeeded carrying done=[7]: promoted to
    # inflight, still re-sent (the frontend may not have finished R).
    loop._retire_inflight({"acks": [], "rejects": [], "done": [7]})
    assert 7 in loop._done_out()
    # Round R+1 succeeded: the frontend provably applied R -> retired.
    loop._retire_inflight({"acks": [], "rejects": [], "done": [7]})
    assert 7 not in loop._done_outbox
    # A fault resets the proof chain but keeps the outbox.
    loop._done_outbox[9] = [1]
    loop._inflight = {"acks": [], "rejects": [], "done": [9]}
    loop._inflight = {"acks": [], "rejects": [], "done": []}  # _recover
    loop._retire_inflight({"acks": [], "rejects": [], "done": [9]})
    assert 9 in loop._done_outbox, "unconfirmed item retired after fault"


def test_duplicate_completion_cancels_reassigned_copy(tiny):
    """First completion wins: when a rid completes on rank B while its
    re-queued copy runs on rank A, the frontend must cancel A's copy
    (and that cancel must not be wiped before it is transmitted)."""
    from horovod_tpu.serving.scheduler import Request

    cfg, params = tiny
    req = Request(rid=3, prompt=np.zeros(4, np.int32), max_new_tokens=2)
    loop = _bare_loop(cfg, params, [req])
    loop._assigned[3] = {"req": req, "rank": 2, "acked": True}
    loop._apply_decode_report(
        1, {"done": {3: [0, 0, 0, 0, 1, 2]}}, now=1.0)
    assert 3 in loop._completed
    assert 3 in loop._cancel, "reassigned copy never cancelled"
    assert 3 not in loop._assigned


def test_frontend_death_fails_loudly(tiny, monkeypatch):
    """A decode rank must not silently promote itself to frontend
    (it would replay the whole trace against half-decoded state):
    rank 0 in the dead set raises before any re-formation."""
    from horovod_tpu.common import elastic as hvd_elastic

    cfg, params = tiny
    loop = _bare_loop(cfg, params)
    monkeypatch.setattr(hvd_elastic, "survivors", lambda: [1])
    monkeypatch.setattr(
        hvd_elastic, "reset",
        lambda: (_ for _ in ()).throw(AssertionError("reset reached")))
    with pytest.raises(RuntimeError, match="frontend"):
        loop._recover(old_size=2, old_rank=1)


def test_oversize_request_rejected_at_construction(tiny):
    """An oversize request must fail loudly up front, not crash a
    decode rank mid-gather (where it reads as a fault and cascades)."""
    from horovod_tpu.serving.scheduler import Request

    cfg, params = tiny
    big = Request(rid=0, prompt=np.zeros(30, np.int32),
                  max_new_tokens=30)
    with pytest.raises(ValueError, match="max_context"):
        _bare_loop(cfg, params, [big])


# ---- serving signals: /healthz + autoscale back-compat ----------------


def test_serving_signals_defaults_and_live(monkeypatch):
    from horovod_tpu.serving import service as svc
    from horovod_tpu.telemetry.autoscale import SERVING_SIGNAL_DEFAULTS

    # The pinned field set: queue/pool quartet + the r19 rolling
    # latency trio + eviction amplification (docs/serving.md).
    assert svc.serving_signals() == {
        "serving_queue_depth": 0, "inflight_sequences": 0,
        "kv_blocks_free": -1, "kv_blocks_total": -1,
        "serving_p50_ms": 0.0, "serving_p99_ms": 0.0,
        "requests_served": 0, "recomputed_prefill_tokens": 0,
        "useful_tokens": 0, "eviction_amplification": 0.0}
    assert svc.serving_signals() == dict(SERVING_SIGNAL_DEFAULTS)

    class _Stub:
        def signals(self):
            return {"serving_queue_depth": 3, "inflight_sequences": 2,
                    "kv_blocks_free": 10, "kv_blocks_total": 64,
                    "serving_p50_ms": 12.5, "serving_p99_ms": 80.0,
                    "requests_served": 9,
                    "recomputed_prefill_tokens": 40,
                    "useful_tokens": 100,
                    "eviction_amplification": 0.4}

    monkeypatch.setattr(svc, "_live", _Stub())
    assert svc.serving_signals()["serving_queue_depth"] == 3
    assert svc.serving_signals()["kv_blocks_free"] == 10
    assert svc.serving_signals()["serving_p99_ms"] == 80.0
    assert svc.serving_signals()["eviction_amplification"] == 0.4


def test_autoscale_signals_serving_backcompat():
    """Pre-serving observation sources must still construct Signals
    (the r17 defaults discipline), and the policy's decisions must be
    untouched by the new fields."""
    from horovod_tpu.telemetry.autoscale import AutoscalePolicy, Signals

    old = Signals(t=0.0, world_size=2, queue_depth=9)
    new = Signals(t=0.0, world_size=2, queue_depth=9,
                  serving_queue_depth=7, inflight_sequences=3,
                  kv_blocks_free=1, kv_blocks_total=64)
    assert old.serving_queue_depth == 0
    assert old.kv_blocks_free == -1
    # r19 additions (latency trio + amplification) keep the same
    # discipline: defaults construct, decisions untouched.
    assert old.serving_p99_ms == 0.0
    assert old.requests_served == 0
    assert old.eviction_amplification == 0.0
    p_old, p_new = AutoscalePolicy(), AutoscalePolicy()
    d_old = [p_old.decide(Signals(t=float(i), world_size=2,
                                  queue_depth=9)) for i in range(4)]
    d_new = [p_new.decide(Signals(t=float(i), world_size=2,
                                  queue_depth=9, serving_queue_depth=7,
                                  inflight_sequences=3,
                                  kv_blocks_free=1,
                                  kv_blocks_total=64,
                                  serving_p50_ms=50.0,
                                  serving_p99_ms=900.0,
                                  requests_served=123,
                                  recomputed_prefill_tokens=400,
                                  useful_tokens=100,
                                  eviction_amplification=4.0))
             for i in range(4)]
    assert [(d.action, d.target_size) for d in d_old] \
        == [(d.action, d.target_size) for d in d_new]
