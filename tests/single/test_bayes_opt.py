"""Native unit test for the autotuner's Bayesian optimizer.

Compiles csrc/bayes_opt.cc with a small driver and checks that GP+EI
finds the optimum of a synthetic response surface in far fewer samples
than exhausting the grid (the property that justifies it over the
previous coordinate-descent: sample efficiency on a noisy objective).
"""

import os
import subprocess

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))

DRIVER = r"""
#include <cstdio>
#include <cmath>
#include "bayes_opt.h"

using hvdtpu::BayesOpt;

int main() {
  // 8x5 grid shaped like the autotuner's 7x5 (fusion x cycle),
  // normalized coords.
  std::vector<std::array<double, 2>> cands;
  for (int f = 0; f < 8; f++)
    for (int c = 0; c < 5; c++)
      cands.push_back({f / 7.0, c / 4.0});
  // Smooth unimodal surface with optimum at (5/7, 1/4): mimics
  // throughput peaking at a mid-grid fusion threshold / cycle time.
  auto score = [](double x, double y) {
    double dx = x - 5.0 / 7.0, dy = y - 0.25;
    return 100.0 * std::exp(-6.0 * (dx * dx + dy * dy));
  };

  BayesOpt opt(cands);
  size_t cur = 0;  // start at the grid corner (worst case)
  for (int step = 0; step < 16; step++) {
    opt.AddSample(cur, score(cands[cur][0], cands[cur][1]));
    cur = opt.Suggest();
  }
  size_t best = opt.Best();
  double got = score(cands[best][0], cands[best][1]);
  // 16 samples over a 40-point grid must land within 2% of the peak.
  if (got < 98.0) {
    printf("FAIL best=%zu score=%.2f\n", best, got);
    return 1;
  }
  printf("OK best=%zu score=%.2f samples=16/40\n", best, got);
  return 0;
}
"""


def test_bayes_opt_converges_sample_efficiently(tmp_path):
    driver = tmp_path / "driver.cc"
    driver.write_text(DRIVER)
    binary = tmp_path / "bayes_test"
    build = subprocess.run(
        ["g++", "-O2", "-std=c++17", f"-I{REPO}/csrc", str(driver),
         f"{REPO}/csrc/bayes_opt.cc", "-o", str(binary)],
        capture_output=True, text=True)
    if build.returncode != 0:
        pytest.skip(f"native toolchain unavailable: {build.stderr[:200]}")
    run = subprocess.run([str(binary)], capture_output=True, text=True,
                         timeout=60)
    assert run.returncode == 0, run.stdout + run.stderr
    assert run.stdout.startswith("OK"), run.stdout
