"""The jaxpr machinery under the jit-lane compute/collective fusion
(``horovod_tpu.parallel.fusion``), pinned in isolation:

- ``interleave_collectives`` — the reorder pass must move each
  reduce-scatter off the program tail to the point its operand is
  ready, WITHOUT changing the math (bit-identical replay under the
  vmap(axis_name) emulation) and without touching collective-free
  programs;
- ``segment_closed_jaxpr`` — segmented replay is bit-equal to the
  monolithic program and fires ``on_boundary`` once per segment (the
  hook the host lane hangs its eager reduce-scatters on);
- ``grad_bucket_cuts`` — bucket readiness points are consistent with
  the producing equations, so wire issue order follows gradient
  availability.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.parallel import fusion
from horovod_tpu.parallel.fusion import (
    _jcore,
    grad_bucket_cuts,
    interleave_collectives,
    segment_closed_jaxpr,
)
from horovod_tpu.parallel.zero import zero_bucket_layout

pytestmark = pytest.mark.quick


def _bits(a):
    return np.asarray(a, dtype=np.float32).view(np.uint32)


def _bunched(x, w):
    # Backward-shaped: all the compute first, every scatter at the
    # tail.  16x16 operands sit above the pass's 64-element hoist
    # threshold, so the dots count as immovable compute.
    a = x @ w
    b = jnp.tanh(a) @ w
    s1 = lax.psum_scatter(a.reshape(-1), "data", scatter_dimension=0,
                          tiled=True)
    s2 = lax.psum_scatter(b.reshape(-1), "data", scatter_dimension=0,
                          tiled=True)
    return s1, s2


def _trace_bunched():
    x, w = jnp.ones((16, 16)), jnp.ones((16, 16))
    return jax.make_jaxpr(_bunched, axis_env=[("data", 2)])(x, w)


def test_interleave_moves_scatters_off_the_tail():
    closed = _trace_bunched()
    orig = [e.primitive.name for e in closed.jaxpr.eqns]
    # Sanity on the fixture itself: tail-bunched.
    assert orig.index("reduce_scatter") > max(
        i for i, p in enumerate(orig) if p == "dot_general")

    re = interleave_collectives(closed)
    new = [e.primitive.name for e in re.jaxpr.eqns]
    # Same equations, different schedule.
    assert sorted(new) == sorted(orig)
    # The first scatter now issues before the remaining compute...
    assert new.index("reduce_scatter") < new.index("tanh")
    # ...and each scatter still follows at least one dot (its operand).
    dots = [i for i, p in enumerate(new) if p == "dot_general"]
    scatters = [i for i, p in enumerate(new) if p == "reduce_scatter"]
    assert scatters[0] > dots[0]
    assert scatters[1] > dots[1]


def test_interleave_preserves_semantics_under_vmap():
    closed = _trace_bunched()
    re = interleave_collectives(closed)
    f_orig = _jcore.jaxpr_as_fun(closed)
    f_re = _jcore.jaxpr_as_fun(re)

    key = jax.random.PRNGKey(3)
    kx, kw = jax.random.split(key)
    x = jax.random.normal(kx, (2, 16, 16))
    w = jax.random.normal(kw, (2, 16, 16))
    outs_o = jax.vmap(f_orig, axis_name="data")(x, w)
    outs_r = jax.vmap(f_re, axis_name="data")(x, w)
    for o, r in zip(outs_o, outs_r):
        assert np.array_equal(_bits(o), _bits(r))


def test_interleave_is_identity_without_collectives():
    def prog(x, w):
        return jnp.tanh(x @ w) @ w

    closed = jax.make_jaxpr(prog)(jnp.ones((16, 16)), jnp.ones((16, 16)))
    re = interleave_collectives(closed)
    assert ([e.primitive.name for e in re.jaxpr.eqns]
            == [e.primitive.name for e in closed.jaxpr.eqns])


def _grad_program():
    def loss_fn(params, x):
        h = jnp.tanh(x @ params["w1"])
        h = jnp.tanh(h @ params["w2"] + params["b"])
        return jnp.sum(h ** 2)

    params = {
        "w1": jax.random.normal(jax.random.PRNGKey(0), (16, 32)) * 0.1,
        "w2": jax.random.normal(jax.random.PRNGKey(1), (32, 8)) * 0.1,
        "b": jnp.zeros((8,)),
    }
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 16))
    leaves, treedef = jax.tree.flatten(params)

    def flat_grad(*flat):
        p = jax.tree.unflatten(treedef, flat)
        loss, g = jax.value_and_grad(loss_fn)(p, x)
        return (loss, *jax.tree.leaves(g))

    return flat_grad, leaves


def test_segment_replay_bit_equal_and_boundary_count():
    flat_grad, leaves = _grad_program()
    closed = jax.make_jaxpr(flat_grad)(*leaves)
    n = len(closed.jaxpr.eqns)
    assert n >= 6  # enough equations for a meaningful split
    cuts = [n // 3, (2 * n) // 3]

    prog = segment_closed_jaxpr(closed, cuts)
    assert len(prog.segments) == len(cuts) + 1

    fired = []
    outs, env = prog.run(*leaves, on_boundary=lambda k, e: fired.append(k))
    assert fired == list(range(len(prog.segments)))

    direct = flat_grad(*leaves)
    assert len(outs) == len(direct)
    for a, b in zip(outs, direct):
        assert np.array_equal(_bits(a), _bits(b))
    # read_output resolves the same values out of the final env.
    for pos in range(len(direct)):
        assert np.array_equal(_bits(prog.read_output(env, pos)),
                              _bits(direct[pos]))


def test_grad_bucket_cuts_follow_producers():
    flat_grad, leaves = _grad_program()
    closed = jax.make_jaxpr(flat_grad)(*leaves)
    n = len(closed.jaxpr.eqns)
    layout = zero_bucket_layout(leaves, n_shards=2, bucket_bytes=1024)
    assert len(layout.buckets) >= 2  # tiny buckets: multiple wire chunks

    cuts, ready = grad_bucket_cuts(closed, layout)
    assert len(ready) == len(layout.buckets)
    assert cuts == sorted(set(cuts))
    assert all(0 < c < n for c in cuts)
    # Every bucket's readiness point is a real cut (or program end),
    # and segmenting at the cuts still replays the exact gradients.
    for r in ready:
        assert r in cuts or r in (0, n)
    prog = segment_closed_jaxpr(closed, cuts)
    outs, _ = prog.run(*leaves)
    for a, b in zip(outs, flat_grad(*leaves)):
        assert np.array_equal(_bits(a), _bits(b))
    # Issue order is by readiness — the contract the host lane uses.
    order = sorted(range(len(ready)), key=ready.__getitem__)
    assert [ready[i] for i in order] == sorted(ready)


def test_fusion_knob_env_and_override():
    # set_jit_fusion overrides the env; None restores env control.
    import os

    old = os.environ.get("HOROVOD_JIT_FUSION")
    try:
        os.environ["HOROVOD_JIT_FUSION"] = "0"
        fusion.set_jit_fusion(None)
        assert fusion.jit_fusion_enabled() is False
        fusion.set_jit_fusion(True)
        assert fusion.jit_fusion_enabled() is True
        os.environ["HOROVOD_JIT_FUSION"] = "1"
        fusion.set_jit_fusion(None)
        assert fusion.jit_fusion_enabled() is True
        fusion.set_jit_fusion(False)
        assert fusion.jit_fusion_enabled() is False
    finally:
        fusion.set_jit_fusion(None)
        if old is None:
            os.environ.pop("HOROVOD_JIT_FUSION", None)
        else:
            os.environ["HOROVOD_JIT_FUSION"] = old
