"""Chunked/compressed ring engine matrix, at the native level.

Drives ``hvdtpu_ring_selftest`` (csrc/ring_selftest.cc): N
socketpair-connected ``DataPlane``s on N threads — no controller, no
init — with explicit chunk/compression knobs. The core checks the
result against a bulk ring-order reference built from the very same
``ReduceInto`` primitive, so an rc of 0 with compression OFF pins
BIT-IDENTITY with the pre-chunking bulk-synchronous ring, for every
chunk size and every ragged count; compressed runs must stay
rank-consistent (bitwise equal across ranks) and inside the
documented bf16-on-wire error bound (docs/wire.md).
"""

import pytest

from horovod_tpu.common import basics

pytestmark = pytest.mark.quick

# csrc/common.h DataType / ReduceOp enums.
U8, I8, I32, I64, F16, BF16, F32, F64, BOOL, U16 = range(10)
AVG, SUM, MIN, MAX, PROD = 0, 1, 2, 3, 4


@pytest.fixture(scope="module")
def b():
    return basics.HorovodBasics()


def _bound(ranks):
    # docs/wire.md: each of the <= N accumulation hops contributes one
    # bf16 rounding (rel 2^-9) of a partial bounded by 2N here (inputs
    # in [-2, 2]), plus the final segment rounding — a generous
    # envelope that still fails loudly on e.g. fp16-width wire bugs.
    return ranks * ranks * 2 ** -7


def _ragged_counts(ranks):
    # Zero-length segments (count < ranks), exact fit, off-by-remainder,
    # and a multi-chunk payload.
    return [0, 1, 3, ranks - 1, ranks, ranks + 3, 1025]


@pytest.mark.parametrize("ranks", [2, 4, 5])
def test_uncompressed_bit_identity_across_chunk_sizes(b, ranks):
    for count in _ragged_counts(ranks):
        for chunk in (0, 64, 4096):  # bulk, many-chunk, few-chunk
            rc, err = b.ring_selftest(ranks, count, dtype=F32, op=SUM,
                                      chunk_bytes=chunk, compression=False)
            assert rc == 0, (ranks, count, chunk, rc)
            assert err == 0.0, (ranks, count, chunk, err)


def test_large_multichunk_payload(b):
    # ~1.2 MB per rank at 4 ranks, 4 KiB chunks: hundreds of chunks per
    # segment, both scratch halves and the overlap worker in play.
    rc, err = b.ring_selftest(4, 300001, dtype=F32, op=SUM,
                              chunk_bytes=4096, compression=False)
    assert rc == 0 and err == 0.0
    rc, err = b.ring_selftest(4, 300001, dtype=F32, op=SUM,
                              chunk_bytes=4096, compression=True)
    assert rc == 0
    assert 0 < err <= _bound(4)  # compression really engaged, inside bound


@pytest.mark.parametrize("ranks", [2, 4])
def test_compressed_error_bound(b, ranks):
    for count in (1, 5, 1025, 100003):
        for chunk in (0, 256, 65536):
            rc, err = b.ring_selftest(ranks, count, dtype=F32, op=SUM,
                                      chunk_bytes=chunk, compression=True)
            assert rc == 0, (ranks, count, chunk, rc)
            assert err <= _bound(ranks), (ranks, count, chunk, err)


def test_compression_bypasses_ineligible_dtypes_and_ops(b):
    # Compression requested, but only (f32, SUM/AVERAGE) may round:
    # every other dtype/op must take the exact path bit-identically.
    for dtype in (U8, I32, I64, F16, BF16, F64, U16):
        for op in (SUM, MIN, MAX, PROD):
            rc, err = b.ring_selftest(4, 1000, dtype=dtype, op=op,
                                      chunk_bytes=128, compression=True)
            assert rc == 0, (dtype, op, rc)
            assert err == 0.0, (dtype, op, err)
    for op in (MIN, MAX, PROD):  # f32 but non-linear: also exact
        rc, err = b.ring_selftest(4, 1000, dtype=F32, op=op,
                                  chunk_bytes=128, compression=True)
        assert rc == 0 and err == 0.0, (op, rc, err)


def test_half_precision_chunked_exact(b):
    # fp16/bf16 ride the chunked engine uncompressed (their wire is
    # already half-width); chunk boundaries must not move the per-hop
    # f32-accumulate-then-round sequence.
    for dtype in (F16, BF16):
        for count in (7, 1024, 4099):
            rc, err = b.ring_selftest(5, count, dtype=dtype, op=SUM,
                                      chunk_bytes=64, compression=False)
            assert rc == 0 and err == 0.0, (dtype, count, rc, err)


def test_postscale_fold_matches_reference(b):
    # postscale folds into the compressed decode / uncompressed tail —
    # both must match ScaleBuffer-after-the-ring semantics exactly.
    rc, err = b.ring_selftest(4, 5000, dtype=F32, op=AVG,
                              chunk_bytes=4096, compression=False,
                              postscale=0.25)
    assert rc == 0 and err == 0.0
    rc, err = b.ring_selftest(4, 5000, dtype=F32, op=AVG,
                              chunk_bytes=4096, compression=True,
                              postscale=0.25)
    assert rc == 0 and err <= _bound(4) * 0.25


def test_knob_surface_roundtrip(b):
    # The get/set pair basics exposes (and the autotuner drives).
    old_chunk, old_comp = b.ring_chunk_bytes(), b.wire_compression()
    try:
        b.set_ring_chunk_bytes(12345)
        assert b.ring_chunk_bytes() == 12345
        b.set_wire_compression(True)
        assert b.wire_compression() is True
    finally:
        b.set_ring_chunk_bytes(old_chunk)
        b.set_wire_compression(old_comp)


# ---- multi-channel striping (HOROVOD_WIRE_CHANNELS, docs/wire.md) ----


@pytest.mark.parametrize("channels", [2, 4])
@pytest.mark.parametrize("ranks", [2, 4, 5])
def test_striped_bit_identical_to_k1(b, channels, ranks):
    """K > 1 moves chunks over parallel sockets but never changes the
    reduce order — every striped uncompressed run must land on the
    SAME bits as the K=1 ring-order reference, across ragged counts
    (empty channels included) and dtypes. N=2 exercises the paired
    plan (direction-split sockets at K=4, shared-socket duplex lanes
    at K=2)."""
    for count in _ragged_counts(ranks):
        for chunk in (64, 4096):
            rc, err = b.ring_selftest(ranks, count, dtype=F32, op=SUM,
                                      chunk_bytes=chunk,
                                      channels=channels)
            assert rc == 0, (ranks, count, chunk, channels, rc)
            assert err == 0.0, (ranks, count, chunk, channels, err)
    for dtype in (BF16, I32, F64):
        rc, err = b.ring_selftest(ranks, 4099, dtype=dtype, op=SUM,
                                  chunk_bytes=256, channels=channels)
        assert rc == 0 and err == 0.0, (dtype, channels, rc, err)


def test_striped_large_payload_and_compression(b):
    # Multi-chunk striped payload, uncompressed: bit-identical.
    rc, err = b.ring_selftest(4, 300001, dtype=F32, op=SUM,
                              chunk_bytes=4096, channels=4)
    assert rc == 0 and err == 0.0
    # bf16 codec striped: same error contract as K=1.
    rc, err = b.ring_selftest(4, 100003, dtype=F32, op=SUM,
                              chunk_bytes=4096, compression=1, channels=4)
    assert rc == 0
    assert 0 < err <= _bound(4)


def test_int8_codec_bounds_and_channel_invariance(b):
    """The int8 blockwise-scaled codec (HOROVOD_WIRE_COMPRESSION=int8,
    the EQuARX stretch): per-block f32 scales, f32 accumulate. Error
    stays inside the coarse-quantization envelope, results are
    rank-consistent (selftest rc 0 enforces bitwise agreement), and
    the error is IDENTICAL at K=1 and K=4 — striping only moves
    chunks, the quantization schedule never changes."""
    errs = {}
    for channels in (1, 4):
        rc, err = b.ring_selftest(4, 100003, dtype=F32, op=SUM,
                                  chunk_bytes=4096, compression=2,
                                  channels=channels)
        assert rc == 0, (channels, rc)
        # inputs in [-2, 2]: per-hop quant error <= amax/254 per
        # element, <= N hops + the final rounding.
        assert 0 < err <= 4 * 4 * 2 ** -6, (channels, err)
        errs[channels] = err
    assert errs[1] == errs[4], errs
    # Ineligible dtypes/ops bypass the codec bit-identically.
    rc, err = b.ring_selftest(4, 1000, dtype=I32, op=SUM,
                              chunk_bytes=128, compression=2)
    assert rc == 0 and err == 0.0


def test_int8_codec_roundtrip_bounds_and_nan_poison(b):
    """Direct codec pins via the hvdtpu_int8_roundtrip entry: per-block
    scale/2 quantization bound, folded postscale, and the NaN contract
    — a non-finite input must poison its WHOLE block to NaN (a NaN
    gradient quantizing to a clean-looking number would dodge every
    divergence tripwire) while other blocks decode exactly."""
    import ctypes
    import numpy as np

    def roundtrip(src, post=1.0):
        out = np.empty_like(src)
        wlen = b.lib.hvdtpu_int8_roundtrip(
            src.ctypes.data_as(ctypes.c_void_p), src.size,
            out.ctypes.data_as(ctypes.c_void_p), float(post))
        assert wlen == 4 * ((src.size + 255) // 256) + src.size
        return out

    rng = np.random.default_rng(7)
    x = (rng.standard_normal(1000) * 2).astype(np.float32)
    y = roundtrip(x)
    # per-block bound: |x - dq| <= scale/2 = amax/254 per block
    for blk in range(0, 1000, 256):
        seg = x[blk:blk + 256]
        bound = np.abs(seg).max() / 254 + 1e-7
        assert np.abs(seg - y[blk:blk + 256]).max() <= bound, blk
    # folded postscale matches scale-after-decode rounding envelope
    y4 = roundtrip(x, post=0.25)
    assert np.allclose(y4, y * 0.25, rtol=0, atol=1e-6)
    # NaN poison: block 1 (elems 256..511) carries one NaN -> the whole
    # block decodes NaN; neighboring blocks are untouched.
    z = x.copy()
    z[300] = np.nan
    out = roundtrip(z)
    assert np.isnan(out[256:512]).all()
    assert np.array_equal(out[:256], y[:256])
    assert np.array_equal(out[512:], y[512:])
    # inf poisons too (clamping it to 127*scale would hide divergence)
    z2 = x.copy()
    z2[10] = np.inf
    out2 = roundtrip(z2)
    assert np.isnan(out2[:256]).all()


def test_simd_kernels_bit_identical_to_scalar(b):
    """The explicit-SIMD ReduceInto / bf16 codec paths (csrc/simd.h)
    must match the scalar reference BIT-FOR-BIT across unaligned
    start offsets and tail lengths, non-finite values included — the
    in-core sweep returns a negative code naming the first divergent
    kernel."""
    assert b.simd_selftest() == 0


def test_stripe_and_simd_knob_roundtrips(b):
    saved_chan = b.wire_channels()
    saved_simd = b.simd_enabled()
    saved_codec = b.wire_codec()
    try:
        b.set_wire_channels(4)
        assert b.wire_channels() == 4
        b.set_wire_channels(999)  # clamped to the stripe cap
        assert b.wire_channels() == 8
        b.set_simd_enabled(False)
        assert b.simd_enabled() is False
        b.set_simd_enabled(True)
        assert b.simd_enabled() is True
        b.set_wire_codec(2)
        assert b.wire_codec() == 2
        assert b.wire_compression() is True  # codec != 0
        b.set_wire_codec(0)
        assert b.wire_compression() is False
    finally:
        b.set_wire_channels(saved_chan)
        b.set_simd_enabled(saved_simd)
        b.set_wire_codec(saved_codec)


@pytest.mark.parametrize("ranks", [2, 4])
def test_crc_framing_is_bit_identical(b, ranks):
    """HOROVOD_WIRE_CRC reframes every duplex as typed CRC32C chunk
    messages (docs/wire.md) — the engine's results must stay
    BIT-identical to the unframed ring, including the size-2 case
    where data and acks share one socket, and under the bf16 codec
    (the compressed hops are CRC-framed like any other)."""
    saved = b.wire_crc()
    b.set_wire_crc(True)
    try:
        for count in (0, 1, ranks + 3, 1025, 5000):
            rc, err = b.ring_selftest(ranks, count, dtype=F32, op=SUM,
                                      chunk_bytes=1024)
            assert rc == 0 and err == 0.0, (ranks, count, rc, err)
        rc, err = b.ring_selftest(ranks, 4096, dtype=F32, op=SUM,
                                  chunk_bytes=1024, compression=True)
        assert rc == 0 and err <= _bound(ranks), (rc, err)
        # Striped CRC: per-channel [D1|idx|crc|payload]/NAK streams
        # (incl. the N=2 shared-socket demux at K=2's duplex lanes).
        for channels in (2, 4):
            rc, err = b.ring_selftest(ranks, 5000, dtype=F32, op=SUM,
                                      chunk_bytes=1024,
                                      channels=channels)
            assert rc == 0 and err == 0.0, (ranks, channels, rc, err)
        # Hierarchical decomposition under CRC: cross-plane hops framed
        # too (2 slices x 2 ranks needs 4), striped included.
        if ranks == 4:
            rc, err = b.hier_selftest(4, 2, 2048, chunk_bytes=512)
            assert rc == 0 and err == 0.0, (rc, err)
            rc, err = b.hier_selftest(4, 2, 2048, chunk_bytes=512,
                                      channels=4)
            assert rc == 0 and err == 0.0, (rc, err)
    finally:
        b.set_wire_crc(saved)
