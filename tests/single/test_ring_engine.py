"""Chunked/compressed ring engine matrix, at the native level.

Drives ``hvdtpu_ring_selftest`` (csrc/ring_selftest.cc): N
socketpair-connected ``DataPlane``s on N threads — no controller, no
init — with explicit chunk/compression knobs. The core checks the
result against a bulk ring-order reference built from the very same
``ReduceInto`` primitive, so an rc of 0 with compression OFF pins
BIT-IDENTITY with the pre-chunking bulk-synchronous ring, for every
chunk size and every ragged count; compressed runs must stay
rank-consistent (bitwise equal across ranks) and inside the
documented bf16-on-wire error bound (docs/wire.md).
"""

import pytest

from horovod_tpu.common import basics

pytestmark = pytest.mark.quick

# csrc/common.h DataType / ReduceOp enums.
U8, I8, I32, I64, F16, BF16, F32, F64, BOOL, U16 = range(10)
AVG, SUM, MIN, MAX, PROD = 0, 1, 2, 3, 4


@pytest.fixture(scope="module")
def b():
    return basics.HorovodBasics()


def _bound(ranks):
    # docs/wire.md: each of the <= N accumulation hops contributes one
    # bf16 rounding (rel 2^-9) of a partial bounded by 2N here (inputs
    # in [-2, 2]), plus the final segment rounding — a generous
    # envelope that still fails loudly on e.g. fp16-width wire bugs.
    return ranks * ranks * 2 ** -7


def _ragged_counts(ranks):
    # Zero-length segments (count < ranks), exact fit, off-by-remainder,
    # and a multi-chunk payload.
    return [0, 1, 3, ranks - 1, ranks, ranks + 3, 1025]


@pytest.mark.parametrize("ranks", [2, 4, 5])
def test_uncompressed_bit_identity_across_chunk_sizes(b, ranks):
    for count in _ragged_counts(ranks):
        for chunk in (0, 64, 4096):  # bulk, many-chunk, few-chunk
            rc, err = b.ring_selftest(ranks, count, dtype=F32, op=SUM,
                                      chunk_bytes=chunk, compression=False)
            assert rc == 0, (ranks, count, chunk, rc)
            assert err == 0.0, (ranks, count, chunk, err)


def test_large_multichunk_payload(b):
    # ~1.2 MB per rank at 4 ranks, 4 KiB chunks: hundreds of chunks per
    # segment, both scratch halves and the overlap worker in play.
    rc, err = b.ring_selftest(4, 300001, dtype=F32, op=SUM,
                              chunk_bytes=4096, compression=False)
    assert rc == 0 and err == 0.0
    rc, err = b.ring_selftest(4, 300001, dtype=F32, op=SUM,
                              chunk_bytes=4096, compression=True)
    assert rc == 0
    assert 0 < err <= _bound(4)  # compression really engaged, inside bound


@pytest.mark.parametrize("ranks", [2, 4])
def test_compressed_error_bound(b, ranks):
    for count in (1, 5, 1025, 100003):
        for chunk in (0, 256, 65536):
            rc, err = b.ring_selftest(ranks, count, dtype=F32, op=SUM,
                                      chunk_bytes=chunk, compression=True)
            assert rc == 0, (ranks, count, chunk, rc)
            assert err <= _bound(ranks), (ranks, count, chunk, err)


def test_compression_bypasses_ineligible_dtypes_and_ops(b):
    # Compression requested, but only (f32, SUM/AVERAGE) may round:
    # every other dtype/op must take the exact path bit-identically.
    for dtype in (U8, I32, I64, F16, BF16, F64, U16):
        for op in (SUM, MIN, MAX, PROD):
            rc, err = b.ring_selftest(4, 1000, dtype=dtype, op=op,
                                      chunk_bytes=128, compression=True)
            assert rc == 0, (dtype, op, rc)
            assert err == 0.0, (dtype, op, err)
    for op in (MIN, MAX, PROD):  # f32 but non-linear: also exact
        rc, err = b.ring_selftest(4, 1000, dtype=F32, op=op,
                                  chunk_bytes=128, compression=True)
        assert rc == 0 and err == 0.0, (op, rc, err)


def test_half_precision_chunked_exact(b):
    # fp16/bf16 ride the chunked engine uncompressed (their wire is
    # already half-width); chunk boundaries must not move the per-hop
    # f32-accumulate-then-round sequence.
    for dtype in (F16, BF16):
        for count in (7, 1024, 4099):
            rc, err = b.ring_selftest(5, count, dtype=dtype, op=SUM,
                                      chunk_bytes=64, compression=False)
            assert rc == 0 and err == 0.0, (dtype, count, rc, err)


def test_postscale_fold_matches_reference(b):
    # postscale folds into the compressed decode / uncompressed tail —
    # both must match ScaleBuffer-after-the-ring semantics exactly.
    rc, err = b.ring_selftest(4, 5000, dtype=F32, op=AVG,
                              chunk_bytes=4096, compression=False,
                              postscale=0.25)
    assert rc == 0 and err == 0.0
    rc, err = b.ring_selftest(4, 5000, dtype=F32, op=AVG,
                              chunk_bytes=4096, compression=True,
                              postscale=0.25)
    assert rc == 0 and err <= _bound(4) * 0.25


def test_knob_surface_roundtrip(b):
    # The get/set pair basics exposes (and the autotuner drives).
    old_chunk, old_comp = b.ring_chunk_bytes(), b.wire_compression()
    try:
        b.set_ring_chunk_bytes(12345)
        assert b.ring_chunk_bytes() == 12345
        b.set_wire_compression(True)
        assert b.wire_compression() is True
    finally:
        b.set_ring_chunk_bytes(old_chunk)
        b.set_wire_compression(old_comp)


@pytest.mark.parametrize("ranks", [2, 4])
def test_crc_framing_is_bit_identical(b, ranks):
    """HOROVOD_WIRE_CRC reframes every duplex as typed CRC32C chunk
    messages (docs/wire.md) — the engine's results must stay
    BIT-identical to the unframed ring, including the size-2 case
    where data and acks share one socket, and under the bf16 codec
    (the compressed hops are CRC-framed like any other)."""
    saved = b.wire_crc()
    b.set_wire_crc(True)
    try:
        for count in (0, 1, ranks + 3, 1025, 5000):
            rc, err = b.ring_selftest(ranks, count, dtype=F32, op=SUM,
                                      chunk_bytes=1024)
            assert rc == 0 and err == 0.0, (ranks, count, rc, err)
        rc, err = b.ring_selftest(ranks, 4096, dtype=F32, op=SUM,
                                  chunk_bytes=1024, compression=True)
        assert rc == 0 and err <= _bound(ranks), (rc, err)
        # Hierarchical decomposition under CRC: cross-plane hops framed
        # too (2 slices x 2 ranks needs 4).
        if ranks == 4:
            rc, err = b.hier_selftest(4, 2, 2048, chunk_bytes=512)
            assert rc == 0 and err == 0.0, (rc, err)
    finally:
        b.set_wire_crc(saved)
