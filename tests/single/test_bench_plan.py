"""Pin bench.py's row order (VERDICT r3 weak #3).

The eager flagship must be the first device-touching config (it needs a
virgin device heap — later placement OOMs under fragmentation even with
zero live arrays) and the SPMD flagship must stay last (the driver
tail-parses the final JSON line). A silent reordering regressed this
once; these tests make it loud.
"""

import pytest

import bench

# Part of the sub-5-minute CI lane (make test-quick).
pytestmark = pytest.mark.quick


def test_eager_flagship_is_first_and_spmd_flagship_last():
    plan = bench.full_run_plan(4, 2048, 10)
    names = [name for name, _ in plan]
    assert names[0] == "eager_flagship"
    assert names[-1] == "spmd_flagship"
    bench._check_plan_order(plan)  # the self-check main() runs


def test_check_plan_order_rejects_reordering():
    plan = bench.full_run_plan(4, 2048, 10)
    with pytest.raises(RuntimeError, match="must run FIRST"):
        bench._check_plan_order(plan[1:] + plan[:1])
    with pytest.raises(RuntimeError, match="must run LAST"):
        bench._check_plan_order(plan[:-2] + [plan[-1], plan[-2]])
    # Middle-row swaps are rejected too (the guard pins the FULL order,
    # not just the endpoints).
    with pytest.raises(RuntimeError, match="plan changed"):
        bench._check_plan_order(
            [plan[0], plan[2], plan[1], plan[3]])
    # An inserted row changes the sequence as well.
    with pytest.raises(RuntimeError, match="plan changed"):
        bench._check_plan_order(plan[:1] + [("extra", plan[1][1])] +
                                plan[1:])
