"""SPMD layer tests on the 8-virtual-device CPU mesh (conftest sets
xla_force_host_platform_device_count=8 — the driver's dryrun substrate).

Reference analog: none (Horovod has no in-graph SPMD); correctness is
asserted against single-device closed forms, in the reference's analytic
spirit (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from horovod_tpu import parallel
from horovod_tpu.parallel import blockwise_attention
from horovod_tpu.parallel.sharding import apply_sharding


def test_mesh_creation():
    mesh = parallel.create_mesh(data=2, tensor=4)
    assert mesh.shape["data"] == 2
    assert mesh.shape["tensor"] == 4
    assert mesh.shape["pipe"] == 1

    mesh = parallel.create_mesh()  # all devices on data
    assert mesh.shape["data"] == 8

    with pytest.raises(ValueError):
        parallel.create_mesh(data=3, tensor=4)  # 12 != 8


def test_in_graph_collectives():
    mesh = parallel.create_mesh(data=8)

    @jax.shard_map(mesh=mesh, in_specs=P("data"), out_specs=P())
    def summed(x):
        return parallel.psum(jnp.sum(x, keepdims=True), "data")

    x = jnp.arange(16.0)
    np.testing.assert_allclose(np.asarray(summed(x))[0], x.sum())

    @jax.shard_map(mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    def rotated(x):
        return parallel.ppermute_ring(x, "data", shift=1)

    r = np.asarray(rotated(jnp.arange(8.0)))
    np.testing.assert_allclose(r, np.roll(np.arange(8.0), 1))

    @jax.shard_map(mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    def bcast(x):
        return parallel.pbroadcast(x, "data", root=3)

    np.testing.assert_allclose(np.asarray(bcast(jnp.arange(8.0))), 3.0)


def _reference_attention(q, k, v, causal):
    nrep = q.shape[2] // k.shape[2]
    k = np.repeat(np.asarray(k), nrep, axis=2)
    v = np.repeat(np.asarray(v), nrep, axis=2)
    q, k, v = map(lambda t: np.asarray(t, np.float64), (q, k, v))
    scale = q.shape[-1] ** -0.5
    s = np.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = np.arange(tk)[None, :] <= np.arange(tq)[:, None]
        s = np.where(mask[None, None], s, -np.inf)
    s = s - s.max(-1, keepdims=True)
    p = np.exp(s)
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("kv_heads", [4, 2])
def test_blockwise_attention_matches_reference(causal, kv_heads):
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, 16, 4, 8), jnp.float32)
    k = jnp.asarray(rng.randn(2, 16, kv_heads, 8), jnp.float32)
    v = jnp.asarray(rng.randn(2, 16, kv_heads, 8), jnp.float32)
    out = blockwise_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out),
                               _reference_attention(q, k, v, causal),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("seq_size", [4, 8])
def test_ring_attention_exact(causal, seq_size):
    mesh = parallel.create_mesh(data=8 // seq_size, seq=seq_size)
    rng = np.random.RandomState(1)
    b, t, h, hkv, d = 2, 32, 4, 2, 8
    q = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, t, hkv, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, t, hkv, d), jnp.float32)

    out = parallel.ring_self_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out),
                               _reference_attention(q, k, v, causal),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_gradients_match():
    mesh = parallel.create_mesh(data=2, seq=4)
    rng = np.random.RandomState(2)
    b, t, h, d = 2, 16, 2, 4
    q = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)

    def loss_ring(q, k, v):
        return jnp.sum(parallel.ring_self_attention(q, k, v, mesh) ** 2)

    def loss_plain(q, k, v):
        return jnp.sum(blockwise_attention(q, k, v) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_plain = jax.grad(loss_plain, argnums=(0, 1, 2))(q, k, v)
    for gr, gp in zip(g_ring, g_plain):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gp),
                                   rtol=1e-4, atol=1e-4)


def test_shard_params_rules():
    mesh = parallel.create_mesh(data=2, tensor=4)
    params = {"layer0": {"wq": jnp.zeros((8, 8)), "bias": jnp.zeros(8)},
              "embed": jnp.zeros((16, 8))}
    rules = [
        (r"wq", P(None, "tensor")),
        (r"embed", P("tensor", None)),
    ]
    sh = parallel.shard_params(params, mesh, rules)
    assert sh["layer0"]["wq"].spec == P(None, "tensor")
    assert sh["layer0"]["bias"].spec == P()
    assert sh["embed"].spec == P("tensor", None)
    placed = apply_sharding(params, sh)
    assert placed["embed"].sharding.spec == P("tensor", None)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("seq_size", [2, 4])
def test_ulysses_attention_exact(causal, seq_size):
    """All-to-all sequence parallelism matches single-device attention,
    including grouped-query K/V with head counts that don't divide the
    axis (replicated internally)."""
    mesh = parallel.create_mesh(data=8 // seq_size, seq=seq_size)
    rng = np.random.RandomState(3)
    b, t, h, hkv, d = 4, 32, 4, 2, 8
    q = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, t, hkv, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, t, hkv, d), jnp.float32)

    out = parallel.ulysses_self_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out),
                               _reference_attention(q, k, v, causal),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_attention_gradients_match():
    mesh = parallel.create_mesh(data=2, seq=4)
    rng = np.random.RandomState(4)
    b, t, h, d = 2, 16, 4, 8
    q = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)

    def loss_uly(q, k, v):
        return jnp.sum(parallel.ulysses_self_attention(q, k, v, mesh) ** 2)

    def loss_plain(q, k, v):
        return jnp.sum(blockwise_attention(q, k, v) ** 2)

    g_u = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
    g_p = jax.grad(loss_plain, argnums=(0, 1, 2))(q, k, v)
    for gu, gp in zip(g_u, g_p):
        np.testing.assert_allclose(np.asarray(gu), np.asarray(gp),
                                   rtol=2e-5, atol=2e-5)


def test_ulysses_gqa_lcm_replication():
    """Hkv % P != 0 with lcm(Hkv, P) < H: K/V replicate only to the lcm
    and the result still matches the reference."""
    mesh = parallel.create_mesh(data=2, seq=4)
    rng = np.random.RandomState(6)
    b, t, h, hkv, d = 2, 32, 8, 2, 8  # lcm(2, 4) = 4 < 8 = H
    q = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, t, hkv, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, t, hkv, d), jnp.float32)

    out = parallel.ulysses_self_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out),
                               _reference_attention(q, k, v, True),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_rejects_indivisible_heads():
    mesh = parallel.create_mesh(data=1, seq=8)
    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(1, 16, 4, 8), jnp.float32)  # 4 heads, P=8
    with pytest.raises(Exception, match="divisible|ring_attention"):
        parallel.ulysses_self_attention(q, q, q, mesh)
