"""Request-scoped tracing (docs/serving.md "Request lifecycle &
tracing"): the kRequest event family's Python/C phase-table ABI, the
cross-rank span stitcher's gap-free exact reconciliation, tail-latency
attribution, the Perfetto per-request fold, and the live
``/requests`` surface.

Synthetic dumps here are hand-built in the exact black-box schema
(header anchor pair + JSONL events) with DELIBERATELY skewed per-rank
steady clocks — the stitcher must merge through the anchor pairs, not
raw timestamps (the r15 CLOCK_SYNC contract).
"""

import json
import urllib.error
import urllib.request

import pytest

from horovod_tpu.telemetry import postmortem, reqtrace
from horovod_tpu.telemetry.reqtrace import REQUEST_PHASES

pytestmark = pytest.mark.quick


# ---- phase-table ABI: python mirror == C table ------------------------


def test_request_phase_table_matches_core():
    """REQUEST_PHASES is index-ABI with csrc/events.h RequestPhase:
    recording phase id i must serialize with the python table's name
    at i (the stitcher consumes the decoded ``phase_name``)."""
    from horovod_tpu.common import basics

    b = basics.HorovodBasics()
    b.events_drain()  # clean cursor (one logical consumer)
    for i in range(len(REQUEST_PHASES)):
        b.record_request(i, 7000 + i, aux=i * 3)
    evs = [e for e in b.events_drain() if e["type"] == "request"
           and 7000 <= e["rid"] < 7000 + len(REQUEST_PHASES)]
    assert len(evs) == len(REQUEST_PHASES)
    for i, e in enumerate(evs):
        assert e["phase"] == i
        assert e["phase_name"] == REQUEST_PHASES[i], (i, e)
        assert e["rid"] == 7000 + i and e["aux"] == i * 3


def test_record_request_rejects_unknown_phase():
    with pytest.raises(ValueError):
        reqtrace.record_request("no_such_phase", 1)


# ---- synthetic dumps --------------------------------------------------


def _write_dump(path, rank, steady_base, events):
    """One black-box dump whose steady clock starts at ``steady_base``
    (per-rank skew) while every rank shares wall time 1_000_000 us at
    that instant — stitching must align through the anchor pair."""
    header = {"kind": "blackbox_header", "rank": rank, "size": 2,
              "epoch": 0, "unix_us": 1_000_000,
              "steady_us": steady_base, "fault": {}}
    with open(path, "w") as f:
        f.write(json.dumps(header) + "\n")
        for seq, (ts, phase, rid, aux) in enumerate(events):
            f.write(json.dumps({
                "seq": seq, "ts_us": steady_base + ts,
                "type": "request", "phase": REQUEST_PHASES.index(phase),
                "rid": rid, "aux": aux, "phase_name": phase}) + "\n")
    return path


def test_stitch_cross_rank_chain_exact(tmp_path):
    """One rid's lifecycle split across two ranks with skewed steady
    clocks: the chain reassembles in wall order, every span carries
    its source rank, and per-phase sums reconcile to the wall latency
    EXACTLY (the r17 standard)."""
    # Frontend (rank 0): queued@0 -> prefill@100 -> kv_ship@300,
    # done@1000. Decode rank (rank 1, steady clock 5_000_000 ahead):
    # decode_wait@500 -> decode_active@600 (wall offsets).
    _write_dump(tmp_path / "blackbox-rank0.jsonl", 0, 10_000, [
        (0, "queued", 1, 8), (100, "prefill", 1, 8),
        (300, "kv_ship", 1, 4096), (1000, "done", 1, 5)])
    _write_dump(tmp_path / "blackbox-rank1.jsonl", 1, 5_000_000, [
        (500, "decode_wait", 1, 2), (600, "decode_active", 1, 9)])
    chains = reqtrace.stitch(str(tmp_path))
    assert set(chains) == {1}
    c = chains[1]
    assert c["complete"] and c["ranks"] == [0, 1]
    assert [(s["phase"], s["rank"], s["dur_us"]) for s in c["spans"]] \
        == [("queued", 0, 100), ("prefill", 0, 200),
            ("kv_ship", 0, 200), ("decode_wait", 1, 100),
            ("decode_active", 1, 400)]
    assert c["wall_us"] == 1000
    assert sum(c["phase_us"].values()) == c["wall_us"]
    assert reqtrace.chain_gaps(c) == []


def test_stitch_merges_adjacent_same_phase_and_drops_zero(tmp_path):
    _write_dump(tmp_path / "blackbox-rank0.jsonl", 0, 0, [
        (0, "queued", 4, 0), (50, "queued", 4, 0),   # re-queue merges
        (50, "prefill", 4, 0),                       # zero-length drop
        (90, "done", 4, 0)])
    c = reqtrace.stitch(str(tmp_path))[4]
    assert [(s["phase"], s["dur_us"]) for s in c["spans"]] \
        == [("queued", 50), ("prefill", 40)]
    assert sum(c["phase_us"].values()) == c["wall_us"] == 90
    assert reqtrace.chain_gaps(c) == []


def test_fault_requeue_attribution_only_on_orphans(tmp_path):
    """The chaos criterion's shape: the orphaned rid's chain carries a
    fault_requeue span covering the dead rank's unobserved window (its
    events died with it); the healthy rid carries none."""
    _write_dump(tmp_path / "blackbox-rank0.jsonl", 0, 0, [
        # rid 1: shipped to the rank that dies -> frontend never sees
        # an adoption; kv_ship extends to the fault_requeue transition.
        (0, "queued", 1, 0), (10, "prefill", 1, 0),
        (20, "kv_ship", 1, 0), (520, "fault_requeue", 1, 0),
        (530, "prefill", 1, 0), (560, "decode_wait", 1, 0),
        (600, "done", 1, 0),
        # rid 2: served before the fault — no fault_requeue anywhere.
        (5, "queued", 2, 0), (15, "prefill", 2, 0),
        (40, "decode_wait", 2, 0), (80, "done", 2, 0)])
    chains = reqtrace.stitch(str(tmp_path))
    assert chains[1]["phase_us"]["fault_requeue"] == 10
    assert chains[1]["phase_us"]["kv_ship"] == 500  # the orphan window
    assert "fault_requeue" not in chains[2]["phase_us"]
    for c in chains.values():
        assert reqtrace.chain_gaps(c) == []
        assert sum(c["phase_us"].values()) == c["wall_us"]


def test_incomplete_chain_reported_not_crashed(tmp_path):
    _write_dump(tmp_path / "blackbox-rank0.jsonl", 0, 0, [
        (0, "queued", 9, 0), (10, "prefill", 9, 0)])
    chains = reqtrace.stitch(str(tmp_path))
    assert not chains[9]["complete"]
    report = reqtrace.tail_report(chains)
    assert report["complete"] == 0 and report["incomplete"] == [9]
    assert reqtrace.format_requests(report)  # renders, no crash


# ---- tail-latency attribution -----------------------------------------


def _chain(rid, phase_us, complete=True):
    spans, t = [], 0
    for ph, us in phase_us.items():
        spans.append({"phase": ph, "rank": 0, "start_us": t,
                      "end_us": t + us, "dur_us": us})
        t += us
    return {"rid": rid, "spans": spans, "phase_us": dict(phase_us),
            "start_us": 0, "end_us": t, "wall_us": t,
            "complete": complete, "ranks": [0]}


def test_tail_report_decomposes_p90_cohort():
    """Nine fast decode-bound requests + one slow one dominated by
    evicted_requeue: the p90 cohort is the slow request, its dominant
    phase is named, and both share tables sum to exactly 1 (chains are
    gap-free, so shares are a partition of wall time)."""
    chains = {r: _chain(r, {"queued": 50, "prefill": 100,
                            "decode_active": 850})
              for r in range(9)}
    chains[9] = _chain(9, {"queued": 50, "prefill": 200,
                           "evicted_requeue": 7100,
                           "decode_active": 650})
    report = reqtrace.tail_report(chains, pct=90.0)
    assert report["threshold_ms"] > 1.0
    assert [c["rid"] for c in report["cohort"]] == [9]
    assert report["cohort"][0]["dominant_phase"] == "evicted_requeue"
    for key in ("cohort_phase_share", "population_phase_share"):
        total = sum(report[key].values())
        assert abs(total - 1.0) < 1e-9, (key, report[key])
    assert report["cohort_phase_share"]["evicted_requeue"] > 0.8
    text = reqtrace.format_requests(report)
    assert "evicted_requeue" in text and "p90" in text


def test_report_cli_requests(tmp_path, capsys):
    _write_dump(tmp_path / "blackbox-rank0.jsonl", 0, 0, [
        (0, "queued", 3, 0), (40, "prefill", 3, 0),
        (90, "decode_active", 3, 0), (500, "done", 3, 0)])
    from horovod_tpu.telemetry import report

    out_json = tmp_path / "requests.json"
    rc = report.main(["--requests", str(tmp_path), "--pct", "50",
                      "-o", str(out_json)])
    assert rc == 0
    printed = capsys.readouterr().out
    assert "decode_active" in printed
    doc = json.loads(out_json.read_text())
    assert doc["report"]["complete"] == 1
    assert doc["chains"]["3"]["wall_us"] == 500


# ---- Perfetto fold: per-request tracks --------------------------------


def test_perfetto_fold_renders_per_request_tracks(tmp_path):
    path = _write_dump(tmp_path / "blackbox-rank0.jsonl", 0, 0, [
        (0, "queued", 5, 0), (100, "prefill", 5, 0),
        (400, "done", 5, 0),
        (10, "queued", 6, 0), (50, "done", 6, 0)])
    dump = postmortem.load_blackbox(str(path))[0]
    evs = postmortem.events_to_trace_events(dump, 0)
    # One named lane per rid...
    names = {e["args"]["name"] for e in evs
             if e.get("name") == "thread_name" and e["tid"] >= 2000}
    assert names == {"rid 5", "rid 6"}
    # ...with phase spans on it: queued/prefill 'X' rows whose tids
    # separate the two requests.
    spans = [e for e in evs if e.get("ph") == "X"]
    assert {(e["name"], e["tid"]) for e in spans} \
        == {("queued", 2005), ("prefill", 2005), ("queued", 2006)}
    q5 = next(e for e in spans if e["tid"] == 2005
              and e["name"] == "queued")
    assert q5["dur"] == 100
    # The terminal transition renders as an instant marker.
    assert any(e.get("ph") == "i" and e.get("name") == "done"
               and e["tid"] == 2005 for e in evs)


# ---- live in-flight table + /requests ---------------------------------


def test_live_requests_and_forget():
    reqtrace.record_request("queued", 801)
    reqtrace.record_request("prefill", 801)
    reqtrace.record_request("queued", 802)
    rows = {r["rid"]: r for r in reqtrace.live_requests()}
    assert rows[801]["phase"] == "prefill"
    assert rows[801]["age_ms"] >= rows[801]["phase_age_ms"] >= 0
    reqtrace.record_request("done", 801)
    assert 801 not in {r["rid"] for r in reqtrace.live_requests()}
    # The duplicate-cancel path retires WITHOUT a done transition.
    reqtrace.forget_request(802)
    assert 802 not in {r["rid"] for r in reqtrace.live_requests()}


def test_debug_server_requests_endpoint():
    from horovod_tpu.common.basics import HorovodBasics
    from horovod_tpu.telemetry import debug_server

    b = HorovodBasics()
    port = debug_server.start(b, 0)
    try:
        reqtrace.record_request("decode_wait", 901)
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/requests?n=8", timeout=10).read()
        rows = json.loads(body)
        assert any(r["rid"] == 901 and r["phase"] == "decode_wait"
                   for r in rows), rows
        reqtrace.record_request("done", 901)
        rows = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/requests", timeout=10).read())
        assert all(r["rid"] != 901 for r in rows)
        # The 404 map advertises the endpoint.
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=10)
            raise AssertionError("404 expected")
        except urllib.error.HTTPError as e:
            assert "/requests" in e.read().decode()
    finally:
        debug_server.stop()
