"""KV-cached decoding must match the full-forward autoregressive chain.

The no-cache reference: repeatedly run llama_forward on the whole
growing sequence and take argmax of the last position. llama_generate
(prefill + cached lax.scan decode) must produce the identical tokens.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models import (
    LlamaConfig,
    llama_forward,
    llama_generate,
    llama_init,
)


def _reference_greedy(params, prompt, cfg, n):
    toks = prompt
    for _ in range(n):
        logits = llama_forward(params, toks, cfg)
        nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(prompt.dtype)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    return toks


def test_greedy_decode_matches_full_forward():
    cfg = LlamaConfig.tiny(dtype="float32", n_layers=2)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 7), 0,
                                cfg.vocab_size)
    out = llama_generate(params, prompt, cfg, max_new_tokens=6)
    ref = _reference_greedy(params, prompt, cfg, 6)
    assert out.shape == (2, 13)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_sampled_decode_shapes_and_determinism():
    cfg = LlamaConfig.tiny(dtype="float32", n_layers=2)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 5), 0,
                                cfg.vocab_size)
    a = llama_generate(params, prompt, cfg, max_new_tokens=4,
                       temperature=0.8, key=jax.random.PRNGKey(7))
    b = llama_generate(params, prompt, cfg, max_new_tokens=4,
                       temperature=0.8, key=jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (1, 9)
    # prompt preserved
    np.testing.assert_array_equal(np.asarray(a[:, :5]), np.asarray(prompt))


def test_moe_greedy_decode_matches_full_forward():
    """MoE routing is per-token, so cached decode matches the full
    forward chain when capacity never overflows (high capacity_factor
    removes drop-divergence between T-token and 1-token routing)."""
    cfg = LlamaConfig.tiny_moe(dtype="float32", n_layers=2,
                               capacity_factor=8.0)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 6), 0,
                                cfg.vocab_size)
    out = llama_generate(params, prompt, cfg, max_new_tokens=5)
    ref = _reference_greedy(params, prompt, cfg, 5)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
