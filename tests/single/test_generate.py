"""KV-cached decoding must match the full-forward autoregressive chain.

The no-cache reference: repeatedly run llama_forward on the whole
growing sequence and take argmax of the last position. llama_generate
(prefill + cached lax.scan decode) must produce the identical tokens.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models import (
    LlamaConfig,
    llama_forward,
    llama_generate,
    llama_init,
)


def _reference_greedy(params, prompt, cfg, n):
    toks = prompt
    for _ in range(n):
        logits = llama_forward(params, toks, cfg)
        nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(prompt.dtype)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    return toks


def test_greedy_decode_matches_full_forward():
    cfg = LlamaConfig.tiny(dtype="float32", n_layers=2)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 7), 0,
                                cfg.vocab_size)
    out = llama_generate(params, prompt, cfg, max_new_tokens=6)
    ref = _reference_greedy(params, prompt, cfg, 6)
    assert out.shape == (2, 13)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_sampled_decode_shapes_and_determinism():
    cfg = LlamaConfig.tiny(dtype="float32", n_layers=2)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 5), 0,
                                cfg.vocab_size)
    a = llama_generate(params, prompt, cfg, max_new_tokens=4,
                       temperature=0.8, key=jax.random.PRNGKey(7))
    b = llama_generate(params, prompt, cfg, max_new_tokens=4,
                       temperature=0.8, key=jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (1, 9)
    # prompt preserved
    np.testing.assert_array_equal(np.asarray(a[:, :5]), np.asarray(prompt))


def test_moe_greedy_decode_matches_full_forward():
    """MoE routing is per-token, so cached decode matches the full
    forward chain when capacity never overflows (high capacity_factor
    removes drop-divergence between T-token and 1-token routing)."""
    cfg = LlamaConfig.tiny_moe(dtype="float32", n_layers=2,
                               capacity_factor=8.0)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 6), 0,
                                cfg.vocab_size)
    out = llama_generate(params, prompt, cfg, max_new_tokens=5)
    ref = _reference_greedy(params, prompt, cfg, 5)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_moe_decode_topk_flops_scale_with_k_not_e():
    """The decode-step MoE FFN must cost ~K/E of the streaming capacity
    dispatch (VERDICT r1 #7): compare XLA-reported FLOPs of the two
    paths on an identical one-token input."""
    from horovod_tpu.models.generate import _moe_ffn_topk
    from horovod_tpu.models.llama import _ffn as _llama_ffn

    cfg = LlamaConfig.tiny_moe(dtype="float32", n_experts=8,
                               n_experts_per_token=2, n_layers=2)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    lp = jax.tree.map(lambda x: x[0], params["layers"])  # one layer
    h = jax.random.normal(jax.random.PRNGKey(1), (2, 1, cfg.d_model),
                          jnp.float32)

    def flops(fn):
        analysis = jax.jit(fn).lower(h).compile().cost_analysis()
        if isinstance(analysis, list):
            analysis = analysis[0]
        return analysis["flops"]

    streaming = flops(lambda h: _llama_ffn(h, lp, cfg, None)[0])
    topk = flops(lambda h: _moe_ffn_topk(h, lp, cfg))
    # K/E = 0.25; allow headroom for routing/gather bookkeeping.
    assert topk < 0.55 * streaming, (topk, streaming)
