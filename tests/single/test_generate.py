"""KV-cached decoding must match the full-forward autoregressive chain.

The no-cache reference: repeatedly run llama_forward on the whole
growing sequence and take argmax of the last position. llama_generate
(prefill + cached lax.scan decode) must produce the identical tokens.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models import (
    LlamaConfig,
    llama_forward,
    llama_generate,
    llama_init,
)


def _reference_greedy(params, prompt, cfg, n):
    toks = prompt
    for _ in range(n):
        logits = llama_forward(params, toks, cfg)
        nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(prompt.dtype)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    return toks


def test_greedy_decode_matches_full_forward():
    cfg = LlamaConfig.tiny(dtype="float32", n_layers=2)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 7), 0,
                                cfg.vocab_size)
    out = llama_generate(params, prompt, cfg, max_new_tokens=6)
    ref = _reference_greedy(params, prompt, cfg, 6)
    assert out.shape == (2, 13)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_sampled_decode_shapes_and_determinism():
    cfg = LlamaConfig.tiny(dtype="float32", n_layers=2)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 5), 0,
                                cfg.vocab_size)
    a = llama_generate(params, prompt, cfg, max_new_tokens=4,
                       temperature=0.8, key=jax.random.PRNGKey(7))
    b = llama_generate(params, prompt, cfg, max_new_tokens=4,
                       temperature=0.8, key=jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (1, 9)
    # prompt preserved
    np.testing.assert_array_equal(np.asarray(a[:, :5]), np.asarray(prompt))


def test_moe_greedy_decode_matches_full_forward():
    """MoE routing is per-token, so cached decode matches the full
    forward chain when capacity never overflows (high capacity_factor
    removes drop-divergence between T-token and 1-token routing)."""
    cfg = LlamaConfig.tiny_moe(dtype="float32", n_layers=2,
                               capacity_factor=8.0)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 6), 0,
                                cfg.vocab_size)
    out = llama_generate(params, prompt, cfg, max_new_tokens=5)
    ref = _reference_greedy(params, prompt, cfg, 5)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_moe_decode_topk_flops_scale_with_k_not_e():
    """The decode-step MoE FFN must cost ~K/E of the streaming capacity
    dispatch (VERDICT r1 #7): compare XLA-reported FLOPs of the two
    paths on an identical one-token input."""
    from horovod_tpu.models.generate import _moe_ffn_topk
    from horovod_tpu.models.llama import _ffn as _llama_ffn

    cfg = LlamaConfig.tiny_moe(dtype="float32", n_experts=8,
                               n_experts_per_token=2, n_layers=2)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    lp = jax.tree.map(lambda x: x[0], params["layers"])  # one layer
    h = jax.random.normal(jax.random.PRNGKey(1), (2, 1, cfg.d_model),
                          jnp.float32)

    def flops(fn):
        analysis = jax.jit(fn).lower(h).compile().cost_analysis()
        if isinstance(analysis, list):
            analysis = analysis[0]
        return analysis["flops"]

    streaming = flops(lambda h: _llama_ffn(h, lp, cfg, None)[0])
    topk = flops(lambda h: _moe_ffn_topk(h, lp, cfg))
    # K/E = 0.25; allow headroom for routing/gather bookkeeping.
    assert topk < 0.55 * streaming, (topk, streaming)


def test_moe_decode_crossover_engaged_vs_streaming():
    """Both sides of the B*T*K vs E trace-time branch
    (generate._decode_ffn) in one run (VERDICT r2 #7): the gather path
    while it touches fewer weights, the streaming dispatch beyond —
    with bit-identity to the selected implementation, numerical
    agreement ACROSS the crossover (no output jump at the boundary),
    and FLOP evidence the right path was traced."""
    from horovod_tpu.models.generate import _decode_ffn, _ffn, _moe_ffn_topk

    cfg = LlamaConfig.tiny_moe(dtype="float32", n_experts=8,
                               n_experts_per_token=2, n_layers=2,
                               capacity_factor=8.0)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    lp = jax.tree.map(lambda x: x[0], params["layers"])

    def flops(fn, x):
        analysis = jax.jit(fn).lower(x).compile().cost_analysis()
        if isinstance(analysis, list):
            analysis = analysis[0]
        return analysis["flops"]

    # E=8, K=2, T=1: B=3 -> B*T*K=6 < 8 (top-k gather engaged);
    # B=4 -> B*T*K=8 (streams all experts).
    for b, engaged in ((3, True), (4, False)):
        h = jax.random.normal(jax.random.PRNGKey(b), (b, 1, cfg.d_model),
                              jnp.float32)
        out = _decode_ffn(h, lp, cfg)
        topk = _moe_ffn_topk(h, lp, cfg)
        stream = _ffn(h, lp, cfg)
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(topk if engaged else stream))
        # High capacity factor removes drops, so the two formulations
        # compute the same function.
        np.testing.assert_allclose(np.asarray(topk), np.asarray(stream),
                                   rtol=2e-5, atol=2e-6)
        f_dec = flops(lambda x: _decode_ffn(x, lp, cfg), h)
        f_stream = flops(lambda x: _ffn(x, lp, cfg), h)
        if engaged:
            assert f_dec < 0.55 * f_stream, (b, f_dec, f_stream)
        else:
            assert f_dec == f_stream, (b, f_dec, f_stream)
