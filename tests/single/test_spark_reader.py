"""Streaming parquet reader (the petastorm-reader replacement for the
Spark estimator data path) — testable without pyspark: the staged data
is plain parquet either way (SURVEY.md §2.5)."""

import os

import numpy as np
import pandas as pd
import pytest

from horovod_tpu.spark.common.fit import _load_np, use_streaming
from horovod_tpu.spark.common.reader import (
    AsyncParquetBatchReader,
    ParquetBatchReader,
    staged_bytes,
)


def _stage(tmp_path, n_rows=100, n_files=3, row_group_size=10, dim=4):
    """Write a staged-parquet-style directory with small row groups."""
    rng = np.random.RandomState(0)
    rows_per_file = n_rows // n_files
    idx = 0
    for f in range(n_files):
        n = rows_per_file + (n_rows % n_files if f == n_files - 1 else 0)
        df = pd.DataFrame({
            "features": [rng.rand(dim).astype("float32").tolist()
                         for _ in range(n)],
            "label": np.arange(idx, idx + n, dtype="float32"),
        })
        idx += n
        df.to_parquet(tmp_path / f"part-{f:05d}.parquet",
                      row_group_size=row_group_size)
    return str(tmp_path)


def test_reader_streams_all_rows_in_batches(tmp_path):
    path = _stage(tmp_path)
    r = ParquetBatchReader(path, ("features",), ("label",), batch_size=16)
    assert r.rows == 100
    assert len(r) == 7  # ceil(100/16)
    batches = list(r)
    assert len(batches) == 7
    assert all(x.shape == (16, 4) for x, _ in batches[:-1])
    assert batches[-1][0].shape == (4, 4)
    # every row seen exactly once (labels are unique row ids), with
    # batches carried across row-group boundaries
    labels = np.concatenate([y[:, 0] for _, y in batches])
    np.testing.assert_array_equal(np.sort(labels), np.arange(100.0))


def test_reader_shards_by_row_group(tmp_path):
    path = _stage(tmp_path)
    readers = [ParquetBatchReader(path, ("features",), ("label",),
                                  batch_size=8, rank=rank, size=2)
               for rank in range(2)]
    # every rank reports the SAME step count (collective matching: one
    # gradient allreduce per batch must pair up across ranks)...
    assert len(readers[0]) == len(readers[1])
    seen = []
    for r in readers:
        batches = list(r)
        assert len(batches) == len(r)  # ...and emits exactly that many
        seen.append(np.concatenate([y[:, 0] for _, y in batches]))
    # shards are disjoint (no row trains twice per epoch); the longer
    # shard's tail beyond the common step count is dropped by design
    both = np.concatenate(seen)
    assert len(np.unique(both)) == len(both)
    assert set(both) <= set(np.arange(100.0))
    assert len(both) >= 2 * 8 * (len(readers[0]) - 1)
    # matches the in-memory loader's total view
    x, y = _load_np(path, ("features",), ("label",), 0, 1)
    assert x.shape == (100, 4) and y.shape == (100, 1)


def test_reader_shuffle_permutes_row_groups_deterministically(tmp_path):
    path = _stage(tmp_path)
    a = ParquetBatchReader(path, ("features",), ("label",), batch_size=10,
                           shuffle=True, seed=7)
    b = ParquetBatchReader(path, ("features",), ("label",), batch_size=10,
                           shuffle=True, seed=7)
    la = np.concatenate([y[:, 0] for _, y in a])
    lb = np.concatenate([y[:, 0] for _, y in b])
    np.testing.assert_array_equal(la, lb)  # same seed, same epoch
    # second epoch reshuffles
    la2 = np.concatenate([y[:, 0] for _, y in a])
    assert not np.array_equal(la, la2)
    np.testing.assert_array_equal(np.sort(la), np.sort(la2))


def test_async_reader_prefetches_and_is_reiterable(tmp_path):
    path = _stage(tmp_path)
    r = AsyncParquetBatchReader(path=path, feature_cols=("features",),
                                label_cols=("label",), batch_size=32)
    try:
        for _ in range(2):  # two epochs over the same reader
            labels = np.concatenate([y[:, 0] for _, y in r])
            np.testing.assert_array_equal(np.sort(labels),
                                          np.arange(100.0))
    finally:
        r.close_async_loader()


def test_use_streaming_thresholds(tmp_path, monkeypatch):
    path = _stage(tmp_path)
    assert staged_bytes(path) > 0
    # explicit override wins both ways (inmemory_cache_all semantics:
    # True = whole shard in memory, False = stream)
    assert use_streaming(True, path) is False
    assert use_streaming(False, path) is True
    # auto: tiny staged data stays in memory...
    monkeypatch.setenv("HOROVOD_SPARK_INMEMORY_THRESHOLD_MB", "512")
    assert use_streaming(None, path) is False
    # ...and anything over the threshold streams
    monkeypatch.setenv("HOROVOD_SPARK_INMEMORY_THRESHOLD_MB", "0.0001")
    assert use_streaming(None, path) is True


def test_empty_staging_rejected(tmp_path):
    (tmp_path / "empty").mkdir()
    with pytest.raises(ValueError, match="row group"):
        ParquetBatchReader(str(tmp_path / "empty"), ("features",),
                           ("label",), batch_size=4)


def _stage_classification(tmp_path, n_rows=64, dim=4, n_classes=3):
    """Staged parquet with INTEGER labels (classification contract)."""
    rng = np.random.RandomState(0)
    df = pd.DataFrame({
        "features": [rng.rand(dim).astype("float32").tolist()
                     for _ in range(n_rows)],
        "label": rng.randint(0, n_classes, n_rows).astype("int64"),
    })
    df.to_parquet(tmp_path / "part-00000.parquet", row_group_size=16)
    return str(tmp_path)


def test_int_labels_round_trip_as_int(tmp_path):
    """Classification labels keep their integer dtype through BOTH the
    streaming reader and the in-memory load (features still cast to
    float32) — sparse-categorical/cross-entropy losses require int
    targets, so a silent float cast breaks the estimator contract."""
    path = _stage_classification(tmp_path)
    r = ParquetBatchReader(path, ("features",), ("label",), batch_size=16)
    for x, y in r:
        assert x.dtype == np.float32
        assert np.issubdtype(y.dtype, np.integer)
    x, y = _load_np(path, ("features",), ("label",), 0, 1)
    assert x.dtype == np.float32
    assert np.issubdtype(y.dtype, np.integer)
    # float labels keep normalizing to float32 (regression contract)
    (tmp_path / "float").mkdir(exist_ok=True)
    path_f = _stage(tmp_path / "float", n_files=1)
    xf, yf = _load_np(path_f, ("features",), ("label",), 0, 1)
    assert yf.dtype == np.float32
    # bool labels ALSO normalize to float32 (BCE wants float targets;
    # no loss consumes bool)
    (tmp_path / "bool").mkdir(exist_ok=True)
    pd.DataFrame({
        "features": [[0.0, 1.0, 0.0, 1.0]] * 8,
        "label": [True, False] * 4,
    }).to_parquet(tmp_path / "bool" / "part-00000.parquet")
    xb, yb = _load_np(str(tmp_path / "bool"), ("features",), ("label",),
                      0, 1)
    assert yb.dtype == np.float32


def test_classification_estimator_path_trains_with_int_labels(tmp_path):
    """End-to-end through the estimator's protocol trainer: cross-entropy
    REQUIRES integer class targets, so this only works because the
    reader preserves them."""
    import torch

    from horovod_tpu.spark.lightning import train_protocol_model

    path = _stage_classification(tmp_path, n_rows=96, n_classes=3)

    class Clf(torch.nn.Module):
        def __init__(self):
            super().__init__()
            torch.manual_seed(3)
            self.net = torch.nn.Linear(4, 3)

        def forward(self, x):
            return self.net(x)

        def training_step(self, batch, batch_idx):
            x, y = batch
            return torch.nn.functional.cross_entropy(
                self(x), y.reshape(-1))

        def configure_optimizers(self):
            return torch.optim.SGD(self.parameters(), lr=0.05)

    reader = ParquetBatchReader(path, ("features",), ("label",),
                                batch_size=16)
    trained = train_protocol_model(
        Clf(), None, None, 16, epochs=2, distributed=False,
        batch_iter=lambda: iter(reader))
    assert trained is not None


def test_split_validation_fraction(tmp_path):
    """validation=0.25 (estimator contract): row-exact random split of
    the staged parquet, deterministic by seed, disjoint and complete."""
    from horovod_tpu.spark.common.fit import split_validation

    path = _stage(tmp_path, n_rows=200, n_files=2, row_group_size=25)
    tr, va = split_validation(path, 0.25, seed=3)
    tr2, va2 = split_validation(path, 0.25, seed=3)
    xt, yt = _load_np(tr, ("features",), ("label",), 0, 1)
    xv, yv = _load_np(va, ("features",), ("label",), 0, 1)
    assert len(yt) + len(yv) == 200
    assert 20 <= len(yv) <= 80  # ~50 expected, loose stochastic bound
    # disjoint, complete (labels are unique row ids)
    both = np.concatenate([yt[:, 0], yv[:, 0]])
    assert len(np.unique(both)) == 200
    # deterministic across calls with the same seed
    xv2, yv2 = _load_np(va2, ("features",), ("label",), 0, 1)
    np.testing.assert_array_equal(np.sort(yv[:, 0]), np.sort(yv2[:, 0]))
    # original staging untouched
    x, y = _load_np(path, ("features",), ("label",), 0, 1)
    assert len(y) == 200


def test_split_validation_column(tmp_path):
    """validation='is_val': truthy rows go to the val set; the marker
    column is dropped from both outputs."""
    import pyarrow.parquet as pq

    from horovod_tpu.spark.common.fit import split_validation

    rng = np.random.RandomState(0)
    df = pd.DataFrame({
        "features": [rng.rand(4).astype("float32").tolist()
                     for _ in range(60)],
        "label": np.arange(60, dtype="float32"),
        "is_val": ([True] * 15 + [False] * 45),
    })
    (tmp_path / "staged").mkdir()
    df.to_parquet(tmp_path / "staged" / "part-00000.parquet",
                  row_group_size=16)
    tr, va = split_validation(str(tmp_path / "staged"), "is_val")
    xt, yt = _load_np(tr, ("features",), ("label",), 0, 1)
    xv, yv = _load_np(va, ("features",), ("label",), 0, 1)
    np.testing.assert_array_equal(np.sort(yv[:, 0]), np.arange(15.0))
    np.testing.assert_array_equal(np.sort(yt[:, 0]),
                                  np.arange(15.0, 60.0))
    for d in (tr, va):
        f = sorted(os.path.join(d, p) for p in os.listdir(d))[0]
        assert "is_val" not in pq.ParquetFile(f).schema_arrow.names
    # unknown column errors loudly
    with pytest.raises(ValueError, match="not in staged"):
        split_validation(str(tmp_path / "staged"), "nope")


def test_split_validation_none_passthrough(tmp_path):
    from horovod_tpu.spark.common.fit import split_validation

    path = _stage(tmp_path)
    assert split_validation(path, None) == (path, None)
    with pytest.raises(ValueError, match="fraction"):
        split_validation(path, 1.5)


def test_split_validation_preserves_file_sharding(tmp_path):
    """The split writes one output file per source file — collapsing to
    a single file would put every rank on the identical full split
    (file-level sharding in _load_np/readers)."""
    from horovod_tpu.spark.common.fit import split_validation

    path = _stage(tmp_path, n_rows=120, n_files=3, row_group_size=10)
    tr, va = split_validation(path, 0.3, seed=1)
    assert len([f for f in os.listdir(tr) if f.endswith(".parquet")]) == 3
    # rank shards are genuinely disjoint subsets
    _, y0 = _load_np(tr, ("features",), ("label",), 0, 3)
    _, y1 = _load_np(tr, ("features",), ("label",), 1, 3)
    assert not set(y0[:, 0]) & set(y1[:, 0])


def test_split_validation_all_rows_selected_errors(tmp_path):
    import pandas as _pd

    from horovod_tpu.spark.common.fit import split_validation

    (tmp_path / "s").mkdir()
    _pd.DataFrame({
        "features": [[1.0, 2.0]] * 8,
        "label": np.arange(8, dtype="float32"),
        "is_val": [True] * 8,
    }).to_parquet(tmp_path / "s" / "part-00000.parquet")
    with pytest.raises(ValueError, match="nothing left to train"):
        split_validation(str(tmp_path / "s"), "is_val")


def test_epoch_val_loss_batched(tmp_path):
    """The shared per-epoch validation helper: batched row-weighted mean
    over the val split, then the caller's cross-rank average."""
    from horovod_tpu.spark.common.fit import epoch_val_loss

    path = _stage(tmp_path, n_rows=50, n_files=1, row_group_size=10)
    seen = []

    def batch_loss(xb, yb):
        seen.append(len(xb))
        return float(yb.mean())

    out = epoch_val_loss(path, ("features",), ("label",), 16, 0, 1,
                         batch_loss, lambda v: v * 2)
    assert sum(seen) == 50 and max(seen) <= 16  # batched, all rows
    # row-weighted mean of label means == global label mean (0..49)
    assert out == pytest.approx(2 * np.arange(50).mean())


def test_lightning_protocol_streams_from_reader(tmp_path):
    """train_protocol_model's batch_iter path (the lightning estimator's
    streaming mode) learns the same function as the in-memory path."""
    import torch

    from horovod_tpu.spark.common.reader import ParquetBatchReader
    from horovod_tpu.spark.lightning import train_protocol_model

    path = _stage(tmp_path, n_rows=96, n_files=2, row_group_size=16)

    class Lit(torch.nn.Module):
        def __init__(self):
            super().__init__()
            torch.manual_seed(7)
            self.net = torch.nn.Linear(4, 1)

        def forward(self, x):
            return self.net(x)

        def training_step(self, batch, batch_idx):
            x, y = batch
            return torch.nn.functional.mse_loss(self(x), y)

        def configure_optimizers(self):
            return torch.optim.SGD(self.parameters(), lr=0.01)

    reader = ParquetBatchReader(path, ("features",), ("label",),
                                batch_size=16)
    streamed = train_protocol_model(
        Lit(), None, None, 16, epochs=2, distributed=False,
        batch_iter=lambda: iter(reader))

    x, y = _load_np(path, ("features",), ("label",), 0, 1)
    inmem = train_protocol_model(
        Lit(), torch.from_numpy(x), torch.from_numpy(y), 16, epochs=2,
        distributed=False)
    for a, b in zip(streamed.parameters(), inmem.parameters()):
        assert torch.allclose(a, b, atol=1e-6)
