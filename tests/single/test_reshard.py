"""The redistribute planner + the hierarchical cross-plane engine
(docs/redistribute.md).

Planner: each (src, dst) layout pair must emit exactly the minimal
collective table from arXiv:2112.01075 — never a gather-then-slice
detour — and the numpy all-rank simulator must make every plan a
faithful data movement (src -> dst -> src is the identity).

Hierarchical: the in-process C selftest (``hvdtpu_hier_selftest``) pins
the 2-slice x 2-rank decomposition BIT-IDENTICAL to the flat host ring
under exact (integer-valued) arithmetic — where association order
cannot explain any difference — and within the documented bf16 bound
when the wire codec rides every hop or the cross hop alone. The
per-plane wire predictions (``reshard.hier_wire_bytes``) reconcile
EXACTLY with the core's split wire counters.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.quick

_ROWS = 13
_BOUND = lambda n: n * n * 2.0 ** -7  # docs/wire.md bf16-on-wire bound


def _layouts():
    from horovod_tpu.parallel.reshard import Layout

    return Layout


# ---- planner rules ---------------------------------------------------

def test_plan_rule_table():
    from horovod_tpu.parallel.reshard import Layout, plan_redistribute

    n = 4
    sh = Layout.sharded(_ROWS, n)
    rep = Layout.replicated(n)
    part = Layout.partial(n)
    uneven = Layout.from_rows([(0, 1), (1, 5), (6, 3), (9, 4)])
    cases = [
        (sh, sh, []),                         # zero-copy
        (rep, rep, []),
        (rep, sh, ["slice"]),                 # no wire
        (sh, rep, ["allgatherv"]),
        (sh, uneven, ["alltoallv"]),
        (part, rep, ["allreduce"]),
        (part, sh, ["reducescatter"]),        # even dst = core's split
        (part, uneven, ["reducescatter", "alltoallv"]),
    ]
    for src, dst, expected in cases:
        plan = plan_redistribute((_ROWS, 3), np.float32, src, dst)
        assert [s.op for s in plan.steps] == expected, \
            (src.kind, dst.kind, plan.describe())
        assert plan.zero_copy == (not expected)


def test_plan_rejects_bad_layouts():
    from horovod_tpu.parallel.reshard import Layout, plan_redistribute

    with pytest.raises(ValueError, match="contiguous"):
        Layout.from_rows([(0, 4), (5, 3)])  # gap
    with pytest.raises(ValueError, match="same world"):
        plan_redistribute((8,), np.float32, Layout.sharded(8, 2),
                          Layout.sharded(8, 4))
    with pytest.raises(ValueError, match="covers"):
        plan_redistribute((9,), np.float32, Layout.sharded(8, 2),
                          Layout.replicated(2))
    with pytest.raises(ValueError, match="partial"):
        plan_redistribute((8,), np.float32, Layout.sharded(8, 2),
                          Layout.partial(2))


def test_roundtrip_property():
    """src -> dst -> src is the identity for every layout pair the
    simulator can express (randomized contiguous partitions)."""
    from horovod_tpu.parallel.reshard import (
        Layout,
        plan_redistribute,
        simulate_plan,
    )

    rng = np.random.RandomState(7)
    n = 4
    full = rng.randn(17, 3).astype(np.float32)

    def random_layout():
        cuts = np.sort(rng.choice(np.arange(1, 17), size=n - 1,
                                  replace=False))
        bounds = [0, *cuts.tolist(), 17]
        return Layout.from_rows(
            [(bounds[i], bounds[i + 1] - bounds[i]) for i in range(n)])

    layouts = [Layout.sharded(17, n), Layout.replicated(n)] + \
        [random_layout() for _ in range(6)]
    for src in layouts:
        locs = simulate_plan(
            plan_redistribute(full.shape, np.float32,
                              Layout.replicated(n), src),
            [full.copy() for _ in range(n)])
        for dst in layouts:
            p = plan_redistribute(full.shape, np.float32, src, dst)
            mid = simulate_plan(p, locs)
            back = simulate_plan(
                plan_redistribute(full.shape, np.float32, dst, src), mid)
            for a, b in zip(locs, back):
                assert np.array_equal(a, b), (src, dst)


def test_partial_layouts_simulate_to_the_sum():
    from horovod_tpu.parallel.reshard import (
        Layout,
        plan_redistribute,
        simulate_plan,
    )

    n = 3
    addends = [np.full((6, 2), float(r + 1), np.float32)
               for r in range(n)]
    out = simulate_plan(
        plan_redistribute((6, 2), np.float32, Layout.partial(n),
                          Layout.replicated(n)), addends)
    for o in out:
        np.testing.assert_array_equal(o, np.full((6, 2), 6.0))


def test_redistribute_zero_copy_returns_same_object():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.parallel.mesh import create_mesh
    from horovod_tpu.parallel.reshard import redistribute

    mesh = create_mesh(data=2, devices=jax.devices()[:2])
    sh = NamedSharding(mesh, P("data"))
    x = jax.device_put(jax.numpy.arange(8.0), sh)
    assert redistribute(x, sh, sh) is x  # zero-copy pin
    rep = NamedSharding(mesh, P())
    y = redistribute(x, sh, rep)
    np.testing.assert_array_equal(np.asarray(y), np.arange(8.0))


def test_layout_from_sharding():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.parallel.mesh import create_mesh
    from horovod_tpu.parallel.reshard import layout_from_sharding

    mesh = create_mesh(data=4, devices=jax.devices()[:4])
    lo = layout_from_sharding(NamedSharding(mesh, P("data")), (16, 3))
    assert lo.kind == "sharded" and len(lo.rows) == 4
    assert lo.rows[0] == (0, 4)
    rep = layout_from_sharding(NamedSharding(mesh, P()), (16, 3))
    assert rep.kind == "replicated"
    with pytest.raises(ValueError, match="later axis"):
        layout_from_sharding(NamedSharding(mesh, P(None, "data")),
                             (16, 8))


def test_compressed_plan_halves_reduce_phase_bytes():
    """``plan_redistribute(compressed=True)`` mirrors the runtime's
    HOROVOD_WIRE_COMPRESSION accounting: f32 reduce phases at half
    width, gather/exchange steps and non-f32 dtypes untouched."""
    from horovod_tpu.parallel.reshard import Layout, plan_redistribute

    n = 4
    part, sh = Layout.partial(n), Layout.sharded(16, n)
    rep = Layout.replicated(n)
    for dst in (sh, rep):
        full = plan_redistribute((16, 8), np.float32, part, dst)
        half = plan_redistribute((16, 8), np.float32, part, dst,
                                 compressed=True)
        assert half.wire_tx_bytes() * 2 == full.wire_tx_bytes()
    # f64 payloads never ride the bf16 codec.
    f64 = plan_redistribute((16, 8), np.float64, part, rep,
                            compressed=True)
    assert f64.wire_tx_bytes() == \
        plan_redistribute((16, 8), np.float64, part, rep).wire_tx_bytes()
    # Pure gather plans are unaffected by the flag.
    ag = plan_redistribute((16, 8), np.float32, sh, rep, compressed=True)
    assert ag.wire_tx_bytes() == \
        plan_redistribute((16, 8), np.float32, sh, rep).wire_tx_bytes()


def test_expected_collectives_for_lint():
    from horovod_tpu.parallel.reshard import Layout, plan_redistribute

    n = 4
    plan = plan_redistribute((8,), np.float32, Layout.sharded(8, n),
                             Layout.replicated(n))
    assert plan.expected_collectives("z") == [("all_gather", ("z",))]
    plan2 = plan_redistribute((8,), np.float32, Layout.partial(n),
                              Layout.sharded(8, n))
    assert plan2.expected_collectives("z") == [("psum_scatter", ("z",))]


# ---- ring segment twins pinned against the C ABI ---------------------

def test_ring_segment_twin_matches_c_abi():
    from horovod_tpu.common import basics
    from horovod_tpu.parallel.reshard import _ring_send_segment

    b = basics.HorovodBasics()
    for size in (2, 3, 4, 5):
        for rot in (-1, 0, 1):
            for rank in range(size):
                for step in range(size):
                    assert _ring_send_segment(rank, step, size, rot) == \
                        b.ring_send_segment(rank, step, size, rot)


# ---- hierarchical selftest pins (emulated 2 slices x 2 ranks) --------

def test_hier_bitexact_vs_flat_ring_uncompressed():
    """Exact integer arithmetic: the hierarchical decomposition must be
    BIT-identical to the flat host ring — the association-free pin."""
    from horovod_tpu.common import basics

    b = basics.HorovodBasics()
    for count in (1, 7, 4096 + 37):
        for dtype in (6, 8, 3):  # f32, f64, int32
            rc, err = b.hier_selftest(4, 2, count, dtype=dtype,
                                      compression=0, exact_fill=True)
            assert rc == 0 and err == 0.0, (count, dtype, rc, err)


def test_hier_compressed_within_documented_bound():
    """Real (non-dyadic) fills in [-2, 2]: bf16-on-wire error must stay
    under the docs/wire.md N^2 * 2^-7 bound, whether the codec rides
    every hop or the cross-plane hop alone, and ranks must agree
    bitwise either way (rc -5 otherwise)."""
    from horovod_tpu.common import basics

    b = basics.HorovodBasics()
    for compression in (1, 2):
        rc, err = b.hier_selftest(4, 2, 4096 + 37, compression=compression,
                                  exact_fill=False)
        assert rc == 0, (compression, rc)
        assert 0 < err <= _BOUND(4), (compression, err)
    # Uncompressed with the same fills is NOT bit-pinned (association
    # differs from the flat ring) but must be far below the bf16 bound.
    rc, err = b.hier_selftest(4, 2, 4096 + 37, compression=0,
                              exact_fill=False)
    assert rc == 0 and err < _BOUND(4) / 16, (rc, err)


def test_hier_wire_bytes_reconcile_exactly_with_core_counters():
    """The per-plane predictor vs the core's split wire counters, run
    in-process (the selftest's 4 planes share one registry, so the
    world totals must match to the byte — cross AND intra)."""
    from horovod_tpu.common import basics
    from horovod_tpu.parallel.reshard import hier_wire_bytes

    b = basics.HorovodBasics()
    ranks, local = 4, 2
    for count, compression in ((1 << 16, 0), (1 << 16, 2), (12345, 0)):
        b.metrics_reset()
        rc, _ = b.hier_selftest(ranks, local, count, compression=compression,
                                exact_fill=True)
        assert rc == 0
        snap = b.metrics_snapshot()["wire"]
        pred = [hier_wire_bytes(count, 4, ranks, local, r,
                                compress_cross=compression == 2)
                for r in range(ranks)]
        assert snap["cross_tx_bytes"] == sum(p["cross"] for p in pred), \
            (compression, snap, pred)
        assert snap["tx_bytes"] == sum(p["cross"] + p["intra"]
                                       for p in pred)
        if compression == 2:
            # Cross-only codec: cross plane at half width, intra full.
            assert snap["cross_tx_bytes"] * 2 == \
                snap["cross_tx_logical_bytes"]
            intra = snap["tx_bytes"] - snap["cross_tx_bytes"]
            intra_logical = (snap["tx_logical_bytes"]
                             - snap["cross_tx_logical_bytes"])
            assert intra == intra_logical


def test_flat_wire_predictor_matches_ring_selftest():
    from horovod_tpu.common import basics
    from horovod_tpu.parallel.reshard import flat_allreduce_wire_bytes

    b = basics.HorovodBasics()
    ranks, count = 4, 1 << 14
    b.metrics_reset()
    rc, err = b.ring_selftest(ranks, count)
    assert rc == 0 and err == 0.0
    snap = b.metrics_snapshot()["wire"]
    pred = sum(flat_allreduce_wire_bytes(count, 4, ranks, r)
               for r in range(ranks))
    assert snap["tx_bytes"] == pred
    assert snap["cross_tx_bytes"] == 0  # flat ring: no cross plane


# ---- in-graph composed-plane ops -------------------------------------

def test_hier_allreduce_equals_double_psum():
    """hier_allreduce == psum over (intra, inter) under the nested
    vmap emulation (exact for the integer-valued operands used)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from horovod_tpu.parallel.ops import hier_allreduce

    intra, inter = 2, 2
    x = jnp.arange(float(intra * inter * 8)).reshape(inter, intra, 8)

    def composed(blk):
        return hier_allreduce(blk, "i", "o")

    def flat(blk):
        return lax.psum(blk, ("o", "i"))

    run = lambda fn: jax.vmap(  # noqa: E731
        jax.vmap(fn, axis_name="i"), axis_name="o")(x)
    np.testing.assert_array_equal(np.asarray(run(composed)),
                                  np.asarray(run(flat)))


def test_zero_hier_apply_matches_single_plane():
    """ZeroConfig(inter_axis=...) — the RS/AG pair split across planes
    — must produce the same updated params as the single-plane ZeRO
    apply (the cross hop only re-associates an exact mean here)."""
    import jax.numpy as jnp

    from horovod_tpu.parallel.precision import fused_adam
    from horovod_tpu.parallel.zero import ZeroConfig, make_zero_apply

    params = {"w": jnp.arange(24, dtype=jnp.float32).reshape(6, 4) / 8,
              "b": jnp.ones((8,), jnp.float32)}
    grads = {"w": jnp.full((6, 4), 0.5, jnp.float32),
             "b": jnp.full((8,), -0.25, jnp.float32)}
    opt = fused_adam(1e-2)
    base_apply, base_init = make_zero_apply(
        opt, ZeroConfig(axis="data", size=4, bucket_bytes=1 << 16))
    hier_apply, hier_init = make_zero_apply(
        opt, ZeroConfig(axis="data", size=4, bucket_bytes=1 << 16,
                        inter_axis="cross", inter_size=2))
    copy = lambda t: {k: jnp.array(v) for k, v in t.items()}  # noqa: E731
    p1, o1 = base_apply(grads, *base_init(copy(params)))
    p2, o2 = hier_apply(grads, *hier_init(copy(params)))
    for k in p1:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                   rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(o1.mu[0]),
                               np.asarray(o2.mu[0]), rtol=1e-6)


def test_new_lint_programs_clean(hvdlint_shipped):
    for name in ("hier_allreduce", "zero1_shard_apply_hier",
                 "redistribute_to_replicated"):
        diags = hvdlint_shipped(name)
        assert diags == [], f"{name}: {diags}"
