"""hvdlint (horovod_tpu/analysis): seeded-bug detection + shipped-
program cleanliness.

Four deliberately-broken programs — one per static check class the
last rounds' bugs motivated — must each fire the EXACT diagnostic
(id + location); every shipped train-step/pipeline/optimizer
combination must lint clean. The whole suite runs on jaxpr tracing
with ``axis_env`` only: no shard_map, no multi-device mesh — which is
precisely what keeps it green on the old-jax (0.4.x) CPU boxes where
the pipeline engines execute under vmap emulation
(``test_full_suite_without_shard_map`` pins that).
"""

import functools

import jax
import jax.numpy as jnp
import pytest
from jax import lax

from horovod_tpu import analysis
from horovod_tpu.analysis import programs

pytestmark = pytest.mark.quick

_ENV = [("data", 2), ("pipe", 2)]


# ---- seeded bugs: each must fire its exact diagnostic ----------------

def test_c1_cond_branches_with_divergent_collectives():
    def prog(x):
        return lax.cond(x.sum() > 0,
                        lambda y: lax.psum(y, "data"),
                        lambda y: y * 2.0, x)

    diags = analysis.lint(prog, (jnp.ones(4),), axis_env=_ENV)
    assert [d.id for d in diags] == ["C1"]
    assert diags[0].severity == analysis.ERROR
    assert "cond" in diags[0].path
    assert "test_analysis_lint" in diags[0].source


def test_c1_rank_dependent_switch_is_called_out():
    """A switch predicate derived from lax.axis_index GUARANTEES ranks
    take different branches — the diagnostic must say so."""
    def prog(x):
        return lax.switch(lax.axis_index("data") % 2,
                          [lambda y: lax.psum(y, "data"),
                           lambda y: y], x)

    diags = analysis.lint(prog, (jnp.ones(4),), axis_env=_ENV)
    assert [d.id for d in diags] == ["C1"]
    assert "axis_index" in diags[0].message


def test_c2_psum_over_undeclared_axis():
    def prog(x):
        return lax.psum(x, "rank")  # not a mesh axis

    diags = analysis.lint(prog, (jnp.ones(4),), axis_env=_ENV)
    assert [d.id for d in diags] == ["C2"]
    assert "rank" in diags[0].message
    # Auto-binding the unknown axis keeps the real trace location.
    assert "test_analysis_lint" in diags[0].source


def test_c2_fires_with_no_declared_axes_at_all():
    """A collective over a typo'd axis in a program linted WITHOUT any
    mesh/axis_env must still flag C2 (the auto-bound undeclared name is
    ground truth enough); only a program with no collective axes at all
    skips the check."""
    d = analysis.lint(lambda x: lax.psum(x, "typo_axis"),
                      (jnp.ones(4),))
    assert [x.id for x in d] == ["C2"]
    assert analysis.lint(lambda x: x * 2.0, (jnp.ones(4),)) == []


def test_c1_taint_survives_scan_outputs():
    """Rank taint must propagate through loop outputs: a switch
    predicate accumulated from lax.axis_index inside a scan is still a
    GUARANTEED divergence."""
    def prog(x):
        def step(c, _):
            return c + lax.axis_index("data"), None
        acc, _ = lax.scan(step, jnp.int32(0), jnp.arange(3))
        return lax.switch(acc % 2,
                          [lambda y: lax.psum(y, "data"),
                           lambda y: y], x)

    diags = analysis.lint(prog, (jnp.ones(4),), axis_env=_ENV)
    assert [d.id for d in diags] == ["C1"]
    assert "axis_index" in diags[0].message


def test_c3_fp32_allreduce_of_bf16():
    def prog(x):
        return lax.psum(x.astype(jnp.float32), "data")  # stays f32

    diags = analysis.lint(prog, (jnp.ones(64, jnp.bfloat16),),
                          axis_env=_ENV)
    assert [d.id for d in diags] == ["C3"]
    assert diags[0].severity == analysis.WARNING
    assert "bfloat16" in diags[0].message


def test_c3_exempts_f32_accumulate_roundtrip():
    """bf16 -> f32 -> psum -> bf16 is the recommended accumulate
    pattern (and what the pipeline share() does) — NOT a finding."""
    def prog(x):
        return lax.psum(x.astype(jnp.float32),
                        "data").astype(jnp.bfloat16)

    assert analysis.lint(prog, (jnp.ones(64, jnp.bfloat16),),
                         axis_env=_ENV) == []


def test_c4_apply_jit_donating_unusable_buffer():
    """The r6/r7 bug class: grads donated into an apply program whose
    outputs are exactly params+opt — the donated grads can never alias
    an output ('donated buffers were not usable')."""
    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def apply_fn(grads, params, opt):
        return params - 0.1 * grads, opt + 1.0

    diags = analysis.lint(apply_fn, (jnp.ones(8),) * 3)
    assert [d.id for d in diags] == ["C4"]
    assert diags[0].path == "pjit:apply_fn"
    assert "cannot alias any output" in diags[0].message


def test_c4_clean_when_only_params_and_opt_donated():
    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def apply_fn(grads, params, opt):
        return params - 0.1 * grads, opt + 1.0

    assert analysis.lint(apply_fn, (jnp.ones(8),) * 3) == []


def test_c5_schedule_sequence_mismatch():
    """An engine emitting one more ring hop than its host schedule
    table predicts must be a C5 error."""
    def prog(x):
        def step(c, _):
            return lax.ppermute(c, "pipe", [(0, 1), (1, 0)]), None
        c, _ = lax.scan(step, x, jnp.arange(4))  # 4 hops...
        return lax.psum(c, "pipe")

    expect = [("ppermute", ("pipe",))] * 3 + [("psum", ("pipe",))]
    diags = analysis.lint(prog, (jnp.ones(4),),
                          axis_env=[("pipe", 2)],
                          expect_collectives=expect)
    assert [d.id for d in diags] == ["C5"]
    assert "deviates" in diags[0].message


def test_c6_unpaired_reduce_scatter():
    """The ZeRO invariant (docs/zero.md): a reduce-scatter with no
    allgather on the same axis leaves state silently sharded — C6."""
    def prog(x):
        return lax.psum_scatter(x, "data", scatter_dimension=0,
                                tiled=True)

    diags = analysis.lint(prog, (jnp.ones(8),), axis_env=_ENV)
    assert [d.id for d in diags] == ["C6"]
    assert diags[0].severity == analysis.ERROR
    assert "unpaired" in diags[0].message


def test_c6_clean_when_scatter_pairs_with_gather():
    """The ZeRO apply shape — scatter grads, update shards, gather
    params — is exactly paired and must NOT fire; a gather on a
    DIFFERENT axis does not count as the pair."""
    def paired(x):
        s = lax.psum_scatter(x, "data", scatter_dimension=0, tiled=True)
        return lax.all_gather(s - 0.1 * s, "data", axis=0, tiled=True)

    assert analysis.lint(paired, (jnp.ones(8),), axis_env=_ENV) == []

    def cross_axis(x):
        s = lax.psum_scatter(x, "data", scatter_dimension=0, tiled=True)
        return lax.all_gather(s, "pipe", axis=0, tiled=True)

    diags = analysis.lint(cross_axis, (jnp.ones(8),), axis_env=_ENV)
    assert [d.id for d in diags] == ["C6"]


def test_c6_gather_before_scatter_does_not_mask():
    """Pairing is ORDERED: an FSDP-style param gather BEFORE the
    scatter cannot reassemble the scatter's result, so a trailing
    unpaired scatter must still fire (pure per-axis counting would be
    blind to exactly this shape)."""
    def prog(x):
        g = lax.all_gather(x, "data", axis=0, tiled=True)
        return lax.psum_scatter(g, "data", scatter_dimension=0,
                                tiled=True)

    diags = analysis.lint(prog, (jnp.ones(8),), axis_env=_ENV)
    assert [d.id for d in diags] == ["C6"]
    assert "unpaired" in diags[0].message


def test_c6_counts_through_loops():
    """Trip counts weigh in: K scatters inside a scan against one
    gather outside is K-1 unpaired."""
    def prog(x):
        def step(c, _):
            return lax.psum_scatter(c, "data", scatter_dimension=0,
                                    tiled=True).repeat(2), None
        c, _ = lax.scan(step, x, jnp.arange(3))
        return lax.all_gather(c[:4], "data", axis=0, tiled=True)

    diags = analysis.lint(prog, (jnp.ones(8),), axis_env=_ENV)
    assert [d.id for d in diags] == ["C6"]
    assert "3 reduce-scatter(s)" in diags[0].message
    assert "only 1 subsequent allgather(s)" in diags[0].message


def _bunched(x, w):
    """Backward-shaped fixture: ALL the arithmetic, then every bucket's
    reduce-scatter bunched at the tail — the pre-fusion split-step
    schedule C7 exists to reject."""
    a = x @ w
    b = jnp.tanh(a) @ w
    s1 = lax.psum_scatter(a.reshape(-1), "data", scatter_dimension=0,
                          tiled=True)
    s2 = lax.psum_scatter(b.reshape(-1), "data", scatter_dimension=0,
                          tiled=True)
    ga = lax.all_gather(s1, "data", axis=0, tiled=True)
    gb = lax.all_gather(s2, "data", axis=0, tiled=True)
    return ga, gb


def test_c7_tail_bunched_scatters_fire():
    x, w = jnp.ones((8, 8)), jnp.ones((8, 8))
    diags = analysis.lint(_bunched, (x, w), axis_env=_ENV)
    assert [d.id for d in diags] == ["C7"]
    assert diags[0].severity == analysis.ERROR
    assert "bunched" in diags[0].message
    assert "HOROVOD_JIT_FUSION" in diags[0].hint


def test_c7_quiet_on_interleaved_schedule():
    """The SAME collectives interleaved with the compute — each
    scatter issued the moment its operand is ready — must pass.
    ``parallel.fusion.interleave_collectives`` produces exactly this
    shape from the bunched one (pinned end-to-end by the registered
    ``zero1_fused_step`` program staying clean)."""
    def interleaved(x, w):
        a = x @ w
        s1 = lax.psum_scatter(a.reshape(-1), "data",
                              scatter_dimension=0, tiled=True)
        b = jnp.tanh(a) @ w
        s2 = lax.psum_scatter(b.reshape(-1), "data",
                              scatter_dimension=0, tiled=True)
        ga = lax.all_gather(s1, "data", axis=0, tiled=True)
        gb = lax.all_gather(s2, "data", axis=0, tiled=True)
        return ga, gb

    x, w = jnp.ones((8, 8)), jnp.ones((8, 8))
    assert analysis.lint(interleaved, (x, w), axis_env=_ENV) == []


def test_c7_quiet_on_reorder_pass_output():
    """Feeding the bunched fixture through the actual fusion pass must
    flip its verdict: the reordered jaxpr replayed via jaxpr_as_fun
    lints clean while the original fires.  Operands are 16x16 (> the
    pass's 64-element hoist threshold) so the dots count as real
    compute to weave the scatters into."""
    from horovod_tpu.parallel.fusion import (
        _jcore,
        interleave_collectives,
    )

    x, w = jnp.ones((16, 16)), jnp.ones((16, 16))
    closed = jax.make_jaxpr(_bunched, axis_env=[("data", 2)])(x, w)
    fixed = _jcore.jaxpr_as_fun(interleave_collectives(closed))
    assert analysis.lint(fixed, (x, w), axis_env=_ENV) == []


def test_c7_quiet_on_eager_lane_and_single_bucket():
    """No collectives in the jaxpr (the eager lane moves bytes outside
    jit) -> quiet; a single scatter (one bucket cannot interleave with
    itself) -> quiet; a pure-wire program (no flop mass) -> quiet."""
    def eager_shaped(x, w):
        return jnp.tanh(x @ w) @ w

    def single(x, w):
        a = jnp.tanh(x @ w) @ w
        s = lax.psum_scatter(a.reshape(-1), "data",
                             scatter_dimension=0, tiled=True)
        return lax.all_gather(s, "data", axis=0, tiled=True)

    def pure_wire(x):
        s1 = lax.psum_scatter(x, "data", scatter_dimension=0,
                              tiled=True)
        g1 = lax.all_gather(s1, "data", axis=0, tiled=True)
        s2 = lax.psum_scatter(g1, "data", scatter_dimension=0,
                              tiled=True)
        return lax.all_gather(s2, "data", axis=0, tiled=True)

    x, w = jnp.ones((8, 8)), jnp.ones((8, 8))
    assert analysis.lint(eager_shaped, (x, w), axis_env=_ENV) == []
    assert analysis.lint(single, (x, w), axis_env=_ENV) == []
    assert analysis.lint(pure_wire, (jnp.ones(8),), axis_env=_ENV) == []


def test_c8_collective_in_rank_dependent_while_fires():
    """A psum inside a while_loop whose trip count derives from
    lax.axis_index is a GUARANTEED deadlock: ranks exit the loop after
    different iteration counts, so collective call counts diverge."""
    def prog(x):
        def cond(c):
            i, _ = c
            return i < lax.axis_index("data") + 1

        def body(c):
            i, y = c
            return i + 1, lax.psum(y, "data")

        _, out = lax.while_loop(cond, body, (jnp.int32(0), x))
        return out

    diags = analysis.lint(prog, (jnp.ones(4),), axis_env=_ENV)
    assert [d.id for d in diags] == ["C8"]
    assert diags[0].severity == analysis.ERROR
    assert "while" in diags[0].path
    assert "axis_index" in diags[0].message
    assert "psum" in diags[0].message


def test_c8_taint_reaches_trip_count_through_carry():
    """fori_loop with an axis_index-derived upper bound: the taint
    rides the loop carry into the cond, not the cond closure — the
    fixpoint over carried values must still mark the trip count."""
    def prog(x):
        n = lax.axis_index("data") + 1
        return lax.fori_loop(0, n,
                             lambda _, y: lax.psum(y, "data"), x)

    diags = analysis.lint(prog, (jnp.ones(4),), axis_env=_ENV)
    assert [d.id for d in diags] == ["C8"]


def test_c8_quiet_fixtures():
    """Static-bound while with a collective: fine. Rank-dependent trip
    count WITHOUT collectives in the body: fine (pure local compute may
    legally diverge). Collective inside scan: trip count is static by
    construction — never C8."""
    def static_while(x):
        def cond(c):
            i, _ = c
            return i < 3

        def body(c):
            i, y = c
            return i + 1, lax.psum(y, "data")

        _, out = lax.while_loop(cond, body, (jnp.int32(0), x))
        return out

    def tainted_no_collective(x):
        n = lax.axis_index("data") + 1
        return lax.fori_loop(0, n, lambda _, y: y * 2.0, x)

    def collective_scan(x):
        def step(c, _):
            return lax.psum(c, "data"), None
        out, _ = lax.scan(step, x, jnp.arange(3))
        return out

    x = jnp.ones(4)
    assert analysis.lint(static_while, (x,), axis_env=_ENV) == []
    assert analysis.lint(tainted_no_collective, (x,), axis_env=_ENV) == []
    assert analysis.lint(collective_scan, (x,), axis_env=_ENV) == []


def test_allowlist_suppresses_by_id_and_path():
    def prog(x):
        return lax.psum(x.astype(jnp.float32), "data")

    x = jnp.ones(8, jnp.bfloat16)
    assert analysis.lint(prog, (x,), axis_env=_ENV, allow=("C3",)) == []
    [d] = analysis.lint(prog, (x,), axis_env=_ENV)
    assert analysis.lint(prog, (x,), axis_env=_ENV,
                         allow=(f"C3:{d.path}",)) == []


# ---- shipped programs: every combination must lint clean -------------

@pytest.mark.parametrize("name", programs.program_names())
def test_shipped_program_is_clean(hvdlint_shipped, name):
    hvdlint_shipped(name)


@pytest.mark.parametrize("name", ["llama_train_step",
                                  "pipeline_interleaved_1f1b"])
def test_shipped_moe_program_is_clean(hvdlint_shipped, name):
    hvdlint_shipped(name, config="tiny_moe")


def test_full_suite_without_shard_map(monkeypatch):
    """The analyzer must run end-to-end on boxes whose jax lacks
    ``jax.shard_map`` (the 0.4.x CPU substrate, where pipelines execute
    under vmap emulation). Force the attribute away and run the whole
    shipped-program sweep."""
    if hasattr(jax, "shard_map"):
        monkeypatch.delattr(jax, "shard_map")
    results = programs.lint_all()
    assert set(results) == set(programs.program_names())
    bad = {n: [d.format() for d in ds]
           for n, ds in results.items() if ds}
    assert not bad, bad


def test_cli_single_program_and_exit_codes(capsys):
    from horovod_tpu.analysis.lint import main

    assert main(["--program", "pipeline_gpipe"]) == 0
    out = capsys.readouterr().out
    assert "pipeline_gpipe: clean" in out
    assert main(["--list"]) == 0
    assert "llama_train_step" in capsys.readouterr().out
