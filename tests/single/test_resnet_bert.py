"""ResNet + BERT model families: shapes, semantics, sharded train step.

Reference analog: the reference validates its benchmark models by
training them end-to-end in examples; here they are library code so they
get unit tests (same pattern as test_llama.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu import parallel
from horovod_tpu.models import (
    BertConfig,
    ResNetConfig,
    bert_forward,
    bert_init,
    bert_mlm_loss,
    bert_partition_rules,
    resnet_forward,
    resnet_init,
    resnet_loss,
)
from horovod_tpu.parallel.sharding import apply_sharding, named_sharding


# ---- resnet ----

def _tiny_resnet(depth=18):
    return ResNetConfig(depth=depth, num_classes=7, width=8,
                        compute_dtype="float32")


def test_resnet_forward_shapes():
    cfg = _tiny_resnet()
    params, state = resnet_init(cfg, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    logits, new_state = resnet_forward(params, state, x, cfg, train=True)
    assert logits.shape == (2, 7)
    assert logits.dtype == jnp.float32
    # Training updates running stats away from init.
    stem = new_state["stem"]["bn"]
    assert not np.allclose(np.asarray(stem["mean"]), 0.0)


def test_resnet_bottleneck_variant():
    cfg = _tiny_resnet(depth=50)
    params, state = resnet_init(cfg, jax.random.PRNGKey(0))
    assert "conv3" in params["stage0"][0]  # bottleneck blocks
    x = jnp.zeros((1, 32, 32, 3))
    logits, _ = resnet_forward(params, state, x, cfg, train=False)
    assert logits.shape == (1, 7)


def test_resnet_eval_uses_running_stats():
    cfg = _tiny_resnet()
    params, state = resnet_init(cfg, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 32, 32, 3))
    l1, s1 = resnet_forward(params, state, x, cfg, train=False)
    # eval must not mutate state
    assert np.allclose(np.asarray(s1["stem"]["bn"]["mean"]),
                       np.asarray(state["stem"]["bn"]["mean"]))


def test_resnet_train_step_decreases_loss():
    cfg = _tiny_resnet()
    params, state = resnet_init(cfg, jax.random.PRNGKey(0))
    tx = optax.sgd(0.5)
    opt = tx.init(params)
    x = jax.random.normal(jax.random.PRNGKey(3), (8, 32, 32, 3))
    y = jnp.arange(8) % 7
    batch = {"images": x, "labels": y}

    @jax.jit
    def step(params, state, opt):
        (loss, state), grads = jax.value_and_grad(
            resnet_loss, has_aux=True)(params, state, batch, cfg)
        updates, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, updates), state, opt, loss

    losses = []
    for _ in range(5):
        params, state, opt, loss = step(params, state, opt)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


# ---- bert ----

def test_bert_forward_shapes():
    cfg = BertConfig.tiny(dtype="float32")
    params = bert_init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    logits = bert_forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_bert_bidirectional():
    # Unlike llama, changing a LATER token changes EARLIER logits.
    cfg = BertConfig.tiny(dtype="float32")
    params = bert_init(cfg, jax.random.PRNGKey(0))
    t1 = jnp.zeros((1, 8), jnp.int32)
    t2 = t1.at[0, 7].set(5)
    l1 = bert_forward(params, t1, cfg)
    l2 = bert_forward(params, t2, cfg)
    assert not np.allclose(np.asarray(l1[0, 0]), np.asarray(l2[0, 0]))


def test_bert_padding_masked_out():
    # Logits at real positions must ignore padding tokens' content.
    cfg = BertConfig.tiny(dtype="float32")
    params = bert_init(cfg, jax.random.PRNGKey(0))
    mask = jnp.array([[1, 1, 1, 1, 0, 0, 0, 0]])
    t1 = jnp.zeros((1, 8), jnp.int32)
    t2 = t1.at[0, 6].set(9)  # change only a padded position
    l1 = bert_forward(params, t1, cfg, attention_mask=mask)
    l2 = bert_forward(params, t2, cfg, attention_mask=mask)
    np.testing.assert_allclose(np.asarray(l1[0, :4]), np.asarray(l2[0, :4]),
                               atol=1e-5)


def test_bert_fully_padded_sample_no_nan():
    # A ragged final batch pads with empty sequences: attention_mask all
    # zero for that sample. The loss must stay finite (regression: -inf
    # mask bias made softmax NaN and poisoned the whole batch).
    cfg = BertConfig.tiny(dtype="float32")
    params = bert_init(cfg, jax.random.PRNGKey(0))
    tokens = jnp.zeros((2, 8), jnp.int32)
    mask = jnp.array([[1] * 8, [0] * 8])
    mlm = jnp.array([[1.0] * 8, [0.0] * 8])
    batch = {"tokens": tokens, "targets": tokens, "mlm_mask": mlm,
             "attention_mask": mask}
    loss = bert_mlm_loss(params, batch, cfg)
    assert jnp.isfinite(loss)


def test_bert_pos_embed_partition_rule():
    # pos_embed must hit its own rule, not the tied-embedding rule
    # (regression: r"embed$" shadowed r"pos_embed").
    import re
    rules = bert_partition_rules()
    first = next(spec for pat, spec in rules if re.search(pat, "pos_embed"))
    from jax.sharding import PartitionSpec as P
    assert first == P(None, "fsdp")
    tied = next(spec for pat, spec in rules if re.search(pat, "embed"))
    assert tied == P("tensor", "fsdp")


def test_bert_mlm_loss_finite_and_masked():
    cfg = BertConfig.tiny(dtype="float32")
    params = bert_init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens, "targets": tokens,
             "mlm_mask": jnp.zeros((2, 16)).at[:, :4].set(1)}
    loss = bert_mlm_loss(params, batch, cfg)
    assert jnp.isfinite(loss)
    # With no predicted positions, loss is exactly 0 (div guarded).
    batch0 = dict(batch, mlm_mask=jnp.zeros((2, 16)))
    assert float(bert_mlm_loss(params, batch0, cfg)) == 0.0


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_bert_sharded_train_step():
    cfg = BertConfig.tiny(dtype="float32", d_model=64, n_heads=4)
    mesh = parallel.create_mesh(data=2, fsdp=2, tensor=2,
                                devices=jax.devices()[:8])
    params = bert_init(cfg, jax.random.PRNGKey(0))
    shardings = parallel.shard_params(params, mesh, bert_partition_rules())
    params = apply_sharding(params, shardings)
    tx = optax.adam(1e-3)
    opt = tx.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens, "targets": tokens,
             "mlm_mask": jnp.ones((4, 16))}
    batch = jax.device_put(batch, named_sharding(mesh, ("data", "fsdp")))

    @jax.jit
    def step(params, opt, batch):
        loss, grads = jax.value_and_grad(bert_mlm_loss)(params, batch, cfg)
        updates, opt = tx.update(grads, opt, params)
        return loss, optax.apply_updates(params, updates), opt

    loss, params, opt = step(params, opt, batch)
    assert jnp.isfinite(loss)
