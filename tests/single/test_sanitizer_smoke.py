"""ThreadSanitizer smoke of the native core's multi-threaded paths.

``make core-tsan`` builds ``horovod_tpu/lib/libhvdtpu_core_tsan.so``
(``-fsanitize=thread``); when that .so is present this test drives
controller + tensor_queue through a multi-threaded allreduce workload
in a subprocess (the TSan runtime must be LD_PRELOADed before python
starts, hence the subprocess) and fails on any data-race report.

When the sanitized .so has not been built — the normal tier-1 state,
since the build costs ~25 s — the test SKIPS: sanitizer runs are an
opt-in lane (``make core-tsan && pytest tests/single/
test_sanitizer_smoke.py``). The knob/counter surfaces the workload
hammers (timeline start/stop churn, fusion-threshold and cycle-time
setters, response-cache stats) are exactly the spots a runtime rebuild
tends to leave racy; the current core passes because they are atomics
or mutex-protected by design, and this test pins that property.
"""

import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
TSAN_LIB = os.path.join(REPO, "horovod_tpu", "lib",
                        "libhvdtpu_core_tsan.so")

_DRIVER = textwrap.dedent("""
    import os, threading
    import numpy as np
    for k in ("HOROVOD_RANK", "HOROVOD_SIZE", "HOROVOD_LOCAL_RANK",
              "HOROVOD_LOCAL_SIZE"):
        os.environ.pop(k, None)
    from horovod_tpu.common import basics
    from horovod_tpu.common import eager_ops as ops

    b = basics.HorovodBasics()
    b.init()
    stop = threading.Event()

    def churner():
        # Timeline lifecycle + runtime knobs + cache counters from a
        # non-loop thread, concurrent with enqueues below.
        i = 0
        while not stop.is_set():
            try:
                b.start_timeline("/tmp/hvdtpu_tsan_timeline.json")
            except ValueError:
                pass
            b.lib.hvdtpu_set_fusion_threshold_bytes((1 << 20) + i)
            b.lib.hvdtpu_set_cycle_time_ms(0.5 + (i % 3))
            b.response_cache_stats()
            # Metrics snapshot from an API thread while the background
            # loop records into the registry (the r9 read path).
            b.metrics_snapshot()
            # Event-ring readers (consuming drain + non-consuming peek)
            # concurrent with the loop's and the ring engine's wait-free
            # writers — the r15 flight-recorder read path.
            b.events_drain()
            b.events(64)
            b.stop_timeline()
            i += 1

    def worker(tid):
        for i in range(15):
            x = np.full((256,), tid, np.float32)
            ops.allreduce_async(x, f"w{tid}_i{i}").synchronize()
            ops.allgather_async(x, f"ag{tid}_i{i}").synchronize()

    def ring_hammer(tid):
        # The chunked/compressed ring engine under TSan: each selftest
        # spins up 4 in-process rank planes, each with its own transfer
        # legs + worker pool (csrc/ring_selftest.cc), alternating
        # bf16-compressed and exact passes and cycling the stripe width
        # (K=4 adds per-channel transfer threads + per-channel reduce
        # workers) — concurrent with the metrics-snapshot churner
        # reading the wire counters the engine's tally writes.
        for i in range(6):
            rc, _err = b.ring_selftest(4, 20000, dtype=6, op=1,
                                       chunk_bytes=2048,
                                       compression=(i % 2 == 1),
                                       channels=(4 if i % 3 == 2 else 1))
            assert rc == 0, (tid, i, rc)

    c = threading.Thread(target=churner)
    c.start()
    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(4)]
    threads += [threading.Thread(target=ring_hammer, args=(t,))
                for t in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    c.join()
    b.shutdown()
    print("SMOKE_OK")
""")


def _find_tsan_runtime():
    """The libtsan.so to LD_PRELOAD (the host python is uninstrumented,
    so the runtime must come in before interpreter start)."""
    try:
        out = subprocess.run(
            ["g++", "-print-file-name=libtsan.so"],
            capture_output=True, text=True, timeout=30).stdout.strip()
        if out and os.sep in out and os.path.exists(out):
            return out
    except (OSError, subprocess.TimeoutExpired):
        pass
    for cand in ("/usr/lib/x86_64-linux-gnu/libtsan.so.0",
                 "/usr/lib/x86_64-linux-gnu/libtsan.so"):
        if os.path.exists(cand):
            return cand
    return None


# Elastic fault lane: rank 1 dies by deterministic injection at its
# 2nd collective; rank 0 must get the typed error (API thread reading
# the fault record the background thread wrote), re-form a 1-rank ring
# via hvdtpu_reinit, and keep collecting metrics — the detection /
# record / reinit handoff is exactly the cross-thread traffic a rebuild
# tends to leave racy (docs/elastic.md).
_FAULT_DRIVER = textwrap.dedent("""
    import numpy as np
    from horovod_tpu.common import basics
    from horovod_tpu.common import eager_ops as ops
    from horovod_tpu.common.exceptions import HorovodPeerFailureError

    b = basics.HorovodBasics()
    b.init()
    x = np.ones(4096, np.float32)
    ops.allreduce_async(x, "w0").synchronize()          # op 0
    try:
        ops.allreduce_async(x, "boom").synchronize()    # op 1: rank 1 dies
        raise SystemExit("boom did not fail")
    except HorovodPeerFailureError as e:
        assert 1 in e.fault_ranks, e.fault_ranks
    assert b.last_fault() is not None
    b.reinit([0], 1)
    out = ops.allreduce_async(x, "reformed").synchronize()
    assert (out == x).all()
    assert b.metrics_snapshot()["elastic"]["faults_recovered"] == 1
    b.shutdown()
    print("FAULT_SMOKE_OK")
""")


# Event-ring churn lane (r15): concurrent events_drain/peek + metrics
# snapshots + the ring selftest's multi-plane writers, WHILE the main
# thread hammers healthy-loop reinit epoch bumps — every reinit joins
# and restarts the background thread, re-records epoch/reinit events,
# and the drain cursor must stay consistent through the churn. The
# ring's slots are all atomics by design; this pins that property.
_EVENTS_REINIT_DRIVER = textwrap.dedent("""
    import os, threading
    import numpy as np
    for k in ("HOROVOD_RANK", "HOROVOD_SIZE", "HOROVOD_LOCAL_RANK",
              "HOROVOD_LOCAL_SIZE"):
        os.environ.pop(k, None)
    from horovod_tpu.common import basics
    from horovod_tpu.common import eager_ops as ops

    b = basics.HorovodBasics()
    b.init()
    stop = threading.Event()
    drained = [0]

    def drainer():
        while not stop.is_set():
            drained[0] += len(b.events_drain())
            b.events(32)
            b.metrics_snapshot()

    def ring_hammer():
        for i in range(4):
            rc, _err = b.ring_selftest(4, 8000, chunk_bytes=1024,
                                       compression=(i % 2 == 1))
            assert rc == 0, (i, rc)

    t = threading.Thread(target=drainer)
    rh = threading.Thread(target=ring_hammer)
    t.start()
    rh.start()
    epoch = 0
    for i in range(6):
        epoch += 1
        # Healthy-loop reinit (negotiated shutdown; a size-1 world is
        # legal): epoch bump + bg-thread restart under reader churn.
        b.reinit([0], epoch)
        x = np.full(64, float(epoch), np.float32)
        out = ops.allreduce_async(x, f"e{epoch}").synchronize()
        assert (out == x).all()
    rh.join()
    stop.set()
    t.join()
    assert b.epoch() == epoch
    assert drained[0] > 0
    b.shutdown()
    print("EVENTS_SMOKE_OK")
""")


def _tsan_env():
    runtime = _find_tsan_runtime()
    if runtime is None:
        return None
    env = dict(os.environ)
    env.update({
        "LD_PRELOAD": runtime,
        "HVDTPU_CORE_LIB": os.path.basename(TSAN_LIB),
        "TSAN_OPTIONS": "exitcode=66 halt_on_error=0",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    return env


def test_tsan_fault_reinit_smoke():
    if not os.path.exists(TSAN_LIB):
        pytest.skip("TSan core not built (run `make core-tsan`)")
    env = _tsan_env()
    if env is None:
        pytest.skip("no libtsan runtime on this host")
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    procs = []
    for rank in range(2):
        renv = dict(env,
                    HOROVOD_RANK=str(rank), HOROVOD_SIZE="2",
                    HOROVOD_LOCAL_RANK=str(rank),
                    HOROVOD_LOCAL_SIZE="2",
                    HOROVOD_CONTROLLER_ADDR="127.0.0.1",
                    HOROVOD_CONTROLLER_PORT=str(port),
                    HOROVOD_WIRE_TIMEOUT_MS="4000",
                    HOROVOD_FAULT_INJECT="1:1")
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _FAULT_DRIVER], env=renv,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    try:
        out0, _ = procs[0].communicate(timeout=300)
        procs[1].wait(timeout=30)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    if procs[0].returncode != 0 and "ThreadSanitizer" not in out0:
        pytest.skip(f"TSan subprocess unusable on this host: "
                    f"rc={procs[0].returncode} {out0[-400:]}")
    assert "WARNING: ThreadSanitizer" not in out0, out0[-4000:]
    assert procs[0].returncode == 0, out0[-2000:]
    assert "FAULT_SMOKE_OK" in out0
    assert procs[1].returncode == -9  # died at the injected collective


def test_tsan_multithreaded_allreduce_smoke():
    if not os.path.exists(TSAN_LIB):
        pytest.skip("TSan core not built (run `make core-tsan`)")
    runtime = _find_tsan_runtime()
    if runtime is None:
        pytest.skip("no libtsan runtime on this host")

    env = dict(os.environ)
    env.update({
        "LD_PRELOAD": runtime,
        "HVDTPU_CORE_LIB": os.path.basename(TSAN_LIB),
        "TSAN_OPTIONS": "exitcode=66 halt_on_error=0",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    proc = subprocess.run([sys.executable, "-c", _DRIVER],
                          capture_output=True, text=True, env=env,
                          timeout=300)
    out = proc.stdout + proc.stderr
    if proc.returncode != 0 and "ThreadSanitizer" not in out:
        pytest.skip(f"TSan subprocess unusable on this host: "
                    f"rc={proc.returncode} {out[-400:]}")
    assert "WARNING: ThreadSanitizer" not in out, out[-4000:]
    assert proc.returncode == 0, out[-2000:]
    assert "SMOKE_OK" in out


def test_tsan_events_drain_snapshot_reinit_hammer():
    """Concurrent events_drain/peek + metrics snapshots + multi-plane
    ring writers while the main thread bumps epochs through healthy
    reinit — the event ring must be TSan-clean under churn (r15
    acceptance)."""
    if not os.path.exists(TSAN_LIB):
        pytest.skip("TSan core not built (run `make core-tsan`)")
    env = _tsan_env()
    if env is None:
        pytest.skip("no libtsan runtime on this host")
    proc = subprocess.run([sys.executable, "-c", _EVENTS_REINIT_DRIVER],
                          capture_output=True, text=True, env=env,
                          timeout=300)
    out = proc.stdout + proc.stderr
    if proc.returncode != 0 and "ThreadSanitizer" not in out:
        pytest.skip(f"TSan subprocess unusable on this host: "
                    f"rc={proc.returncode} {out[-400:]}")
    assert "WARNING: ThreadSanitizer" not in out, out[-4000:]
    assert proc.returncode == 0, out[-2000:]
    assert "EVENTS_SMOKE_OK" in out
