"""The autoscaler policy as a pure function (docs/scale.md): synthetic
signal traces — ramp, spike, flap, drain — against the hysteresis
contract. No core, no processes: decisions are a deterministic map of
the observation stream, which is exactly what makes the policy safe to
run rank-uniformly."""

import pytest

from horovod_tpu.telemetry.autoscale import (
    Autoscaler,
    AutoscalePolicy,
    Decision,
    Signals,
)

pytestmark = pytest.mark.quick


def _policy(**kw):
    kw.setdefault("up_consecutive", 3)
    kw.setdefault("down_consecutive", 4)
    kw.setdefault("cooldown_s", 10.0)
    kw.setdefault("min_size", 2)
    kw.setdefault("max_size", 8)
    return AutoscalePolicy(**kw)


def _sig(t, size=4, queue=0, skew=0.0, step=0.0, faults=0.0, heals=0.0,
         rejoiners=0):
    return Signals(t=float(t), world_size=size, queue_depth=queue,
                   straggler_skew_ms=skew, step_time_ms=step,
                   fault_rate=faults, heal_rate=heals,
                   pending_rejoiners=rejoiners)


def _drive(policy, trace):
    return [policy.decide(s) for s in trace]


def test_signals_backward_compatible_with_pre_r17_field_set():
    """The r17 overlap-ledger fields default: observation sources that
    predate them (recorded traces, older /healthz payloads) must still
    construct Signals — and the policy must decide identically when
    they are absent (they carry no decision weight yet)."""
    s = Signals(t=0.0, world_size=4)
    assert s.overlap_efficiency == 0.0
    assert s.exposed_wire_ms == 0.0
    rich = Signals(t=0.0, world_size=4, overlap_efficiency=0.8,
                   exposed_wire_ms=123.4)
    a, b = _policy(), _policy()
    assert a.decide(s) == b.decide(rich)


def test_signals_fleet_slo_fields_default_and_decision_invariant():
    """The r23 fleet/SLO fields (docs/fleet.md) follow the same
    back-compat discipline: pre-fleet observation sources construct
    Signals unchanged, and a fully-populated fleet view carries no
    decision weight yet — the policy must decide identically with and
    without it."""
    s = Signals(t=0.0, world_size=4)
    assert s.slo_breaches == 0
    assert s.slo_breach_rate == 0.0
    assert s.fleet_utilization == 0.0
    assert s.rank_seconds_unattributed_share == 0.0
    rich = Signals(t=0.0, world_size=4, slo_breaches=7,
                   slo_breach_rate=2.0, fleet_utilization=0.42,
                   rank_seconds_unattributed_share=0.03)
    a, b = _policy(), _policy()
    assert a.decide(s) == b.decide(rich)


def test_ramp_scales_up_after_streak_then_cools_down():
    p = _policy()
    trace = [_sig(t, queue=20) for t in range(8)]
    actions = [d.action for d in _drive(p, trace)]
    # Two holds banking the streak, the up at t=2, then cooldown holds.
    assert actions[:3] == ["hold", "hold", "up"], actions
    assert all(a == "hold" for a in actions[3:]), actions
    # After the cooldown expires, sustained load scales again.
    more = _drive(p, [_sig(13 + t, size=5, queue=20) for t in range(4)])
    assert [d.action for d in more][:3] == ["hold", "hold", "up"], more


def test_single_spike_never_scales():
    p = _policy()
    trace = ([_sig(0, queue=0)] + [_sig(1, queue=100)]
             + [_sig(2 + t, queue=0, skew=0.1) for t in range(3)])
    assert all(d.action == "hold" for d in _drive(p, trace))


def test_flap_never_oscillates_world_size():
    """The hysteresis acceptance: a signal flapping between overload
    and idle every observation must produce ZERO resizes — the
    deadband resets the opposite streak each flip."""
    p = _policy()
    trace = [_sig(t, queue=(100 if t % 2 == 0 else 0),
                  skew=(0.0 if t % 2 == 0 else 200.0))
             for t in range(40)]
    decisions = _drive(p, trace)
    assert all(d.action == "hold" for d in decisions), [
        (i, d.action) for i, d in enumerate(decisions)
        if d.action != "hold"]


def test_sustained_idle_scales_down_to_min_and_stops():
    p = _policy(cooldown_s=2.0)
    decisions = _drive(p, [_sig(t, size=3, queue=0, skew=1.0)
                           for t in range(30)])
    downs = [d for d in decisions if d.action == "down"]
    assert downs and downs[0].target_size == 2, decisions
    # At min_size the policy can only hold.
    p2 = _policy()
    at_min = _drive(p2, [_sig(t, size=2, queue=0) for t in range(10)])
    assert all(d.action == "hold" for d in at_min)


def test_step_time_trend_triggers_scale_up_against_own_baseline():
    p = _policy(up_consecutive=2, baseline_alpha=0.0)
    # Establish a ~100ms baseline, then run 2x slower with an empty
    # queue: the trend signal alone must scale up.
    for t in range(5):
        assert p.decide(_sig(t, step=100.0, queue=5, skew=100.0)
                        ).action == "hold"  # deadband: busy-ish
    late = _drive(p, [_sig(10 + t, step=220.0, queue=0, skew=100.0)
                      for t in range(3)])
    assert [d.action for d in late][:2] == ["hold", "up"], late


def test_instability_gates_all_scaling():
    p = _policy(up_consecutive=1, down_consecutive=1)
    # Overloaded AND faulting: hold. Idle AND healing: hold.
    assert p.decide(_sig(0, queue=100, faults=1.0)).action == "hold"
    assert p.decide(_sig(1, queue=0, heals=2.0)).action == "hold"
    # The streaks were reset — stability must re-bank them.
    assert p.decide(_sig(2, queue=100)).action == "up"  # streak of 1


def test_max_size_caps_growth():
    p = _policy(up_consecutive=1)
    d = p.decide(_sig(0, size=8, queue=100))
    assert d.action == "hold", d  # already at max


def test_autoscaler_driver_applies_decisions_via_callbacks():
    calls = []
    feed = iter([_sig(t, queue=20) for t in range(3)]
                + [_sig(20 + t, size=5, queue=0, skew=0.0)
                   for t in range(6)])
    a = Autoscaler(policy=_policy(up_consecutive=3, down_consecutive=4,
                                  cooldown_s=1.0),
                   collect=lambda: next(feed),
                   grow=lambda d: calls.append(("grow", d.target_size)),
                   shrink=lambda d: calls.append(
                       ("shrink", d.target_size)))
    decisions = [a.step() for _ in range(9)]
    assert calls == [("grow", 5), ("shrink", 4)], (calls, decisions)
    assert len(a.history) == 9
    assert all(isinstance(d, Decision) for d in decisions)
