"""Flagship transformer: correctness + sharded train-step compilation on
the 8-virtual-device mesh (the shape of the driver's dryrun_multichip)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax

from horovod_tpu import parallel
from horovod_tpu.models import (
    LlamaConfig,
    llama_forward,
    llama_init,
    llama_loss,
    llama_partition_rules,
)
from horovod_tpu.parallel.sharding import apply_sharding, named_sharding


def test_forward_shapes_and_determinism():
    cfg = LlamaConfig.tiny(dtype="float32")
    params = llama_init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    logits = llama_forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    np.testing.assert_allclose(
        np.asarray(llama_forward(params, tokens, cfg)), np.asarray(logits))


def test_causality():
    # Changing a future token must not change past logits.
    cfg = LlamaConfig.tiny(dtype="float32")
    params = llama_init(cfg, jax.random.PRNGKey(0))
    t1 = jnp.zeros((1, 8), jnp.int32)
    t2 = t1.at[0, 7].set(5)
    l1 = llama_forward(params, t1, cfg)
    l2 = llama_forward(params, t2, cfg)
    np.testing.assert_allclose(np.asarray(l1[0, :7]), np.asarray(l2[0, :7]),
                               rtol=1e-5, atol=1e-6)
    assert not np.allclose(np.asarray(l1[0, 7]), np.asarray(l2[0, 7]))


def test_sharded_train_step_matches_single_device():
    """dp=2 x fsdp=2 x tensor=2 (+ring attention via seq in the next test):
    the sharded train step must produce the same loss and params as the
    unsharded one."""
    cfg = LlamaConfig.tiny(dtype="float32", n_layers=2)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    # SGD: parameter deltas are linear in the gradient, so they compare
    # cleanly across shardings (adam's eps-normalized first step would
    # amplify 1e-8 reduction-order noise on near-zero grads to full
    # lr-sized sign flips).
    tx = optax.sgd(1e-1)
    opt = tx.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}

    def step(params, opt, batch, mesh=None):
        loss, grads = jax.value_and_grad(llama_loss)(params, batch, cfg,
                                                     mesh)
        updates, opt = tx.update(grads, opt, params)
        return loss, optax.apply_updates(params, updates), opt

    loss_ref, params_ref, _ = jax.jit(
        lambda p, o, b: step(p, o, b))(params, opt, batch)

    mesh = parallel.create_mesh(data=2, fsdp=2, tensor=2)
    shardings = parallel.shard_params(params, mesh, llama_partition_rules())
    p_sh = apply_sharding(params, shardings)
    opt_sh = tx.init(p_sh)
    b_sh = jax.device_put(
        batch, named_sharding(mesh, ("data", "fsdp"), None))

    sharded_step = jax.jit(lambda p, o, b: step(p, o, b, mesh))
    loss_sh, params_new, _ = sharded_step(p_sh, opt_sh, b_sh)

    np.testing.assert_allclose(float(loss_sh), float(loss_ref), rtol=1e-5)
    for a, b_ in zip(jax.tree.leaves(params_ref),
                     jax.tree.leaves(params_new)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-4,
                                   atol=1e-6)


def test_flash_block_is_pure_scheduling():
    """LlamaConfig.flash_block (the bench --sweep knob for the pallas
    q/k grid blocks) must not change the math: loss and grads match the
    kernel-default config. Runs the REAL pallas kernels in interpret
    mode (the XLA fallback ignores the block args, which would make
    this test vacuous on CPU) — an oversized block exercises
    _pick_block's clamp-to-sequence too."""
    import dataclasses
    import importlib

    fa_mod = importlib.import_module("horovod_tpu.ops.flash_attention")

    cfg = LlamaConfig.tiny(dtype="float32", n_layers=2, remat=False)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 32), 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
    fa_mod._INTERPRET = True
    try:
        ref_l, ref_g = jax.value_and_grad(llama_loss)(params, batch, cfg)
        for block in (16, 512):  # clamped to t=32 / below it
            cfg_b = dataclasses.replace(cfg, flash_block=block)
            l, g = jax.value_and_grad(llama_loss)(params, batch, cfg_b)
            np.testing.assert_allclose(float(l), float(ref_l),
                                       rtol=1e-6)
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-5,
                    atol=1e-6),
                ref_g, g)
    finally:
        fa_mod._INTERPRET = False


def _skip_without_shard_map():
    # The ring/ulysses/pipeline paths build on jax.shard_map; older jax
    # (< 0.6, e.g. a CPU-only dev box) only has the experimental alias.
    if not hasattr(jax, "shard_map"):
        import pytest
        pytest.skip("needs jax.shard_map (jax >= 0.6)")


def test_seq_parallel_forward_matches():
    """Ring-attention path (seq=4) must match the single-device forward."""
    _skip_without_shard_map()
    cfg = LlamaConfig.tiny(dtype="float32", n_layers=2)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 32), 0,
                                cfg.vocab_size)
    ref = llama_forward(params, tokens, cfg)

    mesh = parallel.create_mesh(data=2, seq=4)
    shardings = parallel.shard_params(params, mesh, llama_partition_rules())
    p_sh = apply_sharding(params, shardings)
    t_sh = jax.device_put(tokens,
                          named_sharding(mesh, ("data", "fsdp"), "seq"))
    out = jax.jit(
        lambda p, t: llama_forward(p, t, cfg, mesh))(p_sh, t_sh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-4)


def test_seq_parallel_ulysses_matches():
    """Ulysses path (seq_parallel="ulysses", seq=4) must match the
    single-device forward (tiny config has 4 heads -> divisible)."""
    _skip_without_shard_map()
    cfg = LlamaConfig.tiny(dtype="float32", n_layers=2,
                           seq_parallel="ulysses")
    params = llama_init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 32), 0,
                                cfg.vocab_size)
    ref = llama_forward(params, tokens, cfg)

    mesh = parallel.create_mesh(data=2, seq=4)
    shardings = parallel.shard_params(params, mesh, llama_partition_rules())
    p_sh = apply_sharding(params, shardings)
    t_sh = jax.device_put(tokens,
                          named_sharding(mesh, ("data", "fsdp"), "seq"))
    out = jax.jit(
        lambda p, t: llama_forward(p, t, cfg, mesh))(p_sh, t_sh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-4)

# ---- sparse mixture-of-experts (expert parallelism) ----

def test_moe_forward_and_aux():
    cfg = LlamaConfig.tiny_moe(dtype="float32", remat=False)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    assert params["layers"]["moe_gate"].shape == (
        cfg.n_layers, cfg.n_experts, cfg.d_model, cfg.d_ff)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    logits, aux = llama_forward(params, tokens, cfg, return_aux=True)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()
    # Switch aux loss is >= 1 (== 1 only at perfectly uniform routing).
    assert 0.9 < float(aux) < float(cfg.n_experts)


def test_moe_routing_is_sparse():
    # Zeroing an expert's weights must change ONLY tokens routed to it;
    # with k=1 routing, tokens routed elsewhere are bit-identical.
    cfg = LlamaConfig.tiny_moe(dtype="float32", n_layers=1, remat=False,
                               n_experts_per_token=1, capacity_factor=4.0)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    # Enough tokens that every expert gets traffic with overwhelming
    # probability (routing is data-dependent).
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0,
                                cfg.vocab_size)
    ref = np.asarray(llama_forward(params, tokens, cfg))
    mutated = jax.tree.map(lambda x: x, params)
    mutated["layers"]["moe_down"] = (
        params["layers"]["moe_down"].at[:, 0].set(0.0))
    out = np.asarray(llama_forward(mutated, tokens, cfg))
    changed = ~np.isclose(ref, out).all(axis=-1)  # [B, T] per-token
    assert changed.any(), "no token used expert 0"
    assert not changed.all(), "zeroing one expert changed every token"


def test_moe_train_step_decreases_loss():
    cfg = LlamaConfig.tiny_moe(dtype="float32", remat=False)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    tx = optax.adam(1e-2)
    opt = tx.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}

    @jax.jit
    def step(p, o):
        loss, g = jax.value_and_grad(llama_loss)(p, batch, cfg)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    losses = []
    for _ in range(6):
        params, opt, loss = step(params, opt)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_moe_expert_parallel_matches_single_device():
    """EP×TP×FSDP sharded MoE step must produce the same loss as the
    unsharded one (same init, same batch)."""
    if len(jax.devices()) < 8:
        import pytest
        pytest.skip("needs 8 virtual devices")
    # Pin the GShard dispatch on BOTH sides: this test certifies EP
    # sharding, and the mesh-free default would otherwise pick the
    # dropless grouped path whose no-drop semantics legitimately
    # diverge from capacity-1.25 GShard (see
    # test_grouped_moe_matches_gshard_when_dropless for that parity).
    cfg = LlamaConfig.tiny_moe(dtype="float32", remat=False,
                               moe_impl="gshard")
    params = llama_init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
    ref = float(llama_loss(params, batch, cfg))

    mesh = parallel.create_mesh(fsdp=2, expert=2, tensor=2,
                                devices=jax.devices()[:8])
    p_sh = apply_sharding(
        params, parallel.shard_params(params, mesh, llama_partition_rules()))
    b_sh = jax.device_put(batch, named_sharding(mesh, ("data", "fsdp"),
                                                "seq"))
    loss = jax.jit(lambda p, b: llama_loss(p, b, cfg, mesh))(p_sh, b_sh)
    np.testing.assert_allclose(float(loss), ref, rtol=1e-5)


# ---- pipeline parallelism (GPipe over the "pipe" axis) ----

def _skip_unless_8():
    _skip_without_shard_map()
    if len(jax.devices()) < 8:
        import pytest
        pytest.skip("needs 8 virtual devices")


def test_pipeline_forward_matches_single_device():
    _skip_unless_8()
    cfg = LlamaConfig.tiny(dtype="float32", n_layers=4, remat=False)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                                cfg.vocab_size)
    ref = np.asarray(llama_forward(params, tokens, cfg))

    mesh = parallel.create_mesh(pipe=2, fsdp=2, tensor=2,
                                devices=jax.devices()[:8])
    p_sh = apply_sharding(
        params, parallel.shard_params(params, mesh,
                                      llama_partition_rules(pipeline=True)))
    t_sh = jax.device_put(tokens,
                          named_sharding(mesh, ("data", "fsdp"), "seq"))
    out = jax.jit(lambda p, t: llama_forward(p, t, cfg, mesh))(p_sh, t_sh)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)


def test_pipeline_train_step_matches_single_device():
    """Loss AND updated params must match the unsharded step — the param
    comparison is what exercises the gpipe backward pass (grads through
    ppermute + masked collection). SGD so deltas are linear in the
    gradient (see test_sharded_train_step_matches_single_device)."""
    _skip_unless_8()
    cfg = LlamaConfig.tiny(dtype="float32", n_layers=4, remat=False)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
    tx = optax.sgd(1e-1)

    def step(p, o, bt, mesh=None):
        loss, g = jax.value_and_grad(llama_loss)(p, bt, cfg, mesh)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    p_ref, _, ref_loss = jax.jit(lambda p, o, b: step(p, o, b))(
        params, tx.init(params), batch)

    mesh = parallel.create_mesh(pipe=2, fsdp=2, tensor=2,
                                devices=jax.devices()[:8])
    p_sh = apply_sharding(
        params, parallel.shard_params(params, mesh,
                                      llama_partition_rules(pipeline=True)))
    b_sh = jax.device_put(batch, named_sharding(mesh, ("data", "fsdp"),
                                                "seq"))
    p2, o2, loss = jax.jit(lambda p, o, b: step(p, o, b, mesh))(
        p_sh, tx.init(p_sh), b_sh)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for a, b_ in zip(jax.tree.leaves(p_ref), jax.tree.leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-6)


def test_pipeline_with_moe():
    """PP x EP x TP: logits must match; the loss differs only by the
    per-microbatch aux term (Switch aux is nonlinear in batch)."""
    _skip_unless_8()
    # gshard pinned on both sides: mesh-free "auto" would pick the
    # dropless grouped path, which legitimately diverges from
    # capacity-1.25 GShard on overflow tokens.
    cfg = LlamaConfig.tiny_moe(dtype="float32", n_layers=4, remat=False,
                               moe_impl="gshard")
    params = llama_init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                                cfg.vocab_size)
    ref = np.asarray(llama_forward(params, tokens, cfg))

    mesh = parallel.create_mesh(pipe=2, expert=2, tensor=2,
                                devices=jax.devices()[:8])
    p_sh = apply_sharding(
        params, parallel.shard_params(params, mesh,
                                      llama_partition_rules(pipeline=True)))
    t_sh = jax.device_put(tokens,
                          named_sharding(mesh, ("data", "fsdp"), "seq"))
    out = jax.jit(lambda p, t: llama_forward(p, t, cfg, mesh))(p_sh, t_sh)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)


def test_pipeline_rejects_seq_parallel():
    _skip_unless_8()
    import pytest
    cfg = LlamaConfig.tiny(dtype="float32", n_layers=4, remat=False)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    tokens = jnp.zeros((4, 16), jnp.int32)
    mesh = parallel.create_mesh(pipe=2, seq=2, tensor=2,
                                devices=jax.devices()[:8])
    with pytest.raises(ValueError, match="sequence parallelism"):
        llama_forward(params, tokens, cfg, mesh)


def test_pipeline_bf16_compiles_on_cpu():
    """bf16 activations through the pipeline must not hit XLA CPU's
    AllReducePromotion crash (regression: gpipe runs f32 on CPU)."""
    _skip_unless_8()
    cfg = LlamaConfig.tiny(n_layers=4, remat=False)  # default bf16
    params = llama_init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
    mesh = parallel.create_mesh(pipe=2, fsdp=2, tensor=2,
                                devices=jax.devices()[:8])
    p_sh = apply_sharding(
        params, parallel.shard_params(params, mesh,
                                      llama_partition_rules(pipeline=True)))
    b_sh = jax.device_put(batch, named_sharding(mesh, ("data", "fsdp"),
                                                "seq"))
    loss, grads = jax.jit(
        jax.value_and_grad(lambda p, b: llama_loss(p, b, cfg, mesh)))(
            p_sh, b_sh)
    assert np.isfinite(float(loss))
    assert all(np.isfinite(np.asarray(g, dtype=np.float32)).all()
               for g in jax.tree.leaves(grads))


def test_param_dtype_bf16():
    """param_dtype="bfloat16" stores every leaf in bf16 (the pure-bf16
    large-model recipe) and the forward/loss stays finite."""
    cfg = LlamaConfig.tiny(dtype="bfloat16", param_dtype="bfloat16",
                           n_layers=2)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    assert all(l.dtype == jnp.bfloat16 for l in jax.tree.leaves(params))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    out = llama_forward(params, tokens, cfg)
    assert bool(jnp.isfinite(out.astype(jnp.float32)).all())
    loss = llama_loss(params, {"tokens": tokens,
                               "targets": jnp.roll(tokens, -1, 1)}, cfg)
    assert bool(jnp.isfinite(loss))


def test_param_dtype_bf16_sharded():
    """bf16 params compose with TP+FSDP sharding (partition rules are
    dtype-agnostic); the sharded train step runs and stays finite."""
    cfg = LlamaConfig.tiny(dtype="bfloat16", param_dtype="bfloat16",
                           n_layers=2)
    mesh = parallel.create_mesh(data=2, fsdp=2, tensor=2)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    shardings = parallel.shard_params(params, mesh, llama_partition_rules())
    p_sh = apply_sharding(params, shardings)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0,
                                cfg.vocab_size)
    t_sh = jax.device_put(tokens,
                          named_sharding(mesh, ("data", "fsdp"), "seq"))
    tx = optax.adam(1e-3)
    opt = tx.init(p_sh)

    @jax.jit
    def step(p, o, t):
        loss, grads = jax.value_and_grad(llama_loss)(
            p, {"tokens": t, "targets": jnp.roll(t, -1, 1)}, cfg, mesh)
        updates, o = tx.update(grads, o, p)
        return loss, optax.apply_updates(p, updates), o

    loss, p2, opt = step(p_sh, opt, t_sh)
    assert bool(jnp.isfinite(loss))
    assert all(l.dtype == jnp.bfloat16 for l in jax.tree.leaves(p2))


def test_master_weights_tracks_fp32_training():
    """bf16-compute + fp32-master training must track full-fp32 training
    closely (and the master/moments must actually be fp32) — the loss
    parity contract for the mixed-precision recipe."""
    import functools

    import optax

    from horovod_tpu.parallel import master_weights

    cfg32 = LlamaConfig.tiny(d_model=64, n_layers=2, n_heads=4,
                             n_kv_heads=2, d_ff=128, vocab_size=128,
                             dtype="float32", remat=False)
    cfgmw = dataclasses.replace(cfg32, dtype="bfloat16")
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0,
                                cfg32.vocab_size)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}

    def run(cfg, use_master, steps=8):
        params = llama_init(cfg, jax.random.PRNGKey(0))
        tx = optax.adam(1e-2)
        losses = []
        if use_master:
            mw = master_weights(tx)
            state = mw.init(params)
            assert all(x.dtype == jnp.float32
                       for x in jax.tree.leaves(state.master))
            assert all(x.dtype == jnp.float32
                       for x in jax.tree.leaves(state.inner)
                       if x.dtype in (jnp.float32, jnp.bfloat16))

            @jax.jit
            def step(state, batch):
                p = mw.compute_params(state)
                loss, grads = jax.value_and_grad(llama_loss)(p, batch,
                                                             cfg)
                return loss, mw.apply(state, grads)

            for _ in range(steps):
                loss, state = step(state, batch)
                losses.append(float(loss))
        else:
            opt = tx.init(params)

            @jax.jit
            def step(params, opt, batch):
                loss, grads = jax.value_and_grad(llama_loss)(params,
                                                             batch, cfg)
                updates, opt = tx.update(grads, opt, params)
                return loss, optax.apply_updates(params, updates), opt

            for _ in range(steps):
                loss, params, opt = step(params, opt, batch)
                losses.append(float(loss))
        return losses

    ref = run(cfg32, use_master=False)
    mixed = run(cfgmw, use_master=True)
    # both optimize; final losses agree to bf16-forward tolerance
    assert ref[-1] < ref[0] and mixed[-1] < mixed[0]
    assert abs(ref[-1] - mixed[-1]) / abs(ref[-1]) < 0.05, (ref, mixed)


# ---- split-program train step + fused optimizer apply (round 6) ----

def _tiny_train_setup(batch_shape=(4, 16)):
    cfg = LlamaConfig.tiny(dtype="float32", n_layers=2, remat=False)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), batch_shape, 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
    return cfg, params, batch


def _monolithic_step(cfg, tx, params, batch):
    @jax.jit
    def step(p, o, b):
        loss, grads = jax.value_and_grad(llama_loss)(p, b, cfg)
        updates, o = tx.update(grads, o, p)
        return loss, optax.apply_updates(p, updates)

    return step(params, tx.init(params), batch)


def test_split_step_matches_monolithic():
    """The two-program step (grad jit + apply jit, donated buffers)
    must reproduce the single monolithic jit exactly: same loss, same
    updated params. SGD so parameter deltas are linear in the gradient
    (see test_sharded_train_step_matches_single_device)."""
    from horovod_tpu.parallel import make_split_train_step

    cfg, params, batch = _tiny_train_setup()
    tx = optax.sgd(1e-1)
    ref_loss, ref_params = _monolithic_step(cfg, tx, params, batch)

    ts = make_split_train_step(
        lambda p, d: llama_loss(p, d, cfg), tx)
    loss, (p2, _) = ts.step(ts.init(params), batch)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7),
        ref_params, p2)


def test_split_step_2way_accumulation_matches_monolithic():
    """2-way microbatch gradient accumulation (two sequential calls to
    the grad program into a donated accumulator, 1/N loss scaling
    inside the program) must equal the full-batch monolithic step to
    f32 reduction-order tolerance — the pin that certifies the r6
    MoE/flagship attack formulation computes the same math."""
    from horovod_tpu.parallel import make_split_train_step

    cfg, params, batch = _tiny_train_setup()
    tx = optax.sgd(1e-1)
    ref_loss, ref_params = _monolithic_step(cfg, tx, params, batch)

    ts = make_split_train_step(
        lambda p, d: llama_loss(p, d, cfg), tx, microbatches=2)
    loss, (p2, _) = ts.step(ts.init(params), batch)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        ref_params, p2)


def test_split_step_rejects_indivisible_microbatches():
    import pytest

    from horovod_tpu.parallel import make_split_train_step

    cfg, params, batch = _tiny_train_setup(batch_shape=(4, 16))
    ts = make_split_train_step(
        lambda p, d: llama_loss(p, d, cfg), optax.sgd(1e-1),
        microbatches=3)
    with pytest.raises(ValueError, match="microbatches"):
        ts.step(ts.init(params), batch)


def test_fused_adam_matches_optax():
    """The single-pass fused adam (parallel.fused_adam) is the same
    optimizer as optax.adam — moments, bias correction, update — just
    expressed as one fused elementwise pass per leaf. Multi-step so the
    count/bias-correction trajectory is covered."""
    from horovod_tpu.parallel import fused_adam

    cfg, params, batch = _tiny_train_setup()
    grads = jax.grad(llama_loss)(params, batch, cfg)

    tx = optax.adam(1e-2)
    opt = tx.init(params)
    p_ref = params
    fa = fused_adam(1e-2)
    st = fa.init(params)
    p_f = params
    for _ in range(3):
        updates, opt = tx.update(grads, opt, p_ref)
        p_ref = optax.apply_updates(p_ref, updates)
        p_f, st = fa.apply(p_f, grads, st)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7),
        p_ref, p_f)
    assert int(st.count) == 3


def test_fused_master_adam_matches_split_master():
    """fused_master_adam (adam + master cast in ONE pass) must track
    the split formulation (master_weights(optax.adam) then
    compute_params) exactly: same fp32 master trajectory, same bf16
    compute cast; moments stay fp32."""
    from horovod_tpu.parallel import fused_master_adam, master_weights

    cfg, params, batch = _tiny_train_setup()
    grads = jax.grad(llama_loss)(params, batch, cfg)

    mw = master_weights(optax.adam(1e-2))
    mw_state = mw.init(params)
    fm = fused_master_adam(1e-2)
    fm_state = fm.init(params)
    compute = fm.compute_params(fm_state)
    assert all(x.dtype == jnp.bfloat16 for x in jax.tree.leaves(compute))
    assert all(x.dtype == jnp.float32
               for t in (fm_state.master, fm_state.mu, fm_state.nu)
               for x in jax.tree.leaves(t))
    for _ in range(3):
        mw_state = mw.apply(mw_state, grads)
        compute, fm_state = fm.apply(compute, grads, fm_state)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7),
        mw_state.master, fm_state.master)
    # The fused cast IS the fused master rounded to bf16, bitwise.
    jax.tree.map(
        lambda m, c: np.testing.assert_array_equal(
            np.asarray(m.astype(jnp.bfloat16), dtype=np.float32),
            np.asarray(c, dtype=np.float32)),
        fm_state.master, compute)
    # Across the two formulations the casts agree to bf16 resolution
    # (masters within 1e-6 can round across a bf16 ULP boundary).
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, dtype=np.float32),
            np.asarray(b, dtype=np.float32), rtol=1e-2, atol=1e-3),
        mw.compute_params(mw_state), compute)


def test_split_step_with_fused_master_trains():
    """End-to-end: split-program step + 2-way accumulation + the fused
    master-adam apply optimizes (the carry holds the bf16 compute cast;
    the fp32 master lives in the optimizer state)."""
    from horovod_tpu.parallel import (
        fused_master_adam,
        make_split_train_step,
    )

    cfg, params, batch = _tiny_train_setup()
    ts = make_split_train_step(
        lambda p, d: llama_loss(p, d, cfg), fused_master_adam(1e-2),
        microbatches=2)
    carry = ts.init(params)
    assert all(x.dtype == jnp.bfloat16
               for x in jax.tree.leaves(carry[0]))
    losses = []
    for _ in range(6):
        loss, carry = ts.step(carry, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_apply_jit_emits_no_donation_warning(hvdlint):
    """The split step's apply jit must donate ONLY buffers XLA can
    actually alias (params + optimizer state; gradients have no
    matching output). The fp32-master path used to warn "Some donated
    buffers were not usable" on every compute-cast leaf (BENCH r5
    tail); this pins the r6 argument-layout fix for BOTH the fused
    master-adam apply and the optax split apply, on bf16-param
    configs where grads/params/master dtypes actually differ — at
    runtime (the XLA warning) AND statically (hvdlint's C4 check over
    the same step program, the pre-commit form of this class)."""
    import warnings

    from horovod_tpu.parallel import (
        fused_master_adam,
        make_split_train_step,
    )

    cfg = LlamaConfig.tiny(n_layers=2, remat=False,
                           param_dtype="bfloat16")  # bf16 compute+store
    params = llama_init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}

    for tx in (fused_master_adam(1e-2), optax.adam(1e-2)):
        ts = make_split_train_step(
            lambda p, d: llama_loss(p, d, cfg), tx, microbatches=2)
        carry0 = jax.eval_shape(ts.init, params)
        hvdlint(ts.step, (carry0, batch))
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            loss, carry = ts.step(ts.init(params), batch)
            jax.block_until_ready(loss)
        bad = [w for w in caught
               if "donated buffers were not usable" in str(w.message)]
        assert not bad, (type(tx).__name__, [str(w.message)
                                             for w in bad])


def test_remat_modes_agree_on_gradients():
    """Every remat policy is a pure scheduling choice: loss and grads
    must match remat=False bit-for-bit-ish (f32 tolerances). Covers the
    r4 'attn+gate'/'attn+ffn' modes whose saved FFN residuals must not
    change the math."""
    cfg0 = LlamaConfig.tiny(dtype="float32", n_layers=2, remat=False)
    params = llama_init(cfg0, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg0.vocab_size)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}

    def loss_and_grads(remat):
        cfg = dataclasses.replace(cfg0, remat=remat)
        return jax.jit(jax.value_and_grad(
            lambda p: llama_loss(p, batch, cfg)))(params)

    ref_loss, ref_grads = loss_and_grads(False)
    for mode in ("attn", "attn+gate", "attn+gate+qkv", "attn+ffn",
                 "dots", "full"):
        loss, grads = loss_and_grads(mode)
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=1e-6, err_msg=mode)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6,
                err_msg=mode),
            grads, ref_grads)


def test_remat_modes_agree_on_gradients_moe():
    """Same scheduling-only contract for the MoE layer — covers the
    saved moe_dispatch/moe_combine residuals under attn+gate."""
    cfg0 = LlamaConfig.tiny_moe(dtype="float32", n_layers=2, remat=False)
    params = llama_init(cfg0, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg0.vocab_size)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}

    def loss_and_grads(remat):
        cfg = dataclasses.replace(cfg0, remat=remat)
        return jax.jit(jax.value_and_grad(
            lambda p: llama_loss(p, batch, cfg)))(params)

    ref_loss, ref_grads = loss_and_grads(False)
    # attn+moe / moe cover the grouped path's saved residuals
    # (y_slots; x_sorted/gate/up) — remat must stay scheduling-only.
    for mode in ("attn", "attn+gate", "attn+gate+qkv", "attn+ffn",
                 "attn+moe", "moe", "dots", "full"):
        loss, grads = loss_and_grads(mode)
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=1e-6, err_msg=mode)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6,
                err_msg=mode),
            grads, ref_grads)


def test_scan_unroll_is_scheduling_only():
    """scan_unroll must not change values or gradients."""
    cfg0 = LlamaConfig.tiny_moe(dtype="float32", n_layers=4, remat="attn")
    params = llama_init(cfg0, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg0.vocab_size)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}

    def loss_and_grads(unroll):
        cfg = dataclasses.replace(cfg0, scan_unroll=unroll)
        return jax.jit(jax.value_and_grad(
            lambda p: llama_loss(p, batch, cfg)))(params)

    ref_loss, ref_grads = loss_and_grads(1)
    loss, grads = loss_and_grads(4)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        grads, ref_grads)


def test_unknown_remat_mode_rejected():
    import pytest

    cfg = LlamaConfig.tiny(dtype="float32", remat="bogus")
    params = llama_init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                cfg.vocab_size)
    with pytest.raises(ValueError, match="unknown remat mode"):
        llama_forward(params, tokens, cfg)


def test_moe_remat_modes_rejected_without_grouped_dispatch():
    """attn+moe / moe save residuals only grouped_moe_ffn emits — a
    dense config or a forced-GShard one must fail loudly instead of
    silently degrading to plain attn remat."""
    import pytest

    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 256)
    dense = LlamaConfig.tiny(dtype="float32", remat="attn+moe")
    with pytest.raises(ValueError, match="grouped MoE dispatch"):
        llama_forward(llama_init(dense, jax.random.PRNGKey(0)), tokens,
                      dense)
    gshard = LlamaConfig.tiny_moe(dtype="float32", remat="moe",
                                  moe_impl="gshard")
    with pytest.raises(ValueError, match="grouped MoE dispatch"):
        llama_forward(llama_init(gshard, jax.random.PRNGKey(0)), tokens,
                      gshard)
