"""ZeRO-1 sharded optimizer (parallel/zero.py + the zero= split step).

Pins, in order of how expensively they were learned:

- the ring segment-ownership rotation helper agrees with the C++
  engine's C ABI AND with a numpy replay of the ring order — the r10
  "(r+1)%N" off-by-one can no longer be re-derived wrong;
- bucket layout: dtype grouping, bucket_bytes chunking, padding to the
  shard count, pack/unpack roundtrip, shard-aligned boundaries;
- pack stays LAYOUT-EXACT for GSPMD-sharded leaves (the jax-0.4.x CPU
  concatenate miscompile this module's dynamic_update_slice pack dodges
  — see BucketLayout.pack);
- sharded-vs-replicated parity at N in {2, 4}: grads (via loss),
  params, and optimizer state of the zero split step match the r06
  replicated ``fused_adam`` step and ``optax.adam``, for both the plain
  and fp32-master fused kernels;
- the state's uniform leading-dim divisibility (what makes per-rank
  memory exactly 1/N once laid out over the axis), and the byte
  predictors' exact agreement.

Quick lane; pure CPU; no multi-process ranks (the eager 2-rank lane is
tests/parallel/test_zero_eager.py + ``make zero-smoke``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.parallel import zero as Z
from horovod_tpu.parallel.precision import fused_adam, fused_master_adam
from horovod_tpu.parallel.train_step import make_split_train_step

pytestmark = pytest.mark.quick


# ---- segment-ownership rotation --------------------------------------

def _numpy_ring_owned(rank, size, rot):
    """Replay the ring reduce phase and report which segment ended up
    with every rank's contribution at `rank` — the ground truth both
    helpers must match."""
    # seg -> set of contributing ranks, per rank; walk the N-1 steps.
    holders = {r: {s: {r} for s in range(size)} for r in range(size)}
    for step in range(size - 1):
        sends = {}
        for r in range(size):
            seg = (r - step + rot) % size
            sends[(r + 1) % size] = (seg, set(holders[r][seg]))
        for r, (seg, contrib) in sends.items():
            holders[r][seg] |= contrib
    full = [s for s, c in holders[rank].items() if len(c) == size]
    assert len(full) == 1
    return full[0]


@pytest.mark.parametrize("size", [2, 3, 4, 7])
@pytest.mark.parametrize("rot", [0, -1])
def test_ring_owned_segment_matches_ring_replay(size, rot):
    for rank in range(size):
        assert Z.ring_owned_segment(rank, size, rot) == \
            _numpy_ring_owned(rank, size, rot)


def test_ring_owned_segment_known_values():
    # The r10 trap, pinned as literals: allreduce rotation -> (r+1)%N;
    # reduce-scatter rotation -> r itself.
    assert [Z.ring_owned_segment(r, 4) for r in range(4)] == [1, 2, 3, 0]
    assert [Z.ring_owned_segment(r, 4, -1) for r in range(4)] == \
        [0, 1, 2, 3]
    with pytest.raises(ValueError):
        Z.ring_owned_segment(4, 4)


def test_ring_owned_segment_matches_core_c_abi():
    """The Python twin and the engine's own helper must be ONE fact."""
    from horovod_tpu.common.basics import HorovodBasics

    b = HorovodBasics()
    try:
        lib = b.lib
    except OSError:
        pytest.skip("native core not built")
    for size in (2, 3, 4, 5):
        for rank in range(size):
            for rot in (0, -1):
                assert b.ring_owned_segment(rank, size, rot) == \
                    Z.ring_owned_segment(rank, size, rot)
    # send-segment helper: step 0 of the allgather phase (rot=+1 walk)
    # sends exactly the owned segment.
    for size in (2, 4):
        for rank in range(size):
            assert b.ring_send_segment(rank, 0, size, 1) == \
                Z.ring_owned_segment(rank, size)
    assert lib.hvdtpu_ring_owned_segment(9, 4, 0) == -1  # bad rank


# ---- bucket layout ---------------------------------------------------

def _leaves():
    return [jnp.arange(10, dtype=jnp.float32),
            jnp.ones((3, 4), jnp.float32),
            jnp.full((5,), 2, jnp.int32),
            jnp.arange(6, dtype=jnp.float32).reshape(2, 3)]


def test_layout_groups_by_dtype_and_pads_to_shards():
    lay = Z.zero_bucket_layout(_leaves(), n_shards=4,
                               bucket_bytes=1 << 20)
    # f32 leaves (10 + 12 + 6 = 28 elems -> pad 28) and the i32 leaf
    # (5 -> pad 8) land in separate buckets.
    assert len(lay.buckets) == 2
    f32, i32 = lay.buckets
    assert f32.indices == (0, 1, 3) and f32.nelems == 28
    assert f32.padded == 28 and f32.shard_elems(4) == 7
    assert i32.indices == (2,) and i32.padded == 8
    assert i32.shard_elems(4) == 2


def test_layout_bucket_bytes_chunks_and_roundtrip():
    leaves = _leaves()
    lay = Z.zero_bucket_layout(leaves, n_shards=2, bucket_bytes=48)
    # 48-byte buckets split the f32 group: 10*4=40 fits, the next leaf
    # (48 bytes) opens a new bucket, 6*4=24 more closes it at 72>48...
    assert all(b.padded % 2 == 0 for b in lay.buckets)
    packed = lay.pack(leaves)
    assert [p.shape[0] for p in packed] == [b.padded for b in lay.buckets]
    out = lay.unpack(packed)
    for a, b in zip(leaves, out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.dtype == b.dtype and a.shape == b.shape


def test_layout_oversized_single_leaf_gets_one_bucket():
    big = [jnp.ones((1000,), jnp.float32), jnp.ones((3,), jnp.float32)]
    lay = Z.zero_bucket_layout(big, n_shards=4, bucket_bytes=64)
    assert [b.indices for b in lay.buckets] == [(0,), (1,)]
    assert lay.buckets[1].padded == 4  # 3 -> padded to the shard count


def test_shard_boundaries_align_with_buckets():
    """Rank r's shard of every packed bucket is [r*s, (r+1)*s) — the
    rot=-1 ownership — and reassembling shards in rank order IS the
    packed bucket (what the eager allgather does)."""
    leaves = _leaves()
    for n in (2, 4):
        lay = Z.zero_bucket_layout(leaves, n_shards=n,
                                   bucket_bytes=1 << 20)
        for flat in lay.pack(leaves):
            s = flat.shape[0] // n
            shards = [flat[r * s:(r + 1) * s] for r in range(n)]
            np.testing.assert_array_equal(
                np.asarray(jnp.concatenate(shards)), np.asarray(flat))


def test_pack_shard_equals_sliced_pack():
    """The eager lane's direct shard assembly must equal slicing the
    full packed bucket — for every bucket, every rank, at shard counts
    that split leaves mid-way."""
    leaves = _leaves()
    for n in (2, 4):
        lay = Z.zero_bucket_layout(leaves, n_shards=n, bucket_bytes=48)
        packed = lay.pack(leaves)
        for i, b in enumerate(lay.buckets):
            s = b.shard_elems(n)
            for r in range(n):
                np.testing.assert_array_equal(
                    np.asarray(lay.pack_shard(leaves, i, r)),
                    np.asarray(packed[i][r * s:(r + 1) * s]),
                    err_msg=f"bucket {i} rank {r} of {n}")


def test_pack_of_sharded_leaves_is_layout_exact():
    """THE reason pack uses dynamic_update_slice: on this substrate a
    jitted concatenate-of-reshape over an axis-sharded leaf returns the
    physical per-device layout (strided garbage). Run the repro in a
    subprocess with 4 forced host devices and pin pack's output against
    the unsharded truth."""
    import subprocess
    import sys

    code = """
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp, numpy as np
from jax.sharding import PartitionSpec as P, NamedSharding
from horovod_tpu import parallel
from horovod_tpu.parallel import zero as Z
mesh = parallel.create_mesh(devices=jax.devices()[:4], data=2, fsdp=2)
a = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
b = jnp.full((7,), -0.25, jnp.float32)
lay = Z.zero_bucket_layout([a, b], 4, 1 << 20)
a_sh = jax.device_put(a, NamedSharding(mesh, P("fsdp", None)))
packed = jax.jit(lambda x, y: lay.pack([x, y]))(a_sh, b)
ref = np.concatenate([np.arange(64, dtype=np.float32),
                      np.full(7, -0.25, np.float32),
                      np.zeros(1, np.float32)])
np.testing.assert_array_equal(np.asarray(packed[0]), ref)
print("PACK_OK")
"""
    env = dict(__import__("os").environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=4")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=240,
                         cwd=__import__("os").path.dirname(
                             __import__("os").path.dirname(
                                 __import__("os").path.dirname(
                                     __import__("os").path.abspath(
                                         __file__)))))
    assert out.returncode == 0 and "PACK_OK" in out.stdout, (
        out.stdout[-500:], out.stderr[-1500:])


# ---- sharded-vs-replicated parity ------------------------------------

def _problem():
    key = jax.random.PRNGKey(0)
    params = {"w1": jax.random.normal(key, (8, 16)) * 0.1,
              "b1": jnp.zeros((13,)),
              "w2": jax.random.normal(jax.random.PRNGKey(1),
                                      (16, 4)) * 0.1}

    def loss_fn(p, d):
        h = jnp.tanh(d["x"] @ p["w1"] + p["b1"][:16].sum())
        return jnp.mean((h @ p["w2"] - d["y"]) ** 2)

    batch = {"x": jax.random.normal(jax.random.PRNGKey(2), (8, 8)),
             "y": jax.random.normal(jax.random.PRNGKey(3), (8, 4))}
    return params, loss_fn, batch


def _copy(t):
    return jax.tree.map(jnp.array, t)


@pytest.mark.parametrize("n_shards", [2, 4])
def test_zero_adam_matches_replicated_and_optax(n_shards):
    """Grad/param/optimizer-state pins: the zero split step == the r06
    replicated fused_adam step == optax.adam, at N in {2, 4} (powers of
    two, so the scatter's x N / N mean roundtrip is EXACT in f32)."""
    import optax

    params, loss_fn, batch = _problem()
    ref = make_split_train_step(loss_fn, fused_adam(1e-2),
                                microbatches=2)
    zts = make_split_train_step(
        loss_fn, fused_adam(1e-2), microbatches=2,
        zero=Z.ZeroConfig(size=n_shards, bucket_bytes=128))
    ots = make_split_train_step(loss_fn, optax.adam(1e-2),
                                microbatches=2)
    rc, zc, oc = (ref.init(_copy(params)), zts.init(_copy(params)),
                  ots.init(_copy(params)))
    for _ in range(3):
        rl, rc = ref.step(rc, batch)
        zl, zc = zts.step(zc, batch)
        ol, oc = ots.step(oc, batch)
    # Loss (same grads — the grad programs are shared code).
    assert float(zl) == pytest.approx(float(rl), abs=1e-7)
    assert float(zl) == pytest.approx(float(ol), rel=1e-6)
    # Params: zero == replicated fused == optax.
    for a, b in zip(jax.tree.leaves(rc[0]), jax.tree.leaves(zc[0])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-6, atol=1e-7)
    for a, b in zip(jax.tree.leaves(oc[0]), jax.tree.leaves(zc[0])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    # Optimizer state: the zero mu/nu are the PACKED replicated moments.
    layout = Z.zero_bucket_layout(jax.tree.leaves(params), n_shards, 128)
    rmu_packed = layout.pack(jax.tree.leaves(rc[1].mu))
    for packed, z in zip(rmu_packed, zc[1].mu):
        np.testing.assert_allclose(np.asarray(packed), np.asarray(z),
                                   rtol=2e-6, atol=1e-7)
    assert int(zc[1].count[0]) == 3
    # Uniform shardability: every state leaf splits exactly N ways.
    for leaf in jax.tree.leaves(zc[1]):
        assert leaf.shape[0] % n_shards == 0


def test_zero_master_adam_matches_replicated_master():
    """The fp32-master variant: sharded master/moments, compute-dtype
    carry — must match the replicated fused_master_adam step."""
    params, loss_fn, batch = _problem()
    mk = lambda **kw: make_split_train_step(  # noqa: E731
        loss_fn, fused_master_adam(1e-2, compute_dtype=jnp.float32),
        microbatches=1, **kw)
    ref, zts = mk(), mk(zero=Z.ZeroConfig(size=2, bucket_bytes=1 << 20))
    rc, zc = ref.init(_copy(params)), zts.init(_copy(params))
    for _ in range(2):
        rl, rc = ref.step(rc, batch)
        zl, zc = zts.step(zc, batch)
    assert float(zl) == pytest.approx(float(rl), abs=1e-7)
    for a, b in zip(jax.tree.leaves(rc[0]), jax.tree.leaves(zc[0])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-6, atol=1e-7)
    # The fp32 master shards hold the replicated master, packed.
    layout = Z.zero_bucket_layout(jax.tree.leaves(params), 2, 1 << 20)
    m_packed = layout.pack(jax.tree.leaves(rc[1].master))
    for packed, z in zip(m_packed, zc[1].master):
        np.testing.assert_allclose(np.asarray(packed), np.asarray(z),
                                   rtol=2e-6, atol=1e-7)
        assert z.dtype == jnp.float32


def test_zero_requires_a_fused_optimizer():
    import optax

    params, loss_fn, _ = _problem()
    with pytest.raises(ValueError, match="fused optimizer"):
        ts = make_split_train_step(loss_fn, optax.adam(1e-3),
                                   zero=Z.ZeroConfig(size=2))
        ts.init(params)


def test_zero_config_resolves_size_from_mesh():
    from horovod_tpu.parallel.mesh import create_mesh

    assert Z.ZeroConfig(size=3).resolved_size() == 3
    mesh = create_mesh()
    assert Z.ZeroConfig(axis="data", mesh=mesh).resolved_size() == \
        mesh.shape["data"]
    with pytest.raises(ValueError):
        Z.ZeroConfig().resolved_size()


# ---- byte predictors -------------------------------------------------

def test_zero_byte_predictors_agree_exactly():
    """The jaxpr-walker predictor and the layout arithmetic must agree
    to the byte — the invariant the zero_sweep/telemetry
    reconciliation stands on."""
    from horovod_tpu.telemetry.predict import (
        eager_zero_bytes,
        zero_layout_bytes,
    )

    params, loss_fn, batch = _problem()
    for size in (2, 4):
        walked = eager_zero_bytes(loss_fn, params, batch, size=size,
                                  bucket_bytes=128)
        layout = Z.zero_bucket_layout(jax.tree.leaves(params), size, 128)
        assert walked == zero_layout_bytes(layout)


def test_optimizer_state_bytes():
    state = {"mu": jnp.zeros((10,), jnp.float32),
             "nu": jnp.zeros((10,), jnp.bfloat16), "n": 3}
    assert Z.optimizer_state_bytes(state) == 40 + 20
