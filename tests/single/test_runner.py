"""Launcher unit + integration tests.

Reference analog: test/single/test_run.py (arg parsing, host parsing,
cmdline construction with mocks) plus a real local 2-rank launch as the
integration probe (SURVEY.md §4).
"""

import os
import subprocess
import sys
import textwrap

import pytest

from horovod_tpu.runner import launch, util

# Part of the sub-5-minute CI lane (make test-quick).
pytestmark = pytest.mark.quick

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def test_parse_hosts():
    hosts = util.parse_hosts("a:2,b:4,c")
    assert [(h.hostname, h.slots) for h in hosts] == [("a", 2), ("b", 4),
                                                      ("c", 1)]


def test_parse_hostfile(tmp_path):
    f = tmp_path / "hosts"
    f.write_text(textwrap.dedent("""\
        # comment
        node1 slots=4
        node2:2
        node3
    """))
    hosts = util.parse_hostfile(str(f))
    assert [(h.hostname, h.slots) for h in hosts] == [
        ("node1", 4), ("node2", 2), ("node3", 1)]


def test_host_assignments():
    slots = util.get_host_assignments(util.parse_hosts("a:2,b:2"), 3)
    assert [(s.hostname, s.rank, s.local_rank, s.cross_rank)
            for s in slots] == [("a", 0, 0, 0), ("a", 1, 1, 0),
                                ("b", 2, 0, 1)]
    assert all(s.cross_size == 2 for s in slots)
    assert slots[2].local_size == 1

    with pytest.raises(ValueError):
        util.get_host_assignments(util.parse_hosts("a:1"), 2)


def test_parse_args_and_env():
    args = launch.parse_args([
        "-np", "2", "--fusion-threshold-mb", "32", "--cycle-time-ms", "5",
        "--timeline-filename", "/tmp/t.json", "--no-stall-check",
        "--log-level", "DEBUG", "python", "train.py"])
    env = launch.env_from_args(args)
    assert env["HOROVOD_FUSION_THRESHOLD"] == str(32 * 1024 * 1024)
    assert env["HOROVOD_CYCLE_TIME"] == "5.0"
    assert env["HOROVOD_TIMELINE"] == "/tmp/t.json"
    assert env["HOROVOD_STALL_CHECK_DISABLE"] == "1"
    assert env["HOROVOD_LOG_LEVEL"] == "DEBUG"
    assert args.command == ["python", "train.py"]


def test_config_file(tmp_path):
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text("fusion-threshold-mb: 8\nlog-level: INFO\n")
    args = launch.parse_args(["-np", "1", "--config-file", str(cfg),
                              "python", "x.py"])
    env = launch.env_from_args(args)
    assert env["HOROVOD_FUSION_THRESHOLD"] == str(8 * 1024 * 1024)
    assert env["HOROVOD_LOG_LEVEL"] == "INFO"


def test_ssh_wrap():
    slot = util.SlotInfo("remotehost", 1, 0, 1, 2, 1, 2)
    cmd = launch._ssh_wrap(slot, {"HOROVOD_RANK": "1"}, ["python", "t.py"],
                           2222, "/id_rsa")
    assert cmd[0] == "ssh"
    assert "-p" in cmd and "2222" in cmd
    assert "remotehost" in cmd
    assert "HOROVOD_RANK=1" in cmd[-1]


def test_horovodrun_end_to_end(tmp_path):
    """Real 2-rank launch through the CLI: each rank allreduces its rank."""
    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent("""\
        import numpy as np
        from horovod_tpu.common.basics import HorovodBasics
        from horovod_tpu.common import eager_ops
        b = HorovodBasics(); b.init()
        h = eager_ops.allreduce_async(
            np.full(4, float(b.rank()), np.float32), "t")
        out = h.synchronize()
        assert out[0] == sum(range(b.size())), out
        print(f"RANK{b.rank()}-SUM{out[0]:.0f}")
        b.shutdown()
    """))
    env = dict(os.environ, PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr
    assert "RANK0-SUM1" in proc.stdout
    assert "RANK1-SUM1" in proc.stdout


def test_horovodrun_propagates_failure(tmp_path):
    script = tmp_path / "bad.py"
    script.write_text("import sys, os\n"
                      "sys.exit(3 if os.environ['HOROVOD_RANK']=='1' else 0)")
    env = dict(os.environ, PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    assert proc.returncode == 1
    assert "ranks failed" in proc.stderr


# ---- mpi_run / js_run cmdline construction (reference: test_run.py's
# mpirun cmdline asserts, fully mocked — no MPI needed) ----

def test_build_mpi_command_openmpi():
    from horovod_tpu.runner.mpi_run import MpiFlavor, build_mpi_command

    hosts = util.parse_hosts("h1:2,h2:2")
    env = {"HOROVOD_FUSION_THRESHOLD": "1", "PATH": "/bin", "HOME": "/root"}
    cmd = build_mpi_command(4, hosts, ["python", "train.py"], env,
                            flavor=MpiFlavor.OPENMPI, ssh_port=2222)
    assert cmd[0] == "mpirun"
    assert "-H" in cmd and cmd[cmd.index("-H") + 1] == "h1:2,h2:2"
    assert cmd[cmd.index("-np") + 1] == "4"
    assert ["--bind-to", "none"] == cmd[cmd.index("--bind-to"):
                                        cmd.index("--bind-to") + 2]
    # env forwarding: HOROVOD_* and PATH yes, HOME no
    xs = [cmd[i + 1] for i, c in enumerate(cmd) if c == "-x"]
    assert "HOROVOD_FUSION_THRESHOLD" in xs and "PATH" in xs
    assert "HOME" not in xs
    assert "plm_rsh_args" in cmd  # ssh port plumbed
    assert cmd[-2:] == ["python", "train.py"]


def test_build_mpi_command_mpich():
    from horovod_tpu.runner.mpi_run import MpiFlavor, build_mpi_command

    hosts = util.parse_hosts("h1:2")
    cmd = build_mpi_command(2, hosts, ["python", "t.py"],
                            {"HOROVOD_RANK": "0"}, flavor=MpiFlavor.MPICH)
    assert "-genvlist" in cmd and "-hosts" in cmd
    assert cmd[-2:] == ["python", "t.py"]


def test_detect_mpi_flavor():
    from horovod_tpu.runner.mpi_run import MpiFlavor, detect_mpi_flavor

    assert detect_mpi_flavor("mpirun (Open MPI) 4.1.4") == MpiFlavor.OPENMPI
    assert detect_mpi_flavor("HYDRA build details:") == MpiFlavor.MPICH
    assert detect_mpi_flavor("Intel(R) MPI Library") == MpiFlavor.INTEL
    assert detect_mpi_flavor("???") == MpiFlavor.UNKNOWN


def test_lsf_hosts_parsing():
    from horovod_tpu.runner.js_run import LSFUtils, build_js_command

    env = {"LSB_JOBID": "1", "LSB_MCPU_HOSTS": "batch 1 c1 4 c2 4"}
    assert LSFUtils.using_lsf(env)
    hosts = LSFUtils.get_compute_hosts(env)
    assert [(h.hostname, h.slots) for h in hosts] == [("c1", 4), ("c2", 4)]
    assert LSFUtils.get_num_processes(env) == 8
    # One resource set per host carrying all its ranks (multiple all-CPU
    # RSes on one host would be an infeasible jsrun geometry).
    cmd = build_js_command(2, 4, ["python", "t.py"])
    assert cmd[0] == "jsrun"
    assert cmd[cmd.index("--nrs") + 1] == "2"
    assert cmd[cmd.index("--tasks_per_rs") + 1] == "4"
    assert cmd[cmd.index("--rs_per_host") + 1] == "1"


def test_run_controller_choice():
    args = launch.parse_args(["-np", "2", "--mpi", "--", "python", "t.py"])
    assert launch.run_controller(args) == "mpi"
    args = launch.parse_args(["-np", "2", "--", "python", "t.py"])
    assert launch.run_controller(args) == "gloo"
    args = launch.parse_args(["-np", "2", "--js", "--", "python", "t.py"])
    assert launch.run_controller(args) == "js"
    with pytest.raises(ValueError):
        args = launch.parse_args(
            ["-np", "2", "--mpi", "--js", "--", "python", "t.py"])
        launch.run_controller(args)


# ---- driver/task NIC discovery (reference: test_run.py service tests;
# multi-host faked as threads on loopback, SURVEY.md §4) ----

def test_nic_discovery_roundtrip():
    from horovod_tpu.runner.task_service import (
        HorovodRunTaskService,
        discover_common_interfaces,
    )

    def spawn(driver):
        return [HorovodRunTaskService(i, driver.addresses, driver.key)
                for i in range(3)]

    common = discover_common_interfaces(3, spawn, timeout=30)
    assert set(common) == {0, 1, 2}
    # every host is reachable from the others via at least one address
    for idx, addrs in common.items():
        assert addrs, f"no common interface found for task {idx}"


def test_driver_rejects_bad_hmac():
    import socket

    from horovod_tpu.runner.driver_service import (
        HorovodRunDriverService,
        send_msg,
    )

    driver = HorovodRunDriverService(1)
    try:
        with socket.create_connection(driver.addresses, timeout=5) as s:
            send_msg(s, {"type": "register", "index": 0, "host": "x",
                         "addrs": []}, "wrong-key")
            f = s.makefile("rb")
            assert f.readline() == b""  # connection dropped, no ack
        assert driver._registered == {}
    finally:
        driver.shutdown()


def test_launcher_env_translation(monkeypatch):
    """Under mpirun/srun the rank layout arrives in OMPI_*/SLURM_* vars;
    init must translate them to HOROVOD_* (reference: MPIContext)."""
    from horovod_tpu.common.basics import HorovodBasics

    for k in ("HOROVOD_RANK", "HOROVOD_SIZE", "HOROVOD_LOCAL_RANK",
              "HOROVOD_LOCAL_SIZE"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "3")
    monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "8")
    monkeypatch.setenv("OMPI_COMM_WORLD_LOCAL_RANK", "1")
    monkeypatch.setenv("SLURM_TASKS_PER_NODE", "4(x2)")
    HorovodBasics._translate_launcher_env()
    assert os.environ["HOROVOD_RANK"] == "3"
    assert os.environ["HOROVOD_SIZE"] == "8"
    assert os.environ["HOROVOD_LOCAL_RANK"] == "1"
    assert os.environ["HOROVOD_LOCAL_SIZE"] == "4"  # '(x2)' stripped
    # Explicit HOROVOD_* wins over launcher vars.
    monkeypatch.setenv("HOROVOD_RANK", "0")
    HorovodBasics._translate_launcher_env()
    assert os.environ["HOROVOD_RANK"] == "0"


def _interactive_fn(scale):
    """Module-level (picklable) fn for horovod_tpu.runner.run."""
    import numpy as np

    import horovod_tpu.jax as hvd

    hvd.init()
    try:
        out = hvd.allreduce(np.full(3, float(hvd.rank() + 1)), op=hvd.Sum)
        return float(np.asarray(out)[0]) * scale
    finally:
        hvd.shutdown()


def test_interactive_run():
    """Reference analog: test_interactiverun.py — horovod.run() launches
    fn on N local ranks, initializes each, returns results by rank."""
    import os

    from horovod_tpu import runner

    before = os.environ.get("HOROVOD_RANK")
    env = {"JAX_PLATFORMS": "cpu",
           "HOROVOD_XLA_DATA_PLANE": "0"}
    # Generous per-rank timeout: spawned workers import TF/JAX on a
    # single shared core and can take minutes when the machine is loaded.
    results = runner.run(_interactive_fn, args=(10.0,), np=2, env=env,
                         timeout=300)
    assert results == [30.0, 30.0]  # sum(1..2) * 10 on both ranks
    # run() must not mutate the parent environment (other tests may have
    # set HOROVOD_RANK before us; assert it is unchanged, not absent).
    assert os.environ.get("HOROVOD_RANK") == before


def test_tpu_pod_slot_env_binding():
    """--tpu-pod chip binding: libtpu hosts get per-rank TPU_VISIBLE_DEVICES;
    a LOCAL slot under a non-libtpu PJRT plugin (JAX_PLATFORMS names
    something other than tpu) must NOT get the binding vars (they break
    such plugins' registration); remote slots always get them."""
    from unittest import mock

    from horovod_tpu.runner.launch import _slot_env
    from horovod_tpu.runner.util import SlotInfo

    slot = SlotInfo(hostname="localhost", rank=1, local_rank=1,
                    cross_rank=0, size=2, local_size=2, cross_size=1)

    with mock.patch.dict(os.environ, {"JAX_PLATFORMS": "tpu"}):
        env = _slot_env(slot, "127.0.0.1", 29500, tpu_pod=True, local=True)
        assert env["TPU_VISIBLE_DEVICES"] == "1"
        assert env["JAX_LOCAL_DEVICE_IDS"] == "1"

    with mock.patch.dict(os.environ, {"JAX_PLATFORMS": "axon"}):
        # local + plugin platform: no binding vars
        env = _slot_env(slot, "127.0.0.1", 29500, tpu_pod=True, local=True)
        assert "TPU_VISIBLE_DEVICES" not in env
        # remote slot: launcher env says nothing about it -> binding on
        env = _slot_env(slot, "127.0.0.1", 29500, tpu_pod=True,
                        local=False)
        assert env["TPU_VISIBLE_DEVICES"] == "1"

    with mock.patch.dict(os.environ, clear=False) as _:
        os.environ.pop("JAX_PLATFORMS", None)
        env = _slot_env(slot, "127.0.0.1", 29500, tpu_pod=True, local=True)
        assert env["TPU_VISIBLE_DEVICES"] == "1"  # unset -> libtpu default

    # non-tpu-pod launches never set binding vars
    env = _slot_env(slot, "127.0.0.1", 29500, tpu_pod=False)
    assert "TPU_VISIBLE_DEVICES" not in env


def test_check_build_reports_capabilities(capsys):
    """horovodrun --check-build (reference parity): frameworks, planes,
    and the TF native op capability print truthfully."""
    from horovod_tpu.runner.launch import _print_check_build

    _print_check_build()
    out = capsys.readouterr().out
    assert "Available Frameworks" in out
    assert "[X] JAX" in out
    assert "[X] TCP (gloo-style rendezvous)" in out
    assert "[X] host ring (TCP)" in out
    assert "[X] xla_ici device plane (TPU/ICI)" in out
    # this image ships TF headers, so the native op row must be on
    assert "[X] TF native ops (in-jit XLA collectives)" in out
    assert "[ ] NCCL" in out
