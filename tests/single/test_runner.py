"""Launcher unit + integration tests.

Reference analog: test/single/test_run.py (arg parsing, host parsing,
cmdline construction with mocks) plus a real local 2-rank launch as the
integration probe (SURVEY.md §4).
"""

import os
import subprocess
import sys
import textwrap

import pytest

from horovod_tpu.runner import launch, util

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def test_parse_hosts():
    hosts = util.parse_hosts("a:2,b:4,c")
    assert [(h.hostname, h.slots) for h in hosts] == [("a", 2), ("b", 4),
                                                      ("c", 1)]


def test_parse_hostfile(tmp_path):
    f = tmp_path / "hosts"
    f.write_text(textwrap.dedent("""\
        # comment
        node1 slots=4
        node2:2
        node3
    """))
    hosts = util.parse_hostfile(str(f))
    assert [(h.hostname, h.slots) for h in hosts] == [
        ("node1", 4), ("node2", 2), ("node3", 1)]


def test_host_assignments():
    slots = util.get_host_assignments(util.parse_hosts("a:2,b:2"), 3)
    assert [(s.hostname, s.rank, s.local_rank, s.cross_rank)
            for s in slots] == [("a", 0, 0, 0), ("a", 1, 1, 0),
                                ("b", 2, 0, 1)]
    assert all(s.cross_size == 2 for s in slots)
    assert slots[2].local_size == 1

    with pytest.raises(ValueError):
        util.get_host_assignments(util.parse_hosts("a:1"), 2)


def test_parse_args_and_env():
    args = launch.parse_args([
        "-np", "2", "--fusion-threshold-mb", "32", "--cycle-time-ms", "5",
        "--timeline-filename", "/tmp/t.json", "--no-stall-check",
        "--log-level", "DEBUG", "python", "train.py"])
    env = launch.env_from_args(args)
    assert env["HOROVOD_FUSION_THRESHOLD"] == str(32 * 1024 * 1024)
    assert env["HOROVOD_CYCLE_TIME"] == "5.0"
    assert env["HOROVOD_TIMELINE"] == "/tmp/t.json"
    assert env["HOROVOD_STALL_CHECK_DISABLE"] == "1"
    assert env["HOROVOD_LOG_LEVEL"] == "DEBUG"
    assert args.command == ["python", "train.py"]


def test_config_file(tmp_path):
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text("fusion-threshold-mb: 8\nlog-level: INFO\n")
    args = launch.parse_args(["-np", "1", "--config-file", str(cfg),
                              "python", "x.py"])
    env = launch.env_from_args(args)
    assert env["HOROVOD_FUSION_THRESHOLD"] == str(8 * 1024 * 1024)
    assert env["HOROVOD_LOG_LEVEL"] == "INFO"


def test_ssh_wrap():
    slot = util.SlotInfo("remotehost", 1, 0, 1, 2, 1, 2)
    cmd = launch._ssh_wrap(slot, {"HOROVOD_RANK": "1"}, ["python", "t.py"],
                           2222, "/id_rsa")
    assert cmd[0] == "ssh"
    assert "-p" in cmd and "2222" in cmd
    assert "remotehost" in cmd
    assert "HOROVOD_RANK=1" in cmd[-1]


def test_horovodrun_end_to_end(tmp_path):
    """Real 2-rank launch through the CLI: each rank allreduces its rank."""
    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent("""\
        import numpy as np
        from horovod_tpu.common.basics import HorovodBasics
        from horovod_tpu.common import eager_ops
        b = HorovodBasics(); b.init()
        h = eager_ops.allreduce_async(
            np.full(4, float(b.rank()), np.float32), "t")
        out = h.synchronize()
        assert out[0] == sum(range(b.size())), out
        print(f"RANK{b.rank()}-SUM{out[0]:.0f}")
        b.shutdown()
    """))
    env = dict(os.environ, PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr
    assert "RANK0-SUM1" in proc.stdout
    assert "RANK1-SUM1" in proc.stdout


def test_horovodrun_propagates_failure(tmp_path):
    script = tmp_path / "bad.py"
    script.write_text("import sys, os\n"
                      "sys.exit(3 if os.environ['HOROVOD_RANK']=='1' else 0)")
    env = dict(os.environ, PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    assert proc.returncode == 1
    assert "ranks failed" in proc.stderr
