"""Fused decode-attention kernel (interpret mode) vs the einsum path.

The kernel and the XLA fallback must agree exactly in recipe (f32
scores/softmax, bf16 p into f32-accumulated PV), so tolerances are
tight; position masking and GQA grouping are the failure modes worth
pinning.
"""

import importlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

da = importlib.import_module("horovod_tpu.ops.decode_attention")


@pytest.fixture(autouse=True)
def _interpret_mode():
    da._INTERPRET = True
    yield
    da._INTERPRET = False


@pytest.mark.parametrize("pos", [0, 3, 11])
@pytest.mark.parametrize("n_rep", [1, 4])
def test_kernel_matches_einsum(pos, n_rep):
    B, S, HKV, D = 3, 12, 2, 8
    H = HKV * n_rep
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, 1, H, D), jnp.float32)
    ck = jax.random.normal(ks[1], (B, HKV, S, D), jnp.float32)
    cv = jax.random.normal(ks[2], (B, HKV, S, D), jnp.float32)
    out = da.decode_attention(q, ck, cv, jnp.int32(pos))
    ref = da._decode_attention_xla(q, ck, cv, jnp.int32(pos))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_position_mask_blocks_future_slots():
    # Poison cache slots past pos with huge values: output must not move.
    B, S, HKV, D = 1, 8, 1, 8
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (B, 1, 2, D), jnp.float32)
    ck = jax.random.normal(ks[1], (B, HKV, S, D), jnp.float32)
    cv = jax.random.normal(ks[2], (B, HKV, S, D), jnp.float32)
    pos = jnp.int32(4)
    base = da.decode_attention(q, ck, cv, pos)
    ck2 = ck.at[:, :, 5:].set(1e3)
    cv2 = cv.at[:, :, 5:].set(1e3)
    np.testing.assert_array_equal(
        np.asarray(da.decode_attention(q, ck2, cv2, pos)),
        np.asarray(base))


def test_bf16_recipe_kernel_matches_einsum():
    """bf16 caches drive the production recipe (bf16 p into f32
    accumulation): kernel and einsum path must agree in bf16, where
    the p-cast actually rounds."""
    B, S, HKV, D, n_rep = 2, 16, 2, 8, 4
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(ks[0], (B, 1, HKV * n_rep, D), jnp.bfloat16)
    ck = jax.random.normal(ks[1], (B, HKV, S, D), jnp.bfloat16)
    cv = jax.random.normal(ks[2], (B, HKV, S, D), jnp.bfloat16)
    out = da.decode_attention(q, ck, cv, jnp.int32(9))
    ref = da._decode_attention_xla(q, ck, cv, jnp.int32(9))
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_long_cache_falls_back_to_einsum(monkeypatch):
    """Past the VMEM budget the trace-time guard must route to the
    einsum path instead of a pallas lowering failure."""
    called = {}
    real = da._decode_attention_xla

    def spy(*a):
        called["xla"] = True
        return real(*a)

    monkeypatch.setattr(da, "_decode_attention_xla", spy)
    B, S, HKV, D = 1, 64 * 1024, 1, 128   # ~32 MB of K+V per program
    q = jnp.zeros((B, 1, 2, D), jnp.bfloat16)
    ck = jnp.zeros((B, HKV, S, D), jnp.bfloat16)
    cv = jnp.zeros((B, HKV, S, D), jnp.bfloat16)
    out = da.decode_attention(q, ck, cv, jnp.int32(5))
    assert called.get("xla") and out.shape == (B, 1, 2, D)
