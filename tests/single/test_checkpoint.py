"""Checkpoint engine (orbax): one-shot, managed, sharded, elastic.

Reference analog: none in-core (SURVEY.md §5.4 — the reference delegates
checkpointing to frameworks); this is the TPU-idiomatic engine the
elastic/keras/spark layers compose with.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu import checkpoint as ckpt
from horovod_tpu import parallel


def test_one_shot_roundtrip(tmp_path):
    state = {"params": {"w": jnp.arange(6.0).reshape(2, 3)},
             "step": np.int64(7)}
    ckpt.save(tmp_path / "one", state)
    back = ckpt.restore(tmp_path / "one")
    np.testing.assert_allclose(np.asarray(back["params"]["w"]),
                               np.arange(6.0).reshape(2, 3))
    assert int(back["step"]) == 7


def test_manager_retention_and_steps(tmp_path):
    with ckpt.CheckpointManager(tmp_path / "mgr", max_to_keep=2) as mgr:
        for s in (1, 2, 3):
            mgr.save(s, {"x": jnp.full((4,), float(s))}, wait=True)
        assert mgr.latest_step() == 3
        np.testing.assert_allclose(np.asarray(mgr.restore()["x"]), 3.0)
        np.testing.assert_allclose(np.asarray(mgr.restore(step=2)["x"]), 2.0)
    assert sorted(os.listdir(tmp_path / "mgr")) == ["2", "3"]


def test_manager_restore_missing_raises(tmp_path):
    with ckpt.CheckpointManager(tmp_path / "empty") as mgr:
        assert mgr.latest_step() is None
        with pytest.raises(FileNotFoundError):
            mgr.restore()


def test_sharded_restore_onto_mesh(tmp_path):
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    mesh = parallel.create_mesh(fsdp=2, tensor=2, devices=jax.devices()[:4])
    sh = NamedSharding(mesh, P("fsdp", "tensor"))
    w = jax.device_put(jnp.arange(24.0).reshape(4, 6), sh)
    ckpt.save(tmp_path / "sharded", {"w": w})
    target = {"w": jax.ShapeDtypeStruct((4, 6), jnp.float32, sharding=sh)}
    back = ckpt.restore(tmp_path / "sharded", target=target)
    assert back["w"].sharding == sh
    np.testing.assert_allclose(np.asarray(back["w"]),
                               np.arange(24.0).reshape(4, 6))


def test_elastic_state_durable_commit_and_resume(tmp_path):
    from horovod_tpu.jax.elastic import JaxState

    s1 = JaxState(checkpoint_dir=tmp_path / "el",
                  params={"w": jnp.zeros((3,))}, epoch=0)
    s1.params = {"w": jnp.full((3,), 5.0)}
    s1.epoch = 4
    s1.commit()
    s1._ckpt_mgr.wait()

    # Cold restart: a fresh state resumes the last durable commit.
    s2 = JaxState(checkpoint_dir=tmp_path / "el",
                  params={"w": jnp.zeros((3,))}, epoch=0)
    step = s2.resume()
    assert step == 1
    np.testing.assert_allclose(np.asarray(s2.params["w"]), 5.0)
    assert int(s2.epoch) == 4

    # In-memory rollback still works on top.
    s2.params = {"w": jnp.full((3,), 9.0)}
    s2.restore()
    np.testing.assert_allclose(np.asarray(s2.params["w"]), 5.0)


def test_restart_without_resume_keeps_committing(tmp_path):
    """A fresh JaxState on an existing dir must continue step numbering
    (regression: orbax silently skips existing steps, so restarting at 0
    dropped every durable commit)."""
    from horovod_tpu.jax.elastic import JaxState

    s1 = JaxState(checkpoint_dir=tmp_path / "el", v=jnp.zeros(()))
    s1.v = jnp.asarray(1.0)
    s1.commit()
    s1._ckpt_mgr.wait()

    s2 = JaxState(checkpoint_dir=tmp_path / "el", v=jnp.zeros(()))
    s2.v = jnp.asarray(2.0)
    s2.commit()  # must land as step 2, not a silently-skipped step 1
    s2._ckpt_mgr.wait()
    assert s2._ckpt_mgr.latest_step() == 2

    s3 = JaxState(checkpoint_dir=tmp_path / "el", v=jnp.zeros(()))
    assert s3.resume() == 2
    np.testing.assert_allclose(float(s3.v), 2.0)


def test_elastic_state_with_non_array_values(tmp_path):
    """Strings and arbitrary picklables are legal elastic state; durable
    commits must round-trip them (regression: orbax rejects str leaves
    in a deferred async error)."""
    from horovod_tpu.jax.elastic import JaxState

    s1 = JaxState(checkpoint_dir=tmp_path / "el",
                  params={"w": jnp.ones((2,))},
                  run_name="exp-42", meta={"lr": 0.1, "tag": "warmup"})
    s1.commit()
    s1._ckpt_mgr.wait()

    s2 = JaxState(checkpoint_dir=tmp_path / "el",
                  params={"w": jnp.zeros((2,))}, run_name="", meta={})
    assert s2.resume() == 1
    assert s2.run_name == "exp-42"
    assert s2.meta["tag"] == "warmup"
    np.testing.assert_allclose(np.asarray(s2.params["w"]), 1.0)


def test_torch_state_durable_commit_and_resume(tmp_path):
    import torch

    from horovod_tpu.torch.elastic import TorchState

    model = torch.nn.Linear(3, 2)
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    s1 = TorchState(model=model, optimizer=opt,
                    checkpoint_dir=tmp_path / "t", epoch=0)
    with torch.no_grad():
        model.weight.fill_(2.5)
    s1.epoch = 3
    s1.commit()
    s1._ckpt_mgr.wait()

    model2 = torch.nn.Linear(3, 2)
    opt2 = torch.optim.SGD(model2.parameters(), lr=0.1)
    s2 = TorchState(model=model2, optimizer=opt2,
                    checkpoint_dir=tmp_path / "t", epoch=0)
    assert s2.resume() == 1
    assert int(s2.epoch) == 3
    np.testing.assert_allclose(model2.weight.detach().numpy(), 2.5)
