"""Pallas flash-attention kernels in interpret mode — the only CI
coverage the TPU code paths (incl. the bias branches) get without a
chip. Values AND grads compare against reference-math attention.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import importlib

# The package re-exports the flash_attention FUNCTION under the same
# name, shadowing the submodule attribute — resolve the module directly.
fa_mod = importlib.import_module("horovod_tpu.ops.flash_attention")


@pytest.fixture(autouse=True)
def _interpret_mode():
    fa_mod._INTERPRET = True
    yield
    fa_mod._INTERPRET = False


def _skip_without_shard_map():
    """The ring/ulysses mesh comparisons drive jax.shard_map directly
    (same gate as tests/single/test_llama.py): on jax 0.4.x boxes only
    jax.experimental.shard_map exists, with check_rep instead of
    check_vma — skip rather than fail there; the driver's newer-jax box
    runs them."""
    if not hasattr(jax, "shard_map"):
        pytest.skip("needs jax.shard_map (jax >= 0.6)")


def _qkv(seed=0, B=1, T=32, H=2, D=8):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (B, H, T, D)  # kernel layout
    return (jax.random.normal(k1, shape, jnp.float32),
            jax.random.normal(k2, shape, jnp.float32),
            jax.random.normal(k3, shape, jnp.float32))


def _ref(q, k, v, bias=None, causal=False):
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / (d ** 0.5)
    if bias is not None:
        s = s + bias[:, None, :, :]  # [B,1,1,T] -> broadcast
    if causal:
        t = q.shape[2]
        s = jnp.where(jnp.tril(jnp.ones((t, t), bool)), s, -1e30)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)


@pytest.mark.parametrize("causal", [False, True])
def test_kernel_matches_reference(causal):
    q, k, v = _qkv()
    out = fa_mod._flash(q, k, v, causal, 16, 16)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_ref(q, k, v, causal=causal)),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_biased_kernel_matches_reference(causal):
    q, k, v = _qkv()
    B, T = q.shape[0], q.shape[2]
    mask = jnp.ones((B, T)).at[:, T - 10:].set(0)
    bias = jnp.where(mask > 0, 0.0, -1e30).astype(jnp.float32)[:, None, :]
    out = fa_mod._flash_biased(q, k, v, bias, causal, 16, 16)
    ref = _ref(q, k, v, bias=bias, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_biased_kernel_grads_match_reference():
    q, k, v = _qkv()
    B, T = q.shape[0], q.shape[2]
    bias = jnp.where(jnp.arange(T) < T - 10, 0.0,
                     -1e30).astype(jnp.float32)[None, None, :]
    bias = jnp.broadcast_to(bias, (B, 1, T))

    def f(q, k, v):
        return (fa_mod._flash_biased(q, k, v, bias, False, 16, 16) ** 2).sum()

    def fr(q, k, v):
        return (_ref(q, k, v, bias=bias) ** 2).sum()

    g = jax.grad(f, (0, 1, 2))(q, k, v)
    gr = jax.grad(fr, (0, 1, 2))(q, k, v)
    for a, b, name in zip(g, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-4,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.parametrize("causal", [False, True])
def test_gqa_kernel_values_and_grads(causal):
    """GQA-native kernels (k/v at Hkv heads, indexed hi // n_rep in the
    block specs — no repeat materialization): values and all three
    grads must match reference attention over explicitly repeated
    heads."""
    B, T, H, HKV, D = 1, 32, 4, 2, 8
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(k1, (B, H, T, D), jnp.float32)
    k = jax.random.normal(k2, (B, HKV, T, D), jnp.float32)
    v = jax.random.normal(k3, (B, HKV, T, D), jnp.float32)

    def rep(x):  # [B,HKV,T,D] -> [B,H,T,D], blocked head order
        return jnp.broadcast_to(
            x[:, :, None], (B, HKV, H // HKV, T, D)).reshape(B, H, T, D)

    out = fa_mod._flash(q, k, v, causal, 16, 16)
    ref = _ref(q, rep(k), rep(v), causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)

    def f(q, k, v):
        return (fa_mod._flash(q, k, v, causal, 16, 16) ** 2).sum()

    def fr(q, k, v):
        return (_ref(q, rep(k), rep(v), causal=causal) ** 2).sum()

    g = jax.grad(f, (0, 1, 2))(q, k, v)
    gr = jax.grad(fr, (0, 1, 2))(q, k, v)
    for a, b_, name in zip(g, gr, "qkv"):
        assert a.shape == b_.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-3, atol=5e-4,
                                   err_msg=f"d{name} mismatch")


def test_fully_masked_row_stays_finite():
    q, k, v = _qkv()
    B, T = q.shape[0], q.shape[2]
    bias = jnp.full((B, 1, T), -1e30, jnp.float32)  # every key masked
    out = fa_mod._flash_biased(q, k, v, bias, False, 16, 16)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.parametrize("causal", [False, True])
def test_chunked_offsets_kernel_matches_reference(causal):
    """flash_attention_chunk with dynamic global offsets (the ring-step
    kernel): two chunks merged by logsumexp must equal one full-width
    attention — values and grads."""
    B, T, H, D = 1, 32, 2, 8
    q, k, v = _qkv(seed=5, B=B, T=T, H=H, D=D)
    half = T // 2

    def merged(q, k, v):
        o, lse = [], []
        for j, kv0 in ((0, 0), (1, half)):
            ob, lb = fa_mod.flash_attention_chunk(
                q, k[:, :, kv0:kv0 + half], v[:, :, kv0:kv0 + half],
                q_offset=0, kv_offset=kv0, causal=causal,
                block_q=16, block_k=16)
            o.append(ob.astype(jnp.float32))
            lse.append(lb)
        new = jnp.logaddexp(lse[0], lse[1])
        return (jnp.exp(lse[0] - new) * o[0]
                + jnp.exp(lse[1] - new) * o[1])

    out = merged(q, k, v)
    ref = _ref(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)

    g = jax.grad(lambda *a: (merged(*a) ** 2).sum(), (0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: (_ref(*a, causal=causal) ** 2).sum(),
                  (0, 1, 2))(q, k, v)
    for a, b, name in zip(g, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-4,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_flash_path_matches_blockwise(causal):
    """The flash ring path (pallas chunk kernel + logsumexp merge,
    interpret mode) must match the XLA blockwise ring on a real
    sharded mesh — values and grads, including GQA kv heads."""
    _skip_without_shard_map()
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from horovod_tpu.parallel.ring_attention import ring_attention

    n_dev = 2
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("seq",))
    B, T, H, HKV, D = 1, 64, 2, 1, 8
    ks = jax.random.split(jax.random.PRNGKey(9), 3)
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, HKV, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, HKV, D), jnp.float32)
    spec = P(None, "seq", None, None)

    def run(use_flash):
        @jax.shard_map(mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
        def _r(ql, kl, vl):
            return ring_attention(ql, kl, vl, "seq", causal=causal,
                                  use_flash=use_flash)

        return _r

    out_flash = run(True)(q, k, v)
    out_block = run(False)(q, k, v)
    np.testing.assert_allclose(np.asarray(out_flash),
                               np.asarray(out_block),
                               rtol=2e-4, atol=2e-4)

    gf = jax.grad(lambda *a: (run(True)(*a) ** 2).sum(), (0, 1, 2))(
        q, k, v)
    gb = jax.grad(lambda *a: (run(False)(*a) ** 2).sum(), (0, 1, 2))(
        q, k, v)
    for a, b, name in zip(gf, gb, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-4,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_flash_path_matches_blockwise(causal):
    """Ulysses' post-all-to-all local attention through the pallas
    kernels (interpret) must match its blockwise path — incl. the GQA
    grouping that survives the head split."""
    _skip_without_shard_map()
    from jax.sharding import Mesh, PartitionSpec as P

    from horovod_tpu.parallel.ulysses import ulysses_attention

    n_dev = 2
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("seq",))
    B, T, H, HKV, D = 1, 64, 4, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, HKV, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, HKV, D), jnp.float32)
    spec = P(None, "seq", None, None)

    def run(use_flash):
        @jax.shard_map(mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
        def _r(ql, kl, vl):
            return ulysses_attention(ql, kl, vl, "seq", causal=causal,
                                     use_flash=use_flash)

        return _r

    np.testing.assert_allclose(np.asarray(run(True)(q, k, v)),
                               np.asarray(run(False)(q, k, v)),
                               rtol=2e-4, atol=2e-4)


def test_public_api_mask_via_fallback():
    # flash_attention() with kv_bias through the public API (framework
    # [B,T,H,D] layout); under the _INTERPRET fixture this drives the
    # biased pallas kernel on CPU (without it, the XLA fallback — same
    # math either way).
    B, T, H, D = 2, 16, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(kk, (B, T, H, D), jnp.float32)
               for kk in ks)
    mask = jnp.ones((B, T)).at[1, 10:].set(0)
    bias = jnp.where(mask > 0, 0.0, -1e30).astype(jnp.float32)
    out = fa_mod.flash_attention(q, k, v, causal=False, kv_bias=bias)
    ref = _ref(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
               v.transpose(0, 2, 1, 3), bias=bias[:, None, :])
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(ref.transpose(0, 2, 1, 3)),
                               rtol=2e-4, atol=2e-4)


def test_zero_valid_key_rows_zero_output_and_grads():
    """q rows with zero valid keys INSIDE a causally-relevant block
    (kv chunk starts mid-q-block) used to emit mean-of-V rows in the
    forward (m stuck at _NEG -> p uniform) and leak spurious dq/dk/dv
    in the backward (lse ~ _NEG makes exp(s - lse) round to 1). Both
    must be exactly zero so a standalone chunk is correct in its own
    right, not just after logsumexp merging."""
    q, k, v = _qkv(seed=7, T=16)
    # block_q=16 spans all queries; kv chunk starts at global 8, so
    # rows 0..7 have zero valid keys inside a relevant block (row 8
    # attends to one key, etc.) — the whole-block skip does NOT fire.
    def run(qq, kk, vv):
        return fa_mod.flash_attention_chunk(
            qq, kk, vv, q_offset=0, kv_offset=8, causal=True,
            block_q=16, block_k=16)

    o, lse = run(q, k, v)
    np.testing.assert_array_equal(np.asarray(o[:, :, :8]), 0.0)
    assert np.all(np.asarray(lse[:, :, :8]) < -1e29)
    # Rows with valid keys must be untouched by the guard.
    assert np.all(np.abs(np.asarray(o[:, :, 8:])) > 0)

    # Cotangent ONLY on the fully-masked rows: every gradient must be
    # exactly zero (pre-fix: dv max ~8, dq max ~6).
    def loss(qq, kk, vv):
        oo, _ = run(qq, kk, vv)
        return (oo[:, :, :8] ** 2).sum() + oo[:, :, :8].sum()

    dq, dk, dv = jax.grad(loss, (0, 1, 2))(q, k, v)
    for g, name in zip((dq, dk, dv), "qkv"):
        np.testing.assert_array_equal(
            np.asarray(g), 0.0, err_msg=f"d{name} leaked")
