"""Elastic subsystem unit tests: discovery, rendezvous, driver, state.

Reference analog: test/single/elastic/ (test_driver.py, test_rendezvous.py)
— fake discovery scripts and thread-fake workers exercise multi-node logic
without a cluster (SURVEY.md §4).
"""

import os
import stat
import threading
import time

import numpy as np
import pytest

from horovod_tpu.runner.elastic.discovery import (
    FixedHosts,
    HostDiscoveryScript,
    HostManager,
)
from horovod_tpu.runner.elastic.rendezvous import (
    RendezvousClient,
    RendezvousServer,
)
from horovod_tpu.runner.elastic.worker import (
    WorkerNotificationManager,
    notify_worker,
)

# Part of the sub-5-minute CI lane (make test-quick).
pytestmark = pytest.mark.quick


def _script(tmp_path, hosts_file):
    path = tmp_path / "discover.sh"
    path.write_text(f"#!/bin/sh\ncat {hosts_file}\n")
    path.chmod(path.stat().st_mode | stat.S_IEXEC)
    return str(path)


def test_discovery_script_parsing(tmp_path):
    hosts_file = tmp_path / "hosts"
    hosts_file.write_text("node1:4\nnode2:2\n# comment\nnode3\n")
    disc = HostDiscoveryScript(_script(tmp_path, hosts_file),
                               default_slots=3)
    assert disc.find_available_hosts_and_slots() == {
        "node1": 4, "node2": 2, "node3": 3}


def test_host_manager_change_detection_and_blacklist(tmp_path):
    hosts_file = tmp_path / "hosts"
    hosts_file.write_text("a:2\n")
    mgr = HostManager(HostDiscoveryScript(_script(tmp_path, hosts_file)))
    changed, added, removed = mgr.update_available_hosts()
    assert changed and added == ["a"] and not removed
    assert mgr.slot_count() == 2

    hosts_file.write_text("a:2\nb:1\n")
    changed, added, removed = mgr.update_available_hosts()
    assert changed and added == ["b"]

    hosts_file.write_text("b:1\n")
    changed, added, removed = mgr.update_available_hosts()
    assert changed and removed == ["a"]

    mgr.blacklist("b")
    mgr.update_available_hosts()
    assert mgr.current_hosts == {}
    assert mgr.is_blacklisted("b")


def test_rendezvous_assignment_epochs():
    server = RendezvousServer()
    try:
        client = RendezvousClient("127.0.0.1", server.port)
        client.register("w0", "localhost", 0, None)
        client.register("w1", "localhost", 1, None)
        assert set(server.registered_workers()) == {"w0", "w1"}

        # No epoch cut yet -> polling times out.
        with pytest.raises(TimeoutError):
            client.poll_assignment("w0", timeout=0.5)

        server.start_epoch({
            "w0": {"rank": 0, "size": 2},
            "w1": {"rank": 1, "size": 2},
        })
        asg = client.poll_assignment("w0", timeout=5)
        assert asg["rank"] == 0 and asg["epoch"] == 1

        # A worker that consumed epoch 1 must NOT re-adopt it after a
        # failure; it waits for epoch 2.
        with pytest.raises(TimeoutError):
            client.poll_assignment("w0", timeout=0.5, min_epoch=2)
        server.start_epoch({"w0": {"rank": 0, "size": 1}})
        asg = client.poll_assignment("w0", timeout=5, min_epoch=2)
        assert asg["epoch"] == 2 and asg["size"] == 1

        client.kv_put("k", {"v": 1})
        assert client.kv_get("k") == {"v": 1}
        assert client.kv_get("missing") is None
    finally:
        server.stop()


def test_worker_notification_roundtrip():
    mgr = WorkerNotificationManager()
    port = mgr.init()
    try:
        assert mgr.poll_hosts_updated() == (False, False)
        assert notify_worker("127.0.0.1", port, skip_sync=True)
        deadline = time.monotonic() + 5
        updated = skip = False
        while time.monotonic() < deadline and not updated:
            updated, skip = mgr.poll_hosts_updated()
        assert updated and skip
        # Flag is consumed.
        assert mgr.poll_hosts_updated() == (False, False)
    finally:
        mgr.shutdown()


def test_driver_spawns_and_cuts_epoch(tmp_path):
    """Thread-fake workers: the spawned command registers with rendezvous
    and exits 0; the driver must cut an epoch covering every slot."""
    marker = tmp_path / "assignments"
    marker.mkdir()
    worker_src = tmp_path / "worker.py"
    worker_src.write_text(f"""
import json, os, sys
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))!r})
from horovod_tpu.runner.elastic.rendezvous import RendezvousClient

wid = os.environ["HOROVOD_WORKER_ID"]
c = RendezvousClient(os.environ["HOROVOD_RDZV_ADDR"],
                     os.environ["HOROVOD_RDZV_PORT"])
c.register(wid, os.environ["HOROVOD_HOSTNAME"], 0, None)
asg = c.poll_assignment(wid, timeout=30)
open(os.path.join({str(marker)!r}, wid.replace(":", "_")), "w").write(
    json.dumps(asg))
""")
    from horovod_tpu.runner.elastic.driver import ElasticDriver

    import sys

    driver = ElasticDriver(FixedHosts({"localhost": 3}),
                           [sys.executable, str(worker_src)], min_np=3)
    driver.start()
    try:
        rc = driver.wait_for_completion()
    finally:
        driver.stop()
    assert rc == 0
    import json

    got = sorted(json.loads(p.read_text())["rank"]
                 for p in marker.iterdir())
    assert got == [0, 1, 2]
    sizes = {json.loads(p.read_text())["size"] for p in marker.iterdir()}
    assert sizes == {3}


def _reconcile_driver(hosts):
    """ElasticDriver with fake spawn/cut, for reconcile-logic tests."""
    from horovod_tpu.runner.elastic.driver import ElasticDriver, _Worker

    d = ElasticDriver.__new__(ElasticDriver)
    d._lock = threading.RLock()
    d._min_np = 1
    d._max_np = 10 ** 9
    d._start_timeout = 5
    d._final_codes = []
    d._reconcile_needed = threading.Event()
    d._verbose = False
    d._rendezvous = RendezvousServer()
    d._workers = {}
    d._host_failures = {}
    d._shutdown = threading.Event()

    class _Mgr:
        current_hosts = dict(hosts)

    d._manager = _Mgr()
    d._spawned = []
    d._cuts = []

    def fake_spawn(host, idx):
        w = _Worker(f"{host}:{len(d._spawned)}-{idx}", host, idx)
        d._workers[w.worker_id] = w
        d._spawned.append(w)
        return w

    d._spawn = fake_spawn
    d._cut_epoch = lambda workers: d._cuts.append(list(workers))
    return d


def test_reconcile_shrink_respects_host_capacity():
    """fail→respawn→shrink: a surviving oldest worker may hold
    local_index >= slots; the freed lower index must NOT be refilled on
    a host already at capacity (would publish local_size > slots and
    double-bind chips)."""
    d = _reconcile_driver({"h": 4})
    try:
        d._reconcile()
        assert len(d._workers) == 4
        # idx2 fails; its slot frees; the respawn takes it (youngest seq)
        dead = next(w for w in d._workers.values() if w.local_index == 2)
        del d._workers[dead.worker_id]
        d._reconcile()
        assert len(d._workers) == 4
        # shrink to 3 slots: the respawn (youngest) dies; survivors hold
        # indexes {0, 1, 3}; index 2 is free but the host is full.
        d._manager.current_hosts = {"h": 3}
        spawns_before = len(d._spawned)
        d._reconcile()
        assert len(d._workers) == 3
        assert len(d._spawned) == spawns_before
        assert {w.local_index for w in d._workers.values()} == {0, 1, 3}
    finally:
        d._rendezvous.stop()


def test_reconcile_skips_ghost_epoch_when_fleet_unchanged():
    """A reconcile that spawns nothing, kills nothing, and covers no
    re-registration must not cut an epoch (a ghost epoch desyncs the
    next real recovery's last_epoch tracking)."""
    d = _reconcile_driver({"h": 2})
    try:
        d._reconcile()
        assert len(d._cuts) == 1
        d._reconcile()  # discovery delta with no usable change
        assert len(d._cuts) == 1
        d._reconcile(force_cut=True)  # re-registration / retry: must cut
        assert len(d._cuts) == 2
    finally:
        d._rendezvous.stop()


def test_object_state_commit_restore():
    from horovod_tpu.common.elastic import ObjectState

    state = ObjectState(step=0, weights=np.zeros(3))
    state.step = 5
    state.weights = state.weights + 2
    state.save()
    state.step = 9
    state.weights[:] = 99
    state.restore()
    assert state.step == 5
    np.testing.assert_allclose(state.weights, 2)


def test_cut_epoch_rank_layout_survivor_first():
    """Rank 0 is the longest-lived worker; layout is host-major and
    cross_rank agrees with rank // local_size (the hierarchical
    allreduce probe's invariant), regardless of host name order."""
    from horovod_tpu.runner.elastic.driver import ElasticDriver, _Worker

    driver = ElasticDriver.__new__(ElasticDriver)
    driver._lock = threading.RLock()
    driver._min_np = 1
    driver._start_timeout = 5
    driver._final_codes = []
    driver._reconcile_needed = threading.Event()
    driver._verbose = False
    driver._rendezvous = RendezvousServer()
    try:
        # 'zeta' host holds the two oldest workers (incl. the original
        # rank 0); 'alpha' got a fresh respawn (highest seq).
        workers = [_Worker("zeta:a", "zeta", 0),
                   _Worker("zeta:b", "zeta", 1),
                   _Worker("alpha:c", "alpha", 0),
                   _Worker("alpha:d", "alpha", 1)]
        # respawn on alpha slot 0: new uuid, max seq
        respawn = _Worker("alpha:e", "alpha", 0)
        fleet = [workers[0], workers[1], respawn, workers[3]]
        driver._workers = {w.worker_id: w for w in fleet}
        client = RendezvousClient("127.0.0.1", driver._rendezvous.port)
        for w in fleet:
            client.register(w.worker_id, w.host, w.local_index, None)
        driver._cut_epoch(fleet)

        asg = {w.worker_id: client.poll_assignment(w.worker_id, timeout=5)
               for w in fleet}
        # oldest worker (zeta:a) is rank 0 even though 'alpha' < 'zeta'
        assert asg["zeta:a"]["rank"] == 0
        # fresh respawn is ranked last within its host
        assert asg["alpha:e"]["rank"] > asg["alpha:d"]["rank"]
        for a in asg.values():
            assert a["size"] == 4 and a["local_size"] == 2
            assert a["cross_rank"] == a["rank"] // a["local_size"]
            assert a["cross_size"] == 2
    finally:
        driver._rendezvous.stop()
