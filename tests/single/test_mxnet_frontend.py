"""MXNet frontend import gating.

mxnet is not installed in this image, so the testable surface is the
reference-matching ImportError contract (horovod raises a clear error
when an extension isn't available) plus compileability of the module
source. With mxnet present, tests/parallel/test_torch_frontend.py's
pattern applies unchanged (same eager core underneath).
"""

import importlib.util
import pathlib
import py_compile

import pytest

HAS_MXNET = importlib.util.find_spec("mxnet") is not None
PKG = pathlib.Path(__file__).resolve().parents[2] / "horovod_tpu" / "mxnet"


@pytest.mark.skipif(HAS_MXNET, reason="mxnet installed; gating not hit")
def test_import_without_mxnet_raises_informative():
    with pytest.raises(ImportError, match="mxnet"):
        import horovod_tpu.mxnet  # noqa: F401


def test_module_sources_compile():
    for f in PKG.glob("*.py"):
        py_compile.compile(str(f), doraise=True)


@pytest.mark.skipif(not HAS_MXNET, reason="mxnet not installed")
def test_single_rank_allreduce():
    import mxnet as mx

    import horovod_tpu.mxnet as hvd

    hvd.init()
    out = hvd.allreduce(mx.nd.ones((4,)), name="t", op=hvd.Sum)
    assert out.asnumpy().tolist() == [1.0] * 4
