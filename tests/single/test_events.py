"""The structured event ring + black-box post-mortem pipeline
(docs/metrics.md): wait-free recording in the core, the two-call
drain/peek C-ABI, offline dump parsing, root-cause-vs-secondary
attribution, and the events -> Perfetto rendering.

Multi-rank wire recording is pinned in
tests/parallel/test_observability.py; this lane covers everything that
needs no second process.
"""

import json

import numpy as np
import pytest

from horovod_tpu.telemetry import postmortem, report

pytestmark = pytest.mark.quick


@pytest.fixture()
def hvd_core(monkeypatch):
    for k in ("HOROVOD_RANK", "HOROVOD_SIZE", "HOROVOD_LOCAL_RANK",
              "HOROVOD_LOCAL_SIZE"):
        monkeypatch.delenv(k, raising=False)
    from horovod_tpu.common import basics

    b = basics.HorovodBasics()
    b.init()
    yield b
    b.shutdown()


# ---- ring semantics ---------------------------------------------------


def test_events_record_drain_peek(hvd_core):
    from horovod_tpu.common import eager_ops as ops

    hvd_core.events_drain()  # start from a clean cursor
    head0 = hvd_core.lib.hvdtpu_events_head()
    x = np.ones(256, np.float32)
    for i in range(3):
        ops.allreduce_async(x, f"ring.{i}").synchronize()
    evs = [e for e in hvd_core.events() if e["seq"] >= head0]
    types = [e["type"] for e in evs]
    assert types.count("response_launch") >= 3
    assert "negotiate_begin" in types and "negotiate_end" in types
    # seq strictly increasing; every event timestamped and typed.
    seqs = [e["seq"] for e in evs]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    assert all(e["ts_us"] > 0 for e in evs)
    # response_launch carries the negotiated shape bytes + op class.
    launch = [e for e in evs if e["type"] == "response_launch"][-1]
    assert launch["op_class"] == 0 and launch["bytes"] == 256 * 4
    # peek is non-consuming, drain consumes exactly once.
    tail = hvd_core.events(2)
    assert len(tail) == 2 and tail == hvd_core.events(2)
    drained = hvd_core.events_drain()
    assert [e["seq"] for e in drained] == seqs
    # Drain-residue discipline (r15 gotcha): residue assertions after a
    # drain must be TYPE-based, never count-based — a straggling
    # background cycle's bookkeeping events race an immediate second
    # drain under full-suite load. The bare `== []` below is safe ONLY
    # because this world is size 1 with no traffic in flight; in any
    # multi-rank test assert "no traffic types in the residue" like
    # tests/parallel/test_observability.py::_wire_events_worker does,
    # or you will reintroduce the one quick-lane flake r15 fixed.
    assert hvd_core.events_drain() == []


def test_events_disable_toggle(hvd_core):
    from horovod_tpu.common import eager_ops as ops

    assert hvd_core.events_enabled()
    hvd_core.set_events_enabled(False)
    h0 = hvd_core.lib.hvdtpu_events_head()
    ops.allreduce_async(np.ones(16, np.float32), "off.0").synchronize()
    assert hvd_core.lib.hvdtpu_events_head() == h0
    hvd_core.set_events_enabled(True)
    ops.allreduce_async(np.ones(16, np.float32), "on.0").synchronize()
    assert hvd_core.lib.hvdtpu_events_head() > h0


def test_ring_selftest_records_plane_tagged_wire_events(hvd_core):
    """The in-process selftest drives REAL ring transfers: chunk and
    span events appear, and recording is exercised from several caller
    threads at once (each plane's thread-local tag)."""
    hvd_core.events_drain()
    rc, _ = hvd_core.ring_selftest(4, 20000, chunk_bytes=4096)
    assert rc == 0
    evs = hvd_core.events_drain()
    spans = [e for e in evs if e["type"] == "wire_span"]
    chunks = [e for e in evs if e["type"] == "wire_chunk"]
    assert spans and chunks
    assert all(s["plane"] == 0 for s in spans)
    assert all(s["tx_bytes"] > 0 for s in spans)
    assert all(c["len"] > 0 for c in chunks)


def test_step_marks_scope_ledger_and_events(hvd_core):
    """hvdtpu_step_mark boundary semantics + the overlap ledger's exact
    per-plane reconciliation over real selftest wire traffic
    (docs/metrics.md "Step anatomy")."""
    ov0 = hvd_core.metrics_snapshot()["wire"]["overlap"]
    assert hvd_core.step_id() == -1
    sid = hvd_core.step_mark(True)
    assert sid >= 1 and hvd_core.step_id() == sid
    rc, _ = hvd_core.ring_selftest(4, 20000, chunk_bytes=4096)
    assert rc == 0
    assert hvd_core.step_mark(False) == sid
    assert hvd_core.step_id() == -1
    # Begin-while-open closes first (boundary semantics): one call per
    # iteration is a complete driver.
    sid2 = hvd_core.step_mark(True)
    sid3 = hvd_core.step_mark(True)
    assert sid3 == sid2 + 1
    hvd_core.step_mark(False)
    assert hvd_core.step_mark(False) == -1  # nothing open: no-op

    ov1 = hvd_core.metrics_snapshot()["wire"]["overlap"]
    assert ov1["steps"] - ov0["steps"] == 3
    for plane in ("intra", "cross"):
        p = ov1[plane]
        # The reconciliation contract: exact, not approximate.
        assert p["exposed_us"] + p["hidden_us"] == p["total_us"], ov1
    # The selftest's wire never blocks an API thread in hvdtpu_wait
    # (it runs inline in the selftest call), so every span is hidden
    # under host activity; the first window booked it all (intra
    # plane).
    intra = {k: ov1["intra"][k] - ov0["intra"][k]
             for k in ("total_us", "hidden_us", "exposed_us")}
    assert intra["total_us"] > 0 and intra["hidden_us"] > 0, ov1
    assert ov1["overlap_efficiency"] > 0.0

    evs = [e for e in hvd_core.events()
           if e["type"] in ("step_begin", "step_end")][-6:]
    assert [e["type"] for e in evs] == ["step_begin", "step_end"] * 3
    assert evs[0]["step"] == sid and evs[1]["step"] == sid
    assert evs[1]["dur_us"] >= 0
    assert evs[3]["step"] == sid2 and evs[4]["step"] == sid3


def test_event_ring_wraps_without_losing_order(hvd_core):
    """Overfill the ring (capacity 8192) and check the live window is
    the NEWEST events, still seq-ordered."""
    from horovod_tpu.common import eager_ops as ops

    x = np.ones(4, np.float32)
    # Each grouped enqueue negotiates >= 3 events; 3500 rounds laps 8k.
    for i in range(3500):
        ops.allreduce_async(x, f"wrap.{i}").synchronize()
    evs = hvd_core.events()
    seqs = [e["seq"] for e in evs]
    assert seqs == sorted(seqs)
    assert len(evs) <= 8192
    head = hvd_core.lib.hvdtpu_events_head()
    assert seqs[-1] == head - 1
    assert seqs[0] >= head - 8192


# ---- black-box parsing & post-mortem attribution ---------------------


def _write_dump(path, rank, faults, events, unix0=1_700_000_000_000_000,
                steady0=5_000_000, epoch=0, append=False):
    """One dump: header + event lines. ``events`` = (ts_us, type,
    extra-dict) tuples on the rank's steady clock."""
    lines = [json.dumps({
        "kind": "blackbox_header", "rank": rank, "size": 4,
        "epoch": epoch, "unix_us": unix0 + steady0, "steady_us": steady0,
        "fault": faults})]
    for seq, (ts, typ, extra) in enumerate(events):
        lines.append(json.dumps(
            {"seq": seq, "ts_us": ts, "type": typ, **extra}))
    with open(path, "a" if append else "w") as f:
        f.write("\n".join(lines) + "\n")


def test_postmortem_root_cause_vs_secondary(tmp_path):
    """Certain attribution of a rank with NO dump = root-cause death;
    any naming of a rank that dumped = secondary timeout (it was alive
    — the r12 teardown race writes false EOF attributions)."""
    # Rank 3 was SIGKILLed: ranks 0/1 prove it (EOF), rank 2 timed out
    # blaming its quiet neighbor 1 — which dumped, so it is alive.
    _write_dump(tmp_path / "blackbox-rank0.jsonl", 0,
                {"kind": "peer", "certain": True, "ranks": [3]},
                [(1000, "response_launch",
                  {"op_class": 0, "device": 0, "tensors": 1,
                   "bytes": 64}),
                 (2000, "fault", {"kind": 0, "certain": 1, "epoch": 0,
                                  "fault_rank": 3})])
    _write_dump(tmp_path / "blackbox-rank1.jsonl", 1,
                {"kind": "peer", "certain": True, "ranks": [3]},
                [(900, "negotiate_end", {"responses": 1, "shutdown": 0}),
                 (2100, "fault", {"kind": 0, "certain": 1, "epoch": 0,
                                  "fault_rank": 3})])
    _write_dump(tmp_path / "blackbox-rank2.jsonl", 2,
                {"kind": "peer", "certain": False, "ranks": [1]},
                [(1500, "negotiate_end", {"responses": 1, "shutdown": 0}),
                 (2500, "fault", {"kind": 0, "certain": 0, "epoch": 0,
                                  "fault_rank": 1})])
    analysis = postmortem.merge_post_mortem(str(tmp_path))
    assert analysis["root_cause_ranks"] == [3]
    assert analysis["secondary_suspects"] == [1]
    assert analysis["ranks"] == [0, 1, 2]
    text = postmortem.format_post_mortem(analysis)
    assert "root cause: rank(s) [3]" in text
    assert "secondary timeouts" in text


def test_postmortem_corruption_names_live_peer(tmp_path):
    _write_dump(tmp_path / "blackbox-rank0.jsonl", 0,
                {"kind": "corruption", "certain": False, "ranks": [1]},
                [(100, "crc_error", {"sender": 1, "fails": 3, "chunk": 7}),
                 (200, "fault", {"kind": 1, "certain": 0, "epoch": 0,
                                 "fault_rank": 1})])
    _write_dump(tmp_path / "blackbox-rank1.jsonl", 1,
                {"kind": "peer", "certain": False, "ranks": [0]},
                [(150, "negotiate_end", {"responses": 1, "shutdown": 0})])
    analysis = postmortem.merge_post_mortem(str(tmp_path))
    # The corrupting link's sender is the root cause even though its
    # process is alive (and dumped); it is never "secondary".
    assert analysis["root_cause_ranks"] == [1]
    assert analysis["secondary_suspects"] == [0]


def test_postmortem_first_stalled_cutoff(tmp_path):
    """Progress after the stall surfaced (retry windows began) must not
    mask who froze first."""
    # Rank 1 froze at t=1000 then resumed late and did more work; rank
    # 0 kept launching until t=1900, then rode the retry ladder.
    _write_dump(tmp_path / "blackbox-rank0.jsonl", 0,
                {"kind": "peer", "certain": False, "ranks": [1]},
                [(1000, "response_launch",
                  {"op_class": 0, "device": 0, "tensors": 1, "bytes": 8}),
                 (1900, "response_launch",
                  {"op_class": 0, "device": 0, "tensors": 1, "bytes": 8}),
                 (2500, "retry_window", {"attempt": 0, "window_ms": 250}),
                 (4000, "fault", {"kind": 0, "certain": 0, "epoch": 0,
                                  "fault_rank": 1})])
    _write_dump(tmp_path / "blackbox-rank1.jsonl", 1,
                {"kind": "peer", "certain": False, "ranks": [0]},
                [(1000, "response_launch",
                  {"op_class": 0, "device": 0, "tensors": 1, "bytes": 8}),
                 # resumed AFTER rank 0's ladder began: doesn't count
                 (3000, "response_launch",
                  {"op_class": 0, "device": 0, "tensors": 1, "bytes": 8}),
                 (4100, "fault", {"kind": 0, "certain": 0, "epoch": 0,
                                  "fault_rank": 0})])
    analysis = postmortem.merge_post_mortem(str(tmp_path))
    assert analysis["root_cause_ranks"] == []  # nobody provably died
    assert analysis["first_stalled_rank"] == 1
    # timeline is wall-merged and monotonic
    walls = [e["wall_us"] for e in analysis["timeline"]]
    assert walls == sorted(walls) and len(walls) == 7


def test_load_blackbox_multiple_dumps_and_torn_tail(tmp_path):
    p = tmp_path / "blackbox-rank0.jsonl"
    _write_dump(p, 0, {"kind": "peer", "certain": True, "ranks": [2]},
                [(10, "wire_heal", {})], epoch=0)
    _write_dump(p, 0, {"kind": "peer", "certain": True, "ranks": [1]},
                [(20, "wire_heal", {})], epoch=1, append=True)
    with open(p, "a") as f:
        f.write('{"seq": 99, "ts_us": 30, "type": "trunc')  # died here
    dumps = postmortem.load_blackbox(str(p))
    assert len(dumps) == 2
    assert dumps[0]["header"]["epoch"] == 0
    assert dumps[1]["header"]["epoch"] == 1
    assert len(dumps[1]["events"]) == 1  # torn line dropped
    # merge picks the LATEST dump by default
    analysis = postmortem.merge_post_mortem(str(tmp_path))
    assert analysis["root_cause_ranks"] == [1]


# ---- events -> Perfetto ----------------------------------------------


def test_events_fold_into_perfetto_merge(tmp_path):
    """--events renders ring dumps as extra tracks on the merged trace,
    wall-aligned against the timelines' CLOCK_SYNC anchors."""
    sync0 = 1_700_000_000_000_000
    tl = [
        {"name": "process_name", "ph": "M", "pid": 0,
         "args": {"name": "rank 0"}},
        {"name": "CLOCK_SYNC", "ph": "i", "ts": 0, "pid": 0, "tid": 0,
         "s": "p", "args": {"unix_us": sync0, "rank": 0}},
        {"name": "NEGOTIATE", "ph": "B", "ts": 500, "pid": 0, "tid": 1,
         "args": {"tensor": "g0"}},
        {"name": "NEGOTIATE", "ph": "E", "ts": 900, "pid": 0, "tid": 1,
         "args": {"tensor": "g0"}},
    ]
    tl_path = tmp_path / "tl.0.json"
    tl_path.write_text(json.dumps(tl))
    # A dump whose steady clock origin differs: event at steady 7000
    # with anchor (steady 5000 -> wall sync0 + 600) = wall sync0+2600.
    _write_dump(tmp_path / "blackbox-rank0.jsonl", 0,
                {"kind": "peer", "certain": True, "ranks": [1]},
                [(7000, "wire_span",
                  {"plane": 1, "dur_us": 400, "tx_bytes": 64,
                   "rx_bytes": 64}),
                 (7100, "wire_heal", {})],
                unix0=sync0 + 600 - 5000, steady0=5000)
    merged, _skew = report.merge(
        [str(tl_path)],
        events_paths=[str(tmp_path / "blackbox-rank0.jsonl")])
    spans = [e for e in merged if e.get("name", "").startswith(
        "wire_span")]
    assert len(spans) == 1
    # ts = wall - base = 2600, rendered as an X span ending there.
    assert spans[0]["ph"] == "X"
    assert spans[0]["ts"] + spans[0]["dur"] == 2600
    assert spans[0]["pid"] == 0 and spans[0]["args"]["plane"] == 1
    insts = [e for e in merged if e.get("name") == "wire_heal"]
    assert insts and insts[0]["ts"] == 2700
    # the events lane is labeled
    assert any(e.get("name") == "thread_name" and
               e["args"]["name"] == "events" for e in merged)


def test_events_fold_anchors_per_rank_without_alignment(tmp_path):
    """With align=False (or the NEGOTIATE fallback) per-rank offsets
    are NOT sync_r - min(sync): each dump must anchor against ITS OWN
    rank's trace (base = sync_r - offset_r), or the event tracks shear
    off the op spans they annotate."""
    for rank, sync in ((0, 10_000_000), (1, 11_000_000)):
        tl = [
            {"name": "CLOCK_SYNC", "ph": "i", "ts": 0, "pid": rank,
             "s": "p", "args": {"unix_us": sync, "rank": rank}},
            {"name": "OP", "ph": "X", "ts": 500, "dur": 100,
             "pid": rank, "args": {}},
        ]
        (tmp_path / f"tl.{rank}.json").write_text(json.dumps(tl))
        _write_dump(tmp_path / f"blackbox-rank{rank}.jsonl", rank,
                    {"kind": "peer", "certain": True, "ranks": [1]},
                    [(4900, "wire_heal", {})],
                    unix0=sync + 600 - 5000, steady0=5000)
    paths = [str(tmp_path / "tl.0.json"), str(tmp_path / "tl.1.json")]
    for align in (True, False):
        merged, _ = report.merge(paths, align=align,
                                 events_paths=[str(tmp_path)])
        # Each ring event (wall = sync_r + 500) lands at its own
        # trace's t=500 coordinate plus that rank's offset — offsets
        # are 0 when not aligning, sync_r - min(sync) when aligning.
        want = {0: 500, 1: 500 + (1_000_000 if align else 0)}
        got = {e["pid"]: e["ts"] for e in merged
               if e.get("name") == "wire_heal"}
        assert got == want, (align, got, want)


def test_report_post_mortem_cli(tmp_path, capsys):
    _write_dump(tmp_path / "blackbox-rank0.jsonl", 0,
                {"kind": "peer", "certain": True, "ranks": [1],
                 "detect_ms": 12, "reason": "eof"},
                [(100, "negotiate_begin", {"requests": 1}),
                 (300, "fault", {"kind": 0, "certain": 1, "epoch": 0,
                                 "fault_rank": 1})])
    out_json = tmp_path / "analysis.json"
    rc = report.main(["--post-mortem", str(tmp_path / "blackbox-rank0.jsonl"),
                      "-o", str(out_json)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "root cause: rank(s) [1]" in out
    assert out_json.exists()
    saved = json.loads(out_json.read_text())
    assert saved["root_cause_ranks"] == [1]
