"""Cross-rank critical-path attribution (docs/metrics.md "Critical
path"): interval-union math, step-window extraction (including windows
whose begin mark aged out of the ring), blocking-rank/phase verdicts on
synthetic two-rank traces with KNOWN chains, and the 64-rank simworld
merge over synthesized dumps (r16 gotcha 1: the in-process world cannot
emit real per-rank files, so the harness synthesizes them in the exact
DumpBlackBox schema)."""

import json
import os

import pytest

from horovod_tpu.telemetry import critpath, report

pytestmark = pytest.mark.quick

_UNIX0 = 1_700_000_000_000_000


def _write_dump(path, rank, events, steady0=0, unix0=_UNIX0, size=2):
    """One rank's dump with an explicit clock anchor: an event meant at
    TRUE wall time W must be stamped ts_us = W - unix0 + steady0."""
    header = {"kind": "blackbox_header", "rank": rank, "size": size,
              "epoch": 0, "unix_us": unix0, "steady_us": steady0,
              "fault": {}}
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        f.write(json.dumps(header) + "\n")
        for seq, ev in enumerate(events):
            f.write(json.dumps({"seq": seq, **ev}) + "\n")
    return path


def _at(wall, steady0=0, unix0=_UNIX0):
    return wall - unix0 + steady0


# ---- interval-union edge cases ----------------------------------------


def test_union_measure_edge_cases():
    # Abutting intervals merge without double counting.
    assert critpath.union_measure([(0, 5), (5, 10)]) == 10
    # Nested and overlapping collapse.
    assert critpath.union_measure([(0, 10), (2, 5), (8, 12)]) == 12
    # Zero-length spans contribute nothing.
    assert critpath.union_measure([(3, 3)]) == 0
    assert critpath.union_measure([(3, 3), (1, 2)]) == 1
    # Clipping to a window.
    assert critpath.union_measure([(0, 100)], lo=10, hi=30) == 20
    # Inverted (negative) intervals are dropped, not subtracted.
    assert critpath.union_measure([(5, 2), (0, 4)]) == 4
    assert critpath.union_measure([]) == 0


def test_step_window_spanning_ring_wrap(tmp_path):
    """A step_end whose step_begin aged out of the 8192-slot ring opens
    at the dump's earliest event: the window is truncated, not lost."""
    path = _write_dump(str(tmp_path / "blackbox-rank0.jsonl"), 0, [
        {"ts_us": _at(50_000), "type": "wire_span", "plane": 0,
         "dur_us": 10_000, "tx_bytes": 1, "rx_bytes": 1},
        {"ts_us": _at(100_000), "type": "step_end", "step": 7,
         "dur_us": 90_000},
    ])
    dump = critpath.postmortem.load_blackbox(path)[-1]
    windows = critpath.step_windows(dump)
    # The dump's earliest event (the span's END stamp at wall 50 ms)
    # opens the truncated window.
    assert windows == {7: (50_000, 100_000)}, windows
    a = critpath.critical_path(str(tmp_path))
    assert a["steps"][0]["step"] == 7
    assert a["steps"][0]["per_rank"][0]["window_ms"] == 50.0


# ---- known two-rank blocking chains -----------------------------------


def _two_rank_traces(tmp_path):
    """Three steps with a known chain: step 1 rank 0 compute-bound,
    step 2 rank 1 stall-bound (healing-ladder retry window), step 3
    rank 0 wire-bound. Rank 1's steady clock starts elsewhere — the
    anchor pair must realign it."""
    r0, r1 = [], []
    s1 = 500_000  # rank 1 steady offset

    def mark(events, steady0, sid, begin, end, body):
        events.append({"ts_us": _at(begin, steady0),
                       "type": "step_begin", "step": sid})
        events.extend(body)
        events.append({"ts_us": _at(end, steady0), "type": "step_end",
                       "step": sid, "dur_us": end - begin})

    def span(wall_end, dur, steady0):
        return {"ts_us": _at(wall_end, steady0), "type": "wire_span",
                "plane": 0, "dur_us": dur, "tx_bytes": 1, "rx_bytes": 1}

    # Step 1: wall 0..100k. rank0 computes 90k then wires 10k; rank1's
    # span stretches over 90k absorbing the wait.
    mark(r0, 0, 1, 0, 100_000, [span(100_000, 10_000, 0)])
    mark(r1, s1, 1, 0, 100_000, [span(100_000, 90_000, s1)])

    # Step 2: wall 100k..200k. rank1 spends 80k in a retry window then
    # 10k on the wire; rank0 waits on the wire for 90k.
    mark(r0, 0, 2, 100_000, 200_000, [span(200_000, 90_000, 0)])
    mark(r1, s1, 2, 100_000, 200_000, [
        {"ts_us": _at(190_000, s1), "type": "retry_window",
         "attempt": 1, "window_ms": 80},
        span(200_000, 10_000, s1)])

    # Step 3: wall 200k..300k. Both wire-bound; rank0 slightly more
    # self time (88k wire vs rank1's 90k).
    mark(r0, 0, 3, 200_000, 300_000, [span(295_000, 88_000, 0)])
    mark(r1, s1, 3, 200_000, 300_000, [span(295_000, 90_000, s1)])

    _write_dump(str(tmp_path / "blackbox-rank0.jsonl"), 0, r0)
    _write_dump(str(tmp_path / "blackbox-rank1.jsonl"), 1, r1,
                steady0=s1)
    return str(tmp_path)


def test_known_blocking_chain_two_ranks(tmp_path):
    a = critpath.critical_path(_two_rank_traces(tmp_path))
    assert a["ranks"] == [0, 1]
    chain = [(s["step"], s["blocking_rank"], s["phase"])
             for s in a["steps"]]
    assert chain == [(1, 0, "compute"), (2, 1, "stall"),
                     (3, 0, "wire")], chain
    # Per-rank shares carry the evidence: step 1's blocking rank shows
    # 90 ms compute / 10 ms wire; its peer the inverse.
    s1 = a["steps"][0]["per_rank"]
    assert s1[0]["compute_ms"] == 90.0 and s1[0]["wire_ms"] == 10.0
    assert s1[1]["wire_ms"] == 90.0 and s1[1]["self_ms"] == 10.0
    assert a["blocking_counts"] == {0: 2, 1: 1}
    assert a["phase_counts"] == {"compute": 1, "stall": 1, "wire": 1}


def test_injected_delay_gap_attributes_as_stall(tmp_path):
    """A chaos delay:<ms> sleeps between its inject event and the next
    runtime activity — that gap is stall evidence, closed at a
    following wire_span's START so wire time is not swallowed."""
    _write_dump(str(tmp_path / "blackbox-rank0.jsonl"), 0, [
        {"ts_us": _at(0), "type": "step_begin", "step": 1},
        {"ts_us": _at(5_000), "type": "inject", "action": 4,
         "op_index": 3},
        # Sleep 80 ms, then a 10 ms wire span stamped at its end.
        {"ts_us": _at(95_000), "type": "wire_span", "plane": 0,
         "dur_us": 10_000, "tx_bytes": 1, "rx_bytes": 1},
        {"ts_us": _at(100_000), "type": "step_end", "step": 1,
         "dur_us": 100_000},
    ])
    a = critpath.critical_path(str(tmp_path))
    (s,) = a["steps"]
    assert s["phase"] == "stall", s
    r = s["per_rank"][0]
    assert r["stall_ms"] == 80.0 and r["wire_ms"] == 10.0, r


def test_report_cli_critical_path(tmp_path, capsys):
    d = _two_rank_traces(tmp_path / "dumps")
    out_json = str(tmp_path / "cp.json")
    rc = report.main(["--critical-path", d, "-o", out_json])
    assert rc == 0
    out = capsys.readouterr().out
    assert "critical path: rank 0 bounded 2 of 3 steps" in out, out
    assert os.path.exists(out_json)
    with open(out_json) as f:
        assert json.load(f)["blocking_counts"] == {"0": 2, "1": 1}


# ---- 64-rank simworld merge (synthesized dumps, r16 gotcha 1) ---------


def test_simworld_64_rank_straggler_attribution(tmp_path):
    from horovod_tpu.simworld import harness

    harness.write_sim_step_dumps(str(tmp_path), ranks=64, steps=4,
                                 slow_rank=41)
    a = critpath.critical_path(str(tmp_path))
    assert a["ranks"] == list(range(64))
    assert len(a["steps"]) == 4
    for s in a["steps"]:
        assert s["blocking_rank"] == 41, s["step"]
        assert s["phase"] == "compute", s
    assert a["blocking_counts"] == {41: 4}
    # The rendering names the straggler too.
    text = critpath.format_critical_path(a)
    assert "rank 41 bounded 4 of 4 steps" in text, text
