"""Spark/Ray integration units — the dependency-free planning pieces.

Reference analog: test/single/test_ray.py + test_spark.py run against
live local clusters; pyspark/ray aren't in this image, so we test every
pure component (store, params, rank planning, import gating) and skip
the cluster paths (the reference skips the same way when deps missing).
"""

import importlib.util

import pytest

HAS_SPARK = importlib.util.find_spec("pyspark") is not None
HAS_RAY = importlib.util.find_spec("ray") is not None


def test_filesystem_store_roundtrip(tmp_path):
    from horovod_tpu.spark.common.store import FilesystemStore, Store

    store = Store.create(str(tmp_path / "st"))
    assert isinstance(store, FilesystemStore)
    ckpt = store.get_checkpoint_path("run1")
    store.write(ckpt, b"weights")
    assert store.exists(ckpt)
    assert store.read(ckpt) == b"weights"
    assert store.get_train_data_path(3).endswith("intermediate_train_data.3")
    assert "run1" in store.get_logs_path("run1")


def test_store_sync_fn(tmp_path):
    from horovod_tpu.spark.common.store import FilesystemStore

    store = FilesystemStore(str(tmp_path / "st"))
    local = tmp_path / "local"
    local.mkdir()
    (local / "ckpt.bin").write_bytes(b"x")
    store.sync_fn("r1")(str(local))
    assert (tmp_path / "st" / "runs" / "r1" / "ckpt.bin").read_bytes() == b"x"


def test_estimator_params():
    from horovod_tpu.spark.common.params import EstimatorParams

    p = EstimatorParams(batch_size=64, epochs=3, label_cols=("y",))
    assert p.batch_size == 64
    assert p.getBatchSize() == 64          # pyspark.ml-style getter
    assert p.getEpochs() == 3
    with pytest.raises(TypeError, match="unknown"):
        EstimatorParams(bogus=1)


def test_ray_rank_planning():
    from horovod_tpu.ray.runner import plan_ranks

    envs = plan_ranks([(0, "a"), (1, "b"), (2, "a"), (3, "b")])
    # contiguous ranks per host: a -> ranks 0,1 ; b -> ranks 2,3
    assert envs[0]["HOROVOD_RANK"] == "0"
    assert envs[2]["HOROVOD_RANK"] == "1"
    assert envs[2]["HOROVOD_LOCAL_RANK"] == "1"
    assert envs[1]["HOROVOD_CROSS_RANK"] == "1"
    assert all(e["HOROVOD_SIZE"] == "4" for e in envs.values())
    assert all(e["HOROVOD_LOCAL_SIZE"] == "2" for e in envs.values())


def test_ray_strategy_bundles():
    from horovod_tpu.ray.strategy import PackStrategy, SpreadStrategy

    s = PackStrategy(4, cpus_per_worker=2, gpus_per_worker=1)
    assert s.placement_strategy == "PACK"
    assert s.bundles() == [{"CPU": 2, "GPU": 1}] * 4
    assert SpreadStrategy(2).placement_strategy == "SPREAD"


@pytest.mark.skipif(HAS_RAY, reason="ray installed")
def test_ray_executor_gating():
    from horovod_tpu.ray import RayExecutor

    ex = RayExecutor(num_workers=2)
    with pytest.raises(ImportError, match="ray"):
        ex.start()


@pytest.mark.skipif(HAS_SPARK, reason="pyspark installed")
def test_spark_run_gating():
    import horovod_tpu.spark as hs

    with pytest.raises(ImportError, match="pyspark"):
        hs.run(lambda: None, num_proc=2)


def test_lightning_protocol_training():
    """The duck-typed lightning runner trains a module that implements
    training_step/configure_optimizers, without pytorch_lightning."""
    import torch

    from horovod_tpu.spark.lightning import train_protocol_model

    class Lit(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.net = torch.nn.Linear(3, 1)
            self.epoch_ends = 0

        def forward(self, x):
            return self.net(x)

        def training_step(self, batch, batch_idx):
            x, y = batch
            return {"loss": torch.nn.functional.mse_loss(self(x), y)}

        def configure_optimizers(self):
            opt = torch.optim.SGD(self.parameters(), lr=0.1)
            sched = torch.optim.lr_scheduler.StepLR(opt, step_size=1,
                                                    gamma=0.5)
            return [opt], [sched]

        def on_train_epoch_end(self):
            self.epoch_ends += 1

    torch.manual_seed(0)
    model = Lit()
    x = torch.randn(32, 3)
    w = torch.tensor([[1.0], [-2.0], [0.5]])
    y = x @ w
    loss0 = torch.nn.functional.mse_loss(model(x), y).item()
    train_protocol_model(model, x, y, batch_size=8, epochs=3,
                         distributed=False)
    loss1 = torch.nn.functional.mse_loss(model(x), y).item()
    assert loss1 < loss0 * 0.5
    assert model.epoch_ends == 3


def test_protocol_trainer_on_epoch_end_hook():
    """The estimator's per-epoch validation hook: called once per epoch
    with (model, epoch); a recorded val loss shrinks as training
    progresses."""
    import torch

    from horovod_tpu.spark.lightning import train_protocol_model

    torch.manual_seed(0)
    x = torch.randn(64, 4)
    w_true = torch.randn(4, 1)
    y = x @ w_true
    vx, vy = torch.randn(16, 4), None
    vy = vx @ w_true

    class Lin(torch.nn.Module):
        def __init__(self):
            super().__init__()
            torch.manual_seed(1)
            self.net = torch.nn.Linear(4, 1)

        def forward(self, x):
            return self.net(x)

        def training_step(self, batch, batch_idx):
            xb, yb = batch
            return torch.nn.functional.mse_loss(self(xb), yb)

        def configure_optimizers(self):
            return torch.optim.SGD(self.parameters(), lr=0.1)

    calls = []

    def on_epoch_end(m, epoch):
        m.eval()
        with torch.no_grad():
            calls.append((epoch, float(
                torch.nn.functional.mse_loss(m(vx), vy))))
        m.train()

    train_protocol_model(Lin(), x, y, 16, epochs=5, distributed=False,
                         on_epoch_end=on_epoch_end)
    assert [e for e, _ in calls] == [0, 1, 2, 3, 4]
    assert calls[-1][1] < calls[0][1]  # val loss fell


def test_lightning_optimizer_unpacking():
    import torch

    from horovod_tpu.spark.lightning import _unpack_optimizers

    p = torch.nn.Parameter(torch.zeros(1))
    opt = torch.optim.SGD([p], lr=0.1)
    sched = torch.optim.lr_scheduler.StepLR(opt, step_size=1)
    entry = {"scheduler": sched, "interval": "epoch", "frequency": 1}

    assert _unpack_optimizers(opt) == ([opt], [])
    assert _unpack_optimizers([opt]) == ([opt], [])
    assert _unpack_optimizers(([opt], [sched])) == ([opt], [entry])
    assert _unpack_optimizers(
        {"optimizer": opt, "lr_scheduler": {"scheduler": sched}}) \
        == ([opt], [entry])
    assert _unpack_optimizers({"optimizer": opt}) == ([opt], [])

    # interval/frequency metadata rides along (per-step schedulers)
    assert _unpack_optimizers(
        {"optimizer": opt,
         "lr_scheduler": {"scheduler": sched, "interval": "step",
                          "frequency": 2}}) \
        == ([opt], [{"scheduler": sched, "interval": "step",
                     "frequency": 2}])

    # lightning's tuple-of-dicts form (one dict per optimizer)
    opt2 = torch.optim.SGD([p], lr=0.2)
    assert _unpack_optimizers(({"optimizer": opt},
                               {"optimizer": opt2,
                                "lr_scheduler": {"scheduler": sched}})) \
        == ([opt, opt2], [entry])


def test_lightning_step_interval_scheduler():
    """interval='step' schedulers advance per batch, not per epoch."""
    import torch

    from horovod_tpu.spark.lightning import train_protocol_model

    class Lit(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.net = torch.nn.Linear(3, 1)

        def training_step(self, batch, batch_idx):
            x, y = batch
            return torch.nn.functional.mse_loss(self.net(x), y)

        def configure_optimizers(self):
            opt = torch.optim.SGD(self.parameters(), lr=1.0)
            sched = torch.optim.lr_scheduler.StepLR(opt, step_size=1,
                                                    gamma=0.5)
            return {"optimizer": opt,
                    "lr_scheduler": {"scheduler": sched,
                                     "interval": "step"}}

    seen = []

    class Track(Lit):
        def training_step(self, batch, batch_idx):
            seen.append(self._opt.param_groups[0]["lr"])
            return super().training_step(batch, batch_idx)

        def configure_optimizers(self):
            cfg = super().configure_optimizers()
            self._opt = cfg["optimizer"]
            return cfg

    x, y = torch.randn(16, 3), torch.randn(16, 1)
    model = Track()
    train_protocol_model(model, x, y, batch_size=4, epochs=1,
                         distributed=False)
    # lr observed at each of the 4 batches: halved after every step
    assert seen == [1.0, 0.5, 0.25, 0.125]


def test_lightning_gan_style_toggle():
    """Generator loss flowing through the discriminator must not train
    the discriminator (lightning toggle_optimizer semantics)."""
    import torch

    from horovod_tpu.spark.lightning import train_protocol_model

    toggles = []

    class GAN(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.gen = torch.nn.Linear(3, 3)
            self.disc = torch.nn.Linear(3, 1)

        def training_step(self, batch, batch_idx, optimizer_idx):
            x, _ = batch
            toggles.append((optimizer_idx,
                            next(self.gen.parameters()).requires_grad,
                            next(self.disc.parameters()).requires_grad))
            if optimizer_idx == 0:
                # generator loss THROUGH the discriminator
                return -self.disc(self.gen(x)).mean()
            return self.disc(x.detach()).mean()

        def configure_optimizers(self):
            return [torch.optim.SGD(self.gen.parameters(), lr=0.1),
                    torch.optim.SGD(self.disc.parameters(), lr=0.0)]

    torch.manual_seed(0)
    model = GAN()
    disc_before = [p.detach().clone() for p in model.disc.parameters()]
    x = torch.randn(8, 3)
    train_protocol_model(model, x, torch.zeros(8, 1), batch_size=4,
                         epochs=1, distributed=False)
    # during the generator's step the disc was frozen, and vice versa
    assert (0, True, False) in toggles and (1, False, True) in toggles
    # disc lr=0: params bit-identical, and toggle state fully restored
    for p, p0 in zip(model.disc.parameters(), disc_before):
        assert torch.equal(p, p0)
        assert p.requires_grad


def test_lightning_toggle_spares_unowned_params():
    """A param owned by no optimizer keeps requires_grad during every
    training_step (lightning toggle_optimizer only freezes params owned
    by the *other* optimizers)."""
    import torch

    from horovod_tpu.spark.lightning import train_protocol_model

    observed = []

    class M(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.a = torch.nn.Linear(2, 1)
            self.b = torch.nn.Linear(2, 1)
            self.free = torch.nn.Parameter(torch.zeros(1))  # no optimizer

        def training_step(self, batch, batch_idx, optimizer_idx):
            observed.append(self.free.requires_grad)
            x, y = batch
            net = self.a if optimizer_idx == 0 else self.b
            return torch.nn.functional.mse_loss(net(x), y) \
                + 0.0 * self.free.sum()

        def configure_optimizers(self):
            return [torch.optim.SGD(self.a.parameters(), lr=0.1),
                    torch.optim.SGD(self.b.parameters(), lr=0.1)]

    m = M()
    train_protocol_model(m, torch.randn(4, 2), torch.randn(4, 1),
                         batch_size=4, epochs=1, distributed=False)
    assert observed and all(observed)


def test_lightning_multi_optimizer_training():
    """Two optimizers follow lightning's contract: training_step is
    called once per optimizer with optimizer_idx, each one steps."""
    import torch

    from horovod_tpu.spark.lightning import train_protocol_model

    class TwoOpt(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.a = torch.nn.Linear(3, 1)
            self.b = torch.nn.Linear(3, 1)
            self.seen_idx = set()

        def training_step(self, batch, batch_idx, optimizer_idx):
            x, y = batch
            self.seen_idx.add(optimizer_idx)
            net = self.a if optimizer_idx == 0 else self.b
            return torch.nn.functional.mse_loss(net(x), y)

        def configure_optimizers(self):
            return ({"optimizer": torch.optim.SGD(self.a.parameters(),
                                                  lr=0.1)},
                    {"optimizer": torch.optim.SGD(self.b.parameters(),
                                                  lr=0.1)})

    torch.manual_seed(0)
    model = TwoOpt()
    x = torch.randn(32, 3)
    y = x @ torch.tensor([[1.0], [0.5], [-1.0]])
    la0 = torch.nn.functional.mse_loss(model.a(x), y).item()
    lb0 = torch.nn.functional.mse_loss(model.b(x), y).item()
    train_protocol_model(model, x, y, batch_size=8, epochs=3,
                         distributed=False)
    assert model.seen_idx == {0, 1}
    assert torch.nn.functional.mse_loss(model.a(x), y).item() < la0 * 0.5
    assert torch.nn.functional.mse_loss(model.b(x), y).item() < lb0 * 0.5


def test_lightning_estimator_requires_store():
    import torch

    from horovod_tpu.spark.lightning import LightningEstimator

    est = LightningEstimator(model=torch.nn.Linear(2, 1), epochs=1)
    with pytest.raises(ValueError, match="store"):
        est.fit(df=None)


def test_store_create_dispatch(tmp_path):
    from horovod_tpu.spark.common.store import (
        DBFSLocalStore,
        FilesystemStore,
        Store,
    )

    assert isinstance(Store.create(str(tmp_path)), FilesystemStore)
    s = Store.create("dbfs:/ml/exp1")
    assert isinstance(s, DBFSLocalStore)
    assert s.prefix_path == "/dbfs/ml/exp1"
    # hdfs:// requires libhdfs, absent here -> clean gating error
    with pytest.raises(ImportError, match="HDFSStore|libhdfs"):
        Store.create("hdfs://namenode:9000/ml/exp1")


def test_dbfs_path_normalization():
    from horovod_tpu.spark.common.store import DBFSLocalStore

    norm = DBFSLocalStore.normalize_datasets_path
    assert norm("dbfs:/a/b") == "/dbfs/a/b"
    assert norm("/dbfs/a/b") == "/dbfs/a/b"
    assert norm("/plain/path") == "/plain/path"
