"""Spark/Ray integration units — the dependency-free planning pieces.

Reference analog: test/single/test_ray.py + test_spark.py run against
live local clusters; pyspark/ray aren't in this image, so we test every
pure component (store, params, rank planning, import gating) and skip
the cluster paths (the reference skips the same way when deps missing).
"""

import importlib.util

import pytest

HAS_SPARK = importlib.util.find_spec("pyspark") is not None
HAS_RAY = importlib.util.find_spec("ray") is not None


def test_filesystem_store_roundtrip(tmp_path):
    from horovod_tpu.spark.common.store import FilesystemStore, Store

    store = Store.create(str(tmp_path / "st"))
    assert isinstance(store, FilesystemStore)
    ckpt = store.get_checkpoint_path("run1")
    store.write(ckpt, b"weights")
    assert store.exists(ckpt)
    assert store.read(ckpt) == b"weights"
    assert store.get_train_data_path(3).endswith("intermediate_train_data.3")
    assert "run1" in store.get_logs_path("run1")


def test_store_sync_fn(tmp_path):
    from horovod_tpu.spark.common.store import FilesystemStore

    store = FilesystemStore(str(tmp_path / "st"))
    local = tmp_path / "local"
    local.mkdir()
    (local / "ckpt.bin").write_bytes(b"x")
    store.sync_fn("r1")(str(local))
    assert (tmp_path / "st" / "runs" / "r1" / "ckpt.bin").read_bytes() == b"x"


def test_estimator_params():
    from horovod_tpu.spark.common.params import EstimatorParams

    p = EstimatorParams(batch_size=64, epochs=3, label_cols=("y",))
    assert p.batch_size == 64
    assert p.getBatchSize() == 64          # pyspark.ml-style getter
    assert p.getEpochs() == 3
    with pytest.raises(TypeError, match="unknown"):
        EstimatorParams(bogus=1)


def test_ray_rank_planning():
    from horovod_tpu.ray.runner import plan_ranks

    envs = plan_ranks([(0, "a"), (1, "b"), (2, "a"), (3, "b")])
    # contiguous ranks per host: a -> ranks 0,1 ; b -> ranks 2,3
    assert envs[0]["HOROVOD_RANK"] == "0"
    assert envs[2]["HOROVOD_RANK"] == "1"
    assert envs[2]["HOROVOD_LOCAL_RANK"] == "1"
    assert envs[1]["HOROVOD_CROSS_RANK"] == "1"
    assert all(e["HOROVOD_SIZE"] == "4" for e in envs.values())
    assert all(e["HOROVOD_LOCAL_SIZE"] == "2" for e in envs.values())


def test_ray_strategy_bundles():
    from horovod_tpu.ray.strategy import PackStrategy, SpreadStrategy

    s = PackStrategy(4, cpus_per_worker=2, gpus_per_worker=1)
    assert s.placement_strategy == "PACK"
    assert s.bundles() == [{"CPU": 2, "GPU": 1}] * 4
    assert SpreadStrategy(2).placement_strategy == "SPREAD"


@pytest.mark.skipif(HAS_RAY, reason="ray installed")
def test_ray_executor_gating():
    from horovod_tpu.ray import RayExecutor

    ex = RayExecutor(num_workers=2)
    with pytest.raises(ImportError, match="ray"):
        ex.start()


@pytest.mark.skipif(HAS_SPARK, reason="pyspark installed")
def test_spark_run_gating():
    import horovod_tpu.spark as hs

    with pytest.raises(ImportError, match="pyspark"):
        hs.run(lambda: None, num_proc=2)
