"""Telemetry subsystem: core metrics snapshot, StepTimer accounting,
static byte prediction, and the cross-rank trace merge.

Pins the ISSUE-4 acceptance bars: (1) hvd.metrics() reconciles with the
``analysis/extract`` jaxpr-walker byte prediction within 1% on a dryrun
eager train step; (2) ``telemetry.report`` merges synthetic multi-rank
timelines into one Perfetto-loadable trace with a per-rank straggler
table that names the right straggler.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import telemetry
from horovod_tpu.telemetry import predict, report

# Part of the sub-5-minute CI lane (make test-quick).
pytestmark = pytest.mark.quick


@pytest.fixture()
def hvd_core(monkeypatch):
    for k in ("HOROVOD_RANK", "HOROVOD_SIZE", "HOROVOD_LOCAL_RANK",
              "HOROVOD_LOCAL_SIZE"):
        monkeypatch.delenv(k, raising=False)
    from horovod_tpu.common import basics

    b = basics.HorovodBasics()
    b.init()
    yield b
    b.shutdown()


# ---- snapshot shape & monotonicity ------------------------------------


def test_snapshot_before_init_is_valid():
    snap = telemetry.snapshot()
    assert isinstance(snap, dict)
    assert "ops" in snap and "cycle" in snap and "cache" in snap
    # json-roundtrippable (the C side builds the string by hand)
    json.loads(json.dumps(snap))


def test_fully_populated_snapshot_roundtrips_untruncated(hvd_core):
    """The Append buffer grows dynamically (it was a fixed 768-byte
    stack buffer grown by hand every time a section gained rows —
    truncation silently corrupted the JSON): a snapshot with EVERY
    section populated must parse and keep its final key."""
    from horovod_tpu.common import eager_ops as ops

    # Populate every op class the single-rank ring can execute.
    x = np.arange(64, dtype=np.float32)
    ops.allreduce_async(x, "full.ar").synchronize()
    ops.allgather_async(x, "full.ag").synchronize()
    ops.broadcast_async(x, 0, "full.bc").synchronize()
    snap = hvd_core.metrics_snapshot()
    # Every section present...
    for key in ("ops", "device_ops", "negotiation_us", "queue_us",
                "wire_us", "fusion", "cycle", "cache", "straggler",
                "wire", "elastic", "errors", "knobs"):
        assert key in snap, key
    # ...including the self-healing rows and the new knob columns.
    el = snap["elastic"]
    for key in ("heals", "retries", "crc_errors", "ranks_rejoined",
                "ranks_blacklisted", "detect_us"):
        assert key in el, key
    for key in ("wire_retry_attempts", "wire_retry_backoff_ms",
                "wire_crc", "wire_timeout_ms", "cross_plane"):
        assert key in snap["knobs"], key
    # Truncation would cut the TAIL: knobs is the last section, and the
    # raw JSON must end exactly where the parser says it does.
    raw_len = hvd_core.lib.hvdtpu_metrics_snapshot(None, 0)
    import ctypes

    buf = ctypes.create_string_buffer(int(raw_len) + 512)
    hvd_core.lib.hvdtpu_metrics_snapshot(buf, int(raw_len) + 512)
    raw = buf.value.decode()
    assert raw.endswith("}"), raw[-40:]
    assert json.loads(raw)["knobs"]["cross_plane"] in (
        "auto", "ici", "ring", "hier")


def test_counters_monotonic_and_exact_on_eager_path(hvd_core):
    """Counter monotonicity + exact byte accounting: every allreduce
    adds its payload to ops.allreduce.bytes and nothing ever goes
    backwards."""
    from horovod_tpu.common import eager_ops as ops

    telemetry.metrics_reset()
    prev = telemetry.snapshot()
    assert prev["ops"].get("allreduce", {}).get("bytes", 0) == 0
    total = 0
    for step in range(3):
        for i, n in enumerate((64, 256, 1024)):
            h = ops.allreduce_async(np.ones(n, np.float32),
                                    f"mono.{i}")
            h.synchronize()
            total += n * 4
        snap = telemetry.snapshot()
        ar = snap["ops"]["allreduce"]
        assert ar["bytes"] == total
        assert ar["tensors"] == (step + 1) * 3
        # monotonic across every counter family we diff in production
        assert ar["bytes"] >= prev["ops"].get(
            "allreduce", {}).get("bytes", 0)
        assert snap["cycle"]["count"] >= prev["cycle"]["count"]
        assert (snap["queue_us"]["count"]
                >= prev["queue_us"]["count"])
        prev = snap
    assert prev["queue_us"]["count"] == 9
    assert prev["wire_us"]["count"] > 0


def _mlp_loss(params, batch):
    h = jnp.tanh(batch["x"] @ params["w1"])
    return jnp.mean((h @ params["w2"] - batch["y"]) ** 2)


def _mlp_data():
    k = jax.random.PRNGKey(0)
    params = {"w1": jnp.ones((16, 32), jnp.float32),
              "w2": jnp.ones((32, 4), jnp.float32)}
    batch = {"x": jax.random.normal(k, (8, 16), jnp.float32),
             "y": jnp.zeros((8, 4), jnp.float32)}
    return params, batch


def test_eager_reconciliation_within_1pct(hvd_core):
    """ISSUE-4 acceptance: a dryrun eager train step's measured
    collective bytes (hvd.metrics() deltas) reconcile with the
    analysis/extract jaxpr-walker prediction within 1%."""
    from horovod_tpu.common import eager_ops as ops

    params, batch = _mlp_data()
    predicted = predict.eager_allreduce_bytes(_mlp_loss, params, batch)
    # The walker-based predictor and the walker-free eval_shape
    # cross-check must agree exactly (same grad tree).
    assert predicted == predict.grad_tree_bytes(_mlp_loss, params, batch)

    grads = jax.grad(_mlp_loss)(params, batch)
    before = telemetry.total_collective_bytes()
    steps = 3
    for step in range(steps):
        flat = jax.tree_util.tree_flatten_with_path(grads)[0]
        handles = [
            ops.allreduce_async(np.asarray(leaf), f"recon.{i}")
            for i, (_, leaf) in enumerate(flat)
        ]
        for h in handles:
            h.synchronize()
    measured = (telemetry.total_collective_bytes() - before) / steps
    assert predicted > 0
    assert abs(measured - predicted) / predicted < 0.01, (
        measured, predicted)


def test_spmd_predictor_uses_walker():
    """collective_bytes walks psums inside jit/scan like the linter
    does: loop-expanded volumes, no devices needed."""
    def fn(x):
        def body(c, _):
            return c + jax.lax.psum(x, "dp"), None
        out, _ = jax.lax.scan(body, x, None, length=4)
        return out

    x = jax.ShapeDtypeStruct((128,), jnp.float32)
    got = predict.collective_bytes(fn, x, axis_env=[("dp", 8)])
    assert got == 4 * 128 * 4  # 4 loop iterations x 128 f32


# ---- StepTimer ---------------------------------------------------------


def test_step_timer_mfu_known_flops():
    """MFU math on a known-FLOPs program: mfu = flops / (dt * peak)."""
    timer = telemetry.StepTimer(flops_per_step=2e9, peak_flops=1e12)
    timer.step_times = [0.5, 0.004, 0.004]  # first = compile, dropped
    assert timer.mean_step_s() == pytest.approx(0.004)
    assert timer.mfu() == pytest.approx(2e9 / 0.004 / 1e12)
    # 2 GFLOP in 4 ms on a 1 TFLOP/s part = 0.5 MFU
    assert timer.mfu() == pytest.approx(0.5)


def test_step_timer_flops_from_compiled_cost_analysis():
    """flops_per_step sourced from lowered.compile().cost_analysis()
    on a program whose FLOPs are known analytically: an (n,n)x(n,n)
    matmul is 2n^3."""
    n = 64
    fn = jax.jit(lambda a, b: a @ b)
    compiled = fn.lower(jnp.ones((n, n)), jnp.ones((n, n))).compile()
    timer = telemetry.StepTimer(peak_flops=1e12)
    flops = timer.add_flops_from_compiled(compiled)
    if flops is None:
        pytest.skip("backend reports no cost analysis flops")
    assert timer.flops_per_step == pytest.approx(2 * n ** 3, rel=0.2)


def test_step_timer_wraps_split_train_step():
    import optax

    from horovod_tpu.parallel.train_step import make_split_train_step

    params, batch = _mlp_data()
    timer = telemetry.StepTimer(peak_flops=1e12)
    ts = make_split_train_step(_mlp_loss, optax.adam(1e-2),
                               microbatches=2, telemetry=timer)
    carry = ts.init(params)
    for _ in range(3):
        loss, carry = ts.step(carry, batch)
    assert timer.steps == 3
    assert timer.mean_step_s() > 0
    # cost-analysis registration happened on the first call (CPU
    # reports flops); grad x2 microbatches + apply are all counted
    assert timer.flops_per_step is None or timer.flops_per_step > 0
    row = timer.summary()
    assert row["steps"] == 3


def test_step_timer_telemetry_does_not_change_jaxpr():
    """The instrumented step must trace to the SAME program as the
    plain one (what the analysis/programs.py registration lints)."""
    import optax

    from horovod_tpu.parallel.train_step import make_split_train_step

    params, batch = _mlp_data()
    plain = make_split_train_step(_mlp_loss, optax.adam(1e-2),
                                  microbatches=2)
    timer = telemetry.StepTimer(flops_per_step=1.0, block=False)
    inst = make_split_train_step(_mlp_loss, optax.adam(1e-2),
                                 microbatches=2, telemetry=timer)
    carry = jax.eval_shape(plain.init, params)
    j1 = jax.make_jaxpr(plain.step)(carry, batch)
    j2 = jax.make_jaxpr(inst.step)(carry, batch)
    assert str(j1) == str(j2)


# ---- bubble accounting -------------------------------------------------


def test_bubble_measured_vs_analytic():
    """Measured bubble math, and agreement with the schedule tables:
    synthetic timings with zero overhead land exactly on the analytic
    interleaved bubble."""
    from horovod_tpu.parallel.pipeline import build_interleaved_schedule

    S, V, M = 4, 2, 8
    sched = build_interleaved_schedule(S, V, M)
    t_sub = 0.010
    # A zero-overhead step takes n_slots subticks of wall time.
    step_time = sched.n_slots * t_sub
    rep = telemetry.bubble_report("interleaved_1f1b", S, M, V,
                                  step_time, t_sub)
    assert rep["measured_bubble"] == pytest.approx(
        sched.bubble_fraction, abs=1e-4)
    assert rep["excess"] == pytest.approx(0.0, abs=1e-4)
    # Overhead shows up as positive excess.
    rep2 = telemetry.bubble_report("interleaved_1f1b", S, M, V,
                                   step_time * 1.25, t_sub)
    assert rep2["excess"] > 0.15
    # Analytic forms match bench.py's pipeline_bubble rows.
    assert telemetry.analytic_bubble("gpipe", S, M) == pytest.approx(
        2 * (S - 1) / (2 * M + 2 * (S - 1)))
    assert telemetry.analytic_bubble("1f1b", S, M) == pytest.approx(
        2 * (S - 1) / (M + 2 * (S - 1)))


# ---- exporters ---------------------------------------------------------


def test_scraper_exporters(tmp_path, hvd_core):
    from horovod_tpu.common import eager_ops as ops

    h = ops.allreduce_async(np.ones(32, np.float32), "scrape.0")
    h.synchronize()
    jsonl = tmp_path / "flight.jsonl"
    prom = tmp_path / "metrics.prom"
    scraper = telemetry.MetricsScraper(interval_s=3600,
                                       jsonl_path=str(jsonl),
                                       prom_path=str(prom))
    scraper.scrape_once()
    scraper.scrape_once()
    rows = [json.loads(l) for l in jsonl.read_text().splitlines()]
    assert len(rows) == 2
    assert rows[-1]["ops"]["allreduce"]["tensors"] >= 1
    assert rows[-1]["ts"] >= rows[0]["ts"]
    text = prom.read_text()
    assert 'hvdtpu_op_bytes_total{op="allreduce",plane="host",rank="0"}' \
        in text
    assert "hvdtpu_cache_hit_rate" in text


def test_prom_flattening_covers_fully_populated_snapshot():
    """Audit of the Prometheus flattening against a snapshot with EVERY
    section populated: the r13/r14 additions (elastic heal/retry/CRC/
    rejoin counters, per-plane wire.cross_* bytes) must all surface as
    samples — a section silently dropped by the flattener is an
    alerting blind spot, which is how the elastic counters shipped two
    rounds without an exporter row."""
    from horovod_tpu.telemetry.exporters import _flatten_prom

    hist = {"count": 3, "sum_us": 30, "min_us": 5, "max_us": 20,
            "p50_us": 10, "p90_us": 20, "p99_us": 20}
    snap = {
        "initialized": True, "rank": 2, "size": 4,
        "ops": {"allreduce": {"responses": 5, "tensors": 7,
                              "bytes": 4096}},
        "device_ops": {"allgather": {"responses": 1, "tensors": 1,
                                     "bytes": 64}},
        "negotiation_us": hist, "queue_us": hist, "wire_us": hist,
        "fusion": {"fused_responses": 2, "fill_bytes": 100,
                   "capacity_bytes": 400, "fill_ratio": 0.25},
        "cycle": {"count": 9, "stalls": 1, "overrun_us": 12},
        "cache": {"hits": 3, "misses": 1, "entries": 2, "hit_bytes": 99,
                  "hit_rate": 0.75},
        "straggler": {"last_rank_counts": [0, 2, 0, 1],
                      "skew_us": hist},
        "wire": {"tx_bytes": 1000, "rx_bytes": 1000,
                 "tx_logical_bytes": 2000, "rx_logical_bytes": 2000,
                 "compression_ratio": 0.5,
                 "cross_tx_bytes": 250, "cross_rx_bytes": 250,
                 "cross_tx_logical_bytes": 500,
                 "cross_rx_logical_bytes": 500,
                 "cross_compression_ratio": 0.5,
                 "syscalls": {"tx_calls": 40, "rx_calls": 50,
                              "cross_tx_calls": 10,
                              "cross_rx_calls": 12,
                              "per_gb": 45000.0,
                              "channels": [
                                  {"channel": 0, "tx_calls": 30,
                                   "rx_calls": 38},
                                  {"channel": 1, "tx_calls": 10,
                                   "rx_calls": 12}]},
                 "overlap": {"steps": 7, "unattributed_us": 11,
                             "exposed_wire_ms": 5.0,
                             "hidden_wire_ms": 15.0,
                             "overlap_efficiency": 0.75,
                             "intra": {"exposed_us": 5000,
                                       "hidden_us": 15000,
                                       "total_us": 20000,
                                       "overlap_efficiency": 0.75,
                                       "last_exposed_us": 1,
                                       "last_hidden_us": 2,
                                       "last_total_us": 3},
                             "cross": {"exposed_us": 0, "hidden_us": 0,
                                       "total_us": 0,
                                       "overlap_efficiency": 0.0,
                                       "last_exposed_us": 0,
                                       "last_hidden_us": 0,
                                       "last_total_us": 0}}},
        "elastic": {"epoch": 3, "faults_detected": 2,
                    "faults_recovered": 1, "ranks_blacklisted": 1,
                    "ranks_rejoined": 1, "heals": 4, "retries": 6,
                    "crc_errors": 2, "detect_us": hist},
        "errors": 1,
        "knobs": {"fusion_threshold_bytes": 1024},
    }
    text = _flatten_prom(snap, snap["rank"])
    expected = [
        'hvdtpu_wire_cross_tx_bytes_total{rank="2"} 250',
        'hvdtpu_wire_cross_rx_bytes_total{rank="2"} 250',
        'hvdtpu_wire_cross_tx_logical_bytes_total{rank="2"} 500',
        'hvdtpu_wire_cross_rx_logical_bytes_total{rank="2"} 500',
        'hvdtpu_wire_cross_compression_ratio{rank="2"} 0.5',
        'hvdtpu_elastic_heals_total{rank="2"} 4',
        'hvdtpu_elastic_retries_total{rank="2"} 6',
        'hvdtpu_elastic_crc_errors_total{rank="2"} 2',
        'hvdtpu_elastic_ranks_rejoined_total{rank="2"} 1',
        'hvdtpu_elastic_faults_detected_total{rank="2"} 2',
        'hvdtpu_elastic_faults_recovered_total{rank="2"} 1',
        'hvdtpu_elastic_ranks_blacklisted_total{rank="2"} 1',
        'hvdtpu_elastic_epoch{rank="2"} 3',
        'hvdtpu_elastic_detect_p99_us{rank="2"} 20',
        'hvdtpu_wire_tx_bytes_total{rank="2"} 1000',
        'hvdtpu_straggler_last_total{rank="2",straggler="1"} 2',
        'hvdtpu_errors_total{rank="2"} 1',
        # r17 step-anatomy overlap ledger (docs/metrics.md).
        'hvdtpu_overlap_steps_total{rank="2"} 7',
        'hvdtpu_overlap_unattributed_us_total{rank="2"} 11',
        'hvdtpu_overlap_efficiency{rank="2"} 0.75',
        'hvdtpu_overlap_exposed_us_total{plane="intra",rank="2"} 5000',
        'hvdtpu_overlap_hidden_us_total{plane="intra",rank="2"} 15000',
        'hvdtpu_overlap_total_us_total{plane="intra",rank="2"} 20000',
        'hvdtpu_overlap_plane_efficiency{plane="intra",rank="2"} 0.75',
        'hvdtpu_overlap_plane_efficiency{plane="cross",rank="2"} 0.0',
        # r23 syscall accounting (docs/wire.md "Syscall budget"): the
        # io_uring baseline — calls per plane/channel + calls-per-GB.
        'hvdtpu_wire_syscalls_total{direction="tx",rank="2"} 40',
        'hvdtpu_wire_syscalls_total{direction="rx",rank="2"} 50',
        'hvdtpu_wire_cross_syscalls_total{direction="tx",rank="2"} 10',
        'hvdtpu_wire_cross_syscalls_total{direction="rx",rank="2"} 12',
        'hvdtpu_wire_syscalls_per_gb{rank="2"} 45000.0',
        'hvdtpu_wire_channel_syscalls_total{direction="tx",'
        'channel="1",rank="2"} 10',
        'hvdtpu_wire_channel_syscalls_total{direction="rx",'
        'channel="0",rank="2"} 38',
    ]
    for line in expected:
        assert line in text, f"missing exporter row: {line}"
    # Every line is well-formed text-format: "name{labels} value".
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        assert name and not name.endswith("{"), line
        float(value)


def test_step_timer_per_plane_wire_split(monkeypatch):
    """plane_wire_summary splits the transport deltas intra vs cross
    and reconciles per-plane compression independently (cross-hop-only
    bf16: cross ratio 0.5, intra 1.0, intra+cross == total)."""
    from horovod_tpu.telemetry import core as tcore

    snaps = []
    # Per step: total tx grows 1200 (logical 1600); the cross slice of
    # it grows 200 (logical 400) -> intra 1000/1200, cross 200/400.
    for i in range(6):
        snaps.append({
            "initialized": True, "rank": 0, "size": 2, "ops": {},
            "device_ops": {},
            "cache": {"hit_rate": 0.0}, "cycle": {"stalls": 0},
            "wire": {"tx_bytes": 1200 * i, "rx_bytes": 1200 * i,
                     "tx_logical_bytes": 1600 * i,
                     "rx_logical_bytes": 1600 * i,
                     "cross_tx_bytes": 200 * i,
                     "cross_rx_bytes": 200 * i,
                     "cross_tx_logical_bytes": 400 * i,
                     "cross_rx_logical_bytes": 400 * i},
        })
    it = iter(snaps + snaps[-1:] * 4)
    monkeypatch.setattr(tcore, "snapshot", lambda: next(it))
    timer = telemetry.StepTimer(block=False)
    for _ in range(3):
        timer.start_step()
        timer.end_step()
    planes = timer.plane_wire_summary(skip_first=False)
    assert planes["intra"]["tx_bytes_per_step"] == 1000
    assert planes["intra"]["compression_ratio"] == pytest.approx(1000 / 1200)
    assert planes["cross"]["tx_bytes_per_step"] == 200
    assert planes["cross"]["compression_ratio"] == pytest.approx(0.5)
    # intra + cross reconcile exactly with the total wire counters.
    total = timer.wire_bytes_per_step
    for (tx, _txl), p in zip(total, timer.plane_bytes_per_step):
        assert p[0] + p[2] == tx
    assert "plane_wire" in timer.summary()


def test_step_timer_overlap_summary(monkeypatch):
    """overlap_summary aggregates the core ledger's per-step last_*
    rows: per-plane exposed/hidden/total reconcile exactly and the
    combined efficiency is hidden/total across planes."""
    from horovod_tpu.telemetry import core as tcore

    snap = {
        "initialized": True, "rank": 0, "size": 2, "ops": {},
        "device_ops": {}, "cache": {"hit_rate": 0.0},
        "cycle": {"stalls": 0},
        "wire": {"tx_bytes": 0, "tx_logical_bytes": 0,
                 "cross_tx_bytes": 0, "cross_tx_logical_bytes": 0,
                 "overlap": {
                     "steps": 1,
                     "intra": {"last_exposed_us": 4000,
                               "last_hidden_us": 6000,
                               "last_total_us": 10000},
                     "cross": {"last_exposed_us": 1000,
                               "last_hidden_us": 1000,
                               "last_total_us": 2000},
                 }},
    }
    monkeypatch.setattr(tcore, "snapshot", lambda: snap)

    def fake_mark(begin=True, owner=None):
        # Mirror the real step_mark's owner bookkeeping: end_step
        # asserts the window is still the timer's before closing it.
        tcore._window_owner = owner if begin else None
        return 1

    monkeypatch.setattr(tcore, "step_mark", fake_mark)
    timer = telemetry.StepTimer(block=False)
    for _ in range(2):
        timer.start_step()
        timer.end_step()
    ov = timer.overlap_summary(skip_first=False)
    # mean_ prefix on purpose: the snapshot/healthz expose CUMULATIVE
    # exposed_wire_ms — per-step means must not share the key.
    assert ov["intra"]["mean_exposed_wire_ms"] == 4.0
    assert ov["intra"]["mean_hidden_wire_ms"] == 6.0
    assert ov["intra"]["mean_total_wire_ms"] == 10.0
    assert ov["intra"]["overlap_efficiency"] == pytest.approx(0.6)
    assert ov["cross"]["overlap_efficiency"] == pytest.approx(0.5)
    # Combined: hidden 7ms of total 12ms.
    assert ov["overlap_efficiency"] == pytest.approx(7 / 12)
    assert timer.summary()["overlap"] is not None


class _FakeBasics:
    """Just enough of HorovodBasics' step-window surface to replay the
    id-reuse collision python-side: ids restart after metrics_reset,
    exactly like the core registry."""

    def __init__(self):
        self.next_id = 0
        self.open = -1

    def step_mark(self, begin=True):
        if begin:
            self.open = self.next_id
            self.next_id += 1
            return self.open
        sid, self.open = self.open, -1
        return sid

    def step_id(self):
        return self.open

    def metrics_reset(self):
        self.next_id = 0
        self.open = -1


def test_step_window_single_owner_after_id_reuse(monkeypatch):
    """Regression: an explicit StepTimer scope and the fused
    optimizer's implicit boundary in the same iteration must keep ONE
    owner per window. Core step ids restart after metrics_reset(), so
    the optimizer's remembered boundary id can collide with a
    StepTimer-opened window — the id-only deference check then stole
    the window mid-step, splitting the step's overlap ledger across
    two half-windows."""
    from horovod_tpu.jax import optimizer as hvd_opt
    from horovod_tpu.telemetry import core as tcore

    monkeypatch.setattr(tcore, "_basics", _FakeBasics())
    monkeypatch.setattr(tcore, "_window_owner", None)
    monkeypatch.setattr(hvd_opt, "_last_boundary_id", None)

    # Implicit lane first: the optimizer marks a boundary (window 0)
    # and remembers its id.
    hvd_opt._mark_optimizer_step()
    assert tcore.step_id() == 0
    assert tcore.window_owner() == "optimizer"
    assert hvd_opt._last_boundary_id == 0

    # A registry reset (bench phase change, test isolation) restarts
    # the core's ids...
    tcore.metrics_reset()
    assert tcore.window_owner() is None

    # ...so the next explicit scope REUSES id 0.
    timer = telemetry.StepTimer(block=False)
    timer.start_step()
    assert tcore.step_id() == 0  # collides with the remembered id

    # The optimizer's implicit boundary inside the timed iteration must
    # defer to the explicit scope despite the id collision.
    hvd_opt._mark_optimizer_step()
    assert tcore.step_id() == 0
    assert tcore.window_owner() == "StepTimer"

    # The timer closes its own window cleanly.
    timer.end_step()
    assert tcore.step_id() == -1
    assert tcore.window_owner() is None

    # Implicit lane still drives the marks when no explicit scope is
    # active.
    hvd_opt._mark_optimizer_step()
    assert tcore.window_owner() == "optimizer"


def test_step_timer_refuses_stolen_window(monkeypatch):
    """A window re-opened by another driver mid-step fails loudly at
    end_step instead of booking a fragmented half-window."""
    from horovod_tpu.telemetry import core as tcore

    monkeypatch.setattr(tcore, "_basics", _FakeBasics())
    monkeypatch.setattr(tcore, "_window_owner", None)

    timer = telemetry.StepTimer(block=False)
    timer.start_step()
    # Rogue second driver closes and re-opens the window mid-step.
    tcore.step_mark(False)
    tcore.step_mark(True, owner="optimizer")
    with pytest.raises(RuntimeError, match="owned by 'optimizer'"):
        timer.end_step()
    # The timer reset its scope: the next start/end pair is usable.
    tcore.step_mark(False)
    timer.start_step()
    timer.end_step()


# ---- cross-rank trace merge -------------------------------------------


def _synthetic_timeline(rank, clock_offset_us, straggle_us=0,
                        tensors=("g0", "g1"), steps=3):
    """A rank's Chrome-trace timeline with its own clock origin.

    True (wall) submit time of tensor t at step s is
    ``1000*s + 10*idx (+ straggle_us)``; each rank's recorded ts are
    shifted by its clock offset, which CLOCK_SYNC exposes."""
    events = [
        {"name": "process_name", "ph": "M", "pid": rank,
         "args": {"name": f"rank {rank}"}},
        {"name": "CLOCK_SYNC", "ph": "i", "ts": 0, "pid": rank,
         "tid": 0, "s": "p",
         "args": {"unix_us": 1_700_000_000_000_000 + clock_offset_us,
                  "rank": rank}},
    ]
    for s in range(steps):
        for i, t in enumerate(tensors):
            true_b = 1000 * s + 10 * i + straggle_us
            # The coordinator's response broadcast lands on every rank
            # at (near) the same wall instant — after the straggler —
            # which is exactly what the fallback alignment leans on.
            true_e = 1000 * s + 10 * i + 800
            for ph, ts in (("B", true_b), ("E", true_e)):
                events.append({"name": "NEGOTIATE", "ph": ph,
                               "ts": ts - clock_offset_us, "pid": rank,
                               "tid": i, "args": {"tensor": t}})
    return events


def _write_traces(tmp_path, with_sync=True):
    """4 ranks, distinct clock origins, rank 2 always 300 us late."""
    paths = []
    for rank in range(4):
        ev = _synthetic_timeline(
            rank, clock_offset_us=rank * 50_000,
            straggle_us=300 if rank == 2 else 0)
        if not with_sync:
            ev = [e for e in ev if e["name"] != "CLOCK_SYNC"]
        p = tmp_path / f"tl.{rank}.json"
        p.write_text(json.dumps(ev))
        paths.append(str(p))
    return paths


def test_straggler_merge_4_ranks(tmp_path):
    """ISSUE-4 acceptance: one Perfetto-loadable merged trace + a
    per-rank straggler table that blames the planted straggler."""
    paths = _write_traces(tmp_path)
    merged, skew = report.merge(paths)

    # Single valid Chrome-trace array: list of dicts, every event has
    # the fields Perfetto needs, ts sorted.
    assert isinstance(merged, list) and merged
    ts = [e["ts"] for e in merged if "ts" in e]
    assert ts == sorted(ts)
    assert {e["pid"] for e in merged} == {0, 1, 2, 3}
    json.loads(json.dumps(merged))

    # Straggler table: rank 2 arrived last on every matched collective,
    # with ~300us skew; others near zero.
    assert set(skew["per_rank"]) == {0, 1, 2, 3}
    assert skew["matched_events"] == 6  # 2 tensors x 3 steps
    assert skew["per_rank"][2]["last_count"] == 6
    assert skew["per_rank"][2]["mean_skew_us"] == pytest.approx(300, abs=5)
    for r in (0, 1, 3):
        assert skew["per_rank"][r]["last_count"] == 0
        assert skew["per_rank"][r]["mean_skew_us"] < 5
    assert skew["worst_tensors"][0]["last_rank"] == 2


def test_straggler_merge_negotiate_fallback(tmp_path):
    """Without CLOCK_SYNC (older traces), the NEGOTIATE-end median
    alignment recovers the offsets and still blames rank 2."""
    paths = _write_traces(tmp_path, with_sync=False)
    merged, skew = report.merge(paths)
    assert skew["per_rank"][2]["last_count"] == 6
    assert skew["per_rank"][2]["mean_skew_us"] == pytest.approx(300, abs=5)


def test_report_cli(tmp_path, capsys):
    paths = _write_traces(tmp_path)
    out = tmp_path / "merged.json"
    skew_out = tmp_path / "skew.json"
    rc = report.main([*paths, "-o", str(out),
                      "--skew-json", str(skew_out)])
    assert rc == 0
    merged = json.loads(out.read_text())
    assert len(merged) > 0
    skew = json.loads(skew_out.read_text())
    assert skew["per_rank"]["2"]["last_count"] == 6
    captured = capsys.readouterr().out
    assert "rank" in captured and "merged.json" in captured


def test_report_fault_events_in_straggler_table(tmp_path, capsys):
    """Per-rank metrics snapshots fold elastic fault events (epoch,
    fault counts, detection latency) into the straggler table — the
    report names churny hosts, not just slow ones (docs/elastic.md)."""
    paths = _write_traces(tmp_path)
    snap_paths = []
    for rank in range(4):
        snap = {"rank": rank,
                "elastic": {"epoch": 1, "faults_detected": 1,
                            "faults_recovered": 1,
                            "ranks_blacklisted": 1,
                            "detect_us": {"count": 1, "p50_us": 2048}}}
        if rank == 2:  # the flaky rank keeps re-detecting faults
            snap["elastic"]["faults_detected"] = 3
        p = tmp_path / f"snap.{rank}.json"
        p.write_text(json.dumps(snap))
        snap_paths.append(str(p))

    _, skew = report.merge(paths)
    report.attach_fault_events(skew, snap_paths)
    assert skew["fault_events"][2]["faults_detected"] == 3
    assert skew["per_rank"][2]["faults_detected"] == 3
    assert skew["per_rank"][2]["epoch"] == 1
    text = report.format_skew_table(skew)
    assert "faults" in text and "epoch" in text and "2048" in text

    # CLI wiring: --snapshots lands fault_events in the skew JSON.
    out = tmp_path / "merged.json"
    skew_out = tmp_path / "skew.json"
    rc = report.main([*paths, "-o", str(out), "--skew-json",
                      str(skew_out), "--snapshots", *snap_paths])
    assert rc == 0
    skew_json = json.loads(skew_out.read_text())
    assert skew_json["fault_events"]["2"]["faults_detected"] == 3
    assert "faults" in capsys.readouterr().out


def test_real_timeline_has_clock_sync(tmp_path, hvd_core):
    """The core's runtime timeline carries the CLOCK_SYNC anchor and
    stays valid JSON (the merge's preferred alignment path)."""
    from horovod_tpu.common import eager_ops as ops

    path = tmp_path / "tl.json"
    hvd_core.start_timeline(str(path))
    h = ops.allreduce_async(np.ones(8, np.float32), "tl.x")
    h.synchronize()
    hvd_core.stop_timeline()
    events = json.loads(path.read_text())
    sync = [e for e in events if e and e.get("name") == "CLOCK_SYNC"]
    assert len(sync) == 1
    assert sync[0]["args"]["unix_us"] > 1_000_000_000_000_000
    rank, loaded = report.load_timeline(str(path))
    assert rank == 0
    assert any(e.get("name") == "NEGOTIATE" for e in loaded)
