"""Streaming post-mortem merge (docs/scale.md): verdict parity with
the eager merge on small fleets, bounded-timeline semantics, and the
hundreds-of-dumps lane completing in seconds — the fleet-scale half of
the r15 forensics."""

import json
import time

import pytest

from horovod_tpu.simworld import write_sim_dumps
from horovod_tpu.telemetry.postmortem import (
    format_post_mortem,
    merge_post_mortem,
    merge_post_mortem_streaming,
)

pytestmark = pytest.mark.quick


def test_streaming_verdicts_match_eager_merge(tmp_path):
    write_sim_dumps(str(tmp_path), 8, 5, events_per_rank=64)
    eager = merge_post_mortem(str(tmp_path))
    stream = merge_post_mortem_streaming(str(tmp_path))
    for key in ("ranks", "root_cause_ranks", "secondary_suspects",
                "first_stalled_rank"):
        assert eager[key] == stream[key], (key, eager[key], stream[key])
    # Per-rank accounting matches too (minus the timeline cap).
    assert set(eager["per_rank"]) == set(stream["per_rank"])
    for rank, d in eager["per_rank"].items():
        assert stream["per_rank"][rank]["events"] == d["events"]
    assert stream["timeline_total"] == len(eager["timeline"])


def test_streaming_timeline_is_tail_bounded_and_ordered(tmp_path):
    write_sim_dumps(str(tmp_path), 6, 2, events_per_rank=128)
    out = merge_post_mortem_streaming(str(tmp_path), tail=50)
    assert len(out["timeline"]) == 50
    assert out["timeline_total"] == 5 * 128
    walls = [e["wall_us"] for e in out["timeline"]]
    assert walls == sorted(walls)
    # The tail is the NEWEST window of the merged axis.
    full = merge_post_mortem(str(tmp_path))
    assert walls[-1] == full["timeline"][-1]["wall_us"]
    # format renders the bounded analysis and reports the true total.
    text = format_post_mortem(out, tail=5)
    assert f"of {out['timeline_total']} events" in text


def test_streaming_reads_last_dump_of_multi_fault_files(tmp_path):
    # A process that faulted twice APPENDS a second dump to its file;
    # dump_index=-1 must pick the last without materializing the first.
    epoch0 = tmp_path / "epoch0"
    epoch1 = tmp_path / "epoch1"
    merged = tmp_path / "merged"
    write_sim_dumps(str(epoch0), 4, 3, events_per_rank=16, epoch=0)
    write_sim_dumps(str(epoch1), 4, 1, events_per_rank=16, epoch=1)
    merged.mkdir()
    for path in epoch1.iterdir():  # fleet of the SECOND fault
        older = epoch0 / path.name
        prefix = older.read_text() if older.exists() else ""
        (merged / path.name).write_text(prefix + path.read_text())
    out = merge_post_mortem_streaming(str(merged))
    assert all(d["epoch"] == 1 for d in out["per_rank"].values()), \
        out["per_rank"]
    assert out["root_cause_ranks"] == [1], out["root_cause_ranks"]


def test_256_dump_merge_completes_in_seconds(tmp_path):
    """The acceptance lane: a 256-rank fleet's post-mortem merges in
    seconds, not minutes. 512 events per dump keeps CI fast while
    still exercising the k-way path at full width; the wall bound has
    ~10x slack over a laptop run."""
    write_sim_dumps(str(tmp_path), 256, 97, events_per_rank=512)
    t0 = time.monotonic()
    out = merge_post_mortem_streaming(str(tmp_path))
    wall = time.monotonic() - t0
    assert out["root_cause_ranks"] == [97]
    assert out["timeline_total"] == 255 * 512
    assert wall < 30.0, f"streaming merge took {wall:.1f}s"


def test_report_cli_auto_selects_streaming(tmp_path, capsys):
    from horovod_tpu.telemetry.report import main as report_main

    write_sim_dumps(str(tmp_path), 24, 7, events_per_rank=32)
    out_json = tmp_path / "analysis.json"
    rc = report_main(["--post-mortem", str(tmp_path),
                      "-o", str(out_json)])
    assert rc == 0
    text = capsys.readouterr().out
    assert "root cause: rank(s) [7]" in text, text
    analysis = json.loads(out_json.read_text())
    # > _STREAM_THRESHOLD dumps -> the streaming merge (tail-bounded
    # timeline with the total alongside).
    assert "timeline_total" in analysis, sorted(analysis)
