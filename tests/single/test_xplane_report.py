"""Device-op attribution from xplane traces (the TPU-native half of the
timeline subsystem: the runtime timeline shows negotiation phases, this
shows where device time inside XLA programs goes)."""

import pytest

tf = pytest.importorskip("tensorflow")

from tensorflow.tsl.profiler.protobuf import xplane_pb2  # noqa: E402

from horovod_tpu.utils import device_op_report, format_report  # noqa: E402


def _make_trace(tmp_path):
    """Synthetic XSpace with one TPU plane: an XLA Ops line carrying a
    matmul fusion, a pallas custom-call, and a copy."""
    xs = xplane_pb2.XSpace()
    plane = xs.planes.add()
    plane.name = "/device:TPU:0"
    for mid, name in ((1, "%dot_fusion.1"), (2, "%custom-call.flash"),
                      (3, "%copy.7"), (4, "%while.1")):
        plane.event_metadata[mid].id = mid
        plane.event_metadata[mid].name = name
    line = plane.lines.add()
    line.name = "XLA Ops"
    for mid, dur_ms, n in ((1, 30.0, 3), (2, 50.0, 2), (3, 15.0, 5),
                           (4, 80.0, 1)):
        for _ in range(n):
            ev = line.events.add()
            ev.metadata_id = mid
            ev.duration_ps = int(dur_ms / n * 1e9)
    # a line the report must ignore
    other = plane.lines.add()
    other.name = "Steps"
    ev = other.events.add()
    ev.metadata_id = 1
    ev.duration_ps = int(1e12)
    # a non-device plane the filter must skip
    host = xs.planes.add()
    host.name = "/host:CPU"
    d = tmp_path / "plugins" / "profile" / "run1"
    d.mkdir(parents=True)
    (d / "vm.xplane.pb").write_bytes(xs.SerializeToString())
    return str(tmp_path)


def test_device_op_report_buckets_and_top_ops(tmp_path):
    report = device_op_report(_make_trace(tmp_path))
    assert list(report) == ["/device:TPU:0"]
    entry = report["/device:TPU:0"]
    b = entry["buckets"]
    assert b["matmul/conv fusion"] == pytest.approx(0.030)
    assert b["custom-call (pallas/host)"] == pytest.approx(0.050)
    assert b["copy"] == pytest.approx(0.015)
    assert b["control flow"] == pytest.approx(0.080)
    assert entry["total_s"] == pytest.approx(0.175)
    # top op is the while, then the custom-call
    assert entry["top_ops"][0][0] == "%while.1"
    assert entry["top_ops"][1] == ("%custom-call.flash",
                                   pytest.approx(0.050), 2)
    text = format_report(report, top=3)
    assert "custom-call (pallas/host)" in text and "%while.1" in text


def test_missing_trace_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        device_op_report(str(tmp_path))
