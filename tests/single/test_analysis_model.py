"""hvdcheck (horovod_tpu/analysis/model): protocol model checking +
ABI drift guards + chaos-spec grammar.

Mirrors the hvdlint seeded-bug discipline one level up the stack: the
REAL protocol models must verify clean (every interleaving, with fault
injection, inside the bounded configs), and every seeded mutant — each
re-introducing a bug a previous round actually shipped and fixed —
must be CAUGHT with a concrete counterexample interleaving that
replays. The ABI guards scrape csrc and pin the Python twins
bit-for-bit; the round-trip tests prove the guards are load-bearing by
mutating the scraped tables and requiring a failure. Everything here
is jax-free and runs in well under a second.
"""

import ctypes

import pytest

from horovod_tpu.analysis import chaos
from horovod_tpu.analysis import model as hvdcheck
from horovod_tpu.analysis.model import abi

pytestmark = pytest.mark.quick


# ---- real protocol models: every interleaving verifies ---------------

@pytest.mark.parametrize(
    "m", hvdcheck.real_models(), ids=lambda m: m.name)
def test_real_model_verifies(m):
    res = hvdcheck.check(m)
    assert res.ok, res.violation.format()
    assert res.states > 1  # the model actually explored something


# ---- seeded mutants: each historical bug must be caught --------------

@pytest.mark.parametrize("name", list(hvdcheck.MUTANTS))
def test_seeded_mutant_is_caught_with_replayable_trace(name):
    factory, history = hvdcheck.MUTANTS[name]
    m = factory()
    res = hvdcheck.check(m)
    assert not res.ok, f"{name} ({history}) escaped the checker"
    v = res.violation
    assert v.trace, "counterexample must be a concrete interleaving"
    # The trace is not an artifact of search bookkeeping: re-executing
    # its labels from the initial state must reach the same violation.
    hvdcheck.replay(m, v.trace)
    assert v.kind in ("invariant", "deadlock", "livelock")
    assert hvdcheck.format_trace(v.trace)  # printable


def test_mutant_suite_covers_all_three_protocol_families():
    fams = {n.split(".")[0] for n in hvdcheck.MUTANTS}
    assert fams == {"elastic", "wire", "serving"}


# ---- ABI drift guards ------------------------------------------------

def test_abi_twins_match_csrc():
    assert abi.check_abi() == []


def test_abi_guard_catches_event_enum_drift():
    t = abi.scrape_all()
    t["event_types"] = t["event_types"][:-1] + ["RogueEvent"]
    errs = abi.verify(t)
    assert errs and any("event" in e.lower() for e in errs)


def test_abi_guard_catches_event_enum_reorder():
    t = abi.scrape_all()
    a, b, *rest = t["event_types"]
    t["event_types"] = [b, a] + rest
    assert abi.verify(t)


def test_abi_guard_catches_request_phase_drift():
    t = abi.scrape_all()
    t["request_phase_names"] = t["request_phase_names"][:-1] + ["zzz"]
    errs = abi.verify(t)
    assert errs and any("phase" in e.lower() for e in errs)


def test_abi_guard_catches_response_knob_field_drift():
    # Dropping a serialized KNOB field (the r19 wire_channels bug class:
    # knob added to the enum but not to the ResponseList wire format).
    t = abi.scrape_all()
    assert "wire_channels" in t["response_serial_order"]
    t["response_serial_order"] = [
        f for f in t["response_serial_order"] if f != "wire_channels"]
    assert abi.verify(t)

    t2 = abi.scrape_all()
    t2["response_fields"] = [
        f for f in t2["response_fields"] if f != "wire_channels"]
    assert abi.verify(t2)


def test_abi_guard_catches_chaos_constant_drift():
    t = abi.scrape_all()
    t["flip_skip_shift"] = t["flip_skip_shift"] + 1
    assert abi.verify(t)

    t2 = abi.scrape_all()
    t2["fault_actions"] = t2["fault_actions"][::-1]
    assert abi.verify(t2)


def test_abi_guard_rejects_reserved_arg_in_event_specs():
    # "rank" is stamped onto every event by the emitter; a spec
    # declaring it as a payload arg would collide in the trace schema.
    t = abi.scrape_all()
    spec = list(t["event_specs"][0])
    spec[1] = abi.RESERVED_ARG
    t["event_specs"] = [tuple(spec)] + list(t["event_specs"][1:])
    errs = abi.verify(t)
    assert errs and any("rank" in e for e in errs)


# ---- chaos-spec grammar: validate_chaos_spec mirrors ParseFaultSpec --

_VALID = (
    "0:3", "1:5:kill", "0:2:stop:40", "1:0:reset", "1:0:reset:3",
    "0:1:flip:17", "0:1:flip:-9", "0:1:flip:5:2", "0:1:flip:5:2:3",
    "0:4:delay:25", " 0: 3",  # strtoll skips leading whitespace
)

_INVALID = (
    "", "0", "x:0", "-1:0", "0:-1", "0:0:nope", "0:0:kill:1",
    "0:0:stop", "0:0:stop:0", "0:0:delay:0", "0:0:reset:8",
    "0:0:reset:-1", "0:0:flip", "0:0:flip:1048576", "0:0:flip:-1:2",
    "0:0:flip:1:-1", "0:0:flip:1:16777216", "0:0:flip:1:2:8",
    "0:0:flip:1:2:-1", "0:0:stop:5:1", "0:0:kill:1:2:3",
    "0:0:flip:1:2:3:4",  # 7 parts
    "0x1:3",  # strtoll base-10 only, full consume
    "9223372036854775808:3",  # int64 overflow: C clamps, we reject
)


@pytest.mark.parametrize("spec", _VALID)
def test_chaos_spec_valid(spec):
    fs = chaos.validate_chaos_spec(spec)
    assert fs.rank >= 0 and fs.op >= 0
    assert fs.action in chaos.ACTIONS


@pytest.mark.parametrize("spec", _INVALID)
def test_chaos_spec_invalid(spec):
    with pytest.raises(chaos.ChaosSpecError):
        chaos.validate_chaos_spec(spec)


def test_chaos_flip_packing_matches_csrc_layout():
    fs = chaos.validate_chaos_spec("0:1:flip:5:2:3")
    assert fs.param == 5 | (2 << chaos.FLIP_SKIP_SHIFT) \
        | ((3 + 1) << chaos.FLIP_CHAN_SHIFT)
    assert fs.flip_bit == 5
    assert fs.flip_skip == 2
    assert fs.flip_channel == 3
    # No channel part -> all-channels sentinel.
    assert chaos.validate_chaos_spec("0:1:flip:5:2").flip_channel is None
    # Negative bit = persistent flip; only legal in the 4-part form.
    assert chaos.validate_chaos_spec("0:1:flip:-9").param == -9


def test_chaos_spec_differential_against_c_parser():
    """The Python validator must agree with ParseFaultSpec in
    operations.cc decision-for-decision: accept <=> rc in (0, -1)
    (parsed; -1 means not initialized), reject <=> rc == -2. The one
    documented divergence — int64 overflow, which C's strtoll clamps
    and we reject — is excluded from the corpus above."""
    try:
        from horovod_tpu.common import basics
        lib = basics.HorovodBasics().lib
    except (OSError, ImportError) as e:  # no built lib on this box
        pytest.skip(f"libhvdtpu_core unavailable: {e}")
    for spec in _VALID:
        rc = lib.hvdtpu_set_fault_inject_spec(spec.encode())
        assert rc in (0, -1), (spec, rc)
    for spec in _INVALID:
        if "9223372036854775808" in spec:
            continue  # documented divergence (C clamps to LLONG_MAX)
        rc = lib.hvdtpu_set_fault_inject_spec(spec.encode())
        assert rc == -2, (spec, rc)
    lib.hvdtpu_set_fault_inject_spec(ctypes.c_char_p(b""))  # disarm


# ---- CLI -------------------------------------------------------------

def test_cli_all_and_exit_codes(capsys):
    from horovod_tpu.analysis.model.__main__ import main

    assert main(["--abi"]) == 0
    assert main(["--model", "wire"]) == 0
    out = capsys.readouterr().out
    assert "OK" in out

    assert main(["--mutants"]) == 0
    out = capsys.readouterr().out
    for name in hvdcheck.MUTANTS:
        assert name in out
    assert "#1" in out  # counterexamples are printed

    assert main(["--chaos-spec", "0:1:flip:5:2"]) == 0
    assert main(["--chaos-spec", "0:0:stop:0"]) == 1
    capsys.readouterr()
