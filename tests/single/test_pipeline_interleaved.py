"""Interleaved virtual-stage 1F1B (parallel.pipeline) vs 1F1B / GPipe /
the unsharded reference.

Two layers of pinning:

- schedule-table tests run the host-side list scheduler alone
  (build_interleaved_schedule) — slot counts, bubble fractions, the
  >=1.5x V=1 -> V=2 bubble shrink the round-6 acceptance bar names,
  ragged ``M % (S*V)`` remainders;
- gradient-equivalence tests run the full llama path. On jax >= 0.6
  they exercise the real partial-manual ``jax.shard_map``; on older
  boxes ``pipeline._pipe_spmd`` transparently substitutes the
  vmap(axis_name=...) emulation, so these pins run everywhere.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import parallel
from horovod_tpu.models import (
    LlamaConfig,
    llama_init,
    llama_loss,
    llama_partition_rules,
)
from horovod_tpu.parallel import pipeline
from horovod_tpu.parallel.pipeline import build_interleaved_schedule
from horovod_tpu.parallel.sharding import apply_sharding, named_sharding

pytestmark = pytest.mark.quick  # make test-quick runs the pipeline lane


def _skip_unless_8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")


# ---- schedule tables (host-side, no devices needed) ------------------

def test_v1_reduces_to_true_1f1b():
    """V=1 single-subtick slots: U = 2M + 2(S-1) — already below the
    lockstep one_f_one_b's effective 2*(M + 2(S-1)) subticks."""
    for S, M in [(2, 4), (4, 8), (4, 16), (8, 16)]:
        s = build_interleaved_schedule(S, 1, M)
        assert s.n_slots == 2 * M + 2 * (S - 1), (S, M, s.n_slots)


def test_bubble_hits_ideal_when_S_divides_M():
    for S, V, M in [(2, 2, 4), (4, 2, 8), (4, 4, 8), (4, 2, 16),
                    (8, 2, 16), (2, 4, 8)]:
        s = build_interleaved_schedule(S, V, M)
        assert s.n_slots == 2 * M * V + 2 * (S - 1), (S, V, M, s.n_slots)


def test_acceptance_bubble_shrink_v1_to_v2():
    """The round-6 bar: at S=4, M=8 the bubble fraction must shrink by
    >= 1.5x going V=1 -> V=2 (it shrinks 1.73x: 6/22 -> 6/38)."""
    b1 = build_interleaved_schedule(4, 1, 8).bubble_fraction
    b2 = build_interleaved_schedule(4, 2, 8).bubble_fraction
    assert b1 / b2 >= 1.5, (b1, b2)
    b4 = build_interleaved_schedule(4, 4, 8).bubble_fraction
    assert b2 > b4, (b2, b4)


def test_ragged_remainder_schedules_complete():
    """M % (S*V) != 0 (and M < S*V): the list scheduler must still
    place every subtick — build asserts dependency-safety internally —
    with only a graceful slot-count degradation."""
    for S, V, M in [(2, 2, 3), (4, 2, 9), (2, 4, 2), (3, 2, 5)]:
        s = build_interleaved_schedule(S, V, M)
        assert (s.kind != 2).sum() == 2 * S * M * V  # all work placed
        assert s.n_slots <= 2 * M * V + 2 * (S - 1) + S * V


def test_schedule_tables_are_consistent():
    """Every forward's output is delivered exactly once (except the
    last global stage's, consumed locally by the loss head), one ring
    hop after production."""
    S, V, M = 4, 2, 8
    s = build_interleaved_schedule(S, V, M)
    n_fwd = int(((s.kind == 0) | (s.kind == 3)).sum())
    assert n_fwd == S * M * V
    # the loss head runs exactly once per microbatch, on the last device
    assert int((s.kind == 3).sum()) == M
    assert ((s.kind[:, :-1] != 3).all())
    # each non-terminal forward feeds one rf_valid entry next slot
    assert int(s.rf_valid.sum()) == (S * V - 1) * M
    assert int(s.rb_valid.sum()) == (S * V - 1) * M


# ---- gradient equivalence through the llama path ---------------------

def _setup(cfg, batch_shape=(4, 16), seed=1, with_mask=False):
    params = llama_init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(seed), batch_shape, 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
    if with_mask:
        batch["mask"] = jnp.ones(batch_shape).at[1, 10:].set(0)
    return params, batch


def _pipe_loss_and_grads(cfg, params, batch, mesh):
    p_sh = apply_sharding(
        params, parallel.shard_params(params, mesh,
                                      llama_partition_rules(pipeline=True)))
    b_sh = jax.device_put(
        batch, named_sharding(mesh, ("data", "fsdp"), "seq"))
    return jax.jit(jax.value_and_grad(
        lambda p: llama_loss(p, b_sh, cfg, mesh)))(p_sh)


def _assert_tree_close(ref, got, err=""):
    # atol 5e-6: the schedules sum per-microbatch grads in different
    # orders (f32 throughout), so near-zero leaves wobble at float eps.
    for (ka, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(ref),
            jax.tree_util.tree_leaves_with_path(got)):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=2e-4, atol=5e-6,
            err_msg=f"{err}{jax.tree_util.keystr(ka)}")


@pytest.mark.parametrize("with_mask", [False, True])
def test_interleaved_matches_1f1b_gpipe_and_reference(with_mask):
    """S=2, V=2, M=4: the four-way pin the issue asks for."""
    _skip_unless_8()
    cfg_g = LlamaConfig.tiny(dtype="float32", n_layers=4, remat=False,
                             pipeline_microbatches=4)
    cfg_1 = dataclasses.replace(cfg_g, pipeline_schedule="1f1b")
    cfg_i = dataclasses.replace(cfg_g,
                                pipeline_schedule="interleaved_1f1b",
                                pipeline_virtual_stages=2)
    params, batch = _setup(cfg_g, with_mask=with_mask)

    ref_loss, ref_grads = jax.jit(jax.value_and_grad(
        lambda p: llama_loss(p, batch, cfg_g)))(params)

    mesh = parallel.create_mesh(pipe=2, fsdp=2, tensor=2,
                                devices=jax.devices()[:8])
    gp_loss, gp_grads = _pipe_loss_and_grads(cfg_g, params, batch, mesh)
    ob_loss, ob_grads = _pipe_loss_and_grads(cfg_1, params, batch, mesh)
    il_loss, il_grads = _pipe_loss_and_grads(cfg_i, params, batch, mesh)

    for got in (gp_loss, ob_loss, il_loss):
        np.testing.assert_allclose(float(got), float(ref_loss),
                                   rtol=1e-5)
    _assert_tree_close(ref_grads, il_grads, "interleaved vs reference: ")
    _assert_tree_close(gp_grads, il_grads, "interleaved vs gpipe: ")
    _assert_tree_close(ob_grads, il_grads, "interleaved vs 1f1b: ")


def test_interleaved_moe_aux_matches_gpipe():
    """MoE through the interleaved schedule: the constant-cotangent aux
    folding must reproduce gpipe's loss + w*mean(aux) — router grads
    are the sensitive part."""
    _skip_unless_8()
    cfg_g = LlamaConfig.tiny_moe(dtype="float32", n_layers=4,
                                 remat=False, moe_impl="gshard")
    cfg_i = dataclasses.replace(cfg_g,
                                pipeline_schedule="interleaved_1f1b",
                                pipeline_virtual_stages=2)
    params, batch = _setup(cfg_g)
    mesh = parallel.create_mesh(pipe=2, expert=2, tensor=2,
                                devices=jax.devices()[:8])
    gp_loss, gp_grads = _pipe_loss_and_grads(cfg_g, params, batch, mesh)
    il_loss, il_grads = _pipe_loss_and_grads(cfg_i, params, batch, mesh)
    np.testing.assert_allclose(float(il_loss), float(gp_loss), rtol=1e-5)
    _assert_tree_close(gp_grads, il_grads)


def test_interleaved_ragged_microbatch_remainder():
    """M=6 with S*V=4 (remainder 2): the ragged schedule must stay
    gradient-exact, not just complete."""
    _skip_unless_8()
    cfg_g = LlamaConfig.tiny(dtype="float32", n_layers=4, remat=False,
                             pipeline_microbatches=6)
    cfg_i = dataclasses.replace(cfg_g,
                                pipeline_schedule="interleaved_1f1b",
                                pipeline_virtual_stages=2)
    params, batch = _setup(cfg_g, batch_shape=(6, 16))
    ref_loss, ref_grads = jax.jit(jax.value_and_grad(
        lambda p: llama_loss(p, batch, cfg_g)))(params)
    mesh = parallel.create_mesh(pipe=2, fsdp=2, tensor=2,
                                devices=jax.devices()[:8])
    il_loss, il_grads = _pipe_loss_and_grads(cfg_i, params, batch, mesh)
    np.testing.assert_allclose(float(il_loss), float(ref_loss), rtol=1e-5)
    _assert_tree_close(ref_grads, il_grads)


def test_interleaved_bf16_compiles_on_cpu():
    """bf16 activations through the interleaved schedule must not hit
    XLA CPU's AllReducePromotion crash (the shared f32-psum guards)."""
    _skip_unless_8()
    cfg = LlamaConfig.tiny(n_layers=4, remat=False,  # default bf16
                           pipeline_schedule="interleaved_1f1b",
                           pipeline_virtual_stages=2)
    params, batch = _setup(cfg)
    mesh = parallel.create_mesh(pipe=2, fsdp=2, tensor=2,
                                devices=jax.devices()[:8])
    loss, grads = _pipe_loss_and_grads(cfg, params, batch, mesh)
    assert np.isfinite(float(loss))
    assert all(np.isfinite(np.asarray(g)).all()
               for g in jax.tree.leaves(grads))


def test_value_only_call_never_runs_the_schedule(monkeypatch):
    """A no-grad llama_loss under "interleaved_1f1b" must route through
    the custom_vjp PRIMAL (gpipe forward + loss head) — the combined
    forward/backward engine computes every gradient just to discard
    them. Proven by counting engine invocations, not just by value
    equality."""
    _skip_unless_8()
    cfg = LlamaConfig.tiny(dtype="float32", n_layers=4, remat=False,
                           pipeline_schedule="interleaved_1f1b",
                           pipeline_virtual_stages=2)
    params, batch = _setup(cfg, with_mask=True)
    mesh = parallel.create_mesh(pipe=2, fsdp=2, tensor=2,
                                devices=jax.devices()[:8])
    p_sh = apply_sharding(
        params, parallel.shard_params(params, mesh,
                                      llama_partition_rules(pipeline=True)))
    b_sh = jax.device_put(
        batch, named_sharding(mesh, ("data", "fsdp"), "seq"))

    calls = []
    real = pipeline.interleaved_one_f_one_b

    def counting(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(pipeline, "interleaved_one_f_one_b", counting)

    value_only = llama_loss(p_sh, b_sh, cfg, mesh)
    assert not calls, "value-only call engaged the fwd/bwd engine"
    grad_loss, _ = jax.value_and_grad(
        lambda p: llama_loss(p, b_sh, cfg, mesh))(p_sh)
    assert calls, "grad call should engage the engine"
    np.testing.assert_allclose(float(value_only), float(grad_loss),
                               rtol=1e-5)


def test_interleaved_composes_with_split_train_step():
    """The r6 program structure end-to-end: split grad/apply jits with
    2-way microbatch gradient accumulation, each grad call running the
    interleaved schedule (its own M=2 pipeline microbatches inside) —
    loss and updated params must match the monolithic one-jit step."""
    _skip_unless_8()
    import optax

    from horovod_tpu.parallel import make_split_train_step

    cfg = LlamaConfig.tiny(dtype="float32", n_layers=4, remat=False,
                           pipeline_schedule="interleaved_1f1b",
                           pipeline_virtual_stages=2,
                           pipeline_microbatches=2)
    params, batch = _setup(cfg)
    mesh = parallel.create_mesh(pipe=2, fsdp=2, tensor=2,
                                devices=jax.devices()[:8])
    p_sh = apply_sharding(
        params, parallel.shard_params(params, mesh,
                                      llama_partition_rules(pipeline=True)))
    b_sh = jax.device_put(
        batch, named_sharding(mesh, ("data", "fsdp"), "seq"))
    tx = optax.sgd(1e-1)

    def loss_fn(p, d):
        return llama_loss(p, d, cfg, mesh)

    @jax.jit
    def monolithic(params, opt, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt = tx.update(grads, opt, params)
        return loss, optax.apply_updates(params, updates)

    ref_loss, ref_params = monolithic(p_sh, tx.init(p_sh), b_sh)

    ts = make_split_train_step(loss_fn, tx, microbatches=2)
    loss, (p2, _) = ts.step(ts.init(p_sh), b_sh)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    _assert_tree_close(ref_params, p2, "split vs monolithic: ")


def test_virtual_stages_config_validation():
    cfg = LlamaConfig.tiny(dtype="float32", pipeline_virtual_stages=2)
    params, batch = _setup(cfg)
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = parallel.create_mesh(pipe=2, fsdp=2, tensor=2,
                                devices=jax.devices()[:8])
    with pytest.raises(ValueError, match="pipeline_virtual_stages"):
        llama_loss(params, batch, cfg, mesh)
    # n_layers=2 cannot split into 2 stages x 2 chunks
    cfg_bad = LlamaConfig.tiny(dtype="float32", n_layers=2,
                               pipeline_schedule="interleaved_1f1b",
                               pipeline_virtual_stages=2)
    with pytest.raises(ValueError, match="n_layers"):
        llama_loss(params, batch, cfg_bad, mesh)
