"""1F1B pipeline schedule (parallel.pipeline.one_f_one_b) vs GPipe.

The two schedules compute the same mathematical function — gpipe as
all-forwards + AD's reversed scan, 1f1b as a manual interleaved
forward/backward with the loss fused into the last stage — so loss AND
gradients must agree with each other and with the unsharded reference.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import parallel
from horovod_tpu.models import (
    LlamaConfig,
    llama_init,
    llama_loss,
    llama_partition_rules,
)
from horovod_tpu.parallel.sharding import apply_sharding, named_sharding


def _skip_unless_8():
    # No jax.shard_map requirement anymore: on older jax (< 0.6, e.g. a
    # CPU-only dev box) the schedules run through pipeline._pipe_spmd's
    # vmap(axis_name=...) emulation, which has identical collective
    # semantics — only the device count gates these tests now.
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")


def _setup(cfg, batch_shape=(4, 16), seed=1, with_mask=False):
    params = llama_init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(seed), batch_shape, 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
    if with_mask:
        mask = jnp.ones(batch_shape).at[1, 10:].set(0)
        batch["mask"] = mask
    return params, batch


def _pipe_loss_and_grads(cfg, params, batch, mesh):
    p_sh = apply_sharding(
        params, parallel.shard_params(params, mesh,
                                      llama_partition_rules(pipeline=True)))
    b_sh = jax.device_put(
        batch, named_sharding(mesh, ("data", "fsdp"), "seq"))
    return jax.jit(jax.value_and_grad(
        lambda p: llama_loss(p, b_sh, cfg, mesh)))(p_sh)


@pytest.mark.parametrize("with_mask", [False, True])
def test_1f1b_matches_gpipe_and_reference(with_mask):
    _skip_unless_8()
    cfg_g = LlamaConfig.tiny(dtype="float32", n_layers=4, remat=False)
    cfg_1 = dataclasses.replace(cfg_g, pipeline_schedule="1f1b")
    params, batch = _setup(cfg_g, with_mask=with_mask)

    ref_loss, ref_grads = jax.jit(jax.value_and_grad(
        lambda p: llama_loss(p, batch, cfg_g)))(params)

    mesh = parallel.create_mesh(pipe=2, fsdp=2, tensor=2,
                                devices=jax.devices()[:8])
    gp_loss, gp_grads = _pipe_loss_and_grads(cfg_g, params, batch, mesh)
    ob_loss, ob_grads = _pipe_loss_and_grads(cfg_1, params, batch, mesh)

    np.testing.assert_allclose(float(gp_loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(float(ob_loss), float(ref_loss), rtol=1e-5)
    for (ka, a), (_, b_), (_, c_) in zip(
            jax.tree_util.tree_leaves_with_path(ref_grads),
            jax.tree_util.tree_leaves_with_path(gp_grads),
            jax.tree_util.tree_leaves_with_path(ob_grads)):
        np.testing.assert_allclose(
            np.asarray(c_), np.asarray(a), rtol=2e-4, atol=1e-6,
            err_msg=f"1f1b vs reference: {jax.tree_util.keystr(ka)}")
        np.testing.assert_allclose(
            np.asarray(c_), np.asarray(b_), rtol=2e-4, atol=1e-6,
            err_msg=f"1f1b vs gpipe: {jax.tree_util.keystr(ka)}")


def test_1f1b_moe_matches_gpipe():
    """MoE through 1f1b: the aux objective folded via its constant
    cotangent must reproduce the gpipe path's loss + w*mean(aux) — the
    router gradients are the sensitive part."""
    _skip_unless_8()
    cfg_g = LlamaConfig.tiny_moe(dtype="float32", n_layers=4,
                                 remat=False, moe_impl="gshard")
    cfg_1 = dataclasses.replace(cfg_g, pipeline_schedule="1f1b")
    params, batch = _setup(cfg_g)

    mesh = parallel.create_mesh(pipe=2, expert=2, tensor=2,
                                devices=jax.devices()[:8])
    gp_loss, gp_grads = _pipe_loss_and_grads(cfg_g, params, batch, mesh)
    ob_loss, ob_grads = _pipe_loss_and_grads(cfg_1, params, batch, mesh)

    np.testing.assert_allclose(float(ob_loss), float(gp_loss), rtol=1e-5)
    for (ka, a), (_, b_) in zip(
            jax.tree_util.tree_leaves_with_path(gp_grads),
            jax.tree_util.tree_leaves_with_path(ob_grads)):
        np.testing.assert_allclose(
            np.asarray(b_), np.asarray(a), rtol=2e-4, atol=1e-6,
            err_msg=jax.tree_util.keystr(ka))


def test_1f1b_more_microbatches_than_stages():
    """M > S exercises the stash-reuse path (Q < M slots wrap around)."""
    _skip_unless_8()
    cfg_g = LlamaConfig.tiny(dtype="float32", n_layers=4, remat=False,
                             pipeline_microbatches=8)
    cfg_1 = dataclasses.replace(cfg_g, pipeline_schedule="1f1b")
    params, batch = _setup(cfg_g, batch_shape=(8, 16))

    ref_loss, ref_grads = jax.jit(jax.value_and_grad(
        lambda p: llama_loss(p, batch, cfg_g)))(params)
    mesh = parallel.create_mesh(pipe=2, fsdp=2, tensor=2,
                                devices=jax.devices()[:8])
    ob_loss, ob_grads = _pipe_loss_and_grads(cfg_1, params, batch, mesh)
    np.testing.assert_allclose(float(ob_loss), float(ref_loss), rtol=1e-5)
    for (ka, a), (_, b_) in zip(
            jax.tree_util.tree_leaves_with_path(ref_grads),
            jax.tree_util.tree_leaves_with_path(ob_grads)):
        np.testing.assert_allclose(
            np.asarray(b_), np.asarray(a), rtol=2e-4, atol=1e-6,
            err_msg=jax.tree_util.keystr(ka))


def test_1f1b_bf16_compiles_on_cpu():
    """bf16 activations through the 1f1b schedule must not hit XLA
    CPU's AllReducePromotion crash (the shared-psum f32 guards)."""
    _skip_unless_8()
    cfg = LlamaConfig.tiny(n_layers=4, remat=False,
                           pipeline_schedule="1f1b")  # default bf16
    params, batch = _setup(cfg)
    mesh = parallel.create_mesh(pipe=2, fsdp=2, tensor=2,
                                devices=jax.devices()[:8])
    loss, grads = _pipe_loss_and_grads(cfg, params, batch, mesh)
    assert np.isfinite(float(loss))
    assert all(np.isfinite(np.asarray(g)).all()
               for g in jax.tree.leaves(grads))


def test_unknown_pipeline_schedule_rejected():
    cfg = LlamaConfig.tiny(dtype="float32", pipeline_schedule="bogus")
    params, batch = _setup(cfg)
    with pytest.raises(ValueError, match="pipeline_schedule"):
        llama_loss(params, batch, cfg)


def test_1f1b_value_only_routes_through_gpipe_and_matches():
    """A no-grad llama_loss call under pipeline_schedule="1f1b" runs
    the custom_vjp PRIMAL — the gpipe forward + loss head (one forward,
    no gradients; ADVICE r5) — and its value must match the
    differentiated path's loss."""
    _skip_unless_8()
    cfg = LlamaConfig.tiny(dtype="float32", n_layers=4, remat=False,
                           pipeline_schedule="1f1b")
    params, batch = _setup(cfg, with_mask=True)
    mesh = parallel.create_mesh(pipe=2, fsdp=2, tensor=2,
                                devices=jax.devices()[:8])
    p_sh = apply_sharding(
        params, parallel.shard_params(params, mesh,
                                      llama_partition_rules(pipeline=True)))
    b_sh = jax.device_put(
        batch, named_sharding(mesh, ("data", "fsdp"), "seq"))
    value_only = jax.jit(lambda p: llama_loss(p, b_sh, cfg, mesh))(p_sh)
    grad_loss, _ = jax.jit(jax.value_and_grad(
        lambda p: llama_loss(p, b_sh, cfg, mesh)))(p_sh)
    np.testing.assert_allclose(float(value_only), float(grad_loss),
                               rtol=1e-5)
