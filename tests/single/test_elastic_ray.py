"""ElasticRayExecutor lifecycle without a Ray cluster.

Reference analog: ``horovod/ray/elastic_v2.py`` (ElasticRayExecutor +
RayHostDiscovery), tested the reference's own way — fake discovery and
thread-fake workers (SURVEY.md §4): the launcher backend is injected,
so the REAL elastic machinery (ElasticDriver reconcile, rendezvous,
epoch cuts, respawn, survivor-first layout) runs end-to-end while the
"actors" are plain threads.
"""

import sys
import threading
import time
import types

import pytest

from horovod_tpu.ray.elastic import ElasticRayExecutor, RayHostDiscovery
from horovod_tpu.runner.elastic.rendezvous import RendezvousClient


class MutableCluster:
    """Discovery over a host dict the test mutates mid-run."""

    def __init__(self, hosts):
        self._lock = threading.Lock()
        self._hosts = dict(hosts)

    def set_hosts(self, hosts):
        with self._lock:
            self._hosts = dict(hosts)

    def find_available_hosts_and_slots(self):
        with self._lock:
            return dict(self._hosts)


def thread_launcher(worker, env, fn, events):
    """Thread-fake actor: runs fn(env) in-process. Returns (rc, result);
    honors kill/shutdown events the way the Ray backend does."""
    box = {}

    def target():
        try:
            box["result"] = fn(env)
            box["rc"] = 0
        except Exception as e:  # noqa: BLE001 - worker failure is data
            box["error"] = e
            box["rc"] = 1

    t = threading.Thread(target=target, daemon=True)
    t.start()
    while t.is_alive():
        if any(ev.is_set() for ev in events):
            return 1, None  # actor killed; thread is daemonic
        t.join(timeout=0.05)
    return box.get("rc", 1), box.get("result")


def _register_and_poll(env, min_epoch=1, timeout=30):
    client = RendezvousClient(env["HOROVOD_RDZV_ADDR"],
                              env["HOROVOD_RDZV_PORT"])
    client.register(env["HOROVOD_WORKER_ID"], env["HOROVOD_HOSTNAME"],
                    0, None)
    return client, client.poll_assignment(env["HOROVOD_WORKER_ID"],
                                          timeout=timeout,
                                          min_epoch=min_epoch)


def test_elastic_ray_respawns_failed_worker():
    """One worker fails once; the driver must respawn its slot and cut a
    recovery epoch that the whole fleet completes."""
    def fn(env):
        client, asg = _register_and_poll(env)
        if asg["epoch"] == 1:
            if asg["rank"] == 1:
                raise RuntimeError("injected worker failure")
            # Survivor from the pre-failure epoch: wait for the recovery
            # cut (the driver respawns the dead slot into epoch 2).
            asg = client.poll_assignment(env["HOROVOD_WORKER_ID"],
                                         timeout=30, min_epoch=2)
        return (asg["rank"], asg["size"], asg["epoch"])

    ex = ElasticRayExecutor(override_discovery=MutableCluster({"h": 2}),
                            min_np=2, launcher=thread_launcher,
                            poll_interval=0.2, start_timeout=20)
    results = ex.run(fn)
    assert len(results) == 2
    ranks = sorted(r for r, _, _ in results)
    assert ranks == [0, 1]
    assert all(size == 2 for _, size, _ in results)
    assert all(epoch >= 2 for _, _, epoch in results), results


def test_elastic_ray_scale_up_adds_worker():
    """Discovery grows mid-run; the driver must spawn into the new slot
    and publish a bigger epoch."""
    cluster = MutableCluster({"h": 1})
    grown = threading.Event()

    def fn(env):
        client, asg = _register_and_poll(env)
        if asg["size"] == 1:
            # First (solo) worker: trigger the growth, then wait for the
            # scaled-up epoch.
            if not grown.is_set():
                grown.set()
                cluster.set_hosts({"h": 2})
            asg = client.poll_assignment(env["HOROVOD_WORKER_ID"],
                                         timeout=30, min_epoch=2)
        return (asg["rank"], asg["size"])

    ex = ElasticRayExecutor(override_discovery=cluster, min_np=1,
                            launcher=thread_launcher, poll_interval=0.2,
                            start_timeout=20)
    results = ex.run(fn)
    assert sorted(results) == [(0, 2), (1, 2)]


def test_elastic_ray_scale_down_removes_host():
    """A host leaves; its worker is killed (not a failure) and the
    survivors complete at the smaller size."""
    cluster = MutableCluster({"a": 2, "b": 1})
    shrink_once = threading.Event()

    def fn(env):
        client, asg = _register_and_poll(env)
        if asg["size"] == 3:
            if env["HOROVOD_HOSTNAME"] == "a" and asg["rank"] == 0 \
                    and not shrink_once.is_set():
                shrink_once.set()
                cluster.set_hosts({"a": 2})
            if env["HOROVOD_HOSTNAME"] == "b":
                # Killed by the driver when its host vanishes; waiting
                # here keeps the thread alive until the kill lands.
                time.sleep(60)
                raise RuntimeError("host-b worker outlived its host")
            asg = client.poll_assignment(env["HOROVOD_WORKER_ID"],
                                         timeout=30, min_epoch=2)
        return (asg["rank"], asg["size"], env["HOROVOD_HOSTNAME"])

    ex = ElasticRayExecutor(override_discovery=cluster, min_np=2,
                            launcher=thread_launcher, poll_interval=0.2,
                            start_timeout=20)
    results = ex.run(fn)
    assert len(results) == 2
    assert all(size == 2 and host == "a" for _, size, host in results)
    assert sorted(r for r, _, _ in results) == [0, 1]


def test_elastic_ray_failure_exhausts_and_raises():
    """A worker that fails every attempt on a 1-host cluster eventually
    blacklists the host; run() must raise, not hang."""

    def fn(env):
        _register_and_poll(env, timeout=10)
        raise RuntimeError("always failing")

    ex = ElasticRayExecutor(override_discovery=MutableCluster({"h": 1}),
                            min_np=1, launcher=thread_launcher,
                            poll_interval=0.1, start_timeout=5)
    with pytest.raises(RuntimeError, match="elastic ray job failed"):
        ex.run(fn)


def test_ray_host_discovery_parses_cluster(monkeypatch):
    """RayHostDiscovery against a stubbed ray module: alive nodes with
    enough resources become host:slots entries."""
    stub = types.ModuleType("ray")
    stub.nodes = lambda: [
        {"Alive": True, "NodeManagerAddress": "10.0.0.1",
         "Resources": {"CPU": 8, "GPU": 2}},
        {"Alive": True, "NodeManagerAddress": "10.0.0.2",
         "Resources": {"CPU": 2}},
        {"Alive": False, "NodeManagerAddress": "10.0.0.3",
         "Resources": {"CPU": 16}},
    ]
    monkeypatch.setitem(sys.modules, "ray", stub)

    disc = RayHostDiscovery(cpus_per_worker=2)
    assert disc.find_available_hosts_and_slots() == {
        "10.0.0.1": 4, "10.0.0.2": 1}
    # GPU-bounded: 2 GPUs at 1/worker caps the first node at 2 slots;
    # the CPU-only node drops out entirely.
    disc = RayHostDiscovery(cpus_per_worker=1, gpus_per_worker=1)
    assert disc.find_available_hosts_and_slots() == {"10.0.0.1": 2}


def test_elastic_ray_start_timeout_raises_and_stops_rendezvous():
    """An empty cluster must raise TimeoutError from start(), and the
    rendezvous server bound in __init__ must be stopped, not leaked."""
    ex = ElasticRayExecutor(override_discovery=MutableCluster({}),
                            min_np=1, launcher=thread_launcher,
                            poll_interval=0.1, start_timeout=1)
    with pytest.raises(TimeoutError):
        ex.run(lambda env: None)
    # server_close() ran: the listening socket is released.
    assert ex.driver._rendezvous._httpd.socket.fileno() == -1


def test_executor_requires_ray_without_injected_launcher():
    ex = ElasticRayExecutor(override_discovery=MutableCluster({"h": 1}),
                            min_np=1)
    with pytest.raises(ImportError, match="requires the 'ray' package"):
        ex.run(lambda: None)
