"""Single-process sanity of the native core: init/shutdown lifecycle, size-1
collectives (identity semantics), runtime knobs.

Reference analog: the parts of test/parallel/test_torch.py that are
meaningful at size 1, plus basics lifecycle checks.
"""

import numpy as np
import pytest

# Part of the sub-5-minute CI lane (make test-quick).
pytestmark = pytest.mark.quick


@pytest.fixture()
def hvd_core(monkeypatch):
    for k in ("HOROVOD_RANK", "HOROVOD_SIZE", "HOROVOD_LOCAL_RANK",
              "HOROVOD_LOCAL_SIZE"):
        monkeypatch.delenv(k, raising=False)
    from horovod_tpu.common import basics
    b = basics.HorovodBasics()
    b.init()
    yield b
    b.shutdown()


def test_identity_and_knobs(hvd_core):
    from horovod_tpu.common import eager_ops as ops
    assert hvd_core.rank() == 0
    assert hvd_core.size() == 1
    assert hvd_core.local_rank() == 0
    assert hvd_core.is_initialized()

    lib = hvd_core.lib
    assert lib.hvdtpu_fusion_threshold_bytes() == 64 * 1024 * 1024
    lib.hvdtpu_set_fusion_threshold_bytes(1 << 20)
    assert lib.hvdtpu_fusion_threshold_bytes() == 1 << 20
    assert lib.hvdtpu_cycle_time_ms() == pytest.approx(1.0)

    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    h = ops.allreduce_async(x, "id")
    np.testing.assert_array_equal(h.synchronize(), x)

    # average at size 1 is identity
    h = ops.allreduce_async(x, "avg", op=ops.ReduceOp.AVERAGE)
    np.testing.assert_array_equal(h.synchronize(), x)

    h = ops.allgather_async(x, "ag")
    np.testing.assert_array_equal(h.synchronize(), x)

    h = ops.broadcast_async(x, 0, "bc")
    np.testing.assert_array_equal(h.synchronize(), x)

    h = ops.reducescatter_async(x, "rs")
    np.testing.assert_array_equal(h.synchronize(), x)

    ops.barrier()


@pytest.mark.loadflaky
def test_duplicate_name_rejected(hvd_core):
    from horovod_tpu.common import eager_ops as ops
    # Stall the loop by enqueueing two ops with the same name inside one
    # cycle; the second must fail with a precondition error, not corrupt
    # state. RACE BY DESIGN: on a loaded box the background loop can pop
    # the first enqueue before the second lands, making both legal —
    # that is correct behavior, not the bug under test, so retry with a
    # widening cycle until one attempt actually collides (the de-flake
    # contract: only "collided AND was not rejected" may fail).
    lib = hvd_core.lib
    x = np.zeros(4, np.float32)
    try:
        for attempt in range(5):
            lib.hvdtpu_set_cycle_time_ms(100.0 * (attempt + 1))
            h1 = ops.allreduce_async(x, f"dup.{attempt}")
            h2 = ops.allreduce_async(x, f"dup.{attempt}")
            np.testing.assert_array_equal(h1.synchronize(), x)
            try:
                h2.synchronize()
            except ops.HorovodInternalError as e:
                assert "duplicate" in str(e).lower()
                return  # collided and was rejected — the pin holds
        pytest.skip("5 attempts never collided in one cycle (box too "
                    "loaded to exercise the duplicate path this run)")
    finally:
        lib.hvdtpu_set_cycle_time_ms(1.0)


def test_uninitialized_rank_raises():
    # A fresh basics object in a process where init happened is fine; this
    # asserts the error path shape only when the lib reports -1.
    from horovod_tpu.common.basics import HorovodBasics
    b = HorovodBasics()
    if not b.is_initialized():
        with pytest.raises(ValueError):
            b.rank()


def test_scalar_allreduce_preserves_0d(hvd_core):
    """Regression: np.ascontiguousarray promotes 0-d to 1-d; a scalar
    allreduce must round-trip shape-exact (reference semantics)."""
    import numpy as np

    from horovod_tpu.common import eager_ops

    out = eager_ops.allreduce_async(
        np.asarray(3.0, np.float32), "scalar0d").synchronize()
    assert out.shape == ()
    assert out == 3.0
    # Non-contiguous input still works (the contiguity path).
    base = np.arange(10, dtype=np.float32)[::2]
    out2 = eager_ops.allreduce_async(base, "strided").synchronize()
    assert out2.shape == (5,)
    assert np.array_equal(out2, base)


def test_capability_api():
    """Reference parity: hvd.gloo_built()/nccl_built()/... exist on every
    frontend and report the TPU build's reality."""
    import horovod_tpu.jax as hvd

    assert hvd.gloo_built() and hvd.gloo_enabled()
    assert hvd.mpi_built() and hvd.mpi_threads_supported()
    assert not hvd.nccl_built() and not hvd.cuda_built()
    assert not hvd.rocm_built() and not hvd.ccl_built()
    assert hvd.xla_built()          # jax importable here
    assert isinstance(hvd.xla_enabled(), bool)

    import horovod_tpu.torch as ht

    assert ht.gloo_built() and not ht.nccl_built()


def test_check_build_cli(capsys):
    from horovod_tpu.runner import launch

    try:
        launch.run_commandline(["--check-build"])
    except SystemExit as e:
        assert e.code == 0
    out = capsys.readouterr().out
    assert "Available Frameworks" in out
    assert "[X] JAX" in out
    assert "xla_ici device plane" in out


def test_mpi_bootstrap_from_fake_world(monkeypatch):
    """Bare-mpirun init path (ref mpi_context.cc): HOROVOD_* env derives
    from the MPI world when no launcher provided it. mpi4py is absent in
    this image, so a faithful fake comm stands in."""
    import sys
    import types

    class _Comm:
        def __init__(self, rank, size):
            self._rank, self._size = rank, size

        def Get_rank(self):
            return self._rank

        def Get_size(self):
            return self._size

        def Split_type(self, kind, key=0):
            return _Comm(self._rank % 2, 2)   # 2 ranks per fake host

        def Split(self, color, key=0):
            return _Comm(self._rank // 2, self._size // 2)

        def bcast(self, obj, root=0):
            # single process stands in for all ranks; rank 0's endpoint
            return ("node0", "29999") if obj is None else obj

    fake = types.ModuleType("mpi4py")
    fake.MPI = types.SimpleNamespace(
        Is_initialized=lambda: True,
        COMM_TYPE_SHARED=object(),
        COMM_WORLD=_Comm(3, 4),
    )
    monkeypatch.setitem(sys.modules, "mpi4py", fake)

    from horovod_tpu.common.mpi_bootstrap import maybe_bootstrap_from_mpi

    env = {}
    assert maybe_bootstrap_from_mpi(env) is True
    assert env["HOROVOD_RANK"] == "3" and env["HOROVOD_SIZE"] == "4"
    assert env["HOROVOD_LOCAL_RANK"] == "1"
    assert env["HOROVOD_LOCAL_SIZE"] == "2"
    assert env["HOROVOD_CROSS_RANK"] == "1"
    assert env["HOROVOD_CROSS_SIZE"] == "2"
    assert env["HOROVOD_CONTROLLER_ADDR"] == "node0"
    assert env["HOROVOD_CONTROLLER_PORT"] == "29999"

    # a launcher-set env wins — the bootstrap must not touch it
    env2 = {"HOROVOD_RANK": "0"}
    assert maybe_bootstrap_from_mpi(env2) is False
    assert env2 == {"HOROVOD_RANK": "0"}


def test_mpi_bootstrap_noop_without_mpi():
    from horovod_tpu.common.mpi_bootstrap import maybe_bootstrap_from_mpi

    env = {}
    assert maybe_bootstrap_from_mpi(env) is False  # no mpi4py installed
    assert env == {}

    # Even with a launcher env present, absence of mpi4py stays a no-op.
    env = {"OMPI_COMM_WORLD_SIZE": "4"}
    assert maybe_bootstrap_from_mpi(env) is False
    assert env == {"OMPI_COMM_WORLD_SIZE": "4"}


def test_mpi_bootstrap_never_imports_mpi4py_unlaunched(monkeypatch):
    """ADVICE r2 (medium): importing mpi4py MPI_Inits as a side effect,
    which can hard-abort under a stale/foreign launcher env — so without
    an MPI launcher's own env vars the bootstrap must not import it at
    all (an exploding meta-path finder proves the import never starts)."""
    import importlib.abc
    import sys

    attempts = []

    class _Tripwire(importlib.abc.MetaPathFinder):
        def find_spec(self, name, path=None, target=None):
            if name == "mpi4py" or name.startswith("mpi4py."):
                attempts.append(name)
            return None

    monkeypatch.setattr(sys, "meta_path", [_Tripwire()] + sys.meta_path)
    sys.modules.pop("mpi4py", None)

    from horovod_tpu.common.mpi_bootstrap import maybe_bootstrap_from_mpi

    assert maybe_bootstrap_from_mpi({}) is False
    assert attempts == []  # the import never even started

    # Under a genuine MPI launcher env the import IS attempted (and here
    # degrades to a clean no-op, mpi4py being absent from the image).
    assert maybe_bootstrap_from_mpi({"OMPI_COMM_WORLD_SIZE": "2"}) is False
    assert attempts  # gate opened exactly for the launcher case


def test_mpi_bootstrap_imported_but_uninitialized(monkeypatch):
    """Embedding program imported mpi4py but never brought the world up
    and no launcher is present: not an MPI run."""
    import sys
    import types

    fake = types.ModuleType("mpi4py")
    fake.MPI = types.SimpleNamespace(Is_initialized=lambda: False)
    monkeypatch.setitem(sys.modules, "mpi4py", fake)

    from horovod_tpu.common.mpi_bootstrap import maybe_bootstrap_from_mpi

    assert maybe_bootstrap_from_mpi({}) is False
