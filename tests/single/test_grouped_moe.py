"""Dropless grouped-GEMM MoE dispatch (ops/grouped_moe.py) vs the
GShard one-hot path (models/llama.py:_moe_ffn).

When no token exceeds GShard capacity the two are the same function
(same router, gate normalization, aux loss) computed two ways — values
AND gradients must agree. When tokens overflow, GShard drops them on
the residual and grouped (dropless) computes them — a semantic
difference these tests pin on purpose.

The CPU substrate drives grouped_moe's exact one-hot fallback for the
grouped matmul; the megablox kernel itself is bench/TPU-only (its
interpret mode cannot differentiate).
"""

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.models import LlamaConfig, llama_init, llama_loss
from horovod_tpu.models.llama import _moe_ffn
from horovod_tpu.ops.grouped_moe import grouped_moe_ffn


def _layer0(cfg, key=0):
    params = llama_init(cfg, jax.random.PRNGKey(key))
    return jax.tree.map(lambda x: x[0], params["layers"])


def _h(cfg, B=2, T=16, key=3):
    return jax.random.normal(jax.random.PRNGKey(key),
                             (B, T, cfg.d_model), jnp.float32)


def _dropless_cfg(**kw):
    # capacity_factor = E makes per-group capacity C = T*K — no routing
    # pattern can overflow it, so GShard provably drops nothing and the
    # two dispatches compute the same math.
    kw.setdefault("capacity_factor", float(kw.get("n_experts", 4)))
    return LlamaConfig.tiny_moe(dtype="float32", remat=False, **kw)


def test_grouped_moe_matches_gshard_when_dropless():
    cfg = _dropless_cfg()
    lp = _layer0(cfg)
    h = _h(cfg)
    y_ref, aux_ref = _moe_ffn(h, lp, cfg, None)
    y, aux = grouped_moe_ffn(h, lp, cfg)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-6)


def test_grouped_moe_gradients_match_gshard():
    cfg = _dropless_cfg()
    lp = _layer0(cfg)
    h = _h(cfg)

    def loss(fn, h, lp):
        y, aux = fn(h, lp)
        return (y.astype(jnp.float32) ** 2).mean() + 0.01 * aux

    g_ref = jax.grad(lambda h, lp: loss(
        lambda a, b: _moe_ffn(a, b, cfg, None), h, lp), (0, 1))(h, lp)
    g = jax.grad(lambda h, lp: loss(
        lambda a, b: grouped_moe_ffn(a, b, cfg), h, lp), (0, 1))(h, lp)
    np.testing.assert_allclose(np.asarray(g[0]), np.asarray(g_ref[0]),
                               rtol=2e-5, atol=2e-6, err_msg="dh")
    for name in g[1]:
        np.testing.assert_allclose(
            np.asarray(g[1][name]), np.asarray(g_ref[1][name]),
            rtol=2e-5, atol=2e-6, err_msg=f"d{name}")


def test_grouped_moe_is_dropless_where_gshard_drops():
    # Tiny capacity forces GShard to drop most overflow tokens; the
    # grouped path must still compute every (token, k) slot.
    cfg = LlamaConfig.tiny_moe(dtype="float32", remat=False,
                               capacity_factor=0.25)
    lp = _layer0(cfg)
    # Bias the router hard toward expert 0 so overflow is guaranteed.
    lp = dict(lp)
    lp["router"] = lp["router"].at[:, 0].add(10.0)
    h = _h(cfg)
    y_gshard, _ = _moe_ffn(h, lp, cfg, None)
    y_grouped, _ = grouped_moe_ffn(h, lp, cfg)
    # GShard zeroes dropped slots (falls through on the residual);
    # grouped computes them, so some tokens must differ materially.
    diff = np.abs(np.asarray(y_grouped) - np.asarray(y_gshard)).max(-1)
    assert (diff > 1e-3).any(), "expected dropped tokens to differ"
    # And every grouped token got SOME expert output (dropless).
    assert (np.abs(np.asarray(y_grouped)).max(-1) > 1e-6).all()


def test_llama_forward_grouped_impl_end_to_end():
    # moe_impl="auto" with no mesh resolves to the grouped path; the
    # full forward + loss must be finite and trainable.
    cfg = LlamaConfig.tiny_moe(dtype="float32", remat=False)
    assert cfg.moe_impl == "auto"
    params = llama_init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
    loss, grads = jax.value_and_grad(llama_loss)(params, batch, cfg)
    assert np.isfinite(float(loss))
    flat = jax.tree.leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in flat)
    # The expert weights receive gradient (routing engaged).
    assert float(jnp.abs(grads["layers"]["moe_down"]).max()) > 0


def test_bwd_tilings_clamp_per_direction():
    """Each backward matmul's tiling clamps against ITS OWN problem
    dims, not the forward's (ADVICE r5): the dlhs gmm (transpose_rhs)
    reads its (m, contraction, out) as (m, n, k) — contraction over the
    forward's OUTPUT dim n, output over the forward's contraction k —
    while tgmm's dims coincide with the forward's (m, k, n)."""
    from horovod_tpu.ops.grouped_moe import _bwd_tilings

    # d_model(k)=512 < 1024 <= d_ff(n)=2048 — the straddling shape that
    # mis-clamped before: the old forward-dims clamp gave dlhs a
    # contraction tile of 512 (under its real 2048) and an output tile
    # of 1024 (OVER its real 512-wide output).
    dlhs, tgmm = _bwd_tilings(4096, 512, 2048)
    assert dlhs == (512, 1024, 512), dlhs   # (m, n=2048->1024, k=512)
    assert tgmm == (512, 512, 1024), tgmm   # (m, k=512, n=2048->1024)

    # Small-everything shapes clamp every direction to the problem.
    dlhs, tgmm = _bwd_tilings(256, 128, 64)
    assert dlhs == (256, 64, 128), dlhs
    assert tgmm == (256, 128, 64), tgmm

    # Large square shapes sit at the swept optimum in all directions.
    dlhs, tgmm = _bwd_tilings(16384, 2048, 4096)
    assert dlhs == (512, 1024, 1024), dlhs
    assert tgmm == (512, 1024, 1024), tgmm
