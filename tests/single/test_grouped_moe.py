"""Dropless grouped-GEMM MoE dispatch (ops/grouped_moe.py) vs the
GShard one-hot path (models/llama.py:_moe_ffn).

When no token exceeds GShard capacity the two are the same function
(same router, gate normalization, aux loss) computed two ways — values
AND gradients must agree. When tokens overflow, GShard drops them on
the residual and grouped (dropless) computes them — a semantic
difference these tests pin on purpose.

The CPU substrate drives grouped_moe's exact one-hot fallback for the
grouped matmul; the megablox kernel itself is bench/TPU-only (its
interpret mode cannot differentiate).
"""

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.models import LlamaConfig, llama_init, llama_loss
from horovod_tpu.models.llama import _moe_ffn
from horovod_tpu.ops.grouped_moe import grouped_moe_ffn


def _layer0(cfg, key=0):
    params = llama_init(cfg, jax.random.PRNGKey(key))
    return jax.tree.map(lambda x: x[0], params["layers"])


def _h(cfg, B=2, T=16, key=3):
    return jax.random.normal(jax.random.PRNGKey(key),
                             (B, T, cfg.d_model), jnp.float32)


def _dropless_cfg(**kw):
    # capacity_factor = E makes per-group capacity C = T*K — no routing
    # pattern can overflow it, so GShard provably drops nothing and the
    # two dispatches compute the same math.
    kw.setdefault("capacity_factor", float(kw.get("n_experts", 4)))
    return LlamaConfig.tiny_moe(dtype="float32", remat=False, **kw)


def test_grouped_moe_matches_gshard_when_dropless():
    cfg = _dropless_cfg()
    lp = _layer0(cfg)
    h = _h(cfg)
    y_ref, aux_ref = _moe_ffn(h, lp, cfg, None)
    y, aux = grouped_moe_ffn(h, lp, cfg)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-6)


def test_grouped_moe_gradients_match_gshard():
    cfg = _dropless_cfg()
    lp = _layer0(cfg)
    h = _h(cfg)

    def loss(fn, h, lp):
        y, aux = fn(h, lp)
        return (y.astype(jnp.float32) ** 2).mean() + 0.01 * aux

    g_ref = jax.grad(lambda h, lp: loss(
        lambda a, b: _moe_ffn(a, b, cfg, None), h, lp), (0, 1))(h, lp)
    g = jax.grad(lambda h, lp: loss(
        lambda a, b: grouped_moe_ffn(a, b, cfg), h, lp), (0, 1))(h, lp)
    np.testing.assert_allclose(np.asarray(g[0]), np.asarray(g_ref[0]),
                               rtol=2e-5, atol=2e-6, err_msg="dh")
    for name in g[1]:
        np.testing.assert_allclose(
            np.asarray(g[1][name]), np.asarray(g_ref[1][name]),
            rtol=2e-5, atol=2e-6, err_msg=f"d{name}")


def test_grouped_moe_is_dropless_where_gshard_drops():
    # Tiny capacity forces GShard to drop most overflow tokens; the
    # grouped path must still compute every (token, k) slot.
    cfg = LlamaConfig.tiny_moe(dtype="float32", remat=False,
                               capacity_factor=0.25)
    lp = _layer0(cfg)
    # Bias the router hard toward expert 0 so overflow is guaranteed.
    lp = dict(lp)
    lp["router"] = lp["router"].at[:, 0].add(10.0)
    h = _h(cfg)
    y_gshard, _ = _moe_ffn(h, lp, cfg, None)
    y_grouped, _ = grouped_moe_ffn(h, lp, cfg)
    # GShard zeroes dropped slots (falls through on the residual);
    # grouped computes them, so some tokens must differ materially.
    diff = np.abs(np.asarray(y_grouped) - np.asarray(y_gshard)).max(-1)
    assert (diff > 1e-3).any(), "expected dropped tokens to differ"
    # And every grouped token got SOME expert output (dropless).
    assert (np.abs(np.asarray(y_grouped)).max(-1) > 1e-6).all()


def test_llama_forward_grouped_impl_end_to_end():
    # moe_impl="auto" with no mesh resolves to the grouped path; the
    # full forward + loss must be finite and trainable.
    cfg = LlamaConfig.tiny_moe(dtype="float32", remat=False)
    assert cfg.moe_impl == "auto"
    params = llama_init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
    loss, grads = jax.value_and_grad(llama_loss)(params, batch, cfg)
    assert np.isfinite(float(loss))
    flat = jax.tree.leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in flat)
    # The expert weights receive gradient (routing engaged).
    assert float(jnp.abs(grads["layers"]["moe_down"]).max()) > 0
