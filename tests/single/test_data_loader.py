"""BaseDataLoader / AsyncDataLoaderMixin unit tests.

Reference analog: the loader contract exercised by the Spark/Ray estimator
paths (horovod/data/data_loader_base.py).
"""

import time

import pytest

from horovod_tpu.data import AsyncDataLoaderMixin, BaseDataLoader


class RangeLoader(BaseDataLoader):
    def __init__(self, n, delay=0.0):
        self.n = n
        self.delay = delay

    def __len__(self):
        return self.n

    def _iterate(self):
        for i in range(self.n):
            if self.delay:
                time.sleep(self.delay)
            yield i


class AsyncRangeLoader(AsyncDataLoaderMixin, RangeLoader):
    pass


def test_sync_iteration():
    assert list(RangeLoader(5)) == [0, 1, 2, 3, 4]


def test_async_iteration_order_preserved():
    loader = AsyncRangeLoader(n=20)
    assert list(loader) == list(range(20))
    # Re-iterable: a second epoch restarts the producer.
    assert list(loader) == list(range(20))


def test_async_disabled_degrades_to_sync():
    loader = AsyncRangeLoader(async_loading=False, n=7)
    assert list(loader) == list(range(7))
    assert loader._thread is None


def test_async_prefetch_overlaps():
    # With a slow producer, the consumer still sees every batch exactly once.
    loader = AsyncRangeLoader(async_depth=4, n=10, delay=0.005)
    assert list(loader) == list(range(10))


def test_async_error_propagates():
    # The failing _iterate goes on the BASE class: defining it on the
    # mixed class would shadow AsyncDataLoaderMixin._iterate in the MRO
    # and bypass the producer thread entirely.
    class BoomBase(BaseDataLoader):
        def _iterate(self):
            yield 1
            raise ValueError("bad batch")

    class Boom(AsyncDataLoaderMixin, BoomBase):
        pass

    loader = Boom()
    it = iter(loader)
    assert next(it) == 1
    with pytest.raises(ValueError, match="bad batch"):
        list(it)
    assert loader._thread is not None or loader._queue is None  # async ran


def test_close_mid_epoch():
    loader = AsyncRangeLoader(async_depth=2, n=1000, delay=0.001)
    it = iter(loader)
    assert next(it) == 0
    loader.close_async_loader()  # must not hang on the full queue
    assert loader._thread is None
