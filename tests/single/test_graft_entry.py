"""Driver-contract guards for __graft_entry__.py.

The multichip dryrun is only evidence of sharding correctness if the mesh
it builds actually has parallel axes >1 — `_mesh_axes_for` must refuse to
hand back a pure-data-parallel mesh for a device count that can't be split
(VERDICT r3 weak #5: an odd n_devices used to yield a vacuously-green
MULTICHIP artifact).
"""

import pytest

import __graft_entry__ as ge


def test_mesh_axes_even_counts_split_onto_parallel_axes():
    sizes = ge._mesh_axes_for(8)
    assert sizes == {"seq": 2, "tensor": 2, "fsdp": 2, "data": 1}
    sizes = ge._mesh_axes_for(16)
    assert sizes["seq"] == sizes["tensor"] == sizes["fsdp"] == 2
    assert sizes["data"] == 2


def test_mesh_axes_odd_count_raises_instead_of_vacuous_mesh():
    with pytest.raises(ValueError, match="pure data parallel"):
        ge._mesh_axes_for(7)
    with pytest.raises(ValueError, match="pure data parallel"):
        ge._mesh_axes_for(3)


def test_mesh_axes_fixed_axes_must_divide():
    with pytest.raises(ValueError, match="do not divide"):
        ge._mesh_axes_for(7, axes=("tensor", "fsdp"), fixed={"pipe": 2})


def test_mesh_axes_partial_collapse_warns_but_passes(capsys):
    sizes = ge._mesh_axes_for(2)
    assert sizes["seq"] == 2 and sizes["tensor"] == 1
    out = capsys.readouterr().out
    assert "collapsed to size 1" in out


def test_mesh_axes_fixed_axis_counts_as_parallelism():
    # n=2 entirely consumed by a fixed pipe axis: the requested axes all
    # collapse, but the mesh is still parallel (pipe=2) — no raise.
    sizes = ge._mesh_axes_for(2, axes=("tensor", "fsdp"), fixed={"pipe": 2})
    assert sizes == {"pipe": 2, "tensor": 1, "fsdp": 1, "data": 1}


def test_mesh_axes_degenerate_fixed_axis_does_not_bypass_guard():
    # A size-1 fixed axis provides no parallelism — it must not defeat
    # the pure-data-parallel refusal.
    with pytest.raises(ValueError, match="pure data parallel"):
        ge._mesh_axes_for(7, fixed={"pipe": 1})
