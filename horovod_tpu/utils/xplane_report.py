"""Device-op time attribution from an xprof (xplane) trace.

Reference analog: the Horovod Timeline (``common/timeline.cc``) shows
*runtime* phases (negotiation, queue, collective); what it cannot show
is where the DEVICE time inside an XLA program goes. This tool closes
that gap TPU-natively: point it at the trace directory written by
``hvd.start_timeline(..., xprof_dir=...)`` (or any
``jax.profiler.start_trace`` output) and it aggregates the device
plane's per-op durations into readable buckets — matmul fusions,
pallas custom-calls (flash attention), copies, control flow.

TensorBoard isn't required (and isn't in minimal images): the raw
``*.xplane.pb`` protos are parsed directly via TensorFlow's bundled
xplane proto. Used in round 3 to find the flash-attention remat
rerun that cost 12% of the train step (docs/benchmarks.md).

CLI::

    python -m horovod_tpu.utils.xplane_report /tmp/xprof_dir [--top N]
"""

import glob
import re
import os
from collections import defaultdict

# Buckets, first match wins. (name_lower -> bucket)
_BUCKETS = (
    (("custom-call", "custom_call", "flash", "pallas"), "custom-call (pallas/host)"),
    (("while", "condition", "body"), "control flow"),
    (("copy",), "copy"),
    (("dot", "convolution"), "matmul/conv fusion"),
    (("fusion",), "other fusion"),
    (("transpose", "slice", "pad", "concat", "bitcast", "broadcast",
      "reshape", "iota", "reduce"), "data movement / reduce"),
)


# First lowercase identifier directly followed by '(' after the '=':
# that is the opcode (layout/memory-space annotations like T(8,128) or
# S(1) are uppercase, so they can't match).
_OPCODE_RE = re.compile(r"([a-z][a-z0-9_.-]*)\(")


def _op_ident(name):
    """The DEFINED op's identity: ``%lhs = type opcode(operands...)`` →
    ``lhs opcode``. Matching the full HLO text misbuckets badly — any
    matmul fusion whose *operand* is a ``%copy-done`` used to land in
    the copy bucket (this overstated copy time 20× on the round-3
    flagship trace: 276 "copy" ms/step that were mostly fused weight-
    gradient matmuls consuming async-prefetched operands)."""
    lhs, sep, rhs = name.partition(" = ")
    if not sep:
        return name
    m = _OPCODE_RE.search(rhs)
    return f"{lhs} {m.group(1)}" if m else lhs


def _bucket(name):
    n = _op_ident(name).lower()
    for keys, label in _BUCKETS:
        if any(k in n for k in keys):
            return label
    return "other"


def _load_xspace(path):
    """Parse one .xplane.pb. TF ships the proto; keep the import local
    so the package works without TF installed."""
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except ImportError as e:  # pragma: no cover - env without TF
        raise ImportError(
            "xplane_report needs the xplane proto bundled with "
            "tensorflow (tensorflow.tsl.profiler.protobuf)") from e
    xs = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        xs.ParseFromString(f.read())
    return xs


def _find_pb(path):
    if os.path.isfile(path):
        return [path]
    hits = sorted(glob.glob(os.path.join(path, "**", "*.xplane.pb"),
                            recursive=True))
    if not hits:
        raise FileNotFoundError(f"no *.xplane.pb under {path}")
    return hits


def device_op_report(path, plane_filter=("TPU", "GPU"), op_line="XLA Ops"):
    """Aggregate device-op durations from a trace file or directory.

    Returns a dict per device plane::

        {plane_name: {
            "total_s": wall-busy seconds on the op line,
            "buckets": {bucket: seconds, ...},
            "top_ops": [(op_name, seconds, count), ...],   # descending
        }}

    Notes: the op line's while/condition/body events NEST their body
    ops, so "control flow" double-counts against the inner buckets —
    read it as "time spent inside loops", not additional time. Steps /
    module totals live on separate lines and are not summed here.
    """
    report = {}
    per_plane_ops = {}
    for pb in _find_pb(path):
        xs = _load_xspace(pb)
        for plane in xs.planes:
            if plane_filter and not any(p in plane.name
                                        for p in plane_filter):
                continue
            meta = {k: v.name for k, v in plane.event_metadata.items()}
            for line in plane.lines:
                if line.name != op_line:
                    continue
                entry = report.setdefault(plane.name, {
                    "total_s": 0.0,
                    "buckets": defaultdict(float),
                    "top_ops": [],
                })
                # Per-op durations merge ACROSS files (multi-host traces
                # write one .xplane.pb per host; split rows would
                # misrank the heaviest op).
                per_op = per_plane_ops.setdefault(
                    plane.name, defaultdict(lambda: [0.0, 0]))
                for ev in line.events:
                    name = meta.get(ev.metadata_id, "?")
                    dur = ev.duration_ps / 1e12
                    entry["buckets"][_bucket(name)] += dur
                    entry["total_s"] += dur
                    acc = per_op[name]
                    acc[0] += dur
                    acc[1] += 1
    for plane_name, entry in report.items():
        entry["buckets"] = dict(entry["buckets"])
        entry["top_ops"] = sorted(
            ((n, a[0], a[1])
             for n, a in per_plane_ops[plane_name].items()),
            key=lambda t: -t[1])
    return report


def format_report(report, top=10):
    """Human-readable table for :func:`device_op_report` output."""
    lines = []
    for plane, entry in report.items():
        total = entry["total_s"] or 1e-30
        lines.append(f"== {plane}: {entry['total_s'] * 1e3:.1f} ms busy "
                     f"(op line; loops nest their bodies)")
        for k, v in sorted(entry["buckets"].items(), key=lambda kv: -kv[1]):
            lines.append(f"  {k:28s} {v * 1e3:10.2f} ms  "
                         f"{v / total * 100:5.1f}%")
        if top:
            lines.append("  -- top ops --")
            for name, dur, count in entry["top_ops"][:top]:
                lines.append(f"  {dur * 1e3:10.2f} ms  x{count:<4d} "
                             f"{name[:90]}")
    return "\n".join(lines)


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="trace dir or .xplane.pb file")
    ap.add_argument("--top", type=int, default=10)
    args = ap.parse_args(argv)
    print(format_report(device_op_report(args.path), top=args.top))


if __name__ == "__main__":
    main()
