"""Shared utilities (profiling reports, misc tooling)."""

from horovod_tpu.utils.xplane_report import (  # noqa: F401
    device_op_report,
    format_report,
)
