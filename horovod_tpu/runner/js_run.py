"""LSF/jsrun launch path.

Reference analog: ``horovod/runner/js_run.py`` + ``runner/util/lsf.py`` —
on LSF clusters the allocation (hosts × slots) comes from the scheduler's
env (``LSB_HOSTS`` / ``LSB_MCPU_HOSTS``), and processes are spawned with
``jsrun`` instead of ssh/mpirun.
"""

import os
import shlex
import subprocess
import sys

from horovod_tpu.runner import util


class LSFUtils:
    """Read the LSF allocation from the environment (reference:
    horovod/runner/util/lsf.py)."""

    @staticmethod
    def using_lsf(env=None):
        return "LSB_JOBID" in (env or os.environ)

    @staticmethod
    def get_compute_hosts(env=None):
        """Parse LSB_MCPU_HOSTS ('host1 16 host2 16 ...'), dropping the
        launch node (first entry is the batch host)."""
        env = env or os.environ
        mcpu = env.get("LSB_MCPU_HOSTS", "")
        toks = mcpu.split()
        pairs = [(toks[i], int(toks[i + 1])) for i in range(0, len(toks) - 1, 2)]
        # Reference drops the batch/launch host when compute hosts exist.
        if len(pairs) > 1:
            pairs = pairs[1:]
        return [util.HostInfo(h, s) for h, s in pairs]

    @staticmethod
    def get_num_processes(env=None):
        return sum(h.slots for h in LSFUtils.get_compute_hosts(env))


def js_available(env=None):
    from shutil import which

    return which("jsrun", path=(env or os.environ).get("PATH")) is not None


def build_js_command(num_hosts, tasks_per_host, command, extra_args=None):
    """jsrun cmdline: ONE resource set per host holding all that host's
    ranks (the reference's geometry — multiple all-CPU resource sets on a
    host would be infeasible). Unit-testable pure fn."""
    cmd = ["jsrun", "--nrs", str(max(num_hosts, 1)),
           "--tasks_per_rs", str(tasks_per_host),
           "--cpu_per_rs", "ALL_CPUS", "--gpu_per_rs", "ALL_GPUS",
           "--rs_per_host", "1"]
    if extra_args:
        cmd += shlex.split(extra_args)
    cmd += list(command)
    return cmd


def js_run(args, knob_env, command=None):
    if not js_available():
        raise RuntimeError("horovodrun --js requested but 'jsrun' not found "
                           "in PATH (are you inside an LSF allocation?)")
    hosts = LSFUtils.get_compute_hosts()
    np = args.np or LSFUtils.get_num_processes()
    env = dict(os.environ)
    env.update(knob_env)
    env.setdefault("HOROVOD_SIZE", str(np))
    if hosts:
        # jsrun assigns ranks host-major, so rank 0 (the controller's
        # listen socket) lands on the first compute host — workers must
        # dial THAT host, not the launch node (which LSF excludes from
        # the compute list).
        env.setdefault("HOROVOD_CONTROLLER_ADDR", hosts[0].hostname)
        env.setdefault("HOROVOD_CONTROLLER_PORT", str(util.free_port()))
    per_host = hosts[0].slots if hosts else np
    cmd = build_js_command(len(hosts), per_host, command or args.command,
                           extra_args=getattr(args, "js_args", None))
    if args.verbose:
        print(f"[horovodrun] js: {' '.join(map(shlex.quote, cmd))}",
              file=sys.stderr)
    return subprocess.call(cmd, env=env)
