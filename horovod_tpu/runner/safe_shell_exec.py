"""Robust subprocess execution with process-group cleanup.

Reference analog: ``horovod/runner/common/util/safe_shell_exec.py`` —
fork the child in its own process group, pump stdout/stderr via threads,
and on termination kill the entire tree so no orphan ranks linger.
"""

import os
import signal
import subprocess
import threading

GRACEFUL_TERMINATION_TIME_S = 5


def _pump(stream, sink, prefix=b""):
    for line in iter(stream.readline, b""):
        sink.write(prefix + line)
        sink.flush()
    stream.close()


def execute(command, env=None, stdout=None, stderr=None, prefix=None,
            events=None):
    """Run `command` (list or shell string) in its own process group.

    Streams output line-by-line (optionally prefixed, like the
    reference's `[rank]<stdout>` tagging). Returns the exit code.
    `events`: optional list of threading.Event; if any fires, the child
    tree is terminated.
    """
    import sys

    shell = isinstance(command, str)
    proc = subprocess.Popen(
        command, shell=shell, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, start_new_session=True)

    out_sink = getattr(stdout or sys.stdout, "buffer", stdout or sys.stdout)
    err_sink = getattr(stderr or sys.stderr, "buffer", stderr or sys.stderr)
    p = (prefix.encode() if isinstance(prefix, str) else prefix) or b""
    pumps = [
        threading.Thread(target=_pump, args=(proc.stdout, out_sink, p),
                         daemon=True),
        threading.Thread(target=_pump, args=(proc.stderr, err_sink, p),
                         daemon=True),
    ]
    for t in pumps:
        t.start()

    watcher = None
    if events:
        def watch():
            while proc.poll() is None:
                if any(e.wait(0.1) for e in events):
                    terminate_tree(proc)
                    return
        watcher = threading.Thread(target=watch, daemon=True)
        watcher.start()

    proc.wait()
    for t in pumps:
        t.join(timeout=2)
    return proc.returncode


def terminate_tree(proc):
    """SIGTERM the child's process group; SIGKILL after a grace period."""
    try:
        pgid = os.getpgid(proc.pid)
    except ProcessLookupError:
        return
    try:
        os.killpg(pgid, signal.SIGTERM)
        try:
            proc.wait(timeout=GRACEFUL_TERMINATION_TIME_S)
        except subprocess.TimeoutExpired:
            os.killpg(pgid, signal.SIGKILL)
    except ProcessLookupError:
        pass
