"""horovod_tpu.runner — the launch layer.

Reference analog: ``horovod/runner/`` (horovodrun CLI + the
``horovod.run`` in-python launcher).
"""

import multiprocessing
import os

from horovod_tpu.runner import util


def _worker_main(fn, args, kwargs, slot, controller_addr, controller_port,
                 extra_env, q):
    env = {
        "HOROVOD_RANK": str(slot.rank),
        "HOROVOD_SIZE": str(slot.size),
        "HOROVOD_LOCAL_RANK": str(slot.local_rank),
        "HOROVOD_LOCAL_SIZE": str(slot.local_size),
        "HOROVOD_CROSS_RANK": str(slot.cross_rank),
        "HOROVOD_CROSS_SIZE": str(slot.cross_size),
        "HOROVOD_CONTROLLER_ADDR": controller_addr,
        "HOROVOD_CONTROLLER_PORT": str(controller_port),
    }
    env.update(extra_env or {})
    os.environ.update(env)
    try:
        q.put((slot.rank, None, fn(*args, **(kwargs or {}))))
    except BaseException as e:  # noqa: BLE001 — report, don't hang the pool
        import traceback

        traceback.print_exc()
        q.put((slot.rank, f"{type(e).__name__}: {e}", None))


def run(fn, args=(), kwargs=None, np=2, env=None, start_method="spawn",
        timeout=None):
    """Run ``fn`` on ``np`` local ranks; returns results ordered by rank.

    Reference analog: ``horovod.run`` (horovod/runner/__init__.py) in
    local mode — the interactive / notebook launcher. ``fn`` must be
    picklable (module-level).
    """
    ctx = multiprocessing.get_context(start_method)
    q = ctx.Queue()
    port = util.free_port()
    slots = util.get_host_assignments([util.HostInfo("localhost", np)], np)
    procs = [
        ctx.Process(target=_worker_main,
                    args=(fn, args, kwargs, s, "127.0.0.1", port, env, q))
        for s in slots
    ]
    for p in procs:
        p.start()
    results, errors = {}, {}
    try:
        for _ in range(np):
            rank, err, res = q.get(timeout=timeout)
            (errors if err else results)[rank] = err or res
            if err:
                results[rank] = None
    finally:
        for p in procs:
            p.join(timeout=10)
            if p.is_alive():
                p.terminate()
    if errors:
        raise RuntimeError(f"horovod_tpu.run rank failures: {errors}")
    return [results[r] for r in range(np)]
