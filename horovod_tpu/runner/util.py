"""Launcher utilities: host parsing, rank layout, networking.

Reference analog: ``horovod/runner/common/util/hosts.py`` (parse_hosts,
get_host_assignments) and ``network.py``.
"""

import dataclasses
import socket


@dataclasses.dataclass
class HostInfo:
    hostname: str
    slots: int


@dataclasses.dataclass
class SlotInfo:
    hostname: str
    rank: int
    local_rank: int
    cross_rank: int
    size: int
    local_size: int
    cross_size: int


def parse_hosts(hosts_str):
    """'host1:2,host2:4' -> [HostInfo]. Bare 'host' means 1 slot."""
    hosts = []
    for part in hosts_str.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            name, slots = part.rsplit(":", 1)
            hosts.append(HostInfo(name, int(slots)))
        else:
            hosts.append(HostInfo(part, 1))
    return hosts


def parse_hostfile(path):
    """One 'hostname slots=N' (or 'hostname:N' or bare) per line; # comments."""
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            if "slots=" in line:
                name, _, slots = line.partition("slots=")
                hosts.append(HostInfo(name.strip(), int(slots)))
            elif ":" in line:
                name, slots = line.rsplit(":", 1)
                hosts.append(HostInfo(name.strip(), int(slots)))
            else:
                hosts.append(HostInfo(line, 1))
    return hosts


def get_host_assignments(hosts, np):
    """Fill ranks across hosts in order; error if slots < np.

    Mirrors the reference's round-robin-by-host-order placement
    (horovod/runner/common/util/hosts.py get_host_assignments).
    """
    total = sum(h.slots for h in hosts)
    if total < np:
        raise ValueError(
            f"requested -np {np} but hosts only provide {total} slots")
    slots = []
    rank = 0
    used_hosts = []
    for cross_rank, h in enumerate(hosts):
        if rank >= np:
            break
        n_here = min(h.slots, np - rank)
        used_hosts.append((h, n_here))
        for local_rank in range(n_here):
            slots.append(SlotInfo(h.hostname, rank, local_rank, cross_rank,
                                  np, n_here, 0))
            rank += 1
    cross_size = len(used_hosts)
    for s in slots:
        s.cross_size = cross_size
    return slots


def free_port(addr="0.0.0.0"):
    s = socket.socket()
    s.bind((addr, 0))
    port = s.getsockname()[1]
    s.close()
    return port


_LOCAL_NAMES = {"localhost", "127.0.0.1", "::1"}


def is_local_host(hostname):
    if hostname in _LOCAL_NAMES:
        return True
    try:
        local = {socket.gethostname(), socket.getfqdn()}
    except OSError:
        local = set()
    return hostname in local


def resolvable_addr_for(hosts):
    """Controller address the workers should dial: loopback when all hosts
    are local, else this host's primary address."""
    if all(is_local_host(h.hostname) for h in hosts):
        return "127.0.0.1"
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())
    finally:
        s.close()
