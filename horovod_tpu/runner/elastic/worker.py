"""Worker-side notification listener for elastic events.

Reference analog: ``horovod/runner/elastic/worker.py``
(WorkerNotificationService / WorkerNotificationManager) — the driver pings
each worker over HTTP when the host topology changes; the worker raises
``HostsUpdatedInterrupt`` at the next commit boundary.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class WorkerNotificationManager:
    """Singleton per worker process: listens for driver notifications and
    latches a hosts-updated flag that elastic ``State`` objects consume."""

    def __init__(self):
        self._lock = threading.Lock()
        self._httpd = None
        self._hosts_updated = False
        self._skip_sync = False

    def init(self, addr="0.0.0.0"):
        with self._lock:
            if self._httpd is not None:
                return self.port
        manager = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_POST(self):
                if self.path == "/notify":
                    n = int(self.headers.get("Content-Length", 0))
                    event = json.loads(self.rfile.read(n) or b"{}")
                    manager.handle_hosts_updated(
                        skip_sync=bool(event.get("skip_sync", False)))
                    self.send_response(200)
                else:
                    self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()

        httpd = ThreadingHTTPServer((addr, 0), Handler)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        with self._lock:
            self._httpd = httpd
        return self.port

    @property
    def port(self):
        return self._httpd.server_address[1] if self._httpd else None

    def handle_hosts_updated(self, skip_sync=False):
        with self._lock:
            self._hosts_updated = True
            self._skip_sync = skip_sync

    def poll_hosts_updated(self):
        """Consume the latched flag; returns (updated, skip_sync)."""
        with self._lock:
            updated, skip = self._hosts_updated, self._skip_sync
            self._hosts_updated = False
            self._skip_sync = False
            return updated, skip

    def shutdown(self):
        with self._lock:
            if self._httpd is not None:
                self._httpd.shutdown()
                self._httpd.server_close()
                self._httpd = None


notification_manager = WorkerNotificationManager()


def notify_worker(host, port, skip_sync=False, timeout=5):
    """Driver side: ping one worker's notification service."""
    import urllib.request

    data = json.dumps({"skip_sync": skip_sync}).encode()
    req = urllib.request.Request(f"http://{host}:{port}/notify", data=data,
                                 method="POST")
    try:
        urllib.request.urlopen(req, timeout=timeout)
        return True
    except OSError:
        return False
