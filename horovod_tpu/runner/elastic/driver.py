"""The elastic driver: keeps the worker fleet matched to discovered hosts.

Reference analog: ``horovod/runner/elastic/driver.py`` (ElasticDriver:
worker registry, host assignments, ``wait_for_available_slots``, the
discovery thread, respawn of failed slots, host blacklisting).

Lifecycle per epoch:
  1. reconcile: kill workers on removed hosts, spawn workers for empty
     slots (capped at max_np), notify surviving workers if topology grew;
  2. wait until every alive worker has registered with the rendezvous;
  3. publish epoch assignments (rank/local/cross layout + a fresh
     controller endpoint); resetting workers pick them up and re-init.
Worker failure surfaces as process exit: the dead worker's peers hit
HorovodInternalError organically (broken control plane) and re-enter
rendezvous; the driver respawns the slot (or proceeds smaller if the host
is gone, down to min_np).
"""

import itertools
import os
import shlex
import sys
import threading
import time
import uuid

from horovod_tpu.runner import safe_shell_exec, util
from horovod_tpu.runner.elastic.discovery import HostManager
from horovod_tpu.runner.elastic.rendezvous import RendezvousServer
from horovod_tpu.runner.elastic.worker import notify_worker

_FAILURES_TO_BLACKLIST = 3


_spawn_seq = itertools.count()


class _Worker:
    def __init__(self, worker_id, host, local_index):
        self.worker_id = worker_id
        self.host = host
        self.local_index = local_index  # slot on its host at spawn time
        self.seq = next(_spawn_seq)     # spawn age: survivors < respawns
        self.kill_event = threading.Event()
        self.driver_killed = False      # deliberate kill, not a failure
        self.thread = None
        self.exit_code = None


class ElasticDriver:
    def __init__(self, discovery, command, min_np, max_np=None,
                 poll_interval=2.0, start_timeout=60, env=None, verbose=False):
        self._manager = HostManager(discovery)
        self._command = list(command)
        self._min_np = min_np
        self._max_np = max_np or 10 ** 9
        self._poll_interval = poll_interval
        self._start_timeout = start_timeout
        self._extra_env = dict(env or {})
        self._verbose = verbose

        self._rendezvous = RendezvousServer()
        self._lock = threading.RLock()
        self._workers = {}           # worker_id -> _Worker (alive)
        self._host_failures = {}
        self._shutdown = threading.Event()
        self._reconcile_needed = threading.Event()
        self._epoch_cut = threading.Event()
        self._final_codes = []

    # ---- public API -----------------------------------------------------

    @property
    def rendezvous(self):
        return self._rendezvous

    def start(self):
        self._manager.update_available_hosts()
        self.wait_for_available_slots(self._min_np)
        self._reconcile()
        self._thread = threading.Thread(target=self._monitor, daemon=True)
        self._thread.start()

    def wait_for_available_slots(self, min_np, timeout=None):
        """Block until discovery reports at least min_np slots."""
        deadline = time.monotonic() + (timeout or self._start_timeout)
        while self._manager.slot_count() < min_np:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"only {self._manager.slot_count()} slots available "
                    f"after {self._start_timeout}s; need {min_np}")
            time.sleep(self._poll_interval / 4)
            self._manager.update_available_hosts()

    def wait_for_completion(self):
        """Block until the fleet has exited; returns 0 on success."""
        while True:
            with self._lock:
                # The job is over only when workers finished (or failed)
                # on their own: an empty fleet with NO final codes means
                # every worker was driver-killed (e.g. a transient empty
                # discovery result) — keep waiting for discovery to
                # restore hosts and the monitor to respawn.
                if not self._workers \
                        and not self._reconcile_needed.is_set() \
                        and self._final_codes:
                    break
            if self._shutdown.is_set():
                break
            time.sleep(0.25)
        self._shutdown.set()
        with self._lock:
            codes = list(self._final_codes)
        return 0 if codes and all(c == 0 for c in codes) else 1

    def stop(self):
        self._shutdown.set()
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            w.driver_killed = True
            w.kill_event.set()
        self._rendezvous.stop()

    # ---- internals ------------------------------------------------------

    def _rdzv_addr(self):
        hosts = [util.HostInfo(h, s)
                 for h, s in self._manager.current_hosts.items()]
        return util.resolvable_addr_for(hosts)

    def _monitor(self):
        while not self._shutdown.is_set():
            time.sleep(self._poll_interval)
            try:
                changed, added, removed = \
                    self._manager.update_available_hosts()
            except Exception as e:  # discovery script hiccup: keep last view
                if self._verbose:
                    print(f"[elastic driver] discovery failed: {e}",
                          file=sys.stderr)
                continue
            rereg = self._rendezvous.take_reregistrations()
            # _reconcile_needed marks an explicit retry request (worker
            # failure, cut timeout, min_np guard) whose epoch was never
            # published — those must cut even if the fleet looks
            # unchanged, so they count like a pending re-registration.
            needed = self._reconcile_needed.is_set()
            if changed or rereg or needed:
                self._reconcile_needed.clear()
                self._reconcile(notify=bool(added),
                                force_cut=bool(rereg) or needed)

    def _spawn(self, host, local_index):
        worker_id = f"{host}:{uuid.uuid4().hex[:8]}"
        w = _Worker(worker_id, host, local_index)

        def run():
            env = dict(os.environ)
            env.update(self._extra_env)
            env.update({
                "HOROVOD_ELASTIC": "1",
                "HOROVOD_WORKER_ID": worker_id,
                "HOROVOD_HOSTNAME": host,
                "HOROVOD_RDZV_ADDR": self._rdzv_addr(),
                "HOROVOD_RDZV_PORT": str(self._rendezvous.port),
            })
            rc = self._execute_worker(w, env)
            self._on_worker_exit(w, rc)

        w.thread = threading.Thread(target=run, daemon=True)
        with self._lock:
            self._workers[worker_id] = w
        w.thread.start()
        return w

    def _execute_worker(self, worker, env):
        """Launch one worker and block until it exits; return its exit
        code. The default backend execs ``self._command`` as an OS
        process (locally or over ssh). Actor-based executors (the Ray
        elastic executor) override this — the rest of the driver
        (discovery, reconcile, rendezvous, epoch cuts) is backend-
        agnostic. Implementations must honor ``worker.kill_event`` and
        ``self._shutdown``."""
        if util.is_local_host(worker.host):
            cmd = list(self._command)
        else:
            exports = " ".join(
                f"{k}={shlex.quote(v)}" for k, v in sorted(env.items())
                if k.startswith("HOROVOD_"))
            cmd = ["ssh", "-o", "StrictHostKeyChecking=no", worker.host,
                   f"cd {shlex.quote(os.getcwd())} && env {exports} "
                   + " ".join(shlex.quote(c) for c in self._command)]
        return safe_shell_exec.execute(
            cmd, env=env,
            prefix=f"[{worker.worker_id}]: " if self._verbose else b"",
            events=[worker.kill_event, self._shutdown])

    def _on_worker_exit(self, worker, rc):
        worker.exit_code = rc
        with self._lock:
            self._workers.pop(worker.worker_id, None)
            self._rendezvous.forget_worker(worker.worker_id)
            if not worker.driver_killed:
                self._final_codes.append(rc)
        if worker.driver_killed:
            # Deliberate kill (host removed / slot shrunk): not a failure
            # — must not count toward blacklisting or the job's exit code.
            return
        if rc == 0:
            # Clean finish: the job is completing; let peers finish too.
            return
        if self._shutdown.is_set():
            return
        n = self._host_failures[worker.host] = \
            self._host_failures.get(worker.host, 0) + 1
        if n >= _FAILURES_TO_BLACKLIST:
            self._manager.blacklist(worker.host)
        self._reconcile_needed.set()

    def _reconcile(self, notify=False, force_cut=False):
        """Match the fleet to the current host view and cut a new epoch."""
        # The upcoming cut covers any pending re-registrations; drain them
        # so the monitor doesn't cut a second (ghost) epoch for the same
        # recovery.
        force_cut = bool(self._rendezvous.take_reregistrations()) \
            or force_cut
        with self._lock:
            fleet_done = (not self._workers and self._final_codes
                          and all(c == 0 for c in self._final_codes))
        if fleet_done:
            # Everyone exited cleanly: the job is complete; never respawn
            # a fresh fleet into the free slots (it would re-run the job).
            return
        with self._lock:
            hosts = self._manager.current_hosts
            # Kill workers whose host vanished; on slot-count decrease
            # kill only the EXCESS count, youngest first — the oldest
            # workers hold the committed state that rank 0's sync()
            # broadcasts, so they must survive a shrink.
            killed = 0

            def _kill(w):
                nonlocal killed
                w.driver_killed = True
                w.kill_event.set()
                self._workers.pop(w.worker_id, None)
                self._rendezvous.forget_worker(w.worker_id)
                killed += 1

            per_host = {}
            for w in list(self._workers.values()):
                if w.host not in hosts:
                    _kill(w)
                else:
                    per_host.setdefault(w.host, []).append(w)
            for host, ws in per_host.items():
                ws.sort(key=lambda w: w.seq)
                for w in ws[hosts[host]:]:  # youngest beyond capacity
                    _kill(w)
            # Spawn into FREE slot indexes (a respawn reuses the slot its
            # predecessor freed), up to max_np total. A host's LIVE worker
            # count — not its free indexes — bounds spawning: after a
            # fail→respawn→shrink history a surviving oldest worker can
            # occupy local_index >= slots, leaving a lower index free on a
            # host that is already at capacity; filling it would publish
            # local_size > slots and double-bind chips.
            used = {}
            for w in self._workers.values():
                used.setdefault(w.host, set()).add(w.local_index)
            total = sum(len(s) for s in used.values())
            spawned = 0
            for host, slots in sorted(hosts.items()):
                for idx in range(slots):
                    if idx in used.get(host, set()):
                        continue
                    if len(used.get(host, ())) >= slots:
                        break
                    if total >= self._max_np:
                        break
                    self._spawn(host, idx)
                    used.setdefault(host, set()).add(idx)
                    total += 1
                    spawned += 1
            alive = list(self._workers.values())
        if total < self._min_np:
            if self._verbose:
                print(f"[elastic driver] {total} workers < min_np="
                      f"{self._min_np}; waiting for discovery",
                      file=sys.stderr)
            return
        if not spawned and not killed and not force_cut:
            # Nothing about the fleet changed (e.g. a discovery delta
            # while at max_np). Cutting anyway would publish a ghost
            # epoch: a later recovery would re-register with a stale
            # last_epoch, adopt the dead assignment, and burn a full
            # start-timeout round before the real recovery epoch.
            return
        if notify and spawned:
            # Notify only when capacity growth actually ADDED workers: at
            # max_np the discovery delta is unusable, and a notification
            # would tear the whole fleet down for an identically-sized
            # epoch (minutes of TPU re-init for nothing).
            registered = self._rendezvous.registered_workers()
            for w in alive:
                info = registered.get(w.worker_id)
                if info and info.get("notify_port"):
                    notify_worker(w.host if not util.is_local_host(w.host)
                                  else "127.0.0.1", info["notify_port"])
        self._cut_epoch(alive)

    def _cut_epoch(self, workers):
        """Wait for registrations, then publish rank assignments."""
        deadline = time.monotonic() + self._start_timeout
        ids = {w.worker_id for w in workers}
        while time.monotonic() < deadline:
            registered = set(self._rendezvous.registered_workers())
            with self._lock:
                ids &= set(self._workers)  # drop workers that died meanwhile
            if not ids:
                break  # whole cohort exited; fall through to the guard
            if ids <= registered:
                break
            time.sleep(0.1)
        else:
            # Registration timeout: retry the cut only if something
            # actually failed (same rationale as the min_np guard below).
            with self._lock:
                if any(c != 0 for c in self._final_codes):
                    self._reconcile_needed.set()
            return
        with self._lock:
            workers = [self._workers[i] for i in sorted(ids)
                       if i in self._workers]
        if len(workers) < self._min_np:
            # Workers vanished while we were waiting for registrations. A
            # smaller-than-min_np epoch must never be published (it would
            # split the job into an undersized world that trains alone) —
            # but only re-reconcile if something actually FAILED; clean
            # rc==0 exits mean the job is completing, and respawning
            # would re-run the finished job.
            with self._lock:
                any_failed = any(c != 0 for c in self._final_codes)
            if any_failed:
                self._reconcile_needed.set()
            return
        # Rank layout: host-major (hierarchical allreduce requires ranks
        # contiguous per host), with hosts ordered by their oldest
        # member's spawn age and workers within a host oldest-first — so
        # rank 0 is always a SURVIVOR (its state snapshot is what sync()
        # broadcasts; a fresh respawn as rank 0 would wipe committed
        # progress with untrained weights).
        workers.sort(key=lambda w: (w.seq, w.local_index))
        host_order = {}
        for w in workers:
            host_order.setdefault(w.host, len(host_order))
        workers.sort(key=lambda w: (host_order[w.host], w.seq,
                                    w.local_index))
        by_host = {}
        for w in workers:
            by_host.setdefault(w.host, []).append(w)
        # cross_rank must agree with the rank layout above (operations.cc
        # hierarchical probe: cross_rank == rank / local_size), so order
        # hosts exactly as the layout does.
        hostnames = sorted(by_host, key=lambda h: host_order[h])
        root_host = workers[0].host
        controller_addr = ("127.0.0.1" if util.is_local_host(root_host)
                           else root_host)
        controller_port = util.free_port()
        assignments = {}
        for rank, w in enumerate(workers):
            local = by_host[w.host]
            assignments[w.worker_id] = {
                "rank": rank,
                "size": len(workers),
                "local_rank": local.index(w),
                "local_size": len(local),
                "cross_rank": hostnames.index(w.host),
                "cross_size": len(hostnames),
                "controller_addr": controller_addr,
                "controller_port": controller_port,
            }
        epoch = self._rendezvous.start_epoch(assignments)
        # Survivors that re-registered while we waited for respawn
        # registrations are satisfied by the epoch just published — drain
        # their flags so the monitor doesn't cut a ghost epoch for them.
        self._rendezvous.take_reregistrations(satisfied_by=epoch)
        with self._lock:
            # Success is judged on the FINAL epoch only: a worker that died
            # and was recovered from must not fail the whole job.
            self._final_codes.clear()
        if self._verbose:
            print(f"[elastic driver] epoch {epoch}: "
                  f"{[w.worker_id for w in workers]}", file=sys.stderr)
