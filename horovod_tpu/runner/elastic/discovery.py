"""Host discovery for elastic jobs.

Reference analog: ``horovod/runner/elastic/discovery.py``
(HostDiscoveryScript, HostManager) — a user-supplied executable prints the
current worker hosts, one ``hostname:slots`` per line; the driver polls it
and reacts to adds/removes. Hosts that repeatedly fail are blacklisted.
"""

import subprocess
import threading


class HostDiscoveryScript:
    """Runs the user's discovery executable and parses host:slots lines."""

    def __init__(self, script, default_slots=1):
        self.script = script
        self.default_slots = default_slots

    def find_available_hosts_and_slots(self):
        out = subprocess.run([self.script], capture_output=True, text=True,
                             timeout=60, check=True).stdout
        hosts = {}
        for line in out.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if ":" in line:
                name, slots = line.rsplit(":", 1)
                hosts[name] = int(slots)
            else:
                hosts[line] = self.default_slots
        return hosts


class FixedHosts:
    """Static 'discovery' from -H/--hostfile (elastic min/max without a
    script degenerates to failure recovery over a fixed pool)."""

    def __init__(self, hosts):
        self._hosts = dict(hosts)

    def find_available_hosts_and_slots(self):
        return dict(self._hosts)


class HostManager:
    """Tracks the live host set and failure blacklist.

    Reference analog: discovery.HostManager (current_hosts, blacklist).
    """

    def __init__(self, discovery):
        self._discovery = discovery
        self._lock = threading.Lock()
        self._blacklist = set()
        self._current = {}

    def update_available_hosts(self):
        """Re-run discovery; returns (changed, added, removed). ``added``
        lists hosts whose capacity GREW — a brand-new host or extra slots
        on a known one both count (workers must be notified either way,
        or they keep training at the old size while new slots idle)."""
        found = self._discovery.find_available_hosts_and_slots()
        with self._lock:
            found = {h: s for h, s in found.items()
                     if h not in self._blacklist}
            added = sorted(h for h, s in found.items()
                           if s > self._current.get(h, 0))
            removed = sorted(set(self._current) - set(found))
            changed = bool(added or removed) or found != self._current
            self._current = found
            return changed, added, removed

    def blacklist(self, host):
        with self._lock:
            self._blacklist.add(host)
            self._current.pop(host, None)

    def is_blacklisted(self, host):
        with self._lock:
            return host in self._blacklist

    @property
    def current_hosts(self):
        with self._lock:
            return dict(self._current)

    def slot_count(self):
        with self._lock:
            return sum(self._current.values())
