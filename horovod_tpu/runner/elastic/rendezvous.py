"""HTTP rendezvous: workers register and poll for epoch rank assignments.

Reference analog: ``horovod/runner/http/http_server.py`` (RendezvousServer,
the KVStore handler) + ``runner/elastic/rendezvous.py``
(ElasticRendezvousServer). One server per job, driver-side; each elastic
reset bumps the epoch and re-assigns ranks. Also exposes a generic /kv
store, as the reference's Gloo rendezvous does.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class _State:
    def __init__(self):
        self.lock = threading.Lock()
        self.kv = {}
        # worker_id -> info dict (host, local_rank, notify_port, epoch seen)
        self.workers = {}
        self.epoch = 0
        # epoch -> {worker_id -> assignment dict}
        self.assignments = {}
        # Workers that re-registered after already being known: an alive
        # worker re-entering rendezvous (in-process recovery) — the
        # driver must cut a fresh epoch for them even though no process
        # exited.
        self.reregistered = set()


class _Handler(BaseHTTPRequestHandler):
    state = None  # injected by RendezvousServer

    def log_message(self, *args):  # quiet
        pass

    def _send(self, code, payload=None):
        body = b"" if payload is None else json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self):
        n = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(n) or b"{}")

    def do_PUT(self):
        if self.path.startswith("/kv/"):
            with self.state.lock:
                self.state.kv[self.path[4:]] = self._body()
            return self._send(200)
        return self._send(404)

    def do_POST(self):
        if self.path == "/register":
            info = self._body()
            with self.state.lock:
                if info["worker_id"] in self.state.workers \
                        and info.get("last_epoch", 0) >= self.state.epoch:
                    # A known worker that already consumed the current
                    # epoch is waiting for a NEW one: in-process recovery.
                    # (A re-register with last_epoch < current will be
                    # satisfied by the already-published epoch.)
                    self.state.reregistered.add(info["worker_id"])
                self.state.workers[info["worker_id"]] = info
            return self._send(200)
        return self._send(404)

    def do_GET(self):
        if self.path.startswith("/kv/"):
            with self.state.lock:
                val = self.state.kv.get(self.path[4:])
            return self._send(404 if val is None else 200, val)
        if self.path.startswith("/assignment/"):
            worker_id = self.path[len("/assignment/"):]
            with self.state.lock:
                cur = self.state.assignments.get(self.state.epoch, {})
                asg = cur.get(worker_id)
            # 202: registered but this epoch's assignment isn't cut yet.
            return self._send(202 if asg is None else 200, asg)
        if self.path == "/workers":
            with self.state.lock:
                return self._send(200, self.state.workers)
        return self._send(404)


class RendezvousServer:
    """Driver-side registry + rank assignment service."""

    def __init__(self, addr="0.0.0.0"):
        self._state = _State()
        handler = type("Handler", (_Handler,), {"state": self._state})
        self._httpd = ThreadingHTTPServer((addr, 0), handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def port(self):
        return self._httpd.server_address[1]

    def registered_workers(self):
        with self._state.lock:
            return dict(self._state.workers)

    def forget_worker(self, worker_id):
        with self._state.lock:
            self._state.workers.pop(worker_id, None)
            self._state.reregistered.discard(worker_id)

    def take_reregistrations(self, satisfied_by=None):
        """Drain and return worker ids that re-registered while alive
        (in-process recovery awaiting a fresh epoch). With
        ``satisfied_by=N``, drain only workers whose awaited epoch is
        covered by the just-published epoch N (keep ones that failed
        again and already need something newer)."""
        with self._state.lock:
            if satisfied_by is None:
                out = set(self._state.reregistered)
            else:
                out = {w for w in self._state.reregistered
                       if self._state.workers.get(w, {})
                       .get("last_epoch", 0) < satisfied_by}
            self._state.reregistered -= out
            return out

    def start_epoch(self, assignments):
        """Publish a new epoch's worker_id -> assignment map; workers polling
        /assignment see it immediately. Returns the epoch number."""
        with self._state.lock:
            self._state.epoch += 1
            for asg in assignments.values():
                asg["epoch"] = self._state.epoch
            self._state.assignments[self._state.epoch] = dict(assignments)
            return self._state.epoch

    @property
    def epoch(self):
        with self._state.lock:
            return self._state.epoch

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


class RendezvousClient:
    """Worker-side helper for register + assignment polling + KV."""

    def __init__(self, addr, port):
        self.addr = addr
        self.port = int(port)

    def _url(self, path):
        return f"http://{self.addr}:{self.port}{path}"

    def _request(self, method, path, payload=None):
        import urllib.request

        data = None if payload is None else json.dumps(payload).encode()
        req = urllib.request.Request(self._url(path), data=data,
                                     method=method)
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                body = resp.read()
                return resp.status, json.loads(body) if body else None
        except urllib.error.HTTPError as e:  # non-2xx still carries status
            return e.code, None

    def register(self, worker_id, host, local_rank, notify_port,
                 last_epoch=0):
        """``last_epoch`` is the newest epoch this worker has consumed;
        the driver cuts a fresh epoch only for workers that already
        consumed the current one (true in-process recovery), so late
        re-registrations don't produce ghost epochs."""
        code, _ = self._request("POST", "/register", {
            "worker_id": worker_id, "host": host,
            "local_rank": local_rank, "notify_port": notify_port,
            "last_epoch": int(last_epoch)})
        if code != 200:
            raise RuntimeError(f"rendezvous register failed: HTTP {code}")

    def poll_assignment(self, worker_id, timeout, min_epoch=0,
                        interval=0.25):
        """Block until this worker's assignment for an epoch >= min_epoch is
        published; returns the assignment dict.

        min_epoch matters on re-rendezvous: a worker that detected a peer
        failure before the driver did must NOT re-adopt the still-published
        old epoch (it references the dead worker and a stale controller
        endpoint), or it would block forever in controller bootstrap.
        """
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            code, asg = self._request("GET", f"/assignment/{worker_id}")
            if code == 200 and asg.get("epoch", 0) >= min_epoch:
                return asg
            time.sleep(interval)
        raise TimeoutError(
            f"no rendezvous assignment for {worker_id} (epoch >= "
            f"{min_epoch}) within {timeout}s")

    def kv_put(self, key, value):
        self._request("PUT", f"/kv/{key}", value)

    def kv_get(self, key):
        code, val = self._request("GET", f"/kv/{key}")
        return val if code == 200 else None
