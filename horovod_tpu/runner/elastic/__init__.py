"""Elastic launch: driver, host discovery, rendezvous, notifications.

Reference analog: ``horovod/runner/elastic/`` (ElasticDriver,
HostDiscoveryScript, ElasticRendezvousServer, WorkerNotificationService —
SURVEY.md §2.4, §3.4).
"""

from horovod_tpu.runner.elastic.discovery import (  # noqa: F401
    HostDiscoveryScript,
    HostManager,
)
from horovod_tpu.runner.elastic.driver import ElasticDriver  # noqa: F401
from horovod_tpu.runner.elastic.rendezvous import (  # noqa: F401
    RendezvousClient,
    RendezvousServer,
)
