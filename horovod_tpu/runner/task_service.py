"""Pre-launch NIC discovery — task side.

Reference analog: ``horovod/runner/task/task_service.py``: runs briefly on
every job host before launch. Enumerates local interface addresses,
starts a probe listener, registers with the driver, fetches the full
address table, TCP-probes every other task's candidates, and reports what
was reachable. See ``driver_service.py`` for the protocol.
"""

import socket
import threading
import time

from horovod_tpu.runner.driver_service import recv_msg, send_msg


def local_addresses(port):
    """All non-loopback IPv4 addresses of this host (+ loopback fallback).

    Reference uses psutil.net_if_addrs(); we use getaddrinfo on the
    hostname plus a UDP-connect trick, dependency-free.
    """
    addrs = set()
    try:
        for info in socket.getaddrinfo(socket.gethostname(), None,
                                       socket.AF_INET):
            addrs.add(info[4][0])
    except OSError:
        pass
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        addrs.add(s.getsockname()[0])
    except OSError:
        pass
    finally:
        s.close()
    addrs.discard("127.0.0.1")
    if not addrs:
        addrs.add("127.0.0.1")
    return [(a, port) for a in sorted(addrs)]


class HorovodRunTaskService:
    """One per host. start() → registers + probes; runs in-thread."""

    def __init__(self, index, driver_addr, key, probe_timeout=2.0):
        self._index = index
        self._driver_addr = tuple(driver_addr)
        self._key = key
        self._probe_timeout = probe_timeout
        # _stopped must exist before the accept thread can observe self.
        self._stopped = False
        # Probe listener: plain TCP accept; connectability is the test.
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("0.0.0.0", 0))
        self._listener.listen(64)
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    @property
    def listen_port(self):
        return self._listener.getsockname()[1]

    def _accept_loop(self):
        while not self._stopped:
            try:
                conn, _ = self._listener.accept()
                conn.close()
            except OSError:
                return

    def _rpc(self, obj):
        with socket.create_connection(self._driver_addr, timeout=10) as s:
            send_msg(s, obj, self._key)
            f = s.makefile("rb")
            return recv_msg(f, self._key)

    def register(self):
        return self._rpc({"type": "register", "index": self._index,
                          "host": socket.gethostname(),
                          "addrs": local_addresses(self.listen_port)})

    def wait_for_table(self, timeout=60):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            reply = self._rpc({"type": "addr_table"})
            if reply and reply.get("type") == "table":
                return {int(k): v for k, v in reply["table"].items()}
            time.sleep(0.2)
        raise TimeoutError("driver never published the address table")

    def probe(self, table):
        """TCP-connect to every other task's candidate addrs; report
        which were reachable."""
        reachable = {}
        for other, info in table.items():
            if other == self._index:
                continue
            ok = []
            for ip, port in info["addrs"]:
                try:
                    with socket.create_connection(
                            (ip, port), timeout=self._probe_timeout):
                        ok.append(ip)
                except OSError:
                    pass
            reachable[other] = ok
        self._rpc({"type": "probe_result", "index": self._index,
                   "reachable": reachable})
        return reachable

    def run_discovery(self, timeout=60):
        """The full task-side flow (reference: task service main)."""
        self.register()
        table = self.wait_for_table(timeout)
        return self.probe(table)

    def shutdown(self):
        self._stopped = True
        try:
            self._listener.close()
        except OSError:
            pass


def discover_common_interfaces(num_hosts, services_spawner, timeout=60):
    """Drive a full discovery round in-process (used by tests and by the
    launcher's local multi-slot mode)."""
    from horovod_tpu.runner.driver_service import HorovodRunDriverService

    driver = HorovodRunDriverService(num_hosts)
    try:
        tasks = services_spawner(driver)
        threads = [threading.Thread(target=t.run_discovery, daemon=True)
                   for t in tasks]
        for t in threads:
            t.start()
        driver.wait_for_initial_registration(timeout)
        driver.wait_for_probe_results(timeout)
        for t in threads:
            t.join(timeout)
        return driver.get_common_interfaces()
    finally:
        driver.shutdown()
