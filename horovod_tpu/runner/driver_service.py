"""Pre-launch NIC discovery — driver side.

Reference analog: ``horovod/runner/driver/driver_service.py``
(``HorovodRunDriverService`` + ``_driver_fn``): before the real job
starts, a tiny task service is launched on every host; each registers its
network interfaces with this driver, the driver distributes the full
address table, every task probes every other task's candidate addresses,
and the driver intersects the results into the set of interfaces that are
routable from ALL hosts. That set drives ``HOROVOD_GLOO_IFACE``-style
binding so the control plane never picks a dead NIC.

Protocol: newline-delimited JSON over TCP, HMAC-authenticated with the
job secret (reference: ``runner/common/util/secret.py``).
"""

import hmac
import hashlib
import json
import os
import socket
import socketserver
import threading


def make_secret_key():
    """Reference: secret.make_secret_key() — per-job HMAC key."""
    return os.urandom(32).hex()


def sign(key, payload_bytes):
    return hmac.new(key.encode(), payload_bytes, hashlib.sha256).hexdigest()


def send_msg(sock, obj, key):
    body = json.dumps(obj, sort_keys=True).encode()
    frame = json.dumps({"mac": sign(key, body)}).encode() + b"\n" + body + b"\n"
    sock.sendall(frame)


def recv_msg(f, key):
    header = f.readline()
    body = f.readline()
    if not header or not body:
        return None
    mac = json.loads(header)["mac"]
    if not hmac.compare_digest(mac, sign(key, body.rstrip(b"\n"))):
        raise PermissionError("bad message HMAC (wrong job secret?)")
    return json.loads(body)


class HorovodRunDriverService:
    """Collects task registrations, orchestrates cross-host probing, and
    exposes the common routable interface set."""

    def __init__(self, num_hosts, key=None):
        self._num_hosts = num_hosts
        self._key = key or make_secret_key()
        self._registered = {}      # index -> {"host":…, "addrs":[(ip,port)…]}
        self._probe_results = {}   # index -> {other_index: [reachable addrs]}
        self._cv = threading.Condition()
        svc = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    msg = recv_msg(self.rfile, svc._key)
                except PermissionError:
                    return
                if msg is None:
                    return
                reply = svc._dispatch(msg)
                if reply is not None:
                    send_msg(self.connection, reply, svc._key)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server(("0.0.0.0", 0), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def key(self):
        return self._key

    @property
    def addresses(self):
        return ("127.0.0.1", self._server.server_address[1])

    @property
    def port(self):
        return self._server.server_address[1]

    def _dispatch(self, msg):
        kind = msg.get("type")
        with self._cv:
            if kind == "register":
                self._registered[msg["index"]] = {
                    "host": msg["host"], "addrs": msg["addrs"]}
                self._cv.notify_all()
                return {"type": "ack"}
            if kind == "addr_table":
                # Task polls for the full table once everyone registered.
                if len(self._registered) < self._num_hosts:
                    return {"type": "wait"}
                return {"type": "table",
                        "table": {str(k): v for k, v in
                                  self._registered.items()}}
            if kind == "probe_result":
                self._probe_results[msg["index"]] = {
                    int(k): v for k, v in msg["reachable"].items()}
                self._cv.notify_all()
                return {"type": "ack"}
        return {"type": "error", "error": f"unknown message {kind!r}"}

    def wait_for_initial_registration(self, timeout=60):
        with self._cv:
            ok = self._cv.wait_for(
                lambda: len(self._registered) >= self._num_hosts, timeout)
        if not ok:
            missing = self._num_hosts - len(self._registered)
            raise TimeoutError(
                f"{missing} task service(s) never registered with the "
                f"driver within {timeout}s")

    def wait_for_probe_results(self, timeout=60):
        with self._cv:
            ok = self._cv.wait_for(
                lambda: len(self._probe_results) >= self._num_hosts, timeout)
        if not ok:
            raise TimeoutError("probe results incomplete")

    def get_common_interfaces(self):
        """Addresses of each host reachable from EVERY other host:
        {index: [ip, ...]}. Reference: _driver_fn's set intersection."""
        common = {}
        for target, info in self._registered.items():
            addrs = {a[0] for a in info["addrs"]}
            for prober, results in self._probe_results.items():
                if prober == target:
                    continue
                addrs &= set(results.get(target, []))
            common[target] = sorted(addrs)
        return common

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()
