"""``horovodrun`` — the launcher CLI.

Reference analog: ``horovod/runner/launch.py`` (run_commandline /
parse_args / _run) + ``gloo_run.py``: compute rank layout from
-np/-H/--hostfile, export the HOROVOD_* env contract, spawn one process
per slot (ssh for remote hosts), stream rank-prefixed output, tear the
job down if any rank fails.

TPU-pod mode (net-new): ``--tpu-pod`` maps one rank per local TPU chip
and pins each rank to its chip via JAX's PJRT process env so the eager
control plane coexists with per-chip XLA compute.
"""

import argparse
import os
import shlex
import sys
import threading

from horovod_tpu.runner import util
from horovod_tpu.runner import safe_shell_exec
from horovod_tpu.version import __version__


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="horovodrun",
        description="Launch a horovod_tpu distributed job.")
    p.add_argument("-v", "--version", action="version", version=__version__)
    p.add_argument("-np", "--num-proc", type=int, dest="np", required=False,
                   help="total number of processes")
    p.add_argument("-H", "--hosts", dest="hosts",
                   help="host1:slots,host2:slots (default: localhost:np)")
    p.add_argument("--hostfile", help="file with one 'host slots=N' per line")
    p.add_argument("-p", "--ssh-port", type=int, default=None)
    p.add_argument("--ssh-identity-file", default=None)
    p.add_argument("--network-interface", dest="nics", default=None)
    p.add_argument("--start-timeout", type=int, default=60,
                   help="seconds to wait for ranks to register")
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--check-build", action="store_true",
                   help="print available frameworks/controllers/"
                        "tensor-operation backends and exit")
    p.add_argument("--tpu-pod", action="store_true",
                   help="one rank per local TPU chip, chips pinned per rank")
    # Controller choice (reference: --gloo / --mpi / js autodetect).
    p.add_argument("--gloo", action="store_true",
                   help="force the built-in launcher (default)")
    p.add_argument("--mpi", action="store_true",
                   help="delegate process management to mpirun")
    p.add_argument("--mpi-args", default=None,
                   help="extra arguments appended to the mpirun cmdline")
    p.add_argument("--js", action="store_true",
                   help="launch with jsrun (LSF clusters)")
    p.add_argument("--js-args", default=None,
                   help="extra arguments appended to the jsrun cmdline")
    # Elastic mode (reference: --min-np/--max-np/--host-discovery-script)
    p.add_argument("--min-np", type=int, default=None,
                   help="elastic: keep training while >= this many workers")
    p.add_argument("--max-np", type=int, default=None,
                   help="elastic: never run more than this many workers")
    p.add_argument("--host-discovery-script", default=None,
                   help="elastic: executable printing current host:slots "
                        "lines; polled for topology changes")
    p.add_argument("--slots", type=int, default=1,
                   help="elastic: default slots per discovered host")
    p.add_argument("--elastic-poll-interval", type=float, default=2.0,
                   help="elastic: seconds between discovery polls "
                        "(HOROVOD_ELASTIC_TIMEOUT analog)")
    # Tuning knobs -> env (reference: config_parser.py set_env_from_args)
    p.add_argument("--fusion-threshold-mb", type=float, default=None)
    p.add_argument("--cycle-time-ms", type=float, default=None)
    p.add_argument("--cache-capacity", type=int, default=None)
    p.add_argument("--timeline-filename", default=None)
    p.add_argument("--timeline-mark-cycles", action="store_true")
    p.add_argument("--no-stall-check", action="store_true")
    p.add_argument("--stall-check-warning-time-seconds", type=float,
                   default=None)
    p.add_argument("--log-level", default=None,
                   choices=["TRACE", "DEBUG", "INFO", "WARNING", "ERROR",
                            "FATAL"])
    p.add_argument("--autotune", action="store_true")
    p.add_argument("--autotune-log-file", default=None)
    p.add_argument("--hierarchical-allreduce", action="store_true",
                   help="legacy spelling of --cross-plane hier "
                        "(three-phase intra/inter-slice allreduce)")
    p.add_argument("--cross-plane", default=None,
                   choices=["auto", "ici", "ring", "hier"],
                   help="plane selection for collectives "
                        "(HOROVOD_CROSS_PLANE, docs/redistribute.md): "
                        "auto composes the hierarchical decomposition "
                        "on eligible layouts; ring pins the flat host "
                        "ring; hier requires the decomposition")
    p.add_argument("--config-file", default=None,
                   help="YAML file of the above knobs")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="program and args to launch on every rank")
    args = p.parse_args(argv)
    if args.config_file:
        _apply_config_file(args)
    if args.check_build:
        _print_check_build()
        raise SystemExit(0)
    if not args.command:
        p.error("no command given")
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    if args.min_np or args.max_np or args.host_discovery_script:
        if args.np is None:
            args.np = args.min_np
        if args.min_np is None:
            args.min_np = args.np
        if args.np is None:
            p.error("elastic mode needs -np or --min-np")
    elif args.np is None and not args.tpu_pod and not (
            args.js or "LSB_JOBID" in os.environ):
        # jsrun mode derives np from the LSF allocation (LSB_MCPU_HOSTS).
        p.error("-np is required (or use --tpu-pod)")
    return args


def is_elastic(args):
    return bool(args.min_np or args.max_np or args.host_discovery_script)


def _apply_config_file(args):
    """YAML config: CLI takes precedence (reference: config_parser.py)."""
    import yaml

    with open(args.config_file) as f:
        cfg = yaml.safe_load(f) or {}
    for key, value in cfg.items():
        attr = key.replace("-", "_")
        if hasattr(args, attr) and getattr(args, attr) in (None, False):
            setattr(args, attr, value)


def env_from_args(args):
    """The HOROVOD_* tuning env contract (reference keeps CLI/env/YAML in
    sync — SURVEY.md §5.6)."""
    env = {}
    if args.fusion_threshold_mb is not None:
        env["HOROVOD_FUSION_THRESHOLD"] = str(
            int(args.fusion_threshold_mb * 1024 * 1024))
    if args.cycle_time_ms is not None:
        env["HOROVOD_CYCLE_TIME"] = str(args.cycle_time_ms)
    if args.cache_capacity is not None:
        env["HOROVOD_CACHE_CAPACITY"] = str(args.cache_capacity)
    if args.timeline_filename:
        env["HOROVOD_TIMELINE"] = args.timeline_filename
    if args.timeline_mark_cycles:
        env["HOROVOD_TIMELINE_MARK_CYCLES"] = "1"
    if args.no_stall_check:
        env["HOROVOD_STALL_CHECK_DISABLE"] = "1"
    if args.stall_check_warning_time_seconds is not None:
        env["HOROVOD_STALL_CHECK_TIME"] = str(
            args.stall_check_warning_time_seconds)
    if args.log_level:
        env["HOROVOD_LOG_LEVEL"] = args.log_level
    if args.autotune:
        env["HOROVOD_AUTOTUNE"] = "1"
    if args.autotune_log_file:
        env["HOROVOD_AUTOTUNE_LOG"] = args.autotune_log_file
    if os.environ.get("HOROVOD_AUTOTUNE_STEPS"):
        # Not a CLI flag, but it must still reach remote (ssh) ranks —
        # only the coordinator reads it.
        env["HOROVOD_AUTOTUNE_STEPS"] = os.environ["HOROVOD_AUTOTUNE_STEPS"]
    if args.hierarchical_allreduce:
        env["HOROVOD_HIERARCHICAL_ALLREDUCE"] = "1"
    if args.cross_plane:
        env["HOROVOD_CROSS_PLANE"] = args.cross_plane
    if args.nics:
        env["HOROVOD_GLOO_IFACE"] = args.nics
    return env


def _print_check_build():
    """Reference analog: ``horovodrun --check-build`` — what this build
    supports, probed live (frameworks by import, backends from the
    capability API)."""
    from horovod_tpu.common.basics import HorovodBasics
    from horovod_tpu.version import __version__

    def have(mod):
        import importlib.util

        try:
            return importlib.util.find_spec(mod) is not None
        except (ImportError, ModuleNotFoundError, ValueError):
            return False

    b = HorovodBasics()
    box = lambda v: "[X]" if v else "[ ]"  # noqa: E731
    print(f"horovod_tpu v{__version__}:\n")
    print("Available Frameworks:")
    for label, mod in (("JAX", "jax"), ("PyTorch", "torch"),
                      ("TensorFlow", "tensorflow"), ("MXNet", "mxnet")):
        print(f"    {box(have(mod))} {label}")
    print("\nAvailable Controllers:")
    print(f"    {box(b.gloo_built())} TCP (gloo-style rendezvous)")
    print(f"    {box(b.mpi_built())} MPI / Slurm / LSF env pickup")
    print("\nAvailable Tensor Operations:")
    print(f"    {box(b.gloo_built())} host ring (TCP)")
    print(f"    {box(b.xla_built())} xla_ici device plane (TPU/ICI)")
    tf_native = b.tf_native_ops_built()
    tf_note = "" if tf_native or not b.tf_native_ops_buildable() \
        else "  (not built; buildable on demand: make tf)"
    print(f"    {box(tf_native)} TF native ops "
          f"(in-jit XLA collectives){tf_note}")
    print(f"    {box(b.nccl_built())} NCCL")
    print(f"    {box(b.cuda_built())} CUDA")
    print(f"    {box(b.rocm_built())} ROCm")
    print(f"    {box(b.ccl_built())} oneCCL")
    print(f"    {box(b.ddl_built())} DDL")


def _tpu_pod_np():
    """Rank count for --tpu-pod: one per local chip."""
    import jax

    return len(jax.local_devices())


def _slot_env(slot, controller_addr, controller_port, tpu_pod,
              local=True):
    env = {
        "HOROVOD_RANK": str(slot.rank),
        "HOROVOD_SIZE": str(slot.size),
        "HOROVOD_LOCAL_RANK": str(slot.local_rank),
        "HOROVOD_LOCAL_SIZE": str(slot.local_size),
        "HOROVOD_CROSS_RANK": str(slot.cross_rank),
        "HOROVOD_CROSS_SIZE": str(slot.cross_size),
        "HOROVOD_CONTROLLER_ADDR": controller_addr,
        "HOROVOD_CONTROLLER_PORT": str(controller_port),
        # OpenMPI-compatible aliases many scripts read:
        "OMPI_COMM_WORLD_RANK": str(slot.rank),
        "OMPI_COMM_WORLD_SIZE": str(slot.size),
        "OMPI_COMM_WORLD_LOCAL_RANK": str(slot.local_rank),
    }
    if tpu_pod:
        plat = os.environ.get("JAX_PLATFORMS", "")
        # The launcher's JAX_PLATFORMS describes only ITS host: a local
        # slot with a non-libtpu PJRT plugin active (e.g. a tunneled dev
        # chip) must not get the libtpu chip-binding vars (they break the
        # plugin's registration and binding doesn't apply). Remote slots
        # are assumed libtpu TPU hosts and always get rank-per-chip
        # binding (SURVEY.md §7 step 3).
        if not local or not plat or "tpu" in plat.split(","):
            env["TPU_VISIBLE_DEVICES"] = str(slot.local_rank)
            env["TPU_PROCESS_BOUNDS"] = "1,1,1"
            env["JAX_LOCAL_DEVICE_IDS"] = str(slot.local_rank)
    return env


def _ssh_wrap(slot, command_env, command, ssh_port, identity_file):
    """Build the ssh command line for a remote slot (reference:
    gloo_run.get_remote_command)."""
    exports = " ".join(f"{k}={shlex.quote(v)}"
                       for k, v in sorted(command_env.items()))
    ssh = ["ssh", "-o", "StrictHostKeyChecking=no"]
    if ssh_port:
        ssh += ["-p", str(ssh_port)]
    if identity_file:
        ssh += ["-i", identity_file]
    remote = f"cd {shlex.quote(os.getcwd())} && env {exports} " \
             f"{' '.join(shlex.quote(c) for c in command)}"
    return ssh + [slot.hostname, remote]


def run_elastic(args):
    """Elastic launch (reference: launch.py _run_elastic + gloo_run
    elastic path): driver + discovery + rendezvous instead of a static
    slot layout."""
    from horovod_tpu.runner.elastic.discovery import (
        FixedHosts,
        HostDiscoveryScript,
    )
    from horovod_tpu.runner.elastic.driver import ElasticDriver

    if args.host_discovery_script:
        discovery = HostDiscoveryScript(args.host_discovery_script,
                                        default_slots=args.slots)
    else:
        hosts = (util.parse_hostfile(args.hostfile) if args.hostfile
                 else util.parse_hosts(args.hosts or f"localhost:{args.np}"))
        discovery = FixedHosts({h.hostname: h.slots for h in hosts})

    driver = ElasticDriver(
        discovery, args.command, min_np=args.min_np,
        max_np=args.max_np or args.np, poll_interval=args.elastic_poll_interval,
        start_timeout=args.start_timeout, env=env_from_args(args),
        verbose=args.verbose)
    driver.start()
    try:
        return driver.wait_for_completion()
    finally:
        driver.stop()


def run_controller(args):
    """Choose the launch backend (reference: launch.py run_controller —
    explicit flag wins; LSF allocation implies jsrun; default built-in)."""
    from horovod_tpu.runner.js_run import LSFUtils, js_available

    if args.mpi and args.js:
        raise ValueError("--mpi and --js are mutually exclusive")
    if is_elastic(args):
        if args.mpi or args.js:
            raise ValueError(
                "elastic mode needs the built-in launcher (worker respawn "
                "is driven by the elastic driver, not mpirun/jsrun)")
        return "gloo"
    if args.mpi:
        return "mpi"
    if args.js or (not args.gloo and LSFUtils.using_lsf() and js_available()
                   and not args.hosts and not args.hostfile):
        return "js"
    return "gloo"


def run_launcher(args):
    if args.tpu_pod and args.np is None:
        args.np = _tpu_pod_np()
    controller = run_controller(args)
    if args.np is None and controller != "js":
        # parse_args waives -np under LSF expecting the jsrun path to
        # derive it; any other backend has no allocation to read it from.
        raise SystemExit(
            "horovodrun: -np is required (only jsrun mode can derive the "
            "process count from the LSF allocation)")
    if controller == "mpi":
        from horovod_tpu.runner.mpi_run import mpi_run

        return mpi_run(args, env_from_args(args))
    if controller == "js":
        from horovod_tpu.runner.js_run import js_run

        return js_run(args, env_from_args(args))
    if is_elastic(args):
        return run_elastic(args)
    hosts = (util.parse_hostfile(args.hostfile) if args.hostfile
             else util.parse_hosts(args.hosts or f"localhost:{args.np}"))
    slots = util.get_host_assignments(hosts, args.np)
    controller_addr = util.resolvable_addr_for(hosts)
    controller_port = util.free_port()
    knob_env = env_from_args(args)

    if args.verbose:
        print(f"[horovodrun] np={args.np} hosts="
              f"{[(h.hostname, h.slots) for h in hosts]} "
              f"controller={controller_addr}:{controller_port}",
              file=sys.stderr)

    failure = threading.Event()
    rcs = [None] * args.np

    def launch_slot(slot):
        env = dict(os.environ)
        env.update(knob_env)
        slot_env = _slot_env(slot, controller_addr, controller_port,
                             args.tpu_pod,
                             local=util.is_local_host(slot.hostname))
        env.update(slot_env)
        env.setdefault("HOROVOD_START_TIMEOUT", str(args.start_timeout))
        if util.is_local_host(slot.hostname):
            cmd = list(args.command)
        else:
            cmd = _ssh_wrap(slot, {**knob_env, **slot_env}, args.command,
                            args.ssh_port, args.ssh_identity_file)
        rc = safe_shell_exec.execute(
            cmd, env=env, prefix=f"[{slot.rank}]<out>: ".encode()
            if args.verbose else b"", events=[failure])
        rcs[slot.rank] = rc
        if rc != 0:
            failure.set()

    threads = [threading.Thread(target=launch_slot, args=(s,), daemon=True)
               for s in slots]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    bad = [(r, rc) for r, rc in enumerate(rcs) if rc != 0]
    if bad:
        print(f"[horovodrun] ranks failed: {bad}", file=sys.stderr)
        return 1
    return 0


def run_commandline(argv=None):
    return run_launcher(parse_args(argv))


def main():
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()
