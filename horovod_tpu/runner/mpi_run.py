"""MPI launch path: delegate process management to ``mpirun``.

Reference analog: ``horovod/runner/mpi_run.py`` — build the
``mpirun``/``orterun`` command line (host list, ``-x`` env passthrough,
``--bind-to none --map-by slot``, Open MPI vs Spectrum MPI vs MPICH
detection) and exec it, letting MPI own rank placement and lifetimes.
Workers read ``OMPI_COMM_WORLD_RANK``-style env at ``hvd.init`` time, so
the in-process core works identically under either launcher; the
controller bootstrap address is still passed via HOROVOD_CONTROLLER_*.
"""

import os
import shlex
import subprocess
import sys

from horovod_tpu.runner import util

# Env prefixes always forwarded to workers (reference mpi_run.py keeps an
# equivalent list and adds -x for each matching var).
_FORWARD_PREFIXES = ("HOROVOD_", "JAX_", "TPU_", "XLA_", "LIBTPU_",
                     "PYTHONPATH", "PATH", "NCCL_", "LD_LIBRARY_PATH")


class MpiFlavor:
    OPENMPI = "openmpi"
    SPECTRUM = "spectrum"
    MPICH = "mpich"
    INTEL = "impi"
    UNKNOWN = "unknown"


def mpi_available(env=None):
    from shutil import which

    return which("mpirun", path=(env or os.environ).get("PATH")) is not None


def detect_mpi_flavor(version_text=None):
    """Classify the local MPI from ``mpirun --version`` output."""
    if version_text is None:
        try:
            version_text = subprocess.run(
                ["mpirun", "--version"], capture_output=True, text=True,
                timeout=10).stdout
        except (OSError, subprocess.TimeoutExpired):
            return MpiFlavor.UNKNOWN
    text = version_text.lower()
    if "open mpi" in text or "openrte" in text or "open-mpi" in text:
        return MpiFlavor.OPENMPI
    if "spectrum" in text:
        return MpiFlavor.SPECTRUM
    if "intel" in text:
        return MpiFlavor.INTEL
    if "mpich" in text or "hydra" in text:
        return MpiFlavor.MPICH
    return MpiFlavor.UNKNOWN


def build_mpi_command(np, hosts, command, env, flavor=MpiFlavor.OPENMPI,
                      ssh_port=None, extra_mpi_args=None):
    """Pure construction of the mpirun command line (unit-testable, like
    the reference's test_run.py asserts on mpi_run's cmdline).

    ``hosts``: list of HostInfo. ``env``: full worker env dict; vars
    matching _FORWARD_PREFIXES become ``-x`` args (Open MPI family) or a
    ``-genvlist`` (MPICH/Intel family).
    """
    host_arg = ",".join(f"{h.hostname}:{h.slots}" for h in hosts)
    forward = sorted(
        k for k in env
        if k.startswith(_FORWARD_PREFIXES) or k in ("PATH", "PYTHONPATH"))

    if flavor in (MpiFlavor.OPENMPI, MpiFlavor.SPECTRUM):
        cmd = ["mpirun", "--allow-run-as-root", "--tag-output",
               "-np", str(np), "-H", host_arg,
               "--bind-to", "none", "--map-by", "slot",
               "-mca", "pml", "ob1", "-mca", "btl", "^openib"]
        if ssh_port:
            cmd += ["-mca", "plm_rsh_args", f"-p {ssh_port}"]
        for k in forward:
            cmd += ["-x", k]
    else:
        # MPICH / Intel MPI / hydra family.
        cmd = ["mpirun", "-np", str(np), "-hosts",
               ",".join(h.hostname for h in hosts)]
        if forward:
            cmd += ["-genvlist", ",".join(forward)]
    if extra_mpi_args:
        cmd += shlex.split(extra_mpi_args)
    cmd += list(command)
    return cmd


def mpi_run(args, knob_env, command=None):
    """Launch via mpirun. Mirrors reference mpi_run(): build cmdline,
    merge env, os.execvpe into mpirun (it owns the process tree)."""
    if not mpi_available():
        raise RuntimeError(
            "horovodrun --mpi requested but no 'mpirun' found in PATH. "
            "Install an MPI implementation or use the default launcher.")
    if args.np is None:
        raise ValueError("--mpi requires -np (rank count is owned by mpirun)")
    hosts = (util.parse_hostfile(args.hostfile) if args.hostfile
             else util.parse_hosts(args.hosts or f"localhost:{args.np}"))
    controller_addr = util.resolvable_addr_for(hosts)
    env = dict(os.environ)
    env.update(knob_env)
    env.setdefault("HOROVOD_CONTROLLER_ADDR", controller_addr)
    env.setdefault("HOROVOD_CONTROLLER_PORT", str(util.free_port()))
    env.setdefault("HOROVOD_SIZE", str(args.np))
    cmd = build_mpi_command(
        args.np, hosts, command or args.command, env,
        flavor=detect_mpi_flavor(),
        ssh_port=args.ssh_port,
        extra_mpi_args=getattr(args, "mpi_args", None))
    if args.verbose:
        print(f"[horovodrun] mpi: {' '.join(map(shlex.quote, cmd))}",
              file=sys.stderr)
    return subprocess.call(cmd, env=env)
