"""Elastic training for the TensorFlow/Keras frontend.

Reference analog: ``horovod/tensorflow/elastic.py`` (``TensorFlowState``,
``TensorFlowKerasState``, ``run``) — commit/restore snapshots of
tf.Variables (or a whole keras model + optimizer) in host memory, rank-0
broadcast on ``sync()``, driven by the shared elastic retry loop
(``horovod_tpu/common/elastic.py``, SURVEY.md §3.4).
"""

import copy

from horovod_tpu.common import elastic as _elastic
from horovod_tpu.common.elastic import (  # noqa: F401
    ObjectState,
    State,
)

run = _elastic.run_fn
init = _elastic.init
reset = _elastic.reset
survivors = _elastic.survivors
rejoin = _elastic.rejoin


class TensorFlowState(State):
    """Elastic state over a list of ``tf.Variable`` (+ picklable attrs).

    Reference analog: hvd.elastic.TensorFlowState — snapshots variable
    values to host numpy on ``save()``, assigns them back on
    ``restore()``, and broadcasts rank 0's snapshot on ``sync()``.
    """

    def __init__(self, variables=None, **kwargs):
        super().__init__()
        self.variables = list(variables) if variables is not None else []
        self._extra_keys = list(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self.save()

    def save(self):
        self._saved = {
            "variables": [v.numpy().copy() for v in self.variables],
            "extra": {k: copy.deepcopy(getattr(self, k))
                      for k in self._extra_keys},
        }

    def restore(self):
        saved = self._saved["variables"]
        if len(saved) != len(self.variables):
            raise ValueError(
                f"saved snapshot has {len(saved)} variables but state "
                f"tracks {len(self.variables)} — the variable list must "
                "match across ranks and commits")
        for var, val in zip(self.variables, saved):
            var.assign(val)
        for k, v in self._saved["extra"].items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self):
        _elastic._sync_state(self, "elastic.tf_state")


class TensorFlowKerasState(State):
    """Elastic state for a keras model + optimizer (+ picklable attrs).

    Reference analog: hvd.elastic.TensorFlowKerasState — snapshots
    ``model.get_weights()`` and the optimizer's variables; ``sync()``
    broadcasts rank 0's snapshot so a rejoined worker starts from the
    surviving ranks' weights.
    """

    def __init__(self, model, optimizer=None, **kwargs):
        super().__init__()
        self.model = model
        self.optimizer = optimizer if optimizer is not None else getattr(
            model, "optimizer", None)
        self._extra_keys = list(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self.save()

    def _opt_vars(self):
        if self.optimizer is None:
            return []
        # keras 3 exposes .variables; keras 2 optimizers expose
        # .variables() (callable) or weights.
        vars = getattr(self.optimizer, "variables", None)
        if callable(vars):
            vars = vars()
        return list(vars) if vars is not None else []

    def save(self):
        self._saved = {
            "model": [w.copy() for w in self.model.get_weights()],
            "optimizer": [v.numpy().copy() for v in self._opt_vars()],
            "extra": {k: copy.deepcopy(getattr(self, k))
                      for k in self._extra_keys},
        }

    def restore(self):
        if self._saved["model"]:
            self.model.set_weights(
                [w.copy() for w in self._saved["model"]])
        saved_opt = self._saved["optimizer"]
        if not saved_opt:
            # Snapshot predates the optimizer's (lazy) build — nothing to
            # roll back; leave whatever slots exist rather than failing
            # recovery (mirrors the lenient empty-model branch above).
            for k, v in self._saved["extra"].items():
                setattr(self, k, copy.deepcopy(v))
            return
        opt_vars = self._opt_vars()
        if len(opt_vars) != len(saved_opt) and self.optimizer is not None:
            # A freshly-(re)joined worker may hold an unbuilt optimizer
            # (no slot variables yet) while the broadcast snapshot came
            # from a built one; build the slots, then restore.
            build = getattr(self.optimizer, "build", None)
            tvars = getattr(self.model, "trainable_variables", None)
            if callable(build) and tvars:
                try:
                    build(tvars)
                except Exception:  # noqa: BLE001 — fall through to check
                    pass
            opt_vars = self._opt_vars()
        if len(opt_vars) != len(saved_opt):
            raise ValueError(
                f"optimizer snapshot has {len(saved_opt)} variables but "
                f"the local optimizer has {len(opt_vars)}; restoring "
                "would silently diverge — ensure the optimizer is built "
                "identically on every rank")
        for var, val in zip(opt_vars, saved_opt):
            var.assign(val)
        for k, v in self._saved["extra"].items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self):
        _elastic._sync_state(self, "elastic.tf_keras_state")
