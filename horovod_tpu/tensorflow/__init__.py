"""horovod_tpu.tensorflow — the TensorFlow frontend
(``import horovod_tpu.tensorflow as hvd``).

Reference analog: ``horovod/tensorflow/__init__.py`` — init/rank/size,
collectives, ``DistributedGradientTape``, ``broadcast_variables``,
``Compression``.
"""

import tensorflow as tf

from horovod_tpu.common.process_sets import (  # noqa: F401
    ProcessSet,
    add_process_set,
    global_process_set,
    remove_process_set,
)
from horovod_tpu.common.exceptions import (  # noqa: F401
    HorovodInternalError,
    HostsUpdatedInterrupt,
)
from horovod_tpu.tensorflow.compression import Compression  # noqa: F401
from horovod_tpu.tensorflow.mpi_ops import (  # noqa: F401
    Adasum,
    Average,
    Max,
    Min,
    Product,
    ReduceOp,
    Sum,
    allgather,
    allreduce,
    alltoall,
    barrier,
    broadcast,
    broadcast_variables,
    cross_rank,
    cross_size,
    grouped_allreduce,
    init,
    is_initialized,
    join,
    local_rank,
    local_size,
    rank,
    reducescatter,
    shutdown,
    size,
    start_timeline,
    stop_timeline,
)
from horovod_tpu.tensorflow.sync_batch_norm import (  # noqa: F401
    SyncBatchNormalization,
)


class DistributedGradientTape:
    """Wrap a ``tf.GradientTape`` so ``gradient()`` returns allreduce-
    averaged gradients.

    Reference analog: hvd.DistributedGradientTape
    (horovod/tensorflow/__init__.py _DistributedGradientTape).
    """

    def __init__(self, tape, compression=Compression.none, op=Average,
                 process_set_id=0):
        self._tape = tape
        self._compression = compression
        self._op = op
        self._process_set_id = process_set_id

    def __getattr__(self, item):
        return getattr(self._tape, item)

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources,
                                    output_gradients=output_gradients)
        return self._allreduce_grads(grads)

    def _allreduce_grads(self, grads):
        flat = tf.nest.flatten(grads)
        compressed, ctxs, live_ix = [], [], []
        for i, g in enumerate(flat):
            if g is None:
                continue
            if isinstance(g, tf.IndexedSlices):
                g = tf.convert_to_tensor(g)
            c, ctx = self._compression.compress(g)
            compressed.append(c)
            ctxs.append(ctx)
            live_ix.append(i)
        from horovod_tpu.tensorflow import mpi_ops

        reduced = mpi_ops.grouped_allreduce(
            compressed, names=[f"tape.grad.{i}" for i in live_ix],
            op=self._op, process_set_id=self._process_set_id)
        out = list(flat)
        for i, r, ctx in zip(live_ix, reduced, ctxs):
            out[i] = self._compression.decompress(r, ctx)
        return tf.nest.pack_sequence_as(grads, out)

# Capability surface (reference analog: hvd.mpi_built()/gloo_built()/...).
from horovod_tpu.tensorflow.mpi_ops import (  # noqa: F401,E402
    ccl_built,
    cuda_built,
    ddl_built,
    gloo_built,
    gloo_enabled,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rocm_built,
    xla_built,
    xla_enabled,
)
