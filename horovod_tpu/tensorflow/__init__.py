"""horovod_tpu.tensorflow — the TensorFlow frontend
(``import horovod_tpu.tensorflow as hvd``).

Reference analog: ``horovod/tensorflow/__init__.py`` — init/rank/size,
collectives, ``DistributedGradientTape``, ``broadcast_variables``,
``Compression``.
"""

import itertools

import tensorflow as tf

from horovod_tpu.common.process_sets import (  # noqa: F401
    ProcessSet,
    add_process_set,
    global_process_set,
    remove_process_set,
)
from horovod_tpu.common.exceptions import (  # noqa: F401
    HorovodInternalError,
    HorovodPeerFailureError,
    HorovodWireCorruptionError,
    HostsUpdatedInterrupt,
)
from horovod_tpu.tensorflow.compression import Compression  # noqa: F401
from horovod_tpu.tensorflow.mpi_ops import (  # noqa: F401
    Adasum,
    Average,
    Max,
    Min,
    Product,
    ReduceOp,
    Sum,
    allgather,
    allreduce,
    alltoall,
    barrier,
    broadcast,
    broadcast_variables,
    cross_rank,
    cross_size,
    grouped_allreduce,
    init,
    is_initialized,
    join,
    local_rank,
    local_size,
    debug_port,
    events,
    metrics,
    metrics_reset,
    rank,
    reducescatter,
    shutdown,
    size,
    start_timeline,
    stop_timeline,
)
from horovod_tpu.tensorflow.sync_batch_norm import (  # noqa: F401
    SyncBatchNormalization,
)
from horovod_tpu.tensorflow.functions import (  # noqa: F401
    allgather_object,
    broadcast_object,
    broadcast_object_fn,
)
from horovod_tpu.tensorflow import elastic  # noqa: F401


def DistributedOptimizer(optimizer, compression=Compression.none,
                         op=Average, backward_passes_per_step=1):
    """Wrap an optimizer so gradients are allreduce-averaged before apply.

    Reference analog: hvd.DistributedOptimizer
    (horovod/tensorflow/__init__.py). Keras optimizers delegate to the
    keras wrapper (the tf2-native path); legacy
    ``tf.compat.v1.train.Optimizer`` instances get a v1-style wrapper
    whose ``compute_gradients`` allreduces.
    """
    if _is_v1_optimizer(optimizer):
        if backward_passes_per_step != 1:
            raise ValueError(
                "backward_passes_per_step > 1 is not supported for "
                "tf.compat.v1 optimizers; use a keras optimizer")
        return _make_v1_distributed_optimizer(optimizer, compression, op)
    from horovod_tpu import keras as _keras

    return _keras.DistributedOptimizer(
        optimizer, compression=compression, op=op,
        backward_passes_per_step=backward_passes_per_step)


def _is_v1_optimizer(optimizer):
    """True for legacy graph-mode ``tf.compat.v1.train.Optimizer``
    instances (shared by the keras and TF DistributedOptimizer dispatch)."""
    v1_base = getattr(getattr(tf.compat, "v1", None), "train", None)
    v1_base = getattr(v1_base, "Optimizer", None)
    return v1_base is not None and isinstance(optimizer, v1_base)


def _allreduce_grads_list(grads, compression, op, names,
                          process_set_id=0):
    """Allreduce a gradient list (None-preserving, IndexedSlices
    densified, compression applied around the wire). ``names`` must be
    globally consistent across ranks — callers derive them from variable
    names, not call order."""
    from horovod_tpu.tensorflow import mpi_ops

    live = [(i, g) for i, g in enumerate(grads) if g is not None]
    compressed, ctxs = [], []
    for _, g in live:
        if isinstance(g, tf.IndexedSlices):
            g = tf.convert_to_tensor(g)
        c, ctx = compression.compress(g)
        compressed.append(c)
        ctxs.append(ctx)
    reduced = mpi_ops.grouped_allreduce(
        compressed, names=[names[i] for i, _ in live], op=op,
        process_set_id=process_set_id)
    out = list(grads)
    for (i, _), r, ctx in zip(live, reduced, ctxs):
        out[i] = compression.decompress(r, ctx)
    return out


_v1_wrapper_count = itertools.count()


def _make_v1_distributed_optimizer(optimizer, compression, op):
    """Graph-mode wrapper: a genuine ``tf.compat.v1.train.Optimizer``
    subclass (so isinstance checks in estimators etc. pass) whose
    ``compute_gradients`` allreduces; apply/slots delegate. The inherited
    ``minimize`` composes the two with full v1 kwargs semantics."""
    v1_base = tf.compat.v1.train.Optimizer

    class _V1DistributedOptimizer(v1_base):
        def __init__(self):
            super().__init__(use_locking=False,
                             name=f"Distributed{type(optimizer).__name__}")
            self._opt = optimizer
            self._compression = compression
            self._hvd_op = op
            # Both counters advance at graph-construction time, which is
            # identical program order on every rank (SPMD), so the names
            # stay globally consistent.
            self._uid = next(_v1_wrapper_count)
            self._cg_calls = itertools.count()

        def compute_gradients(self, *args, **kwargs):
            gvs = self._opt.compute_gradients(*args, **kwargs)
            # Names keyed on (wrapper instance, call, variable): two
            # wrapped optimizers — or two towers calling
            # compute_gradients twice over shared variables — must not
            # collide when session.run interleaves their groups.
            call_n = next(self._cg_calls)
            names = [
                f"v1opt.{self._uid}.{call_n}.{getattr(v, 'name', i)}"
                for i, (_, v) in enumerate(gvs)]
            grads = _allreduce_grads_list(
                [g for g, _ in gvs], self._compression, self._hvd_op,
                names)
            return list(zip(grads, [v for _, v in gvs]))

        def apply_gradients(self, grads_and_vars, global_step=None,
                            name=None):
            return self._opt.apply_gradients(
                grads_and_vars, global_step=global_step, name=name)

        def get_slot(self, var, name):
            return self._opt.get_slot(var, name)

        def get_slot_names(self):
            return self._opt.get_slot_names()

        def variables(self):
            return self._opt.variables()

    return _V1DistributedOptimizer()


class DistributedGradientTape:
    """Wrap a ``tf.GradientTape`` so ``gradient()`` returns allreduce-
    averaged gradients.

    Reference analog: hvd.DistributedGradientTape
    (horovod/tensorflow/__init__.py _DistributedGradientTape).
    """

    def __init__(self, tape, compression=Compression.none, op=Average,
                 process_set_id=0):
        self._tape = tape
        self._compression = compression
        self._op = op
        self._process_set_id = process_set_id

    def __getattr__(self, item):
        return getattr(self._tape, item)

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources,
                                    output_gradients=output_gradients)
        return self._allreduce_grads(grads)

    def _allreduce_grads(self, grads):
        flat = tf.nest.flatten(grads)
        out = _allreduce_grads_list(
            flat, self._compression, self._op,
            [f"tape.grad.{i}" for i in range(len(flat))],
            process_set_id=self._process_set_id)
        return tf.nest.pack_sequence_as(grads, out)

# Capability surface (reference analog: hvd.mpi_built()/gloo_built()/...).
from horovod_tpu.tensorflow.mpi_ops import (  # noqa: F401,E402
    ccl_built,
    cuda_built,
    ddl_built,
    gloo_built,
    gloo_enabled,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rocm_built,
    xla_built,
    xla_enabled,
)
