"""horovod_tpu.tensorflow.keras — tf.keras frontend alias.

Reference analog: ``horovod/tensorflow/keras/__init__.py`` — the
tf.keras-flavored entry point; identical surface to
``horovod_tpu.keras`` (which targets the same tf.keras here, since
standalone Keras is not a separate install in this environment).
"""

from horovod_tpu.keras import *  # noqa: F401,F403
from horovod_tpu.keras import callbacks  # noqa: F401
from horovod_tpu.tensorflow.keras import elastic  # noqa: F401
