"""tf.keras elastic alias (reference analog:
``horovod/tensorflow/keras/elastic.py``)."""

from horovod_tpu.keras.elastic import (  # noqa: F401
    CommitStateCallback,
    KerasState,
    ObjectState,
    State,
    TensorFlowKerasState,
    TensorFlowState,
    UpdateBatchStateCallback,
    UpdateEpochStateCallback,
    init,
    reset,
    run,
)
