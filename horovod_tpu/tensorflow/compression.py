"""Gradient compression (TF flavor).

Reference analog: ``horovod/tensorflow/compression.py``.
"""

import tensorflow as tf


class Compressor:
    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating and tensor.dtype != tf.float16:
            return tf.cast(tensor, tf.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tf.cast(tensor, ctx) if ctx is not None else tensor


class BFloat16Compressor(Compressor):
    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating and tensor.dtype != tf.bfloat16:
            return tf.cast(tensor, tf.bfloat16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tf.cast(tensor, ctx) if ctx is not None else tensor


class Compression:
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BFloat16Compressor
