"""TensorFlow eager collective ops.

Reference analog: ``horovod/tensorflow/mpi_ops.py`` + ``mpi_ops.cc``. The
reference registers TF custom C ops; here eager tensors round-trip
through the shared numpy engine (``common/eager_ops``) and graph-mode use
goes through ``tf.py_function`` — on TPU the in-graph path is
``horovod_tpu.parallel`` (XLA collectives), mirroring how upstream's
``xla_mpi_ops.cc`` bridges into XLA programs.
"""



import numpy as np
import tensorflow as tf

from horovod_tpu.common import eager_ops
from horovod_tpu.common.eager_ops import ReduceOp

Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT
Adasum = ReduceOp.ADASUM

_basics = eager_ops._basics

# In elastic mode (HOROVOD_RDZV_ADDR set) init consults the driver's
# rendezvous for this epoch's rank assignment; static mode unchanged.
from horovod_tpu.common import elastic as _elastic_init_mod
init = _elastic_init_mod.init
shutdown = _basics.shutdown
is_initialized = _basics.is_initialized
rank = _basics.rank
size = _basics.size
local_rank = _basics.local_rank
local_size = _basics.local_size
cross_rank = _basics.cross_rank
cross_size = _basics.cross_size

for _cap in _basics.CAPABILITY_NAMES:
    globals()[_cap] = getattr(_basics, _cap)
start_timeline = _basics.start_timeline
stop_timeline = _basics.stop_timeline

from horovod_tpu.common.auto_name import make_auto_namer

_auto_name = make_auto_namer()



def _to_np(tensor):
    if isinstance(tensor, tf.Tensor) or isinstance(tensor, tf.Variable):
        arr = tensor.numpy()
    else:
        arr = np.asarray(tensor)
    # Not np.ascontiguousarray: it promotes 0-d to 1-d and scalar
    # variables (e.g. an optimizer's iteration counter) must round-trip
    # shape-exact through broadcast.
    if arr.ndim and not arr.flags["C_CONTIGUOUS"]:
        arr = np.ascontiguousarray(arr)
    return arr


def _run_numpy(fn, tensor, out_dtype=None):
    """Run a host collective on an eager or graph tensor."""
    if tf.executing_eagerly() and not isinstance(tensor,
                                                 tf.__internal__.FuncGraph):
        return tf.convert_to_tensor(fn(_to_np(tensor)))
    return tf.py_function(lambda t: fn(t.numpy()), [tensor],
                          Tout=out_dtype or tensor.dtype)


def allreduce(tensor, name=None, op=Average, prescale_factor=1.0,
              postscale_factor=1.0, process_set_id=0):
    nm = name or _auto_name("allreduce")

    def _fn(arr):
        return eager_ops.allreduce_async(
            arr, nm, op=op, prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            process_set_id=process_set_id).synchronize()

    return _run_numpy(_fn, tensor)


def grouped_allreduce(tensors, names=None, op=Average, process_set_id=0):
    if names is None:
        base = _auto_name("grouped_allreduce")
        names = [f"{base}.{i}" for i in range(len(tensors))]

    def _grouped_np(arrs):
        if arrs and all(a.dtype == arrs[0].dtype for a in arrs):
            handles = eager_ops.grouped_allreduce_async(
                arrs, names, op=op, process_set_id=process_set_id)
            return [h.synchronize() for h in handles]
        return [eager_ops.allreduce_async(
                    a, n, op=op,
                    process_set_id=process_set_id).synchronize()
                for a, n in zip(arrs, names)]

    symbolic = (not tf.executing_eagerly()
                or any(not hasattr(t, "numpy") for t in tensors))
    if symbolic:
        # Inside tf.function (keras model.fit's train_step): one
        # py_function hop for the whole group keeps them fusing as one
        # negotiation, mirroring the eager path.
        outs = tf.py_function(
            lambda *ts: _grouped_np([_to_np(t) for t in ts]),
            list(tensors), Tout=[t.dtype for t in tensors])
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        for o, t in zip(outs, tensors):
            o.set_shape(t.shape)
        return list(outs)
    return [tf.convert_to_tensor(r)
            for r in _grouped_np([_to_np(t) for t in tensors])]


def allgather(tensor, name=None, process_set_id=0):
    nm = name or _auto_name("allgather")

    def _fn(arr):
        return eager_ops.allgather_async(
            arr, nm, process_set_id=process_set_id).synchronize()

    return _run_numpy(_fn, tensor)


def broadcast(tensor, root_rank, name=None, process_set_id=0):
    nm = name or _auto_name("broadcast")

    def _fn(arr):
        return eager_ops.broadcast_async(
            arr, root_rank, nm,
            process_set_id=process_set_id).synchronize()

    return _run_numpy(_fn, tensor)


def alltoall(tensor, splits=None, name=None, process_set_id=0):
    nm = name or _auto_name("alltoall")

    def _fn(arr):
        return eager_ops.alltoall_async(
            arr, None if splits is None else np.asarray(splits), nm,
            process_set_id=process_set_id).synchronize()

    return _run_numpy(_fn, tensor)


def reducescatter(tensor, name=None, op=Average, process_set_id=0):
    nm = name or _auto_name("reducescatter")

    def _fn(arr):
        return eager_ops.reducescatter_async(
            arr, nm, op=op, process_set_id=process_set_id).synchronize()

    return _run_numpy(_fn, tensor)


def broadcast_variables(variables, root_rank=0, prefix="var"):
    """Assign every variable its root-rank value (reference:
    hvd.broadcast_variables)."""
    handles = []
    for i, v in enumerate(variables):
        arr = _to_np(v)
        handles.append((v, eager_ops.broadcast_async(
            arr, root_rank, f"broadcast.{prefix}.{i}")))
    for v, h in handles:
        v.assign(h.synchronize())


def join():
    """Block until every rank has joined; contribute zeros meanwhile.

    Reference analog: ``hvd.join`` (horovod/tensorflow/__init__.py).
    Returns the last rank to join.
    """
    return eager_ops.join()


def barrier(process_set_id=0):
    eager_ops.barrier(process_set_id=process_set_id)
