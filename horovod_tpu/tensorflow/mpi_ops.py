"""TensorFlow collective ops.

Reference analog: ``horovod/tensorflow/mpi_ops.py`` + ``mpi_ops.cc`` +
``xla_mpi_ops.cc``. Two data paths:

- **Native ops** (``csrc/tf_ops.cc`` -> ``libhvdtpu_tf.so``, built on
  demand): real TF custom ops whose CPU kernels enqueue straight into
  the core (no Python/GIL hop) and whose tf2xla kernels lower to an XLA
  custom-call into the same core — collectives work inside
  ``tf.function(jit_compile=True)``, upstream's HOROVOD_ENABLE_XLA_OPS
  feature. Used automatically when the library builds/loads.
- **Numpy fallback**: eager tensors round-trip through the shared numpy
  engine (``common/eager_ops``); graph mode via ``tf.py_function``.
  Active when TF headers aren't available (set
  ``HOROVOD_TF_NATIVE_OPS=0`` to force it).
"""

import os
import subprocess
import threading

import numpy as np
import tensorflow as tf

from horovod_tpu.common import eager_ops
from horovod_tpu.common.eager_ops import ReduceOp

Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT
Adasum = ReduceOp.ADASUM

_basics = eager_ops._basics

# In elastic mode (HOROVOD_RDZV_ADDR set) init consults the driver's
# rendezvous for this epoch's rank assignment; static mode unchanged.
from horovod_tpu.common import elastic as _elastic_init_mod


def init(*args, **kwargs):
    # The native op library must register its tf2xla kernels BEFORE the
    # first XLA compilation in the process: TF materializes the
    # XLA_CPU_JIT kernel set once, lazily, and ignores later
    # registrations. init() is the earliest hook every program calls.
    _load_native()
    return _elastic_init_mod.init(*args, **kwargs)
shutdown = _basics.shutdown
is_initialized = _basics.is_initialized
rank = _basics.rank
size = _basics.size
local_rank = _basics.local_rank
local_size = _basics.local_size
cross_rank = _basics.cross_rank
cross_size = _basics.cross_size

for _cap in _basics.CAPABILITY_NAMES:
    globals()[_cap] = getattr(_basics, _cap)
start_timeline = _basics.start_timeline
stop_timeline = _basics.stop_timeline
# Metrics registry snapshot (docs/metrics.md) — same surface on
# every frontend.
metrics = _basics.metrics_snapshot
metrics_reset = _basics.metrics_reset
# Structured event-ring tail (flight recorder, docs/metrics.md).
events = _basics.events


def debug_port():
    """Bound port of this rank's debug server (None when not running);
    the discovery path under ``HOROVOD_DEBUG_PORT=0`` (docs/scale.md).
    """
    from horovod_tpu.telemetry import debug_server

    return debug_server.debug_port()

from horovod_tpu.common.auto_name import make_auto_namer

_auto_name = make_auto_namer()

# ---- native op library (build-on-demand, like basics.py for the core) ----

_native_lock = threading.Lock()
_native = None
_native_failed = False


class _NativeBuildPending(Exception):
    """The op library is building in the background; this process uses
    the numpy fallback (the build benefits the NEXT process — loading
    tf2xla kernels after the process's first XLA compile would be
    silently ignored, so a mid-process hot-load is never attempted)."""


def _spawn_background_build(root, lib_dir):
    """Kick off `make tf` detached, holding the cross-process build lock
    for the build's lifetime (the lock fd is inherited by the child, and
    flock follows the open file description, so the lock holds even
    after this process exits)."""
    import fcntl
    import sys

    lock = open(os.path.join(lib_dir, ".tf_build_lock"), "w")
    try:
        fcntl.flock(lock, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        lock.close()
        return False  # another process is already building
    log = open(os.path.join(lib_dir, "tf_build.log"), "w")
    # A failed build leaves a marker so later processes stop relaunching
    # the same doomed minutes-long compile (they fall back immediately
    # and point at the log; delete the marker or run `make tf` by hand
    # to retry).
    marker = os.path.join(lib_dir, ".tf_build_failed")
    subprocess.Popen(
        ["/bin/sh", "-c",
         f"make -s tf PYTHON='{sys.executable}' || : > '{marker}'"],
        cwd=root, stdout=log, stderr=subprocess.STDOUT,
        start_new_session=True, pass_fds=(lock.fileno(),))
    log.close()
    lock.close()  # the child's inherited fd keeps the lock alive
    return True


def _ensure_built(path, root):
    """Make sure ``path`` exists, building per HOROVOD_TF_NATIVE_BUILD:

    - ``async`` (default): never block init — start a detached
      background build and raise _NativeBuildPending; THIS process runs
      the numpy fallback, the next one loads the built library. (A cold
      `make tf` takes minutes; blocking hvd.init() on it stalled real
      programs — VERDICT r2.)
    - ``sync``: the old behavior — build inline under the cross-process
      lock (deterministic for CI images that pre-warm).
    - ``0``/``off``: never build; fall back immediately.
    """
    if os.path.exists(path):
        return
    if not os.path.exists(os.path.join(root, "Makefile")):
        raise FileNotFoundError(path)
    mode = os.environ.get("HOROVOD_TF_NATIVE_BUILD", "async").lower()
    if mode in ("0", "off", "false", "no"):
        raise FileNotFoundError(f"{path} (builds disabled by "
                                "HOROVOD_TF_NATIVE_BUILD)")
    lib_dir = os.path.dirname(path)
    os.makedirs(lib_dir, exist_ok=True)
    marker = os.path.join(lib_dir, ".tf_build_failed")
    if os.path.exists(marker):
        raise FileNotFoundError(
            f"{path} (a previous background build FAILED — see "
            f"{os.path.join(lib_dir, 'tf_build.log')}; delete {marker} "
            f"or run `make tf` to retry)")
    if mode == "sync":
        import fcntl
        import sys

        # Cross-process lock: concurrently launched ranks must not race
        # the build.
        with open(os.path.join(lib_dir, ".tf_build_lock"), "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            if not os.path.exists(path):
                subprocess.run(
                    ["make", "-s", "tf", f"PYTHON={sys.executable}"],
                    cwd=root, check=True, capture_output=True)
        return
    _spawn_background_build(root, lib_dir)
    raise _NativeBuildPending(path)


def _load_native():
    """tf.load_op_library the native TF ops, building them on first use.
    Returns the op module or None (numpy fallback)."""
    global _native, _native_failed
    if _native is not None or _native_failed:
        return _native
    with _native_lock:
        if _native is not None or _native_failed:
            return _native
        # HOROVOD_ENABLE_XLA_OPS=0 (the reference's flag) disables only
        # the in-jit path — the tf2xla kernels check it at compile time
        # (csrc/tf_ops.cc) — while the native CPU kernels stay active.
        # HOROVOD_TF_NATIVE_OPS=0 disables the whole library.
        if os.environ.get("HOROVOD_TF_NATIVE_OPS", "1") == "0":
            _native_failed = True
            return None
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        path = os.path.join(pkg, "lib", "libhvdtpu_tf.so")
        try:
            _ensure_built(path, os.path.dirname(pkg))
            _native = tf.load_op_library(path)
        except _NativeBuildPending:
            tf.get_logger().warning(
                "hvdtpu native TF ops are building in the background "
                "(%s/tf_build.log); THIS process uses the py_function "
                "fallback (no jit_compile support) — restart once the "
                "build finishes, or set HOROVOD_TF_NATIVE_BUILD=sync to "
                "block init on the build instead",
                os.path.join(pkg, "lib"))
            _native_failed = True
        except Exception as e:  # missing TF headers, old TF, build break…
            tf.get_logger().warning(
                "hvdtpu native TF ops unavailable (%s); falling back to "
                "the py_function path (no jit_compile support)", e)
            _native_failed = True
    return _native



def _to_np(tensor):
    if isinstance(tensor, tf.Tensor) or isinstance(tensor, tf.Variable):
        arr = tensor.numpy()
    else:
        arr = np.asarray(tensor)
    # Not np.ascontiguousarray: it promotes 0-d to 1-d and scalar
    # variables (e.g. an optimizer's iteration counter) must round-trip
    # shape-exact through broadcast.
    if arr.ndim and not arr.flags["C_CONTIGUOUS"]:
        arr = np.ascontiguousarray(arr)
    return arr


def _run_numpy(fn, tensor, out_dtype=None):
    """Run a host collective on an eager or graph tensor."""
    if tf.executing_eagerly() and not isinstance(tensor,
                                                 tf.__internal__.FuncGraph):
        return tf.convert_to_tensor(fn(_to_np(tensor)))
    return tf.py_function(lambda t: fn(t.numpy()), [tensor],
                          Tout=out_dtype or tensor.dtype)


# Dtypes the native op registrations cover (csrc/tf_ops.cc).
_NATIVE_DTYPES = frozenset((tf.uint8, tf.int8, tf.uint16, tf.int32,
                            tf.int64, tf.float16, tf.bfloat16, tf.float32,
                            tf.float64))


def _native_op(tensor, allow_bool=False):
    """(lib, tensor) when the native op library serves this input, else
    None — the shared gate for every collective's dispatch."""
    lib = _load_native()
    if lib is None:
        return None
    t = tf.convert_to_tensor(tensor)
    if t.dtype in _NATIVE_DTYPES or (allow_bool and t.dtype == tf.bool):
        return lib, t
    return None


def allreduce(tensor, name=None, op=Average, prescale_factor=1.0,
              postscale_factor=1.0, process_set_id=0):
    nm = name or _auto_name("allreduce")

    native = _native_op(tensor)
    if native:
        lib, t = native
        return lib.hvd_tpu_allreduce(
            t, tensor_name=nm, reduce_op=int(op),
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            process_set_id=process_set_id)

    def _fn(arr):
        return eager_ops.allreduce_async(
            arr, nm, op=op, prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            process_set_id=process_set_id).synchronize()

    return _run_numpy(_fn, tensor)


def grouped_allreduce(tensors, names=None, op=Average, process_set_id=0):
    if names is None:
        base = _auto_name("grouped_allreduce")
        names = [f"{base}.{i}" for i in range(len(tensors))]

    lib = _load_native()
    if lib is not None and tensors:
        ts = [tf.convert_to_tensor(t) for t in tensors]
        if (all(t.dtype == ts[0].dtype for t in ts)
                and ts[0].dtype in _NATIVE_DTYPES):
            # One variadic op = one atomic group negotiation, on every
            # path (eager, graph, jit_compile).
            return list(lib.hvd_tpu_grouped_allreduce(
                ts, tensor_names=list(names), reduce_op=int(op),
                process_set_id=process_set_id))
        # Mixed dtypes: per-dtype native groups keep the no-GIL path and
        # negotiate each sub-group atomically.
        if all(t.dtype in _NATIVE_DTYPES for t in ts):
            by_dtype = {}
            for i, t in enumerate(ts):
                by_dtype.setdefault(t.dtype, []).append(i)
            out = [None] * len(ts)
            for idxs in by_dtype.values():
                red = lib.hvd_tpu_grouped_allreduce(
                    [ts[i] for i in idxs],
                    tensor_names=[names[i] for i in idxs],
                    reduce_op=int(op), process_set_id=process_set_id)
                for i, r in zip(idxs, red):
                    out[i] = r
            return out

    def _grouped_np(arrs):
        if arrs and all(a.dtype == arrs[0].dtype for a in arrs):
            handles = eager_ops.grouped_allreduce_async(
                arrs, names, op=op, process_set_id=process_set_id)
            return [h.synchronize() for h in handles]
        return [eager_ops.allreduce_async(
                    a, n, op=op,
                    process_set_id=process_set_id).synchronize()
                for a, n in zip(arrs, names)]

    symbolic = (not tf.executing_eagerly()
                or any(not hasattr(t, "numpy") for t in tensors))
    if symbolic:
        # Inside tf.function (keras model.fit's train_step): one
        # py_function hop for the whole group keeps them fusing as one
        # negotiation, mirroring the eager path.
        outs = tf.py_function(
            lambda *ts: _grouped_np([_to_np(t) for t in ts]),
            list(tensors), Tout=[t.dtype for t in tensors])
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        for o, t in zip(outs, tensors):
            o.set_shape(t.shape)
        return list(outs)
    return [tf.convert_to_tensor(r)
            for r in _grouped_np([_to_np(t) for t in tensors])]


def allgather(tensor, name=None, process_set_id=0):
    nm = name or _auto_name("allgather")

    native = _native_op(tensor, allow_bool=True)
    if native:
        lib, t = native
        return lib.hvd_tpu_allgather(t, tensor_name=nm,
                                     process_set_id=process_set_id)

    def _fn(arr):
        return eager_ops.allgather_async(
            arr, nm, process_set_id=process_set_id).synchronize()

    return _run_numpy(_fn, tensor)


def broadcast(tensor, root_rank, name=None, process_set_id=0):
    nm = name or _auto_name("broadcast")

    native = _native_op(tensor, allow_bool=True)
    if native:
        lib, t = native
        return lib.hvd_tpu_broadcast(
            t, tensor_name=nm, root_rank=root_rank,
            process_set_id=process_set_id)

    def _fn(arr):
        return eager_ops.broadcast_async(
            arr, root_rank, nm,
            process_set_id=process_set_id).synchronize()

    return _run_numpy(_fn, tensor)


def alltoall(tensor, splits=None, name=None, process_set_id=0):
    nm = name or _auto_name("alltoall")

    native = _native_op(tensor, allow_bool=True)
    if native:
        lib, t = native
        sp = (tf.constant([], dtype=tf.int64) if splits is None
              else tf.cast(tf.convert_to_tensor(splits), tf.int64))
        return lib.hvd_tpu_alltoall(t, sp, tensor_name=nm,
                                    process_set_id=process_set_id)

    def _fn(arr):
        return eager_ops.alltoall_async(
            arr, None if splits is None else np.asarray(splits), nm,
            process_set_id=process_set_id).synchronize()

    return _run_numpy(_fn, tensor)


def reducescatter(tensor, name=None, op=Average, process_set_id=0):
    nm = name or _auto_name("reducescatter")

    native = _native_op(tensor)
    if native:
        lib, t = native
        return lib.hvd_tpu_reducescatter(
            t, tensor_name=nm, reduce_op=int(op),
            process_set_id=process_set_id)

    def _fn(arr):
        return eager_ops.reducescatter_async(
            arr, nm, op=op, process_set_id=process_set_id).synchronize()

    return _run_numpy(_fn, tensor)


def broadcast_variables(variables, root_rank=0, prefix="var"):
    """Assign every variable its root-rank value (reference:
    hvd.broadcast_variables)."""
    handles = []
    for i, v in enumerate(variables):
        arr = _to_np(v)
        handles.append((v, eager_ops.broadcast_async(
            arr, root_rank, f"broadcast.{prefix}.{i}")))
    for v, h in handles:
        v.assign(h.synchronize())


def join():
    """Block until every rank has joined; contribute zeros meanwhile.

    Reference analog: ``hvd.join`` (horovod/tensorflow/__init__.py).
    Returns the last rank to join.
    """
    return eager_ops.join()


def barrier(process_set_id=0):
    eager_ops.barrier(process_set_id=process_set_id)
