"""Object broadcast/gather helpers for the TensorFlow frontend.

Reference analog: ``horovod/tensorflow/functions.py``
(``broadcast_object``, ``broadcast_object_fn``, ``allgather_object``) —
pickle the object, ship the length then the payload as uint8 tensors
through the eager collective engine.
"""

from horovod_tpu.common.elastic import (
    _allgather_object,
    _broadcast_object,
)


def broadcast_object(obj, root_rank=0, name=None, process_set_id=0):
    """Broadcast an arbitrary picklable python object from ``root_rank``;
    every rank returns the root's object."""
    return _broadcast_object(obj, root_rank=root_rank,
                             name=name or "tf.broadcast_object",
                             process_set_id=process_set_id)


def broadcast_object_fn(root_rank=0, name=None, process_set_id=0):
    """Return a callable ``f(obj) -> obj`` bound to ``root_rank`` —
    reference parity with hvd.broadcast_object_fn (used where the object
    to broadcast is produced lazily, e.g. inside a tf.function guard)."""

    def _fn(obj):
        return broadcast_object(obj, root_rank=root_rank, name=name,
                                process_set_id=process_set_id)

    return _fn


def allgather_object(obj, name=None, process_set_id=0):
    """Gather a picklable python object from every rank; returns a list
    indexed by rank."""
    return _allgather_object(obj, name=name or "tf.allgather_object",
                             process_set_id=process_set_id)
