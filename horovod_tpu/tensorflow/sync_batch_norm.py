"""Cross-rank synchronized batch normalization for TF/Keras.

Reference analog: ``horovod/tensorflow/sync_batch_norm.py``
(SyncBatchNormalization): batch moments are computed over the GLOBAL
batch — per-rank sums of x and x² are allreduce-summed before
normalization — so data-parallel training with small per-rank batches
behaves like one large batch.
"""

import tensorflow as tf


class SyncBatchNormalization(tf.keras.layers.Layer):
    """Drop-in BatchNormalization whose training-time moments span all
    ranks (channels-last; normalizes over every axis but the last)."""

    _counter = 0

    def __init__(self, momentum=0.99, epsilon=1e-3, center=True, scale=True,
                 process_set_id=0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.epsilon = epsilon
        self.center = center
        self.scale = scale
        self.process_set_id = process_set_id
        self._hvd_name = f"sync_bn.{SyncBatchNormalization._counter}"
        SyncBatchNormalization._counter += 1

    def build(self, input_shape):
        c = int(input_shape[-1])
        self.gamma = self.add_weight(name="gamma", shape=(c,),
                                     initializer="ones",
                                     trainable=self.scale)
        self.beta = self.add_weight(name="beta", shape=(c,),
                                    initializer="zeros",
                                    trainable=self.center)
        self.moving_mean = self.add_weight(name="moving_mean", shape=(c,),
                                           initializer="zeros",
                                           trainable=False)
        self.moving_variance = self.add_weight(name="moving_variance",
                                               shape=(c,),
                                               initializer="ones",
                                               trainable=False)
        super().build(input_shape)

    def _global_moments(self, x):
        from horovod_tpu.tensorflow import mpi_ops

        axes = list(range(x.shape.rank - 1))
        n_local = tf.cast(
            tf.reduce_prod([tf.shape(x)[a] for a in axes]), tf.float32)
        local_sum = tf.reduce_sum(x, axis=axes)
        local_sq = tf.reduce_sum(tf.square(x), axis=axes)
        # process_set_id may be a ProcessSet object (it carries the
        # subgroup size) or the world id 0.
        ps_size = (self.process_set_id.size()
                   if hasattr(self.process_set_id, "size")
                   else mpi_ops.size())
        if ps_size > 1:
            # One fused negotiation for [sum, sum_sq, count].
            packed = tf.concat(
                [local_sum, local_sq, tf.reshape(n_local, [1])], axis=0)
            packed = mpi_ops.allreduce(
                packed, name=self._hvd_name, op=mpi_ops.Sum,
                process_set_id=self.process_set_id)
            # In graph mode the collective rides a py_function whose
            # output rank is unknown; restore it so downstream
            # (moving-stat assigns) see static [C] shapes.
            c = int(local_sum.shape[0])
            packed = tf.ensure_shape(packed, [2 * c + 1])
            g_sum, g_sq, g_n = (packed[:c], packed[c:2 * c], packed[-1])
        else:
            g_sum, g_sq, g_n = local_sum, local_sq, n_local
        mean = g_sum / g_n
        var = g_sq / g_n - tf.square(mean)
        return mean, var

    def call(self, inputs, training=None):
        x = tf.cast(inputs, tf.float32)
        if training is None:
            training = False

        def train_moments():
            mean, var = self._global_moments(x)
            self.moving_mean.assign(
                self.momentum * self.moving_mean + (1 - self.momentum) * mean)
            self.moving_variance.assign(
                self.momentum * self.moving_variance
                + (1 - self.momentum) * var)
            return mean, var

        def infer_moments():
            return (tf.identity(self.moving_mean),
                    tf.identity(self.moving_variance))

        # training may be a symbolic tensor under tf.function/Keras graph
        # mode — branch with smart_cond, not Python `if`.
        mean, var = tf.__internal__.smart_cond.smart_cond(
            training, train_moments, infer_moments)
        y = (x - mean) * tf.math.rsqrt(var + self.epsilon)
        y = y * self.gamma + self.beta
        return tf.cast(y, inputs.dtype)

    def get_config(self):
        cfg = super().get_config()
        cfg.update(momentum=self.momentum, epsilon=self.epsilon,
                   center=self.center, scale=self.scale,
                   # ProcessSet objects aren't JSON-serializable; persist
                   # the integer id (rebinding is on the loader).
                   process_set_id=int(self.process_set_id))
        return cfg
