"""DistributedOptimizer for torch: async per-parameter gradient allreduce.

Reference analog: ``horovod/torch/optimizer.py`` ``_DistributedOptimizer``
— per-param hooks fire an async allreduce the moment a gradient is
accumulated (overlapping communication with the rest of backward);
``step()`` synchronizes every handle, writes the averaged gradients back
and runs the wrapped optimizer. Local gradient aggregation
(``backward_passes_per_step``) and wire compression are supported.

Mechanically we subclass the wrapped optimizer's class at runtime (the
reference's trick) so isinstance checks and schedulers keep working.
"""

import contextlib

import torch

from horovod_tpu.torch import mpi_ops
from horovod_tpu.torch.compression import Compression


class _DistributedOptimizer(torch.optim.Optimizer):
    def __init__(self, params, named_parameters, compression,
                 backward_passes_per_step, op, process_set_id):
        super(self.__class__, self).__init__(params)
        self._compression = compression
        self._op = op
        self._process_set_id = process_set_id
        self.backward_passes_per_step = backward_passes_per_step
        self._handles = {}       # param -> (Handle, ctx)
        self._allreduce_delay = {}
        self._should_synchronize = True
        self._hook_handles = []

        if named_parameters is not None:
            self._param_names = {p: name for name, p in named_parameters}
        else:
            self._param_names = {
                p: f"param.{gi}.{pi}"
                for gi, group in enumerate(self.param_groups)
                for pi, p in enumerate(group["params"])}

        if mpi_ops.size() > 1:
            self._register_hooks()

    def _register_hooks(self):
        for group in self.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    self._allreduce_delay[p] = self.backward_passes_per_step
                    self._hook_handles.append(
                        p.register_post_accumulate_grad_hook(
                            self._make_hook()))

    def _make_hook(self):
        def hook(p):
            if p not in self._allreduce_delay:
                return
            self._allreduce_delay[p] -= 1
            if self._allreduce_delay[p] == 0:
                self._allreduce_grad_async(p)
        return hook

    def _allreduce_grad_async(self, p):
        name = f"allreduce.{self._param_names.get(p, 'noname')}"
        grad = p.grad
        if self.backward_passes_per_step > 1:
            grad = grad / self.backward_passes_per_step
        compressed, ctx = self._compression.compress(grad.contiguous())
        handle = mpi_ops.allreduce_async(
            compressed, name=name, op=self._op,
            process_set_id=self._process_set_id)
        self._handles[p] = (handle, ctx)

    def _drain_inflight(self):
        """Complete-or-discard every in-flight handle and reset ALL
        delay countdowns — returns the optimizer to a clean state
        (elastic recovery path). Every delay resets, not just handled
        params': a param whose enqueue itself failed, or whose countdown
        was mid-flight on a survivor, would otherwise stay desynced from
        respawned peers forever."""
        for _, (handle, _ctx) in list(self._handles.items()):
            try:
                handle.synchronize()
            except Exception:  # noqa: BLE001 — poisoned by the failure
                pass
        self._handles.clear()
        for p in self._allreduce_delay:
            self._allreduce_delay[p] = self.backward_passes_per_step

    def synchronize(self):
        """Wait for all outstanding allreduces; write averaged grads back."""
        # Params whose countdown has not fired (e.g. user stepped early)
        # are flushed now, like the reference's missing-handle path.
        for p, delay in self._allreduce_delay.items():
            if 0 < delay < self.backward_passes_per_step \
                    and p not in self._handles and p.grad is not None:
                self._allreduce_grad_async(p)
        try:
            for p, (handle, ctx) in list(self._handles.items()):
                out = handle.synchronize()
                p.grad.copy_(self._compression.decompress(out, ctx)
                             .view_as(p.grad))
                self._allreduce_delay[p] = self.backward_passes_per_step
        except Exception:
            # One failed collective poisons the rest of the batch: drain
            # them all so the optimizer is reusable after the elastic
            # loop restores and re-rendezvouses, then let the failure
            # surface to the recovery scope.
            self._drain_inflight()
            raise
        self._handles.clear()

    @contextlib.contextmanager
    def skip_synchronize(self):
        """For the clip-grad pattern: synchronize() manually, clip, then
        ``with optimizer.skip_synchronize(): optimizer.step()``."""
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    def step(self, closure=None):
        if self._should_synchronize and mpi_ops.size() > 1:
            self.synchronize()
        return super(self.__class__, self).step(closure)

    def zero_grad(self, *args, **kwargs):
        if self._handles:
            from horovod_tpu.common.basics import HorovodBasics

            if HorovodBasics().lib.hvdtpu_loop_failed():
                # Handles left over from a step the collective runtime's
                # failure aborted (a hook enqueued, then a peer died
                # before synchronize ran): stale, not a usage error.
                self._drain_inflight()
            else:
                raise AssertionError(
                    "zero_grad called with allreduces in flight; call "
                    "optimizer.step() or optimizer.synchronize() first")
        return super(self.__class__, self).zero_grad(*args, **kwargs)


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step=1, op=mpi_ops.Average,
                         process_set_id=0):
    """Wrap a torch optimizer for data-parallel training.

    Reference analog: hvd.DistributedOptimizer (horovod/torch/optimizer.py).
    """
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    if named_parameters is not None:
        named_parameters = list(named_parameters)
    dist = cls(optimizer.param_groups, named_parameters, compression,
               backward_passes_per_step, op, process_set_id)

    # Elastic recovery: handles enqueued by backward hooks before a peer
    # failure are stale after re-init; drain them so the next
    # zero_grad/step starts clean. Weakref so the hook registry doesn't
    # keep dead optimizers alive.
    import weakref

    from horovod_tpu.common import elastic as _elastic

    def _drain_on_reset():
        opt = ref()
        if opt is not None:
            opt._drain_inflight()

    # Unregister when the optimizer is collected so long-lived elastic
    # processes that construct optimizers repeatedly don't accumulate
    # dead hooks.
    ref = weakref.ref(
        dist, lambda _r: _elastic.unregister_post_reset_hook(
            _drain_on_reset))
    _elastic.register_post_reset_hook(_drain_on_reset)
    return dist
