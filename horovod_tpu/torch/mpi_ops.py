"""Torch eager collective ops.

Reference analog: ``horovod/torch/mpi_ops.py`` + ``mpi_ops_v2.cc`` +
``adapter_v2.cc``/``ready_event.cc`` (device tensors). Two data paths:

- **CPU tensors** need no C extension: their storage is exposed through
  numpy views, so the core's ctypes enqueue writes results straight into
  tensor memory (the in-place ``allreduce_``/``broadcast_`` semantics).
- **Device (non-CPU) tensors** bridge zero-copy via dlpack into the jax
  frontend, whose eager collectives run on the ``xla_ici`` device data
  plane when active — payloads stay in HBM, the reference's
  adapter_v2/ready_event role. ``HOROVOD_TORCH_DEVICE_OPS=1`` forces
  this bridge for CPU tensors too (used by tests; jax CPU arrays ride
  the same code path as TPU ones).
"""

import os

import numpy as np
import torch

from horovod_tpu.common import eager_ops
from horovod_tpu.common.eager_ops import ReduceOp

Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT
Adasum = ReduceOp.ADASUM

_basics = eager_ops._basics

# In elastic mode (HOROVOD_RDZV_ADDR set) init consults the driver's
# rendezvous for this epoch's rank assignment; static mode unchanged.
from horovod_tpu.common import elastic as _elastic_init_mod
init = _elastic_init_mod.init
shutdown = _basics.shutdown
is_initialized = _basics.is_initialized
rank = _basics.rank
size = _basics.size
local_rank = _basics.local_rank
local_size = _basics.local_size
cross_rank = _basics.cross_rank
cross_size = _basics.cross_size

for _cap in _basics.CAPABILITY_NAMES:
    globals()[_cap] = getattr(_basics, _cap)
start_timeline = _basics.start_timeline
stop_timeline = _basics.stop_timeline
# Metrics registry snapshot (docs/metrics.md) — same surface on
# every frontend.
metrics = _basics.metrics_snapshot
metrics_reset = _basics.metrics_reset
# Structured event-ring tail (flight recorder, docs/metrics.md).
events = _basics.events


def debug_port():
    """Bound port of this rank's debug server (None when not running);
    the discovery path under ``HOROVOD_DEBUG_PORT=0`` (docs/scale.md).
    """
    from horovod_tpu.telemetry import debug_server

    return debug_server.debug_port()

from horovod_tpu.common.auto_name import make_auto_namer

_auto_name = make_auto_namer()



def _jax_canonicalizes(dtype):
    """True when jax (x64 disabled, the default) would silently downcast
    this torch dtype (int64->int32, float64->float32)."""
    if dtype not in (torch.int64, torch.float64):
        return False
    import jax

    return not jax.config.jax_enable_x64


def _use_device_bridge(tensor):
    """Route through the dlpack->jax device plane? Non-CPU tensors
    always (64-bit dtypes stage through the host instead — see
    _host_staged_async); CPU tensors when HOROVOD_TORCH_DEVICE_OPS=1
    (testable on CPU-only images, where jax CPU arrays take the
    identical path)."""
    if tensor.device.type != "cpu":
        return True
    return (os.environ.get("HOROVOD_TORCH_DEVICE_OPS", "0") == "1"
            and not _jax_canonicalizes(tensor.dtype))


def _np_to_torch(arr):
    """host numpy array -> torch tensor (bfloat16-aware copy)."""
    arr = np.asarray(arr)
    if arr.dtype.name == "bfloat16":
        return torch.from_numpy(
            arr.view(np.uint16).copy()).view(torch.bfloat16)
    return torch.from_numpy(np.array(arr, copy=True))


def _to_jax(tensor):
    """torch tensor -> jax array. dlpack imports the buffer zero-copy,
    then one device-side copy snapshots the input: the host path's
    'input snapshot' invariant (mutating the tensor before synchronize
    must not corrupt the reduction) holds on the bridge too."""
    import jax

    t = tensor.detach()
    if not t.is_contiguous():
        t = t.contiguous()
    try:
        return jax.numpy.array(jax.dlpack.from_dlpack(t), copy=True)
    except Exception:
        # Exotic layout/device pairing: host round-trip fallback.
        return jax.numpy.asarray(t.cpu().numpy())


def _from_jax(array, like):
    """jax array -> torch tensor with `like`'s device/dtype."""
    import torch.utils.dlpack as _tdl

    try:
        out = _tdl.from_dlpack(array)
    except Exception:
        # torch has no device type for this jax buffer (e.g. plain torch
        # with a TPU-resident array): land on host, then move.
        out = _np_to_torch(np.asarray(array))
    if like is not None and out.device != like.device:
        out = out.to(like.device)
    return out


class _BridgeHandle:
    """In-flight device-plane op (dlpack->jax). ``dest`` keeps in-place
    semantics: the result is copied into the original tensor."""

    def __init__(self, inner, dest=None, like=None):
        self._inner = inner
        self._dest = dest
        self._like = like if like is not None else dest

    def poll(self):
        return self._inner.poll()

    def synchronize(self):
        out = self._inner.synchronize()
        res = _from_jax(out, self._like)
        if self._dest is not None:
            with torch.no_grad():  # dest may be a requires-grad leaf
                self._dest.copy_(res.reshape(self._dest.shape))
            return self._dest
        return res


_plane_probed = False


def _probe_device_plane():
    """First bridged op: give the xla_ici device plane the same chance
    to come up as hvd.init() in the jax frontend does (on TPU, bridged
    payloads then stay in HBM; off TPU this is a no-op and the host
    path serves)."""
    global _plane_probed
    if not _plane_probed:
        from horovod_tpu.jax import mpi_ops as _jax_ops

        _jax_ops._maybe_enable_xla_data_plane()
        _plane_probed = True


def _bridge_async(kind, tensor, dest, *args, **kwargs):
    from horovod_tpu.jax import mpi_ops as _jax_ops

    _probe_device_plane()
    if _jax_canonicalizes(tensor.dtype):
        # jax would downcast int64/float64: stage through the host path
        # on a CPU clone and copy back, keeping exact-width semantics.
        host = tensor.detach().cpu()
        if not host.is_contiguous():
            host = host.contiguous()
        inner = _HOST_ASYNC[kind](host, *args, **kwargs)
        return _HostStagedHandle(inner, dest=dest, like=tensor)
    inner = getattr(_jax_ops, kind)(_to_jax(tensor), *args, **kwargs)
    return _BridgeHandle(inner, dest=dest, like=tensor)


class _HostStagedHandle:
    """64-bit-exact device op: ran on a host clone; synchronize copies
    the result back onto the original device tensor."""

    def __init__(self, inner, dest=None, like=None):
        self._inner = inner
        self._dest = dest
        self._like = like

    def poll(self):
        return self._inner.poll()

    def synchronize(self):
        res = self._inner.synchronize()
        if self._dest is not None:
            with torch.no_grad():
                self._dest.copy_(res.reshape(self._dest.shape))
            return self._dest
        if self._like is not None and res.device != self._like.device:
            res = res.to(self._like.device)
        return res


def _np_view(tensor):
    """Contiguous numpy view sharing the CPU tensor's storage."""
    if tensor.device.type != "cpu":
        raise ValueError(
            "horovod_tpu.torch eager ops require CPU tensors (XLA/TPU "
            "tensors go through the in-graph path)")
    t = tensor.detach()
    if not t.is_contiguous():
        raise ValueError("tensor must be contiguous for in-place collectives")
    if t.dtype == torch.bfloat16:
        import ml_dtypes

        return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
    return t.numpy()


class Handle:
    """In-flight op; synchronize() returns the torch result tensor."""

    def __init__(self, inner, output_tensor=None, like=None):
        self._inner = inner
        self._output_tensor = output_tensor
        self._like = like if like is not None else output_tensor

    def poll(self):
        return self._inner.poll()

    def synchronize(self):
        out = self._inner.synchronize()
        if self._output_tensor is not None:
            return self._output_tensor
        np_out = np.asarray(out)
        if self._like is not None and self._like.dtype == torch.bfloat16:
            import ml_dtypes

            np_out = np_out.view(ml_dtypes.bfloat16)
        return _np_to_torch(np_out)


def allreduce_async_(tensor, name=None, op=Average, prescale_factor=1.0,
                     postscale_factor=1.0, process_set_id=0):
    """In-place async allreduce; result lands in `tensor`'s storage."""
    if _use_device_bridge(tensor):
        return _bridge_async(
            "allreduce_async", tensor, tensor,
            name or _auto_name("allreduce"), op=op,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            process_set_id=process_set_id)
    view = _np_view(tensor)
    inp = np.array(view, copy=True)  # input snapshot; output aliases tensor
    lib = eager_ops._basics.lib
    import ctypes

    h = lib.hvdtpu_enqueue_allreduce(
        (name or _auto_name("allreduce")).encode(),
        inp.ctypes.data_as(ctypes.c_void_p),
        view.ctypes.data_as(ctypes.c_void_p), view.ndim,
        eager_ops._shape_array(view.shape),
        eager_ops._dtype_enum(view.dtype), int(op), float(prescale_factor),
        float(postscale_factor), int(process_set_id))
    inner = eager_ops.Handle(eager_ops._check_handle(h, "allreduce"),
                             (inp, view, tensor), view, False, view.dtype)
    return Handle(inner, output_tensor=tensor)


def allreduce_async(tensor, name=None, op=Average, prescale_factor=1.0,
                    postscale_factor=1.0, process_set_id=0):
    out = tensor.detach().clone()
    return allreduce_async_(out, name, op, prescale_factor, postscale_factor,
                            process_set_id)


def allreduce(tensor, name=None, op=Average, prescale_factor=1.0,
              postscale_factor=1.0, process_set_id=0):
    return allreduce_async(tensor, name, op, prescale_factor,
                           postscale_factor, process_set_id).synchronize()


def allreduce_(tensor, name=None, op=Average, prescale_factor=1.0,
               postscale_factor=1.0, process_set_id=0):
    return allreduce_async_(tensor, name, op, prescale_factor,
                            postscale_factor, process_set_id).synchronize()


def grouped_allreduce_async_(tensors, names=None, op=Average,
                             process_set_id=0):
    if names is None:
        base = _auto_name("grouped_allreduce")
        names = [f"{base}.{i}" for i in range(len(tensors))]
    if (tensors and all(_use_device_bridge(t) for t in tensors)
            and len({t.dtype for t in tensors}) == 1
            and not _jax_canonicalizes(tensors[0].dtype)):
        # One atomic group negotiation through the jax frontend (fuses
        # into a single device program when the xla_ici plane is up),
        # instead of N independent bridged ops.
        from horovod_tpu.jax import mpi_ops as _jax_ops

        _probe_device_plane()
        handles = _jax_ops.grouped_allreduce_async(
            [_to_jax(t) for t in tensors], names=list(names), op=op,
            process_set_id=process_set_id)
        return [_BridgeHandle(h, dest=t, like=t)
                for h, t in zip(handles, tensors)]
    return [allreduce_async_(t, n, op, process_set_id=process_set_id)
            for t, n in zip(tensors, names)]


def grouped_allreduce_(tensors, names=None, op=Average, process_set_id=0):
    hs = grouped_allreduce_async_(tensors, names, op, process_set_id)
    return [h.synchronize() for h in hs]


def allgather_async(tensor, name=None, process_set_id=0):
    if _use_device_bridge(tensor):
        return _bridge_async(
            "allgather_async", tensor, None,
            name or _auto_name("allgather"), process_set_id=process_set_id)
    view = _np_view(tensor)
    inner = eager_ops.allgather_async(
        np.array(view, copy=True), name or _auto_name("allgather"),
        process_set_id=process_set_id)
    return Handle(inner, like=tensor)


def allgather(tensor, name=None, process_set_id=0):
    return allgather_async(tensor, name, process_set_id).synchronize()


def grouped_allgather_async(tensors, names=None, process_set_id=0):
    """Allgather a list of tensors as ONE atomic negotiation group
    (reference analog: hvd.grouped_allgather)."""
    if names is None:
        base = _auto_name("grouped_allgather")
        names = [f"{base}.{i}" for i in range(len(tensors))]
    if (tensors and all(_use_device_bridge(t) for t in tensors)
            and not any(_jax_canonicalizes(t.dtype) for t in tensors)):
        from horovod_tpu.jax import mpi_ops as _jax_ops

        _probe_device_plane()
        handles = _jax_ops.grouped_allgather_async(
            [_to_jax(t) for t in tensors], names=list(names),
            process_set_id=process_set_id)
        return [_BridgeHandle(h, like=t)
                for h, t in zip(handles, tensors)]
    views = [np.array(_np_view(t), copy=True) for t in tensors]
    inners = eager_ops.grouped_allgather_async(
        views, list(names), process_set_id=process_set_id)
    return [Handle(i, like=t) for i, t in zip(inners, tensors)]


def grouped_allgather(tensors, names=None, process_set_id=0):
    hs = grouped_allgather_async(tensors, names, process_set_id)
    return [h.synchronize() for h in hs]


def broadcast_async_(tensor, root_rank, name=None, process_set_id=0):
    if _use_device_bridge(tensor):
        return _bridge_async(
            "broadcast_async", tensor, tensor, root_rank,
            name or _auto_name("broadcast"), process_set_id=process_set_id)
    view = _np_view(tensor)
    import ctypes

    lib = eager_ops._basics.lib
    h = lib.hvdtpu_enqueue_broadcast(
        (name or _auto_name("broadcast")).encode(),
        view.ctypes.data_as(ctypes.c_void_p), view.ndim,
        eager_ops._shape_array(view.shape),
        eager_ops._dtype_enum(view.dtype), int(root_rank),
        int(process_set_id))
    inner = eager_ops.Handle(eager_ops._check_handle(h, "broadcast"),
                             (view, tensor), view, False, view.dtype)
    return Handle(inner, output_tensor=tensor)


def broadcast_async(tensor, root_rank, name=None, process_set_id=0):
    out = tensor.detach().clone()
    return broadcast_async_(out, root_rank, name, process_set_id)


def broadcast(tensor, root_rank, name=None, process_set_id=0):
    return broadcast_async(tensor, root_rank, name,
                           process_set_id).synchronize()


def broadcast_(tensor, root_rank, name=None, process_set_id=0):
    return broadcast_async_(tensor, root_rank, name,
                            process_set_id).synchronize()


def alltoall_async(tensor, splits=None, name=None, process_set_id=0):
    if _use_device_bridge(tensor):
        return _bridge_async(
            "alltoall_async", tensor, None, splits,
            name or _auto_name("alltoall"), process_set_id=process_set_id)
    view = _np_view(tensor)
    inner = eager_ops.alltoall_async(
        np.array(view, copy=True),
        None if splits is None else np.asarray(splits),
        name or _auto_name("alltoall"), process_set_id=process_set_id)
    return Handle(inner, like=tensor)


def alltoall(tensor, splits=None, name=None, process_set_id=0):
    return alltoall_async(tensor, splits, name, process_set_id).synchronize()


def reducescatter_async(tensor, name=None, op=Average, process_set_id=0):
    if _use_device_bridge(tensor):
        return _bridge_async(
            "reducescatter_async", tensor, None,
            name or _auto_name("reducescatter"), op=op,
            process_set_id=process_set_id)
    view = _np_view(tensor)
    inner = eager_ops.reducescatter_async(
        np.array(view, copy=True), name or _auto_name("reducescatter"),
        op=op, process_set_id=process_set_id)
    return Handle(inner, like=tensor)


def reducescatter(tensor, name=None, op=Average, process_set_id=0):
    return reducescatter_async(tensor, name, op,
                               process_set_id).synchronize()


def grouped_reducescatter_async(tensors, names=None, op=Average,
                                process_set_id=0):
    """Reduce-scatter a list of tensors as ONE atomic negotiation group
    (reference analog: hvd.grouped_reducescatter)."""
    if names is None:
        base = _auto_name("grouped_reducescatter")
        names = [f"{base}.{i}" for i in range(len(tensors))]
    if (tensors and all(_use_device_bridge(t) for t in tensors)
            and not any(_jax_canonicalizes(t.dtype) for t in tensors)):
        from horovod_tpu.jax import mpi_ops as _jax_ops

        _probe_device_plane()
        handles = _jax_ops.grouped_reducescatter_async(
            [_to_jax(t) for t in tensors], names=list(names), op=op,
            process_set_id=process_set_id)
        return [_BridgeHandle(h, like=t)
                for h, t in zip(handles, tensors)]
    views = [np.array(_np_view(t), copy=True) for t in tensors]
    inners = eager_ops.grouped_reducescatter_async(
        views, list(names), op=op, process_set_id=process_set_id)
    return [Handle(i, like=t) for i, t in zip(inners, tensors)]


def grouped_reducescatter(tensors, names=None, op=Average,
                          process_set_id=0):
    hs = grouped_reducescatter_async(tensors, names, op, process_set_id)
    return [h.synchronize() for h in hs]


def synchronize(handle):
    return handle.synchronize()


def poll(handle):
    return handle.poll()


def barrier(process_set_id=0):
    eager_ops.barrier(process_set_id=process_set_id)


def join():
    """Block until every rank has joined; contribute zeros meanwhile.

    Reference analog: ``hvd.join`` (horovod/torch/mpi_ops.py).
    Returns the last rank to join.
    """
    return eager_ops.join()


# Host-path implementations backing _bridge_async's 64-bit staging (the
# in-place variants write into the staged host clone, which
# _HostStagedHandle then copies back to the device tensor).
_HOST_ASYNC = {
    "allreduce_async": allreduce_async_,
    "allgather_async": allgather_async,
    "broadcast_async": broadcast_async_,
    "alltoall_async": alltoall_async,
    "reducescatter_async": reducescatter_async,
}
