"""Torch eager collective ops.

Reference analog: ``horovod/torch/mpi_ops.py`` + ``mpi_ops_v2.cc`` — here
no C extension is needed: CPU torch tensors expose their storage through
numpy views, so the core's ctypes enqueue writes results straight into
tensor memory (the in-place ``allreduce_``/``broadcast_`` semantics).
"""



import numpy as np
import torch

from horovod_tpu.common import eager_ops
from horovod_tpu.common.eager_ops import ReduceOp

Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT
Adasum = ReduceOp.ADASUM

_basics = eager_ops._basics

# In elastic mode (HOROVOD_RDZV_ADDR set) init consults the driver's
# rendezvous for this epoch's rank assignment; static mode unchanged.
from horovod_tpu.common import elastic as _elastic_init_mod
init = _elastic_init_mod.init
shutdown = _basics.shutdown
is_initialized = _basics.is_initialized
rank = _basics.rank
size = _basics.size
local_rank = _basics.local_rank
local_size = _basics.local_size
cross_rank = _basics.cross_rank
cross_size = _basics.cross_size

for _cap in _basics.CAPABILITY_NAMES:
    globals()[_cap] = getattr(_basics, _cap)
start_timeline = _basics.start_timeline
stop_timeline = _basics.stop_timeline

from horovod_tpu.common.auto_name import make_auto_namer

_auto_name = make_auto_namer()



def _np_view(tensor):
    """Contiguous numpy view sharing the CPU tensor's storage."""
    if tensor.device.type != "cpu":
        raise ValueError(
            "horovod_tpu.torch eager ops require CPU tensors (XLA/TPU "
            "tensors go through the in-graph path)")
    t = tensor.detach()
    if not t.is_contiguous():
        raise ValueError("tensor must be contiguous for in-place collectives")
    if t.dtype == torch.bfloat16:
        import ml_dtypes

        return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
    return t.numpy()


class Handle:
    """In-flight op; synchronize() returns the torch result tensor."""

    def __init__(self, inner, output_tensor=None, like=None):
        self._inner = inner
        self._output_tensor = output_tensor
        self._like = like if like is not None else output_tensor

    def poll(self):
        return self._inner.poll()

    def synchronize(self):
        out = self._inner.synchronize()
        if self._output_tensor is not None:
            return self._output_tensor
        np_out = np.asarray(out)
        if self._like is not None and self._like.dtype == torch.bfloat16:
            import ml_dtypes

            return torch.from_numpy(
                np_out.view(np.uint16).copy()).view(torch.bfloat16)
        return torch.from_numpy(np.array(np_out, copy=True))


def allreduce_async_(tensor, name=None, op=Average, prescale_factor=1.0,
                     postscale_factor=1.0, process_set_id=0):
    """In-place async allreduce; result lands in `tensor`'s storage."""
    view = _np_view(tensor)
    inp = np.array(view, copy=True)  # input snapshot; output aliases tensor
    lib = eager_ops._basics.lib
    import ctypes

    h = lib.hvdtpu_enqueue_allreduce(
        (name or _auto_name("allreduce")).encode(),
        inp.ctypes.data_as(ctypes.c_void_p),
        view.ctypes.data_as(ctypes.c_void_p), view.ndim,
        eager_ops._shape_array(view.shape),
        eager_ops._dtype_enum(view.dtype), int(op), float(prescale_factor),
        float(postscale_factor), int(process_set_id))
    inner = eager_ops.Handle(eager_ops._check_handle(h, "allreduce"),
                             (inp, view, tensor), view, False, view.dtype)
    return Handle(inner, output_tensor=tensor)


def allreduce_async(tensor, name=None, op=Average, prescale_factor=1.0,
                    postscale_factor=1.0, process_set_id=0):
    out = tensor.detach().clone()
    return allreduce_async_(out, name, op, prescale_factor, postscale_factor,
                            process_set_id)


def allreduce(tensor, name=None, op=Average, prescale_factor=1.0,
              postscale_factor=1.0, process_set_id=0):
    return allreduce_async(tensor, name, op, prescale_factor,
                           postscale_factor, process_set_id).synchronize()


def allreduce_(tensor, name=None, op=Average, prescale_factor=1.0,
               postscale_factor=1.0, process_set_id=0):
    return allreduce_async_(tensor, name, op, prescale_factor,
                            postscale_factor, process_set_id).synchronize()


def grouped_allreduce_async_(tensors, names=None, op=Average,
                             process_set_id=0):
    if names is None:
        base = _auto_name("grouped_allreduce")
        names = [f"{base}.{i}" for i in range(len(tensors))]
    return [allreduce_async_(t, n, op, process_set_id=process_set_id)
            for t, n in zip(tensors, names)]


def grouped_allreduce_(tensors, names=None, op=Average, process_set_id=0):
    hs = grouped_allreduce_async_(tensors, names, op, process_set_id)
    return [h.synchronize() for h in hs]


def allgather_async(tensor, name=None, process_set_id=0):
    view = _np_view(tensor)
    inner = eager_ops.allgather_async(
        np.array(view, copy=True), name or _auto_name("allgather"),
        process_set_id=process_set_id)
    return Handle(inner, like=tensor)


def allgather(tensor, name=None, process_set_id=0):
    return allgather_async(tensor, name, process_set_id).synchronize()


def broadcast_async_(tensor, root_rank, name=None, process_set_id=0):
    view = _np_view(tensor)
    import ctypes

    lib = eager_ops._basics.lib
    h = lib.hvdtpu_enqueue_broadcast(
        (name or _auto_name("broadcast")).encode(),
        view.ctypes.data_as(ctypes.c_void_p), view.ndim,
        eager_ops._shape_array(view.shape),
        eager_ops._dtype_enum(view.dtype), int(root_rank),
        int(process_set_id))
    inner = eager_ops.Handle(eager_ops._check_handle(h, "broadcast"),
                             (view, tensor), view, False, view.dtype)
    return Handle(inner, output_tensor=tensor)


def broadcast_async(tensor, root_rank, name=None, process_set_id=0):
    out = tensor.detach().clone()
    return broadcast_async_(out, root_rank, name, process_set_id)


def broadcast(tensor, root_rank, name=None, process_set_id=0):
    return broadcast_async(tensor, root_rank, name,
                           process_set_id).synchronize()


def broadcast_(tensor, root_rank, name=None, process_set_id=0):
    return broadcast_async_(tensor, root_rank, name,
                            process_set_id).synchronize()


def alltoall_async(tensor, splits=None, name=None, process_set_id=0):
    view = _np_view(tensor)
    inner = eager_ops.alltoall_async(
        np.array(view, copy=True),
        None if splits is None else np.asarray(splits),
        name or _auto_name("alltoall"), process_set_id=process_set_id)
    return Handle(inner, like=tensor)


def alltoall(tensor, splits=None, name=None, process_set_id=0):
    return alltoall_async(tensor, splits, name, process_set_id).synchronize()


def reducescatter_async(tensor, name=None, op=Average, process_set_id=0):
    view = _np_view(tensor)
    inner = eager_ops.reducescatter_async(
        np.array(view, copy=True), name or _auto_name("reducescatter"),
        op=op, process_set_id=process_set_id)
    return Handle(inner, like=tensor)


def reducescatter(tensor, name=None, op=Average, process_set_id=0):
    return reducescatter_async(tensor, name, op,
                               process_set_id).synchronize()


def synchronize(handle):
    return handle.synchronize()


def poll(handle):
    return handle.poll()


def barrier(process_set_id=0):
    eager_ops.barrier(process_set_id=process_set_id)


def join():
    """Block until every rank has joined; contribute zeros meanwhile.

    Reference analog: ``hvd.join`` (horovod/torch/mpi_ops.py).
    Returns the last rank to join.
    """
    return eager_ops.join()
