"""Cross-rank synchronized BatchNorm.

Reference analog: ``horovod/torch/sync_batch_norm.py`` — batch statistics
are allreduce-averaged across ranks in forward, and the two gradient sums
are allreduced in backward, so the layer behaves as if the global batch
were on one device. Assumes equal per-rank batch sizes (the reference's
common case; it gathers counts — we keep the fast equal-size path).
"""

import torch
from torch.nn.modules.batchnorm import _BatchNorm

from horovod_tpu.torch import mpi_ops


class _SyncBatchNormFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, x, weight, bias, running_mean, running_var, eps,
                momentum, training, name):
        c = x.size(1)
        dims = [0] + list(range(2, x.dim()))
        shape = [1, c] + [1] * (x.dim() - 2)

        if training:
            local_count = x.numel() // c
            mean = x.mean(dims)
            sqmean = (x * x).mean(dims)
            stats = mpi_ops.allreduce(torch.cat([mean, sqmean]),
                                      op=mpi_ops.Average,
                                      name=f"sync_bn.{name}.fwd")
            mean, sqmean = stats[:c], stats[c:]
            var = (sqmean - mean * mean).clamp_(min=0)
            world = mpi_ops.size()
            total = local_count * world
            if running_mean is not None:
                unbiased = var * total / max(total - 1, 1)
                running_mean.mul_(1 - momentum).add_(mean, alpha=momentum)
                running_var.mul_(1 - momentum).add_(unbiased,
                                                    alpha=momentum)
        else:
            mean, var = running_mean, running_var

        invstd = torch.rsqrt(var + eps)
        xhat = (x - mean.view(shape)) * invstd.view(shape)
        out = xhat * weight.view(shape) + bias.view(shape)
        ctx.save_for_backward(xhat, weight, invstd)
        ctx.dims = dims
        ctx.shape = shape
        ctx.training = training
        ctx.name = name
        return out

    @staticmethod
    def backward(ctx, dy):
        xhat, weight, invstd = ctx.saved_tensors
        dims, shape = ctx.dims, ctx.shape
        c = xhat.size(1)
        n = xhat.numel() // c

        grad_weight = (dy * xhat).sum(dims)
        grad_bias = dy.sum(dims)

        if ctx.training:
            stats = mpi_ops.allreduce(
                torch.cat([grad_bias, grad_weight]) / n,
                op=mpi_ops.Average, name=f"sync_bn.{ctx.name}.bwd")
            mean_dy, mean_dy_xhat = stats[:c], stats[c:]
            dx = (weight * invstd).view(shape) * (
                dy - mean_dy.view(shape) - xhat * mean_dy_xhat.view(shape))
        else:
            dx = (weight * invstd).view(shape) * dy
        return dx, grad_weight, grad_bias, None, None, None, None, None, None


class SyncBatchNorm(_BatchNorm):
    """Drop-in replacement for BatchNorm1d/2d/3d with cross-rank stats."""

    _counter = 0

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._name = f"bn{SyncBatchNorm._counter}"
        SyncBatchNorm._counter += 1

    def _check_input_dim(self, x):
        if x.dim() < 2:
            raise ValueError(f"expected at least 2D input, got {x.dim()}D")

    def forward(self, x):
        self._check_input_dim(x)
        training = self.training or not self.track_running_stats
        if not training or mpi_ops.size() == 1:
            return torch.nn.functional.batch_norm(
                x, self.running_mean, self.running_var, self.weight,
                self.bias, training, self.momentum, self.eps)
        if self.track_running_stats:
            self.num_batches_tracked.add_(1)
        return _SyncBatchNormFn.apply(
            x, self.weight, self.bias, self.running_mean, self.running_var,
            self.eps, self.momentum, training, self._name)
