"""Gradient compression (torch flavor).

Reference analog: ``horovod/torch/compression.py``.
"""

import torch


class Compressor:
    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating_point and tensor.dtype != torch.float16:
            return tensor.to(torch.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.to(ctx) if ctx is not None else tensor


class BFloat16Compressor(Compressor):
    """TPU-flavored 2x compression (fp32 exponent range, no overflow
    handling needed) — net-new vs reference."""

    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating_point and tensor.dtype != torch.bfloat16:
            return tensor.to(torch.bfloat16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.to(ctx) if ctx is not None else tensor


class Compression:
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BFloat16Compressor
