"""horovod_tpu.torch — the PyTorch frontend
(``import horovod_tpu.torch as hvd``).

Reference analog: ``horovod/torch/__init__.py`` — same API: init/rank/
size, (grouped_)allreduce(_async)(_), allgather, broadcast(_), alltoall,
reducescatter, DistributedOptimizer with per-param hooks,
broadcast_parameters / broadcast_optimizer_state / broadcast_object,
Compression, SyncBatchNorm.
"""

from horovod_tpu.common.process_sets import (  # noqa: F401
    ProcessSet,
    add_process_set,
    global_process_set,
    remove_process_set,
)
from horovod_tpu.common.exceptions import (  # noqa: F401
    HorovodInternalError,
    HorovodPeerFailureError,
    HorovodWireCorruptionError,
    HostsUpdatedInterrupt,
)
from horovod_tpu.torch.compression import Compression  # noqa: F401
from horovod_tpu.torch.functions import (  # noqa: F401
    allgather_object,
    broadcast_object,
    broadcast_optimizer_state,
    broadcast_parameters,
)
from horovod_tpu.torch.mpi_ops import (  # noqa: F401
    Adasum,
    Average,
    Max,
    Min,
    Product,
    ReduceOp,
    Sum,
    allgather,
    allgather_async,
    allreduce,
    allreduce_,
    allreduce_async,
    allreduce_async_,
    alltoall,
    alltoall_async,
    barrier,
    join,
    broadcast,
    broadcast_,
    broadcast_async,
    broadcast_async_,
    cross_rank,
    cross_size,
    grouped_allgather,
    grouped_allgather_async,
    grouped_allreduce_,
    grouped_allreduce_async_,
    grouped_reducescatter,
    grouped_reducescatter_async,
    init,
    is_initialized,
    local_rank,
    debug_port,
    events,
    metrics,
    metrics_reset,
    local_size,
    poll,
    rank,
    reducescatter,
    reducescatter_async,
    shutdown,
    size,
    start_timeline,
    stop_timeline,
    synchronize,
)
from horovod_tpu.torch.optimizer import DistributedOptimizer  # noqa: F401
from horovod_tpu.torch.sync_batch_norm import SyncBatchNorm  # noqa: F401

from horovod_tpu.torch import elastic  # noqa: E402,F401

# Capability surface (reference analog: hvd.mpi_built()/gloo_built()/...).
from horovod_tpu.torch.mpi_ops import (  # noqa: F401,E402
    ccl_built,
    cuda_built,
    ddl_built,
    gloo_built,
    gloo_enabled,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rocm_built,
    xla_built,
    xla_enabled,
)
