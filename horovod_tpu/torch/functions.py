"""Model/optimizer state broadcast helpers (torch flavor).

Reference analog: ``horovod/torch/functions.py``.
"""

import io
import pickle

import numpy as np
import torch

from horovod_tpu.torch import mpi_ops


def broadcast_parameters(params, root_rank=0):
    """Broadcast model parameters in place.

    `params` is either a ``state_dict()`` (name->tensor) or an iterable of
    (name, tensor) pairs / module.named_parameters().
    """
    if hasattr(params, "items"):
        items = list(params.items())
    else:
        items = list(params)
    handles = []
    for name, p in items:
        if not torch.is_tensor(p):
            continue
        handles.append(mpi_ops.broadcast_async_(
            p.data, root_rank, name=f"broadcast.param.{name}"))
    for h in handles:
        h.synchronize()


def broadcast_optimizer_state(optimizer, root_rank=0):
    """Broadcast an optimizer's state dict from root_rank, in place.

    Tensor state broadcasts natively; scalars ride via broadcast_object
    (reference does the same dance with an identity-mapped state dict).
    """
    state = optimizer.state_dict()
    # Non-tensor payload (param_groups + scalar state) by pickle:
    scalar_blob = broadcast_object(
        _strip_tensors(state), root_rank, name="opt_state.scalars")
    if mpi_ops.rank() != root_rank:
        _merge_scalars(state, scalar_blob)
    handles = []
    for sid, pstate in sorted(state.get("state", {}).items(),
                              key=lambda kv: str(kv[0])):
        for key, val in sorted(pstate.items()):
            if torch.is_tensor(val):
                handles.append(mpi_ops.broadcast_async_(
                    val, root_rank, name=f"opt.{sid}.{key}"))
    for h in handles:
        h.synchronize()
    optimizer.load_state_dict(state)


def _strip_tensors(obj):
    if torch.is_tensor(obj):
        return None
    if isinstance(obj, dict):
        return {k: _strip_tensors(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_strip_tensors(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def _merge_scalars(dst, src):
    if isinstance(dst, dict):
        for k, v in dst.items():
            if torch.is_tensor(v):
                continue
            if isinstance(v, (dict, list)):
                _merge_scalars(v, src[k] if isinstance(src, dict) else None)
            elif isinstance(src, dict) and k in src and src[k] is not None:
                dst[k] = src[k]
    elif isinstance(dst, list) and isinstance(src, (list, tuple)):
        for i, v in enumerate(dst):
            if isinstance(v, (dict, list)):
                _merge_scalars(v, src[i])
            elif not torch.is_tensor(v) and src[i] is not None:
                dst[i] = src[i]


def broadcast_object(obj, root_rank=0, name=None):
    """Pickle-broadcast an arbitrary object (reference:
    hvd.broadcast_object)."""
    name = name or "broadcast_object"
    if mpi_ops.rank() == root_rank:
        buf = io.BytesIO()
        pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
        payload = torch.from_numpy(
            np.frombuffer(buf.getvalue(), dtype=np.uint8).copy())
    else:
        payload = torch.zeros(0, dtype=torch.uint8)
    sz = torch.tensor([payload.numel()], dtype=torch.int64)
    sz = mpi_ops.broadcast(sz, root_rank, name=f"{name}.len")
    if mpi_ops.rank() != root_rank:
        payload = torch.zeros(int(sz[0]), dtype=torch.uint8)
    payload = mpi_ops.broadcast(payload, root_rank, name=f"{name}.data")
    return pickle.loads(payload.numpy().tobytes())


def allgather_object(obj, name=None):
    from horovod_tpu.common.elastic import _allgather_object

    return _allgather_object(obj, name=name or "allgather_object")
