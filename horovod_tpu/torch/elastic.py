"""Elastic training for the PyTorch frontend.

Reference analog: ``horovod/torch/elastic/state.py`` (``TorchState``:
per-handler commit/restore of model and optimizer state_dicts, rank-0
broadcast on sync) + ``horovod/torch/elastic/__init__.py`` (``run``).
"""

import copy

from horovod_tpu.common import elastic as _elastic
from horovod_tpu.common.elastic import State

run = _elastic.run_fn
init = _elastic.init
reset = _elastic.reset
ObjectState = _elastic.ObjectState
survivors = _elastic.survivors
rejoin = _elastic.rejoin


def _cpu_state_dict(sd):
    import torch

    def conv(v):
        if isinstance(v, torch.Tensor):
            return v.detach().cpu().clone()
        if isinstance(v, dict):
            return {k: conv(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return type(v)(conv(x) for x in v)
        return copy.deepcopy(v)

    return conv(sd)


class TorchState(State):
    """Elastic state for a model + optimizer (+ extra picklable attrs).

    Reference analog: hvd.elastic.TorchState. ``checkpoint_dir`` makes
    every ``commit()`` also durable on disk (whole-state pickle through
    the orbax engine — torch state dicts are host tensors, so there is
    no sharded-array layout to preserve) and ``resume()`` reloads the
    newest commit after a full job restart; the reference's state is
    memory-only (SURVEY.md §5.4).
    """

    def __init__(self, model=None, optimizer=None, checkpoint_dir=None,
                 **kwargs):
        super().__init__()
        self.model = model
        self.optimizer = optimizer
        self._extra_keys = list(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._ckpt_mgr = None
        self._commit_step = 0
        if checkpoint_dir is not None:
            from horovod_tpu.checkpoint import CheckpointManager

            self._ckpt_mgr = CheckpointManager(checkpoint_dir)
            self._commit_step = self._ckpt_mgr.latest_step() or 0
        self.save()

    def commit(self):
        self.save()
        if self._ckpt_mgr is not None:
            import pickle

            import numpy as np

            self._commit_step += 1
            blob = np.frombuffer(pickle.dumps(self._saved),
                                 np.uint8).copy()
            self._ckpt_mgr.save(self._commit_step, {"state": blob})
        self.check_host_updates()

    def resume(self):
        """Load the newest on-disk commit (cold restart). Returns the
        restored step, or None when no checkpoint exists yet."""
        if self._ckpt_mgr is None:
            raise ValueError(
                "TorchState was created without checkpoint_dir")
        step = self._ckpt_mgr.latest_step()
        if step is None:
            return None
        import pickle

        import numpy as np

        blob = self._ckpt_mgr.restore(step)["state"]
        self._saved = pickle.loads(np.asarray(blob).tobytes())
        self._commit_step = step
        self.restore()
        return step

    def save(self):
        self._saved = {
            "model": _cpu_state_dict(self.model.state_dict())
            if self.model is not None else None,
            "optimizer": _cpu_state_dict(self.optimizer.state_dict())
            if self.optimizer is not None else None,
            "extra": {k: copy.deepcopy(getattr(self, k))
                      for k in self._extra_keys},
        }

    def restore(self):
        if self.model is not None and self._saved["model"] is not None:
            self.model.load_state_dict(copy.deepcopy(self._saved["model"]))
        if self.optimizer is not None and \
                self._saved["optimizer"] is not None:
            self.optimizer.load_state_dict(
                copy.deepcopy(self._saved["optimizer"]))
        for k, v in self._saved["extra"].items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self):
        _elastic._sync_state(self, "elastic.torch_state")
