"""Cross-rank critical-path attribution over step windows.

The straggler table (``report.py``) attributes by *arrival order* at
the coordinator — who submitted last. This module attributes at *span
granularity*: per step, which rank — and which phase of that rank's
step — actually bounded the step's wall time. It consumes per-rank
event-ring dumps in the black-box schema (``step_begin``/``step_end``
windows from ``hvdtpu_step_mark`` plus the ``wire_span``/
``negotiate_*``/``stall``/``retry_window``/``inject`` events inside
them), merges them onto one wall axis via the header's
``(unix_us, steady_us)`` anchor pair (the r15 CLOCK_SYNC contract),
and decomposes each rank's step window into four phases::

    wire        = interval union of its wire spans (wall time with
                  >= 1 transfer in flight — the overlap ledger's
                  "exposed" measure, recomputed offline)
    negotiation = union of negotiate_begin -> negotiate_end cycles
    stall       = union of recorded stall evidence: stall events,
                  healing-ladder retry windows, and the gap after an
                  injected chaos delay
    compute     = window - union(everything above): time the runtime
                  recorded NO activity for — local work (or an
                  uninstrumented sleep)

**Blocking rank**: in a synchronous step, a rank's wire spans stretch
to absorb waiting for slower peers, so wire time is where OTHER ranks'
slowness pools. The rank that bounded the step is the one with the
most NON-wire time (``window - wire``) — everyone else was, for that
long, waiting on the wire for it. **Blocking phase** is the largest
share among that rank's four phases (wire wins only when the step is
genuinely transport-bound on the blocking rank too).

Phases may overlap on the wall clock (a negotiation cycle can run
under a wire span of the previous collective), so per-rank shares need
not sum to the window; ``compute`` is always the exact remainder of
the union of the other three.

CLI: ``python -m horovod_tpu.telemetry.report --critical-path
<dumps-or-dir>``. Dumps come from a fault (the core's black box) or
from a live rank via :func:`write_event_dump` (what ``make perf-smoke``
and the simworld harness use).
"""

import json
import os
import time
from collections import defaultdict

from horovod_tpu.telemetry import postmortem

# kInject "action" values (csrc/operations.cc FaultAction) that sleep
# between the inject event and the next runtime activity — the gap is
# stall evidence. stop (1) SIGSTOPs the whole process for its param;
# delay (4) sleeps the background loop. The others either kill the
# process or are instantaneous.
_INJECT_GAP_ACTIONS = (1, 4)


def union_measure(intervals, lo=None, hi=None):
    """Total length of the union of ``[start, end)`` intervals, clipped
    to ``[lo, hi]`` when given. Abutting intervals merge, nested ones
    collapse, zero-length ones contribute nothing — the same sweep the
    core's overlap ledger runs (csrc/metrics.cc OverlapLedger)."""
    clipped = []
    for a, b in intervals:
        if lo is not None:
            a = max(a, lo)
        if hi is not None:
            b = min(b, hi)
        if b > a:
            clipped.append((a, b))
    clipped.sort()
    total = 0
    cur_lo, cur_hi = None, None
    for a, b in clipped:
        if cur_hi is None:
            cur_lo, cur_hi = a, b
        elif a <= cur_hi:
            cur_hi = max(cur_hi, b)
        else:
            total += cur_hi - cur_lo
            cur_lo, cur_hi = a, b
    if cur_hi is not None:
        total += cur_hi - cur_lo
    return total


def _wall(ev, hdr):
    return postmortem._wall_us(ev, hdr)


def step_windows(dump):
    """``{step_id: (begin_wall_us, end_wall_us)}`` from one rank's
    dump. A ``step_end`` whose ``step_begin`` aged out of the ring
    (window spanning a ring wrap) opens at the dump's earliest event —
    the window is truncated, not dropped, so a long step that evicted
    its own begin mark still attributes."""
    hdr = dump["header"]
    events = dump["events"]
    first_wall = _wall(events[0], hdr) if events else 0
    begins, windows = {}, {}
    for ev in events:
        if ev.get("type") == "step_begin":
            begins[ev.get("step")] = _wall(ev, hdr)
        elif ev.get("type") == "step_end":
            sid = ev.get("step")
            windows[sid] = (begins.pop(sid, first_wall), _wall(ev, hdr))
    return windows


def intersect_intervals(a, b):
    """Pairwise intersection of two ``[start, end)`` interval lists —
    the offline twin of the overlap ledger's exposure clip (wire time
    under the union of API-thread waits)."""
    a = sorted((lo, hi) for lo, hi in a if hi > lo)
    b = sorted((lo, hi) for lo, hi in b if hi > lo)
    out = []
    i = j = 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            out.append((lo, hi))
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return out


def phase_intervals(dump):
    """Wall-axis intervals per phase (``wire``/``negotiation``/
    ``stall``, plus the raw ``wait`` blocks) for one rank's dump;
    ``compute`` is derived later as the per-window remainder.

    When the dump carries ``wait`` events (hvdtpu_wait blocks, r23),
    ``wire`` is the ledger's *exposed* measure — wire spans clipped to
    the union of the waits — so a fused lane whose transfers drained
    under host compute attributes as compute-bound, exactly like the
    live overlap ledger. Dumps without wait events (pre-r23, or
    synthesized) keep the raw span union."""
    hdr = dump["header"]
    out = {"wire": [], "negotiation": [], "stall": [], "wait": []}
    spans = []
    nego_begin = None
    prev_wall = None
    pending_delay = None
    for ev in dump["events"]:
        wall = _wall(ev, hdr)
        typ = ev.get("type")
        if pending_delay is not None and typ != "inject":
            # An injected straggler delay sleeps between the inject
            # event and whatever the loop does next: that gap IS the
            # stall (the chaos lane's ground truth, docs/elastic.md).
            # A wire_span (and a wait) is stamped at its END — close
            # the gap at the interval's START so the stall does not
            # swallow wire time.
            end = wall
            if typ in ("wire_span", "wait"):
                end = wall - int(ev.get("dur_us", 0))
            if end > pending_delay:
                out["stall"].append((pending_delay, end))
            pending_delay = None
        if typ == "wire_span":
            dur = int(ev.get("dur_us", 0))
            spans.append((wall - dur, wall))
        elif typ == "wait":
            dur = int(ev.get("dur_us", 0))
            out["wait"].append((wall - dur, wall))
        elif typ == "negotiate_begin":
            nego_begin = wall
        elif typ == "negotiate_end":
            if nego_begin is not None:
                out["negotiation"].append((nego_begin, wall))
                nego_begin = None
        elif typ == "stall":
            out["stall"].append(
                (wall - int(ev.get("waited_s", 0)) * 1_000_000, wall))
        elif typ == "retry_window":
            out["stall"].append(
                (wall - int(ev.get("window_ms", 0)) * 1000, wall))
        elif typ == "inject" and ev.get("action") in _INJECT_GAP_ACTIONS:
            pending_delay = wall
        prev_wall = wall
    if pending_delay is not None and prev_wall is not None:
        out["stall"].append((pending_delay, prev_wall))
    out["wire"] = (intersect_intervals(spans, out["wait"])
                   if out["wait"] else spans)
    return out


def critical_path(paths_or_dir, dump_index=-1):
    """Merge per-rank dumps and attribute, per step, the blocking rank
    and phase. Returns::

        {"ranks": [...],
         "steps": [{"step": id, "wall_ms": ..., "blocking_rank": r,
                    "phase": "compute|wire|negotiation|stall",
                    "per_rank": {rank: {window_ms, wire_ms,
                                        negotiation_ms, stall_ms,
                                        compute_ms, self_ms}}}, ...],
         "blocking_counts": {rank: steps it bounded},
         "phase_counts": {phase: steps it bounded}}

    ``self_ms`` is ``window - wire`` — the rank's own contribution to
    step length; its argmax is the blocking rank (module docstring).
    Steps are matched across ranks by the monotonic step id (every
    rank's marks count the same boundaries when one driver — StepTimer
    or the eager optimizer — paces the SPMD loop).
    """
    paths = postmortem.collect_paths(paths_or_dir)
    dumps = {}
    for path in paths:
        loaded = postmortem.load_blackbox(path)
        if not loaded:
            continue
        dump = loaded[dump_index]
        dumps[dump["header"].get("rank", -1)] = dump
    if not dumps:
        raise ValueError(f"no event dumps found in {paths_or_dir!r}")

    windows = {r: step_windows(d) for r, d in dumps.items()}
    phases = {r: phase_intervals(d) for r, d in dumps.items()}
    step_ids = sorted(set().union(*(set(w) for w in windows.values())))

    steps = []
    blocking_counts = defaultdict(int)
    phase_counts = defaultdict(int)
    for sid in step_ids:
        per_rank = {}
        for rank, w in windows.items():
            if sid not in w:
                continue
            lo, hi = w[sid]
            shares = {
                ph: union_measure(phases[rank][ph], lo, hi)
                for ph in ("wire", "negotiation", "stall")
            }
            busy = union_measure(
                phases[rank]["wire"] + phases[rank]["negotiation"]
                + phases[rank]["stall"], lo, hi)
            shares["compute"] = (hi - lo) - busy
            per_rank[rank] = {
                "window_ms": round((hi - lo) / 1000.0, 3),
                "self_ms": round((hi - lo - shares["wire"]) / 1000.0, 3),
                **{f"{ph}_ms": round(v / 1000.0, 3)
                   for ph, v in shares.items()},
            }
        if not per_rank:
            continue
        blocking = max(per_rank,
                       key=lambda r: (per_rank[r]["self_ms"],
                                      per_rank[r]["window_ms"]))
        b = per_rank[blocking]
        phase = max(("wire", "negotiation", "stall", "compute"),
                    key=lambda ph: b[f"{ph}_ms"])
        blocking_counts[blocking] += 1
        phase_counts[phase] += 1
        steps.append({
            "step": sid,
            "wall_ms": max(d["window_ms"] for d in per_rank.values()),
            "blocking_rank": blocking,
            "phase": phase,
            "per_rank": per_rank,
        })
    return {
        "ranks": sorted(dumps),
        "steps": steps,
        "blocking_counts": dict(blocking_counts),
        "phase_counts": dict(phase_counts),
    }


def format_critical_path(analysis, max_steps=40):
    """Operator-facing rendering: one line per step plus the summary
    attribution."""
    lines = []
    bc = analysis["blocking_counts"]
    if bc:
        worst = max(bc, key=bc.get)
        lines.append(
            f"critical path: rank {worst} bounded {bc[worst]} of "
            f"{len(analysis['steps'])} steps "
            f"(phases: {dict(sorted(analysis['phase_counts'].items()))})")
    lines.append(f"{'step':>6} {'wall ms':>9} {'rank':>5} {'phase':>12} "
                 f"{'self ms':>9} {'wire ms':>9} {'compute ms':>11}")
    for s in analysis["steps"][-max_steps:]:
        b = s["per_rank"][s["blocking_rank"]]
        lines.append(
            f"{s['step']:>6} {s['wall_ms']:>9.3f} "
            f"{s['blocking_rank']:>5} {s['phase']:>12} "
            f"{b['self_ms']:>9.3f} {b['wire_ms']:>9.3f} "
            f"{b['compute_ms']:>11.3f}")
    return "\n".join(lines)


def write_event_dump(path, rank, size, events, epoch=0):
    """Write a LIVE rank's ring events (``hvd.events()`` /
    ``events_drain()`` dicts) in the black-box dump schema, so the
    critical-path and post-mortem tooling consume healthy-run traces
    exactly like fault dumps. The ``(unix_us, steady_us)`` anchor pair
    is sampled together here — call it on the rank whose events these
    are (the anchor maps THAT process's steady clock to the wall)."""
    header = {
        "kind": "blackbox_header", "rank": rank, "size": size,
        "epoch": epoch, "unix_us": int(time.time() * 1e6),
        "steady_us": _steady_us(), "fault": {},
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        f.write(json.dumps(header) + "\n")
        for ev in events:
            f.write(json.dumps(ev) + "\n")
    return path


def _steady_us():
    """The same steady clock the core stamps events with
    (CLOCK_MONOTONIC microseconds — csrc/metrics.cc MetricsNowUs)."""
    return int(time.clock_gettime(time.CLOCK_MONOTONIC) * 1e6)
