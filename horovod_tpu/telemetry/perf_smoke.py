"""Two-process step-anatomy smoke: ``make perf-smoke``.

The step-anatomy layer end to end, one command, no accelerator: 2 real
ranks drive an eager allreduce loop under a :class:`StepTimer` (whose
marks open/close the core's step windows) while a chaos ``delay:<ms>``
injection makes rank 1 a straggler for one deterministic step. Asserts:

1. **overlap-ledger reconciliation** — per plane, exposed + hidden ==
   total wire time EXACTLY, and the ledger's step-scoped totals match
   the independent ``wire_us`` histogram within 1% (the acceptance
   bound; the two are recorded by different code paths around the same
   transport calls);
2. **critical-path attribution** — the cross-rank merge over live
   event dumps (``report.py --critical-path``) names the DELAYED rank,
   with phase ``stall``, on exactly the step the injection hit — and
   does NOT blame it for the healthy steps.
"""

import os
import subprocess
import sys
import tempfile
import time

DELAY_MS = 300
DELAY_AT_OP = 9  # collective index the chaos delay fires at (rank 1)
STEPS = 8
OPS_PER_STEP = 2
WARMUP_OPS = 2
ELEMS = 1 << 18  # 1 MiB f32 per op: wire spans are ms-scale, so the
#                  scope-overhead slack inside the 1% bound is real


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def worker(tmpdir):
    import numpy as np

    from horovod_tpu.common import eager_ops
    from horovod_tpu.common.basics import HorovodBasics
    from horovod_tpu.telemetry import critpath
    from horovod_tpu.telemetry.step_timer import StepTimer

    b = HorovodBasics()
    b.init()
    rank, size = b.rank(), b.size()
    if rank == 1:
        b.set_fault_inject_spec(f"1:{DELAY_AT_OP}:delay:{DELAY_MS}")
    x = np.full(ELEMS, float(rank + 1), np.float32)
    for i in range(WARMUP_OPS):  # outside any step: unattributed lane
        eager_ops.allreduce_async(x, f"warm.{i}").synchronize()

    snap0 = b.metrics_snapshot()
    timer = StepTimer()
    for s in range(STEPS):
        with timer.step():
            for i in range(OPS_PER_STEP):
                out = eager_ops.allreduce_async(
                    x, f"step.{s}.{i}").synchronize()
        assert out[0] == 3.0, out[0]  # SUM over ranks 1.0 + 2.0
    snap1 = b.metrics_snapshot()

    # (1) Ledger reconciliation. Exact per plane by construction...
    ov0, ov1 = (s["wire"]["overlap"] for s in (snap0, snap1))
    for plane in ("intra", "cross"):
        p = ov1[plane]
        assert p["exposed_us"] + p["hidden_us"] == p["total_us"], ov1
    # ...and within 1% of the independently recorded wire_us histogram
    # over the stepped window (plus the warmup delta that the ledger
    # books as unattributed).
    ledger_us = sum(ov1[p]["total_us"] - ov0[p]["total_us"]
                    for p in ("intra", "cross"))
    ledger_us += ov1["unattributed_us"] - ov0["unattributed_us"]
    wire_us = (snap1["wire_us"]["sum_us"] - snap0["wire_us"]["sum_us"])
    drift = abs(ledger_us - wire_us) / max(wire_us, 1)
    assert drift < 0.01, (
        f"overlap ledger vs wire_us drift {drift:.4f} "
        f"(ledger {ledger_us} us, wire_us {wire_us} us)")
    assert ov1["steps"] - ov0["steps"] == STEPS, (ov0, ov1)
    assert len(timer.overlap_per_step) == STEPS

    # Export this rank's ring events as a live (non-fault) dump for the
    # cross-rank critical-path merge.
    critpath.write_event_dump(
        os.path.join(tmpdir, "dumps", f"blackbox-rank{rank}.jsonl"),
        rank, size, b.events())
    # r12 ordering discipline: don't tear sockets down under the peer.
    time.sleep(0.5)
    b.shutdown()
    print(f"PERF_SMOKE_OK rank={rank} drift={drift:.4f} "
          f"ledger_ms={ledger_us / 1000.0:.1f}")
    return 0


def main():
    if "--worker" in sys.argv:
        return worker(os.environ["HVDTPU_SMOKE_TMP"])

    from horovod_tpu.telemetry import critpath, report

    size = 2
    port = _free_port()
    with tempfile.TemporaryDirectory() as tmpdir:
        procs = []
        for rank in range(size):
            env = dict(os.environ,
                       HOROVOD_RANK=str(rank), HOROVOD_SIZE=str(size),
                       HOROVOD_CONTROLLER_ADDR="127.0.0.1",
                       HOROVOD_CONTROLLER_PORT=str(port),
                       HVDTPU_SMOKE_TMP=tmpdir,
                       JAX_PLATFORMS="cpu")
            procs.append(subprocess.Popen(
                [sys.executable, "-m",
                 "horovod_tpu.telemetry.perf_smoke", "--worker"],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        failed = False
        for rank, p in enumerate(procs):
            try:
                out, _ = p.communicate(timeout=180)
            except subprocess.TimeoutExpired:
                p.kill()
                out = "TIMEOUT"
            ok = p.returncode == 0 and "PERF_SMOKE_OK" in out
            print(out.strip())
            if not ok:
                print(f"rank {rank} FAILED (rc={p.returncode})")
                failed = True
        if failed:
            return 1

        dump_dir = os.path.join(tmpdir, "dumps")
        analysis = critpath.critical_path(dump_dir)
        assert len(analysis["steps"]) == STEPS, analysis["steps"]
        # Locate the injected step: the inject event in rank 1's dump.
        dumps = {d["header"]["rank"]: d for d in
                 (critpath.postmortem.load_blackbox(
                     os.path.join(dump_dir, f"blackbox-rank{r}.jsonl"))
                  [-1] for r in range(size))}
        inject = [e for e in dumps[1]["events"]
                  if e.get("type") == "inject"]
        assert inject, "chaos delay never fired"
        wall = critpath._wall(inject[0], dumps[1]["header"])
        windows = critpath.step_windows(dumps[1])
        delayed = [sid for sid, (lo, hi) in windows.items()
                   if lo <= wall <= hi]
        assert delayed, (wall, windows)
        hit = delayed[0]
        by_step = {s["step"]: s for s in analysis["steps"]}
        # The delayed step blames rank 1's injected stall...
        assert by_step[hit]["blocking_rank"] == 1, by_step[hit]
        assert by_step[hit]["phase"] == "stall", by_step[hit]
        # ...and attribution is per-span EVIDENCE, not reputation: no
        # healthy step carries a stall verdict (the only stall evidence
        # in this run is the injection), and the delayed step's wall
        # time dominates every healthy step's.
        healthy = [s for s in analysis["steps"] if s["step"] != hit]
        assert len(healthy) == STEPS - 1
        assert all(s["phase"] != "stall" for s in healthy), healthy
        assert all(by_step[hit]["wall_ms"] > s["wall_ms"] + DELAY_MS / 2
                   for s in healthy), analysis["steps"]
        print(critpath.format_critical_path(analysis))
        rc = report.main(["--critical-path", dump_dir])
        assert rc == 0
        print(f"perf-smoke: OK (step {hit} blamed on rank 1 / stall; "
              "ledger reconciled within 1% on both ranks)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
