"""Two-process metrics smoke: ``make metrics-smoke``.

Launches 2 real ranks over the eager host ring, drives a few steps of
named allreduces, and asserts a sane metrics snapshot on every rank
(exact byte accounting, steady-state cache hits, live cycle counters).
Each rank also records a timeline; the parent merges them through
``telemetry.report`` and checks the straggler table — the whole
telemetry stack, one command, no accelerator.
"""

import json
import os
import subprocess
import sys
import tempfile

STEPS = 6
TENSORS = 4
ELEMS = 1024  # float32


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def worker(tmpdir):
    import numpy as np

    from horovod_tpu.common import eager_ops
    from horovod_tpu.common.basics import HorovodBasics
    from horovod_tpu import telemetry

    b = HorovodBasics()
    b.init()
    rank, size = b.rank(), b.size()
    b.start_timeline(os.path.join(tmpdir, f"tl.{rank}.json"))
    try:
        for step in range(STEPS):
            handles = [
                eager_ops.allreduce_async(
                    np.full(ELEMS, float(rank + step), np.float32),
                    f"grad.{i}")
                for i in range(TENSORS)
            ]
            for i, h in enumerate(handles):
                out = h.synchronize()
                expect = sum(r + step for r in range(size))
                assert out[0] == expect, (i, out[0], expect)
        eager_ops.barrier()
        snap = telemetry.snapshot()
        # Exact byte accounting: every allreduce this rank executed.
        ar = snap["ops"]["allreduce"]
        want_bytes = STEPS * TENSORS * ELEMS * 4
        assert ar["tensors"] == STEPS * TENSORS, ar
        assert ar["bytes"] == want_bytes, (ar["bytes"], want_bytes)
        assert snap["cycle"]["count"] > 0
        assert snap["queue_us"]["count"] >= STEPS * TENSORS
        assert snap["wire_us"]["count"] > 0
        # Wire-vs-logical reconciliation (docs/wire.md): no compression
        # here, so transport bytes == full-width bytes, and the ring
        # moved at least 2(N-1)/N x payload (plus barrier/bookkeeping).
        wire = snap["wire"]
        assert wire["tx_bytes"] == wire["tx_logical_bytes"], wire
        assert wire["tx_bytes"] >= 2 * (size - 1) // size * want_bytes, (
            wire, want_bytes)
        # Steady state: repeated names ride the response-cache bitvector.
        assert snap["cache"]["hits"] > 0, snap["cache"]
        assert snap["cache"]["hit_rate"] > 0
        scraper = telemetry.MetricsScraper(
            interval_s=3600,
            jsonl_path=os.path.join(tmpdir, f"metrics.{rank}.jsonl"),
            prom_path=os.path.join(tmpdir, f"metrics.{rank}.prom"))
        scraper.scrape_once()
        print(f"METRICS_SMOKE_OK rank={rank} bytes={ar['bytes']} "
              f"cache_hits={snap['cache']['hits']}")
    finally:
        b.stop_timeline()
        b.shutdown()


def main():
    if "--worker" in sys.argv:
        worker(os.environ["HVDTPU_SMOKE_TMP"])
        return 0

    from horovod_tpu.telemetry import report

    size = 2
    port = _free_port()
    with tempfile.TemporaryDirectory() as tmpdir:
        procs = []
        for rank in range(size):
            env = dict(os.environ,
                       HOROVOD_RANK=str(rank), HOROVOD_SIZE=str(size),
                       HOROVOD_CONTROLLER_ADDR="127.0.0.1",
                       HOROVOD_CONTROLLER_PORT=str(port),
                       HVDTPU_SMOKE_TMP=tmpdir,
                       JAX_PLATFORMS="cpu")
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "horovod_tpu.telemetry.smoke",
                 "--worker"],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        failed = False
        for rank, p in enumerate(procs):
            try:
                out, _ = p.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                p.kill()
                out = "TIMEOUT"
            ok = p.returncode == 0 and "METRICS_SMOKE_OK" in out
            print(out.strip())
            if not ok:
                print(f"rank {rank} FAILED (rc={p.returncode})")
                failed = True
        if failed:
            return 1
        # Cross-rank piece: merge both timelines, expect a straggler
        # table covering both ranks.
        paths = [os.path.join(tmpdir, f"tl.{r}.json")
                 for r in range(size)]
        merged, skew = report.merge(paths)
        assert len(merged) > 0
        assert set(skew["per_rank"]) == set(range(size)), skew
        assert skew["matched_events"] > 0, skew
        # And the exporters left well-formed artifacts behind.
        for r in range(size):
            with open(os.path.join(tmpdir, f"metrics.{r}.jsonl")) as f:
                row = json.loads(f.read().splitlines()[-1])
                assert row["ops"]["allreduce"]["tensors"] > 0
            assert os.path.getsize(
                os.path.join(tmpdir, f"metrics.{r}.prom")) > 0
        print(f"metrics-smoke: OK ({size} ranks, "
              f"{skew['matched_events']} matched negotiate events, "
              f"merged trace {len(merged)} events)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
