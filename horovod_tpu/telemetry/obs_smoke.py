"""Two-process observability smoke: ``make obs-smoke``.

The full flight-recorder stack, one command, no accelerator: 2 real
ranks over the eager host ring with the debug endpoint up on both,
then a chaos-injected ``stop:<ms>`` stall (SIGSTOP + SIGCONT waker)
that escalates to a typed fault. Asserts:

1. **live introspection mid-run** — ``/healthz`` answers on BOTH ranks
   while the job is running (and ``/stacks`` + ``/events`` on the rank
   that is about to be wedged against the stalled peer);
2. **black-box post-mortem** — both ranks dump their event-ring tail
   the moment they record the fault, and the merged causal timeline
   (``report --post-mortem``) names the stalled rank as first-stalled
   WITHOUT declaring anyone dead (a stall is suspicion, not proof —
   both processes survived and dumped).
"""

import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

STALL_MS = 2500
STALL_AT_OP = 3


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def worker(tmpdir):
    import numpy as np

    from horovod_tpu.common import eager_ops
    from horovod_tpu.common.basics import HorovodBasics
    from horovod_tpu.common.exceptions import HorovodInternalError

    b = HorovodBasics()
    b.init()
    rank, size = b.rank(), b.size()
    if rank == 1:
        b.set_fault_inject_spec(f"1:{STALL_AT_OP}:stop:{STALL_MS}")
    x = np.full(2048, float(rank + 1), np.float32)
    for i in range(STALL_AT_OP):  # clean warmup ops
        out = eager_ops.allreduce_async(x, f"warm.{i}").synchronize()
        assert out[0] == 3.0, out[0]
    # Handshake: tell the driver both ranks are up (debug servers
    # answering) and wait for its go before running the op that stalls.
    with open(os.path.join(tmpdir, f"ready.{rank}"), "w") as f:
        f.write("ready")
    deadline = time.monotonic() + 60
    while not os.path.exists(os.path.join(tmpdir, "go")):
        assert time.monotonic() < deadline, "driver never said go"
        time.sleep(0.05)
    try:
        eager_ops.allreduce_async(x, "stall").synchronize()
        print(f"OBS_SMOKE_FAIL rank={rank}: stall op did not fault")
        return 1
    except HorovodInternalError:
        pass
    fault = b.last_fault()
    assert fault is not None
    # r12 ordering rule: keep sockets open until the peer has
    # classified its own fault too, then leave.
    time.sleep(1.5)
    b.shutdown()
    print(f"OBS_SMOKE_OK rank={rank} fault_ranks={fault['ranks']} "
          f"certain={fault['certain']}")
    return 0


def _get(url, timeout=10):
    return urllib.request.urlopen(url, timeout=timeout).read()


def main():
    if "--worker" in sys.argv:
        return worker(os.environ["HVDTPU_SMOKE_TMP"])

    from horovod_tpu.telemetry import postmortem

    size = 2
    port = _free_port()
    dbg_port = _free_port()
    with tempfile.TemporaryDirectory() as tmpdir:
        bb_dir = os.path.join(tmpdir, "blackbox")
        procs = []
        for rank in range(size):
            env = dict(os.environ,
                       HOROVOD_RANK=str(rank), HOROVOD_SIZE=str(size),
                       HOROVOD_CONTROLLER_ADDR="127.0.0.1",
                       HOROVOD_CONTROLLER_PORT=str(port),
                       HOROVOD_WIRE_TIMEOUT_MS="600",
                       HOROVOD_WIRE_RETRY_ATTEMPTS="0",
                       HOROVOD_DEBUG_PORT=str(dbg_port),
                       HOROVOD_BLACKBOX_DIR=bb_dir,
                       HVDTPU_SMOKE_TMP=tmpdir,
                       JAX_PLATFORMS="cpu")
            procs.append(subprocess.Popen(
                [sys.executable, "-m",
                 "horovod_tpu.telemetry.obs_smoke", "--worker"],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))

        # Phase 1: both ranks warmed up -> /healthz must answer on BOTH
        # mid-run (plus /stacks and /events on rank 0, which is about
        # to block against the stalled peer).
        deadline = time.monotonic() + 60
        while not all(os.path.exists(os.path.join(tmpdir, f"ready.{r}"))
                      for r in range(size)):
            if time.monotonic() > deadline:
                for p in procs:
                    p.kill()
                print("obs-smoke: FAILED (workers never became ready)")
                return 1
            time.sleep(0.05)
        for r in range(size):
            health = json.loads(_get(
                f"http://127.0.0.1:{dbg_port + r}/healthz"))
            assert health["rank"] == r and health["initialized"], health
            assert health["epoch"] == 0 and not health["loop_failed"]
        stacks = _get(f"http://127.0.0.1:{dbg_port}/stacks")
        assert b"File" in stacks or b"Thread" in stacks
        events = json.loads(_get(
            f"http://127.0.0.1:{dbg_port}/events?n=64"))
        assert any(e["type"] == "response_launch" for e in events)
        print(f"obs-smoke: /healthz answered on both ranks mid-run, "
              f"/stacks + /events live ({len(events)} ring events)")

        # Phase 2: release the stall op; the fault must leave per-rank
        # black boxes whose merge names the stalled rank.
        with open(os.path.join(tmpdir, "go"), "w") as f:
            f.write("go")
        failed = False
        for rank, p in enumerate(procs):
            try:
                out, _ = p.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                p.kill()
                out = "TIMEOUT"
            ok = p.returncode == 0 and "OBS_SMOKE_OK" in out
            print(out.strip())
            if not ok:
                print(f"rank {rank} FAILED (rc={p.returncode})")
                failed = True
        if failed:
            return 1

        dumps = sorted(os.listdir(bb_dir))
        assert dumps == [f"blackbox-rank{r}.jsonl" for r in range(size)], \
            dumps
        analysis = postmortem.merge_post_mortem(bb_dir)
        # A stall is suspicion, not proof: nobody is declared dead
        # (both processes dumped = both alive), and the first-stalled
        # analysis names the SIGSTOPped rank.
        assert analysis["root_cause_ranks"] == [], analysis
        assert analysis["first_stalled_rank"] == 1, {
            k: analysis[k] for k in ("first_stalled_rank", "per_rank")}
        assert analysis["timeline"], "empty merged timeline"
        print(postmortem.format_post_mortem(analysis, tail=12))
        print(f"obs-smoke: OK (merged post-mortem over {size} ranks "
              f"names rank 1 as first-stalled, "
              f"{len(analysis['timeline'])} causal events)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
