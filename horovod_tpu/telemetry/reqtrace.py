"""Request-scoped tracing: per-request span ledgers and cross-rank
trace stitching for the serving lane.

The step-anatomy observatory (critpath.py) explains *steps*; this
module explains *requests* — the unit users experience. The serving
lane records one ``request`` event (csrc/events.h ``kRequest``) per
lifecycle transition, rid-tagged, through the same always-on event
ring as everything else: each event marks the instant a request ENTERS
a phase, so a rid's span ledger is simply the gaps between its
consecutive transitions — **gap-free by construction** (every
microsecond of a request's wall time lands in exactly one phase, the
same exact-reconciliation standard as the r17 overlap ledger).

Phases (``REQUEST_PHASES`` — index-ABI with the C table, pinned in
tests/single/test_reqtrace.py and by the hvdcheck ABI drift guard,
which scrapes csrc/events.h + kRequestPhaseNames and requires this
tuple to match bit-for-bit: analysis/model/abi.py,
``make model-check``)::

    queued           admitted to the frontend's pending line
    prefill          prefill compute running for this request
    kv_ship          packed; KV payload in flight to its decode rank
    decode_wait      adopted/admitted, between decode steps
    decode_active    inside a decode step's batch
    evicted_requeue  LIFO-evicted; waiting for re-prefill
    fault_requeue    orphaned by a peer fault; re-queued
    done             terminal: completion reached the scoreboard

Transitions for ONE rid happen on more than one rank (frontend
prefills and scoreboards; a decode rank decodes), so :func:`stitch`
merges per-rank event dumps on the r15 anchor-pair wall axis
(``postmortem._wall_us`` — the CLOCK_SYNC contract) and reassembles
each rid's chain across ranks. A decode rank that died without dumping
(SIGKILL) simply leaves its phases unobserved: the preceding frontend
phase extends until the frontend's next transition (``fault_requeue``),
so chains stay gap-free even through the chaos case they exist to
explain.

``report.py --requests`` renders the tail-latency attribution: pick a
percentile band and see where its wall time went ("the p99 cohort
spends 71% in evicted_requeue"), plus the dominant phase per slow
request. The live counterpart is the debug server's ``/requests?n=``
endpoint over :func:`live_requests` — in-flight rids with current
phase and age, no dump needed.
"""

import time
from collections import defaultdict

# Index-ABI with csrc/events.h RequestPhase / events.cc
# kRequestPhaseNames (pinned in tests/single/test_reqtrace.py).
REQUEST_PHASES = ("queued", "prefill", "kv_ship", "decode_wait",
                  "decode_active", "evicted_requeue", "fault_requeue",
                  "done")
TERMINAL_PHASE = "done"

# rid -> (phase, t_phase, t_first) on this process's monotonic clock —
# the /requests live table. Plain dict on purpose: writers are the
# serving thread, readers (the debug server) copy under the GIL.
_live = {}

_basics = None
_basics_ok = None  # None = unresolved, False = core lib unavailable
_tracing = None    # None = resolve from the ring's enabled() lazily


def _lib():
    """The core binding, or None when the native lib is unavailable
    (pure-python test environments) — tracing then degrades to the
    live table only."""
    global _basics, _basics_ok
    if _basics_ok is None:
        try:
            from horovod_tpu.common.basics import HorovodBasics

            _basics = HorovodBasics()
            # HorovodBasics resolves the .so lazily on first `.lib`
            # access — touch it HERE so a missing/unbuildable core
            # fails inside this try and the fallback actually engages
            # (a lazy failure would otherwise surface later, inside
            # record_request, in exactly the environment this clause
            # protects).
            _basics.lib
            _basics_ok = True
        except Exception:  # noqa: BLE001 — tracing must never be the
            _basics_ok = False  # reason a serving process cannot start
    return _basics if _basics_ok else None


def tracing_enabled():
    """Whether request events reach the ring (rides the ring's own
    HOROVOD_EVENTS gate; :func:`set_tracing` overrides in-process)."""
    global _tracing
    if _tracing is None:
        b = _lib()
        _tracing = bool(b is not None and b.events_enabled())
    return _tracing


def set_tracing(on):
    """Flip request tracing (and the event ring) in-process — the
    tracing-overhead bench's off switch (bench_lane.py)."""
    global _tracing
    _tracing = bool(on)
    b = _lib()
    if b is not None:
        b.lib.hvdtpu_set_events_enabled(1 if on else 0)


def record_request(phase, rid, aux=0):
    """Record one lifecycle transition: ``rid`` enters ``phase`` now.

    Always updates the live in-flight table (the ``/requests``
    endpoint's source — ~a dict store); emits the ring event only while
    tracing is on. A ``done`` transition retires the rid from the live
    table. Unknown phase names raise — a typo'd phase would silently
    corrupt every downstream ledger."""
    pid = REQUEST_PHASES.index(phase)
    if phase == TERMINAL_PHASE:
        _live.pop(rid, None)
    else:
        now = time.monotonic()
        prev = _live.get(rid)
        _live[rid] = (phase, now, prev[2] if prev else now)
    if tracing_enabled():
        b = _lib()
        if b is not None:
            b.lib.hvdtpu_record_request(pid, int(rid), int(aux))


def forget_request(rid):
    """Drop a rid from the live table WITHOUT a ``done`` transition —
    the duplicate-cancel path (another rank owns the completion; its
    ``done`` is the chain's terminal, not ours)."""
    _live.pop(rid, None)


def live_requests(limit=64):
    """The in-flight table for ``/requests?n=``: one row per live rid
    with its current phase, time in that phase, and total age — oldest
    first, capped at ``limit`` (<= 0 = all)."""
    now = time.monotonic()
    rows = [{"rid": rid, "phase": ph,
             "phase_age_ms": round((now - t_ph) * 1000.0, 3),
             "age_ms": round((now - t0) * 1000.0, 3)}
            for rid, (ph, t_ph, t0) in list(_live.items())]
    rows.sort(key=lambda r: -r["age_ms"])
    return rows[:int(limit)] if int(limit) > 0 else rows


# ---- cross-rank stitching ---------------------------------------------


def _request_transitions(paths_or_dir):
    """Every ``request`` event across all dumps, wall-aligned and
    source-rank-tagged: ``[(wall_us, seq, rank, phase, rid, aux)]``.
    Folds each event once by seq per file (a process appends one dump
    per fault; successive dumps overlap — the report.py --events
    discipline)."""
    from horovod_tpu.telemetry import postmortem

    out = []
    for path in postmortem.collect_paths(paths_or_dir):
        seen = set()
        for dump in postmortem.load_blackbox(path):
            hdr = dump["header"]
            rank = hdr.get("rank", -1)
            for ev in dump["events"]:
                if ev.get("type") != "request" or ev.get("seq") in seen:
                    continue
                seen.add(ev.get("seq"))
                phase = ev.get("phase_name")
                if phase is None:
                    pid = ev.get("phase", -1)
                    phase = (REQUEST_PHASES[pid]
                             if 0 <= pid < len(REQUEST_PHASES)
                             else "unknown")
                out.append((postmortem._wall_us(ev, hdr),
                            ev.get("seq", 0), rank, phase,
                            ev.get("rid"), ev.get("aux", 0)))
    return out


def stitch(paths_or_dir):
    """Merge per-rank dumps and reassemble each rid's span chain.

    Returns ``{rid: chain}`` where a chain is::

        {"rid": rid,
         "spans": [{"phase", "rank", "start_us", "end_us", "dur_us"}],
         "phase_us": {phase: total us},   # every phase observed
         "start_us", "end_us", "wall_us", # chain extent (wall axis)
         "complete": bool,                # a terminal `done` was seen
         "ranks": [ranks that contributed transitions]}

    Chains are gap-free and overlap-free BY CONSTRUCTION: transitions
    sort onto one wall axis and span *i* is exactly
    ``[t_i, t_{i+1})`` — so ``sum(phase_us.values()) == wall_us``
    holds to the microsecond (the r17 exact-reconciliation standard;
    serve-smoke re-verifies it from the span list rather than trusting
    this sentence). Adjacent same-phase spans merge; zero-length spans
    contribute nothing. Time after an intermediate ``done`` (a decode
    rank completed; the frontend scoreboard confirmed later) books to
    the ``done`` phase — completion-report latency is real latency.
    """
    per_rid = defaultdict(list)
    for t in _request_transitions(paths_or_dir):
        per_rid[t[4]].append(t)
    chains = {}
    for rid, transitions in per_rid.items():
        transitions.sort(key=lambda t: (t[0], t[1]))
        spans = []
        for (w0, _s0, rank, phase, _r0, _a0), (w1, *_rest) in zip(
                transitions, transitions[1:]):
            dur = w1 - w0
            if dur <= 0:
                continue
            if spans and spans[-1]["phase"] == phase \
                    and spans[-1]["rank"] == rank \
                    and spans[-1]["end_us"] == w0:
                spans[-1]["end_us"] = w1
                spans[-1]["dur_us"] += dur
                continue
            spans.append({"phase": phase, "rank": rank,
                          "start_us": w0, "end_us": w1, "dur_us": dur})
        phase_us = defaultdict(int)
        for s in spans:
            phase_us[s["phase"]] += s["dur_us"]
        start = transitions[0][0]
        end = transitions[-1][0]
        chains[rid] = {
            "rid": rid,
            "spans": spans,
            "phase_us": dict(phase_us),
            "start_us": start,
            "end_us": end,
            "wall_us": end - start,
            "complete": any(t[3] == TERMINAL_PHASE for t in transitions),
            "ranks": sorted({t[2] for t in transitions}),
        }
    return chains


def chain_gaps(chain):
    """Independent gap/overlap audit of one chain (what serve-smoke
    asserts empty instead of trusting :func:`stitch`'s construction):
    returns a list of ``(kind, at_us, us)`` defects — ``gap`` for
    uncovered wall time between spans, ``overlap`` for doubly-covered
    time, plus a ``sum`` defect when the span durations do not total
    the chain's wall extent exactly."""
    defects = []
    spans = chain["spans"]
    cursor = chain["start_us"]
    for s in spans:
        if s["start_us"] > cursor:
            defects.append(("gap", cursor, s["start_us"] - cursor))
        elif s["start_us"] < cursor:
            defects.append(("overlap", s["start_us"],
                            cursor - s["start_us"]))
        cursor = s["end_us"]
    if cursor != chain["end_us"]:
        defects.append(("gap", cursor, chain["end_us"] - cursor))
    total = sum(s["dur_us"] for s in spans)
    if total != chain["wall_us"]:
        defects.append(("sum", chain["start_us"],
                        chain["wall_us"] - total))
    return defects


# Package-level alias (``telemetry.stitch_requests``): ``stitch`` is
# unambiguous inside this module, not at the package surface.
stitch_requests = stitch


# ---- tail-latency attribution -----------------------------------------


def tail_report(chains, pct=99.0):
    """Decompose a latency percentile band: which phases own the slow
    requests' wall time.

    Returns::

        {"requests", "complete", "pct", "threshold_ms",
         "population_phase_share": {phase: fraction},
         "cohort_phase_share": {phase: fraction},
         "cohort": [{"rid", "wall_ms", "dominant_phase",
                     "phases_ms": {...}, "ranks"}],   # slowest first
         "incomplete": [rids without a terminal done]}

    The cohort is every COMPLETE chain at or above the ``pct``-th
    percentile of complete-chain wall latency; shares are
    phase-time / total-wall-time over the respective set (they sum to
    1 exactly, because chains are gap-free).
    """
    import numpy as np

    complete = [c for c in chains.values() if c["complete"]]
    incomplete = sorted(c["rid"] for c in chains.values()
                        if not c["complete"])
    if not complete:
        return {"requests": len(chains), "complete": 0, "pct": pct,
                "threshold_ms": 0.0, "population_phase_share": {},
                "cohort_phase_share": {}, "cohort": [],
                "incomplete": incomplete}
    walls = np.asarray([c["wall_us"] for c in complete], np.float64)
    threshold = float(np.percentile(walls, pct))
    cohort = sorted((c for c in complete if c["wall_us"] >= threshold),
                    key=lambda c: -c["wall_us"])

    def shares(cs):
        total = sum(c["wall_us"] for c in cs)
        if total <= 0:
            return {}
        acc = defaultdict(int)
        for c in cs:
            for ph, us in c["phase_us"].items():
                acc[ph] += us
        return {ph: round(us / total, 6)
                for ph, us in sorted(acc.items())}

    rows = []
    for c in cohort:
        dominant = max(c["phase_us"], key=c["phase_us"].get) \
            if c["phase_us"] else "-"
        rows.append({
            "rid": c["rid"],
            "wall_ms": round(c["wall_us"] / 1000.0, 3),
            "dominant_phase": dominant,
            "phases_ms": {ph: round(us / 1000.0, 3)
                          for ph, us in sorted(c["phase_us"].items())},
            "ranks": c["ranks"],
        })
    return {
        "requests": len(chains),
        "complete": len(complete),
        "pct": pct,
        "threshold_ms": round(threshold / 1000.0, 3),
        "population_phase_share": shares(complete),
        "cohort_phase_share": shares(cohort),
        "cohort": rows,
        "incomplete": incomplete,
    }


def format_requests(report, max_rows=20):
    """Operator-facing rendering of :func:`tail_report`: the headline
    names where the slow band's time goes."""
    lines = []
    cs = report["cohort_phase_share"]
    if cs:
        worst = max(cs, key=cs.get)
        lines.append(
            f"p{report['pct']:g} cohort ({len(report['cohort'])} of "
            f"{report['complete']} requests, >= "
            f"{report['threshold_ms']:.1f} ms): spends "
            f"{cs[worst]:.0%} in {worst}")
    else:
        lines.append("no complete request chains")
    ps = report["population_phase_share"]
    if ps:
        lines.append("population: " + "  ".join(
            f"{ph} {frac:.0%}" for ph, frac in
            sorted(ps.items(), key=lambda kv: -kv[1])))
    lines.append(f"{'rid':>8} {'wall ms':>10} {'dominant':>16} "
                 f"{'share':>6}  phases")
    for row in report["cohort"][:max_rows]:
        dom_ms = row["phases_ms"].get(row["dominant_phase"], 0.0)
        share = dom_ms / row["wall_ms"] if row["wall_ms"] else 0.0
        detail = " ".join(f"{ph}={ms:.1f}" for ph, ms in
                          sorted(row["phases_ms"].items(),
                                 key=lambda kv: -kv[1])[:4])
        lines.append(f"{row['rid']:>8} {row['wall_ms']:>10.1f} "
                     f"{row['dominant_phase']:>16} {share:>6.0%}  "
                     f"{detail}")
    if report["incomplete"]:
        lines.append(f"incomplete (no terminal done): "
                     f"{report['incomplete']}")
    return "\n".join(lines)
